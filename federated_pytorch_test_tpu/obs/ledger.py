"""Communication-volume ledger: the paper's headline quantity, measured.

The reference's one-sentence thesis is that communicating ONE parameter
group per round slashes bandwidth (reference README.md:2), and related
work reports exactly this figure — L-FGADMM (arXiv:1911.03654) plots
layer-wise communication cost, TAMUNA (arXiv:2302.09832) its sparsified
exchange volume under partial participation. Until this module nothing in
the repo computed communicated bytes at all.

The volume is *exact and static*, not sampled: every consensus exchange
moves the active group's coordinates — `Partition.group_size(gid)` values
of the parameter dtype — for each PARTICIPATING client (consensus/
fedavg.py, consensus/admm.py: a dropped client's contribution is excluded
from the masked aggregation and it does not receive the broadcast, so it
contributes zero bytes in both directions). The recorded `comm_bytes`
series is the UPLINK volume of one exchange,

    comm_bytes = group_size(gid) * dtype_bytes * survivors,

the hand-computable contract of tests/test_obs.py; the symmetric
consensus broadcast doubles it, which the summary reports separately.
Under an exchange codec (exchange/, `--exchange-dtype bfloat16`)
`dtype_bytes` above becomes the codec's WIRE bytes-per-value — exactly
half under bf16 — while the full-exchange baseline below keeps the
parameter width (compression is part of the savings being measured).

Two baselines put the number in context:

* **full-parameter exchange** — the same schedule shipping the WHOLE
  flat vector every round (what naive FedAvg/ADMM without the partition
  would send): `total * dtype_bytes * survivors` per round. The
  `savings_vs_full` ratio is the paper's claim as a number.
* **data-transfer floor** — shipping the raw training shards to one host
  once and training centrally (the non-federated alternative federated
  learning exists to avoid); a run whose cumulative model traffic
  exceeds it has spent more wire than centralization would have.
"""

from __future__ import annotations

from typing import Optional, Sequence


class CommLedger:
    """Accumulates per-round communicated bytes for one experiment."""

    def __init__(
        self,
        partition,
        n_clients: int,
        dtype_bytes: int = 4,
        data_floor_bytes: Optional[int] = None,
        wire_bytes: Optional[int] = None,
        exchange_dtype: str = "float32",
        codec=None,
    ):
        """`dtype_bytes` is the PARAMETER dtype's width (what the naive
        full-model f32 exchange baseline ships). The wire side is priced
        one of two ways: `codec` (an exchange/ `ExchangeCodec`) makes
        every exchange cost `codec.bytes_on_wire(group_size)` per
        transmitting client — EXACT for sparse/framed members (topk's
        index+value pairs, quant's scale header) where no flat per-value
        width exists; without a codec, `wire_bytes` is the flat
        bytes-per-value (half of dtype_bytes under bf16; defaults to
        dtype_bytes — pre-codec ledgers are unchanged)."""
        self.partition = partition
        self.n_clients = int(n_clients)
        self.dtype_bytes = int(dtype_bytes)
        self.codec = codec
        if codec is not None and wire_bytes is None and codec.flat_wire:
            wire_bytes = codec.bytes_per_value
        self.wire_bytes = (
            int(wire_bytes) if wire_bytes is not None else int(dtype_bytes)
        )
        # the flat per-value width the summary reports; None for codecs
        # whose wire has no such number (topk, quant)
        self.wire_bytes_per_value: Optional[int] = (
            None
            if codec is not None and not codec.flat_wire
            else self.wire_bytes
        )
        self.exchange_dtype = str(exchange_dtype)
        self.data_floor_bytes = (
            int(data_floor_bytes) if data_floor_bytes is not None else None
        )
        self._uplink = 0
        self._full = 0
        self._rounds = 0
        # uplink spent by QUARANTINED senders (consensus/robust.py
        # auto-quarantine): they transmit — they don't know they're
        # excluded — and the exchange discards the bytes on arrival
        self._wasted = 0

    # --------------------------------------------------------- pure queries

    def round_bytes(self, gid: int, survivors: int) -> int:
        """Uplink bytes of ONE consensus exchange of group `gid` — at
        the WIRE cost: the codec's exact `bytes_on_wire` of the group
        slice per transmitting client (half the f32 ledger under bf16,
        `kept * 8` under topk, `4 + ceil(n*bits/8)` under quant —
        tests/test_exchange.py, tests/test_codecs.py hand-checks), or
        the flat `wire_bytes` per value for codec-less ledgers."""
        if self.codec is not None:
            per_client = self.codec.bytes_on_wire(
                self.partition.group_size(gid)
            )
        else:
            per_client = self.partition.group_size(gid) * self.wire_bytes
        return per_client * int(survivors)

    def full_round_bytes(self, survivors: int) -> int:
        """The same exchange if the WHOLE parameter vector were sent —
        at the PARAMETER width (the naive uncompressed-full-model
        baseline the savings ratio is measured against)."""
        return self.partition.total * self.dtype_bytes * int(survivors)

    def savings_vs_full(self, group_order: Sequence[int]) -> float:
        """Partial-vs-full ratio for one pass over `group_order`.

        Pure partition + codec arithmetic (participation cancels): how
        many times MORE a whole-model f32 exchange would move than the
        per-group wire-format one, over one outer loop's visit order —
        the codec's compression factor multiplies the partition's.
        """
        if self.codec is not None:
            part_wire = sum(
                self.codec.bytes_on_wire(self.partition.group_size(g))
                for g in group_order
            )
        else:
            part_wire = self.wire_bytes * sum(
                self.partition.group_size(g) for g in group_order
            )
        return (
            self.partition.total * len(group_order) * self.dtype_bytes
        ) / part_wire

    # ---------------------------------------------------------- accumulation

    def account(self, gid: int, survivors: int) -> int:
        """Accumulate one consensus exchange into the totals (no record).

        Used directly by the resume path to reconstruct rounds that will
        NOT re-run and left no stream to absorb: every fault mask is a
        pure function of (plan seed, round cursor), so the pre-restore
        traffic is recomputable exactly (engine/trainer.py).
        """
        b = self.round_bytes(gid, survivors)
        self._uplink += b
        self._full += self.full_round_bytes(survivors)
        self._rounds += 1
        return b

    def record(
        self, recorder, gid: int, survivors: int, *, nloop, nadmm,
        quarantined: int = 0,
    ) -> None:
        """Account one consensus exchange and log its `comm_bytes` record.

        `survivors` counts TRANSMITTING clients (plan-alive, whether
        trusted or not); `quarantined` is how many of them the exchange
        discarded on arrival — their share of the uplink is attributed
        as wasted in the summary. The record grows a `quarantined` key
        only when the count is nonzero, so quarantine-free streams are
        byte-identical to pre-quarantine ones.
        """
        b = self.account(gid, survivors)
        self._wasted += self.round_bytes(gid, quarantined)
        ctx = dict(
            nloop=nloop, group=gid, nadmm=nadmm, survivors=int(survivors)
        )
        if quarantined:
            ctx["quarantined"] = int(quarantined)
        recorder.log("comm_bytes", int(b), **ctx)

    def absorb(self, records: Sequence[dict]) -> None:
        """Seed the totals from replayed `comm_bytes` records.

        A resumed run replays the pre-crash rounds from the JSONL stream
        instead of re-running them; absorbing their records keeps the
        end-of-run summary identical to an uninterrupted run's.
        """
        for rec in records:
            s = int(rec.get("survivors", self.n_clients))
            self._uplink += int(rec["value"])
            self._full += self.full_round_bytes(s)
            self._rounds += 1
            q = int(rec.get("quarantined", 0))
            if q and s:
                # value == group_bytes * survivors exactly, so the
                # per-sender share reconstructs without the partition
                self._wasted += int(rec["value"]) // s * q

    def summary(self) -> dict:
        """End-of-run totals vs the two baselines (module docstring)."""
        up, full = self._uplink, self._full
        out = {
            "rounds": self._rounds,
            "n_clients": self.n_clients,
            "dtype_bytes": self.dtype_bytes,
            # the wire format (exchange/): what one exchanged value
            # actually cost on the uplink under the active codec (None
            # for sparse/framed codecs — their exact per-exchange cost
            # lives in the codec descriptor below and the comm_bytes
            # records themselves)
            "exchange_dtype": self.exchange_dtype,
            "wire_bytes_per_value": self.wire_bytes_per_value,
            "bytes_total": int(up),
            "bytes_total_bidirectional": int(2 * up),
            "bytes_per_round_mean": (
                round(up / self._rounds, 1) if self._rounds else None
            ),
            "bytes_full_exchange": int(full),
            "savings_vs_full": round(full / up, 4) if up else None,
            # uplink spent by quarantined senders — transmitted, then
            # discarded at the exchange (the defense's bandwidth cost)
            "bytes_quarantined_wasted": int(self._wasted),
            "data_floor_bytes": self.data_floor_bytes,
            "vs_data_floor": (
                round(up / self.data_floor_bytes, 6)
                if self.data_floor_bytes
                else None
            ),
        }
        if self.codec is not None:
            # the full wire identity (name + parameters + short label —
            # exchange/codec.py describe()): what `report` labels
            # frontier points with (obs/registry.py)
            out["codec"] = self.codec.describe()
        return out
