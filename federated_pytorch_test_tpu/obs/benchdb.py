"""The bench trend database behind the `trend` CLI verb.

The BENCH_r0N trajectory has been unqueryable prose: five wrapper files
at the repo root, one torn payload (round 3's ~3KB headline truncated
mid-JSON and recorded as `parsed: null`), and no machine anywhere that
notices a regression — or a CPU number masquerading as a TPU result —
before it lands. This module is obs/ part 4's data layer:

* **tolerant ingestion** of every measurement artifact the repo emits:
  the driver's `{n, cmd, rc, tail, parsed}` BENCH wrappers (a missing
  or torn `parsed` payload is skipped with a named warning, the
  registry's torn-tail rule applied to benchmarks — never a crash),
  bare bench.py headline JSONs, `benchmarks/full_*_tpu.json` schedule
  artifacts, `benchmarks/*scaling*_tpu*.json` sweep artifacts, and the
  CI preflight/tier-walls JSON (scripts/ci.sh);
* an **append-only trend store** (one JSON line per measurement record,
  content-digest deduplicated — re-ingesting the same files adds
  nothing, so the report is byte-identical on re-ingest) keyed by
  `(metric, provenance class)` (obs/provenance.py);
* a **deterministic trajectory report** (JSON + markdown, sorted keys,
  no wall-clock content) with noise-aware per-point deltas — the
  bench headline's `sps_p25/p75` dispersion becomes each point's
  relative noise band;
* the **regression sentinel**: a directional metric that worsens
  beyond its noise band vs the LAST baseline of the SAME provenance
  class is flagged. CPU-twin compares against CPU-twin, TPU against
  TPU, and unstamped (pre-provenance) history only against itself —
  never across;
* **debt closing**: an ingested record whose provenance satisfies a
  DEBT.json entry's owed condition AND carries the owed metric closes
  the entry (obs/debt.py) — the first TPU session burns the queue down
  by just running it.

Like `report`/`watch`/`scrub`, the verb is pure host-side file
analysis: no engine import, no accelerator backend init.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import warnings
from typing import List, Optional, Tuple

from federated_pytorch_test_tpu.obs.provenance import (
    STAMP_KEYS,
    provenance_class,
)

TREND_VERSION = 1
STORE_SCHEMA = 1

# The sentinel's noise-band floor: relative change a directional metric
# may move between consecutive same-class points before it flags, when
# no measured dispersion says otherwise. Deliberately wide — BASELINE.md
# records single flagship draws ranging 160-2600 samples/s on the
# shared chip; the measured sps_p25/p75 band widens (never narrows
# below) this floor.
REL_NOISE_FLOOR = 0.25

# metric names that are facts/knobs, not performance — never sentineled
NEUTRAL_METRICS = {
    "batch",
    "repeats",
    "n",
    "n_clients",
    "nloop",
    "linesearch_probes",
    "effective_gemm_m",
    "round_dispatches",
    "rounds_evaluated",
    "store_resident_chunks",
    "store_evictions",
    "threshold_pcpu",
}

_HIGHER_TOKENS = (
    "speedup",
    "throughput",
    "samples_per_sec",
    "sps",
    "mfu",
    "tflops",
    "pct_peak",
    "accuracy",
    "acc_",
    "efficiency",
    "scaling",
    "vs_baseline",
    "savings",
    "gain",
    "passed",
    "hbm_frac",
    "flat_in_n",
)
_LOWER_TOKENS = (
    "time",
    "wall",
    "overhead",
    "rss",
    "seconds",
    "bytes",
    "evals_per_step",
    "stray_cpu_hogs",
)


class TrendRefused(ValueError):
    """A file `trend` cannot treat as a measurement (named reason)."""


def metric_direction(name: str) -> Optional[str]:
    """`'higher'` / `'lower'` = which way is better, `None` = neutral
    (never sentineled). Namespaced metrics (`full_fedavg_tpu:wall_
    seconds`) are judged by their base name."""
    base = name.rsplit(":", 1)[-1]
    if base in NEUTRAL_METRICS:
        return None
    for tok in _HIGHER_TOKENS:
        if tok in base:
            return "higher"
    for tok in _LOWER_TOKENS:
        if tok in base:
            return "lower"
    return None


def _numeric_items(doc: dict) -> dict:
    return {
        k: v
        for k, v in doc.items()
        if isinstance(v, (int, float)) and not isinstance(v, bool)
    }


def _trim_stamp(prov) -> Optional[dict]:
    if not isinstance(prov, dict):
        return None
    return {k: prov.get(k) for k in STAMP_KEYS}


def _headline_measurement(parsed: dict, source: str) -> dict:
    """One bench.py headline -> a trend record's metrics/spread."""
    metrics = _numeric_items(parsed)
    spread = {}
    name = parsed.get("metric")
    value = metrics.pop("value", None)
    if isinstance(name, str) and value is not None:
        metrics[name] = value
        p25, p75 = metrics.pop("sps_p25", None), metrics.pop("sps_p75", None)
        if p25 is not None and p75 is not None and value:
            # the headline's measured dispersion, as the primary
            # metric's relative noise band
            spread[name] = round(abs(p75 - p25) / abs(value), 4)
    return {
        "source": source,
        "order": parsed.get("n"),
        "metrics": metrics,
        "spread": spread,
        "provenance": _trim_stamp(parsed.get("provenance")),
    }


def extract_measurement(doc, source: str) -> dict:
    """One artifact JSON -> one trend record (no store fields yet).

    Raises `TrendRefused` (with the file and reason named) for torn or
    unrecognized documents — directory ingestion downgrades that to a
    warning, the registry's skip-with-a-named-warning idiom.
    """
    stem = os.path.splitext(os.path.basename(source))[0]
    if not isinstance(doc, dict):
        raise TrendRefused(f"{source}: not a JSON object")

    # the driver's BENCH wrapper: {n, cmd, rc, tail, parsed}
    if "parsed" in doc and "cmd" in doc:
        parsed = doc.get("parsed")
        if not isinstance(parsed, dict):
            raise TrendRefused(
                f"{source}: wrapper parsed payload missing or torn "
                f"(rc={doc.get('rc')}) — skipping, tail not trusted"
            )
        rec = _headline_measurement(parsed, stem)
        if rec.get("order") is None:
            rec["order"] = doc.get("n")
        return rec

    # a bare bench.py headline (or bench_full.json's top level)
    if "metric" in doc and "value" in doc and "unit" in doc:
        return _headline_measurement(doc, stem)

    # benchmarks/full_schedule_tpu.py artifact
    if "experiment" in doc:
        metrics = {}
        for key in (
            "wall_seconds",
            "epoch_step_time_median_s",
            "fused_round_time_median_s",
        ):
            if isinstance(doc.get(key), (int, float)):
                metrics[f"{stem}:{key}"] = doc[key]
        curve = doc.get("acc_mean_per_round")
        if isinstance(curve, list) and curve:
            metrics[f"{stem}:final_acc_mean"] = curve[-1]
        if not metrics:
            raise TrendRefused(f"{source}: schedule artifact has no walls")
        return {
            "source": stem,
            "order": None,
            "metrics": metrics,
            "spread": {},
            "provenance": _trim_stamp(doc.get("provenance")),
        }

    # benchmarks/client_scaling_tpu.py / cohort sweep artifact. Older
    # committed generations spelled the keys per-client
    # (`samples_per_sec_per_client`, `scaling_efficiency_vs_k3`) before
    # the per-device rename — both generations ingest.
    if "workload" in doc and isinstance(doc.get("rows"), list):
        def _column(*names):
            vals = []
            for r in doc["rows"]:
                if not isinstance(r, dict):
                    continue
                for name in names:
                    v = r.get(name)
                    if isinstance(v, (int, float)) and not isinstance(v, bool):
                        vals.append(v)
                        break
            return vals

        sps = _column("samples_per_sec_per_device", "samples_per_sec_per_client")
        eff = _column("scaling_efficiency", "scaling_efficiency_vs_k3")
        flat = _column("flat_in_n")
        metrics = {}
        if sps:
            metrics[f"{stem}:samples_per_sec_per_device_max"] = max(sps)
        if eff:
            metrics[f"{stem}:scaling_efficiency_min"] = min(eff)
        if flat:
            metrics[f"{stem}:flat_in_n_min"] = min(flat)
        if metrics:
            return {
                "source": stem,
                "order": None,
                "metrics": metrics,
                "spread": {},
                "provenance": _trim_stamp(doc.get("provenance")),
            }
        # unknown row schema: fall through to top-level numeric facts

    # other benchmarks/ artifacts (stream overlap, ...): numeric
    # top-level facts, namespaced by stem
    if "workload" in doc:
        metrics = {
            f"{stem}:{k}": v for k, v in sorted(_numeric_items(doc).items())
        }
        if not metrics:
            raise TrendRefused(f"{source}: workload artifact has no numbers")
        return {
            "source": stem,
            "order": None,
            "metrics": metrics,
            "spread": {},
            "provenance": _trim_stamp(doc.get("provenance")),
        }

    # scripts/ci.sh preflight + per-tier walls JSON
    if "tiers" in doc or "stray_cpu_hogs" in doc:
        metrics = {}
        for tier in doc.get("tiers") or []:
            if not isinstance(tier, dict) or "tier" not in tier:
                continue
            label = str(tier["tier"])
            if isinstance(tier.get("wall_s"), (int, float)):
                metrics[f"ci_{label}_wall_s"] = tier["wall_s"]
            if isinstance(tier.get("passed"), (int, float)):
                metrics[f"ci_{label}_passed"] = tier["passed"]
        hogs = doc.get("stray_cpu_hogs")
        if isinstance(hogs, list):
            metrics["ci_stray_cpu_hogs"] = len(hogs)
        if not metrics:
            raise TrendRefused(f"{source}: preflight JSON has no tier walls")
        return {
            "source": stem,
            "order": None,
            "metrics": metrics,
            "spread": {},
            "provenance": _trim_stamp(doc.get("provenance")),
        }

    raise TrendRefused(f"{source}: unrecognized measurement document")


def _record_digest(rec: dict) -> str:
    """Content digest for append-only dedup: a record re-ingested from
    the same bytes is the same record, whatever session ingests it."""
    canon = json.dumps(
        {k: rec.get(k) for k in ("source", "order", "metrics", "spread",
                                 "provenance")},
        sort_keys=True,
    )
    return hashlib.sha1(canon.encode()).hexdigest()[:16]


class BenchDB:
    """The append-only trend store: one JSON line per measurement."""

    def __init__(self, store_path: str):
        self.store_path = store_path
        self.records: List[dict] = []
        self._digests = set()
        self._load()

    def _load(self) -> None:
        try:
            f = open(self.store_path)
        except OSError:
            return
        with f:
            for ln, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    # the store is append-only and line-buffered: only a
                    # torn final line is legitimate; nothing after the
                    # first unparsable line is trusted (the stream rule)
                    warnings.warn(
                        f"{self.store_path}: torn store line {ln} — "
                        "dropping it and everything after"
                    )
                    break
                self.records.append(rec)
                self._digests.add(rec.get("digest"))

    # -- ingestion ----------------------------------------------------
    def ingest_doc(self, doc, source: str) -> Optional[dict]:
        """Ingest one parsed artifact; returns the appended record or
        None when it deduplicated against the store."""
        rec = extract_measurement(doc, source)
        rec["schema"] = STORE_SCHEMA
        rec["class"] = provenance_class(rec.get("provenance"))
        rec["digest"] = _record_digest(rec)
        if rec["digest"] in self._digests:
            return None
        self.records.append(rec)
        self._digests.add(rec["digest"])
        with open(self.store_path, "a") as f:
            f.write(json.dumps(rec, sort_keys=True) + "\n")
        return rec

    def ingest_path(self, path: str) -> Optional[dict]:
        try:
            with open(path) as f:
                doc = json.load(f)
        except OSError as e:
            raise TrendRefused(f"{path}: unreadable ({e})")
        except ValueError as e:
            raise TrendRefused(f"{path}: not JSON ({e})")
        return self.ingest_doc(doc, path)

    def ingest(self, paths) -> Tuple[int, int]:
        """Files and directories -> `(added, skipped)`. Directories are
        scanned for `BENCH_*.json` wrappers and `benchmarks/*_tpu*.json`
        artifacts; every refusal is a named warning, never a crash —
        one torn wrapper must not cost the rest of the trajectory."""
        files: List[str] = []
        for p in paths:
            if os.path.isdir(p):
                names = sorted(os.listdir(p))
                files += [
                    os.path.join(p, n)
                    for n in names
                    if n.startswith("BENCH_") and n.endswith(".json")
                ]
                bdir = os.path.join(p, "benchmarks")
                if os.path.isdir(bdir):
                    files += [
                        os.path.join(bdir, n)
                        for n in sorted(os.listdir(bdir))
                        if n.endswith(".json") and "_tpu" in n
                    ]
            else:
                files.append(p)
        added = skipped = 0
        for path in files:
            try:
                rec = self.ingest_path(path)
            except TrendRefused as e:
                warnings.warn(str(e))
                skipped += 1
                continue
            if rec is None:
                skipped += 1
            else:
                added += 1
        return added, skipped

    # -- the trajectory report ---------------------------------------
    def report(self) -> dict:
        """The deterministic trajectory document: a pure function of
        the store's record content (sorted keys, no wall-clock, no
        hostnames) — byte-identical however many times the same files
        were re-ingested."""
        classes: dict = {}
        series: dict = {}
        for seq, rec in enumerate(self.records):
            cls = rec.get("class", "unstamped")
            classes[cls] = classes.get(cls, 0) + 1
            noise = rec.get("spread") or {}
            for metric, value in sorted((rec.get("metrics") or {}).items()):
                point = {
                    "seq": seq,
                    "source": rec.get("source"),
                    "value": value,
                }
                if metric in noise:
                    point["noise_rel"] = noise[metric]
                series.setdefault(metric, {}).setdefault(cls, []).append(
                    point
                )

        regressions: List[dict] = []
        checked = 0
        metrics_doc: dict = {}
        for metric in sorted(series):
            direction = metric_direction(metric)
            per_class: dict = {}
            for cls in sorted(series[metric]):
                points = series[metric][cls]
                for prev, cur in zip(points, points[1:]):
                    if prev["value"]:
                        cur["delta_rel"] = round(
                            (cur["value"] - prev["value"]) / abs(prev["value"]),
                            4,
                        )
                    if direction is None:
                        continue
                    checked += 1
                    band = max(
                        REL_NOISE_FLOOR,
                        prev.get("noise_rel", 0.0),
                        cur.get("noise_rel", 0.0),
                    )
                    if not prev["value"]:
                        continue
                    change = (cur["value"] - prev["value"]) / abs(prev["value"])
                    worse = (
                        change < -band
                        if direction == "higher"
                        else change > band
                    )
                    if worse:
                        cur["flagged"] = True
                        regressions.append(
                            {
                                "metric": metric,
                                "class": cls,
                                "source": cur["source"],
                                "value": cur["value"],
                                "baseline_source": prev["source"],
                                "baseline": prev["value"],
                                "change_rel": round(change, 4),
                                "band_rel": round(band, 4),
                                "direction": direction,
                            }
                        )
                per_class[cls] = {
                    "points": points,
                    "last": points[-1]["value"],
                }
            metrics_doc[metric] = {
                "direction": direction,
                "classes": per_class,
            }
        return {
            "trend_version": TREND_VERSION,
            "records": len(self.records),
            "classes": {k: classes[k] for k in sorted(classes)},
            "metrics": metrics_doc,
            "sentinel": {
                "checked_deltas": checked,
                "noise_floor_rel": REL_NOISE_FLOOR,
                "regressions": regressions,
                "pass": not regressions,
            },
        }


def render_trend_markdown(doc: dict) -> str:
    """The trajectory as markdown tables, one per (metric, class)."""
    out = [
        "# Bench trend",
        "",
        f"{doc['records']} measurement record(s); classes: "
        + ", ".join(f"{k}={v}" for k, v in doc["classes"].items()),
        "",
    ]
    sent = doc["sentinel"]
    if sent["pass"]:
        out.append(
            f"**Regression sentinel: PASS** "
            f"({sent['checked_deltas']} delta(s) checked, noise floor "
            f"±{int(sent['noise_floor_rel'] * 100)}%)"
        )
    else:
        out.append(
            f"**Regression sentinel: {len(sent['regressions'])} "
            "REGRESSION(S)**"
        )
        for r in sent["regressions"]:
            out.append(
                f"- `{r['metric']}` [{r['class']}]: {r['baseline']} "
                f"({r['baseline_source']}) -> {r['value']} "
                f"({r['source']}), {r['change_rel']:+.1%} vs a "
                f"±{r['band_rel']:.0%} band"
            )
    out.append("")
    for metric, m in doc["metrics"].items():
        arrow = {"higher": "↑ better", "lower": "↓ better", None: "neutral"}[
            m["direction"]
        ]
        out.append(f"## {metric}  ({arrow})")
        out.append("")
        out.append("| class | source | value | delta | flag |")
        out.append("|---|---|---|---|---|")
        for cls, block in m["classes"].items():
            for p in block["points"]:
                delta = (
                    f"{p['delta_rel']:+.1%}" if "delta_rel" in p else "-"
                )
                flag = "REGRESSION" if p.get("flagged") else ""
                out.append(
                    f"| {cls} | {p['source']} | {p['value']} | {delta} "
                    f"| {flag} |"
                )
        out.append("")
    return "\n".join(out) + "\n"


def trend_main(argv=None) -> int:
    """`python -m federated_pytorch_test_tpu trend [PATHS...]`."""
    ap = argparse.ArgumentParser(
        prog="federated_pytorch_test_tpu trend",
        description="ingest BENCH wrappers / benchmark artifacts into "
        "the append-only trend store and report the per-metric, "
        "per-provenance-class trajectory with the regression sentinel",
    )
    ap.add_argument(
        "paths",
        nargs="*",
        default=["."],
        help="files or directories to ingest (dirs scan BENCH_*.json "
        "and benchmarks/*_tpu*.json); default: the current directory",
    )
    ap.add_argument(
        "--store",
        default="TREND.jsonl",
        help="append-only trend store path (default TREND.jsonl)",
    )
    ap.add_argument(
        "--debt",
        default=None,
        help="DEBT.json to close against newly-ingested provenanced "
        "measurements (default: ./DEBT.json when present; 'none' "
        "disables debt closing)",
    )
    ap.add_argument("--json", dest="json_out", default=None)
    ap.add_argument("--md", dest="md_out", default=None)
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)

    db = BenchDB(args.store)
    before = len(db.records)
    added, skipped = db.ingest(args.paths or ["."])

    debt_path = args.debt
    if debt_path is None and os.path.exists("DEBT.json"):
        debt_path = "DEBT.json"
    closed = []
    if debt_path and debt_path != "none" and os.path.exists(debt_path):
        from federated_pytorch_test_tpu.obs.debt import (
            close_entries,
            load_debt,
            save_debt,
        )

        try:
            doc = load_debt(debt_path)
        except ValueError as e:
            # a broken ledger must not cost the trend report
            warnings.warn(f"debt ledger unreadable, not closing: {e}")
            doc = None
        if doc is not None:
            for rec in db.records[before:]:
                closed += close_entries(doc, rec)
            if closed:
                save_debt(debt_path, doc)

    report = db.report()
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")
    md = render_trend_markdown(report)
    if args.md_out:
        with open(args.md_out, "w") as f:
            f.write(md)
    if not args.quiet:
        print(md, end="")
    sent = report["sentinel"]
    print(
        f"trend: {added} ingested, {skipped} skipped/deduped, "
        f"{report['records']} in store ({args.store}); sentinel "
        + ("PASS" if sent["pass"] else f"{len(sent['regressions'])} "
           "REGRESSION(S)")
        + (f"; debt closed: {', '.join(closed)}" if closed else "")
    )
    return 0 if sent["pass"] else 1
