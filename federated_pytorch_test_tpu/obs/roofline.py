"""Roofline telemetry: per-round cost models + achieved-utilization records.

ROADMAP item 2 asks for "an honest roofline note" on the memory-bound
L-BFGS epoch; until this module that note was prose assembled by hand
from `bench.py` output. Here the accounting is code, shared by
`bench.py`, `benchmarks/full_schedule_tpu.py`, and the trainer's
end-of-run `roofline` record:

* `chip_peaks(device_kind)` — the public spec-sheet (peak dense bf16 MXU
  TFLOP/s, peak HBM GB/s) pairs per TPU generation (previously a private
  table inside bench.py);
* `lbfgs_round_cost(...)` — the ANALYTIC cost model: bytes moved and
  FLOPs of one federated round derived from the static shape of the
  work (param count n, L-BFGS history m, inner iterations, line-search
  probes P, clients K, steps, nepoch, nadmm). This is the model behind
  the memory-bound argument: every model evaluation streams the full
  parameter vector through HBM, and each inner L-BFGS iteration streams
  the 2·m history vectors on top — BLAS1 traffic with O(m·n) FLOPs, far
  below any MXU ridge;
* `roofline_record(...)` — measured wall + FLOP/byte counts (XLA's
  `cost_analysis()` where a compiled program is at hand, the analytic
  model otherwise) → the record: achieved FLOP/s, MFU, achieved HBM
  bandwidth and its fraction of peak, arithmetic intensity vs the
  chip's ridge point, and the memory/compute verdict.

The record is ANALYSIS-ONLY: computing it involves no device dispatch
(cost analysis happens at AOT-compile time, walls come from the already-
recorded `step_time` series), and the trainer logs it `stream=False` —
walls are facts about THIS PROCESS (a resumed run's differ), so
streaming them would break the crash/resume stream-identity contract.
"""

from __future__ import annotations

from typing import Optional

# (peak dense MXU TFLOP/s in bf16, peak HBM GB/s) per device_kind prefix.
# Public spec-sheet numbers; 'TPU v5 lite' == v5e.
CHIP_PEAKS = {
    "TPU v5 lite": (197.0, 819.0),
    "TPU v5e": (197.0, 819.0),
    "TPU v5p": (459.0, 2765.0),
    "TPU v4": (275.0, 1228.0),
    "TPU v6 lite": (918.0, 1640.0),
    "TPU v6e": (918.0, 1640.0),
}


def chip_peaks(device_kind: str):
    """`(peak_tflops_bf16, peak_hbm_gbps)` for a device kind, or
    `(None, None)` when unknown (CPU hosts, new chips)."""
    for prefix, peaks in CHIP_PEAKS.items():
        if device_kind.startswith(prefix):
            return peaks
    return None, None


def lbfgs_round_cost(
    *,
    n_params: int,
    history: int,
    max_iter: int,
    k_clients: int,
    steps: int,
    nepoch: int = 1,
    nadmm: int = 1,
    ls_probes: int = 1,
    client_fold: str = "gemm",
    func_evals_per_step: Optional[float] = None,
    model_flops_per_sample: Optional[float] = None,
    batch: Optional[int] = None,
    dtype_bytes: int = 4,
) -> dict:
    """Analytic FLOPs / HBM bytes of ONE federated round's local work.

    Per optimizer step (one lockstep minibatch, one client):

    * `func_evals_per_step` model evaluations, each streaming the
      parameter vector in and the gradient out (2·n values). Default
      `1 + max_iter` — the floor of one value_and_grad per inner
      iteration plus the entry evaluation; pass the measured
      `mean_func_evals_per_step` (bench.py) for honest numbers (the
      Armijo search's extra probes are real traffic). Under the widened
      fold (`client_fold='gemm'`) a probe fan (`ls_probes` > 1) streams
      the parameters ONCE per widened pass — the amortization
      `--linesearch-probes` exists for — so the per-eval stream is
      divided by the fan width for the probe share. `client_fold='vmap'`
      gets NO such credit: there every probe carries its own full
      probe-batched parameter copy through the model (the whole tree is
      fan-batched), i.e. P independent parameter streams — the modeling
      bug this argument used to have (ISSUE-17 satellite: the old model
      amortized the fan unconditionally).
    * each of the `max_iter` inner iterations streams the 2·m-vector
      L-BFGS history (the compact/two-loop recursion's dominant reads)
      plus ~2·n of iterate/direction writes, costing ~8·m·n BLAS1 FLOPs.
    * `model_flops_per_sample` (forward+backward, per sample, per
      evaluation), when known, adds `func_evals · batch ·
      model_flops_per_sample`; without it the FLOP total covers the
      optimizer's BLAS1 terms only and is flagged as a lower bound.

    Totals multiply by `steps × nepoch × nadmm × k_clients`. This is an
    order-of-magnitude model for the roofline argument (activation
    traffic and XLA fusion are out of scope) — prefer XLA's
    `cost_analysis()` where a compiled program is available; this model
    is the fallback and the shape-level sanity check against it.
    """
    n, m = int(n_params), int(history)
    fe = float(
        func_evals_per_step
        if func_evals_per_step is not None
        else 1 + max_iter
    )
    # parameter streams: read params + write grads per evaluation; a
    # P-wide probe fan shares one parameter read across its P probes —
    # but only when the fold re-batches at the tree level ('gemm');
    # the 'vmap' fan batches the whole parameter tree along P, so each
    # probe streams its own full copy
    probe_share = max(0.0, fe - (1 + max_iter))
    base_evals = fe - probe_share
    shared = int(ls_probes) if client_fold == "gemm" else 1
    param_vals = (base_evals + probe_share / max(1, shared)) * 2 * n
    history_vals = max_iter * (2 * m * n + 2 * n)
    step_bytes = (param_vals + history_vals) * dtype_bytes
    step_flops = max_iter * 8.0 * m * n
    model_flops = 0.0
    if model_flops_per_sample is not None and batch:
        model_flops = fe * float(batch) * float(model_flops_per_sample)
    mult = int(steps) * int(nepoch) * int(nadmm) * int(k_clients)
    out = {
        "source": "analytic",
        "n_params": n,
        "lbfgs_history": m,
        "lbfgs_max_iter": int(max_iter),
        "ls_probes": int(ls_probes),
        "client_fold": client_fold,
        "func_evals_per_step": round(fe, 3),
        "steps_per_round": mult,
        "hbm_bytes": float(step_bytes * mult),
        "flops": float((step_flops + model_flops) * mult),
        # without model FLOPs the total is the optimizer's BLAS1 floor
        "model_flops_included": bool(model_flops),
    }
    if batch:
        # what M the MXU sees through the probe fan (the widened-GEMM
        # intensity claim as a number): the fold merges K·P·B example
        # rows into one contraction per frozen layer; without it each
        # of the K·P skinny dots carries M = B
        out["effective_gemm_m"] = int(
            int(k_clients) * int(ls_probes) * int(batch)
            if client_fold == "gemm" and int(ls_probes) > 1
            else int(batch)
        )
    return out


def roofline_record(
    *,
    wall_s: float,
    flops: Optional[float] = None,
    hbm_bytes: Optional[float] = None,
    device_kind: str = "",
    peak_tflops: Optional[float] = None,
    peak_hbm_gbps: Optional[float] = None,
    source: str = "measured",
    ndigits: int = 4,
    provenance: Optional[dict] = None,
) -> dict:
    """One roofline record: achieved rates vs the chip's two walls.

    `flops`/`hbm_bytes` come from XLA's `cost_analysis()` of the
    measured program (preferred) or `lbfgs_round_cost` (analytic);
    `wall_s` is the measured wall the work actually took. Peaks default
    to `chip_peaks(device_kind)`; on unknown chips the achieved rates
    are still reported, only the fractions are omitted. `provenance`
    (an obs/provenance.py stamp) is attached verbatim when given —
    passed explicitly by callers that already hold one, never probed
    here (this module stays import-cheap and backend-free).
    """
    if peak_tflops is None and peak_hbm_gbps is None and device_kind:
        peak_tflops, peak_hbm_gbps = chip_peaks(device_kind)
    rec: dict = {"source": source, "wall_s": round(float(wall_s), 4)}
    if provenance is not None:
        rec["provenance"] = provenance
    if device_kind:
        rec["device"] = device_kind
    if peak_tflops:
        rec["peak_tflops_bf16"] = peak_tflops
    if peak_hbm_gbps:
        rec["peak_hbm_gbps"] = peak_hbm_gbps
    if flops:
        tf = flops / wall_s / 1e12
        rec["achieved_tflops"] = round(tf, ndigits)
        if peak_tflops:
            rec["mfu"] = round(tf / peak_tflops, ndigits)
    if hbm_bytes:
        gbps = hbm_bytes / wall_s / 1e9
        rec["achieved_hbm_gbps"] = round(gbps, 1)
        if peak_hbm_gbps:
            rec["achieved_hbm_frac"] = round(gbps / peak_hbm_gbps, ndigits)
    if flops and hbm_bytes:
        rec["arithmetic_intensity"] = round(flops / hbm_bytes, 1)
    if peak_tflops and peak_hbm_gbps:
        ridge = round(peak_tflops * 1e12 / (peak_hbm_gbps * 1e9), 1)
        rec["ridge_intensity"] = ridge
        if "arithmetic_intensity" in rec:
            rec["bound"] = (
                "memory" if rec["arithmetic_intensity"] < ridge else "compute"
            )
    return rec
