"""Observability: streaming sinks, the communication ledger, trace export.

Three pillars over the structured metric store (`utils/metrics.py`):

* `JsonlSink` — a crash-safe append-only JSONL metric stream with
  per-outer-loop commit markers; `resume='auto'` replays it and truncates
  to the restore point, so a chaos run's metric series is continuous
  across crashes (sinks.py);
* `CommLedger` — exact per-round communicated bytes from the static
  `Partition` spec, dtype, and participation masks: the quantity the
  paper's bandwidth claim is about, finally measured (ledger.py);
* `TraceRecorder` / `DispatchCounter` — host-side span recording exported
  as Chrome trace-event JSON (loadable in Perfetto) plus dispatch- and
  recompile-count series, so fusion regressions show up as metrics
  (trace.py).
"""

from federated_pytorch_test_tpu.obs.ledger import CommLedger
from federated_pytorch_test_tpu.obs.sinks import JsonlSink
from federated_pytorch_test_tpu.obs.trace import DispatchCounter, TraceRecorder

__all__ = [
    "CommLedger",
    "DispatchCounter",
    "JsonlSink",
    "TraceRecorder",
]
