"""Observability: sinks, ledger, traces, health, roofline, registry,
flight recorder, memory telemetry, live console, provenance + trend.

Twelve pillars over the structured metric store (`utils/metrics.py`):

* `JsonlSink` — a crash-safe append-only JSONL metric stream with
  per-outer-loop commit markers; `resume='auto'` replays it and truncates
  to the restore point, so a chaos run's metric series is continuous
  across crashes (sinks.py);
* `CommLedger` — exact per-round communicated bytes from the static
  `Partition` spec, dtype, and participation masks: the quantity the
  paper's bandwidth claim is about, finally measured (ledger.py);
* `TraceRecorder` / `DispatchCounter` — host-side span recording exported
  as Chrome trace-event JSON (loadable in Perfetto) plus dispatch- and
  recompile-count series, so fusion regressions show up as metrics
  (trace.py);
* `HealthEngine` / `PercentileSketch` — streaming in-run statistics
  (P²-style online percentile sketches over loss / update norms /
  client-time tails) and a windowed anomaly monitor emitting a `health`
  series + `health:*` trace instants, replay-identical across crash and
  resume (health.py);
* `lbfgs_round_cost` / `roofline_record` / `chip_peaks` — the analytic
  per-round cost model and achieved-utilization accounting behind the
  trainer's, bench.py's, and full_schedule_tpu.py's `roofline` records
  (roofline.py);
* `RunRegistry` — the cross-run experiment registry behind the
  `python -m federated_pytorch_test_tpu report` CLI: validated stream
  ingestion, round-aligned comparisons, and the convergence-vs-bytes
  frontier (registry.py);
* `FlightRecorder` — a bounded ring over exactly the records the JSONL
  sink persists, dumped as self-contained `incident-<nloop>-<round>.json`
  bundles when the health engine fires or the process dies mid-run
  (flight.py; `report --incidents` tables them);
* `memory_record` / `host_rss_peak_bytes` — host RSS + per-device
  allocator stats as the process-local `memory` series and the
  bounded-RSS evidence ROADMAP item 4 gates on (memory.py);
* `watch_main` — the `watch` CLI verb: a refreshing terminal dashboard
  tailing metric streams through the registry's validated ingestion
  (console.py);
* `provenance_stamp` / `provenance_class` / `condition_satisfied` — the
  self-describing stamp (commit, backend, chip, host, repeats) attached
  to every measurement artifact, the isolation key the trend layer
  compares within, and the DEBT.json condition grammar (provenance.py);
* `BenchDB` / `trend_main` — the `trend` CLI verb: append-only trend
  store over BENCH wrappers and benchmark artifacts, keyed by (metric,
  provenance class), with the noise-aware regression sentinel
  (benchdb.py);
* `debt_main` — the `debt` CLI verb: the re-measurement debt ledger as
  data plus the runnable script that pays it (debt.py).
"""

from federated_pytorch_test_tpu.obs.benchdb import (
    BenchDB,
    TrendRefused,
    extract_measurement,
    metric_direction,
    render_trend_markdown,
    trend_main,
)
from federated_pytorch_test_tpu.obs.console import render, watch_main
from federated_pytorch_test_tpu.obs.debt import (
    close_entries,
    debt_main,
    emit_script,
    load_debt,
    open_entries,
    render_debt_markdown,
    save_debt,
)
from federated_pytorch_test_tpu.obs.flight import (
    MAX_INCIDENTS,
    FlightRecorder,
    incidents_dir,
    list_incidents,
    validate_incident,
)
from federated_pytorch_test_tpu.obs.health import (
    DEADLINE_WARMUP_OBS,
    DeadlineController,
    HealthEngine,
    P2Quantile,
    PercentileSketch,
)
from federated_pytorch_test_tpu.obs.ledger import CommLedger
from federated_pytorch_test_tpu.obs.memory import (
    device_memory_stats,
    host_rss_bytes,
    host_rss_peak_bytes,
    memory_record,
)
from federated_pytorch_test_tpu.obs.provenance import (
    STAMP_KEYS,
    cached_stamp,
    condition_satisfied,
    git_info,
    host_stamp,
    provenance_class,
    provenance_stamp,
)
from federated_pytorch_test_tpu.obs.registry import (
    RunRegistry,
    StreamRefused,
    read_stream,
    render_markdown,
    report_main,
)
from federated_pytorch_test_tpu.obs.roofline import (
    CHIP_PEAKS,
    chip_peaks,
    lbfgs_round_cost,
    roofline_record,
)
from federated_pytorch_test_tpu.obs.sinks import JsonlSink
from federated_pytorch_test_tpu.obs.trace import DispatchCounter, TraceRecorder

__all__ = [
    "BenchDB",
    "CHIP_PEAKS",
    "CommLedger",
    "DEADLINE_WARMUP_OBS",
    "DeadlineController",
    "DispatchCounter",
    "FlightRecorder",
    "HealthEngine",
    "JsonlSink",
    "MAX_INCIDENTS",
    "P2Quantile",
    "PercentileSketch",
    "RunRegistry",
    "STAMP_KEYS",
    "StreamRefused",
    "TraceRecorder",
    "TrendRefused",
    "cached_stamp",
    "chip_peaks",
    "close_entries",
    "condition_satisfied",
    "debt_main",
    "device_memory_stats",
    "emit_script",
    "extract_measurement",
    "git_info",
    "host_rss_bytes",
    "host_rss_peak_bytes",
    "host_stamp",
    "incidents_dir",
    "lbfgs_round_cost",
    "list_incidents",
    "load_debt",
    "memory_record",
    "metric_direction",
    "open_entries",
    "provenance_class",
    "provenance_stamp",
    "read_stream",
    "render",
    "render_debt_markdown",
    "render_markdown",
    "render_trend_markdown",
    "report_main",
    "roofline_record",
    "save_debt",
    "trend_main",
    "watch_main",
]
