"""Observability: sinks, comm ledger, traces, health, roofline, registry.

Six pillars over the structured metric store (`utils/metrics.py`):

* `JsonlSink` — a crash-safe append-only JSONL metric stream with
  per-outer-loop commit markers; `resume='auto'` replays it and truncates
  to the restore point, so a chaos run's metric series is continuous
  across crashes (sinks.py);
* `CommLedger` — exact per-round communicated bytes from the static
  `Partition` spec, dtype, and participation masks: the quantity the
  paper's bandwidth claim is about, finally measured (ledger.py);
* `TraceRecorder` / `DispatchCounter` — host-side span recording exported
  as Chrome trace-event JSON (loadable in Perfetto) plus dispatch- and
  recompile-count series, so fusion regressions show up as metrics
  (trace.py);
* `HealthEngine` / `PercentileSketch` — streaming in-run statistics
  (P²-style online percentile sketches over loss / update norms /
  client-time tails) and a windowed anomaly monitor emitting a `health`
  series + `health:*` trace instants, replay-identical across crash and
  resume (health.py);
* `lbfgs_round_cost` / `roofline_record` / `chip_peaks` — the analytic
  per-round cost model and achieved-utilization accounting behind the
  trainer's, bench.py's, and full_schedule_tpu.py's `roofline` records
  (roofline.py);
* `RunRegistry` — the cross-run experiment registry behind the
  `python -m federated_pytorch_test_tpu report` CLI: validated stream
  ingestion, round-aligned comparisons, and the convergence-vs-bytes
  frontier (registry.py).
"""

from federated_pytorch_test_tpu.obs.health import (
    DEADLINE_WARMUP_OBS,
    DeadlineController,
    HealthEngine,
    P2Quantile,
    PercentileSketch,
)
from federated_pytorch_test_tpu.obs.ledger import CommLedger
from federated_pytorch_test_tpu.obs.registry import (
    RunRegistry,
    StreamRefused,
    read_stream,
    render_markdown,
    report_main,
)
from federated_pytorch_test_tpu.obs.roofline import (
    CHIP_PEAKS,
    chip_peaks,
    lbfgs_round_cost,
    roofline_record,
)
from federated_pytorch_test_tpu.obs.sinks import JsonlSink
from federated_pytorch_test_tpu.obs.trace import DispatchCounter, TraceRecorder

__all__ = [
    "CHIP_PEAKS",
    "CommLedger",
    "DEADLINE_WARMUP_OBS",
    "DeadlineController",
    "DispatchCounter",
    "HealthEngine",
    "JsonlSink",
    "P2Quantile",
    "PercentileSketch",
    "RunRegistry",
    "StreamRefused",
    "TraceRecorder",
    "chip_peaks",
    "lbfgs_round_cost",
    "read_stream",
    "render_markdown",
    "report_main",
    "roofline_record",
]
