"""Streaming metric sinks: the crash-durable half of the metric store.

`MetricsRecorder` is in-memory; before this module a crash+resume lost
every recorded series even though the *parameters* recovered (the PR-1
checkpoint layer). A sink receives every record as it is logged and makes
it durable incrementally.

The JSONL line protocol (one JSON object per line):

    {"event": "stream_header", "version": 2, "tag": "<tag>", "crc": "..."}
    {"series": "<name>", "t": ..., "value": ..., <context>, "crc": "..."}
    {"event": "nloop_complete", "nloop": N, "crc": "..."}

* Every record is ONE line-buffered `write()` of a newline-terminated
  line, so a crash can tear at most the final line — never interleave or
  split earlier ones.
* Version 2 stamps every line with a CRC over its other fields
  (fault/io.py `stamp_crc`): the torn-tail tolerance used to trust any
  JSON-PARSABLE line, so a bit-rotted-but-parsable line would have been
  spliced into resume/report as truth — now it is dropped (with
  everything after it) exactly like a torn tail. Version-1 streams are
  still READ by the report tooling (obs/registry.py), but resume onto
  one starts fresh: appending checksummed lines to an unchecksummed
  stream would leave a file neither reader fully trusts.
* `flush()` (called by the trainer once per partition round) pushes the
  buffer to the OS; `commit(nloop)` (called at each outer-loop checkpoint
  boundary) writes the marker line and fsyncs: everything before a marker
  is durable and complete.
* On `resume='auto'` the trainer reopens the stream with
  `open(resume_nloops=C)`: the file is truncated to the byte just past
  the `nloop_complete` marker of loop `C-1` (the restore point — the
  rounds after it will be re-run and re-recorded), the surviving records
  are returned for replay into the in-memory store, and writing resumes
  in append mode. A torn final line or any garbage past the last parsable
  line is discarded. The resumed stream is therefore identical to an
  uninterrupted run's (modulo wall-clock `t` fields) — the continuity
  contract tested in tests/test_obs.py.
* A header-tag mismatch (different preset/seed/fault plan writing to the
  same path) or a missing restore-point marker abandons the old stream
  with a warning and starts fresh: splicing two different experiments'
  series would be worse than losing one.
* DEFERRED records (async evals, utils/metrics.py Deferred) never reach
  `record()` unresolved: the recorder queues them — and every streamed
  record behind them, preserving order — until its round-boundary
  harvest, and always resolves the queue BEFORE `commit(nloop)` writes a
  marker. A leaked thunk would fail `json.dumps` loudly here rather than
  corrupt a line.
"""

from __future__ import annotations

import json
import os
import warnings
from typing import Any, List, Optional, Tuple

import numpy as np

from federated_pytorch_test_tpu.fault.io import retry_io, stamp_crc, verify_crc

STREAM_VERSION = 2


def jsonable(o: Any):
    """`json.dumps` default hook for the numpy scalars/arrays metric
    values occasionally carry (recorder APIs convert, raw `log()` calls
    may not)."""
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, np.bool_):
        return bool(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON-serializable: {type(o).__name__}")


class JsonlSink:
    """Crash-safe append-only JSONL metric stream (see module docstring).

    Lifecycle: construct, `open(...)` (returns records to replay), then
    `record`/`flush`/`commit` from the recorder, `close()` at run end.
    All writers are no-ops after `close()` — a test poking a finished
    trainer must not crash on a closed file.
    """

    MARKER = "nloop_complete"

    def __init__(self, path: str, tag: str = "", storage_io=None):
        self.path = os.path.abspath(path)
        self.tag = tag
        # optional fault/io.py StorageFaultShim: the metrics stream is a
        # disk-facing byte path too, so write-side chaos (ioerror/enospc
        # plans) exercises it — reads go through obs/registry.py which
        # verifies per-line CRCs instead
        self._io = storage_io
        self._f = None

    # ------------------------------------------------------------ lifecycle

    def open(
        self, resume_nloops: Optional[int] = None
    ) -> List[Tuple[str, dict]]:
        """Open the stream; returns `[(series, record), ...]` to replay.

        `resume_nloops=None` starts a fresh stream (truncating any prior
        file); an integer `C` resumes: truncate to the commit marker of
        loop `C-1` (just the header for `C == 0`) and replay what's kept.
        """
        if resume_nloops is None or not os.path.exists(self.path):
            self._start_fresh()
            return []
        records, cut = self._scan(int(resume_nloops))
        if cut is None:
            self._start_fresh()
            return []
        os.truncate(self.path, cut)
        self._f = open(self.path, "a", buffering=1)
        return records

    def _start_fresh(self) -> None:
        d = os.path.dirname(self.path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(self.path, "w", buffering=1)
        self._write(
            {"event": "stream_header", "version": STREAM_VERSION, "tag": self.tag}
        )

    def _scan(self, resume_nloops: int):
        """Find the truncation offset for a resume at `resume_nloops`.

        Returns `(records_to_replay, byte_offset)`; offset None means the
        stream cannot be resumed (tag mismatch, no header, or the restore
        point's marker is missing) and a fresh stream must be started.
        """
        with open(self.path, "rb") as f:
            data = f.read()
        pos = 0
        cut = None
        upto: List[Tuple[str, dict]] = []
        records: List[Tuple[str, dict]] = []
        header_seen = False
        for raw in data.splitlines(keepends=True):
            end = pos + len(raw)
            if not raw.endswith(b"\n"):
                break  # torn tail from a crash mid-write
            try:
                d = json.loads(raw.decode("utf-8"))
            except (UnicodeDecodeError, ValueError):
                break  # corrupt line: nothing past it is trustworthy
            if not header_seen:
                header_seen = True
                if (
                    d.get("event") != "stream_header"
                    or d.get("tag") != self.tag
                ):
                    warnings.warn(
                        f"metric stream {self.path} was written by a "
                        f"different experiment (tag {d.get('tag')!r} != "
                        f"{self.tag!r}); starting a fresh stream"
                    )
                    return [], None
                if d.get("version") != STREAM_VERSION:
                    # never append v2 checksummed lines to a v1 stream
                    # (or vice versa): the mixed file would have no
                    # version a reader could fully trust
                    warnings.warn(
                        f"metric stream {self.path} is format version "
                        f"{d.get('version')!r} (writer is "
                        f"{STREAM_VERSION}); starting a fresh stream"
                    )
                    return [], None
                if not verify_crc(d):
                    warnings.warn(
                        f"metric stream {self.path} header failed its "
                        "line checksum; starting a fresh stream"
                    )
                    return [], None
                if resume_nloops == 0:
                    cut = end  # keep just the header; re-run records all
                pos = end
                continue
            if not verify_crc(d):
                # bit-rotted-but-parsable line: drop it AND everything
                # after it, exactly like a torn tail — nothing past a
                # corrupt line is trustworthy
                break
            d.pop("crc", None)  # replayed records match in-memory ones
            if d.get("event") == self.MARKER:
                if int(d.get("nloop", -1)) == resume_nloops - 1:
                    # the restore point: records before it are final
                    cut = end
                    records = list(upto)
            elif "series" in d:
                name = d.pop("series")
                upto.append((name, d))
            pos = end
        if cut is None and header_seen:
            warnings.warn(
                f"metric stream {self.path} has no commit marker for "
                f"outer loop {resume_nloops - 1} (checkpoints and stream "
                "are out of step); starting a fresh stream"
            )
            return [], None
        return records, cut

    def close(self) -> None:
        if self._f is not None:
            self._f.flush()
            self._f.close()
            self._f = None

    # -------------------------------------------------------------- writers

    def _write(self, d: dict) -> None:
        # one write per line; line buffering makes the newline the flush
        # boundary, so a crash tears at most this line. stamp_crc splices
        # the line checksum in as the last key (fault/io.py).
        line = stamp_crc(d, default=jsonable) + "\n"
        if self._io is not None:
            # chaos shim: transient write faults (ioerror/enospc plans)
            # fire BEFORE the bytes move and get the shared bounded
            # retry; the actual write below happens exactly once
            retry_io(
                lambda: self._io.before_write("metrics stream"),
                what=f"metrics stream write ({os.path.basename(self.path)})",
            )
        self._f.write(line)

    def record(self, name: str, rec: dict) -> None:
        if self._f is not None:
            self._write({"series": name, **rec})

    def commit(self, nloop: int) -> None:
        """Durability barrier: marker + fsync at a checkpoint boundary."""
        if self._f is not None:
            self._write({"event": self.MARKER, "nloop": int(nloop)})
            self._f.flush()
            os.fsync(self._f.fileno())

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()
