"""In-run health engine: streaming statistics + anomaly detection.

The raw metric streams (obs/sinks.py) record everything and judge
nothing: whether a run is *healthy* — losses finite and moving, no
rollback churn, the quarantine not firing every round, deadlines mostly
made — was a post-hoc grep until this module. The `HealthEngine` watches
the same records the JSONL sink receives and distills them, once per
partition round, into a structured `health` series record plus
`health:*` trace instants when an anomaly fires.

Two kinds of state, both bounded:

* **P²-style percentile sketches** (`P2Quantile` / `PercentileSketch`,
  Jain & Chlamtac 1985): online p50/p95/p99 estimates over the
  `train_loss`, `update_norm`, and `client_time` observations in five
  markers per quantile — O(1) memory, no array retention, one pass.
  The `client_time` sketch is the online tail-latency estimate ROADMAP
  item 4's learned deadlines will consume: it ingests each exchange's
  cross-client p95 simulated time, so its p50 is a stable "typical p95"
  deadline signal and its p95 a conservative one.
* **a windowed round monitor**: per-round counters (non-finite
  observations, detected faults, rollbacks, quarantined clients,
  deadline misses, exchanges) kept for the last `window` completed
  rounds, yielding rates plus loss-explosion / loss-plateau detection
  against the windowed per-round mean-loss history and quarantine-burst
  / deadline-miss-spike detection against the windowed counter means
  (the flight recorder's full trigger set, obs/flight.py).

Crash-safety rides the usual resume-stream-identity contract
(docs/OBSERVABILITY.md): the engine is a PURE function of the streamed
record sequence — it consumes values exactly as they appear in the JSONL
stream (floats JSON-round-trip exactly), never wall-clock `t` fields —
so a resumed run replays the kept records through `replay()` and
continues with bit-identical internal state: a crashed+resumed run's
`health` series equals an uninterrupted twin's. The engine does no
device work at all: every input is a host value the trainer already
fetched, so enabling it adds zero dispatches (the folded round stays
`{round: 1, round_init: 1}`).

The knobs (`health_monitor`, `health_window`) are analysis-only — they
never change the training trajectory — so they are excluded from the
metrics-stream header tag: a resumed run may flip them and still splice
(engine/trainer.py `_stream_tag`).
"""

from __future__ import annotations

import collections
import math
from typing import Any, Iterable, List, Optional, Tuple

DEFAULT_QUANTILES = (0.5, 0.95, 0.99)


def _quantile_key(q: float) -> str:
    """0.5 -> 'p50', 0.95 -> 'p95', 0.99 -> 'p99' (no trailing zeros)."""
    s = f"{100.0 * q:g}"
    return "p" + s.replace(".", "_")


class P2Quantile:
    """One quantile, estimated online with the P² algorithm.

    Five markers (min, three interior, max) adjusted per observation by
    parabolic (fallback linear) interpolation toward their desired
    positions — O(1) memory and update cost. Exact for the first five
    observations (sorted-buffer interpolation); thereafter an estimate
    whose rank error the sketch tests bound against numpy on adversarial
    sequences (tests/test_health.py). Non-finite observations are
    ignored (a NaN marker height would poison every later estimate).
    """

    __slots__ = ("q", "count", "_init", "_h", "_n", "_np", "_dn")

    def __init__(self, q: float):
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {q}")
        self.q = float(q)
        self.count = 0
        self._init: List[float] = []
        self._h: Optional[List[float]] = None  # marker heights
        self._n: Optional[List[float]] = None  # marker positions (1-based)
        self._np: Optional[List[float]] = None  # desired positions
        self._dn: Optional[List[float]] = None  # desired-position increments

    def update(self, x: float) -> None:
        x = float(x)
        if not math.isfinite(x):
            return
        self.count += 1
        if self._h is None:
            self._init.append(x)
            if len(self._init) == 5:
                self._init.sort()
                q = self.q
                self._h = list(self._init)
                self._n = [1.0, 2.0, 3.0, 4.0, 5.0]
                self._np = [1.0, 1 + 2 * q, 1 + 4 * q, 3 + 2 * q, 5.0]
                self._dn = [0.0, q / 2, q, (1 + q) / 2, 1.0]
            return
        h, n, np_, dn = self._h, self._n, self._np, self._dn
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 3
            for i in range(4):
                if h[i] <= x < h[i + 1]:
                    k = i
                    break
        for i in range(k + 1, 5):
            n[i] += 1.0
        for i in range(5):
            np_[i] += dn[i]
        for i in (1, 2, 3):
            d = np_[i] - n[i]
            if (d >= 1.0 and n[i + 1] - n[i] > 1.0) or (
                d <= -1.0 and n[i - 1] - n[i] < -1.0
            ):
                s = 1.0 if d >= 1.0 else -1.0
                si = int(s)
                hp = h[i] + s / (n[i + 1] - n[i - 1]) * (
                    (n[i] - n[i - 1] + s) * (h[i + 1] - h[i]) / (n[i + 1] - n[i])
                    + (n[i + 1] - n[i] - s) * (h[i] - h[i - 1]) / (n[i] - n[i - 1])
                )
                if not (h[i - 1] < hp < h[i + 1]):
                    # parabolic prediction left the bracket: linear step
                    hp = h[i] + s * (h[i + si] - h[i]) / (n[i + si] - n[i])
                h[i] = hp
                n[i] += s

    def value(self) -> Optional[float]:
        if self.count == 0:
            return None
        if self._h is None:
            xs = sorted(self._init)
            pos = self.q * (len(xs) - 1)
            lo = int(math.floor(pos))
            hi = min(lo + 1, len(xs) - 1)
            return xs[lo] + (pos - lo) * (xs[hi] - xs[lo])
        return self._h[2]


class PercentileSketch:
    """A bundle of `P2Quantile`s over one observation stream."""

    def __init__(self, quantiles: Iterable[float] = DEFAULT_QUANTILES):
        self.quantiles = tuple(float(q) for q in quantiles)
        self._est = [P2Quantile(q) for q in self.quantiles]

    def update(self, x: float) -> None:
        try:
            x = float(x)
        except (TypeError, ValueError):
            return
        if not math.isfinite(x):
            return
        for e in self._est:
            e.update(x)

    @property
    def count(self) -> int:
        return self._est[0].count if self._est else 0

    def estimates(self, ndigits: int = 6) -> Optional[dict]:
        """`{"p50": ..., "p95": ..., "p99": ..., "n": count}` or None
        while empty. Rounded for record compactness — rounding is
        deterministic, so twin streams stay identical."""
        if self.count == 0:
            return None
        out = {
            _quantile_key(q): round(float(e.value()), ndigits)
            for q, e in zip(self.quantiles, self._est)
        }
        out["n"] = self.count
        return out


def _median(xs: List[float]) -> float:
    ys = sorted(xs)
    m = len(ys) // 2
    return ys[m] if len(ys) % 2 else 0.5 * (ys[m - 1] + ys[m])


# observations the deadline sketch needs before its estimate replaces
# the warmup constant — the P² initialization threshold (estimates are
# exact sorted-buffer interpolation below it, but a deadline pinned to
# one or two early samples would whipsaw the budget schedule)
DEADLINE_WARMUP_OBS = 5


class DeadlineController:
    """The closed-loop `--round-deadline auto[:pXX]` policy.

    Tracks the SAME online `client_time` signal the health engine
    sketches — each consensus exchange's cross-client p95 simulated
    time, the record `engine/trainer.py _record_hetero` streams — in a
    P² percentile sketch of its own (the controller must work with
    `--no-health-monitor`, and its quantile is the operator's `pXX`,
    default p50: ROADMAP item 3's "typical p95" deadline). `decide()`
    returns the deadline for the NEXT round from the observations
    already streamed; until the sketch holds `DEADLINE_WARMUP_OBS`
    observations it returns the warmup constant (the nominal full-work
    time `total_steps * step_time_s`: nominal-speed clients get full
    budgets, stragglers already get clipped).

    Purity contract (the replay-identity gate, tests/test_fleet.py):
    the controller is a pure function of the streamed `client_time`
    record sequence — wired as a recorder OBSERVER like `HealthEngine`,
    fed replayed records through `replay()` on resume BEFORE attaching,
    so a crashed+resumed run re-decides every deadline identically to
    its uninterrupted twin. Decisions are rounded to 6 digits (like the
    sketch estimates) so the recorded `deadline` series and the budget
    arithmetic consume the identical float. The trainer REFUSES to
    resume an auto-deadline run without a metrics stream to replay —
    re-estimating the sketch fresh would silently shift every
    post-resume budget schedule (engine/trainer.py).
    """

    def __init__(self, quantile: float, warmup_s: float,
                 min_obs: int = DEADLINE_WARMUP_OBS):
        if not 0.0 < quantile < 1.0:
            raise ValueError(
                f"deadline quantile must be in (0, 1), got {quantile}"
            )
        if not (math.isfinite(warmup_s) and warmup_s > 0):
            raise ValueError(
                f"deadline warmup must be finite and > 0, got {warmup_s}"
            )
        self.quantile = float(quantile)
        self.warmup_s = float(warmup_s)
        self.min_obs = int(min_obs)
        self.sketch = PercentileSketch((self.quantile,))

    # recorder-observer protocol (utils/metrics.py observers)
    def observe(self, name: str, rec: dict) -> None:
        if name != "client_time":
            return
        v = rec.get("value")
        if isinstance(v, dict):
            p95 = v.get("p95")
            if p95 is not None:
                self.sketch.update(p95)

    def replay(self, records: Iterable[Tuple[str, dict]]) -> None:
        """Rebuild sketch state from a resumed stream's replayed records
        (stream order — the same sequence `observe` saw live)."""
        for name, rec in records:
            self.observe(name, rec)

    def decide(self) -> Tuple[float, dict]:
        """The next round's deadline plus its provenance dict (the
        `deadline` record value minus the seconds): `source` is
        'warmup' below `min_obs` observations, else 'sketch'; `n_obs`
        is the sketch count the decision was taken at."""
        n = self.sketch.count
        if n < self.min_obs:
            return self.warmup_s, {"source": "warmup", "n_obs": n}
        est = self.sketch.estimates()
        val = round(float(est[_quantile_key(self.quantile)]), 6)
        # a degenerate fleet (all-zero times cannot happen — client
        # times are total*step_time*speed > 0) still must never emit a
        # non-positive deadline, which config validation forbids
        return max(val, 1e-9), {"source": "sketch", "n_obs": n}


# per-round counter template (one dict per partition round)
_ROUND_KEYS = (
    "nonfinite", "faults", "rollbacks", "quarantined", "deadline_missed",
)


class HealthEngine:
    """Streaming in-run health: sketches + windowed anomaly monitor.

    Wiring (engine/trainer.py): the engine sits on
    `MetricsRecorder.observers` and receives every STREAMED record at
    log time via `observe(name, rec)` — exactly the records (and order)
    the JSONL sink persists, which is what makes `replay()` reconstruct
    identical state on resume. At each partition-round boundary the
    trainer calls `round_record()` for the `health` record value and the
    round's anomaly list (emitted as `health:<kind>` trace instants),
    which also advances the round window.

    On `resume='auto'` the trainer feeds the sink's replayed records
    through `replay()` BEFORE attaching the observer: raw records
    re-update the sketches/counters and each replayed `health` record
    advances the window, so the resumed engine's state equals the
    crashed process's at the truncation point. Without a metrics stream
    a resumed engine starts cold (like the quarantine scoreboard, the
    windowed history is resume-proof only via a replayed stream).
    """

    def __init__(
        self,
        window: int = 8,
        explode_factor: float = 10.0,
        plateau_rtol: float = 1e-3,
        quantiles: Iterable[float] = DEFAULT_QUANTILES,
    ):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = int(window)
        self.explode_factor = float(explode_factor)
        self.plateau_rtol = float(plateau_rtol)
        self.loss = PercentileSketch(quantiles)
        self.update_norm = PercentileSketch(quantiles)
        self.client_time = PercentileSketch(quantiles)
        self.rounds = 0  # completed (advanced-past) rounds
        self.anomalies_total = 0
        # completed rounds' counter dicts (plus per-round "loss_mean")
        self._win: collections.deque = collections.deque(maxlen=self.window)
        self._cur = self._blank()

    @staticmethod
    def _blank() -> dict:
        d = {k: 0 for k in _ROUND_KEYS}
        d["loss_sum"] = 0.0
        d["loss_n"] = 0
        return d

    # ------------------------------------------------------------ ingestion

    def observe(self, name: str, rec: dict) -> None:
        """One streamed record, at log (or replay) time. Pure in the
        record sequence; `t` and other wall-clock fields are never read.
        Series the engine does not understand — including its own
        `health` records (the replay path segments on those instead) —
        are ignored."""
        v = rec.get("value")
        if name == "train_loss" and isinstance(v, (list, tuple)):
            cur = self._cur
            for x in v:
                try:
                    x = float(x)
                except (TypeError, ValueError):
                    continue
                if math.isfinite(x):
                    self.loss.update(x)
                    cur["loss_sum"] += x
                    cur["loss_n"] += 1
                else:
                    cur["nonfinite"] += 1
        elif name == "update_norm" and isinstance(v, (list, tuple)):
            for x in v:
                if x is None:
                    # a null norm marks a non-finite (corrupted) update
                    # (utils/metrics.py update_norms)
                    self._cur["nonfinite"] += 1
                else:
                    self.update_norm.update(x)
        elif name == "client_time" and isinstance(v, dict):
            p95 = v.get("p95")
            if p95 is not None:
                self.client_time.update(p95)
        elif name == "fault" and isinstance(v, dict):
            kind = v.get("kind")
            if kind == "round_rollback":
                self._cur["rollbacks"] += 1
            else:
                self._cur["faults"] += 1
        elif name == "quarantine" and isinstance(v, dict):
            self._cur["quarantined"] += len(v.get("clients", ()))
        elif name == "deadline_miss" and isinstance(v, dict):
            self._cur["deadline_missed"] += len(v.get("clients", ()))

    def replay(self, records: Iterable[Tuple[str, dict]]) -> None:
        """Rebuild state from a resumed stream's replayed records
        (obs/sinks.py `open(resume_nloops=...)` output, in stream
        order). Raw records re-ingest; each replayed `health` record
        marks a completed round and advances the window exactly as the
        live `round_record()` did when it was written."""
        for name, rec in records:
            if name == "health":
                v = rec.get("value")
                if isinstance(v, dict):
                    self.anomalies_total += len(v.get("anomalies", ()))
                self._advance()
            else:
                self.observe(name, rec)

    # ------------------------------------------------------- round boundary

    def _advance(self) -> None:
        cur = self._cur
        cur["loss_mean"] = (
            cur["loss_sum"] / cur["loss_n"] if cur["loss_n"] else None
        )
        self._win.append(cur)
        self._cur = self._blank()
        self.rounds += 1

    def round_record(self) -> Tuple[dict, List[str]]:
        """Close the current partition round: returns `(value,
        anomalies)` — the `health` record value plus the round's anomaly
        kinds — and advances the window. Deterministic in the observed
        record sequence (twin runs emit identical values)."""
        cur = self._cur
        mean_loss = cur["loss_sum"] / cur["loss_n"] if cur["loss_n"] else None
        prev_means = [
            r["loss_mean"] for r in self._win if r["loss_mean"] is not None
        ]

        anomalies: List[str] = []
        if cur["nonfinite"] or cur["faults"]:
            anomalies.append("nonfinite")
        if cur["rollbacks"]:
            anomalies.append("rollback")
        # burst/spike detection (the flight recorder's trigger set,
        # obs/flight.py): a round whose quarantine or deadline-miss
        # count at least doubles the windowed mean — with a floor of 2,
        # so a single flagged client never pages — is an incident; a
        # CHRONIC rate (every round missing the same 2) stops alerting
        # once the window has absorbed it. Pure in the record sequence.
        prev = list(self._win)

        def _spike(key: str) -> bool:
            n = cur[key]
            if n < 2:
                return False
            base = sum(r[key] for r in prev) / len(prev) if prev else 0.0
            return n > 2.0 * base

        if _spike("quarantined"):
            anomalies.append("quarantine_burst")
        if _spike("deadline_missed"):
            anomalies.append("deadline_miss_spike")
        if mean_loss is not None and prev_means:
            med = _median(prev_means)
            if med > 0 and mean_loss > self.explode_factor * med:
                anomalies.append("loss_explosion")
        means = prev_means + ([mean_loss] if mean_loss is not None else [])
        if len(means) >= self.window + 1:
            spread = max(means) - min(means)
            scale = max(abs(_median(means)), 1e-12)
            if spread <= self.plateau_rtol * scale:
                anomalies.append("loss_plateau")

        rounds_w = list(self._win) + [cur]
        n = len(rounds_w)

        def rate(key: str) -> float:
            return round(sum(r[key] for r in rounds_w) / n, 6)

        window = {
            "rounds": n,
            "nonfinite_rate": rate("nonfinite"),
            "fault_rate": rate("faults"),
            "rollback_rate": rate("rollbacks"),
            "quarantine_rate": rate("quarantined"),
            "deadline_miss_rate": rate("deadline_missed"),
            "loss_mean": (
                round(mean_loss, 6) if mean_loss is not None else None
            ),
        }
        value: dict = {
            "round": self.rounds,
            "anomalies": anomalies,
            "window": window,
        }
        if self.loss.count:
            value["train_loss"] = self.loss.estimates()
        if self.update_norm.count:
            value["update_norm"] = self.update_norm.estimates()
        if self.client_time.count:
            value["client_time"] = self.client_time.estimates()
        self.anomalies_total += len(anomalies)
        self._advance()
        return value, anomalies
