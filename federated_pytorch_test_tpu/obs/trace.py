"""Host-side tracing: Chrome trace-event export + dispatch/recompile counts.

`jax.profiler.trace` (config.profile_dir) captures device timelines but
needs TensorBoard tooling and profiles *programs*, not the trainer's loop
nest. `TraceRecorder` is the complementary host-side view: every
round/epoch/consensus/compile region the trainer enters becomes one
span in a Chrome trace-event JSON. Evals appear as a SPLIT pair —
`eval_enqueue` (the async program dispatch, inside its round's span) and
`eval_harvest` (the deferred device->host fetch at the round-boundary
flush, after the round span) — or not at all when they are folded into
the fused round program (docs/OBSERVABILITY.md). Drag the file into
https://ui.perfetto.dev (or chrome://tracing) and the whole experiment's
nesting, stalls, and per-phase walls are a timeline. The span context
managers are shared with the `step_time` metric calls
(`MetricsRecorder.phase`), so the trace and the timing series can never
disagree about what was measured.

`DispatchCounter` turns PR 2's headline property — one jitted dispatch
per fused round — into a *recorded series* instead of a one-off test
assertion: every jitted program the trainer builds is wrapped in a
counting proxy (tagged at its `engine/steps.py` build site), per-round
deltas land in a `dispatch_count` series, and the number of distinct
compiled programs (sampled from jax's jit caches) lands in
`recompile_count`. A change that silently de-fuses a round or triggers
per-round recompiles now shows up in the metrics of every run, not vibes.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List

from federated_pytorch_test_tpu.obs.sinks import jsonable


class TraceRecorder:
    """Records host-side spans as Chrome trace-event JSON.

    Events use the "X" (complete) phase with microsecond timestamps on a
    single host track; Perfetto nests them by time containment, which
    mirrors the trainer's `round > {epoch, consensus, eval}` structure.
    `save()` writes the JSON-object trace format
    (`{"traceEvents": [...]}`) atomically (tmp + rename).
    """

    def __init__(self, label: str = "fedtpu host"):
        self._t0 = time.perf_counter()
        self._pid = os.getpid()
        # per-thread track ids: Chrome-trace complete ("X") events on
        # ONE track must nest by time containment, and the cohort
        # prefetcher's spans (clients/prefetch.py) deliberately OVERLAP
        # the main thread's round spans — on a shared track Perfetto
        # would mis-nest them. The constructing (main) thread keeps the
        # historical track 0; each further thread gets the next small id.
        self._tids: Dict[int, int] = {threading.get_ident(): 0}
        self._tids_lock = threading.Lock()
        self.events: List[dict] = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": self._pid,
                "tid": 0,
                "args": {"name": label},
            }
        ]

    def _tid(self) -> int:
        ident = threading.get_ident()
        tid = self._tids.get(ident)
        if tid is None:
            with self._tids_lock:  # two first-touching threads must not
                # both read len() before either inserts (same track id
                # == the very mis-nesting per-thread tracks prevent)
                tid = self._tids.setdefault(ident, len(self._tids))
        return tid

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    @contextlib.contextmanager
    def span(self, name: str, **args):
        """One complete ("X") event covering the with-block, crash-safe:
        the event is recorded even when the block raises (an InjectedCrash
        mid-round still leaves its span in the trace)."""
        t0 = self._now_us()
        try:
            yield
        finally:
            self.events.append(
                {
                    "name": name,
                    "cat": "trainer",
                    "ph": "X",
                    "ts": round(t0, 3),
                    "dur": round(self._now_us() - t0, 3),
                    "pid": self._pid,
                    "tid": self._tid(),
                    "args": args,
                }
            )

    def instant(self, name: str, **args) -> None:
        """A zero-duration marker (faults, crash points)."""
        self.events.append(
            {
                "name": name,
                "cat": "trainer",
                "ph": "i",
                "s": "t",
                "ts": round(self._now_us(), 3),
                "pid": self._pid,
                "tid": self._tid(),
                "args": args,
            }
        )

    def counter(self, name: str, values: Dict[str, int]) -> None:
        """A counter ("C") sample — cumulative dispatch counts per round."""
        self.events.append(
            {
                "name": name,
                "cat": "trainer",
                "ph": "C",
                "ts": round(self._now_us(), 3),
                "pid": self._pid,
                "args": {k: int(v) for k, v in values.items()},
            }
        )

    def to_dict(self) -> dict:
        return {"traceEvents": list(self.events), "displayTimeUnit": "ms"}

    def save(self, path: str) -> str:
        """Atomically write the trace (the checkpoint writer's tmp+rename
        pattern: a crash mid-write must not leave torn JSON)."""
        path = os.path.abspath(path)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            # span args arrive from arbitrary call sites and may carry
            # numpy scalars — same hook the JSONL sink uses
            json.dump(self.to_dict(), f, default=jsonable)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path


class _CountedProgram:
    """Transparent counting proxy around one jitted program.

    Forwards everything (`lower`, `trace`, ...) to the wrapped function so
    AOT-seeding (`Trainer.compile_round`) and benchmarks keep working;
    only `__call__` is intercepted.
    """

    def __init__(self, fn, counter: "DispatchCounter", category: str):
        self._fn = fn
        self._counter = counter
        self._category = category

    def __call__(self, *args, **kwargs):
        c = self._counter.counts
        c[self._category] = c.get(self._category, 0) + 1
        return self._fn(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)


class DispatchCounter:
    """Counts jitted-program dispatches and compiled-program cache growth.

    `wrap(fn, category)` is called by the `engine/steps.py` builders (the
    one place that knows what kind of program it built); the trainer
    snapshots `counts` around each partition round to produce the
    per-round `dispatch_count` deltas, and samples `compiled_programs()`
    — the summed jit-cache sizes of every tracked program — for the
    `recompile_count` series. The cache sizes are read through the jit
    object's `_cache_size()` (private but stable across the pinned jax
    line; absent attributes degrade to not-counted, never to a crash).
    """

    def __init__(self):
        self.counts: Dict[str, int] = {}
        self._programs: List[_CountedProgram] = []

    def wrap(self, fn, category: str):
        if fn is None:
            return None
        p = _CountedProgram(fn, self, category)
        self._programs.append(p)
        return p

    def snapshot(self) -> Dict[str, int]:
        return dict(self.counts)

    def delta_since(self, snap: Dict[str, int]) -> Dict[str, int]:
        d = {
            k: v - snap.get(k, 0)
            for k, v in self.counts.items()
            if v - snap.get(k, 0)
        }
        d["total"] = sum(d.values())
        return d

    def compiled_programs(self) -> int:
        n = 0
        for p in self._programs:
            cache_size = getattr(p._fn, "_cache_size", None)
            if callable(cache_size):
                try:
                    n += int(cache_size())
                except Exception:
                    pass
        return n
