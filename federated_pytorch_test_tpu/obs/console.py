"""Live fleet console: `python -m federated_pytorch_test_tpu watch DIR`.

`report` (obs/registry.py) is the post-hoc verb; nothing watched a run
WHILE it ran — the operator tailing a JSONL stream by eye is the gap
this module closes. `watch` re-reads a directory (or one file) of
`--metrics-stream` files every `--interval` seconds through the
registry's validated ingestion — the SAME parser `report` and resume
use, so torn tails from a crash mid-write are tolerated and foreign
headers are refused, never half-read — and renders a refreshing
terminal dashboard per run:

* accuracy and per-round mean-loss sparklines (the tail, newest right),
* health verdict (rounds monitored, anomalies, the last round's kinds),
* comm uplink + bytes the adaptive scheduler saved by skipping,
* fleet counters: quarantined clients, churn absences, cohort size,
  the current deadline decision,
* memory (host RSS + device bytes) from the trainer's
  `<stream>.status.json` sidecar — memory is a process fact that never
  enters the stream (obs/memory.py), so the sidecar is its live surface,
* incident-bundle count + names from `<stream>.incidents/`
  (obs/flight.py).

`--once` renders a single frame and exits (the scriptable/CI mode —
the tier-2 incident smoke gates on it); otherwise the screen refreshes
in place until Ctrl-C. Like `report`, the verb is dispatched before the
engine import chain and never initializes an accelerator backend — it
runs on any host, including one whose TPU runtime would block on init.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time
from typing import List, Optional, Tuple

from federated_pytorch_test_tpu.obs.flight import list_incidents
from federated_pytorch_test_tpu.obs.registry import (
    RunRegistry,
    RunStream,
    StreamRefused,
)

_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(xs, width: int = 40) -> str:
    """Unicode block sparkline of the series TAIL (the console is about
    now, not history); constant series render flat-low, non-finite
    values (a poisoned round's NaN losses) are dropped."""
    xs = [
        float(x)
        for x in xs
        if x is not None and math.isfinite(float(x))
    ]
    if not xs:
        return "-"
    xs = xs[-width:]
    lo, hi = min(xs), max(xs)
    if hi <= lo:
        return _BLOCKS[0] * len(xs)
    return "".join(
        _BLOCKS[min(len(_BLOCKS) - 1, int((x - lo) / (hi - lo) * len(_BLOCKS)))]
        for x in xs
    )


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024.0 or unit == "TiB":
            return f"{n:,.0f} {unit}" if unit == "B" else f"{n:,.1f} {unit}"
        n /= 1024.0
    return "-"


def _run_view(run: RunStream) -> dict:
    """One pass over a stream's records → everything a dashboard panel
    needs (content-only; wall-clock fields are never read)."""
    v = {
        "label": run.label or "?",
        "records": len(run.records),
        "loops_committed": len(run.markers),
        "loss_per_round": [],  # per-round mean train loss (sparkline data)
        "last_loss": None,
        "acc_curve": [],
        "comm_bytes": 0,
        "bytes_saved": 0,
        "quarantined": 0,
        "churn_absent": None,
        "cohort": None,
        "deadline": None,
        "health_rounds": 0,
        "health_anomalies": 0,
        "last_anomalies": [],
    }
    loss_sum, loss_n = 0.0, 0
    for series, rec in run.records:
        val = rec.get("value")
        if series == "train_loss" and isinstance(val, list):
            finite = [
                float(x)
                for x in val
                if isinstance(x, (int, float)) and math.isfinite(float(x))
            ]
            if finite:
                loss_sum += sum(finite) / len(finite)
                loss_n += 1
                v["last_loss"] = sum(finite) / len(finite)
        elif series == "test_accuracy" and isinstance(val, list):
            accs = [float(x) for x in val if isinstance(x, (int, float))]
            if accs:
                v["acc_curve"].append(sum(accs) / len(accs))
        elif series == "comm_bytes":
            v["comm_bytes"] += int(val)
        elif series == "group_schedule" and isinstance(val, dict):
            if val.get("skipped"):
                v["bytes_saved"] += int(val.get("saved_bytes", 0))
        elif series == "quarantine" and isinstance(val, dict):
            v["quarantined"] += len(val.get("clients", ()))
        elif series == "availability" and isinstance(val, dict):
            v["churn_absent"] = val.get("absent")
        elif series == "cohort" and isinstance(val, dict):
            v["cohort"] = len(val.get("clients", ()))
        elif series == "deadline" and isinstance(val, dict):
            v["deadline"] = val
        elif series == "dispatch_count":
            # round boundary (the flight recorder's segmentation rule):
            # fold the round's mean loss into the sparkline series
            if loss_n:
                v["loss_per_round"].append(loss_sum / loss_n)
            loss_sum, loss_n = 0.0, 0
        elif series == "health" and isinstance(val, dict):
            v["health_rounds"] += 1
            an = list(val.get("anomalies", ()))
            v["health_anomalies"] += len(an)
            v["last_anomalies"] = an
    return v


def _read_status(stream_path: str) -> Optional[dict]:
    """The trainer's atomically-rewritten live sidecar (memory, current
    cursor) — absent or torn reads degrade to None, never an error."""
    try:
        with open(stream_path + ".status.json") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _render_run(name: str, run: RunStream) -> List[str]:
    v = _run_view(run)
    status = _read_status(run.path)
    # the sidecar's completed flag is what separates a finished run from
    # a live one — without it a stale sidecar reads as live forever
    state = ""
    if status is not None:
        if status.get("completed"):
            state = "  (completed)"
        elif status.get("crashed"):
            state = "  (crashed)"
        else:
            state = "  (live)"
    lines = [
        f"== {name}  [{v['label']}]  loops committed: "
        f"{v['loops_committed']}  records: {v['records']}{state}"
    ]
    loss = f"{v['last_loss']:.4g}" if v["last_loss"] is not None else "-"
    lines.append(
        f"   loss  {loss:>10}  {sparkline(v['loss_per_round'])}"
    )
    acc = f"{v['acc_curve'][-1]:.4f}" if v["acc_curve"] else "-"
    lines.append(
        f"   acc   {acc:>10}  {sparkline(v['acc_curve'])}"
        f"  ({len(v['acc_curve'])} evals)"
    )
    last = f" last: {','.join(v['last_anomalies'])}" if v["last_anomalies"] else ""
    lines.append(
        f"   health {v['health_rounds']} rounds monitored, "
        f"{v['health_anomalies']} anomalies{last}"
    )
    comm = f"   comm  {_fmt_bytes(v['comm_bytes'])} uplink"
    if v["bytes_saved"]:
        comm += f" (+{_fmt_bytes(v['bytes_saved'])} saved by skipping)"
    lines.append(comm)
    fleet = [f"quarantined {v['quarantined']}"]
    if v["deadline"] is not None:
        dl = v["deadline"]
        fleet.append(
            f"deadline {dl.get('seconds')}s ({dl.get('source', '?')})"
        )
    if v["churn_absent"] is not None:
        fleet.append(f"churn absent {v['churn_absent']}")
    if v["cohort"] is not None:
        fleet.append(f"cohort {v['cohort']}")
    lines.append("   fleet " + " | ".join(fleet))
    if status is not None:
        mem = status.get("memory") or {}
        parts = []
        if mem.get("rss_bytes"):
            parts.append(f"rss {_fmt_bytes(mem['rss_bytes'])}")
        if mem.get("peak_rss_bytes"):
            parts.append(f"peak {_fmt_bytes(mem['peak_rss_bytes'])}")
        for i, dev in enumerate(mem.get("devices") or []):
            if dev and dev.get("bytes_in_use") is not None:
                line = f"dev{i} {_fmt_bytes(dev['bytes_in_use'])}"
                if dev.get("bytes_limit"):
                    line += f"/{_fmt_bytes(dev['bytes_limit'])}"
                parts.append(line)
        if status.get("profile_captures"):
            parts.append(f"profiler captures {status['profile_captures']}")
        if parts:
            lines.append("   memory " + " | ".join(parts))
        store = status.get("store")
        if isinstance(store, dict):
            # spilled client store (clients/store.py, docs/SCALE.md
            # §Spilled store): live residency vs budget + what eviction
            # has spilled — the bounded-RSS story at a glance
            budget = store.get("resident_budget")
            parts = [
                f"resident {store.get('resident_chunks', '-')}"
                + (f"/{budget}" if budget is not None else "")
                + " chunks",
                f"on disk {store.get('on_disk_chunks', '-')}",
            ]
            if store.get("evictions"):
                parts.append(
                    f"evictions {store['evictions']} "
                    f"({_fmt_bytes(store.get('spill_bytes'))} spilled)"
                )
            if store.get("spill_reads"):
                parts.append(f"spill reads {store['spill_reads']}")
            lines.append("   store  " + " | ".join(parts))
        intg = status.get("integrity")
        if isinstance(intg, dict):
            # storage-integrity digest (clients/store.py, docs/FAULT.md
            # §Storage-integrity axis): verified spill reads vs detected
            # corruption and how the repair ladder resolved it
            parts = [
                f"checksums {'on' if intg.get('checksums') else 'off'}",
                f"verified reads {intg.get('verified_reads', 0)}",
            ]
            if intg.get("failures"):
                parts.append(f"failures {intg['failures']}")
            if intg.get("retry_heals"):
                parts.append(f"retry heals {intg['retry_heals']}")
            if intg.get("repairs_prior") or intg.get("repairs_reinit"):
                parts.append(
                    f"repairs {intg.get('repairs_prior', 0)} prior / "
                    f"{intg.get('repairs_reinit', 0)} reinit"
                )
            if status.get("storage_faults"):
                parts.append(
                    f"injected faults {status['storage_faults']}"
                )
            lines.append("   integrity " + " | ".join(parts))
        roof = status.get("roofline")
        if isinstance(roof, dict):
            # end-of-run roofline (obs/roofline.py, docs/PERF.md §Widened
            # GEMM): fold mode + the M the MXU actually saw, then the
            # achieved-vs-peak verdict when the chip is known
            parts = [f"fold {roof.get('client_fold', '?')}"]
            if roof.get("effective_gemm_m") is not None:
                parts.append(f"GEMM M {roof['effective_gemm_m']}")
            if roof.get("arithmetic_intensity") is not None:
                parts.append(
                    f"intensity {roof['arithmetic_intensity']} flop/B"
                )
            if roof.get("mfu") is not None:
                parts.append(f"MFU {roof['mfu']:.2%}")
            if roof.get("bound"):
                parts.append(f"{roof['bound']}-bound")
            lines.append("   roofline " + " | ".join(parts))
        prov = status.get("provenance")
        if isinstance(prov, dict):
            # the provenance row (obs/provenance.py): WHO is producing
            # these numbers — backend (twin-flagged), commit (dirty
            # starred), chip — so a live run is attributable at a glance
            backend = prov.get("backend") or "?"
            if prov.get("cpu_twin"):
                backend += " (cpu twin)"
            parts = [f"backend {backend}"]
            if prov.get("git_sha"):
                parts.append(
                    f"sha {prov['git_sha']}"
                    + ("*" if prov.get("git_dirty") else "")
                )
            if prov.get("device_kind"):
                parts.append(
                    f"{prov['device_kind']} x{prov.get('device_count', '?')}"
                )
            if prov.get("jax_version"):
                parts.append(f"jax {prov['jax_version']}")
            lines.append("   prov  " + " | ".join(parts))
    bundles = list_incidents(run.path)
    if bundles:
        names = []
        for fname, doc in bundles:
            # defensive: a parseable-but-foreign bundle (hand-edited,
            # other schema) must degrade to a label, never crash the
            # dashboard — the registry's validate-and-warn is for
            # `report --incidents`, the console just points at files
            if not isinstance(doc, dict):
                names.append(f"{fname}(unreadable)")
                continue
            kinds = doc.get("anomalies")
            label = (
                ",".join(str(k) for k in kinds)
                if isinstance(kinds, list) and kinds
                else str(doc.get("kind", "?"))
            )
            names.append(f"{fname}[{label}]")
        lines.append(f"   incidents {len(bundles)}: {', '.join(names)}")
    else:
        lines.append("   incidents 0")
    return lines


def render(
    target: str, glob: str = "*.jsonl", match: Optional[str] = None
) -> Tuple[str, int]:
    """One dashboard frame over `target` (a directory of streams, or one
    stream file). Returns `(text, run count)`."""
    reg = RunRegistry(match=match)
    refused: List[str] = []
    if os.path.isfile(target):
        try:
            reg.ingest(target)
        except StreamRefused as e:
            refused.append(str(e))
    else:
        refused = reg.ingest_dir(target, pattern=glob)
    stamp = time.strftime("%H:%M:%S")
    lines = [
        f"federated_pytorch_test_tpu watch — {target} "
        f"({len(reg.runs)} run(s), {stamp})",
        "",
    ]
    if not reg.runs:
        lines.append(
            f"no valid metric streams (pattern {glob!r}; "
            f"{len(refused)} file(s) refused) — waiting for a "
            "--metrics-stream writer"
        )
    for name, run in sorted(reg.runs.items()):
        lines.extend(_render_run(name, run))
        lines.append("")
    return "\n".join(lines) + "\n", len(reg.runs)


def watch_main(argv=None) -> int:
    """`python -m federated_pytorch_test_tpu watch DIR` — pure host-side
    file tailing; no accelerator backend is ever initialized."""
    ap = argparse.ArgumentParser(
        prog="federated_pytorch_test_tpu watch",
        description=(
            "Live terminal dashboard over a directory (or one file) of "
            "--metrics-stream JSONL files: sparklines, health, comm, "
            "fleet counters, memory, incidents (docs/OBSERVABILITY.md)."
        ),
    )
    ap.add_argument(
        "dir", help="directory of --metrics-stream files (or one file)"
    )
    ap.add_argument(
        "--glob", default="*.jsonl", help="stream filename pattern"
    )
    ap.add_argument(
        "--match",
        default=None,
        help="refuse streams whose header tag lacks this substring",
    )
    ap.add_argument(
        "--once",
        action="store_true",
        help="render one frame and exit (scriptable/CI mode)",
    )
    ap.add_argument(
        "--interval",
        type=float,
        default=2.0,
        help="seconds between refreshes (default 2)",
    )
    args = ap.parse_args(argv)

    if args.once:
        text, n_runs = render(args.dir, args.glob, args.match)
        print(text, end="")
        return 0 if n_runs else 1
    try:
        while True:
            text, _ = render(args.dir, args.glob, args.match)
            # clear + home, then the frame: refresh in place
            sys.stdout.write("\x1b[2J\x1b[H" + text)
            sys.stdout.flush()
            time.sleep(max(args.interval, 0.1))
    except KeyboardInterrupt:
        return 0
