"""Exchange subsystem: wire-format codecs + layer-group scheduling —
what crosses the interconnect, and whether anything crosses at all.

The comm ledger (obs/ledger.py, PR 3) made the paper's bandwidth claim a
measured number; this package moves the number, two ways. TAMUNA
(arXiv:2302.09832) and L-FGADMM (arXiv:1911.03654) both argue that
compressed / partial exchange is where communication-efficient federated
optimization actually wins:

* **codec zoo** (codec.py): `identity` (f32 on the wire,
  bit-transparent — the pre-codec program compiles unchanged), `bf16`
  (half the uplink, one round-to-nearest-even per value), `topk`
  (TAMUNA-style sparsification: the `ceil(fraction*n)` largest
  magnitudes as index+value pairs) and `quant` (q8/q4 symmetric
  stochastic-rounding quantization), each stating its EXACT
  `bytes_on_wire`, optionally composed with the per-(client, group)
  error-feedback residual (`--error-feedback`, engine/steps.py);
* **adaptive layer-group scheduling** (schedule.py,
  `--group-schedule adaptive`): pick WHICH partition group each round
  exchanges from the in-scan post-round per-group drift signal —
  including sending nothing for slots whose best remaining group has
  stopped drifting (`--group-skip-frac`), the one codec whose wire
  format is silence.

Placement contract (engine/steps.py `_consensus_local`): the codec wraps
the UPLINKED partition-group slice only. Master weights, the consensus
variable z, and all L-BFGS math stay f32; the aggregation — mean, the
robust order-statistic combiners, AND the z-score auto-quarantine — all
operate on the DECODED f32 views, so an encoded liar is still
quarantined whatever the codec (tests/test_exchange.py,
tests/test_codecs.py). In-transit corruption faults (fault/plan.py)
garble the decoded view: the adversary sits on the wire, after the
sender's encoder (and after its error-feedback compensation).
"""

from federated_pytorch_test_tpu.exchange.codec import (
    EXCHANGE_CODECS,
    EXCHANGE_DTYPES,
    Bf16Codec,
    ExchangeCodec,
    IdentityCodec,
    QuantCodec,
    TopKCodec,
    get_codec,
    make_codec,
)
from federated_pytorch_test_tpu.exchange.schedule import (
    GROUP_SCHEDULES,
    GroupScheduler,
    validate_group_skip_frac,
)

__all__ = [
    "EXCHANGE_CODECS",
    "EXCHANGE_DTYPES",
    "GROUP_SCHEDULES",
    "Bf16Codec",
    "ExchangeCodec",
    "GroupScheduler",
    "IdentityCodec",
    "QuantCodec",
    "TopKCodec",
    "get_codec",
    "make_codec",
    "validate_group_skip_frac",
]
