"""Exchange wire-format codecs: what actually crosses the interconnect.

The comm ledger (obs/ledger.py, PR 3) made the paper's bandwidth claim a
measured number; this package moves the number. TAMUNA
(arXiv:2302.09832) and L-FGADMM (arXiv:1911.03654) both argue that
compressed / partial exchange is where communication-efficient federated
optimization actually wins — the codec protocol here is the seed of
ROADMAP item 3's pluggable-codec interface (top-k sparsification,
stochastic quantization, sparse masks), shipping with its two simplest
members: `identity` (f32 on the wire, bit-transparent — the pre-codec
program compiles unchanged) and `bf16` (half the uplink bytes, one
round-to-nearest-even per value).

Placement contract (engine/steps.py `_consensus_local`): the codec wraps
the UPLINKED partition-group slice only. Master weights, the consensus
variable z, and all L-BFGS math stay f32; the aggregation — mean, the
robust order-statistic combiners, AND the z-score auto-quarantine — all
operate on the DECODED f32 views, so a bf16-encoded liar is still
quarantined (tests/test_exchange.py). In-transit corruption faults
(fault/plan.py) garble the decoded view: the adversary sits on the wire,
after the sender's encoder.
"""

from federated_pytorch_test_tpu.exchange.codec import (
    EXCHANGE_DTYPES,
    Bf16Codec,
    ExchangeCodec,
    IdentityCodec,
    get_codec,
)

__all__ = [
    "EXCHANGE_DTYPES",
    "Bf16Codec",
    "ExchangeCodec",
    "IdentityCodec",
    "get_codec",
]
