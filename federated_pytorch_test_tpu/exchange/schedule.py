"""Adaptive layer-group scheduling: spend the round on the group that
drifted.

Round-robin visits every partition group once per outer loop in a fixed
order — the reference's schedule, and the right one when nothing is
known about the groups. But the groups are NOT equally out of consensus:
L-FGADMM (arXiv:1911.03654) shows layer-wise exchange frequency should
follow how much a layer's copies disagree, and the repo already computes
exactly that disagreement — `parallel/diagnostics.py group_distances`,
each group's mean client distance from the cross-client mean. This
module turns that signal into the schedule: `--group-schedule adaptive`
picks, at each round slot, the not-yet-visited group with the LARGEST
last-observed drift, and (with `--group-skip-frac`) sends NOTHING at all
for tail slots whose best remaining group has drifted to a negligible
fraction of the run's peak — the first codec that saves bytes by
staying silent.

Mechanics mirror the PR-11 `DeadlineController` exactly:

* the signal is streamed: under the adaptive schedule every round ends
  with a `group_distance` record (in-scan inside the fused round
  program — engine/steps.py `build_round_fn(group_drift=True)` shares
  the `group_distances` body, so the folded dispatch stays
  `{round: 1, round_init: 1}`; the unfused path dispatches the same
  body standalone), replacing the `--diagnostics-every` host cadence as
  the signal source;
* the scheduler is a pure OBSERVER of those records (recorder-observer
  protocol, utils/metrics.py) — decisions are a pure function of the
  streamed record sequence, taken ONCE at round start, memoized by the
  trainer and streamed as the `group_schedule` series;
* resume REPLAYS: a resumed run feeds the kept records through
  `replay()` and seeds its decision memo from the replayed
  `group_schedule` records, so a crashed+resumed twin's stream is
  byte-identical to an uninterrupted run's (the trainer refuses to
  resume an adaptive run without a metrics stream, like auto
  deadlines).

Signal shape notes: under full-participation FedAvg the broadcast sets
every survivor's active-group coordinates to z, so an exchanged group's
post-round drift is ~0 and an untouched group's stays wherever training
left it — the argmax then behaves like least-recently-exchanged, which
degrades gracefully to round-robin order on all-equal drift (ties break
toward the round-robin position). The signal is sharpest where copies
genuinely diverge: ADMM (clients keep their own x), partial
participation (dropouts/deadline misses rejoin stale), and cohort mode
(gathered clients trained in different loops).
"""

from __future__ import annotations

import math
from typing import Dict, Iterable, List, Tuple

# the `--group-schedule` vocabulary (engine/config.py validates against
# this; the CLI error names the field)
GROUP_SCHEDULES = ("roundrobin", "adaptive")


def validate_group_skip_frac(skip_frac) -> float:
    """THE one range definition for `--group-skip-frac`, shared by the
    config validation (engine/config.py) and `GroupScheduler` — the
    make_codec delegation pattern: config-time and run-time validation
    cannot drift apart when there is only one check."""
    if isinstance(skip_frac, bool) or not isinstance(
        skip_frac, (int, float)
    ):
        raise ValueError(
            f"group_skip_frac must be a number in [0, 1), got {skip_frac!r}"
        )
    if not 0.0 <= float(skip_frac) < 1.0:
        raise ValueError(
            f"group_skip_frac must be in [0, 1), got {skip_frac}"
        )
    return float(skip_frac)


class GroupScheduler:
    """Per-slot group decisions from the observed drift signal.

    One instance per run, observing the recorder's streamed
    `group_distance` records (each a `[num_groups]` vector — one round's
    post-round per-group distances). `decide(visited)` returns
    `(gid, info)` for the next slot: the highest-drift group among
    `group_order` minus `visited`, round-robin warmup while any remaining
    group is unobserved, and `info["skipped"] = True` when the skip rule
    fires (`drift <= skip_frac * peak observed drift` — everything still
    unvisited has drifted to noise, so the slot sends nothing). Within a
    loop the trainer marks skipped groups visited too: once the BEST
    remaining group is below the skip line, so is everything after it.
    The FIRST slot of a loop (`visited` empty) never skips: every loop
    trains at least its top-drift group, so the drift signal refreshes
    and an all-quiet state cannot become absorbing (skipped slots run
    no training — if they could skip a whole loop, nothing would ever
    move the signal back above the line).

    Purity contract: state is a pure function of the observed record
    sequence (non-finite entries are ignored — a rolled-back poisoned
    round must not wedge the argmax on NaN), so `replay()` of a resumed
    stream reproduces the live scheduler's decisions exactly.
    """

    def __init__(self, group_order: Iterable[int], skip_frac: float = 0.0):
        self.group_order: List[int] = [int(g) for g in group_order]
        if not self.group_order:
            raise ValueError("group_order must name at least one group")
        self.skip_frac = validate_group_skip_frac(skip_frac)
        self._drift: Dict[int, float] = {}  # gid -> latest finite drift
        self._peak = 0.0  # largest drift ever observed (the skip anchor)

    # ---------------------------------------- recorder-observer protocol

    def observe(self, name: str, rec: dict) -> None:
        if name != "group_distance":
            return
        vals = rec.get("value")
        if not isinstance(vals, (list, tuple)):
            return
        for g in self.group_order:
            if g < len(vals):
                v = float(vals[g])
                if math.isfinite(v):
                    self._drift[g] = v
                    if v > self._peak:
                        self._peak = v

    def replay(self, records: Iterable[Tuple[str, dict]]) -> None:
        """Rebuild signal state from a resumed stream's replayed records
        (stream order — the same sequence `observe` saw live)."""
        for name, rec in records:
            self.observe(name, rec)

    # ----------------------------------------------------------- policy

    def decide(self, visited) -> Tuple[int, dict]:
        """The next slot's group + its provenance dict (the
        `group_schedule` record value minus slot/group): `source` is
        'warmup' while the pick has no drift evidence, else 'drift' with
        the deciding value; `skipped` appears (True) when the slot
        should send nothing. Deterministic: ties break toward the
        earlier round-robin position."""
        remaining = [g for g in self.group_order if g not in visited]
        if not remaining:
            raise ValueError(
                f"every group of {self.group_order} already visited"
            )
        unobserved = [g for g in remaining if g not in self._drift]
        if unobserved:
            return unobserved[0], {"source": "warmup"}
        best = max(
            range(len(remaining)),
            key=lambda i: (self._drift[remaining[i]], -i),
        )
        gid = remaining[best]
        d = self._drift[gid]
        info = {"source": "drift", "drift": round(d, 9)}
        # skip only TAIL slots (`visited` nonempty): a loop's first slot
        # always runs, so every loop trains at least one group and emits
        # a fresh drift record. Without this floor an all-quiet state
        # would be absorbing — skipped slots run no training, the signal
        # would freeze below the line, and the rest of the run would
        # silently no-op while the report counted the "savings".
        if self.skip_frac > 0.0 and self._peak > 0.0 and visited:
            if d <= self.skip_frac * self._peak:
                info["skipped"] = True
        return gid, info
