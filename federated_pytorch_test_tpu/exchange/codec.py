"""The codec protocol and its two shipped members (package docstring).

A codec is three pure functions plus static wire metadata:

* `encode(x)`  — the sender's half: f32 group slice -> wire array;
* `decode(w)`  — the receiver's half: wire array -> f32 view (what every
  combiner, residual, and quarantine statistic consumes);
* `bytes_on_wire(n_values)` — EXACT uplink bytes of one client's encoded
  slice, the quantity the comm ledger records (obs/ledger.py: a codec
  that cannot state its bytes exactly does not belong on the ledger).

Codecs must be jit-traceable (encode/decode run INSIDE the fused round
program) and deterministic — fused and unfused chaos runs must decode
identical views. `is_identity` is a STATIC build flag: the engine skips
the roundtrip entirely for the identity codec, so an
`--exchange-dtype float32` run compiles the exact pre-codec program
(the bitwise fallback, tests/test_exchange.py).

Future members (ROADMAP item 3: top-k, stochastic quantization,
TAMUNA-style sparse masks) implement the same three functions;
`bytes_on_wire` is per-value-count rather than per-array so sparse
codecs can report index + payload bytes exactly. NOTE: today's ledger
consumes the flat `bytes_per_value` (obs/ledger.py `wire_bytes` — exact
for both dense members here); landing the first sparse codec means
passing `bytes_on_wire` itself through to the ledger's round arithmetic,
which is the point at which this protocol method stops being
forward-looking and becomes the wire contract.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp

# the `--exchange-dtype` vocabulary (engine/config.py validates against
# this; the CLI error names the field)
EXCHANGE_DTYPES = ("float32", "bfloat16")


@dataclasses.dataclass(frozen=True)
class ExchangeCodec:
    """Base codec: f32 on the wire, bit-transparent."""

    name: str = "identity"
    bytes_per_value: int = 4
    is_identity: bool = True

    def encode(self, x: jnp.ndarray) -> jnp.ndarray:
        return x

    def decode(self, wire: jnp.ndarray) -> jnp.ndarray:
        return wire

    def roundtrip(self, x: jnp.ndarray) -> jnp.ndarray:
        """encode→decode — the aggregation's view of the sent slice."""
        return self.decode(self.encode(x))

    def bytes_on_wire(self, n_values: int) -> int:
        """Exact uplink bytes of one client's `n_values`-value slice."""
        return self.bytes_per_value * int(n_values)


class IdentityCodec(ExchangeCodec):
    pass


@dataclasses.dataclass(frozen=True)
class Bf16Codec(ExchangeCodec):
    """bfloat16 on the wire: exactly half the f32 uplink.

    encode rounds f32 -> bf16 (round-to-nearest-even, the one lossy
    operation); decode widens bf16 -> f32 exactly (bf16 is a prefix of
    f32: 8 exponent bits, 7 mantissa bits — every bf16 value is exactly
    representable in f32, so decode(encode(x)) == x whenever x already
    has a 7-bit mantissa, and differs by <= 2^-8 relative otherwise).
    Non-finite values survive the roundtrip as themselves (a nan_burst
    liar still looks non-finite to the combiners' exclusion logic and
    the quarantine's finiteness flag).
    """

    name: str = "bf16"
    bytes_per_value: int = 2
    is_identity: bool = False

    def encode(self, x: jnp.ndarray) -> jnp.ndarray:
        return x.astype(jnp.bfloat16)

    def decode(self, wire: jnp.ndarray) -> jnp.ndarray:
        return wire.astype(jnp.float32)


_CODECS = {
    "float32": IdentityCodec(),
    "bfloat16": Bf16Codec(),
}


def get_codec(exchange_dtype: str) -> ExchangeCodec:
    """The codec for a config's `exchange_dtype` knob."""
    try:
        return _CODECS[exchange_dtype]
    except KeyError:
        raise ValueError(
            f"exchange_dtype must be one of {list(EXCHANGE_DTYPES)}, "
            f"got {exchange_dtype!r}"
        ) from None
