"""The codec protocol and the codec zoo (package docstring).

A codec is three pure functions plus static wire metadata:

* `encode(x)`  — the sender's half: f32 group slice -> wire array;
* `decode(w)`  — the receiver's half: wire array -> f32 view (what every
  combiner, residual, and quarantine statistic consumes);
* `bytes_on_wire(n_values)` — EXACT uplink bytes of one client's encoded
  slice, the quantity the comm ledger records (obs/ledger.py: a codec
  that cannot state its bytes exactly does not belong on the ledger).

Codecs must be jit-traceable (encode/decode run INSIDE the fused round
program) and deterministic — fused and unfused chaos runs must decode
identical views, and a crashed+resumed run must re-encode exactly what
its uninterrupted twin sent (no ambient PRNG state: the quantizer's
stochastic rounding derives its dither from the value's own bits, see
`QuantCodec`). `is_identity` is a STATIC build flag: the engine skips
the roundtrip entirely for the identity codec, so a default run
compiles the exact pre-codec program (the bitwise fallback,
tests/test_exchange.py).

The zoo (ROADMAP item 2, docs/PERF.md codec table):

* `identity` / `bf16` — the dense members (flat bytes-per-value wire);
* `topk` (`--exchange-codec topk`) — TAMUNA-style sparse exchange
  (arXiv:2302.09832): each client ships only its `ceil(fraction * n)`
  largest-magnitude coordinates as (index, value) pairs;
* `quant` (`--exchange-codec quant`, `--quant-bits {4,8}`) — symmetric
  per-client stochastic-rounding quantization: one f32 scale plus
  `bits` bits per value.

Sparse/framed members cannot state a flat per-value width, so the
ledger consumes `bytes_on_wire` itself (obs/ledger.py `round_bytes` —
the point at which the protocol method became the wire contract);
`flat_wire` marks the dense members whose `bytes_per_value` is still
the whole story. The optional error-feedback accumulator
(`--error-feedback`, engine/steps.py) lives OUTSIDE the codec: the
sender adds its carried residual before encoding and keeps
`(x + e) - decode(encode(x + e))` for the next exchange, so any lossy
member composes with it unchanged.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

# the `--exchange-dtype` vocabulary (engine/config.py validates against
# this; the CLI error names the field)
EXCHANGE_DTYPES = ("float32", "bfloat16")

# the `--exchange-codec` vocabulary (None defers to `--exchange-dtype`,
# which picks a dense member below)
EXCHANGE_CODECS = ("topk", "quant")


@dataclasses.dataclass(frozen=True)
class ExchangeCodec:
    """Base codec: f32 on the wire, bit-transparent."""

    name: str = "identity"
    bytes_per_value: int = 4
    is_identity: bool = True
    # dense members' uplink is exactly `bytes_per_value * n`; sparse or
    # framed members (index+value pairs, per-slice scale headers) set
    # False and the ledger consumes `bytes_on_wire` directly
    flat_wire: bool = True

    def encode(self, x: jnp.ndarray) -> jnp.ndarray:
        return x

    def decode(self, wire: jnp.ndarray) -> jnp.ndarray:
        return wire

    def roundtrip(self, x: jnp.ndarray) -> jnp.ndarray:
        """encode→decode — the aggregation's view of the sent slice."""
        return self.decode(self.encode(x))

    def bytes_on_wire(self, n_values: int) -> int:
        """Exact uplink bytes of one client's `n_values`-value slice."""
        return self.bytes_per_value * int(n_values)

    def describe(self) -> dict:
        """Static wire identity for the comm summary / report labels
        (JSON-safe, deterministic key order)."""
        return {"name": self.name, "label": self.label()}

    def label(self) -> str:
        """Short human label for frontier points ('topk(0.1)', 'q8')."""
        return self.name


class IdentityCodec(ExchangeCodec):
    pass


@dataclasses.dataclass(frozen=True)
class Bf16Codec(ExchangeCodec):
    """bfloat16 on the wire: exactly half the f32 uplink.

    encode rounds f32 -> bf16 (round-to-nearest-even, the one lossy
    operation); decode widens bf16 -> f32 exactly (bf16 is a prefix of
    f32: 8 exponent bits, 7 mantissa bits — every bf16 value is exactly
    representable in f32, so decode(encode(x)) == x whenever x already
    has a 7-bit mantissa, and differs by <= 2^-8 relative otherwise).
    Non-finite values survive the roundtrip as themselves (a nan_burst
    liar still looks non-finite to the combiners' exclusion logic and
    the quarantine's finiteness flag).
    """

    name: str = "bf16"
    bytes_per_value: int = 2
    is_identity: bool = False

    def encode(self, x: jnp.ndarray) -> jnp.ndarray:
        return x.astype(jnp.bfloat16)

    def decode(self, wire: jnp.ndarray) -> jnp.ndarray:
        return wire.astype(jnp.float32)


@dataclasses.dataclass(frozen=True)
class TopKCodec(ExchangeCodec):
    """Top-k sparsification: ship only the largest-magnitude coordinates.

    The sender keeps its `k = ceil(fraction * n)` largest-|value| entries
    and transmits them as (index, value) pairs — `bytes_per_value` here
    is the cost of one KEPT pair (4-byte u32 index + 4-byte f32 value),
    so `bytes_on_wire(n) = k(n) * 8`, exact whatever the data. The
    on-device wire array models the RECEIVER's view of that packed
    format: the dense scatter of the pairs, zeros elsewhere (`decode` is
    then the identity) — every downstream consumer (mean, robust
    combiners, quarantine norms) sees exactly what decoding the packed
    pairs would produce.

    Selection is per client slice (last axis), by magnitude with
    NON-FINITE values ranked above everything: a nan_burst liar's NaNs
    are always among the kept pairs, so the corruption stays visible to
    the combiners' exclusion logic and the quarantine's finiteness flag
    (a sparsifier that silently dropped the evidence would launder the
    attack). Ties at the k-th magnitude resolve to the lower index
    (lax.top_k's stable order) — deterministic, so fused, unfused, and
    resumed runs keep identical wires.
    """

    name: str = "topk"
    bytes_per_value: int = 8  # one kept (u32 index, f32 value) pair
    is_identity: bool = False
    flat_wire: bool = False
    fraction: float = 0.1

    def __post_init__(self):
        f = self.fraction
        if isinstance(f, bool) or not isinstance(f, (int, float)):
            raise ValueError(
                f"topk_fraction must be a number in (0, 1], got {f!r}"
            )
        if not (0.0 < float(f) <= 1.0):
            raise ValueError(
                f"topk_fraction must be in (0, 1], got {f}"
            )

    def kept(self, n_values: int) -> int:
        """How many coordinates of an `n_values` slice go on the wire."""
        n = int(n_values)
        return min(n, max(1, math.ceil(self.fraction * n))) if n else 0

    def encode(self, x: jnp.ndarray) -> jnp.ndarray:
        n = x.shape[-1]
        k = self.kept(n)
        if k >= n:
            return x

        def one(row):
            # non-finite magnitudes rank as +inf: corruption is always
            # selected onto the wire, never silently dropped
            mag = jnp.where(jnp.isfinite(row), jnp.abs(row), jnp.inf)
            _, idx = lax.top_k(mag, k)
            keep = jnp.zeros((n,), bool).at[idx].set(True)
            return jnp.where(keep, row, 0.0)

        flat = x.reshape((-1, n))
        return jax.vmap(one)(flat).reshape(x.shape)

    def bytes_on_wire(self, n_values: int) -> int:
        return self.kept(n_values) * self.bytes_per_value

    def describe(self) -> dict:
        return {**super().describe(), "fraction": float(self.fraction)}

    def label(self) -> str:
        return f"topk({self.fraction:g})"


def _bit_hash_uniform(x: jnp.ndarray) -> jnp.ndarray:
    """Deterministic per-value dither in [0, 1): a murmur3-style finalizer
    over the value's OWN f32 bit pattern. No PRNG key, no ambient state —
    pure in the input, so fused/unfused/crash-resumed runs quantize
    identically (the codec determinism contract, module docstring). The
    dither varies per coordinate and changes whenever the value does,
    which is what stochastic rounding needs from round to round.
    """
    h = lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    # top 24 bits -> [0, 1): exactly representable in f32
    return (h >> 8).astype(jnp.float32) * jnp.float32(2.0**-24)


@dataclasses.dataclass(frozen=True)
class QuantCodec(ExchangeCodec):
    """Symmetric stochastic-rounding quantization (q8 / q4).

    Per client slice (last axis): one f32 scale `s = max|finite x| / Q`
    with `Q = 2^(bits-1) - 1`, then each value rounds stochastically to
    an integer level in `[-Q, Q]` — `floor(x/s + u)` with the
    deterministic per-value dither `u` of `_bit_hash_uniform`, clipped.
    The wire is the scale header (4 bytes) plus `bits` bits per value:
    `bytes_on_wire(n) = 4 + ceil(n * bits / 8)`, exact. As with topk the
    on-device wire array models the receiver's decoded view
    (`level * s`, `decode` the identity).

    NON-FINITE values bypass quantization and cross as themselves (a
    real wire would use a reserved level; either way the receiver sees
    the non-finite evidence), so nan_burst liars stay visible. Error
    bound: `|roundtrip(x) - x| < s` for every finite value — one
    quantization step (tests/test_codecs.py pins it).
    """

    name: str = "quant"
    bytes_per_value: int = 1  # informational; the wire is bit-packed
    is_identity: bool = False
    flat_wire: bool = False
    bits: int = 8

    def __post_init__(self):
        if isinstance(self.bits, bool) or self.bits not in (4, 8):
            raise ValueError(
                f"quant_bits must be 4 or 8, got {self.bits!r}"
            )

    def encode(self, x: jnp.ndarray) -> jnp.ndarray:
        q = float(2 ** (self.bits - 1) - 1)
        finite = jnp.isfinite(x)
        amax = jnp.max(
            jnp.where(finite, jnp.abs(x), 0.0), axis=-1, keepdims=True
        )
        scale = jnp.where(amax > 0, amax / q, 1.0)
        level = jnp.clip(
            jnp.floor(x / scale + _bit_hash_uniform(x)), -q, q
        )
        return jnp.where(finite, level * scale, x)

    def bytes_on_wire(self, n_values: int) -> int:
        n = int(n_values)
        return (4 + math.ceil(n * self.bits / 8)) if n else 0

    def describe(self) -> dict:
        return {**super().describe(), "bits": int(self.bits)}

    def label(self) -> str:
        return f"q{self.bits}"


_CODECS = {
    "float32": IdentityCodec(),
    "bfloat16": Bf16Codec(),
}


def get_codec(exchange_dtype: str) -> ExchangeCodec:
    """The codec for a config's `exchange_dtype` knob."""
    try:
        return _CODECS[exchange_dtype]
    except KeyError:
        raise ValueError(
            f"exchange_dtype must be one of {list(EXCHANGE_DTYPES)}, "
            f"got {exchange_dtype!r}"
        ) from None


def make_codec(
    exchange_dtype: str = "float32",
    exchange_codec: Optional[str] = None,
    topk_fraction: float = 0.1,
    quant_bits: int = 8,
) -> ExchangeCodec:
    """The ONE config-to-codec mapping (engine/steps.py builds the
    consensus body through it, engine/trainer.py prices the ledger
    through it — a drifted copy would let the program ship different
    bytes than the ledger records). `exchange_codec=None` defers to
    `exchange_dtype` (the dense members: identity / bf16)."""
    if exchange_codec is None:
        return get_codec(exchange_dtype)
    if exchange_codec == "topk":
        return TopKCodec(fraction=topk_fraction)
    if exchange_codec == "quant":
        return QuantCodec(bits=quant_bits)
    raise ValueError(
        f"exchange_codec must be one of {list(EXCHANGE_CODECS)} "
        f"(or None for the --exchange-dtype member), got {exchange_codec!r}"
    )
