"""ResNet18 (ELU variant) with the reference's 10-block partition grouping.

Capability parity with the inline ResNet of reference
src/federated_trio_resnet.py:65-152 (duplicated in
src/consensus_admm_trio_resnet.py:64-151): BasicBlock with two 3x3 convs +
BatchNorm, ELU activations everywhere ReLU would normally be, a 1x1-conv
shortcut when shape changes, 4x4 average pool, and a 10-class linear head.

The reference groups its 62 parameter tensors into 10 communication blocks
with the hand-written table `upidx=[2,8,14,23,29,38,44,53,59,61]`
(reference src/federated_trio_resnet.py:174-178). Decoding that table
against torch's parameter order shows the blocks are exactly structural:
[stem, layer1.0, layer1.1, layer2.0, layer2.1, layer3.0, layer3.1,
layer4.0, layer4.1, linear]. Here the grouping is expressed structurally by
module name, so it cannot drift from the architecture.

BatchNorm batch statistics are a separate `batch_stats` collection, outside
the partition: they are client-local by design and must never be averaged
(the reference likewise only communicates `net.parameters()`, which excludes
running stats; see SURVEY.md §7 hard part 5).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from federated_pytorch_test_tpu.models.base import (
    PartitionedModel,
    bias_init,
    kernel_init,
)


def _conv(features: int, kernel: int, stride: int, name: str, dtype=None) -> nn.Conv:
    return nn.Conv(
        features=features,
        kernel_size=(kernel, kernel),
        strides=(stride, stride),
        padding="SAME" if kernel == 3 else "VALID",
        use_bias=False,
        name=name,
        kernel_init=kernel_init,
        dtype=dtype,
    )


def _bn(name: str, train: bool, dtype=None) -> nn.BatchNorm:
    # the WHOLE layer — including the mean/var reductions — follows the
    # model compute dtype. Profiled on a v5e (see BASELINE.md roofline
    # note): flax's default force_float32_reductions emitted an unfusable
    # convert+reduce pair per BN per closure evaluation that was 42% of
    # the bfloat16 epoch (f32-pinned BN, the round-1 design, was worse
    # still — two HBM casts per conv->BN->conv seam). bf16 statistics
    # over CIFAR batch*H*W samples agree with f32 to ~1e-2 relative —
    # convergence-checked against the f32 path in tests/test_engine.py.
    # Running stats still live in f32 (param_dtype default).
    low_prec = dtype is not None and dtype != jnp.float32
    return nn.BatchNorm(
        use_running_average=not train,
        momentum=0.9,
        epsilon=1e-5,
        name=name,
        dtype=dtype,
        # f32 keeps flax defaults exactly; low precision trades them for
        # fusable reductions + the cancellation-safe two-pass variance
        # (E[(x-mean)^2] — E[x^2]-E[x]^2 in bf16 measured no faster and
        # loses digits to cancellation)
        force_float32_reductions=not low_prec,
        use_fast_variance=not low_prec,
    )


class BasicBlock(nn.Module):
    """Two 3x3 conv+BN with ELU and an optional 1x1-conv shortcut.

    Reference src/federated_trio_resnet.py:65-87.
    """

    planes: int
    stride: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        in_planes = x.shape[-1]
        dt = self.dtype
        out = nn.elu(_bn("bn1", train, dt)(_conv(self.planes, 3, self.stride, "conv1", dt)(x)))
        out = _bn("bn2", train, dt)(_conv(self.planes, 3, 1, "conv2", dt)(out))
        if self.stride != 1 or in_planes != self.planes:
            x = _bn("sc_bn", train, dt)(_conv(self.planes, 1, self.stride, "sc_conv", dt)(x))
        return nn.elu(out + x.astype(out.dtype))


class ResNet18(PartitionedModel):
    """ResNet18 for 32x32 inputs, ELU activations, NHWC.

    Reference src/federated_trio_resnet.py:118-152 (`ResNet` + `ResNet18()`).
    """

    num_classes: int = 10

    # Stage layout [2,2,2,2] with planes 64/128/256/512 and stride 2 at each
    # stage entry (reference src/federated_trio_resnet.py:124-128,151).
    STAGES = (  # un-annotated: class attr, not a linen field
        (64, 1),
        (64, 1),
        (128, 2),
        (128, 1),
        (256, 2),
        (256, 1),
        (512, 2),
        (512, 1),
    )

    # 10 communication blocks == the decoded `upidx` table
    # (reference src/federated_trio_resnet.py:174-178).
    GROUP_PATHS = (
        (("conv1",), ("bn1",)),
        (("block0",),),
        (("block1",),),
        (("block2",),),
        (("block3",),),
        (("block4",),),
        (("block5",),),
        (("block6",),),
        (("block7",),),
        (("linear",),),
    )
    LINEAR_GROUP_IDS = ()  # resnet drivers apply no L1/L2 in their closures
    TRAIN_ORDER = tuple(range(10))  # drivers use np.random.permutation at runtime
    FOLD_LAYERS = {"conv": "free", "norm": "free", "dense": "grouped"}

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        x = nn.elu(_bn("bn1", train, self.dtype)(_conv(64, 3, 1, "conv1", self.dtype)(x)))
        for i, (planes, stride) in enumerate(self.STAGES):
            x = BasicBlock(
                planes=planes, stride=stride, dtype=self.dtype, name=f"block{i}"
            )(x, train=train)
        x = nn.avg_pool(x, window_shape=(4, 4), strides=(4, 4))  # 4x4 -> 1x1
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(
            self.num_classes, name="linear", kernel_init=kernel_init,
            bias_init=bias_init, dtype=self.dtype,
        )(x)
