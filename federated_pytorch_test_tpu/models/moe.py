"""Mixture-of-experts layer with expert parallelism over an `experts` axis.

Neither the reference nor SURVEY.md asks for MoE (SURVEY §2.3 lists expert
parallelism as absent/non-goal); this module completes the framework's
parallelism families so every axis the mesh design reserved is a real,
tested capability: clients (mesh.py), seq (ring.py), model (tensor.py),
stages (pipeline.py), experts (here).

`MoEMLP` is a switch-style top-1 routed MLP (one gate projection, E
expert MLPs, capacity-bounded dispatch) designed for XLA:

- routing is dense one-hot einsums (the Shazeer dispatch/combine masks),
  so there is no data-dependent control flow and the whole layer jits
  to static shapes;
- capacity C = ceil(tokens/E * capacity_factor) bounds every expert's
  work; tokens over capacity fall through the residual (their combine
  weight is zero), the standard switch-transformer overflow semantics;
- expert weights are stacked `[E, ...]` leaves, vmapped over E — the
  expert-parallel layout is a SHARDING of that axis, not different code.

Expert parallelism follows the tensor.py idiom and lives with the other
axes' mesh/sharding helpers (parallel/expert.py, re-exported here):
`ep_param_specs` returns `PartitionSpec('experts', ...)` for every
stacked expert leaf (gate and non-expert params replicated),
`shard_params_ep` device_puts them on an `expert_mesh`/
`client_expert_mesh`, and XLA's SPMD partitioner slices the vmapped
expert compute per device and inserts the combine collectives.
"""

from __future__ import annotations

import math
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from federated_pytorch_test_tpu.models.base import bias_init, kernel_init
from federated_pytorch_test_tpu.ops import grouped_matmul

# the axis's mesh/sharding idiom lives with the other axes' in parallel/
from federated_pytorch_test_tpu.parallel.expert import (  # noqa: F401
    EXPERT_AXIS,
    client_expert_mesh,
    ep_param_specs,
    expert_mesh,
    shard_params_ep,
)

PyTree = Any


class MoEMLP(nn.Module):
    """Switch-style top-1 MoE MLP, drop-in for a transformer block's MLP.

    Token t routes to expert argmax(gate(x_t)); its output is the chosen
    expert's MLP scaled by the gate probability (so routing receives
    gradient).

    The switch load-balance term E * Σ_e (fraction_e · prob_e) (minimized
    at uniform routing) is ALWAYS sown into the `intermediates` collection
    under `"moe_aux"`, so it is reachable through any wrapping model —
    e.g. `TransformerLM(moe_experts=E)`:

        logits, mut = lm.apply(vars, tokens, mutable=["intermediates"])
        aux = sum(jax.tree.leaves(mut["intermediates"]))
        loss = ce(logits) + 0.01 * aux

    With `return_aux=True` the layer also returns it directly as a second
    output (the standalone-layer API).
    """

    dim: int
    n_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    return_aux: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray):
        b, s, d = x.shape
        t = b * s
        e = self.n_experts
        cap = max(1, int(math.ceil(t / e * self.capacity_factor)))
        xt = x.reshape(t, d)

        # --- routing (always f32: softmax over few logits, cheap) ---
        logits = nn.Dense(
            e, name="gate", kernel_init=kernel_init, bias_init=bias_init,
            dtype=jnp.float32,
        )(xt.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)  # [T, E]
        expert_idx = jnp.argmax(probs, axis=-1)  # [T]
        gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)  # [T, E]

        # capacity: token's slot within its expert; over-capacity tokens
        # get combine weight 0 (they ride the residual connection)
        pos = jnp.cumsum(onehot, axis=0) - 1.0  # [T, E] position per expert
        pos_t = jnp.sum(pos * onehot, axis=1)  # [T]
        keep = (pos_t < cap).astype(jnp.float32)
        slot = jax.nn.one_hot(
            pos_t.astype(jnp.int32), cap, dtype=jnp.float32
        )  # [T, C]

        # dispatch/combine masks (dense einsums, XLA-friendly)
        dispatch = onehot[:, :, None] * slot[:, None, :] * keep[:, None, None]
        # [T, E, C]
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch, xt.astype(jnp.float32)
        ).astype(self.dtype)  # [E, C, D]

        # --- expert MLPs: stacked [E, ...] params, one grouped GEMM per
        # projection (ops/grouped_gemm.py — the [E, C, D] x [E, D, H]
        # block contraction; einsum backend, bitwise-identical to the
        # vmap-over-E formulation it replaced, tests/test_widened.py) ---
        h = self.mlp_ratio * d

        def mlp_grouped(x_e, w1, b1, w2, b2):
            y = grouped_matmul(x_e, w1) + b1[:, None, :]
            y = nn.gelu(y)
            return grouped_matmul(y, w2) + b2[:, None, :]

        w1 = self.param(
            "w1", nn.initializers.xavier_uniform(), (e, d, h), jnp.float32
        ).astype(self.dtype)
        b1 = self.param(
            "b1", nn.initializers.constant(0.01), (e, h), jnp.float32
        ).astype(self.dtype)
        w2 = self.param(
            "w2", nn.initializers.xavier_uniform(), (e, h, d), jnp.float32
        ).astype(self.dtype)
        b2 = self.param(
            "b2", nn.initializers.constant(0.01), (e, d), jnp.float32
        ).astype(self.dtype)
        expert_out = mlp_grouped(expert_in, w1, b1, w2, b2)  # [E, C, D]

        combine = dispatch * gate[:, None, None]  # [T, E, C]
        out = jnp.einsum(
            "tec,ecd->td", combine, expert_out.astype(jnp.float32)
        )
        out = out.reshape(b, s, d).astype(self.dtype)
        # switch load-balance loss: E * Σ_e mean(onehot_e) * mean(prob_e)
        frac = jnp.mean(onehot, axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        aux = e * jnp.sum(frac * mean_prob)
        self.sow("intermediates", "moe_aux", aux)
        if not self.return_aux:
            return out
        return out, aux
