"""Transformer model family with partition metadata.

The reference has no attention models (SURVEY.md §2.3: no sequence axis
anywhere), but this framework treats transformers and long-context as
first-class: `ViT` is a vision transformer over CIFAR 4x4 patches that
plugs into the same partial-parameter federated/ADMM engine as the CNNs —
its partition groups are (embedding+positions), each encoder block, and
the head, mirroring how the reference groups ResNet18's 62 tensors into 10
blocks (reference src/federated_trio_resnet.py:174-178).

Attention is pluggable: `attn_impl='dense'` runs the single-device
reference path; `attn_impl='flash'` runs the Pallas blockwise kernels
(ops/flash_attention.py — no [S, S] scores in HBM, the single-device
long-context path); `attn_impl='ring'` runs ring attention over the `seq`
mesh axis (parallel/ring.py) for sequences sharded across devices;
`attn_impl='ring_flash'` composes the two — the ring streams K/V blocks
over ICI while the Pallas kernel streams VMEM tiles within each device,
so neither the global nor the local sequence length is score-matrix-
bound. The model code is identical in every case, which is the point:
how attention executes is a property of the call site, not a fork of
the model.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from federated_pytorch_test_tpu.models.base import (
    PartitionedModel,
    bias_init,
    kernel_init,
)
from federated_pytorch_test_tpu.parallel.ring import (
    SEQ_AXIS,
    dense_attention,
    ring_attention,
)


# Version stamp for the fused qkv projection's column ORDER. v2 = the
# head-major layout ([h0(q,k,v), h1(q,k,v), ...]) that makes a contiguous
# `model`-axis split head-local (parallel/tensor.py); v1 (rounds 1-2) was
# [q-heads, k-heads, v-heads]. The two interpret the same kernel shape
# differently, so a v1 checkpoint loaded under v2 would compute scrambled
# attention WITHOUT any shape error — the engine stamps this version into
# transformer-family checkpoints and refuses a mismatch (engine/trainer.py).
QKV_LAYOUT_VERSION = 2


class MultiHeadAttention(nn.Module):
    """QKV projection + pluggable attention core + output projection."""

    dim: int
    num_heads: int
    attn_impl: str = "dense"  # 'dense' | 'ring' | 'flash' | 'ring_flash'
    causal: bool = False
    seq_axis: str = SEQ_AXIS
    # MXU precision of the flash kernels / ring folds (None = each
    # impl's default); 'default' = single bf16 passes, the fast choice
    # for long-context training
    attn_precision: Any = None
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.attn_impl not in (
            "dense", "ring", "flash", "ring_flash", "auto"
        ):
            raise ValueError(
                f"attn_impl must be 'dense', 'ring', 'flash', "
                f"'ring_flash' or 'auto', got {self.attn_impl!r}"
            )
        b, s, _ = x.shape
        impl = self.attn_impl
        # one effective precision for BOTH the auto crossover and the
        # flash kernel call, validated up front: a non-canonical value
        # must raise here, not silently pick the conservative crossover
        # (flash_attention would only validate it when flash is chosen)
        prec = self.attn_precision or "highest"
        if prec not in ("highest", "default"):
            raise ValueError(
                f"attn_precision must be None, 'highest' or 'default', "
                f"got {self.attn_precision!r}"
            )
        if impl == "auto":
            # measured single-chip crossover, round-5 kernels + the
            # floor-subtracted v2 protocol (benchmarks/
            # long_context_tpu.json, flash_f32_tiles.json): at 'default'
            # precision flash beats dense 1.55x already at S=1024 (4.6x
            # at S=2048); at 'highest' S=1024 still belongs to dense
            # (0.72x) and flash wins from S=2048 (1.27-1.29x). The
            # threshold is therefore precision-dependent. S is static
            # under jit, so this resolves at trace time.
            # (the flash kernels also need S % 128 == 0 — ragged lengths
            # always take dense, whatever their size)
            crossover = 1024 if prec == "default" else 2048
            impl = "flash" if s >= crossover and s % 128 == 0 else "dense"
        h, hd = self.num_heads, self.dim // self.num_heads
        qkv = nn.Dense(
            3 * self.dim, name="qkv", kernel_init=kernel_init,
            bias_init=bias_init, dtype=self.dtype,
        )(x)
        # attention core in f32: the online softmax must not lose mass to
        # bf16 rounding (projections carry the compute dtype; the core is
        # a small fraction of the FLOPs at these widths).
        # HEAD-MAJOR layout: the fused projection's output axis is ordered
        # [h0(q,k,v), h1(q,k,v), ...] so a contiguous split across a
        # `model` mesh axis (parallel/tensor.py column-parallel spec) puts
        # each head's q, k AND v on the same device — attention stays
        # head-local under tensor parallelism.
        qkv = qkv.reshape(b, s, h, 3, hd).astype(jnp.float32)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        if impl in ("ring", "ring_flash"):
            # 'ring_flash' = same ring schedule with the Pallas flash
            # kernel as each step's block compute (two-level streaming:
            # ICI across devices, VMEM tiles within)
            out = ring_attention(
                q, k, v, axis_name=self.seq_axis, causal=self.causal,
                use_flash=impl == "ring_flash",
                precision=self.attn_precision,
            )
        elif impl == "flash":
            # Pallas blockwise kernels (ops/flash_attention.py): no [S, S]
            # scores in HBM — the long-context single-device path
            from federated_pytorch_test_tpu.ops.flash_attention import (
                flash_attention,
            )

            out = flash_attention(
                q, k, v, causal=self.causal, precision=prec,
            )
        else:
            out = dense_attention(q, k, v, causal=self.causal)
        out = out.reshape(b, s, self.dim)
        return nn.Dense(
            self.dim, name="proj", kernel_init=kernel_init,
            bias_init=bias_init, dtype=self.dtype,
        )(out)


class Block(nn.Module):
    """Pre-norm encoder block: LN -> MHA -> +res; LN -> MLP -> +res.

    `moe_experts > 0` swaps the dense MLP for a switch-style top-1 MoE
    with that many experts (models/moe.py); over-capacity tokens ride
    this block's residual connection.
    """

    dim: int
    num_heads: int
    mlp_ratio: int = 4
    attn_impl: str = "dense"
    causal: bool = False
    attn_precision: Any = None
    moe_experts: int = 0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        y = nn.LayerNorm(name="ln1", dtype=self.dtype)(x)
        x = x + MultiHeadAttention(
            self.dim,
            self.num_heads,
            attn_impl=self.attn_impl,
            causal=self.causal,
            attn_precision=self.attn_precision,
            dtype=self.dtype,
            name="attn",
        )(y)
        y = nn.LayerNorm(name="ln2", dtype=self.dtype)(x)
        if self.moe_experts:
            from federated_pytorch_test_tpu.models.moe import MoEMLP

            y = MoEMLP(
                self.dim,
                self.moe_experts,
                mlp_ratio=self.mlp_ratio,
                dtype=self.dtype,
                name="moe",
            )(y)
        else:
            y = nn.Dense(
                self.mlp_ratio * self.dim,
                name="fc1",
                kernel_init=kernel_init,
                bias_init=bias_init,
                dtype=self.dtype,
            )(y)
            y = nn.gelu(y)
            y = nn.Dense(
                self.dim, name="fc2", kernel_init=kernel_init,
                bias_init=bias_init, dtype=self.dtype,
            )(y)
        return x + y


class TransformerLM(PartitionedModel):
    """Causal decoder LM — the long-context member of the model family.

    Token embedding + learned positions, 4 pre-norm causal blocks, tied
    to nothing (separate head). Positions are an EXPLICIT input: under
    sequence parallelism each device holds a contiguous token shard and
    passes its global positions, so the same module runs unsharded
    (`positions=None` → arange) or inside a `seq`-axis shard_map with
    `attn_impl='ring'` — long context is a property of the call site,
    not a fork of the model.

    Partition groups mirror ViT's: (embeddings), each block (last one
    carries the pre-head norm), head alone (the regularizable group).
    """

    GROUP_PATHS = (
        (("embed",), ("pos_embed",)),
        (("block0",),),
        (("block1",),),
        (("block2",),),
        (("block3",), ("ln_out",)),
        (("head",),),
    )
    LINEAR_GROUP_IDS = (5,)
    TRAIN_ORDER = (0, 1, 2, 3, 4, 5)
    FOLD_LAYERS = {
        "embed": "free", "norm": "free",
        "dense": "grouped", "attn": "grouped", "expert": "grouped",
    }

    vocab: int = 256
    dim: int = 64
    depth: int = 4  # must match the 4 block groups above
    num_heads: int = 4
    max_len: int = 2048
    attn_impl: str = "dense"
    attn_precision: Any = None
    moe_experts: int = 0  # >0: switch-MoE MLPs (models/moe.py)

    @classmethod
    def input_shape(cls):
        raise NotImplementedError(
            "TransformerLM consumes int32 token ids, not images; use "
            "dummy_input() (init_client_params does)"
        )

    def dummy_input(self) -> jnp.ndarray:
        return jnp.zeros((1, min(64, self.max_len)), jnp.int32)

    @nn.compact
    def __call__(
        self, tokens: jnp.ndarray, positions: jnp.ndarray | None = None
    ) -> jnp.ndarray:
        assert self.depth == 4, "GROUP_PATHS pins depth=4; add groups to change"
        if positions is None:
            if tokens.shape[1] > self.max_len:
                raise ValueError(
                    f"sequence length {tokens.shape[1]} exceeds max_len="
                    f"{self.max_len}; jnp.take would silently clamp "
                    "positions (raise max_len or pass explicit positions)"
                )
            positions = jnp.arange(tokens.shape[1])[None, :]
        # explicit positions (the sequence-parallel path) are the caller's
        # contract: they must be < max_len
        x = nn.Embed(
            self.vocab, self.dim, name="embed",
            embedding_init=nn.initializers.normal(0.02),
        )(tokens)
        pos_table = self.param(
            "pos_embed", nn.initializers.normal(0.02), (self.max_len, self.dim)
        )
        x = x + jnp.take(pos_table, positions, axis=0)
        for i in range(self.depth):
            x = Block(
                self.dim,
                self.num_heads,
                attn_impl=self.attn_impl,
                causal=True,
                attn_precision=self.attn_precision,
                moe_experts=self.moe_experts,
                dtype=self.dtype,
                name=f"block{i}",
            )(x)
        x = nn.LayerNorm(name="ln_out", dtype=self.dtype)(x)
        return nn.Dense(
            self.vocab, name="head", kernel_init=kernel_init,
            bias_init=bias_init, dtype=self.dtype,
        )(x)


class ViT(PartitionedModel):
    """Tiny vision transformer for 32x32 inputs (4x4 patches, 64 tokens).

    Partition groups: 0 = patch embedding + positions, 1..4 = encoder
    blocks (the last one also carries the pre-head LayerNorm — feature
    extraction ends there), 5 = the classifier head ALONE, so elastic-net
    regularization touches only true linear weights, matching how the
    CNN/ResNet groups expose fc layers (reference src/simple_models.py:29-30)
    and never normalization parameters.
    """

    GROUP_PATHS = (
        (("embed",), ("pos_embed",)),
        (("block0",),),
        (("block1",),),
        (("block2",),),
        (("block3",), ("ln_out",)),
        (("head",),),
    )
    LINEAR_GROUP_IDS = (5,)
    TRAIN_ORDER = (0, 1, 2, 3, 4, 5)
    FOLD_LAYERS = {
        "embed": "free", "norm": "free",
        "dense": "grouped", "attn": "grouped",
    }

    num_classes: int = 10
    dim: int = 64
    depth: int = 4  # must match the 4 block groups above
    num_heads: int = 4
    patch: int = 4
    attn_impl: str = "dense"
    attn_precision: Any = None
    moe_experts: int = 0  # >0: switch-MoE MLPs (models/moe.py)

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        assert self.depth == 4, "GROUP_PATHS pins depth=4; add groups to change"
        b = x.shape[0]
        x = nn.Conv(
            self.dim,
            (self.patch, self.patch),
            strides=(self.patch, self.patch),
            name="embed",
            kernel_init=kernel_init,
            bias_init=bias_init,
            dtype=self.dtype,
        )(x)  # [B, 8, 8, dim]
        x = x.reshape(b, -1, self.dim)  # [B, 64, dim]
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (1, x.shape[1], self.dim)
        )
        x = x + pos
        for i in range(self.depth):
            x = Block(
                self.dim,
                self.num_heads,
                attn_impl=self.attn_impl,
                attn_precision=self.attn_precision,
                moe_experts=self.moe_experts,
                dtype=self.dtype,
                name=f"block{i}",
            )(x)
        x = nn.LayerNorm(name="ln_out", dtype=self.dtype)(x)
        x = jnp.mean(x, axis=1)  # mean-pool tokens
        return nn.Dense(
            self.num_classes, name="head", kernel_init=kernel_init,
            bias_init=bias_init, dtype=self.dtype,
        )(x)
