"""Shared model protocol: partition metadata + common-seed client init."""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from federated_pytorch_test_tpu.partition import Partition, build_partition
from federated_pytorch_test_tpu.partition.flat import leaf_offsets

PyTree = Any

# Reference init: xavier_uniform on conv/linear weights, bias = 0.01
# (reference src/federated_trio.py:115-118).
kernel_init = nn.initializers.xavier_uniform()
bias_init = nn.initializers.constant(0.01)


class PartitionedModel(nn.Module):
    """A flax module that knows its own layer/block partition.

    Subclasses set three class attrs mirroring the reference's metadata
    methods (reference src/simple_models.py:29-39):

      GROUP_PATHS:      per-group list of path prefixes into the params tree
      LINEAR_GROUP_IDS: groups that receive L1/L2 regularization
      TRAIN_ORDER:      default group visit order per outer loop

    Every model carries a `dtype` compute-dtype field (declared here once):
    params stay f32; convs/matmuls run in `dtype` (the engine's
    `compute_dtype` knob) while norms and the loss stay f32.
    """

    dtype: Any = jnp.float32

    # NOTE: deliberately un-annotated so linen's dataclass transform treats
    # them as plain class attributes, not module fields.
    GROUP_PATHS = ()
    LINEAR_GROUP_IDS = ()
    TRAIN_ORDER = ()
    # Widened-GEMM fold capability per layer kind (docs/PERF.md §Widened
    # GEMM). "free": weights are probe-invariant under the fold (broadcast
    # or per-client vectors) — the probe axis folds straight into the
    # example axis of the dot. "grouped": the layer's weights live in a
    # trainable group, so when that group is active its dot stays a G-way
    # grouped block GEMM (ops/grouped_gemm.py on TPU, batched dot_general
    # elsewhere). Metadata only — consumed by docs/roofline, never by the
    # apply path.
    FOLD_LAYERS = {}

    @classmethod
    def partition(cls, params: PyTree) -> Partition:
        """Build the static `Partition` for a params tree of this model."""
        return build_partition(
            params,
            cls.GROUP_PATHS,
            linear_group_ids=cls.LINEAR_GROUP_IDS,
            train_order=cls.TRAIN_ORDER,
        )

    @classmethod
    def input_shape(cls) -> Tuple[int, int, int]:
        return (32, 32, 3)

    def dummy_input(self) -> jnp.ndarray:
        """A minimal batch for `init`. Image models derive it from
        `input_shape`; token models (TransformerLM) override both."""
        return jnp.zeros((1,) + tuple(self.input_shape()), jnp.float32)


def init_client_params(model: nn.Module, n_clients: int, seed: int = 0) -> PyTree:
    """Initialize K identical clients (common-seed init).

    The reference re-seeds before each client's init so all clients start
    from the same point (reference src/federated_trio.py:229-236). Here we
    init once and broadcast along a leading `clients` axis; the stacked tree
    is what gets sharded over the client mesh axis.

    Returns the full variables dict with every leaf shaped `[K, ...]`
    (including e.g. `batch_stats` collections for BatchNorm models).
    """
    import inspect

    rng = jax.random.PRNGKey(seed)
    dummy = (
        model.dummy_input()
        if hasattr(model, "dummy_input")
        else jnp.zeros((1,) + tuple(model.input_shape()), jnp.float32)
    )
    kwargs = {}
    if "train" in inspect.signature(model.__call__).parameters:
        kwargs["train"] = False
    variables = model.init(rng, dummy, **kwargs)
    return jax.tree.map(
        lambda x: jnp.broadcast_to(x[None], (n_clients,) + x.shape), variables
    )


def active_leaf_mask(unravel, partition: Partition, gid: int):
    """Which params-tree leaves intersect group `gid`'s flat segments.

    The widened-GEMM fold (`--client-fold gemm`, engine/steps.py) needs to
    know, per leaf of `unravel`'s output tree, whether any of the leaf's
    flat coordinates belong to the active group: those leaves vary along
    the probe fan and must stay probe-batched, while every other leaf is
    probe-invariant and can be taken from a single unbatched tree — which
    is what lets vmap fold the probe axis into the M dimension of the
    frozen layers' dots.

    A leaf that only PARTIALLY overlaps the group is conservatively
    marked active (it varies along the fan, so it cannot be frozen).

    Returns a list of bools in canonical tree-flatten leaf order.
    """
    template = jax.eval_shape(
        unravel, jax.ShapeDtypeStruct((partition.total,), jnp.float32)
    )
    segs = partition.groups[gid]
    mask = []
    for _path, start, size in leaf_offsets(template):
        end = start + size
        mask.append(
            any(s.start < end and start < s.start + s.size for s in segs)
        )
    return mask


def fold_params(probed: PyTree, frozen: PyTree, mask) -> PyTree:
    """Merge a probe-batched and an unbatched params tree leaf-wise.

    `probed` is `unravel(x_full)` evaluated INSIDE the probe-fan vmap
    (every leaf carries the batched alpha), `frozen` is `unravel(base)`
    evaluated outside it, and `mask` is `active_leaf_mask`'s verdict.
    Active leaves come from `probed` (their values genuinely vary along
    the fan); all others come from `frozen`, so downstream dots see them
    unbatched and vmap widens M instead of emitting one skinny dot per
    probe. XLA dead-code-eliminates the unused probed slices.
    """
    p_leaves, treedef = jax.tree_util.tree_flatten(probed)
    f_leaves = jax.tree_util.tree_leaves(frozen)
    merged = [p if a else f for p, f, a in zip(p_leaves, f_leaves, mask)]
    return jax.tree_util.tree_unflatten(treedef, merged)
