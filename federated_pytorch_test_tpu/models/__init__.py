"""Flax model zoo with partition metadata.

TPU-native re-design of the reference's models: the three simple CNNs
(reference src/simple_models.py:9-131) and the inline ResNet18 with ELU
(reference src/federated_trio_resnet.py:65-152). Inputs are NHWC
`[batch, 32, 32, 3]` (TPU-friendly layout; the reference is NCHW torch).
Each model carries static partition metadata — the layer/block grouping,
the linear-layer ids used for regularization, and the default training
order — replacing the reference's `linear_layer_ids` /
`train_order_layer_ids` methods and the hand-written `upidx` block table
(reference src/federated_trio_resnet.py:174-178).
"""

from federated_pytorch_test_tpu.models.base import PartitionedModel, init_client_params
from federated_pytorch_test_tpu.models.moe import (
    EXPERT_AXIS,
    MoEMLP,
    client_expert_mesh,
    ep_param_specs,
    expert_mesh,
    shard_params_ep,
)
from federated_pytorch_test_tpu.models.simple import Net, Net1, Net2
from federated_pytorch_test_tpu.models.resnet import ResNet18
from federated_pytorch_test_tpu.models.transformer import TransformerLM, ViT

# the image-classification families the CIFAR engine can drive; the
# token-based TransformerLM trains through the optimizer/partition APIs
# directly (tests/test_ring.py long-context tests)
MODELS = {
    "net": Net,
    "net1": Net1,
    "net2": Net2,
    "resnet18": ResNet18,
    "vit": ViT,
}

__all__ = [
    "EXPERT_AXIS",
    "MoEMLP",
    "Net",
    "Net1",
    "Net2",
    "ResNet18",
    "TransformerLM",
    "ViT",
    "PartitionedModel",
    "client_expert_mesh",
    "ep_param_specs",
    "expert_mesh",
    "init_client_params",
    "shard_params_ep",
    "MODELS",
]
