"""The three simple CNN clients (ELU), NHWC, with partition metadata.

Capability parity with reference src/simple_models.py:9-131 (`Net`, `Net1`,
`Net2`): same layer shapes, ELU activations, max-pooling, and the same
layer-numbering universe for the partition metadata — layer g is the
(kernel, bias) pair of the g-th module in construction order, matching the
reference's `unfreeze_one_layer` convention of `ci == 2*layer_id`
(reference src/federated_trio.py:120-126).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from federated_pytorch_test_tpu.models.base import (
    PartitionedModel,
    bias_init,
    kernel_init,
)


def _conv(features: int, kernel: int, padding: str, name: str, dtype=None) -> nn.Conv:
    return nn.Conv(
        features=features,
        kernel_size=(kernel, kernel),
        padding=padding,
        name=name,
        kernel_init=kernel_init,
        bias_init=bias_init,
        dtype=dtype,
    )


def _dense(features: int, name: str, dtype=None) -> nn.Dense:
    return nn.Dense(
        features=features, name=name, kernel_init=kernel_init,
        bias_init=bias_init, dtype=dtype,
    )


def _maxpool(x: jnp.ndarray) -> jnp.ndarray:
    return nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))


class Net(PartitionedModel):
    """LeNet-style 5-layer CNN (~62K params). Reference src/simple_models.py:9-39."""

    GROUP_PATHS = tuple(
        ((name,),) for name in ("conv1", "conv2", "fc1", "fc2", "fc3")
    )
    LINEAR_GROUP_IDS = (2, 3, 4)  # reference src/simple_models.py:29-30
    TRAIN_ORDER = (2, 0, 1, 3, 4)  # reference src/simple_models.py:38-39
    FOLD_LAYERS = {"conv": "free", "dense": "grouped"}

    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        dt = self.dtype
        x = _maxpool(nn.elu(_conv(6, 5, "VALID", "conv1", dt)(x)))  # 32->28->14
        x = _maxpool(nn.elu(_conv(16, 5, "VALID", "conv2", dt)(x)))  # 14->10->5
        x = x.reshape((x.shape[0], -1))  # 5*5*16 = 400
        x = nn.elu(_dense(120, "fc1", dt)(x))
        x = nn.elu(_dense(84, "fc2", dt)(x))
        return _dense(self.num_classes, "fc3", dt)(x)


class Net1(PartitionedModel):
    """6-layer CNN (~890K params). Reference src/simple_models.py:44-79."""

    GROUP_PATHS = tuple(
        ((name,),)
        for name in ("conv1", "conv2", "conv3", "conv4", "fc1", "fc2")
    )
    LINEAR_GROUP_IDS = (4, 5)  # reference src/simple_models.py:69-70
    TRAIN_ORDER = (2, 5, 1, 3, 0, 4)  # reference src/simple_models.py:78-79
    FOLD_LAYERS = {"conv": "free", "dense": "grouped"}

    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        dt = self.dtype
        x = nn.elu(_conv(32, 3, "VALID", "conv1", dt)(x))  # 32->30
        x = nn.elu(_conv(32, 3, "VALID", "conv2", dt)(x))  # 30->28
        x = _maxpool(x)  # 28->14
        x = nn.elu(_conv(64, 3, "VALID", "conv3", dt)(x))  # 14->12
        x = nn.elu(_conv(64, 3, "VALID", "conv4", dt)(x))  # 12->10
        x = _maxpool(x)  # 10->5
        x = x.reshape((x.shape[0], -1))  # 5*5*64 = 1600
        x = nn.elu(_dense(512, "fc1", dt)(x))
        return _dense(self.num_classes, "fc2", dt)(x)


class Net2(PartitionedModel):
    """9-layer CNN (~2.5M params). Reference src/simple_models.py:83-131."""

    GROUP_PATHS = tuple(
        ((name,),)
        for name in (
            "conv1",
            "conv2",
            "conv3",
            "conv4",
            "fc1",
            "fc2",
            "fc3",
            "fc4",
            "fc5",
        )
    )
    LINEAR_GROUP_IDS = (4, 5, 6, 7, 8)  # reference src/simple_models.py:119-120
    TRAIN_ORDER = (7, 2, 1, 4, 8, 6, 3, 0, 5)  # reference src/simple_models.py:130-131
    FOLD_LAYERS = {"conv": "free", "dense": "grouped"}

    num_classes: int = 10

    @nn.compact
    def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
        dt = self.dtype
        x = _maxpool(nn.elu(_conv(64, 3, "SAME", "conv1", dt)(x)))  # 32->16
        x = _maxpool(nn.elu(_conv(128, 3, "SAME", "conv2", dt)(x)))  # 16->8
        x = _maxpool(nn.elu(_conv(256, 3, "SAME", "conv3", dt)(x)))  # 8->4
        x = _maxpool(nn.elu(_conv(512, 3, "SAME", "conv4", dt)(x)))  # 4->2
        x = x.reshape((x.shape[0], -1))  # 2*2*512 = 2048
        x = nn.elu(_dense(128, "fc1", dt)(x))
        x = nn.elu(_dense(256, "fc2", dt)(x))
        x = nn.elu(_dense(512, "fc3", dt)(x))
        x = nn.elu(_dense(1024, "fc4", dt)(x))
        return _dense(self.num_classes, "fc5", dt)(x)
