// Race-detection harness for the native batcher (built with
// -fsanitize=thread by `make tsan`; see tests/test_native.py).
//
// Exercises the pathological schedules the Python binding can produce:
//  * a consumer blocked in batcher_next while another thread destroys
//  * rapid create/consume/destroy cycles
//  * destruction with the staging ring both full and empty
// ThreadSanitizer reports any data race / use-after-free as a fatal
// diagnostic (exit code != 0), which the test asserts against.

#include "cifar_loader.cpp"

#include <cstdio>
#include <cstdlib>
#include <thread>
#include <vector>

int main() {
  const int64_t n = 64, batch = 8;
  std::vector<uint8_t> images(n * 3072, 7);
  std::vector<int32_t> labels(n);
  for (int64_t i = 0; i < n; ++i) labels[i] = static_cast<int32_t>(i);

  for (int trial = 0; trial < 50; ++trial) {
    void* b = batcher_create(images.data(), labels.data(), n, batch,
                             /*seed=*/trial, /*drop_last=*/1,
                             /*prefetch_depth=*/1);
    if (!b) return 2;

    std::thread consumer([b] {
      std::vector<uint8_t> img(batch * 3072);
      std::vector<int32_t> lbl(batch);
      while (batcher_next(b, img.data(), lbl.data()) >= 0) {
      }
    });
    // let the consumer run a little, sometimes not at all
    if (trial % 3) std::this_thread::yield();
    batcher_destroy(b);  // must drain the (possibly blocked) consumer
    consumer.join();
  }

  // decode reentrancy: two threads decoding from the same source buffer
  std::vector<uint8_t> raw(32 * 3073, 9);
  std::vector<uint8_t> out1(32 * 3072), out2(32 * 3072);
  std::vector<int32_t> l1(32), l2(32);
  std::thread t1([&] { cifar_decode_records(raw.data(), 32, 1, out1.data(), l1.data(), 2); });
  std::thread t2([&] { cifar_decode_records(raw.data(), 32, 1, out2.data(), l2.data(), 2); });
  t1.join();
  t2.join();

  std::puts("stress OK");
  return 0;
}
