// Native data-loader runtime: CIFAR record decode + threaded batch prefetch.
//
// The reference delegates data loading to torchvision's DataLoader with one
// worker thread (reference src/federated_trio.py:68-70); its own code has no
// native components at all (SURVEY.md §2.1). This framework's host-side IO
// runtime is native where it counts:
//
//  * cifar_chw_to_hwc / cifar_decode_records: the plane->interleaved
//    transpose of every image (the one real CPU pass over the whole
//    dataset at startup), multithreaded across record ranges.
//  * batcher_*: a background-thread minibatch prefetcher over a bounded
//    ring of staging buffers (Fisher-Yates reshuffle per epoch), for
//    host-streaming pipelines whose dataset does not fit on device.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this environment);
// the Python side (data/native.py) compiles this file on demand with g++
// and falls back to numpy transparently when unavailable.
//
// Thread-safety contract: a batcher handle may be consumed from one Python
// thread while its producer thread fills buffers; decode entry points are
// stateless and reentrant.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

constexpr int64_t kImgBytes = 3072;  // 3 x 32 x 32
constexpr int64_t kHW = 1024;        // 32 x 32

// One image: CHW planes (R[1024] G[1024] B[1024]) -> HWC interleaved.
inline void transpose_one(const uint8_t* src, uint8_t* dst) {
  const uint8_t* r = src;
  const uint8_t* g = src + kHW;
  const uint8_t* b = src + 2 * kHW;
  for (int64_t p = 0; p < kHW; ++p) {
    dst[3 * p + 0] = r[p];
    dst[3 * p + 1] = g[p];
    dst[3 * p + 2] = b[p];
  }
}

void parallel_for(int64_t n, int n_threads, void (*fn)(int64_t, int64_t, void*),
                  void* ctx) {
  if (n_threads <= 1 || n < 2 * n_threads) {
    fn(0, n, ctx);
    return;
  }
  std::vector<std::thread> threads;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo >= hi) break;
    threads.emplace_back([=] { fn(lo, hi, ctx); });
  }
  for (auto& th : threads) th.join();
}

}  // namespace

extern "C" {

// [n, 3072] CHW-plane images -> [n, 32, 32, 3] HWC. Reentrant.
void cifar_chw_to_hwc(const uint8_t* src, int64_t n, uint8_t* dst,
                      int n_threads) {
  struct Ctx {
    const uint8_t* src;
    uint8_t* dst;
  } ctx{src, dst};
  parallel_for(
      n, n_threads,
      [](int64_t lo, int64_t hi, void* c) {
        auto* x = static_cast<Ctx*>(c);
        for (int64_t i = lo; i < hi; ++i)
          transpose_one(x->src + i * kImgBytes, x->dst + i * kImgBytes);
      },
      &ctx);
}

// Raw .bin records ([label_bytes | 3072 image bytes] x n) -> HWC images +
// int32 fine labels (the LAST label byte, matching the published layout
// where cifar-100 records carry [coarse, fine]). Reentrant.
void cifar_decode_records(const uint8_t* raw, int64_t n, int label_bytes,
                          uint8_t* images, int32_t* labels, int n_threads) {
  struct Ctx {
    const uint8_t* raw;
    uint8_t* images;
    int32_t* labels;
    int64_t rec;
    int lb;
  } ctx{raw, images, labels, label_bytes + kImgBytes, label_bytes};
  parallel_for(
      n, n_threads,
      [](int64_t lo, int64_t hi, void* c) {
        auto* x = static_cast<Ctx*>(c);
        for (int64_t i = lo; i < hi; ++i) {
          const uint8_t* r = x->raw + i * x->rec;
          x->labels[i] = static_cast<int32_t>(r[x->lb - 1]);
          transpose_one(r + x->lb, x->images + i * kImgBytes);
        }
      },
      &ctx);
}

// ---------------------------------------------------------------------------
// Prefetching batcher: producer thread, bounded ring of staging buffers.

struct Batcher {
  const uint8_t* images;  // [n, 3072] HWC bytes (not owned)
  const int32_t* labels;  // [n] (not owned)
  int64_t n;
  int64_t batch;
  bool drop_last;
  uint64_t seed;
  int64_t epoch;

  struct Slot {
    std::vector<uint8_t> img;
    std::vector<int32_t> lbl;
    int64_t count;
  };
  std::queue<Slot> ready;
  size_t capacity;
  std::mutex mu;
  std::condition_variable cv_ready, cv_space, cv_idle;
  std::atomic<bool> stop{false};
  int active_consumers = 0;  // guarded by mu; drained before destruction
  std::thread producer;

  void run() {
    std::vector<int64_t> perm(n);
    for (int64_t i = 0; i < n; ++i) perm[i] = i;
    while (!stop.load()) {
      // fresh shuffle each epoch, deterministic in (seed, epoch)
      std::mt19937_64 rng(seed + static_cast<uint64_t>(epoch) * 0x9e3779b97f4a7c15ULL);
      for (int64_t i = n - 1; i > 0; --i) {
        std::uniform_int_distribution<int64_t> d(0, i);
        std::swap(perm[i], perm[d(rng)]);
      }
      for (int64_t off = 0; off < n; off += batch) {
        int64_t count = std::min(batch, n - off);
        if (count < batch && drop_last) break;
        Slot s;
        s.count = count;
        s.img.resize(static_cast<size_t>(count) * kImgBytes);
        s.lbl.resize(static_cast<size_t>(count));
        for (int64_t j = 0; j < count; ++j) {
          int64_t src = perm[off + j];
          std::memcpy(s.img.data() + j * kImgBytes, images + src * kImgBytes,
                      kImgBytes);
          s.lbl[j] = labels[src];
        }
        std::unique_lock<std::mutex> lk(mu);
        cv_space.wait(lk, [&] { return ready.size() < capacity || stop.load(); });
        if (stop.load()) return;
        ready.push(std::move(s));
        cv_ready.notify_one();
      }
      ++epoch;
    }
  }
};

// Live-handle registry: batcher_next pins a handle under the registry
// lock, so a next() racing with destroy either pins before the drain
// (and is drained) or finds the handle already unregistered and returns
// -1 — a stale handle can never touch freed memory. Handles are
// monotonically increasing ids (NOT pointers), so a freed handle value is
// never reissued and the ABA hazard of address reuse cannot arise.
// Lock order: g_registry_mu, then Batcher::mu.
static std::mutex g_registry_mu;
static std::unordered_map<uint64_t, Batcher*> g_registry;
static uint64_t g_next_handle = 1;

static Batcher* registry_find(void* handle) {
  auto it = g_registry.find(reinterpret_cast<uint64_t>(handle));
  return it == g_registry.end() ? nullptr : it->second;
}

void* batcher_create(const uint8_t* images, const int32_t* labels, int64_t n,
                     int64_t batch, uint64_t seed, int drop_last,
                     int64_t prefetch_depth) {
  if (n <= 0 || batch <= 0 || batch > n) return nullptr;
  auto* b = new Batcher();
  b->images = images;
  b->labels = labels;
  b->n = n;
  b->batch = batch;
  b->drop_last = drop_last != 0;
  b->seed = seed;
  b->epoch = 0;
  b->capacity = static_cast<size_t>(prefetch_depth > 0 ? prefetch_depth : 2);
  b->producer = std::thread([b] { b->run(); });
  uint64_t id;
  {
    std::lock_guard<std::mutex> reg(g_registry_mu);
    id = g_next_handle++;
    g_registry.emplace(id, b);
  }
  return reinterpret_cast<void*>(id);
}

// Blocks until a batch is staged; copies it into the caller's buffers.
// Returns the sample count (<= batch; < batch only for a non-dropped
// tail), or -1 once the batcher is destroyed (or being destroyed).
int64_t batcher_next(void* handle, uint8_t* out_images, int32_t* out_labels) {
  Batcher* b;
  Batcher::Slot s;
  {
    std::unique_lock<std::mutex> lk;
    {
      std::lock_guard<std::mutex> reg(g_registry_mu);
      b = registry_find(handle);
      if (!b) return -1;  // destroyed (ids are never reissued)
      lk = std::unique_lock<std::mutex>(b->mu);
      ++b->active_consumers;  // pinned: destroy now waits for us
    }
    b->cv_ready.wait(lk, [&] { return !b->ready.empty() || b->stop.load(); });
    if (b->stop.load() && b->ready.empty()) {
      // destroy() is waiting on cv_idle for us to leave before freeing b
      --b->active_consumers;
      b->cv_idle.notify_all();
      return -1;
    }
    s = std::move(b->ready.front());
    b->ready.pop();
    b->cv_space.notify_one();
    --b->active_consumers;
    b->cv_idle.notify_all();
  }
  std::memcpy(out_images, s.img.data(), s.img.size());
  std::memcpy(out_labels, s.lbl.data(), s.lbl.size() * sizeof(int32_t));
  return s.count;
}

// Safe against consumers concurrently inside OR entering batcher_next
// (e.g. a GC-triggered close from another Python thread while the GIL is
// released in the ctypes call): the handle is unregistered first, so new
// calls bounce, and pinned consumers are woken and drained before the
// free. Idempotent: a second destroy on the same handle is a no-op.
void batcher_destroy(void* handle) {
  Batcher* b;
  {
    std::lock_guard<std::mutex> reg(g_registry_mu);
    b = registry_find(handle);
    if (!b) return;  // already destroyed
    g_registry.erase(reinterpret_cast<uint64_t>(handle));
  }
  b->stop.store(true);
  {
    std::unique_lock<std::mutex> lk(b->mu);
    b->cv_ready.notify_all();
    b->cv_space.notify_all();
    b->cv_idle.wait(lk, [&] { return b->active_consumers == 0; });
  }
  if (b->producer.joinable()) b->producer.join();
  delete b;
}

}  // extern "C"
