"""Run a COMPLETE reference training schedule on the real TPU chip.

`--preset fedavg` is the full `federated_trio.py` schedule (Nloop=12,
5 partition groups, Nadmm=3, batch 512, biased inputs, elastic net) and
`--preset admm` the full `consensus_admm_trio.py` one (Nadmm=5,
BB-adaptive rho) — end to end: every epoch, every consensus round, every
full-test-set evaluation. Writes `full_<preset>_tpu.json` next to this
file (the artifacts `BASELINE.md` cites).

No CIFAR archive ships in this environment, so the deterministic
synthetic stand-in at the reference's exact shapes (50k/10k) is used.
By default it is the DISCRIMINATING variant (class overlap + label
noise, the same HARDNESS the parity oracle uses — accuracy plateaus
near ~0.78 instead of saturating at 1.0, so a subtly wrong consensus
step shows up in the curve, round-2 VERDICT weak #1); `--separable`
restores the easy set. The per-round residual series are recorded
alongside the accuracy curve, plus the communication ledger's exact
per-round uplink bytes and its partial-vs-full-exchange summary
(obs/ledger.py, docs/OBSERVABILITY.md).

Run: python benchmarks/full_schedule_tpu.py --preset fedavg
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--preset",
        default="fedavg",
        choices=["fedavg", "admm", "fedavg_resnet", "admm_resnet"],
    )
    # the resnet schedules are ~10x the simple ones on one shared chip
    # (10 groups x 520 batch-32 minibatches per epoch); --nloop trims the
    # OUTER loop count only — every group, every consensus round, every
    # eval still runs, so the schedule STRUCTURE stays complete
    ap.add_argument("--nloop", type=int, default=None)
    # route the epoch through the host-streaming path (chunked scans):
    # the resident ResNet epoch is a single 520-step scanned program that
    # crashes this environment's TPU worker; 8-step chunks do not
    ap.add_argument("--stream", action="store_true")
    # the linearly-separable easy synthetic (every healthy config hits
    # 1.0 — useful only for throughput, not as an oracle)
    ap.add_argument("--separable", action="store_true")
    # escape hatch: per-epoch dispatches instead of the fused one-
    # dispatch round (engine/steps.py build_round_fn) — for measuring
    # the dispatch tail the fusion harvests
    ap.add_argument("--no-fuse-rounds", action="store_true")
    # escape hatch: per-consensus-round evals as standalone dispatches on
    # the round's state snapshots instead of folded inside the fused
    # program — for measuring the eval tail the fold harvests (the full
    # fedavg/admm schedules issue 180/300 standalone eval launches)
    ap.add_argument("--no-fold-eval", action="store_true")
    # JAX persistent compilation cache: warm reruns of the same schedule
    # skip XLA backend compilation (config.compile_cache)
    ap.add_argument("--compile-cache", metavar="DIR", default=None)
    # multi-alpha line-search fan width (config.linesearch_probes,
    # docs/PERF.md): 1 = the sequential bitwise-identical search; 4 = the
    # widened probe fan (same accepted alpha per step up to ulp ties,
    # amortized parameter streaming — the roofline lever bench.py prices
    # as probe_batch_speedup)
    ap.add_argument("--linesearch-probes", type=int, default=None)
    # widened client fold (config.client_fold, docs/PERF.md §Widened
    # GEMM): 'gemm' (engine default) re-batches the probe fan at the
    # tree level so frozen layers run once per fan and active
    # contractions widen to M = B·P; 'vmap' compiles today's exact
    # probe-batched programs byte-for-byte — the baseline the
    # widened_gemm_speedup claim is measured against
    ap.add_argument(
        "--client-fold", choices=["gemm", "vmap"], default=None
    )
    # exchange wire codec (config.exchange_dtype, exchange/): 'bfloat16'
    # halves every exchange's uplink bytes; the recorded comm series and
    # summary show the wire bytes exactly
    ap.add_argument(
        "--exchange-dtype", choices=["float32", "bfloat16"], default=None
    )
    # load a REAL-FORMAT on-disk archive (scripts/make_cifar_archive.py
    # writes a checksum-verified one in the published binary layout) via
    # the real loader path — native bin decoding, no synthetic fallback
    ap.add_argument("--real-archive", metavar="ROOT", default=None)
    args = ap.parse_args()

    import jax

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    assert jax.default_backend() == "tpu", jax.default_backend()

    over = {"nloop": args.nloop} if args.nloop is not None else {}
    if args.no_fuse_rounds:
        over["fuse_rounds"] = False
    if args.no_fold_eval:
        over["fold_eval"] = False
    if args.compile_cache:
        over["compile_cache"] = args.compile_cache
    if args.linesearch_probes is not None:
        over["linesearch_probes"] = args.linesearch_probes
    if args.exchange_dtype is not None:
        over["exchange_dtype"] = args.exchange_dtype
    if args.client_fold is not None:
        over["client_fold"] = args.client_fold
    if args.stream:
        over.update(hbm_data_budget_mb=0, stream_chunk_steps=8)
    if args.real_archive:
        over.update(data_root=args.real_archive, synthetic_ok=False)
    cfg = get_preset(args.preset, **over)
    source = None
    hardness = None
    if args.real_archive:
        pass  # Trainer loads from disk through load_cifar (bin decoder)
    elif not args.separable:
        # the parity oracle's HARDNESS knobs (convergence_parity.py):
        # sub-saturation accuracy makes the curve discriminating
        hardness = dict(noise=110.0, overlap=0.35, label_noise=0.25)
        source = synthetic_cifar(
            n_train=50000, n_test=10000, seed=0,
            num_classes=100 if cfg.dataset == "cifar100" else 10,
            **hardness,
        )
    tr = Trainer(cfg, verbose=False, source=source)
    t0 = time.perf_counter()
    if tr._fused_enabled():
        # AOT-seed the round programs INSIDE the timed wall (the run's
        # first round pays this compile either way) — compile_round also
        # stashes each program's exact XLA FLOP/byte counts, so the run
        # ends with measured `roofline` records (obs/roofline.py):
        # ROADMAP item 2's honest roofline note as an artifact field
        for g in tr.group_order:
            tr.compile_round(g)
    rec = tr.run()
    wall = time.perf_counter() - t0

    accs = rec.series["test_accuracy"]
    step_times = [
        e["value"]["seconds"]
        for e in rec.series.get("step_time", [])
        if e["value"].get("phase") == "epoch"
    ]
    # fused rounds (the default): one `fused_round` timing per partition
    # round covering nadmm*(nepoch epochs + consensus). No derived
    # per-epoch number — dividing the round time by nadmm*nepoch would
    # fold the consensus collectives (and the first round's compile)
    # into a figure the committed unfused runs report as PURE epoch
    # dispatch time; fused runs leave epoch_step_time_median_s null and
    # report the round median instead (compare via --no-fuse-rounds).
    round_times = [
        e["value"]["seconds"]
        for e in rec.series.get("step_time", [])
        if e["value"].get("phase") == "fused_round"
    ]
    out = {
        "experiment": f"full {args.preset} preset (complete reference schedule)"
        + (f" at nloop={args.nloop}" if args.nloop is not None else "")
        + (" via the streaming data path" if args.stream else ""),
        "nloop": cfg.nloop,
        "backend": "tpu",
        "device": str(jax.devices()[0]),
        "dataset": (
            f"REAL-FORMAT binary archive at {args.real_archive} "
            "(published CIFAR bin layout, native decoder, no synthetic "
            "fallback; generator: scripts/make_cifar_archive.py)"
            if args.real_archive
            else "synthetic 50k/10k, separable (throughput only)"
            if args.separable
            else "synthetic 50k/10k DISCRIMINATING "
            f"(overlap {hardness['overlap']}, label noise "
            f"{hardness['label_noise']} -> sub-saturation plateau)"
        ),
        "wall_seconds": round(wall, 1),
        "rounds_evaluated": len(accs),
        "final_per_client_accuracy": [float(a) for a in accs[-1]["value"]],
        # the full per-round series: mean accuracy + residuals — the
        # in-loop telemetry the reference prints per round (reference
        # src/federated_trio.py:358-366)
        "acc_mean_per_round": [
            round(float(np.mean(a["value"])), 4) for a in accs
        ],
        "dual_residual_per_round": [
            float(r["value"]) for r in rec.series.get("dual_residual", [])
        ],
        "epoch_step_time_median_s": (
            round(float(np.median(step_times)), 3) if step_times else None
        ),
        "fused_rounds": bool(round_times),
        "fused_round_time_median_s": (
            round(float(np.median(round_times)), 3) if round_times else None
        ),
        # eval placement (the eval-tail PR): 'folded' = evals inside the
        # fused round program (default — zero standalone eval dispatches),
        # 'async' = standalone eval dispatches with deferred host
        # harvest (--no-fold-eval, or wherever fusion falls back),
        # 'sync' would require --no-async-eval too
        "eval_mode": (
            "folded" if tr._fold_eval_enabled()
            else "async" if cfg.async_eval and cfg.check_results
            else "sync" if cfg.check_results
            else None
        ),
        "round_dispatches_total": sum(
            r["value"].get("total", 0)
            for r in rec.series.get("dispatch_count", [])
        ),
        "eval_dispatches_total": sum(
            r["value"].get("eval", 0)
            for r in rec.series.get("dispatch_count", [])
        ),
        "compile_cache": args.compile_cache,
        # the roofline knobs this schedule ran under (docs/PERF.md)
        "linesearch_probes": cfg.linesearch_probes,
        "client_fold": cfg.client_fold,
        "exchange_dtype": cfg.exchange_dtype,
        # the communication ledger (obs/ledger.py): exact per-exchange
        # uplink bytes and the end-of-run summary comparing the partial-
        # parameter schedule against the hypothetical full-model exchange
        # and the ship-the-data floor — the paper's bandwidth claim as a
        # recorded artifact of the complete reference schedule
        "comm_bytes_per_round": [
            int(r["value"]) for r in rec.series.get("comm_bytes", [])
        ],
        "comm_summary": rec.latest("comm_summary"),
        # the measured roofline (obs/roofline.py): the AOT round
        # program's XLA cost counts over the median warm-round wall —
        # achieved FLOP/s, HBM fraction, arithmetic intensity vs the
        # ridge, and the memory/compute verdict, per partition group
        "roofline_per_group": {
            str(r["group"]): r["value"]
            for r in rec.series.get("roofline", [])
        },
        "roofline": rec.latest("roofline"),
        # the in-run health engine's verdict (obs/health.py): rounds
        # monitored, anomalies fired, and the final sketch/window state
        "health_rounds": len(rec.series.get("health", [])),
        "health_anomalies": sum(
            len(r["value"].get("anomalies", ()))
            for r in rec.series.get("health", [])
        ),
        "health_final": (
            rec.series["health"][-1]["value"]
            if rec.series.get("health")
            else None
        ),
    }
    if args.preset.startswith("admm"):
        out["primal_residual_per_round"] = [
            float(r["value"]) for r in rec.series.get("primal_residual", [])
        ]
        out["mean_rho_per_round"] = [
            float(r["value"]) for r in rec.series.get("mean_rho", [])
        ]
        out["final_primal_residual"] = float(
            rec.latest("primal_residual")
        )
        out["final_dual_residual"] = float(rec.latest("dual_residual"))
        out["final_mean_rho"] = float(rec.latest("mean_rho"))

    suffix = "_realformat" if args.real_archive else ""
    # the escape-hatch comparison pairs must not overwrite their baselines
    if args.no_fuse_rounds:
        suffix += "_nofused"
    if args.no_fold_eval:
        suffix += "_nofoldeval"
    if cfg.exchange_dtype == "bfloat16":
        suffix += "_bf16x"  # codec runs sit beside their f32 baselines
    if cfg.linesearch_probes != 1:
        suffix += f"_p{cfg.linesearch_probes}"
    if cfg.client_fold == "vmap":
        suffix += "_vmapfold"  # the widened-GEMM comparison baseline
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"full_{args.preset}{suffix}_tpu.json",
    )
    # the provenance stamp (obs/provenance.py): this artifact closes a
    # DEBT.json entry only if the stamp satisfies its condition — a
    # CPU-twin run of this script can never pay a backend==tpu debt
    from federated_pytorch_test_tpu.obs.provenance import provenance_stamp

    out["provenance"] = provenance_stamp()
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
