"""Run a COMPLETE reference training schedule on the real TPU chip.

`--preset fedavg` is the full `federated_trio.py` schedule (Nloop=12,
5 partition groups, Nadmm=3, batch 512, biased inputs, elastic net) and
`--preset admm` the full `consensus_admm_trio.py` one (Nadmm=5,
BB-adaptive rho) — end to end: every epoch, every consensus round, every
full-test-set evaluation. Writes `full_<preset>_tpu.json` next to this
file (the artifacts `BASELINE.md` cites).

No CIFAR archive ships in this environment, so the deterministic
synthetic stand-in at the reference's exact shapes (50k/10k) is used —
identical compute, learnable labels (accuracy saturates quickly).

Run: python benchmarks/full_schedule_tpu.py --preset fedavg
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--preset",
        default="fedavg",
        choices=["fedavg", "admm", "fedavg_resnet", "admm_resnet"],
    )
    # the resnet schedules are ~10x the simple ones on one shared chip
    # (10 groups x 520 batch-32 minibatches per epoch); --nloop trims the
    # OUTER loop count only — every group, every consensus round, every
    # eval still runs, so the schedule STRUCTURE stays complete
    ap.add_argument("--nloop", type=int, default=None)
    # route the epoch through the host-streaming path (chunked scans):
    # the resident ResNet epoch is a single 520-step scanned program that
    # crashes this environment's TPU worker; 8-step chunks do not
    ap.add_argument("--stream", action="store_true")
    args = ap.parse_args()

    import jax

    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    assert jax.default_backend() == "tpu", jax.default_backend()

    over = {"nloop": args.nloop} if args.nloop is not None else {}
    if args.stream:
        over.update(hbm_data_budget_mb=0, stream_chunk_steps=8)
    cfg = get_preset(args.preset, **over)
    tr = Trainer(cfg, verbose=False)
    t0 = time.perf_counter()
    rec = tr.run()
    wall = time.perf_counter() - t0

    accs = rec.series["test_accuracy"]
    step_times = [
        e["value"]["seconds"]
        for e in rec.series.get("step_time", [])
        if e["value"].get("phase") == "epoch"
    ]
    out = {
        "experiment": f"full {args.preset} preset (complete reference schedule)"
        + (f" at nloop={args.nloop}" if args.nloop is not None else "")
        + (" via the streaming data path" if args.stream else ""),
        "nloop": cfg.nloop,
        "backend": "tpu",
        "device": str(jax.devices()[0]),
        "dataset": "synthetic 50k/10k (no CIFAR archive in this environment)",
        "wall_seconds": round(wall, 1),
        "rounds_evaluated": len(accs),
        "final_per_client_accuracy": [float(a) for a in accs[-1]["value"]],
        "epoch_step_time_median_s": (
            round(float(np.median(step_times)), 3) if step_times else None
        ),
    }
    if args.preset.startswith("admm"):
        out["final_primal_residual"] = float(
            rec.latest("primal_residual")
        )
        out["final_dual_residual"] = float(rec.latest("dual_residual"))
        out["final_mean_rho"] = float(rec.latest("mean_rho"))

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"full_{args.preset}_tpu.json",
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
