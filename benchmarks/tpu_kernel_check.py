"""On-TPU validation of the flash kernels (compiled, not interpret mode).

Compares `flash_attention` forward/backward and the offset-aware
`flash_block` partials against HIGHEST-precision dense attention on the
real chip. The dense reference must ALSO be pinned to HIGHEST precision:
at default precision XLA lowers f32 einsums to bf16 MXU passes and the
diff (~3e-3 at S=256) measures the reference, not the kernel.

Run: python benchmarks/tpu_kernel_check.py   (requires a TPU backend)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax, jax.numpy as jnp
import numpy as np
from federated_pytorch_test_tpu.ops.flash_attention import flash_attention, flash_block
from federated_pytorch_test_tpu.parallel import dense_attention

assert jax.default_backend() == "tpu"
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(1, 256, 2, 64), jnp.float32)
k = jnp.asarray(rng.randn(1, 256, 2, 64), jnp.float32)
v = jnp.asarray(rng.randn(1, 256, 2, 64), jnp.float32)
with jax.default_matmul_precision("highest"):
    for causal in (False, True):
        out_f = jax.jit(lambda q,k,v: flash_attention(q,k,v,causal=causal))(q,k,v)
        out_d = dense_attention(q,k,v,causal=causal)
        err = float(jnp.abs(out_f - out_d).max())
        gf = jax.jit(jax.grad(lambda q,k,v: flash_attention(q,k,v,causal=causal).sum(), argnums=(0,1,2)))(q,k,v)
        gd = jax.jit(jax.grad(lambda q,k,v: dense_attention(q,k,v,causal=causal).sum(), argnums=(0,1,2)))(q,k,v)
        gerr = max(float(jnp.abs(a-b).max()) for a,b in zip(gf,gd))
        print(f"flash_attention causal={causal}: fwd {err:.2e} grad {gerr:.2e}")
        assert err < 2e-5 and gerr < 2e-3, (err, gerr)

    # flash_block with dynamic offsets (jitted, traced offsets): merge two
    # K/V halves for rows 128..255 == full causal attention
    ref = dense_attention(q, k, v, causal=True)
    @jax.jit
    def merged(q, k, v):
        qb = q[:, 128:]
        parts = []
        for j in (0, 1):
            o, lse = flash_block(qb, k[:, 128*j:128*(j+1)], v[:, 128*j:128*(j+1)],
                                 jnp.int32(128), jnp.int32(128*j), causal=True)
            parts.append((o, lse))  # o already [B,H,Sq,D]
        m = jnp.maximum(parts[0][1], parts[1][1])
        w0, w1 = (jnp.exp(l - m) for l in (parts[0][1], parts[1][1]))
        out = (parts[0][0]*w0[...,None] + parts[1][0]*w1[...,None]) / (w0+w1)[...,None]
        return jnp.transpose(out, (0,2,1,3))
    err = float(jnp.abs(merged(q,k,v) - ref[:, 128:]).max())
    print(f"flash_block offset merge: {err:.2e}")
    assert err < 2e-5

    # fully-future block: exact zeros / -BIG lse
    o, lse = jax.jit(lambda q,k,v: flash_block(q[:, :128], k[:, 128:], v[:, 128:],
                      jnp.int32(0), jnp.int32(128), causal=True))(q,k,v)
    assert float(jnp.abs(o).max()) == 0.0 and float(lse.max()) <= -1e29
# --- compact L-BFGS direction kernels (ops/compact_pallas.py) vs the
# pure-JAX compact backend (optim/compact.py) on the chip ---
from federated_pytorch_test_tpu.ops.compact_pallas import compact_direction_pallas
from federated_pytorch_test_tpu.optim.compact import compact_direction

m, n = 10, 1_000_003  # odd N exercises the masked tail tile
s_hist = jnp.asarray(rng.randn(m, n) * 1e-2, jnp.float32)
y_hist = jnp.asarray(rng.randn(m, n) * 1e-2, jnp.float32)
g = jnp.asarray(rng.randn(n), jnp.float32)
for count in (0, 4, 10):
    cnt = jnp.int32(count)
    hd = jnp.float32(0.7)
    d_pl = jax.jit(compact_direction_pallas)(g, s_hist, y_hist, cnt, hd)
    with jax.default_matmul_precision("highest"):
        d_ref = jax.jit(compact_direction)(g, s_hist, y_hist, cnt, hd)
    scale = float(jnp.abs(d_ref).max())
    err = float(jnp.abs(d_pl - d_ref).max()) / max(scale, 1e-30)
    print(f"compact direction count={count}: rel err {err:.2e}")
    assert err < 5e-5, err
print("COMPACT-ON-TPU OK")
print("NEW-FLASH-ON-TPU OK")
