"""Client-scaling sweeps: vmapped K on one chip, cohorts over N virtual.

Two probes in one harness:

* **K sweep** (default): the flagship workload (ResNet18 FedAvg epoch,
  batch 32/client, stochastic L-BFGS with line search) at K =
  3/6/12/24/48 clients. The reference hard-codes K=3 (reference
  src/federated_trio.py:98-100); this framework folds ANY K into
  vmapped local blocks per device (parallel/mesh.py), so the sweep
  answers: where does the vmapped client batch saturate a device?
  Efficiency is reported PER DEVICE — `samples_per_sec_per_device` and
  `scaling_efficiency` = per-device throughput vs the first row's —
  because on a multi-device mesh K folds to K/D clients per device and
  the old per-client absolute numbers conflated "the chip saturated"
  with "we divided by more clients" (the efficiency collapse the cohort
  axis exists to fix is a PER-DEVICE phenomenon).

* **cohort sweep** (`--virtual-clients N1,N2,... --cohort C`): cohort
  mode (clients/, docs/SCALE.md) at fixed C over growing virtual
  populations N. The scale contract is that the warm
  gather→round→scatter wall is FLAT in N (per-device work is C/D,
  the store is lazy, the sampler O(C)); `flat_vs_smallest` per row is
  the smallest-N wall over this row's — ≈1.0 everywhere is a pass,
  and the acceptance gate reads the C=8→C=64 per-device flatness off
  the same rows.

Writes `client_scaling_tpu.json` (K sweep) or `cohort_scaling_tpu.json`
(cohort sweep; `_cpu` suffix when forced onto the host platform) next to
this file.

Run: python benchmarks/client_scaling_tpu.py
     python benchmarks/client_scaling_tpu.py --virtual-clients \
         1000,10000 --cohort 8 [--allow-cpu]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

KS = (3, 6, 12, 24, 48)
BATCH = 32
STEPS = 8


def _k_sweep(jax, jnp, client_fold=None):
    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset
    from federated_pytorch_test_tpu.parallel import mesh_size

    fold_over = {} if client_fold is None else {"client_fold": client_fold}
    rows = []
    for k in KS:
        src = synthetic_cifar(n_train=k * BATCH * STEPS, n_test=64)
        cfg = get_preset(
            "fedavg_resnet", n_clients=k, batch=BATCH, check_results=False,
            **fold_over,
        )
        tr = Trainer(cfg, verbose=False, source=src)
        gid = tr.group_order[0]
        epoch_fn, _, init_fn = tr._fns(gid)
        lstate, y, z, rho, extra = init_fn(tr.flat)
        flat, stats = tr.flat, tr.stats
        idx = tr._epoch_indices(0, gid, 0, 0)[:STEPS]

        def run(flat, lstate, stats):
            flat, lstate, stats, _ = epoch_fn(
                flat, lstate, stats, tr.shard_imgs, tr.shard_labels,
                idx, tr.mean, tr.std, y, z, rho,
            )
            return flat, lstate, stats

        # warmup/compile; scalar fetch is the true completion barrier on
        # the tunneled runtime (see bench.py)
        flat, lstate, stats = run(flat, lstate, stats)
        float(jnp.sum(flat[:, 0]))
        dt = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            flat, lstate, stats = run(flat, lstate, stats)
            float(jnp.sum(flat[:, 0]))
            dt = min(dt, time.perf_counter() - t0)

        d = mesh_size(tr.mesh)
        sps = STEPS * k * BATCH / dt
        row = {
            "n_clients": k,
            "n_devices": d,
            "samples_per_sec": round(sps, 1),
            "epoch_time_s": round(dt, 4),
            # PER-DEVICE throughput: the saturation question is about a
            # device's local client block, not the global client count
            "samples_per_sec_per_device": round(sps / d, 1),
            "samples_per_sec_per_client": round(sps / k, 1),
            "scaling_efficiency": None,  # filled below (per device)
        }
        rows.append(row)
        print(json.dumps(row))

    base = rows[0]["samples_per_sec_per_device"]
    for r in rows:
        r["scaling_efficiency"] = round(
            r["samples_per_sec_per_device"] / base, 3
        )
    return {
        "workload": f"ResNet18 FedAvg jitted epoch, batch {BATCH}/client, "
                    f"{STEPS} lockstep minibatches, K client blocks folded "
                    "onto the mesh (K/D vmapped clients per device); "
                    "scaling_efficiency is PER-DEVICE throughput vs the "
                    "first row",
        "device": str(jax.devices()[0]),
        "client_fold": client_fold or "gemm",
        "rows": rows,
    }


def _cohort_sweep(jax, ns, cohorts, model, batch, steps, prefetch=True,
                  client_fold=None):
    """Warm gather→round→scatter wall over (cohort C, population N).

    Per-CLIENT work is held constant across every row: the shard pool is
    sized so each client's shard is exactly `batch * steps` samples,
    whatever C or N — so the only things varying are the cohort width of
    the compiled client axis (the per-device block is C/D) and the
    virtual-population size behind the store. Two flatness ratios per
    row:

    * `flat_in_n` — smallest-N wall / this wall at the SAME C: ≈1.0
      means per-round cost is independent of the population (the store
      is lazy, the sampler O(C));
    * `per_device_vs_smallest_c` — per-device samples/sec vs the
      smallest-C row at the same N: ≈1.0 means the sharded cohort axis
      scales (each device's C/D-client block neither starves nor
      saturates as C grows) — the acceptance curve, within 10% from
      C=8 to C=64.
    """
    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset
    from federated_pytorch_test_tpu.parallel import mesh_size

    shards = max(cohorts)
    src = synthetic_cifar(n_train=shards * batch * steps, n_test=64)
    rows = []
    for cohort in cohorts:
        for n_virtual in ns:
            if n_virtual < shards:
                # every row shares one shard pool (max cohort) so
                # per-client work is constant; a population smaller than
                # the pool can't map onto it — say so rather than
                # silently shifting the flatness baselines
                print(json.dumps({
                    "virtual_clients": n_virtual, "cohort": cohort,
                    "skipped": f"n_virtual < shard pool ({shards}): "
                    "raise --virtual-clients or drop the largest cohort",
                }))
                continue
            fold_over = (
                {} if client_fold is None else {"client_fold": client_fold}
            )
            cfg = get_preset(
                "fedavg", model=model, batch=batch, check_results=False,
                nadmm=1, nepoch=1, max_groups=1, reg_mode="none",
                virtual_clients=n_virtual, cohort=cohort,
                data_shards=shards, prefetch=prefetch, **fold_over,
            )
            tr = Trainer(cfg, verbose=False, source=src)
            tr.run_loop(0)  # warmup: compile-dominated
            dts = []
            for nloop in range(1, 4):
                t0 = time.perf_counter()
                tr.run_loop(nloop)  # one gather -> round -> scatter
                dts.append(time.perf_counter() - t0)
            dt = float(np.median(dts))
            d = mesh_size(tr.mesh)
            sps = steps * cohort * batch / dt
            rows.append({
                "virtual_clients": n_virtual,
                "cohort": cohort,
                "prefetch": bool(prefetch),
                "n_devices": d,
                "round_time_s": round(dt, 4),
                "samples_per_sec": round(sps, 1),
                "samples_per_sec_per_device": round(sps / d, 1),
                "flat_in_n": None,                # filled below
                "per_device_vs_smallest_c": None,  # filled below
            })
            print(json.dumps(rows[-1]))
            tr.close()
    for r in rows:
        same_c = [x for x in rows if x["cohort"] == r["cohort"]]
        r["flat_in_n"] = round(
            same_c[0]["round_time_s"] / r["round_time_s"], 3
        )
        same_n = [
            x for x in rows
            if x["virtual_clients"] == r["virtual_clients"]
        ]
        r["per_device_vs_smallest_c"] = round(
            r["samples_per_sec_per_device"]
            / same_n[0]["samples_per_sec_per_device"],
            3,
        )
    return {
        "workload": f"{model} FedAvg cohort round (gather + one fused "
                    f"round + scatter), batch {batch}/client, "
                    f"{steps} lockstep steps/client, shard pool "
                    f"{shards}; cohort C sharded over the mesh, N "
                    "virtual clients behind the host store",
        "device": str(jax.devices()[0]),
        "n_devices": len(jax.devices()),
        "client_fold": client_fold or "gemm",
        "rows": rows,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--virtual-clients", default=None,
        help="comma-separated virtual-population sizes: run the cohort "
        "sweep instead of the K sweep",
    )
    ap.add_argument(
        "--cohort", default="8",
        help="comma-separated cohort sizes for the cohort sweep "
        "(e.g. 8,16,32,64 for the per-device flatness curve)",
    )
    ap.add_argument(
        "--model", default="resnet18",
        help="model for the cohort sweep (use 'net' on the CPU twin — "
        "a ResNet18 epoch costs minutes of host CPU per step)",
    )
    ap.add_argument("--batch", type=int, default=BATCH)
    ap.add_argument("--steps", type=int, default=STEPS)
    ap.add_argument(
        "--allow-cpu", action="store_true",
        help="run on the CPU mesh twin (no TPU reachable); output gets "
        "a _cpu suffix and the TPU re-measurement stays owed",
    )
    ap.add_argument(
        "--client-fold", choices=["gemm", "vmap"], default=None,
        help="widened client fold (docs/PERF.md §Widened GEMM): 'gemm' "
        "(engine default) widens the probe fan into the example axis; "
        "'vmap' compiles the probe-batched baseline byte-for-byte — "
        "output gets a _vmapfold suffix so pairs sit side by side",
    )
    ap.add_argument(
        "--no-prefetch", action="store_true",
        help="disable the pipelined cohort prefetch for the cohort "
        "sweep (clients/prefetch.py) — measures the synchronous-gather "
        "wall the prefetch removes; rows record which mode they ran",
    )
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    if not args.allow_cpu:
        assert jax.default_backend() == "tpu", jax.default_backend()

    here = os.path.dirname(os.path.abspath(__file__))
    suffix = "" if jax.default_backend() == "tpu" else "_cpu"
    if args.client_fold == "vmap":
        suffix += "_vmapfold"  # baseline runs sit beside their gemm twins
    if args.virtual_clients:
        # both axes sorted ascending: the flatness ratios below are
        # defined against the smallest-N / smallest-C row of each group
        ns = sorted(int(v) for v in args.virtual_clients.split(","))
        cohorts = sorted(int(v) for v in args.cohort.split(","))
        out = _cohort_sweep(
            jax, ns, cohorts, args.model, args.batch, args.steps,
            prefetch=not args.no_prefetch, client_fold=args.client_fold,
        )
        path = os.path.join(here, f"cohort_scaling_tpu{suffix}.json")
    else:
        out = _k_sweep(jax, jnp, client_fold=args.client_fold)
        path = os.path.join(here, f"client_scaling_tpu{suffix}.json")
    # the provenance stamp (obs/provenance.py): the trend layer keys
    # scaling baselines on the stamp's class, and only a satisfying
    # stamp (backend==tpu) closes the vmapfold DEBT.json entry
    from federated_pytorch_test_tpu.obs.provenance import provenance_stamp

    out["provenance"] = provenance_stamp()
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
