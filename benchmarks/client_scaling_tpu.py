"""Client-scaling sweep on one real TPU chip.

The reference hard-codes K=3 clients (reference src/federated_trio.py:
98-100). This framework folds ANY K into vmapped local blocks per device
(parallel/mesh.py), so one chip can simulate a whole pod's worth of
clients — the single-chip half of the scale-out story. This sweep runs
the flagship workload (ResNet18 FedAvg epoch, batch 32/client, stochastic
L-BFGS with line search) at K = 3/6/12/24/48 local clients on ONE device
and records throughput, answering: where does the vmapped client batch
saturate the chip?

Writes `client_scaling_tpu.json` next to this file. Requires a TPU.

Run: python benchmarks/client_scaling_tpu.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

KS = (3, 6, 12, 24, 48)
BATCH = 32
STEPS = 8


def main():
    import jax
    import jax.numpy as jnp

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    assert jax.default_backend() == "tpu", jax.default_backend()
    rows = []
    for k in KS:
        src = synthetic_cifar(n_train=k * BATCH * STEPS, n_test=64)
        cfg = get_preset(
            "fedavg_resnet", n_clients=k, batch=BATCH, check_results=False
        )
        tr = Trainer(cfg, verbose=False, source=src)
        gid = tr.group_order[0]
        epoch_fn, _, init_fn = tr._fns(gid)
        lstate, y, z, rho, extra = init_fn(tr.flat)
        flat, stats = tr.flat, tr.stats
        idx = tr._epoch_indices(0, gid, 0, 0)[:STEPS]

        def run(flat, lstate, stats):
            flat, lstate, stats, _ = epoch_fn(
                flat, lstate, stats, tr.shard_imgs, tr.shard_labels,
                idx, tr.mean, tr.std, y, z, rho,
            )
            return flat, lstate, stats

        # warmup/compile; scalar fetch is the true completion barrier on
        # the tunneled runtime (see bench.py)
        flat, lstate, stats = run(flat, lstate, stats)
        float(jnp.sum(flat[:, 0]))
        dt = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            flat, lstate, stats = run(flat, lstate, stats)
            float(jnp.sum(flat[:, 0]))
            dt = min(dt, time.perf_counter() - t0)

        sps = STEPS * k * BATCH / dt
        row = {
            "n_clients": k,
            "samples_per_sec": round(sps, 1),
            "epoch_time_s": round(dt, 4),
            "samples_per_sec_per_client": round(sps / k, 1),
            "scaling_efficiency_vs_k3": None,  # filled below
        }
        rows.append(row)
        print(json.dumps(row))

    base = rows[0]["samples_per_sec"] / rows[0]["n_clients"]
    for r in rows:
        r["scaling_efficiency_vs_k3"] = round(
            (r["samples_per_sec"] / r["n_clients"]) / base, 3
        )

    out = {
        "workload": f"ResNet18 FedAvg jitted epoch, batch {BATCH}/client, "
                    f"{STEPS} lockstep minibatches, K vmapped client blocks "
                    "on ONE device (group = first shuffled block)",
        "device": str(jax.devices()[0]),
        "rows": rows,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "client_scaling_tpu.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
