"""One-off attribution probe for the D=64 flash ceiling (round 5).

Answers two questions on the real chip before committing to a packed-head
kernel design:

1. Does the MXU pad sub-128 contraction/output dims temporally? Timed
   bf16 matmul chains [M,K]x[K,N] at K in {64, 128, 256} and N in
   {64, 128} — if time(K=64) ~= time(K=128), the D=64 score dot wastes
   half the array, as BASELINE.md's constant-width sweep implied.

2. Where does the flash fwd tile step actually spend its time? Three
   kernels on the SAME grid / BlockSpecs / tile shapes (S=4096, D=64,
   causal triangular grid, tile 512):
     full    — the real forward (matmuls + online softmax)
     mmonly  — matmuls only (o += (q kT) v, no max/exp/sum)
     dmaonly — tile copy only (no MXU, no VPU beyond a vector add)
   full - mmonly ~= VPU softmax cost; mmonly - dmaonly ~= MXU cost;
   dmaonly ~= DMA + grid overhead. Writes flash_attrib_probe.json.
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

import importlib

# ops/__init__ re-exports the flash_attention FUNCTION under the same
# name, shadowing the submodule attribute `import ... as` would resolve
fa = importlib.import_module("federated_pytorch_test_tpu.ops.flash_attention")

S = 4096
D = 64
B, H = 2, 8
BQ = 512
# the tunnel's flat per-call latency is ~0.07-0.11 s (measured, varies);
# every measurement loops enough inner steps inside ONE jitted call that
# the floor is <5% of the total, and subtracts a measured floor estimate
INNER_TILE = 256
INNER_MM = 16384
REPS = 6


def floor_estimate():
    from tpu_timing import dispatch_floor  # single copy of the protocol

    return dispatch_floor()


def best_of(fn, inner, floor, *args):
    float(fn(*args))  # compile + warm
    best = float("inf")
    for _ in range(REPS):
        t0 = time.perf_counter()
        float(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return max(best - floor, 0.0) / inner


def matmul_chain(m, k, n, floor):
    """Per-step time of a dependent bf16 [m,k]x[k,n] matmul chain."""
    a = jnp.asarray(np.random.default_rng(0).normal(size=(m, k)), jnp.bfloat16)
    b = jnp.asarray(np.random.default_rng(1).normal(size=(k, n)), jnp.bfloat16)

    @jax.jit
    def step(a, b):
        def body(i, acc):
            x = jax.lax.dot_general(
                a * (1 + i.astype(jnp.bfloat16) * jnp.bfloat16(1e-3)), b,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return acc + jnp.sum(x * x)

        return jax.lax.fori_loop(0, INNER_MM, body, jnp.float32(0))

    return best_of(step, INNER_MM, floor, a, b)


# ---------------------------------------------------------------- tile probes


def _probe_kernel(itab, jtab, q_ref, k_ref, v_ref, o_ref, o_acc, m_acc,
                  l_acc, *, mode: str, bq: int):
    p_id = pl.program_id(1)
    i = itab[p_id]
    j = jtab[p_id]

    @pl.when(j == 0)
    def _():
        o_acc[:] = jnp.zeros_like(o_acc)
        m_acc[:] = jnp.full_like(m_acc, fa._NEG_BIG)
        l_acc[:] = jnp.zeros_like(l_acc)

    if mode == "dmaonly":
        o_acc[:] = o_acc[:] + q_ref[0] + k_ref[0] + v_ref[0]
    elif mode == "mmonly":
        sc = fa._dot(q_ref[0], k_ref[0], fa._LL, None)
        o_acc[:] = o_acc[:] + fa._dot(sc, v_ref[0], fa._LF, None)
    elif mode in ("full", "diagmask", "exp2", "slicewrite", "combo",
                  "combo_bf16"):
        sc = fa._dot(q_ref[0], k_ref[0], fa._LL, None)
        if mode in ("diagmask", "combo", "combo_bf16"):
            # off-diagonal tiles (j < i) are entirely sub-diagonal: the
            # causal mask is the identity there — only the j == i tile
            # needs the iota/compare/where pass
            sc = jax.lax.cond(
                j == i,
                lambda s: fa._causal_mask(s, i * bq, j * bq),
                lambda s: s,
                sc,
            )
        else:
            sc = fa._causal_mask(sc, i * bq, j * bq)
        m, l, o = m_acc[:, 0], l_acc[:, 0], o_acc[:]
        m_new = jnp.maximum(m, jnp.max(sc, axis=1))
        if mode in ("exp2", "combo", "combo_bf16"):
            # scores pre-scaled by log2(e) would fold the base change into
            # the q scale; the probe approximates the cost with exp2 direct
            p = jnp.exp2(sc - m_new[:, None])
        else:
            p = jnp.exp(sc - m_new[:, None])
        if mode == "combo_bf16":
            p16 = p.astype(jnp.bfloat16)
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=1)
            o_new = o * corr[:, None] + fa._dot(p16, v_ref[0], fa._LF, None)
        else:
            corr = jnp.exp(m - m_new)
            l_new = l * corr + jnp.sum(p, axis=1)
            o_new = o * corr[:, None] + fa._dot(p, v_ref[0], fa._LF, None)
        o_acc[:] = o_new
        if mode in ("slicewrite", "combo", "combo_bf16"):
            m_acc[:, 0:1] = m_new[:, None]
            l_acc[:, 0:1] = l_new[:, None]
        else:
            m_acc[:] = jnp.broadcast_to(m_new[:, None], m_acc.shape)
            l_acc[:] = jnp.broadcast_to(l_new[:, None], l_acc.shape)
    elif mode == "bf16p":
        sc = fa._causal_mask(
            fa._dot(q_ref[0], k_ref[0], fa._LL, None), i * bq, j * bq
        )
        m, l, o = m_acc[:, 0], l_acc[:, 0], o_acc[:]
        m_new = jnp.maximum(m, jnp.max(sc, axis=1))
        p = jnp.exp(sc - m_new[:, None]).astype(jnp.bfloat16)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p.astype(jnp.float32), axis=1)
        o_new = o * corr[:, None] + fa._dot(p, v_ref[0], fa._LF, None)
        o_acc[:] = o_new
        m_acc[:] = jnp.broadcast_to(m_new[:, None], m_acc.shape)
        l_acc[:] = jnp.broadcast_to(l_new[:, None], l_acc.shape)
    else:
        raise ValueError(mode)

    @pl.when(j == i)
    def _():
        o_ref[0] = o_acc[:]


def tile_probe(mode: str, floor: float):
    bh = B * H
    nq = S // BQ
    itab, jtab = fa._tri_tables_qmajor(nq)
    spec = pl.BlockSpec((1, BQ, D), lambda b, p, it, jt: (b, it[p], 0))
    kvspec = pl.BlockSpec((1, BQ, D), lambda b, p, it, jt: (b, jt[p], 0))
    call = pl.pallas_call(
        functools.partial(_probe_kernel, mode=mode, bq=BQ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(bh, itab.shape[0]),
            in_specs=[spec, kvspec, kvspec],
            out_specs=spec,
            scratch_shapes=[
                pltpu.VMEM((BQ, D), jnp.float32),
                pltpu.VMEM((BQ, 128), jnp.float32),
                pltpu.VMEM((BQ, 128), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, S, D), jnp.float32),
    )
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(bh, S, D)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(bh, S, D)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(bh, S, D)), jnp.bfloat16)
    it, jt = jnp.asarray(itab), jnp.asarray(jtab)

    @jax.jit
    def step(q, k, v):
        def body(i, acc):
            qi = q * (1 + i.astype(jnp.bfloat16) * jnp.bfloat16(1e-3))
            o = call(it, jt, qi, k, v)
            return acc + jnp.sum(o * o)

        return jax.lax.fori_loop(0, INNER_TILE, body, jnp.float32(0))

    return best_of(step, INNER_TILE, floor, q, k, v)


def main():
    floor = floor_estimate()
    print(f"[floor] {floor*1e3:.1f} ms per call", flush=True)
    out = {"device": jax.devices()[0].device_kind, "S": S, "D": D,
           "tile": BQ, "inner_tile": INNER_TILE, "inner_mm": INNER_MM,
           "dispatch_floor_s": round(floor, 4)}
    mm = {}
    for m, k, n in [(4096, 64, 4096), (4096, 128, 4096), (4096, 256, 4096),
                    (4096, 512, 64), (4096, 512, 128)]:
        t = matmul_chain(m, k, n, floor)
        useful = 2 * m * k * n
        mm[f"{m}x{k}x{n}"] = {
            "step_s": round(t, 8),
            "useful_tflops": round(useful / t / 1e12, 2),
        }
        print(f"[mm] {m}x{k}x{n}: {t*1e6:.0f} us  "
              f"{useful / t / 1e12:.1f} TF/s useful", flush=True)
    out["matmul_chains"] = mm

    tiles = {}
    for mode in ("dmaonly", "mmonly", "full", "diagmask", "exp2",
                 "slicewrite", "bf16p", "combo", "combo_bf16"):
        t = tile_probe(mode, floor)
        tiles[mode] = round(t, 6)
        print(f"[tile] {mode}: {t*1e3:.3f} ms/step", flush=True)
    out["tile_modes_fwd_s"] = tiles
    out["attribution"] = {
        "dma_plus_grid_s": tiles["dmaonly"],
        "mxu_s": round(tiles["mmonly"] - tiles["dmaonly"], 6),
        "vpu_softmax_s": round(tiles["full"] - tiles["mmonly"], 6),
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "flash_attrib_probe.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out["attribution"]))


if __name__ == "__main__":
    main()
