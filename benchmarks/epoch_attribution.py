"""Attribute flagship epoch time to its components, by measurement.

BASELINE.md argues the flagship (3-client ResNet18 FedAvg) plateaus at
~4.5k samples/s because the inner solver's sequential dependency chain —
line-search probes, direction algebra, curvature guards between every
forward — cannot be hidden by batch size. Round-3 VERDICT weak #5:
that attribution was a hypothesis. This benchmark MEASURES it.

Method: with the same scalar-fetch timing barrier bench.py uses, time
separately, best-of-3, at batch 512 and 2048 (f32, group = the shuffled
order's first block):

  epoch_step   one step of the jitted sharded epoch program (the real
               thing: L-BFGS step + metrics, S steps scanned, / S)
  grad_eval    one vmapped value_and_grad of the SAME group loss at the
               same batch (what each inner iteration pays for its
               closure gradient)
  probe_eval   one vmapped forward-only loss (what each line-search
               probe pays)
  machinery    one full lbfgs_step on a dummy quadratic loss of the same
               group dimension (direction algebra, curvature updates,
               line-search control flow — everything BUT the model)

and read the solver's own counter (aux.func_evals) for how many
closure-equivalent evaluations one step actually performs. The modeled
step time is then

  modeled = n_grad * grad_eval + n_probe * probe_eval + machinery

with n_grad = max_iter re-evals and n_probe = func_evals - n_grad, and
`unattributed = epoch_step - modeled` is dispatch/scan overhead the
components cannot see. Writes epoch_attribution.json.

Run: python benchmarks/epoch_attribution.py
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def _best_of(fn, n=3):
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def measure(batch: int, steps: int) -> dict:
    import jax
    import jax.numpy as jnp

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset
    from federated_pytorch_test_tpu.engine.steps import _data_loss
    from federated_pytorch_test_tpu.optim import (
        LBFGSConfig,
        lbfgs_init,
        lbfgs_step,
    )

    k = 3
    src = synthetic_cifar(n_train=k * batch * max(steps, 4), n_test=64)
    cfg = get_preset(
        "fedavg_resnet",
        n_clients=k,
        batch=batch,
        check_results=False,
        max_scan_steps=None,
    )
    tr = Trainer(cfg, verbose=False, source=src)
    gid = tr.group_order[0]
    ctx = tr._ctx(gid)
    epoch_fn, _, init_fn = tr._fns(gid)
    lstate, y, z, rho, extra = init_fn(tr.flat)
    idx = tr._epoch_indices(0, gid, 0, 0)[:steps]
    # the epoch program donates (flat, lstate, stats); keep copies for
    # the component measurements below, which run after the epoch timing
    flat_snap = jnp.array(tr.flat)
    stats_snap = jax.tree.map(jnp.array, tr.stats)

    # ---- the real epoch program (S steps scanned), per-step time ----
    # epoch_fn donates (flat, lstate, stats): thread them through calls
    carry = {"flat": tr.flat, "lstate": lstate, "stats": tr.stats}

    def run_epoch():
        flat2, lstate2, stats2, _losses = epoch_fn(
            carry["flat"], carry["lstate"], carry["stats"],
            tr.shard_imgs, tr.shard_labels, idx, tr.mean, tr.std, y, z, rho,
        )
        carry.update(flat=flat2, lstate=lstate2, stats=stats2)
        float(jnp.sum(flat2[:, 0]))  # scalar fetch = completion barrier

    run_epoch()  # compile + warmup
    t_epoch_step = _best_of(run_epoch) / steps
    # the solver's own counter: closure-equivalent evals per step,
    # cumulative over 1 warmup + 3 timed epochs
    fe = np.asarray(
        jax.tree.leaves(carry["lstate"].func_evals)[0]
    ).reshape(-1)
    evals_per_step = float(fe.mean()) / (4 * steps)

    # ---- one vmapped grad eval / probe eval of the same group loss ----
    imgs0 = tr.shard_imgs[:, : batch]
    labs0 = tr.shard_labels[:, : batch]
    flat_c, stats_c = flat_snap, stats_snap

    def group_loss(x_k, flat_k, stats_k, img_k, lab_k, mean_k, std_k):
        from federated_pytorch_test_tpu.data import normalize

        full = ctx.partition.insert(flat_k, gid, x_k)
        loss, _ = _data_loss(
            ctx, full, stats_k, normalize(img_k, mean_k, std_k), lab_k
        )
        return loss

    x0 = jax.vmap(lambda f: ctx.partition.extract(f, gid))(flat_c)

    # each component is measured as ONE jitted program of R dependent
    # repeats (the tiny carry update forces sequential execution), then
    # divided by R — the tunneled runtime's ~0.1 s flat dispatch+fetch
    # latency otherwise dominates a single component call and the
    # standalone numbers overstate the epoch's true per-eval cost
    R = 8
    from jax import lax

    def vg_chain(x, flat_k, stats_k, img_k, lab_k, mean_k, std_k):
        def body(c, _):
            l, g = jax.value_and_grad(group_loss)(
                c, flat_k, stats_k, img_k, lab_k, mean_k, std_k
            )
            return c + 1e-12 * g, l

        xf, ls = lax.scan(body, x, None, length=R)
        return xf, ls

    def fwd_chain(x, flat_k, stats_k, img_k, lab_k, mean_k, std_k):
        def body(c, _):
            l = group_loss(c, flat_k, stats_k, img_k, lab_k, mean_k, std_k)
            return c * (1.0 + 1e-12 * l), l

        xf, ls = lax.scan(body, x, None, length=R)
        return xf, ls

    vg = jax.jit(jax.vmap(vg_chain))
    fwd = jax.jit(jax.vmap(fwd_chain))

    def run_vg():
        xf, l = vg(x0, flat_c, stats_c, imgs0, labs0, tr.mean, tr.std)
        float(jnp.sum(xf[:, 0]))

    def run_fwd():
        xf, l = fwd(x0, flat_c, stats_c, imgs0, labs0, tr.mean, tr.std)
        float(jnp.sum(xf[:, 0]))

    run_vg()
    t_grad = _best_of(run_vg) / R
    run_fwd()
    t_fwd = _best_of(run_fwd) / R

    # ---- solver machinery on a dummy quadratic of the group size ----
    n = int(x0.shape[1])
    lcfg = LBFGSConfig(
        max_iter=cfg.lbfgs_max_iter,
        history_size=cfg.lbfgs_history,
        line_search=True,
        batch_mode=True,
        direction=cfg.lbfgs_direction,
    )

    def quad(v):
        return 0.5 * jnp.sum(v * v)

    def machinery_chain(xs, ss):
        def one(x, s):
            x_init = x

            def body(carry, _):
                xx, sst = carry
                x2, s2, _ = lbfgs_step(quad, xx, sst, lcfg)
                # re-inflate: on the plain quadratic the solver converges
                # in one repeat and later repeats would early-exit on a
                # ~zero gradient, understating the algebra cost; the
                # displacement keeps the gradient O(|x_init|) every
                # repeat while the carried state keeps real curvature
                # history flowing through the direction computation
                return (x2 + x_init, s2), None

            (xf, _), _ = lax.scan(body, (x, s), None, length=R)
            return xf

        return jax.vmap(one)(xs, ss)

    ms = jax.jit(machinery_chain)
    st0 = jax.vmap(lambda x: lbfgs_init(x, lcfg))(x0)
    xs = ms(x0, st0)
    float(jnp.sum(xs[:, 0]))

    def run_mach():
        a = ms(x0, st0)
        float(jnp.sum(a[:, 0]))

    t_mach = _best_of(run_mach) / R

    n_grad = float(cfg.lbfgs_max_iter)
    n_probe = max(evals_per_step - n_grad, 0.0)
    modeled = n_grad * t_grad + n_probe * t_fwd + t_mach
    return {
        "batch": batch,
        "steps_timed": steps,
        "group_id": int(gid),
        "group_dim": n,
        "epoch_step_ms": round(1e3 * t_epoch_step, 2),
        "grad_eval_ms": round(1e3 * t_grad, 2),
        "probe_eval_ms": round(1e3 * t_fwd, 2),
        "machinery_ms": round(1e3 * t_mach, 2),
        "evals_per_step": round(evals_per_step, 2),
        "n_grad": n_grad,
        "n_probe": round(n_probe, 2),
        "modeled_step_ms": round(1e3 * modeled, 2),
        "unattributed_ms": round(1e3 * (t_epoch_step - modeled), 2),
        "modeled_fraction": round(modeled / t_epoch_step, 3),
    }


def main() -> None:
    import jax

    assert jax.default_backend() == "tpu", jax.default_backend()
    rows = [measure(512, 4), measure(2048, 2)]
    out = {
        "workload": "fedavg_resnet flagship epoch, f32, 3 clients, "
        "first shuffled group",
        "method": "component timings as 8-repeat dependent scans with "
        "scalar-fetch barriers, best-of-3 / 8 (amortizes the tunneled "
        "runtime's ~0.1 s flat dispatch latency exactly as the scanned "
        "epoch does); evals from the solver's own func_evals counter",
        "rows": rows,
    }
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "epoch_attribution.json"
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
