"""Convergence parity v2: reference algorithm (torch) vs this framework
(JAX), same data, same hyper-parameters — a DISCRIMINATING oracle.

v1's synthetic set was linearly separable: every healthy configuration
reached 1.0 accuracy, so the curves could not distinguish a correct
implementation from a subtly wrong one. v2 hardens the dataset (class
overlap + 25% label noise -> test accuracy plateaus near the ~0.78 Bayes
ceiling, see data/cifar.synthetic_cifar) and compares, per averaging
round, BOTH the accuracy trajectory AND the consensus-residual
trajectories, with explicit tolerance bands:

  * accuracy: |final_fw - final_ref| <= 0.05 and mean per-round
    |diff| <= 0.06 (the inner-epoch minibatch shuffles are independent
    streams, so curves agree statistically, not bitwise);
  * residuals: median |log10(fw / ref)| <= 0.5 over the aligned rounds
    (residuals decay over orders of magnitude; half an order is tight
    enough to catch a wrong z/y/rho update and loose enough for the
    shuffle noise);
  * ADMM mean rho: final ratio in [0.5, 2] (BB adaptation must walk the
    same path).

Five configurations, mirroring the reference driver pairs:

  fedavg_simple  Net, FULL schedule: nloop x 5 groups x nadmm=3
  admm_simple    Net, FULL schedule: nloop x 5 groups x nadmm=5, BB rho
  fedavg_resnet  ResNet18, FULL 10-block shuffled schedule: nloop x 10
                 groups x nadmm=3, on a shrunken shard (128/client) so
                 the torch side stays a few hours, not days — both sides
                 train well above chance, so the 0.05 accuracy band is
                 as discriminating as the simple configs' (round-2
                 VERDICT item 1)
  admm_resnet    ResNet18, FULL schedule: same structure, fixed rho
  fedavg_resnet_matched
                 ResNet18 FedAvg with the inner solver constrained
                 identically on both sides (max_iter=2) so neither runs
                 away: the sides converge to the same accuracy and the
                 residual half-order band is REQUIRED by the suite gate
                 (round-4 VERDICT item 3 — matched dynamics validated
                 by measurement, not argument)

The torch side imports the reference's own `LBFGSNew` from
/root/reference/src (imported, NOT copied) and re-drives the algorithms
exactly as SURVEY.md §3.1/§3.2 document them; the ADMM/BB semantics
follow consensus/admm.py, which was trajectory-validated against a numpy
mirror of the reference in round 1.

Run (one config per invocation; results merge into
benchmarks/convergence_parity.json):

  python benchmarks/convergence_parity.py fedavg_simple
  python benchmarks/convergence_parity.py admm_simple
  python benchmarks/convergence_parity.py fedavg_resnet
  python benchmarks/convergence_parity.py admm_resnet
  python benchmarks/convergence_parity.py fedavg_resnet_matched

Env: PARITY_NLOOP overrides the simple configs' outer-loop count
(default 8; the reference uses 12 — pure runtime knob, the schedule
structure is identical).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

K = 3
SEED = 0
N_TEST = 300
NLOOP_SIMPLE = int(os.environ.get("PARITY_NLOOP", "8"))

# dataset hardness: overlap shrinks class margins, label noise caps the
# achievable test accuracy at ~0.78 — the plateau the oracle needs
HARDNESS = dict(noise=110.0, overlap=0.35, label_noise=0.25)

SIMPLE = dict(batch=64, n_train=960)   # 320/client -> 5 lockstep batches
# 128/client -> 4 lockstep batches of 32. Small on purpose: the torch
# side pays ~36 s per lockstep minibatch on this 1-core host
# (benchmarks/reference_throughput.json), so the full-10-block resnet
# schedule at RESNET_NLOOP outer loops is hours, not days — the dataset
# is shrunk and the loop count raised until both sides learn well above
# chance (round-2 VERDICT item 1: "shrink the dataset / raise epochs
# rather than truncating blocks")
RESNET = dict(batch=32, n_train=int(os.environ.get("PARITY_RESNET_NTRAIN",
                                                   "384")))
NLOOP_RESNET = int(os.environ.get("PARITY_RESNET_NLOOP", "2"))

REFERENCE_SRC = os.environ.get("REFERENCE_SRC", "/root/reference/src")

ADMM_RHO0 = float(os.environ.get("PARITY_RHO0", "1e-3"))
BB = dict(period=2, corr_min=0.2, eps=1e-3, rho_max=0.1)


def synthetic(n_train):
    """The suite's dataset: discriminating synthetic by default, or the
    REAL archive (`PARITY_DATA=real`, root from $CIFAR_DATA_DIR) when one
    is present — same deterministic subsample on both sides, retiring
    the "all parity evidence is synthetic" cap the moment an archive
    exists (scripts/parity_suite.sh is the rehearsed one-command path).
    """
    if os.environ.get("PARITY_DATA") == "real":
        import dataclasses

        from federated_pytorch_test_tpu.data import load_cifar

        src = load_cifar("cifar10", synthetic_ok=False)
        rng = np.random.default_rng(SEED)
        tr = rng.permutation(len(src.train_images))[:n_train]
        te = rng.permutation(len(src.test_images))[:N_TEST]
        return dataclasses.replace(
            src,
            train_images=src.train_images[tr],
            train_labels=src.train_labels[tr],
            test_images=src.test_images[te],
            test_labels=src.test_labels[te],
        )
    from federated_pytorch_test_tpu.data import synthetic_cifar

    return synthetic_cifar(
        n_train=n_train, n_test=N_TEST, seed=SEED, **HARDNESS
    )


# --------------------------------------------------------------- torch side


def _torch_models(kind):
    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    if kind == "net":

        class Net(nn.Module):
            # the reference's 5-layer simple CNN shape-for-shape
            # (reference src/simple_models.py:9-39), ELU, NCHW
            def __init__(self):
                super().__init__()
                self.conv1 = nn.Conv2d(3, 6, 5)
                self.conv2 = nn.Conv2d(6, 16, 5)
                self.fc1 = nn.Linear(400, 120)
                self.fc2 = nn.Linear(120, 84)
                self.fc3 = nn.Linear(84, 10)

            def forward(self, x):
                x = F.max_pool2d(F.elu(self.conv1(x)), 2)
                x = F.max_pool2d(F.elu(self.conv2(x)), 2)
                x = x.flatten(1)
                x = F.elu(self.fc1(x))
                x = F.elu(self.fc2(x))
                return self.fc3(x)

        groups = [["conv1"], ["conv2"], ["fc1"], ["fc2"], ["fc3"]]
        order = [2, 0, 1, 3, 4]  # reference src/simple_models.py:38-39
        return Net, groups, order

    class Block(nn.Module):
        # BasicBlock with ELU (reference src/federated_trio_resnet.py:65-87)
        def __init__(self, inp, planes, stride):
            super().__init__()
            self.conv1 = nn.Conv2d(inp, planes, 3, stride, 1, bias=False)
            self.bn1 = nn.BatchNorm2d(planes)
            self.conv2 = nn.Conv2d(planes, planes, 3, 1, 1, bias=False)
            self.bn2 = nn.BatchNorm2d(planes)
            self.short = None
            if stride != 1 or inp != planes:
                self.short = nn.Sequential(
                    nn.Conv2d(inp, planes, 1, stride, bias=False),
                    nn.BatchNorm2d(planes),
                )

        def forward(self, x):
            out = F.elu(self.bn1(self.conv1(x)))
            out = self.bn2(self.conv2(out))
            sc = x if self.short is None else self.short(x)
            return F.elu(out + sc)

    class ResNet18(nn.Module):
        # stage layout (reference src/federated_trio_resnet.py:118-152)
        STAGES = [(64, 1), (64, 1), (128, 2), (128, 1),
                  (256, 2), (256, 1), (512, 2), (512, 1)]

        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 64, 3, 1, 1, bias=False)
            self.bn1 = nn.BatchNorm2d(64)
            inp = 64
            for i, (planes, stride) in enumerate(self.STAGES):
                setattr(self, f"block{i}", Block(inp, planes, stride))
                inp = planes
            self.linear = nn.Linear(512, 10)

        def forward(self, x):
            x = F.elu(self.bn1(self.conv1(x)))
            for i in range(8):
                x = getattr(self, f"block{i}")(x)
            x = F.avg_pool2d(x, 4)
            return self.linear(x.flatten(1))

    # the decoded upidx table: [stem, block0..7, linear]
    # (reference src/federated_trio_resnet.py:174-178)
    groups = [["conv1", "bn1"]] + [[f"block{i}"] for i in range(8)] + [["linear"]]
    rng = np.random.RandomState(0)  # reference :296-297
    order = list(rng.permutation(10))
    return ResNet18, groups, order


def _trainable(net, groups, gid):
    """Freeze all but group `gid`; return its live parameter list."""
    want = set(groups[gid])
    params = []
    for name, mod in net.named_children():
        on = name in want
        for p in mod.parameters():
            p.requires_grad = on
        if on:
            params.extend(mod.parameters())
    return params


def _flat(params):
    import torch

    with torch.no_grad():
        return torch.cat([p.reshape(-1) for p in params]).clone()


def _put_flat(params, vec):
    import torch

    with torch.no_grad():
        i = 0
        for p in params:
            n = p.numel()
            p.copy_(vec[i : i + n].reshape(p.shape))
            i += n


def run_reference(kind, src, batch, nloop, nadmm, strategy, bb, group_slice,
                  lbfgs=None):
    import torch
    import torch.nn as nn

    sys.path.insert(0, REFERENCE_SRC)
    from lbfgsnew import LBFGSNew  # reference optimizer (imported, not copied)

    lb = lbfgs or {}
    Model, groups, order = _torch_models(kind)
    order = order[:group_slice] if group_slice else order
    L = len(groups)

    torch.manual_seed(SEED)
    nets = []
    for _ in range(K):
        torch.manual_seed(SEED)  # common-seed init across clients
        nets.append(Model())

    def norm(a):  # unbiased (x/255 - .5)/.5, NCHW
        return (a.astype(np.float32) / 255.0 - 0.5) / 0.5

    imgs, labs = norm(src.train_images), src.train_labels.astype(np.int64)
    per = len(imgs) // K
    shards = [
        (
            torch.from_numpy(imgs[c * per : (c + 1) * per].transpose(0, 3, 1, 2)),
            torch.from_numpy(labs[c * per : (c + 1) * per]),
        )
        for c in range(K)
    ]
    te_x = torch.from_numpy(norm(src.test_images).transpose(0, 3, 1, 2))
    te_y = torch.from_numpy(src.test_labels.astype(np.int64))
    crit = nn.CrossEntropyLoss()
    rng = np.random.default_rng(SEED)

    def accuracy():
        accs = []
        for net in nets:
            net.eval()
            with torch.no_grad():
                accs.append(float((net(te_x).argmax(1) == te_y).float().mean()))
            net.train()
        return accs

    rho_store = {g: [ADMM_RHO0] * K for g in range(L)}  # persistent rho
    acc, dual_r, primal_r, rho_r = [accuracy()], [], [], []

    for loop in range(nloop):
        for gid in order:
            plists = [_trainable(net, groups, gid) for net in nets]
            opts = [
                LBFGSNew(pl, lr=lb.get("lr", 1.0),
                         history_size=lb.get("history", 10),
                         max_iter=lb.get("max_iter", 4),
                         line_search_fn=True, batch_mode=True)
                for pl in plists
            ]
            n = _flat(plists[0]).numel()
            z = torch.zeros(n)
            ys = [torch.zeros(n) for _ in range(K)]
            rho = [float(r) for r in rho_store[gid]]
            # BB state quirks (consensus/admm.py; reference :299-302):
            # yhat0 initializes to the group's STARTING parameter values
            yhat0 = [_flat(pl) for pl in plists]
            x0 = [torch.zeros(n) for _ in range(K)]

            for it in range(nadmm):
                # one epoch of lockstep minibatches (x-update)
                orders = [rng.permutation(per) for _ in range(K)]
                for s in range(per // batch):
                    for c in range(K):
                        sel = orders[c][s * batch : (s + 1) * batch]
                        bx, by = shards[c][0][sel], shards[c][1][sel]

                        def closure():
                            if torch.is_grad_enabled():
                                opts[c].zero_grad()
                            loss = crit(nets[c](bx), by)
                            if strategy == "admm":
                                # LIVE cat view: the aug-Lagrangian term is
                                # part of the autograd graph (reference
                                # src/consensus_admm_trio.py:343)
                                xv = torch.cat(
                                    [p.reshape(-1) for p in plists[c]]
                                )
                                diff = xv - z
                                loss = loss + torch.dot(ys[c], diff) \
                                    + 0.5 * rho[c] * torch.dot(diff, diff)
                            if loss.requires_grad:
                                loss.backward()
                            return loss

                        opts[c].step(closure)

                xs = [_flat(pl) for pl in plists]
                if strategy == "fedavg":
                    znew = sum(xs) / K
                    dual_r.append(float(torch.norm(z - znew)) / n)
                    for pl in plists:
                        _put_flat(pl, znew)
                    z = znew
                else:
                    if bb:
                        due = it > 0 and it % BB["period"] == 0
                        yhat = [ys[c] + rho[c] * (xs[c] - z) for c in range(K)]
                        if due:
                            for c in range(K):
                                dy, dx = yhat[c] - yhat0[c], xs[c] - x0[c]
                                d11 = float(torch.dot(dy, dy))
                                d12 = float(torch.dot(dy, dx))
                                d22 = float(torch.dot(dx, dx))
                                if (abs(d12) > BB["eps"] and d11 > BB["eps"]
                                        and d22 > BB["eps"]):
                                    alpha = d12 / np.sqrt(d11 * d22)
                                    a_sd, a_mg = d11 / d12, d12 / d22
                                    a_hat = a_mg if 2 * a_mg > a_sd \
                                        else a_sd - 0.5 * a_mg
                                    if (alpha >= BB["corr_min"]
                                            and a_hat < BB["rho_max"]):
                                        rho[c] = a_hat
                        if it == 0 or due:
                            x0 = [x.clone() for x in xs]
                        if due:
                            yhat0 = [yh.clone() for yh in yhat]
                    wsum = sum(rho)
                    znew = sum(ys[c] + rho[c] * xs[c] for c in range(K)) / wsum
                    dual_r.append(float(torch.norm(z - znew)) / n)
                    for c in range(K):
                        ys[c] = ys[c] + rho[c] * (xs[c] - znew)
                    primal_r.append(
                        sum(float(torch.norm(xs[c] - znew)) for c in range(K))
                        / (K * n)
                    )
                    rho_r.append(sum(rho) / K)
                    z = znew
                acc.append(accuracy())
            rho_store[gid] = list(rho)

    return dict(acc=acc, dual=dual_r, primal=primal_r, mean_rho=rho_r)


# ----------------------------------------------------------- framework side


def run_framework(kind, src, batch, nloop, nadmm, strategy, bb, group_slice,
                  lbfgs=None):
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    preset = {
        ("net", "fedavg"): "fedavg",
        ("net", "admm"): "admm",
        ("resnet18", "fedavg"): "fedavg_resnet",
        ("resnet18", "admm"): "admm_resnet",
    }[(kind, strategy)]
    lb = lbfgs or {}
    cfg = get_preset(
        preset,
        model=kind if kind == "net" else "resnet18",
        batch=batch,
        nloop=nloop,
        nadmm=nadmm,
        biased_input=False,
        reg_mode="none",
        check_results=True,
        bb_update=bb,
        admm_rho0=ADMM_RHO0,
        seed=SEED,
        eval_batch=N_TEST,
        lbfgs_lr=lb.get("lr", 1.0),
        lbfgs_history=lb.get("history", 10),
        lbfgs_max_iter=lb.get("max_iter", 4),
    )
    tr = Trainer(cfg, verbose=False, source=src)
    if group_slice:
        tr.group_order = tr.group_order[:group_slice]
    acc = [list(np.asarray(tr.evaluate(), float))]
    rec = tr.run()
    acc += [r["value"] for r in rec.series["test_accuracy"]]
    out = dict(
        acc=acc,
        dual=[r["value"] for r in rec.series.get("dual_residual", [])],
        primal=[r["value"] for r in rec.series.get("primal_residual", [])],
        mean_rho=[r["value"] for r in rec.series.get("mean_rho", [])],
    )
    return out


# ------------------------------------------------------------------ compare


def _mean_curve(acc_series):
    return [float(np.mean(a)) for a in acc_series]


def _log_ratio_band(fw, ref):
    """Median |log10(fw/ref)| over aligned, strictly-positive rounds."""
    m = min(len(fw), len(ref))
    pairs = [
        (f, r)
        for f, r in zip(fw[:m], ref[:m])
        if f and r and f > 0 and r > 0
    ]
    if not pairs:
        return None
    return float(
        np.median([abs(np.log10(f / r)) for f, r in pairs])
    )


def compare(fw, ref, strategy, acc_band=0.05, num_classes=10,
            matched=False):
    """`acc_band` is the final-accuracy tolerance: all four configs run
    their FULL schedule until both sides sit well above chance, where a
    0.05 band on the plateau is a meaningful oracle (a wrong consensus
    step costs more than that; shuffle noise costs less).

    `num_classes` sets the chance floor (1/num_classes) for the
    above-2x-chance sanity check — a 100-class config must clear 0.02,
    not inherit the 10-class 0.2 bar.

    `matched=True` (matched-dynamics configs) additionally emits
    `matched_pass`: the SINGLE source of the stricter oracle the suite
    gate enforces for those configs — primary pass AND similar final
    accuracy AND every trajectory band for this strategy present and
    true (a residual series that stops being produced fails here rather
    than passing by omission). The gate reads only this bool, never the
    band key set.
    """
    fa, ra = _mean_curve(fw["acc"]), _mean_curve(ref["acc"])
    m = min(len(fa), len(ra))
    diffs = [abs(f - r) for f, r in zip(fa[:m], ra[:m])]
    chance = 1.0 / num_classes
    out = {
        "num_classes": num_classes,
        "final_acc": {"framework": fa[-1], "reference": ra[-1]},
        "final_acc_diff": round(abs(fa[-1] - ra[-1]), 4),
        "mean_acc_diff": round(float(np.mean(diffs)), 4),
        "acc_band": acc_band,
        # the PRIMARY oracle is one-sided — parity or better: the
        # framework must not trail the reference by more than the band,
        # and both sides must sit well above chance for the comparison
        # to mean anything. A framework that BEATS the reference by more
        # than the band fails the symmetric check below while being
        # exactly the desired outcome, so both views are recorded.
        "both_above_2x_chance": fa[-1] >= 2 * chance and ra[-1] >= 2 * chance,
        "framework_ge_reference_minus_band": fa[-1] >= ra[-1] - acc_band,
        "framework_beats_reference": fa[-1] > ra[-1],
        "acc_final_within_band": abs(fa[-1] - ra[-1]) <= acc_band,
        "acc_mean_within_0.06": float(np.mean(diffs)) <= 0.06,
        "dual_log10_median": _log_ratio_band(fw["dual"], ref["dual"]),
    }
    # the gate's single source of truth: the PRIMARY oracle as one bool,
    # so consumers never have to mirror this function's key set
    out["primary_pass"] = bool(
        out["both_above_2x_chance"] and out["framework_ge_reference_minus_band"]
    )
    if out["dual_log10_median"] is not None:
        out["dual_within_half_order"] = out["dual_log10_median"] <= 0.5
    if strategy == "admm":
        out["primal_log10_median"] = _log_ratio_band(
            fw["primal"], ref["primal"]
        )
        if out["primal_log10_median"] is not None:
            out["primal_within_half_order"] = (
                out["primal_log10_median"] <= 0.5
            )
        if fw["mean_rho"] and ref["mean_rho"]:
            ratio = fw["mean_rho"][-1] / ref["mean_rho"][-1]
            out["final_rho_ratio"] = round(float(ratio), 3)
            out["rho_ratio_within_2x"] = 0.5 <= ratio <= 2.0
    if matched:
        required = ["acc_final_within_band", "acc_mean_within_0.06",
                    "dual_within_half_order"]
        if strategy == "admm":
            required += ["primal_within_half_order", "rho_ratio_within_2x"]
        out["matched_pass"] = bool(
            out["primary_pass"]
            and all(out.get(k, False) for k in required)
        )
    return out


CONFIGS = {
    "fedavg_simple": dict(kind="net", strategy="fedavg", bb=False,
                          nloop=NLOOP_SIMPLE, nadmm=3, group_slice=None,
                          acc_band=0.05, **SIMPLE),
    # MATCHED-DYNAMICS resnet FedAvg (round-4 VERDICT item 3): at the
    # headline schedule the framework outruns the torch reference
    # (0.50 vs 0.30 final acc), so its residual trajectory legitimately
    # diverges and the half-order band is waived. This fifth config
    # constrains the inner solver identically on BOTH sides
    # (max_iter=2) so neither runs away: the sides converge to similar
    # accuracy and the gate REQUIRES the residual bands here — the
    # resnet-FedAvg dynamics are validated by measurement, not argument.
    # recorded verdict (PARITY_MATCHED_NTRAIN=256 default): final acc
    # 0.328 vs 0.329 (diff 0.0011), dual_log10_median 0.33 -> the
    # half-order band HOLDS and the gate requires it. Own n_train knob
    # so the headline configs' PARITY_RESNET_NTRAIN doesn't move this
    # measured configuration.
    "fedavg_resnet_matched": dict(kind="resnet18", strategy="fedavg",
                                  bb=False, nloop=NLOOP_RESNET, nadmm=3,
                                  group_slice=None, acc_band=0.05,
                                  lbfgs=dict(max_iter=2), batch=32,
                                  matched=True,  # gate reads this flag
                                  n_train=int(os.environ.get(
                                      "PARITY_MATCHED_NTRAIN", "256"))),
    "admm_simple": dict(kind="net", strategy="admm", bb=True,
                        nloop=NLOOP_SIMPLE, nadmm=5, group_slice=None,
                        acc_band=0.05, **SIMPLE),
    "fedavg_resnet": dict(kind="resnet18", strategy="fedavg", bb=False,
                          nloop=NLOOP_RESNET, nadmm=3, group_slice=None,
                          acc_band=0.05, **RESNET),
    "admm_resnet": dict(kind="resnet18", strategy="admm", bb=False,
                        nloop=NLOOP_RESNET, nadmm=3, group_slice=None,
                        acc_band=0.05, **RESNET),
}

PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "convergence_parity.json")


def main():
    name = sys.argv[1] if len(sys.argv) > 1 else None
    if name not in CONFIGS:
        sys.exit(f"usage: convergence_parity.py {{{'|'.join(CONFIGS)}}}")
    if not os.path.isdir(REFERENCE_SRC):
        sys.exit(f"reference checkout not found at {REFERENCE_SRC}")
    c = CONFIGS[name]
    src = synthetic(c["n_train"])

    t0 = time.time()
    fw = run_framework(c["kind"], src, c["batch"], c["nloop"], c["nadmm"],
                       c["strategy"], c["bb"], c["group_slice"],
                       lbfgs=c.get("lbfgs"))
    t_fw = time.time() - t0
    t0 = time.time()
    ref = run_reference(c["kind"], src, c["batch"], c["nloop"], c["nadmm"],
                        c["strategy"], c["bb"], c["group_slice"],
                        lbfgs=c.get("lbfgs"))
    t_ref = time.time() - t0

    result = {
        "config": {k: v for k, v in c.items()},
        "hardness": HARDNESS,
        "seconds": {"framework": round(t_fw, 1), "reference": round(t_ref, 1)},
        "curves": {
            "framework": {
                "acc_mean": _mean_curve(fw["acc"]),
                "dual": fw["dual"], "primal": fw["primal"],
                "mean_rho": fw["mean_rho"],
            },
            "reference": {
                "acc_mean": _mean_curve(ref["acc"]),
                "dual": ref["dual"], "primal": ref["primal"],
                "mean_rho": ref["mean_rho"],
            },
        },
        "verdict": compare(fw, ref, c["strategy"], c["acc_band"],
                           num_classes=c.get("num_classes", 10),
                           matched=c.get("matched", False)),
    }

    merged = {}
    if os.path.exists(PATH):
        try:
            merged = json.load(open(PATH))
        except Exception:
            merged = {}
    if "workload" not in merged or "rows" in merged:
        merged = {
            "workload": (
                f"{K}-client partial-param consensus on a DISCRIMINATING "
                f"synthetic set (class overlap {HARDNESS['overlap']}, label "
                f"noise {HARDNESS['label_noise']} -> ~0.78 accuracy "
                "ceiling); torch reference drives the imported LBFGSNew"
            ),
        }
    merged[name] = result
    with open(PATH, "w") as f:
        json.dump(merged, f, indent=1)
    print(json.dumps({name: result["verdict"]}))


if __name__ == "__main__":
    main()
