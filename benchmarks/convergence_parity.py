"""Convergence parity: reference algorithm (torch) vs this framework (JAX),
same data, same hyper-parameters, accuracy after every averaging round.

The reference repo publishes no curves (BASELINE.md), and this environment
has no CIFAR archive, so parity is established on the deterministic
synthetic dataset both sides can load: 3 simple-CNN clients, disjoint
shards, partial-parameter FedAvg (one layer group per round), stochastic
L-BFGS inner solver. The torch side imports the reference's own
`LBFGSNew` optimizer from /root/reference/src (imported, NOT copied) and
re-drives its algorithm exactly as SURVEY.md §3.1 documents it: freeze all
but one layer pair, fresh optimizer per group, average the active group
across clients after each round (reference src/federated_trio.py:256-363).

Writes benchmarks/convergence_parity.json:
  {"reference": {"acc": [...]}, "framework": {"acc": [...]}, ...}

Run: python benchmarks/convergence_parity.py   (~2-4 min, CPU)
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

K = 3
BATCH = 64
NLOOP = 2  # outer loops over the 5 layer groups
NADMM = 2  # averaging rounds per group
N_TRAIN = 960  # per all clients; 320/client => 5 lockstep batches
N_TEST = 300
SEED = 0


def synthetic():
    from federated_pytorch_test_tpu.data import synthetic_cifar

    # noise high enough that the task is NOT saturated in one round —
    # otherwise both sides hit ceiling and the curves say nothing
    return synthetic_cifar(
        n_train=N_TRAIN, n_test=N_TEST, seed=SEED, noise=150.0
    )


# --------------------------------------------------------------- torch side


REFERENCE_SRC = os.environ.get("REFERENCE_SRC", "/root/reference/src")
if not os.path.isdir(REFERENCE_SRC):  # fail fast, before any training runs
    sys.exit(
        f"reference checkout not found at {REFERENCE_SRC} "
        "(set REFERENCE_SRC to its src/ directory)"
    )


def run_reference(src) -> list:
    import torch
    import torch.nn as nn
    import torch.nn.functional as F

    sys.path.insert(0, REFERENCE_SRC)
    from lbfgsnew import LBFGSNew  # reference optimizer (imported, not copied)

    torch.manual_seed(SEED)

    class Net(nn.Module):
        # the reference's 5-layer simple CNN shape-for-shape
        # (reference src/simple_models.py:9-39), ELU, NCHW
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2d(3, 6, 5)
            self.conv2 = nn.Conv2d(6, 16, 5)
            self.fc1 = nn.Linear(400, 120)
            self.fc2 = nn.Linear(120, 84)
            self.fc3 = nn.Linear(84, 10)

        def forward(self, x):
            x = F.max_pool2d(F.elu(self.conv1(x)), 2)
            x = F.max_pool2d(F.elu(self.conv2(x)), 2)
            x = x.flatten(1)
            x = F.elu(self.fc1(x))
            x = F.elu(self.fc2(x))
            return self.fc3(x)

    mods = ["conv1", "conv2", "fc1", "fc2", "fc3"]
    train_order = [2, 0, 1, 3, 4]  # reference src/simple_models.py:38-39

    # identical common-seed init across clients (reference
    # src/federated_trio.py:229-236)
    nets = []
    for _ in range(K):
        torch.manual_seed(SEED)
        nets.append(Net())

    # disjoint contiguous shards; the reference's unbiased normalization
    # Normalize((.5,.5,.5),(.5,.5,.5)) after ToTensor, i.e.
    # (x/255 - 0.5)/0.5 (reference src/no_consensus_trio.py:34-38) —
    # identical to the framework side's UNBIASED stat, so both curves see
    # the SAME input scaling; NCHW for torch
    def norm(a):
        return (a.astype(np.float32) / 255.0 - 0.5) / 0.5

    imgs = norm(src.train_images)
    labs = src.train_labels.astype(np.int64)
    per = len(imgs) // K
    shards = [
        (
            torch.from_numpy(imgs[c * per : (c + 1) * per].transpose(0, 3, 1, 2)),
            torch.from_numpy(labs[c * per : (c + 1) * per]),
        )
        for c in range(K)
    ]
    te_x = torch.from_numpy(norm(src.test_images).transpose(0, 3, 1, 2))
    te_y = torch.from_numpy(src.test_labels.astype(np.int64))

    crit = nn.CrossEntropyLoss()
    rng = np.random.default_rng(SEED)

    def accuracy():
        accs = []
        with torch.no_grad():
            for net in nets:
                pred = net(te_x).argmax(1)
                accs.append(float((pred == te_y).float().mean()))
        return accs

    def unfreeze_only(net, gid):
        want = mods[gid]
        for name, mod in net.named_children():
            for p in mod.parameters():
                p.requires_grad = name == want
        return list(getattr(net, want).parameters())

    series = [accuracy()]
    for nloop in range(NLOOP):
        for gid in train_order:
            opts = [
                LBFGSNew(
                    unfreeze_only(net, gid),
                    history_size=10,
                    max_iter=4,
                    line_search_fn=True,
                    batch_mode=True,
                )
                for net in nets
            ]
            for nadmm in range(NADMM):
                # one epoch of lockstep minibatches per round
                order = [rng.permutation(per) for _ in range(K)]
                for s in range(per // BATCH):
                    for c in range(K):
                        x = shards[c][0][order[c][s * BATCH : (s + 1) * BATCH]]
                        y = shards[c][1][order[c][s * BATCH : (s + 1) * BATCH]]

                        def closure():
                            if torch.is_grad_enabled():
                                opts[c].zero_grad()
                            loss = crit(nets[c](x), y)
                            if loss.requires_grad:
                                loss.backward()
                            return loss

                        opts[c].step(closure)
                # FedAvg the ACTIVE group only (reference :353-363)
                with torch.no_grad():
                    mod_params = [
                        list(getattr(net, mods[gid]).parameters()) for net in nets
                    ]
                    for pi in range(len(mod_params[0])):
                        mean = sum(mp[pi] for mp in mod_params) / K
                        for mp in mod_params:
                            mp[pi].copy_(mean)
                series.append(accuracy())
    return series


# ----------------------------------------------------------- framework side


def run_framework(src) -> list:
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    cfg = get_preset(
        "fedavg",
        model="net",
        batch=BATCH,
        nloop=NLOOP,
        nadmm=NADMM,
        biased_input=False,
        reg_mode="none",
        check_results=True,
        seed=SEED,
        eval_batch=N_TEST,
    )
    tr = Trainer(cfg, verbose=False, source=src)
    series = [list(np.asarray(tr.evaluate(), float))]
    rec = tr.run()
    series += [r["value"] for r in rec.series["test_accuracy"]]
    return series


def main():
    src = synthetic()
    t0 = time.time()
    fw = run_framework(src)
    t_fw = time.time() - t0
    t0 = time.time()
    ref = run_reference(src)
    t_ref = time.time() - t0

    out = {
        "workload": (
            f"{K}-client simple-CNN partial-param FedAvg on deterministic "
            f"synthetic CIFAR ({N_TRAIN} train / {N_TEST} test), batch "
            f"{BATCH}, nloop={NLOOP}, nadmm={NADMM}, L-BFGS(10,4,ls,batch)"
        ),
        "reference": {"acc": ref, "seconds": round(t_ref, 1)},
        "framework": {"acc": fw, "seconds": round(t_fw, 1)},
        "final_mean_acc": {
            "reference": round(float(np.mean(ref[-1])), 4),
            "framework": round(float(np.mean(fw[-1])), 4),
        },
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "convergence_parity.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out["final_mean_acc"]))


if __name__ == "__main__":
    main()
