"""Framework-side learning-curve explorer for the resnet parity configs.

The torch reference pays ~36 s per lockstep minibatch on this host, so
the (n_train, nloop, hardness) point for the FULL 10-block resnet parity
runs must be chosen before spending hours on the torch side. This runs
ONLY the framework half of a convergence_parity config (fast on the
chip) and prints the per-round accuracy curve + an estimate of what the
matching torch run would cost.

Usage:
  python benchmarks/parity_explore.py fedavg_resnet
  PARITY_RESNET_NLOOP=4 python benchmarks/parity_explore.py admm_resnet
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import convergence_parity as cp


def main():
    name = sys.argv[1] if len(sys.argv) > 1 else "fedavg_resnet"
    c = cp.CONFIGS[name]
    src = cp.synthetic(c["n_train"])
    import time

    t0 = time.time()
    fw = cp.run_framework(c["kind"], src, c["batch"], c["nloop"], c["nadmm"],
                          c["strategy"], c["bb"], c["group_slice"])
    dt = time.time() - t0
    curve = cp._mean_curve(fw["acc"])
    n_groups = 10 if c["kind"] == "resnet18" else 5
    steps = (c["n_train"] // cp.K) // c["batch"]
    torch_minibatches = c["nloop"] * n_groups * c["nadmm"] * steps
    print(json.dumps({
        "config": name,
        "n_train": c["n_train"],
        "nloop": c["nloop"],
        "framework_seconds": round(dt, 1),
        "acc_first": curve[0],
        "acc_last": curve[-1],
        "acc_curve": [round(a, 3) for a in curve],
        "dual_first_last": [fw["dual"][0], fw["dual"][-1]]
        if fw["dual"] else None,
        "est_torch_hours": round(torch_minibatches * 36.3 / 3600, 2),
    }))


if __name__ == "__main__":
    main()
