"""Long-context attention benchmark on the real TPU chip.

Times one training-style evaluation (forward + backward of a sum-of-
squares loss over the attention output) for the dense reference
(`parallel.dense_attention`, materializes the [B, H, S, S] scores in
HBM) against the Pallas flash kernels (`ops.flash_attention`, nothing
whole-sequence-resident in VMEM, no scores in HBM), causal, across
sequence lengths — each at BOTH matmul precisions ('default' = single
bf16 MXU passes, 'highest' = full f32 passes), so kernel-vs-dense is
compared like for like. Writes `long_context_tpu.json` next to this
file.

The dense path's HBM footprint grows as S^2 (one f32 score tensor is
B*H*S^2 * 4 bytes * several live copies through softmax/backward); the
flash path's grows linearly, so past the dense OOM point the flash
column keeps going — that regime is the point of the kernels.

Timing caveat (this runtime): the TPU is reached through a remote
PJRT tunnel on which `block_until_ready` returns at dispatch-ack, not
completion, and repeated dispatch of an identical (executable, args)
pair can be served from a result cache. Every measurement therefore
uses DISTINCT pre-staged inputs per repetition and synchronizes by
fetching a scalar reduced from every repetition's output.

Run: python benchmarks/long_context_tpu.py   (requires a TPU backend)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from federated_pytorch_test_tpu.ops.flash_attention import flash_attention
from federated_pytorch_test_tpu.parallel import dense_attention

B, H, D = 2, 8, 64
LENGTHS = (1024, 2048, 4096, 8192, 16384)
DENSE_MAX = 8192  # [2, 8, 16384^2] f32 scores = 17 GiB/copy: past HBM


def timed(fn, qs, ks, vs, reps, inner):
    """Best-of-`reps` PER-STEP time over distinct resident inputs.

    Each call runs `inner` fwd+bwd steps INSIDE the jitted function (a
    fori_loop perturbing q per iteration): the remote-tunnel dispatch
    latency (~0.1 s/call, flat in S — it used to swamp every row of this
    table) is paid once per call and amortized away by the division.
    Input set 0 is burned on compile+warmup; sets 1..reps are each timed
    individually and the MINIMUM is reported (as bench.py does): on the
    shared chip a single contended rep would otherwise poison a mean."""
    float(fn(qs[0], ks[0], vs[0]))
    best = float("inf")
    for i in range(1, reps + 1):
        t0 = time.perf_counter()
        float(fn(qs[i], ks[i], vs[i]))  # forces the call; fetches 4 bytes
        best = min(best, time.perf_counter() - t0)
    return best / inner


def main():
    assert jax.default_backend() == "tpu", jax.default_backend()
    rng = np.random.RandomState(0)
    reps = 3
    # burn the tunnel's first-dispatch overhead on a throwaway call
    w = jnp.ones((1, 128, 1, 64), jnp.float32)
    float(flash_attention(w, w, w, causal=True).sum())
    rows = []
    for s in LENGTHS:
        # distinct inputs per repetition (defeats result caching), staged
        # on device and forced resident before any timing
        qs, ks, vs = (
            [jnp.asarray(rng.randn(B, s, H, D), jnp.float32)
             for _ in range(reps + 1)]
            for _ in range(3)
        )
        float(sum(x[0, 0, 0, 0] for x in qs + ks + vs))

        # inner fwd+bwd steps per jitted call: enough that real kernel
        # time dominates the flat ~0.1 s dispatch latency at every S
        inner = max(4, (8192 * 8192) // (s * s) * 4)

        def make(attn, prec):
            def step(q, k, v):
                def loss(q, k, v):
                    with jax.default_matmul_precision(prec):
                        out = attn(q, k, v, causal=True)
                    return jnp.sum(out ** 2)

                def body(i, acc):
                    # perturb q so no iteration repeats the last one's
                    # inputs; full-reduce every grad so none is dead code
                    qi = q * (1.0 + i.astype(jnp.float32) * 1e-6)
                    l, gs = jax.value_and_grad(loss, argnums=(0, 1, 2))(
                        qi, k, v
                    )
                    return acc + l + sum(jnp.sum(g) for g in gs)

                return jax.lax.fori_loop(0, inner, body, jnp.float32(0))

            return jax.jit(step)

        row = {"seq_len": s, "inner_steps": inner}
        for prec in ("default", "highest"):
            flash = lambda q, k, v, causal: flash_attention(
                q, k, v, causal=causal, precision=prec
            )
            t_flash = timed(make(flash, prec), qs, ks, vs, reps, inner)
            row[f"flash_{prec}_step_s"] = round(t_flash, 5)
            row[f"flash_{prec}_tokens_per_s"] = round(B * s / t_flash)
            if s <= DENSE_MAX:
                t_dense = timed(
                    make(dense_attention, prec), qs, ks, vs, reps, inner
                )
                row[f"dense_{prec}_step_s"] = round(t_dense, 5)
                row[f"speedup_{prec}"] = round(t_dense / t_flash, 2)
            else:
                row[f"dense_{prec}_step_s"] = None  # scores exceed HBM
                row[f"speedup_{prec}"] = None
        rows.append(row)
        print(json.dumps(row))

    out = {
        "workload": f"causal attention fwd+bwd, B={B} H={H} D={D}, f32 "
                    "inputs; 'default'=bf16 MXU passes, 'highest'=f32 passes",
        "device": str(jax.devices()[0]),
        "rows": rows,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "long_context_tpu.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
