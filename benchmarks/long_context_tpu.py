"""Long-context attention benchmark on the real TPU chip.

Times one training-style evaluation (forward + backward of a sum-of-
squares loss over the attention output) for the dense reference
(`parallel.dense_attention`, materializes the [B, H, S, S] scores in
HBM) against the Pallas flash kernels (`ops.flash_attention`, nothing
whole-sequence-resident in VMEM, no scores in HBM), causal, across
sequence lengths — each at BOTH matmul precisions ('default' = single
bf16 MXU passes, 'highest' = full f32 passes), so kernel-vs-dense is
compared like for like. Writes `long_context_tpu.json` next to this
file.

The dense path's HBM footprint grows as S^2 (one f32 score tensor is
B*H*S^2 * 4 bytes * several live copies through softmax/backward); the
flash path's grows linearly, so past the dense OOM point the flash
column keeps going — that regime is the point of the kernels.

Timing caveat (this runtime): the TPU is reached through a remote
PJRT tunnel on which `block_until_ready` returns at dispatch-ack, not
completion, and repeated dispatch of an identical (executable, args)
pair can be served from a result cache. Every measurement therefore
uses DISTINCT pre-staged inputs per repetition and synchronizes by
fetching a scalar reduced from every repetition's output.

Run: python benchmarks/long_context_tpu.py   (requires a TPU backend)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from federated_pytorch_test_tpu.ops.flash_attention import flash_attention
from federated_pytorch_test_tpu.parallel import dense_attention

B, H, D = 2, 8, 64
LENGTHS = (1024, 2048, 4096, 8192, 16384)
DENSE_MAX = 8192  # [2, 8, 16384^2] f32 scores = 17 GiB/copy: past HBM


def timed(fn, qs, ks, vs, reps):
    """Mean step time over `reps` calls on distinct resident inputs.

    Input set 0 is burned on compile+warmup; sets 1..reps are timed, so
    no timed call repeats an (executable, args) pair the runtime has
    already seen."""
    float(fn(qs[0], ks[0], vs[0])[0])
    t0 = time.perf_counter()
    losses = [fn(qs[i], ks[i], vs[i])[0] for i in range(1, reps + 1)]
    float(jnp.stack(losses).sum())  # forces every rep; fetches 4 bytes
    return (time.perf_counter() - t0) / reps


def main():
    assert jax.default_backend() == "tpu", jax.default_backend()
    rng = np.random.RandomState(0)
    reps = 2
    # burn the tunnel's first-dispatch overhead on a throwaway call
    w = jnp.ones((1, 128, 1, 64), jnp.float32)
    float(flash_attention(w, w, w, causal=True).sum())
    rows = []
    for s in LENGTHS:
        # distinct inputs per repetition (defeats result caching), staged
        # on device and forced resident before any timing
        qs, ks, vs = (
            [jnp.asarray(rng.randn(B, s, H, D), jnp.float32)
             for _ in range(reps + 1)]
            for _ in range(3)
        )
        float(sum(x[0, 0, 0, 0] for x in qs + ks + vs))

        def make(attn, prec):
            def step(q, k, v):
                def loss(q, k, v):
                    with jax.default_matmul_precision(prec):
                        out = attn(q, k, v, causal=True)
                    return jnp.sum(out ** 2)

                l, grads = jax.value_and_grad(loss, argnums=(0, 1, 2))(q, k, v)
                return l, grads

            return jax.jit(step)

        row = {"seq_len": s}
        for prec in ("default", "highest"):
            flash = lambda q, k, v, causal: flash_attention(
                q, k, v, causal=causal, precision=prec
            )
            t_flash = timed(make(flash, prec), qs, ks, vs, reps)
            row[f"flash_{prec}_step_s"] = round(t_flash, 4)
            row[f"flash_{prec}_tokens_per_s"] = round(B * s / t_flash)
            if s <= DENSE_MAX:
                t_dense = timed(make(dense_attention, prec), qs, ks, vs, reps)
                row[f"dense_{prec}_step_s"] = round(t_dense, 4)
                row[f"speedup_{prec}"] = round(t_dense / t_flash, 2)
            else:
                row[f"dense_{prec}_step_s"] = None  # scores exceed HBM
                row[f"speedup_{prec}"] = None
        rows.append(row)
        print(json.dumps(row))

    out = {
        "workload": f"causal attention fwd+bwd, B={B} H={H} D={D}, f32 "
                    "inputs; 'default'=bf16 MXU passes, 'highest'=f32 passes",
        "device": str(jax.devices()[0]),
        "rows": rows,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "long_context_tpu.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
