"""Long-context attention benchmark on the real TPU chip.

Times one training-style evaluation (forward + backward of a sum-of-
squares loss over the attention output) for the dense reference
(`parallel.dense_attention`, materializes the [B, H, S, S] scores in
HBM) against the Pallas flash kernels (`ops.flash_attention`, nothing
whole-sequence-resident in VMEM, no scores in HBM), causal, across
sequence lengths — each at BOTH matmul precisions ('default' = single
bf16 MXU passes, 'highest' = full f32 passes), so kernel-vs-dense is
compared like for like. Writes `long_context_tpu.json` next to this
file.

The dense path's HBM footprint grows as S^2 (one f32 score tensor is
B*H*S^2 * 4 bytes * several live copies through softmax/backward); the
flash path's grows linearly, so past the dense OOM point the flash
column keeps going — that regime is the point of the kernels.

Timing caveat (this runtime): the TPU is reached through a remote
PJRT tunnel on which `block_until_ready` returns at dispatch-ack, not
completion, and repeated dispatch of an identical (executable, args)
pair can be served from a result cache. Every measurement therefore
uses DISTINCT pre-staged inputs per repetition and synchronizes by
fetching a scalar reduced from every repetition's output.

Run: python benchmarks/long_context_tpu.py   (requires a TPU backend)
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import _peaks  # the chip peak table lives with the flagship bench
from federated_pytorch_test_tpu.ops.flash_attention import flash_attention
from federated_pytorch_test_tpu.parallel import dense_attention
from tpu_timing import make_fwd_bwd_step, timed

B, H, D = 2, 8, 64
LENGTHS = (1024, 2048, 4096, 8192, 16384)
DENSE_MAX = 8192  # [2, 8, 16384^2] f32 scores = 17 GiB/copy: past HBM


def attn_flops(s: int) -> float:
    """Analytical FLOPs of one causal fwd+bwd attention step.

    Forward: QK^T and PV are each 2*S^2*D MAC-FLOPs per (batch, head);
    backward re-does the score matmul and adds dQ, dK, dV, dP — 5 score-
    shaped matmuls against the forward's 2. Causality halves the score
    area. Total: B*H * 0.5 * (2+5) * 2*S^2*D = 7*B*H*S^2*D. This is the
    textbook count (flash and dense do the same math), so achieved
    TFLOP/s is comparable across implementations; XLA's cost model is
    not used here because it cannot see inside Pallas kernels.
    """
    return 7.0 * B * H * float(s) * s * D


def main():
    assert jax.default_backend() == "tpu", jax.default_backend()
    rng = np.random.RandomState(0)
    reps = 3
    # burn the tunnel's first-dispatch overhead on a throwaway call
    w = jnp.ones((1, 128, 1, 64), jnp.float32)
    float(flash_attention(w, w, w, causal=True).sum())
    peak_tflops, _ = _peaks(jax.devices()[0].device_kind)
    rows = []
    for s in LENGTHS:
        # distinct inputs per repetition (defeats result caching), staged
        # on device and forced resident before any timing
        qs, ks, vs = (
            [jnp.asarray(rng.randn(B, s, H, D), jnp.float32)
             for _ in range(reps + 1)]
            for _ in range(3)
        )
        float(sum(x[0, 0, 0, 0] for x in qs + ks + vs))

        # inner fwd+bwd steps per jitted call: enough that real kernel
        # time dominates the flat ~0.1 s dispatch latency at every S
        # (protocol + step builder shared with flash_f32_tiles.py via
        # tpu_timing.py)
        inner = max(16, (8192 * 8192) // (s * s) * 24)  # ~1 s of work/call (protocol v2)
        make = lambda attn, prec: make_fwd_bwd_step(attn, prec, inner)

        row = {"seq_len": s, "inner_steps": inner}
        fl = attn_flops(s)
        for prec in ("default", "highest"):
            flash = lambda q, k, v, causal: flash_attention(
                q, k, v, causal=causal, precision=prec
            )
            t_flash = timed(make(flash, prec), qs, ks, vs, reps, inner)
            row[f"flash_{prec}_step_s"] = round(t_flash, 5)
            row[f"flash_{prec}_tokens_per_s"] = round(B * s / t_flash)
            # %-of-roofline (round-2 VERDICT missing #4): both precisions
            # are held against the bf16 MXU peak — 'highest' does each
            # f32 matmul as multiple bf16 passes, so its pct_peak is
            # conservative by that multiplier
            row[f"flash_{prec}_achieved_tflops"] = round(fl / t_flash / 1e12, 2)
            if peak_tflops:
                row[f"flash_{prec}_pct_peak"] = round(
                    100.0 * fl / t_flash / 1e12 / peak_tflops, 1
                )
            if s <= DENSE_MAX:
                t_dense = timed(
                    make(dense_attention, prec), qs, ks, vs, reps, inner
                )
                row[f"dense_{prec}_step_s"] = round(t_dense, 5)
                row[f"dense_{prec}_achieved_tflops"] = round(
                    fl / t_dense / 1e12, 2
                )
                row[f"speedup_{prec}"] = round(t_dense / t_flash, 2)
            else:
                row[f"dense_{prec}_step_s"] = None  # scores exceed HBM
                row[f"speedup_{prec}"] = None
        rows.append(row)
        print(json.dumps(row))

    out = {
        "workload": f"causal attention fwd+bwd, B={B} H={H} D={D}, f32 "
                    "inputs; 'default'=bf16 MXU passes, 'highest'=f32 passes",
        "device": str(jax.devices()[0]),
        "peak_tflops_bf16": peak_tflops,
        "flop_model": "7*B*H*S^2*D per fwd+bwd step (causal; see attn_flops)",
        "rows": rows,
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "long_context_tpu.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
