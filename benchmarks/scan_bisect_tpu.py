"""Bisect the resident-epoch scan length that kills the tunneled TPU worker.

Round-2 observation (BASELINE.md): the fedavg_resnet preset's resident
epoch — ONE jitted call scanning 520 lockstep ResNet18 minibatches —
crashes this environment's tunneled TPU worker, while 8-step streamed
chunks run fine. This probe pins the boundary: it builds the exact
fedavg_resnet group-0 epoch program and runs it with ascending scan
lengths S (idx sliced to [S, K, B]), fetching the losses to the host
after each call (the only true completion barrier over the tunnel).

The last S that completes and the first S that crashes bound the safe
chunk size for the trainer's resident auto-chunking (`max_scan_steps`).

Usage: python benchmarks/scan_bisect_tpu.py [S ...]   (default sweep below)
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from federated_pytorch_test_tpu.engine import Trainer, get_preset


def main():
    steps = [int(s) for s in sys.argv[1:]] or [8, 65, 130, 260, 390, 520]
    smax = max(steps)
    # big enough shard for smax lockstep batches per client
    cfg = get_preset(
        "fedavg_resnet",
        synthetic_n_train=3 * smax * 32,
        synthetic_n_test=96,
        check_results=False,
        nloop=1,
        fault_mode="off",
        max_scan_steps=None,  # probe the raw un-chunked scan
    )
    tr = Trainer(cfg, verbose=False)
    gid = tr.group_order[0]
    epoch_fn, _, init_fn = tr._fns(gid)
    lstate, y, z, rho, _ = init_fn(tr.flat)
    idx_full = tr._epoch_indices(0, gid, 0, 0)
    print(f"probe ready: shard={tr.fed.shard_size} full_S={idx_full.shape[0]}",
          flush=True)

    # the epoch fn donates flat/lstate/stats; thread the outputs through
    flat, stats = tr.flat, tr.stats
    for s in steps:
        t0 = time.perf_counter()
        try:
            flat, lstate, stats, losses = epoch_fn(
                flat, lstate, stats, tr.shard_imgs, tr.shard_labels,
                idx_full[:s], tr.mean, tr.std, y, z, rho,
            )
            host = np.asarray(losses)  # completion barrier
            dt = time.perf_counter() - t0
            print(f"S={s:4d}  OK    {dt:7.1f}s  mean_loss={host.mean():.4f}",
                  flush=True)
        except Exception as e:
            dt = time.perf_counter() - t0
            print(f"S={s:4d}  CRASH {dt:7.1f}s  {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)
            break


if __name__ == "__main__":
    main()
