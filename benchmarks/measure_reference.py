"""Measure the reference implementation's training throughput on this host.

The reference publishes no numbers (BASELINE.md), so the baseline is
measured: the reference's own `LBFGSNew` optimizer (imported from
/root/reference/src at runtime — nothing is copied) driving 3 sequential
torch CNN clients exactly as its drivers do (one `opt.step(closure)` per
client per lockstep minibatch, reference
src/federated_trio_resnet.py:320-338), on the same workload bench.py runs
(ResNet18-class model, batch 32, CIFAR-shaped synthetic data, CPU — the
reference has no device-placement code, SURVEY.md §0).

Writes benchmarks/reference_throughput.json, consumed by bench.py's
`vs_baseline`.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np
import torch
import torch.nn as nn
import torch.nn.functional as F

sys.path.insert(0, "/root/reference/src")
from lbfgsnew import LBFGSNew  # noqa: E402  (reference optimizer, not copied)


class _Block(nn.Module):
    """Standard CIFAR BasicBlock (3x3 conv x2 + BN, ELU, 1x1 shortcut)."""

    def __init__(self, in_planes, planes, stride=1):
        super().__init__()
        self.conv1 = nn.Conv2d(in_planes, planes, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.short = nn.Sequential()
        if stride != 1 or in_planes != planes:
            self.short = nn.Sequential(
                nn.Conv2d(in_planes, planes, 1, stride, bias=False),
                nn.BatchNorm2d(planes),
            )

    def forward(self, x):
        out = F.elu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return F.elu(out + self.short(x))


class _ResNet18(nn.Module):
    def __init__(self, num_classes=10):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 3, 1, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        layers = []
        in_planes = 64
        for planes, stride in [
            (64, 1), (64, 1), (128, 2), (128, 1),
            (256, 2), (256, 1), (512, 2), (512, 1),
        ]:
            layers.append(_Block(in_planes, planes, stride))
            in_planes = planes
        self.blocks = nn.Sequential(*layers)
        self.linear = nn.Linear(512, num_classes)

    def forward(self, x):
        out = F.elu(self.bn1(self.conv1(x)))
        out = self.blocks(out)
        out = F.avg_pool2d(out, 4).flatten(1)
        return self.linear(out)


class _Net(nn.Module):
    """The reference's 5-layer simple CNN shape-for-shape
    (reference src/simple_models.py:9-39), ELU, NCHW."""

    def __init__(self):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 6, 5)
        self.conv2 = nn.Conv2d(6, 16, 5)
        self.fc1 = nn.Linear(400, 120)
        self.fc2 = nn.Linear(120, 84)
        self.fc3 = nn.Linear(84, 10)

    def forward(self, x):
        x = F.max_pool2d(F.elu(self.conv1(x)), 2)
        x = F.max_pool2d(F.elu(self.conv2(x)), 2)
        x = x.flatten(1)
        x = F.elu(self.fc1(x))
        x = F.elu(self.fc2(x))
        return self.fc3(x)


def main() -> None:
    torch.manual_seed(0)
    # WORKLOAD=simple: the federated_trio.py config (Net, batch 512,
    # reference src/federated_trio.py:18); default: the resnet flagship
    simple = os.environ.get("WORKLOAD") == "simple"
    k = 3
    batch = 512 if simple else 32
    steps = int(os.environ.get("BENCH_STEPS", "3" if simple else "10"))

    nets = [(_Net if simple else _ResNet18)() for _ in range(k)]
    opts = [
        LBFGSNew(
            n.parameters(),
            history_size=10,
            max_iter=4,
            line_search_fn=True,
            batch_mode=True,
        )
        for n in nets
    ]
    crit = nn.CrossEntropyLoss()
    rng = np.random.default_rng(0)
    data = torch.from_numpy(
        rng.normal(0, 1, (steps, k, batch, 3, 32, 32)).astype(np.float32)
    )
    labels = torch.from_numpy(
        rng.integers(0, 10, (steps, k, batch)).astype(np.int64)
    )

    def one_step(s):
        for c in range(k):
            x, y = data[s, c], labels[s, c]

            def closure():
                if torch.is_grad_enabled():
                    opts[c].zero_grad()
                loss = crit(nets[c](x), y)
                if loss.requires_grad:
                    loss.backward()
                return loss

            opts[c].step(closure)

    one_step(0)  # warmup
    t0 = time.perf_counter()
    for s in range(steps):
        one_step(s)
    dt = time.perf_counter() - t0

    sps = steps * k * batch / dt
    row = {
        "samples_per_sec": round(sps, 2),
        "sec_per_lockstep_minibatch": round(dt / steps, 3),
        "workload": (
            "3-client simple-CNN (Net), batch 512"
            if simple
            else "3-client ResNet18-class CIFAR shapes, batch 32"
        )
        + ", LBFGSNew(history=10, max_iter=4, line_search, batch_mode), "
        "torch CPU",
        "host": os.uname().nodename,
    }
    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "reference_throughput.json"
    )
    # the flagship (resnet) row keeps the top-level keys bench.py reads;
    # the simple-CNN row lives under its own key
    merged = {}
    if os.path.exists(path):
        try:
            with open(path) as f:
                merged = json.load(f)
        except Exception:
            merged = {}
    if simple:
        merged["simple_cnn_batch512"] = row
    else:
        merged.update(row)
    with open(path, "w") as f:
        json.dump(merged, f, indent=1)
    print(json.dumps(row))


if __name__ == "__main__":
    main()
