"""Streamed vs resident data-path epoch on the real chip.

The streaming path (config `hbm_data_budget_mb`; trainer
`_run_stream_epoch`) exists for datasets that do not fit HBM: per-client
native PrefetchBatchers assemble lockstep minibatch chunks host-side and
each chunk's `device_put` is issued while the previous chunk's jitted
scan still runs. This benchmark quantifies the overlap on the flagship
workload: it times (a) the resident path, (b) the streamed path, and
(c) the streamed path's H2D + host-assembly cost alone — if
(b) < (a) + (c), transfer and compute demonstrably overlapped.

Writes stream_overlap_tpu.json. Run: python benchmarks/stream_overlap_tpu.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K, BATCH, STEPS = 3, 32, 24
CHUNK = 6


def main():
    import jax
    import jax.numpy as jnp

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    assert jax.default_backend() == "tpu", jax.default_backend()
    src = synthetic_cifar(n_train=K * BATCH * STEPS, n_test=64)

    def build(stream: bool):
        cfg = get_preset(
            "fedavg_resnet", n_clients=K, batch=BATCH, check_results=False,
            hbm_data_budget_mb=0 if stream else None,
            stream_chunk_steps=CHUNK,
        )
        return Trainer(cfg, verbose=False, source=src)

    def timed_epochs(tr, reps=3):
        gid = tr.group_order[0]
        epoch_fn, _, init_fn = tr._fns(gid)
        lstate, y, z, rho, extra = init_fn(tr.flat)
        times = []
        for _ in range(reps + 1):  # first rep is compile/warmup
            t0 = time.perf_counter()
            if tr._stream:
                lstate, _, _ = tr._run_stream_epoch(epoch_fn, lstate, y, z, rho)
                # _run_stream_epoch fetches losses: already synchronized
            else:
                idx = tr._epoch_indices(0, gid, 0, 0)[:STEPS]
                tr.flat, lstate, tr.stats, losses = epoch_fn(
                    tr.flat, lstate, tr.stats, tr.shard_imgs,
                    tr.shard_labels, idx, tr.mean, tr.std, y, z, rho,
                )
                float(jnp.sum(tr.flat[:, 0]))  # completion barrier
            times.append(time.perf_counter() - t0)
        return min(times[1:])

    t_resident = timed_epochs(build(False))
    tr_s = build(True)
    t_streamed = timed_epochs(tr_s)

    # SERIALIZED streaming: same chunks, but each chunk is assembled and
    # staged only AFTER the previous chunk's result is synchronized —
    # what the epoch costs with zero transfer/compute overlap. (A pure
    # "transfer alone" leg is unmeasurable on this tunneled runtime:
    # any forcing fetch pays a ~1 s round trip that swamps the H2D.)
    from jax.sharding import NamedSharding, PartitionSpec
    from federated_pytorch_test_tpu.parallel import CLIENT_AXIS
    import numpy as np

    sh = NamedSharding(tr_s.mesh, PartitionSpec(None, CLIENT_AXIS))
    gid = tr_s.group_order[0]
    epoch_fn, _, init_fn = tr_s._fns(gid)

    def serial_epoch():
        # fresh optimizer state per call: epoch_fn DONATES (flat, lstate,
        # stats), so a state object from a previous call is a dead buffer
        ls, y, z, rho, _ = init_fn(tr_s.flat)
        flat, stats = tr_s.flat, tr_s.stats
        t0 = time.perf_counter()
        for _ in range(STEPS // CHUNK):
            imgs = np.empty((CHUNK, K, BATCH, 32, 32, 3), np.uint8)
            labs = np.empty((CHUNK, K, BATCH), np.int32)
            for s in range(CHUNK):
                for c in range(K):
                    im, lb = next(tr_s._batchers[c])
                    imgs[s, c], labs[s, c] = im, lb
            di = jax.device_put(imgs, sh)
            dl = jax.device_put(labs, sh)
            flat, ls, stats, l = epoch_fn(
                flat, ls, stats, di, dl, tr_s.mean, tr_s.std, y, z, rho
            )
            float(jnp.sum(l))  # synchronize: no overlap with next chunk
        tr_s.flat, tr_s.stats = flat, stats
        return time.perf_counter() - t0

    serial_epoch()  # warm
    t_serial = min(serial_epoch() for _ in range(2))

    out = {
        "workload": f"ResNet18 FedAvg epoch, {STEPS} minibatches x {K} "
                    f"clients x batch {BATCH}, chunk {CHUNK}",
        "device": str(jax.devices()[0]),
        "resident_epoch_s": round(t_resident, 4),
        "streamed_epoch_s": round(t_streamed, 4),
        "streamed_serialized_s": round(t_serial, 4),
        "stream_overhead_vs_resident_s": round(t_streamed - t_resident, 4),
        "overlap_gain_s": round(t_serial - t_streamed, 4),
        "overlap_demonstrated": bool(t_streamed < t_serial),
        "note": "double-buffered streaming beats the serialized variant "
                "by overlap_gain_s: assembly+H2D rode under the compute",
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "stream_overlap_tpu.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
