"""Tile sweep for the full-f32 flash kernels at short sequence lengths.

Round-2 result: flash in 'highest' (full f32 matmul passes) LOSES to
dense XLA attention at S=1024 (0.79x) while winning at S>=2048. This
probe times the f32 fwd+bwd step across (block_q, block_k) tile pairs at
S=1024/2048 against dense, to either find a winning tile shape for the
short-S f32 regime or measure that none exists (in which case dense IS
the right implementation there and the dispatch docs say so).

Run: python benchmarks/flash_f32_tiles.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from federated_pytorch_test_tpu.ops.flash_attention import flash_attention
from federated_pytorch_test_tpu.parallel import dense_attention
from tpu_timing import make_fwd_bwd_step, timed

B, H, D = 2, 8, 64
TILES = [(512, 512), (256, 512), (512, 256), (256, 256), (128, 256),
         (256, 128), (128, 128), (1024, 512), (512, 1024)]


def main():
    assert jax.default_backend() == "tpu", jax.default_backend()
    rng = np.random.RandomState(0)
    reps = 3
    out = {"rows": []}
    for s in (1024, 2048):
        inner = max(16, (8192 * 8192) // (s * s) * 24)  # ~1 s of work/call (protocol v2)
        qs, ks, vs = (
            [jnp.asarray(rng.randn(B, s, H, D), jnp.float32)
             for _ in range(reps + 1)]
            for _ in range(3)
        )
        float(sum(x[0, 0, 0, 0] for x in qs + ks + vs))
        t_dense = timed(
            make_fwd_bwd_step(dense_attention, "highest", inner),
            qs, ks, vs, reps, inner,
        )
        row = {"seq_len": s, "dense_step_s": round(t_dense, 5), "tiles": {}}
        for bq, bk in TILES:
            if bq > s or bk > s:
                continue
            attn = lambda q, k, v, causal: flash_attention(
                q, k, v, causal=causal, precision="highest",
                block_q=bq, block_k=bk,
            )
            try:
                t = timed(
                    make_fwd_bwd_step(attn, "highest", inner),
                    qs, ks, vs, reps, inner,
                )
                row["tiles"][f"{bq}x{bk}"] = {
                    "step_s": round(t, 5),
                    "speedup_vs_dense": round(t_dense / t, 3),
                }
            except Exception as e:
                row["tiles"][f"{bq}x{bk}"] = {"error": str(e)[:120]}
            print(json.dumps({"s": s, "tile": f"{bq}x{bk}",
                              **row["tiles"][f"{bq}x{bk}"]}), flush=True)
        out["rows"].append(row)
    best1k = max(
        (t.get("speedup_vs_dense", 0.0) for t in out["rows"][0]["tiles"].values()),
        default=0.0,
    )
    if best1k < 1.0:
        out["conclusion"] = (
            f"no tile shape beats dense at S=1024 full-f32 (best {best1k}x "
            "of 9 swept): at short S the f32 multi-pass matmuls cannot "
            "amortize the per-tile overhead against XLA's fused dense "
            "path, so short-S f32 attention BELONGS to dense — encoded as "
            "the attn_impl='auto' dispatch crossover "
            "(models/transformer.py: flash from S>=2048)"
        )
    else:
        out["conclusion"] = (
            f"a swept tile shape now BEATS dense at S=1024 full-f32 "
            f"(best {best1k}x): revisit the attn_impl='auto' crossover in "
            "models/transformer.py, which currently assumes dense wins "
            "below S=2048"
        )
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "flash_f32_tiles.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)


if __name__ == "__main__":
    main()
