"""bf16 tile sweep for the flash kernels at long sequence lengths.

Round-3 VERDICT item 7: flash sustains ~8% of bf16 peak at S=2k — tune
bf16 tile shapes at S=4k/8k and report the kernel-only roofline per
shape, or document the measured ceiling.

This probe times the causal fwd+bwd step of `ops.flash_attention` at
S=4096 and S=8192 across square VMEM tile sizes (causal pairs equal
tiles, so rectangular shapes collapse to the min — only squares are
distinct), in BOTH input regimes:

  f32-in   f32 q/k/v, 'default' precision (single bf16 MXU passes —
           what the engine's compute_dtype=float32 path gets)
  bf16-in  bf16 q/k/v end-to-end (half the HBM traffic on every tile
           load; softmax statistics and accumulators stay f32 inside
           the kernel) — the long-context training configuration.

Per row: achieved TFLOP/s against the analytical 7*B*H*S^2*D fwd+bwd
count (same math both regimes, so rows are comparable) and % of the
chip's bf16 peak — the kernel-only roofline. Timing uses the shared
tunnel-safe harness (tpu_timing.py: inner-loop amortization, distinct
inputs, scalar-fetch barrier, best-of-N). Writes flash_bf16_tiles.json
with the per-shape winner and updates nothing automatically — if a
non-default tile wins decisively, change `_BQ`/`_BK` in
ops/flash_attention.py and record it here.

Run: python benchmarks/flash_bf16_tiles.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from bench import _peaks
from federated_pytorch_test_tpu.ops.flash_attention import flash_attention
from tpu_timing import dispatch_floor, make_fwd_bwd_step, timed

B, H, D = 2, 8, 64
LENGTHS = (4096, 8192)
SQUARE_TILES = (128, 256, 512, 1024)

# protocol v2 (round 5): inner-step counts sized so one jitted call runs
# ~1 s of kernel work and the measured ~0.1 s tunnel dispatch floor is
# subtracted. Rounds 3-4 ran inner=16 WITHOUT floor subtraction, so a
# ~5 ms kernel measured as ~11 ms — those rows understate the kernel by
# up to ~2x and are not comparable with v2 rows.
PROTOCOL = "v2: floor-subtracted, ~1s of work per call (round 5)"


def attn_flops(s: int) -> float:
    return 7.0 * B * H * float(s) * s * D  # causal fwd+bwd (long_context_tpu)


def main():
    assert jax.default_backend() == "tpu", jax.default_backend()
    rng = np.random.RandomState(0)
    reps = 3
    peak_tflops, _ = _peaks(jax.devices()[0].device_kind)
    w = jnp.ones((1, 128, 1, 64), jnp.float32)
    float(flash_attention(w, w, w, causal=True).sum())

    floor = dispatch_floor()
    out = {
        "workload": f"causal flash fwd+bwd, B={B} H={H} D={D}; "
        "kernel-only roofline vs bf16 peak",
        "device": str(jax.devices()[0].device_kind),
        "peak_tflops_bf16": peak_tflops,
        "protocol": PROTOCOL,
        "dispatch_floor_s": round(floor, 4),
        "rows": [],
    }
    for s in LENGTHS:
        # ~1 s of kernel work per call, assuming ~40 TF/s (measured
        # round-5 kernel class) — overshooting just lengthens the run
        flops = attn_flops(s)
        inner = max(16, int(40e12 * 1.0 / flops))
        row = {"seq_len": s, "inner_steps": inner, "regimes": {}}
        for regime, dtype in (("f32_in", jnp.float32), ("bf16_in", jnp.bfloat16)):
            qs, ks, vs = (
                [jnp.asarray(rng.randn(B, s, H, D), dtype)
                 for _ in range(reps + 1)]
                for _ in range(3)
            )
            float(sum(x[0, 0, 0, 0].astype(jnp.float32) for x in qs + ks + vs))
            tiles = {}
            best_tile, best_t = None, float("inf")
            for bt in SQUARE_TILES:
                if bt > s:
                    continue

                def attn(q, k, v, causal=True, _bt=bt):
                    return flash_attention(
                        q, k, v, causal=causal, precision="default",
                        block_q=_bt, block_k=_bt,
                    )

                try:
                    t = timed(
                        make_fwd_bwd_step(attn, "default", inner),
                        qs, ks, vs, reps, inner, floor_s=floor,
                    )
                except Exception as e:  # a tile too big for VMEM etc.
                    tiles[str(bt)] = {"error": f"{type(e).__name__}: {e}"[:120]}
                    continue
                tf = flops / t / 1e12
                tiles[str(bt)] = {
                    "step_s": round(t, 5),
                    "achieved_tflops": round(tf, 2),
                    "pct_peak": round(100.0 * tf / peak_tflops, 1),
                }
                if t < best_t:
                    best_tile, best_t = bt, t
            row["regimes"][regime] = {
                "tiles": tiles,
                "best_tile": best_tile,
                "best_achieved_tflops": round(flops / best_t / 1e12, 2),
                "best_pct_peak": round(100.0 * flops / best_t / 1e12 / peak_tflops, 1),
            }
            print(json.dumps({"seq_len": s, "regime": regime,
                              "best": row["regimes"][regime]["best_tile"],
                              "pct_peak": row["regimes"][regime]["best_pct_peak"]}),
                  flush=True)
        out["rows"].append(row)

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "flash_bf16_tiles.json"
    )
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
