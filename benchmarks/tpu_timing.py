"""Shared tunnel-safe timing harness for the attention benchmarks.

This runtime's TPU sits behind a remote PJRT tunnel with three
measurement traps (see BASELINE.md): `block_until_ready` returns at
dispatch-ack rather than completion (only a device->host scalar fetch is
a true barrier), per-call dispatch latency is ~0.1 s flat in problem
size (so real kernel time must be amortized by looping `inner` steps
inside one jitted call), and the chip is shared (so best-of-N minima,
never means). Both long_context_tpu.py and flash_f32_tiles.py measure
through these two helpers so the protocol lives in exactly one place.
"""

import time

import jax
import jax.numpy as jnp


def make_fwd_bwd_step(attn, prec, inner):
    """Jitted `inner`-step fwd+bwd loop over `attn(q, k, v, causal=True)`.

    `prec` is applied as the default matmul precision around the
    attention call (covers the dense path; the flash kernels take their
    precision as a kwarg, already bound into `attn` by the caller). Each
    iteration perturbs q so no dispatch repeats the previous one's
    inputs, and every gradient is fully reduced into the scalar result
    so none is dead code.
    """

    def step(q, k, v):
        def loss(q, k, v):
            with jax.default_matmul_precision(prec):
                out = attn(q, k, v, causal=True)
            return jnp.sum(out**2)

        def body(i, acc):
            qi = q * (1.0 + i.astype(jnp.float32) * 1e-6)
            l, gs = jax.value_and_grad(loss, argnums=(0, 1, 2))(qi, k, v)
            return acc + l + sum(jnp.sum(g) for g in gs)

        return jax.lax.fori_loop(0, inner, body, jnp.float32(0))

    return jax.jit(step)


def dispatch_floor() -> float:
    """Min wall time of a trivial jitted call + scalar fetch.

    The tunnel's flat per-call latency is 0.07-0.11 s (measured round 5,
    varies run to run). Any per-call timing INCLUDES one floor's worth of
    latency; at inner=16 over a ~5 ms kernel the floor used to be ~50%
    of the measurement — every round-3/4 flash number understated the
    kernel for exactly this reason. Callers size `inner` so the floor is
    <10% of a call and subtract this estimate from the wall time.
    """
    f = jax.jit(lambda x: jnp.sum(x * x))
    x = jnp.ones((128, 128), jnp.float32)
    float(f(x))
    best = float("inf")
    for _ in range(6):
        t0 = time.perf_counter()
        float(f(x))
        best = min(best, time.perf_counter() - t0)
    return best


def timed(step, qs, ks, vs, reps, inner, floor_s: float | None = None):
    """Best-of-`reps` PER-STEP time over distinct resident inputs.

    Input set 0 is burned on compile+warmup; sets 1..reps are each timed
    individually (scalar fetch = completion barrier) and the MINIMUM is
    reported: on the shared chip a single contended rep would otherwise
    poison a mean. The dispatch floor (see `dispatch_floor`) is
    subtracted from each call's wall time before the per-step division —
    measured here by default so EVERY caller of this harness is on the
    v2 protocol; pass `floor_s` to reuse one measurement across many
    `timed` calls. Callers must still size `inner` so the floor is a
    small fraction of a call (the subtraction corrects the mean, not
    the noise).
    """
    if floor_s is None:
        floor_s = dispatch_floor()
    float(step(qs[0], ks[0], vs[0]))
    best = float("inf")
    for i in range(1, reps + 1):
        t0 = time.perf_counter()
        float(step(qs[i], ks[i], vs[i]))  # forces the call; fetches 4 bytes
        best = min(best, time.perf_counter() - t0)
    return max(best - floor_s, 0.0) / inner
