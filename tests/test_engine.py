"""Integration tests for the training engine (SURVEY.md §4c/§4d).

Run on the virtual 8-device CPU mesh (conftest), with tiny synthetic data
so each jitted epoch compiles in seconds. These are the distributed-sim
analogue of the reference's in-process three-client simulation.
"""

import numpy as np
import pytest

from federated_pytorch_test_tpu.data import synthetic_cifar
from federated_pytorch_test_tpu.engine import (
    PRESETS,
    ExperimentConfig,
    Trainer,
    get_preset,
)

pytestmark = pytest.mark.slow  # heavy tier (jit-compile dominated)

SRC = synthetic_cifar(n_train=240, n_test=60)


def tiny(preset: str, **over) -> ExperimentConfig:
    base = dict(batch=40, nloop=1, check_results=False, synthetic_ok=True)
    base.update(over)
    return get_preset(preset, **base)


def test_presets_cover_reference_drivers():
    # the five reference driver scripts -> five presets (SURVEY.md §2 C12),
    # plus the two BASELINE.json config-#5 scale-out presets
    assert set(PRESETS) == {
        "no_consensus",
        "fedavg",
        "fedavg_resnet",
        "admm",
        "admm_resnet",
        "fedavg_scale64",
        "admm_scale64",
    }
    assert PRESETS["admm"].nadmm == 5 and PRESETS["admm"].bb_update
    assert PRESETS["fedavg"].batch == 512
    assert PRESETS["admm_resnet"].bb_update is False
    assert PRESETS["no_consensus"].strategy == "none"
    for name in ("fedavg_scale64", "admm_scale64"):
        assert PRESETS[name].n_clients == 64
        assert PRESETS[name].dataset == "cifar100"
        assert PRESETS[name].model == "resnet18"
    # the resnet drivers use ONE unbiased transform for all clients
    # (reference src/federated_trio_resnet.py:27-29); the simple drivers
    # bias per client (reference src/federated_trio.py:34)
    for name, cfg in PRESETS.items():
        assert cfg.biased_input == (cfg.model != "resnet18"), name


def test_fedavg_round_trains_and_syncs():
    cfg = tiny("fedavg", model="net", nadmm=2)
    tr = Trainer(cfg, verbose=False, source=SRC)
    tr.group_order = tr.group_order[:2]
    rec = tr.run()

    losses = rec.series["train_loss"]
    first = np.mean(losses[0]["value"])
    last = np.mean(losses[-1]["value"])
    assert np.isfinite(last) and last < first

    # after a FedAvg round the active group's coords are identical across
    # clients (z broadcast back, reference src/federated_trio.py:361-363)
    flat = np.asarray(tr.flat)
    last_gid = tr.group_order[-1]
    for seg in tr.partition.groups[last_gid]:
        blk = flat[:, seg.start : seg.start + seg.size]
        assert np.abs(blk - blk[:1]).max() == 0.0

    # dual residuals were recorded for every round
    assert len(rec.series["dual_residual"]) == 2 * 2


def test_admm_residuals_and_client_divergence():
    cfg = tiny("admm", model="net", nadmm=3, bb_update=True)
    tr = Trainer(cfg, verbose=False, source=SRC)
    tr.group_order = tr.group_order[:1]
    rec = tr.run()

    assert len(rec.series["primal_residual"]) == 3
    assert len(rec.series["mean_rho"]) == 3
    p = [r["value"] for r in rec.series["primal_residual"]]
    assert all(np.isfinite(p))
    # ADMM clients keep their own x (no z write-back, reference
    # src/consensus_admm_trio.py keeps per-client x between rounds)
    flat = np.asarray(tr.flat)
    gid = tr.group_order[0]
    seg = tr.partition.groups[gid][0]
    blk = flat[:, seg.start : seg.start + seg.size]
    assert not np.allclose(blk[0], blk[1])


def test_no_consensus_full_model_training():
    # net1 is the reference driver's model (src/no_consensus_trio.py:11);
    # one epoch (2 full-batch L-BFGS steps) already shows the loss drop
    cfg = tiny("no_consensus", nepoch=1, model="net1")
    tr = Trainer(cfg, verbose=False, source=SRC)
    assert tr.partition.num_groups == 1
    assert tr.partition.group_size(0) == tr.n_params
    rec = tr.run()
    losses = rec.series["train_loss"]
    assert np.mean(losses[-1]["value"]) < np.mean(losses[0]["value"])
    # independent clients: different data + biased norms => diverged params
    flat = np.asarray(tr.flat)
    assert not np.allclose(flat[0], flat[1])


def test_eval_returns_per_client_accuracy():
    cfg = tiny("fedavg", model="net", nadmm=1, check_results=True, eval_batch=30)
    tr = Trainer(cfg, verbose=False, source=SRC)
    tr.group_order = tr.group_order[:1]
    rec = tr.run()
    accs = rec.latest("test_accuracy")
    assert len(accs) == 3
    assert all(0.0 <= a <= 1.0 for a in accs)


@pytest.mark.parametrize("preset", ["fedavg", "admm"])
def test_checkpoint_roundtrip(tmp_path, preset):
    cfg = tiny(
        preset,
        model="net",
        nadmm=1,
        save_model=True,
        checkpoint_dir=str(tmp_path),
    )
    tr = Trainer(cfg, verbose=False, source=SRC)
    tr.group_order = tr.group_order[:1]
    tr.run()

    cfg2 = cfg.replace(load_model=True)
    tr2 = Trainer(cfg2, verbose=False, source=SRC)
    np.testing.assert_allclose(
        np.asarray(tr2.flat), np.asarray(tr.flat), rtol=1e-6
    )
    assert tr2._completed_nloops == 1
    # the persistent ADMM rho store survives the round trip (str/int key
    # conversion, device_put) so BB-adapted resume replays exactly
    assert sorted(tr2._rho_store) == sorted(tr._rho_store)
    for g in tr._rho_store:
        np.testing.assert_allclose(
            np.asarray(tr2._rho_store[g]), np.asarray(tr._rho_store[g])
        )
    if preset == "admm":
        assert tr._rho_store  # non-empty: the write-back path was covered


def test_resnet_smoke_with_batch_stats():
    # BatchNorm path: stats thread through the epoch scan and stay
    # client-local (never averaged) — SURVEY.md §7 hard part 5.
    cfg = tiny("fedavg_resnet", batch=30, nadmm=1, eval_batch=30)
    tr = Trainer(cfg, verbose=False, source=SRC)
    assert tr.has_stats
    tr.group_order = [9]  # linear head only: cheapest resnet group
    rec = tr.run()
    assert np.isfinite(np.mean(rec.series["train_loss"][-1]["value"]))
    stats = np.concatenate(
        [np.ravel(x) for x in __import__("jax").tree.leaves(tr.stats)]
    )
    assert np.isfinite(stats).all()


def _group_norm(tr, gid):
    flat = np.asarray(tr.flat)
    segs = tr.model_partition.groups[gid]
    v = np.concatenate(
        [flat[:, s.start : s.start + s.size] for s in segs], axis=1
    )
    return float(np.linalg.norm(v))


@pytest.mark.parametrize("mode,preset", [
    ("first_linear", "no_consensus"),  # the fc1 or-quirk
    ("active_linear", "fedavg"),       # reference src/federated_trio.py:309
])
def test_regularization_modes_bite(mode, preset):
    # a large elastic net must shrink the regularized group relative to an
    # unregularized run — proving the penalty reaches the right segments
    norms = {}
    for lam in (0.0, 0.5):
        cfg = tiny(
            preset, model="net", nadmm=1, reg_mode=mode,
            lambda1=lam, lambda2=lam,
        )
        tr = Trainer(cfg, verbose=False, source=SRC)
        gid = tr.model_partition.linear_group_ids[0]  # fc1
        if preset != "no_consensus":  # 'none' trains the whole vector
            tr.group_order = [gid]
        tr.run()
        norms[lam] = _group_norm(tr, gid)
    assert norms[0.5] < 0.9 * norms[0.0], norms


def test_admm_rho_persists_across_rounds():
    # the reference allocates rho once OUTSIDE its loops, so BB-adapted
    # values for a layer carry to that layer's next visit
    # (reference src/consensus_admm_trio.py:263); y/z are re-zeroed
    import jax.numpy as jnp

    cfg = tiny("admm", model="net", nadmm=1, bb_update=True)
    tr = Trainer(cfg, verbose=False, source=SRC)
    gid = tr.group_order[0]

    # a round on an EMPTY store must write the group's rho back
    assert not tr._rho_store
    tr.run_round(nloop=0, gid=gid)
    assert gid in tr._rho_store

    # a seeded store must be USED by the next visit of that group
    _, _, _, rho0, _ = tr._fns(gid)[2](tr.flat)
    custom = jnp.full_like(rho0, 0.0567)
    tr._rho_store[gid] = custom
    tr.run_round(nloop=1, gid=gid)
    assert np.isclose(tr.recorder.latest("mean_rho"), 0.0567, rtol=1e-5)
    assert np.asarray(tr._rho_store[gid]).shape == np.asarray(rho0).shape


def test_average_model_one_shot_mean():
    # reference src/no_consensus_trio.py:22,134-160: independently-drawn
    # clients optionally replaced by their whole-model mean at startup
    cfg = tiny("no_consensus", model="net", init_model=False, average_model=True)
    tr = Trainer(cfg, verbose=False, source=SRC)
    flat = np.asarray(tr.flat)
    assert np.abs(flat - flat[:1]).max() == 0.0  # all clients identical

    # without the flag, independent draws differ
    cfg = tiny("no_consensus", model="net", init_model=False)
    tr = Trainer(cfg, verbose=False, source=SRC)
    flat = np.asarray(tr.flat)
    assert np.abs(flat - flat[:1]).max() > 0.0


def test_trainer_accepts_explicit_mesh():
    from federated_pytorch_test_tpu.parallel import client_mesh

    src4 = synthetic_cifar(n_train=320, n_test=60)
    cfg = tiny("fedavg", model="net", nadmm=1, n_clients=4)
    tr = Trainer(cfg, verbose=False, source=src4, mesh=client_mesh(2))
    assert tr.mesh.devices.size == 2
    tr.group_order = tr.group_order[:1]
    tr.run()
    assert np.asarray(tr.flat).shape[0] == 4

    with pytest.raises(ValueError, match="not divisible"):
        Trainer(cfg, verbose=False, source=src4, mesh=client_mesh(3))


def test_remat_matches_no_remat():
    # jax.checkpoint must change memory, not math: identical training
    # trajectory with and without
    flats = {}
    for remat in (False, True):
        cfg = tiny("fedavg", model="net", nadmm=1, remat=remat)
        tr = Trainer(cfg, verbose=False, source=SRC)
        tr.group_order = tr.group_order[:1]
        tr.run()
        flats[remat] = np.asarray(tr.flat)
    np.testing.assert_allclose(flats[False], flats[True], rtol=1e-5, atol=1e-6)


def test_bfloat16_compute_trains():
    # mixed precision: convs/matmuls bf16, params + loss + L-BFGS f32
    cfg = tiny("fedavg", model="net", nadmm=2, compute_dtype="bfloat16")
    tr = Trainer(cfg, verbose=False, source=SRC)
    assert np.asarray(tr.flat).dtype == np.float32  # params stay f32
    tr.group_order = tr.group_order[:2]
    rec = tr.run()
    losses = rec.series["train_loss"]
    first, last = np.mean(losses[0]["value"]), np.mean(losses[-1]["value"])
    assert np.isfinite(last) and last < first
    assert "fault" not in rec.series  # no non-finite anything


def test_config_rejects_invalid_enums():
    for field, bad in [
        ("fault_mode", "Raise"),
        ("strategy", "fedsgd"),
        ("reg_mode", "all"),
    ]:
        with pytest.raises(ValueError, match=field.split("_")[0]):
            get_preset("fedavg", **{field: bad})


def test_step_times_recorded():
    # fused default: the whole round is one dispatch, timed as one
    # `fused_round` phase; the unfused path keeps the per-dispatch
    # epoch/consensus phases
    cfg = tiny("fedavg", model="net", nadmm=1)
    tr = Trainer(cfg, verbose=False, source=SRC)
    tr.group_order = tr.group_order[:1]
    rec = tr.run()
    times = rec.series["step_time"]
    phases = {t["value"]["phase"] for t in times}
    assert phases == {"fused_round"}
    assert all(t["value"]["seconds"] > 0 for t in times)

    cfg = tiny("fedavg", model="net", nadmm=1, fuse_rounds=False)
    tr = Trainer(cfg, verbose=False, source=SRC)
    tr.group_order = tr.group_order[:1]
    rec = tr.run()
    times = rec.series["step_time"]
    phases = {t["value"]["phase"] for t in times}
    assert phases == {"epoch", "consensus"}
    assert all(t["value"]["seconds"] > 0 for t in times)


def test_fault_detection_warn_and_raise():
    import jax.numpy as jnp

    # poison client 1's params with NaN before a round: fault_mode='warn'
    # must record the fault (and the optimizer's guards keep siblings
    # finite); fault_mode='raise' must abort
    cfg = tiny("fedavg", model="net", nadmm=1, fault_mode="warn")
    tr = Trainer(cfg, verbose=False, source=SRC)
    tr.flat = tr.flat.at[1].set(jnp.nan)
    tr.group_order = tr.group_order[:1]
    rec = tr.run()
    faults = rec.series["fault"]
    # the poisoned client is identified by the per-epoch loss check...
    assert any(
        f["value"]["kind"] == "nonfinite_loss" and f["value"]["clients"] == [1]
        for f in faults
    )
    # ...and after the FedAvg mean propagates its NaN group coordinates to
    # everyone (exactly what the reference's z=(x1+x2+x3)/3 would do), the
    # per-round param check reports the blast radius
    assert any(
        f["value"]["kind"] == "nonfinite_params" and 1 in f["value"]["clients"]
        for f in faults
    )

    cfg = tiny("fedavg", model="net", nadmm=1, fault_mode="raise")
    tr = Trainer(cfg, verbose=False, source=SRC)
    tr.flat = tr.flat.at[1].set(jnp.nan)
    tr.group_order = tr.group_order[:1]
    with pytest.raises(FloatingPointError, match="clients \\[1\\]"):
        tr.run()


def test_scale64_preset_runs_on_8_devices():
    # BASELINE.json config #5: K=64 clients, CIFAR100, one client per core
    # on a v4-64. On the 8-device CPU mesh the 64 clients fold into local
    # blocks of 8; the model is downsized for CPU CI but keeps the
    # 100-class head the preset specifies.
    src = synthetic_cifar(n_train=64 * 10, n_test=128, num_classes=100)
    cfg = get_preset(
        "fedavg_scale64", model="net", batch=5, nloop=1, nadmm=1,
        shuffle_group_order=False,
    )
    tr = Trainer(cfg, verbose=False, source=src)
    assert tr.cfg.n_clients == 64 and tr.fed.num_classes == 100
    tr.group_order = tr.group_order[:1]
    rec = tr.run()
    flat = np.asarray(tr.flat)
    assert flat.shape[0] == 64
    gid = tr.group_order[0]
    for seg in tr.partition.groups[gid]:
        blk = flat[:, seg.start : seg.start + seg.size]
        assert np.abs(blk - blk[:1]).max() == 0.0  # all 64 synced
    assert np.isfinite(np.mean(rec.series["train_loss"][-1]["value"]))


def test_k6_clients_on_3_devices_local_blocks():
    # K need not equal device count: 6 clients on 3 devices => local
    # blocks of 2. Collectives reduce the local axis before the psum, so
    # results must be consistent with the pure cross-client math.
    src6 = synthetic_cifar(n_train=480, n_test=60)
    cfg = tiny(
        "fedavg", model="net", nadmm=1, n_clients=6, max_devices=3
    )
    tr = Trainer(cfg, verbose=False, source=src6)
    assert tr.mesh.devices.size == 3 and tr.cfg.n_clients == 6
    tr.group_order = tr.group_order[:1]
    rec = tr.run()
    flat = np.asarray(tr.flat)
    assert flat.shape[0] == 6
    gid = tr.group_order[0]
    for seg in tr.partition.groups[gid]:
        blk = flat[:, seg.start : seg.start + seg.size]
        assert np.abs(blk - blk[:1]).max() == 0.0  # all 6 synced
    assert np.isfinite(np.mean(rec.series["train_loss"][-1]["value"]))


def test_resume_replays_exact_trajectory(tmp_path):
    # the claim at utils/checkpoint.py: a resumed run replays the EXACT
    # trajectory of an uninterrupted one. Run 2 loops straight; run 1 loop,
    # checkpoint, resume into loop 2 from a fresh Trainer; the continued
    # params AND the continued metric series must be bit-identical.
    common = dict(
        model="net", nadmm=2, save_model=True, check_results=True,
        eval_batch=30,
    )
    cfg_a = tiny("fedavg", nloop=2, checkpoint_dir=str(tmp_path / "a"),
                 **common)
    tr_a = Trainer(cfg_a, verbose=False, source=SRC)
    tr_a.group_order = tr_a.group_order[:1]
    rec_a = tr_a.run()

    # "interrupted" run: same config but stop after loop 0 (loop counters,
    # not cfg.nloop, seed the epoch shuffles, so loop 0 is identical)
    cfg_b = tiny("fedavg", nloop=1, checkpoint_dir=str(tmp_path / "b"),
                 **common)
    tr_b = Trainer(cfg_b, verbose=False, source=SRC)
    tr_b.group_order = tr_b.group_order[:1]
    tr_b.run()

    # resume for loop 1
    cfg_b2 = cfg_b.replace(nloop=2, load_model=True)
    tr_b2 = Trainer(cfg_b2, verbose=False, source=SRC)
    tr_b2.group_order = tr_b2.group_order[:1]
    assert tr_b2._completed_nloops == 1  # restored cursor
    rec_b2 = tr_b2.run()

    np.testing.assert_array_equal(
        np.asarray(tr_b2.flat), np.asarray(tr_a.flat)
    )
    # continued series == the uninterrupted run's loop-1 slice, bit for bit
    for name in ("train_loss", "dual_residual", "test_accuracy"):
        a_vals = [r["value"] for r in rec_a.series[name] if r["nloop"] == 1]
        b_vals = [r["value"] for r in rec_b2.series[name]]
        assert a_vals == b_vals, name


def test_eval_every_batch_cadence():
    # reference check_results=True evaluates after EVERY batch
    # (reference src/no_consensus_trio.py:266-267): the knob must produce
    # one accuracy record per minibatch and leave training unchanged.
    # The cadence machinery is model-agnostic; the cheap 62k-param model
    # keeps this two-full-trainings test off the suite's critical path
    # (net1 here measured 425 s on the 1-core CI host).
    base = dict(model="net", nepoch=2, check_results=True, eval_batch=30)
    cfg = tiny("no_consensus", eval_every_batch=True, **base)
    tr = Trainer(cfg, verbose=False, source=SRC)
    rec = tr.run()

    accs = rec.series["test_accuracy"]
    # 240 train / 3 clients = 80/client; batch 40 => 2 minibatches/epoch
    assert len(accs) == 2 * 2
    assert [a["minibatch"] for a in accs] == [0, 1, 0, 1]

    cfg2 = tiny("no_consensus", eval_every_batch=False, **base)
    tr2 = Trainer(cfg2, verbose=False, source=SRC)
    tr2.run()
    np.testing.assert_allclose(
        np.asarray(tr.flat), np.asarray(tr2.flat), rtol=1e-6, atol=1e-7
    )


def test_bfloat16_resnet_bn_stats_match_f32():
    # the bf16 BN computes its batch statistics in bf16 (fusable
    # reductions, models/resnet.py:_bn): training must stay finite and
    # the running stats must agree with the f32 path to bf16 tolerance
    import jax

    # one lockstep step per run: a single BN-stat update already
    # discriminates bf16-vs-f32 statistics, and each extra step is
    # another 9-eval resnet pass per client on the 1-core CI host
    small = synthetic_cifar(n_train=90, n_test=30)

    def run(dtype):
        cfg = tiny("fedavg_resnet", batch=30, nadmm=1, compute_dtype=dtype)
        tr = Trainer(cfg, verbose=False, source=small)
        tr.group_order = [9]  # linear head: cheapest resnet group
        rec = tr.run()
        stats = np.concatenate(
            [np.ravel(x) for x in jax.tree.leaves(tr.stats)]
        )
        return rec, stats

    rec16, stats16 = run("bfloat16")
    rec32, stats32 = run("float32")
    assert np.isfinite(stats16).all()
    assert np.isfinite(np.mean(rec16.series["train_loss"][-1]["value"]))
    # bf16 mantissa is 8 bits: stats should track f32 to ~1e-2 relative
    np.testing.assert_allclose(stats16, stats32, rtol=3e-2, atol=3e-2)
    l16 = np.mean(rec16.series["train_loss"][-1]["value"])
    l32 = np.mean(rec32.series["train_loss"][-1]["value"])
    assert abs(l16 - l32) < 0.15


def test_streaming_data_path_trains():
    # hbm_data_budget_mb below the dataset size => data never fully
    # resides on device: per-client PrefetchBatchers assemble lockstep
    # chunks, double-buffered against the jitted scan
    # (trainer._run_stream_epoch). Must train like the resident path.
    src = synthetic_cifar(n_train=360, n_test=60)  # 120/client
    cfg = tiny(
        "fedavg", model="net", nadmm=2,
        hbm_data_budget_mb=0,  # force streaming (dataset ~1 MB > 0)
        stream_chunk_steps=2,  # 3 minibatches/epoch -> chunks of 2 and 1:
                               # exercises the chunked loop AND the
                               # smaller TAIL chunk (its own compile)
    )
    tr = Trainer(cfg, verbose=False, source=src)
    assert tr._stream and tr.shard_imgs is None
    assert len(tr._batchers) == 3
    tr.group_order = tr.group_order[:2]
    rec = tr.run()

    losses = rec.series["train_loss"]
    # 360/3 = 120/client, batch 40 -> 3 lockstep minibatches per epoch
    assert len(losses[0]["value"]) == 3
    per_epoch = [
        e for e in losses
        if e["nloop"] == 0 and e["group"] == tr.group_order[0]
        and e["nadmm"] == 0
    ]
    assert len(per_epoch) == 3  # all 3 steps (2-chunk + tail) recorded
    first, last = np.mean(losses[0]["value"]), np.mean(losses[-1]["value"])
    assert np.isfinite(last) and last < first
    # FedAvg sync still holds through the streamed epochs
    flat = np.asarray(tr.flat)
    gid = tr.group_order[-1]
    for seg in tr.partition.groups[gid]:
        blk = flat[:, seg.start : seg.start + seg.size]
        assert np.abs(blk - blk[:1]).max() == 0.0
    for b in tr._batchers.values():
        b.close()


def test_streaming_rejects_incompatible_modes(tmp_path):
    # the streaming path cannot honor per-batch eval (resident-only) —
    # fail LOUDLY at construction, not diverge silently mid-run. A
    # checkpoint written by a RESIDENT run carries no stream positions,
    # so resuming it under streaming must also fail loudly.
    base = dict(model="net", hbm_data_budget_mb=0)
    with pytest.raises(NotImplementedError, match="eval_every_batch"):
        Trainer(
            tiny("fedavg", check_results=True, eval_every_batch=True, **base),
            verbose=False,
            source=SRC,
        )
    cfg = tiny("fedavg", model="net", nloop=1, nadmm=1, save_model=True,
               checkpoint_dir=str(tmp_path))
    tr = Trainer(cfg, verbose=False, source=SRC)
    tr.group_order = tr.group_order[:1]
    tr.run()
    with pytest.raises(ValueError, match="resident"):
        Trainer(
            tiny("fedavg", nloop=2, load_model=True,
                 checkpoint_dir=str(tmp_path), **base),
            verbose=False,
            source=SRC,
        )
    # ... and the mirror image: a STREAMING checkpoint resumed resident
    # would silently reseed the minibatch stream — must also fail loudly
    cfg_s = tiny("fedavg", nloop=1, nadmm=1, save_model=True,
                 checkpoint_dir=str(tmp_path / "s"), **base)
    tr_s = Trainer(cfg_s, verbose=False, source=SRC)
    tr_s.group_order = tr_s.group_order[:1]
    tr_s.run()
    with pytest.raises(ValueError, match="STREAMING"):
        Trainer(
            tiny("fedavg", model="net", nloop=2, load_model=True,
                 checkpoint_dir=str(tmp_path / "s")),
            verbose=False,
            source=SRC,
        )


def test_qkv_layout_guard_refuses_stale_transformer_checkpoints(tmp_path):
    # the fused-qkv column order changed to head-major in round 3
    # (models/transformer.py QKV_LAYOUT_VERSION): a pre-change checkpoint
    # loads shape-compatibly but computes scrambled attention, so restore
    # must refuse it. Un-stamped checkpoints are by definition v1.
    from federated_pytorch_test_tpu.utils.checkpoint import (
        load_checkpoint,
        save_checkpoint,
    )

    cfg = tiny("fedavg", model="vit", checkpoint_dir=str(tmp_path))
    tr = Trainer(cfg, verbose=False, source=SRC)
    tr.save(step=1)

    # same-version round trip is fine
    Trainer(cfg.replace(load_model=True), verbose=False, source=SRC)

    # simulate a v1 (pre-stamp) checkpoint
    state = load_checkpoint(str(tmp_path))
    del state["qkv_layout"]
    save_checkpoint(str(tmp_path), state, step=1)
    with pytest.raises(ValueError, match="qkv column order"):
        Trainer(cfg.replace(load_model=True), verbose=False, source=SRC)

    # CNN checkpoints carry no stamp and are unaffected by the guard
    cfg_cnn = tiny("fedavg", model="net", checkpoint_dir=str(tmp_path / "c"))
    tr_c = Trainer(cfg_cnn, verbose=False, source=SRC)
    tr_c.save(step=1)
    assert "qkv_layout" not in load_checkpoint(str(tmp_path / "c"))
    Trainer(cfg_cnn.replace(load_model=True), verbose=False, source=SRC)


def test_stream_resume_replays_exact_trajectory(tmp_path):
    # streaming checkpoint/resume (round-2 VERDICT item 4): the batchers'
    # streams are pure functions of (seed, batch, drawn-count), the drawn
    # counts are checkpointed, and restore fast-forwards fresh batchers —
    # so a resumed streaming run must replay the uninterrupted trajectory
    # bit for bit, exactly like the resident path.
    src = synthetic_cifar(n_train=360, n_test=60)
    common = dict(
        model="net", nadmm=2, save_model=True, check_results=True,
        eval_batch=30, hbm_data_budget_mb=0, stream_chunk_steps=2,
    )
    cfg_a = tiny("fedavg", nloop=2, checkpoint_dir=str(tmp_path / "a"),
                 **common)
    tr_a = Trainer(cfg_a, verbose=False, source=src)
    tr_a.group_order = tr_a.group_order[:1]
    rec_a = tr_a.run()

    cfg_b = tiny("fedavg", nloop=1, checkpoint_dir=str(tmp_path / "b"),
                 **common)
    tr_b = Trainer(cfg_b, verbose=False, source=src)
    tr_b.group_order = tr_b.group_order[:1]
    tr_b.run()
    drawn_at_save = [b.drawn for b in tr_b._batchers.values()]
    assert all(d > 0 for d in drawn_at_save)

    cfg_b2 = cfg_b.replace(nloop=2, load_model=True)
    tr_b2 = Trainer(cfg_b2, verbose=False, source=src)
    tr_b2.group_order = tr_b2.group_order[:1]
    assert tr_b2._completed_nloops == 1
    assert [b.drawn for b in tr_b2._batchers.values()] == drawn_at_save  # fast-forwarded
    rec_b2 = tr_b2.run()

    np.testing.assert_array_equal(
        np.asarray(tr_b2.flat), np.asarray(tr_a.flat)
    )
    for name in ("train_loss", "dual_residual", "test_accuracy"):
        a_vals = [r["value"] for r in rec_a.series[name] if r["nloop"] == 1]
        b_vals = [r["value"] for r in rec_b2.series[name]]
        assert a_vals == b_vals, name
    for tr in (tr_a, tr_b, tr_b2):
        for b in tr._batchers.values():
            b.close()


def test_resident_auto_chunking_is_bit_identical():
    # max_scan_steps caps the minibatches per jitted resident call (the
    # guard for TPU runtimes that die on very long scans — round-2's
    # 520-step crash). Chunked (cap 2 over 3 steps: a 2-slice + a tail
    # slice) must produce the EXACT trajectory of the single-call epoch.
    src = synthetic_cifar(n_train=360, n_test=60)  # 3 minibatches/epoch
    base = dict(model="net", nadmm=2, check_results=False)
    tr_one = Trainer(tiny("fedavg", max_scan_steps=None, **base),
                     verbose=False, source=src)
    tr_one.group_order = tr_one.group_order[:1]
    rec_one = tr_one.run()
    tr_chk = Trainer(tiny("fedavg", max_scan_steps=2, **base),
                     verbose=False, source=src)
    tr_chk.group_order = tr_chk.group_order[:1]
    rec_chk = tr_chk.run()

    np.testing.assert_array_equal(
        np.asarray(tr_one.flat), np.asarray(tr_chk.flat)
    )
    l1 = [r["value"] for r in rec_one.series["train_loss"]]
    l2 = [r["value"] for r in rec_chk.series["train_loss"]]
    assert l1 == l2  # per-minibatch losses identical, chunked or not


def test_max_groups_limits_partition_order():
    # the reduced-schedule knob: train only the first N groups of the
    # (possibly shuffled) order — also reachable as --max-groups via the
    # auto-generated CLI
    cfg = tiny("fedavg", model="net", nadmm=1, max_groups=2)
    tr = Trainer(cfg, verbose=False, source=SRC)
    assert tr.group_order == [2, 0]  # first 2 of train_order [2,0,1,3,4]
    rec = tr.run()
    assert len(rec.series["dual_residual"]) == 2  # one round per group
    with pytest.raises(ValueError, match="max_groups"):
        tiny("fedavg", max_groups=0)


def test_moe_aux_loss_reaches_engine_loss():
    # ADVICE r3: a MoE model trained through the Trainer must optimize the
    # switch load-balance term, not silently drop it. The ViT-MoE's sown
    # `moe_aux` (models/moe.py:145) flows into the engine loss scaled by
    # cfg.moe_aux_coef; zeroing the coef removes exactly that term.
    import jax.numpy as jnp

    from federated_pytorch_test_tpu.engine.steps import _data_loss

    cfg = tiny("fedavg", model="vit", model_kwargs={"moe_experts": 2})
    tr = Trainer(cfg, verbose=False, source=SRC)
    assert tr.model.moe_experts == 2
    ctx = tr._ctx(tr.group_order[0])
    assert ctx.moe_aux_coef == cfg.moe_aux_coef > 0

    flat0 = jnp.asarray(np.asarray(tr.flat)[0])
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.normal(size=(4, 32, 32, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, size=(4,)), jnp.int32)
    with_aux, _ = _data_loss(ctx, flat0, {}, imgs, labels)
    without, _ = _data_loss(
        ctx._replace(moe_aux_coef=0.0), flat0, {}, imgs, labels
    )
    # the switch aux term E * sum(frac * prob) is >= 1 per MoE layer
    # (Cauchy-Schwarz, equality at uniform routing); 4 blocks at coef c
    # must raise the loss by >= ~4c
    gap = float(with_aux) - float(without)
    assert gap > 0.9 * 4 * cfg.moe_aux_coef, gap


def test_model_kwargs_are_validated():
    with pytest.raises(ValueError, match="model_kwargs"):
        Trainer(
            tiny("fedavg", model="net", model_kwargs={"moe_experts": 2}),
            verbose=False,
            source=SRC,
        )


def test_diag_forward_off_keeps_trajectory_identical():
    # skipping the per-batch diagnostic forward (a pure-throughput knob,
    # benchmarks/epoch_attribution.py) must not change the parameter
    # trajectory — only the reported per-batch loss (entry vs accepted)
    runs = {}
    for diag in (True, False):
        cfg = tiny("fedavg", nadmm=2, diag_forward=diag)
        tr = Trainer(cfg, verbose=False, source=SRC)
        tr.group_order = tr.group_order[:1]
        tr.run()
        runs[diag] = np.asarray(tr.flat)
    assert np.array_equal(runs[True], runs[False])


def test_diag_forward_forced_on_for_batch_stats_models():
    cfg = tiny("fedavg_resnet", batch=8, diag_forward=False,
               synthetic_n_train=48, synthetic_n_test=24)
    tr = Trainer(cfg, verbose=False, source=None)
    assert tr._ctx(tr.group_order[0]).diag_forward is True


def test_config_is_hashable_with_model_kwargs():
    # frozen dataclasses derive __hash__ from raw field values; the
    # dict-valued model_kwargs would raise TypeError the first time a
    # config lands in a set / dict key / jit static arg (ADVICE r4).
    a = tiny("fedavg", model="vit", model_kwargs={"moe_experts": 4})
    b = tiny("fedavg", model="vit", model_kwargs={"moe_experts": 4})
    c = tiny("fedavg", model="vit", model_kwargs={"moe_experts": 8})
    assert hash(a) == hash(b) and a == b
    assert a != c
    assert len({a, b, c}) == 2


def test_compile_round_seeds_cache_without_training():
    # the dryrun's compile-only scale64 seeding pass: lower+compile the
    # epoch program, touch no parameters, and leave the trainer able to
    # run the identical round afterwards (cache hit, same trajectory as
    # an un-seeded twin).
    cfg = tiny("fedavg", model="net", nadmm=1)
    tr = Trainer(cfg, verbose=False, source=SRC)
    gid = tr.group_order[0]
    before = np.asarray(tr.flat).copy()
    tr.compile_round(gid)
    assert np.array_equal(np.asarray(tr.flat), before), (
        "compile_round must not execute a training step"
    )
    tr.run_round(nloop=0, gid=gid)
    twin = Trainer(cfg, verbose=False, source=SRC)
    twin.run_round(nloop=0, gid=gid)
    np.testing.assert_array_equal(np.asarray(tr.flat), np.asarray(twin.flat))


def test_folded_diag_forward_matches_explicit():
    # round-5 fold: the Armijo-accepted evaluation IS at the step's
    # final params, so threading its (data loss, BN stats) out of
    # lbfgs_step replaces the explicit diagnostic forward. Parameters
    # must be BIT-identical (train-mode BN never reads running stats);
    # running stats and the loss telemetry agree to XLA-fusion ulps.
    # One jitted client step on one minibatch (a double Trainer.run on
    # resnet costs ~10 min of compiles on the 1-core CI host; the fold
    # lives entirely inside _client_train_step, so one call covers it).
    import jax
    import jax.numpy as jnp

    from federated_pytorch_test_tpu.engine.steps import _client_train_step

    src = synthetic_cifar(n_train=48, n_test=12)
    cfg = tiny("fedavg_resnet", model="resnet18", batch=16,
               synthetic_n_train=48, synthetic_n_test=12)
    tr = Trainer(cfg, verbose=False, source=src)
    gid = tr.group_order[0]
    _, _, init_fn = tr._fns(gid)
    lstate_k, y_k, z, rho_k, _ = init_fn(tr.flat)
    one = lambda t: jax.tree.map(lambda x: jnp.asarray(np.asarray(x)[0]), t)
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(rng.integers(0, 256, size=(16, 32, 32, 3)), jnp.uint8)
    labels = jnp.asarray(rng.integers(0, 10, size=(16,)), jnp.int32)
    args = (one(tr.flat), one(lstate_k), one(tr.stats), imgs, labels,
            one(tr.mean), one(tr.std), one(y_k), jnp.asarray(z), one(rho_k))

    outs = {}
    for fold in (True, False):
        ctx = tr._ctx(gid)._replace(fold_diag=fold)
        outs[fold] = jax.jit(_client_train_step(ctx))(*args)
    flat_f, _, stats_f, loss_f = outs[True]
    flat_e, _, stats_e, loss_e = outs[False]
    np.testing.assert_array_equal(np.asarray(flat_f), np.asarray(flat_e))
    for a, b in zip(jax.tree.leaves(stats_f), jax.tree.leaves(stats_e)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7
        )
    np.testing.assert_allclose(
        float(loss_f), float(loss_e), rtol=1e-5, atol=1e-6
    )


def test_folded_diag_forward_matches_explicit_bnless_and_admm():
    # BN-less model + ADMM penalties: the folded data-loss telemetry
    # must equal the explicit diagnostic forward's (penalty-free) loss
    src = synthetic_cifar(n_train=120, n_test=24)
    base = tiny("admm", model="net", batch=24, nadmm=2,
                synthetic_n_train=120, synthetic_n_test=24)
    runs = {}
    for fold in (True, False):
        tr = Trainer(base.replace(fold_diag_forward=fold), verbose=False,
                     source=src)
        tr.group_order = tr.group_order[:1]
        rec = tr.run()
        runs[fold] = (
            np.asarray(tr.flat).copy(),
            [r["value"] for r in rec.series["train_loss"]],
        )
    np.testing.assert_array_equal(runs[True][0], runs[False][0])
    np.testing.assert_allclose(
        np.asarray(runs[True][1]), np.asarray(runs[False][1]),
        rtol=1e-5, atol=1e-6,
    )
