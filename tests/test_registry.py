"""Cross-run registry + `report` CLI tests (obs/registry.py).

Smoke tier: stream ingestion/validation mechanics on hand-built JSONL
files (header refusal mirrors the resume path's checks; torn tails
tolerated), and the frontier hand-checked against two tiny synthetic
runs with KNOWN ledger totals (the ISSUE-10 coverage item).

Middle (default) tier: `report` over two real trainer runs (f32 vs bf16
exchange — a two-point codec sweep) emits the convergence-vs-bytes
frontier with the bf16 uplink exactly half the f32 one, and the output
is byte-deterministic (the property the tier-2 report_smoke's
crashed-twin byte-compare rides on).
"""

import json
import warnings

import pytest

from federated_pytorch_test_tpu.obs import (
    RunRegistry,
    StreamRefused,
    read_stream,
    render_markdown,
    report_main,
)

smoke = pytest.mark.smoke


def _write_stream(path, tag, records, markers=(0,), torn_tail=False):
    """A hand-built metric stream: header + records + commit markers."""
    lines = [{"event": "stream_header", "version": 1, "tag": tag}]
    lines += records
    for m in markers:
        lines.append({"event": "nloop_complete", "nloop": m})
    with open(path, "w") as f:
        for d in lines:
            f.write(json.dumps(d) + "\n")
        if torn_tail:
            f.write('{"series": "train_loss", "val')  # crash mid-write
    return path


def _known_run(bytes_per_exchange, accs):
    """Records of a run with KNOWN ledger totals: one comm_bytes +
    test_accuracy pair per exchange."""
    recs = []
    for i, acc in enumerate(accs):
        recs.append(
            {"series": "comm_bytes", "t": 0.1 * i,
             "value": bytes_per_exchange, "nloop": 0, "group": 2,
             "nadmm": i, "survivors": 3}
        )
        recs.append(
            {"series": "test_accuracy", "t": 0.1 * i, "value": acc,
             "nloop": 0, "group": 2, "nadmm": i}
        )
    recs.append(
        {"series": "comm_summary", "t": 1.0,
         "value": {"exchange_dtype": "float32", "wire_bytes_per_value": 4,
                   "bytes_per_round_mean": float(bytes_per_exchange),
                   "savings_vs_full": 5.0}}
    )
    return recs


# ------------------------------------------------------------- validation


@smoke
def test_read_stream_refuses_foreign_files(tmp_path):
    # no header: not a metric stream
    p = tmp_path / "not_a_stream.jsonl"
    p.write_text('{"series": "train_loss", "value": [1.0]}\n')
    with pytest.raises(StreamRefused, match="not a stream_header"):
        read_stream(str(p))
    # wrong version: a foreign format must not be misread
    q = tmp_path / "future.jsonl"
    q.write_text('{"event": "stream_header", "version": 99, "tag": "x"}\n')
    with pytest.raises(StreamRefused, match="version"):
        read_stream(str(q))
    # empty file
    r = tmp_path / "empty.jsonl"
    r.write_text("")
    with pytest.raises(StreamRefused, match="empty"):
        read_stream(str(r))


@smoke
def test_read_stream_tolerates_torn_tail_and_stops_at_garbage(tmp_path):
    p = _write_stream(
        tmp_path / "a.jsonl", "exp:seed0:cfgx:noplan",
        _known_run(120, [[0.5, 0.7]]), torn_tail=True,
    )
    run = read_stream(str(p))
    assert run.tag == "exp:seed0:cfgx:noplan"
    assert run.label == "exp:seed0"
    assert run.markers == [0]
    assert len(run.records) == 3  # torn tail dropped
    # garbage mid-file: nothing past it is trusted (the resume rule)
    with open(p, "w") as f:
        f.write('{"event": "stream_header", "version": 1, "tag": "t"}\n')
        f.write("}{ not json\n")
        f.write('{"series": "comm_bytes", "value": 5}\n')
    assert read_stream(str(p)).records == []


@smoke
def test_registry_match_filter_and_duplicate_names(tmp_path):
    _write_stream(tmp_path / "a.jsonl", "fedavg:seed0:cfgx:noplan", [])
    _write_stream(tmp_path / "b.jsonl", "admm:seed0:cfgy:noplan", [])
    reg = RunRegistry(match="fedavg:seed0")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        skipped = reg.ingest_dir(str(tmp_path))
    assert [s.endswith("b.jsonl") for s in skipped] == [True]
    assert any("foreign experiment" in str(w.message) for w in caught)
    assert set(reg.runs) == {"a"}
    # the same run name twice is refused, not silently replaced
    with pytest.raises(StreamRefused, match="already ingested"):
        reg.ingest(str(tmp_path / "a.jsonl"))


# ----------------------------------------------- frontier hand-check


@smoke
def test_report_frontier_hand_checked_against_known_totals(tmp_path):
    """Two tiny runs with known ledger totals (the ISSUE-10 test item):
    run `cheap` ships 3 x 100 B reaching 0.8, run `costly` 3 x 200 B
    reaching 0.7 — cheap strictly dominates, costly is off the
    frontier."""
    _write_stream(
        tmp_path / "cheap.jsonl", "fedavg:seed0:cfga:noplan",
        _known_run(100, [[0.4, 0.6], [0.6, 0.8], [0.8, 0.8]]),
    )
    _write_stream(
        tmp_path / "costly.jsonl", "fedavg:seed0:cfgb:noplan",
        _known_run(200, [[0.3, 0.5], [0.5, 0.7], [0.7, 0.7]]),
    )
    reg = RunRegistry()
    assert reg.ingest_dir(str(tmp_path)) == []
    doc = reg.report()

    cheap, costly = doc["runs"]["cheap"], doc["runs"]["costly"]
    assert cheap["total_comm_bytes"] == 300  # 3 exchanges x 100 B
    assert costly["total_comm_bytes"] == 600
    assert cheap["exchanges"] == costly["exchanges"] == 3
    assert cheap["final_accuracy"] == pytest.approx(0.8)
    assert costly["final_accuracy"] == pytest.approx(0.7)
    # the curve is cumulative bytes at each eval, in stream order
    assert [p["cum_bytes"] for p in cheap["curve"]] == [100, 200, 300]
    assert [p["accuracy"] for p in cheap["curve"]] == [0.5, 0.7, 0.8]
    assert cheap["comm"]["savings_vs_full"] == 5.0

    front = {p["run"]: p for p in doc["frontier"]}
    assert front["cheap"]["pareto"] is True
    assert front["costly"]["pareto"] is False
    # frontier rows sorted by total bytes
    assert [p["run"] for p in doc["frontier"]] == ["cheap", "costly"]
    # aligned-by-eval series for cross-run plots
    assert doc["aligned"]["accuracy_by_eval"]["costly"] == [0.4, 0.6, 0.7]

    # codec-less streams label as the dense identity/roundrobin config
    assert cheap["config"]["label"] == "identity/roundrobin"
    assert cheap["bytes_saved_by_skipping"] == 0

    md = render_markdown(doc)
    assert (
        "| cheap | fedavg:seed0 | identity/roundrobin | 3 | 0.8000 "
        "| 300 | 3 | 0 |" in md
    )
    # dominated points are flagged explicitly in the frontier table
    assert (
        "| costly | identity/roundrobin | 600 | 0 | 0.7000 | dominated |"
        in md
    )


@smoke
def test_report_cli_writes_deterministic_outputs(tmp_path, capsys):
    d = tmp_path / "runs"
    d.mkdir()
    _write_stream(
        d / "a.jsonl", "fedavg:seed0:cfga:noplan",
        _known_run(100, [[0.5, 0.5]]),
    )
    (d / "junk.jsonl").write_text("definitely not json\n")
    out1, out2 = tmp_path / "r1.json", tmp_path / "r2.json"
    with warnings.catch_warnings(record=True):
        warnings.simplefilter("always")
        assert report_main([str(d), "--json", str(out1), "--quiet"]) == 0
        assert report_main([str(d), "--json", str(out2)]) == 0
    # byte-determinism: the property the tier-2 report_smoke twin
    # byte-compare rides on
    assert out1.read_bytes() == out2.read_bytes()
    assert "Convergence vs bytes frontier" in capsys.readouterr().out
    # an all-refused directory exits nonzero
    empty = tmp_path / "none"
    empty.mkdir()
    assert report_main([str(empty), "--quiet"]) == 1


# ------------------------------------- Trainer integration (slow tier)
# The real-sweep leg costs two full trainer runs; the tier-1 wall sits
# within ~10 s of the 870 s gate, so it rides tier 2 (the frontier
# arithmetic itself is gated in tier 0 above, and the end-to-end CLI
# twin byte-compare in scripts/ci.sh report_smoke).


@pytest.mark.slow
def test_report_over_real_codec_combiner_sweep(tmp_path):
    """The ISSUE-10 acceptance sweep: a real {codec × combiner} grid —
    identical tiny configs crossed over exchange wire format
    {f32, bf16} and robust combiner {mean, trimmed} — reported as one
    directory. Per codec the ledger totals must show bf16 at EXACTLY
    half the f32 bytes regardless of combiner (the PR-9 wire contract
    through the registry path), every run health-monitored, and the
    frontier emitted over all four points."""
    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    src = synthetic_cifar(n_train=240, n_test=60)
    d = tmp_path / "runs"
    d.mkdir()
    grid = [
        (codec, agg)
        for codec in ("float32", "bfloat16")
        for agg in ("mean", "trimmed")
    ]
    for codec, agg in grid:
        name = f"{'f32' if codec == 'float32' else 'bf16'}_{agg}"
        cfg = get_preset(
            "fedavg", batch=40, nloop=1, nadmm=2, max_groups=1,
            model="net", check_results=True, eval_batch=30,
            synthetic_ok=True, exchange_dtype=codec, robust_agg=agg,
            robust_f=1, metrics_stream=str(d / f"{name}.jsonl"),
        )
        Trainer(cfg, verbose=False, source=src).run()

    reg = RunRegistry()
    assert reg.ingest_dir(str(d)) == []
    doc = reg.report()
    runs = doc["runs"]
    assert set(runs) == {"f32_mean", "f32_trimmed", "bf16_mean",
                         "bf16_trimmed"}
    for agg in ("mean", "trimmed"):
        f32, bf16 = runs[f"f32_{agg}"], runs[f"bf16_{agg}"]
        assert f32["total_comm_bytes"] == 2 * bf16["total_comm_bytes"] > 0
        assert bf16["comm"]["exchange_dtype"] == "bfloat16"
        assert bf16["comm"]["wire_bytes_per_value"] == 2
        assert f32["evals"] == bf16["evals"] == 2
        assert f32["health"]["records"] == bf16["health"]["records"] == 1
    # the frontier covers the whole grid; the best-accuracy bf16 run is
    # on it by construction (no f32 run can dominate it on bytes, and
    # ties among the equal-byte bf16 runs leave the better one standing)
    assert len(doc["frontier"]) == 4
    assert doc["frontier"][0]["run"].startswith("bf16")  # fewest bytes first
    assert any(
        p["pareto"] for p in doc["frontier"] if p["run"].startswith("bf16")
    )
