"""Provenance + trend + debt layer (obs/provenance.py, obs/benchdb.py,
obs/debt.py — ISSUE-18).

Everything here is pure host-side file analysis — no jax import, no
engine — mirroring the verbs under test (`trend`/`debt` dispatch before
the engine import chain). The load-bearing contracts:

* provenance-class ISOLATION: a CPU-twin measurement never closes a
  `backend==tpu` debt entry and never serves as the baseline a TPU
  number is sentinel-judged against (unit + end-to-end);
* tolerant ingestion: the driver's `{n, cmd, rc, tail, parsed}` wrapper
  with a torn/missing `parsed` payload skips with a named warning,
  never crashes (the committed BENCH_r03.json is exactly this case);
* determinism: re-ingesting the same files leaves the report
  byte-identical (digest-deduped append-only store);
* the regression sentinel: a beyond-band worsening in the SAME class is
  flagged, within-band twin noise is not, neutral metrics never are.
"""

import json
import warnings

import pytest

from federated_pytorch_test_tpu.obs.benchdb import (
    REL_NOISE_FLOOR,
    BenchDB,
    TrendRefused,
    extract_measurement,
    metric_direction,
    render_trend_markdown,
    trend_main,
)
from federated_pytorch_test_tpu.obs.debt import (
    close_entries,
    debt_main,
    emit_script,
    load_debt,
    open_entries,
    save_debt,
)
from federated_pytorch_test_tpu.obs.provenance import (
    STAMP_KEYS,
    condition_satisfied,
    host_stamp,
    provenance_class,
    provenance_stamp,
)

smoke = pytest.mark.smoke


def _stamp(backend, **over):
    s = {k: None for k in STAMP_KEYS}
    s.update(
        schema=1, backend=backend,
        cpu_twin=(backend == "cpu") if backend else None,
        git_sha="abc1234", git_dirty=False,
    )
    s.update(over)
    return s


def _wrapper(n, value, *, stamp=None, spread=0.02, metric="throughput_sps"):
    parsed = {
        "metric": metric, "value": value, "unit": "samples/sec",
        "sps_p25": value * (1 - spread), "sps_p75": value * (1 + spread),
    }
    if stamp is not None:
        parsed["provenance"] = stamp
    return {"n": n, "cmd": "python bench.py", "rc": 0,
            "tail": json.dumps(parsed), "parsed": parsed}


# ---------------------------------------------------------------- stamps

@smoke
def test_provenance_class_mapping():
    assert provenance_class(None) == "unstamped"
    assert provenance_class("garbage") == "unstamped"
    assert provenance_class({}) == "unstamped"
    assert provenance_class(_stamp(None)) == "unstamped"
    assert provenance_class(_stamp("cpu")) == "cpu_twin"
    assert provenance_class(_stamp("tpu")) == "tpu"
    assert provenance_class(_stamp("gpu")) == "gpu"
    # an explicit cpu_twin flag wins even with an odd backend string
    assert provenance_class(_stamp("tpu", cpu_twin=True)) == "cpu_twin"


@smoke
def test_provenance_stamp_backend_free():
    # probe_jax=False must never touch jax; explicit facts pass through
    s = provenance_stamp(probe_jax=False, backend="tpu",
                         device_kind="TPU v4", device_count=4, repeats=7)
    assert tuple(s) == STAMP_KEYS
    assert s["backend"] == "tpu" and s["cpu_twin"] is False
    assert s["device_kind"] == "TPU v4" and s["bench_repeats"] == 7
    assert host_stamp()["cpu_twin"] is True


@smoke
def test_condition_satisfied_truth_table():
    tpu, cpu = _stamp("tpu"), _stamp("cpu")
    assert condition_satisfied("backend==tpu", tpu)
    assert not condition_satisfied("backend==tpu", cpu)
    # THE isolation rule as a parser property: no stamp satisfies nothing
    assert not condition_satisfied("backend==tpu", None)
    assert not condition_satisfied("backend==tpu", {})
    assert condition_satisfied("", tpu) and condition_satisfied("", None)
    assert condition_satisfied("backend!=cpu", tpu)
    assert not condition_satisfied("backend!=cpu", cpu)
    assert condition_satisfied("backend==tpu and git_dirty==false", tpu)
    assert not condition_satisfied(
        "backend==tpu and git_dirty==true", tpu
    )
    # case-insensitive value compare (True == true)
    assert condition_satisfied("cpu_twin==true", cpu)
    with pytest.raises(ValueError):
        condition_satisfied("backend is tpu", tpu)


# ----------------------------------------------------------- ingestion

@smoke
def test_torn_wrapper_refused_with_named_reason():
    torn = {"n": 3, "cmd": "python bench.py", "rc": 0,
            "tail": '{"metric": "thr', "parsed": None}
    with pytest.raises(TrendRefused) as e:
        extract_measurement(torn, "BENCH_r03.json")
    assert "torn" in str(e.value) and "BENCH_r03" in str(e.value)


@smoke
def test_dir_ingest_skips_torn_wrapper_never_crashes(tmp_path):
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(_wrapper(1, 100.0)))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "cmd": "python bench.py", "rc": 0,
         "tail": "truncated mid-J", "parsed": None}))
    (tmp_path / "BENCH_r03.json").write_text("not json at all")
    db = BenchDB(str(tmp_path / "t.jsonl"))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        added, skipped = db.ingest([str(tmp_path)])
    assert (added, skipped) == (1, 2)
    msgs = " | ".join(str(x.message) for x in w)
    assert "BENCH_r02" in msgs and "BENCH_r03" in msgs


@smoke
def test_headline_spread_becomes_noise_band(tmp_path):
    db = BenchDB(str(tmp_path / "t.jsonl"))
    rec = db.ingest_doc(_wrapper(1, 200.0, spread=0.4), "BENCH_x.json")
    assert rec["metrics"]["throughput_sps"] == 200.0
    assert rec["spread"]["throughput_sps"] == pytest.approx(0.8)


@smoke
def test_metric_direction_vocabulary():
    assert metric_direction("throughput_sps") == "higher"
    assert metric_direction("widened_gemm_speedup") == "higher"
    assert metric_direction("full_fedavg_tpu:wall_seconds") == "lower"
    assert metric_direction("epoch_time_s") == "lower"
    assert metric_direction("ci_tier1_wall_s") == "lower"
    assert metric_direction("batch") is None
    assert metric_direction("linesearch_probes") is None
    assert metric_direction("full_x_tpu:final_acc_mean") == "higher"


# ------------------------------------------------- store + determinism

@smoke
def test_reingest_is_byte_identical(tmp_path):
    files = [tmp_path / f"BENCH_s{i}.json" for i in (1, 2)]
    files[0].write_text(json.dumps(_wrapper(1, 100.0)))
    files[1].write_text(json.dumps(_wrapper(2, 104.0)))
    store = str(tmp_path / "t.jsonl")

    db = BenchDB(store)
    db.ingest([str(f) for f in files])
    r1 = json.dumps(db.report(), sort_keys=True)
    m1 = render_trend_markdown(db.report())

    db2 = BenchDB(store)  # fresh load of the same store file
    added, skipped = db2.ingest([str(f) for f in files])
    assert added == 0 and skipped == 2  # all digest-deduped
    assert json.dumps(db2.report(), sort_keys=True) == r1
    assert render_trend_markdown(db2.report()) == m1


@smoke
def test_store_tolerates_torn_final_line(tmp_path):
    store = tmp_path / "t.jsonl"
    db = BenchDB(str(store))
    db.ingest_doc(_wrapper(1, 100.0), "BENCH_a.json")
    with open(store, "a") as f:
        f.write('{"torn": ')
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        db2 = BenchDB(str(store))
    assert len(db2.records) == 1
    assert any("torn store line" in str(x.message) for x in w)


# ----------------------------------------------------------- sentinel

@smoke
def test_sentinel_flags_same_class_slowdown():
    db = BenchDB("/nonexistent/never-written")
    db.records = []  # in-memory only
    for i, v in enumerate([100.0, 101.0, 48.0], 1):
        rec = _wrapper(i, v, stamp=_stamp("cpu"))
        m = extract_measurement(rec, f"BENCH_s{i:02d}.json")
        m["class"] = provenance_class(m["provenance"])
        db.records.append(m)
    rep = db.report()
    regs = rep["sentinel"]["regressions"]
    assert len(regs) == 1
    assert regs[0]["metric"] == "throughput_sps"
    assert regs[0]["class"] == "cpu_twin"
    assert regs[0]["source"] == "BENCH_s03"
    assert not rep["sentinel"]["pass"]
    assert "REGRESSION" in render_trend_markdown(rep)


@smoke
def test_sentinel_passes_twin_noise_within_band():
    db = BenchDB("/nonexistent/never-written")
    db.records = []
    # 20% swing < the 25% floor: honest rerun noise, not a regression
    for i, v in enumerate([100.0, 80.0, 96.0], 1):
        m = extract_measurement(
            _wrapper(i, v, stamp=_stamp("cpu")), f"BENCH_s{i:02d}.json"
        )
        m["class"] = provenance_class(m["provenance"])
        db.records.append(m)
    assert db.report()["sentinel"]["pass"]


@smoke
def test_cpu_twin_never_baselines_tpu():
    # THE isolation contract: a fast CPU-twin record followed by a
    # (legitimately much slower... or faster) TPU record — neither
    # direction may be judged across classes. Same metric, wild swing,
    # zero regressions, because each class has only one point.
    db = BenchDB("/nonexistent/never-written")
    db.records = []
    for i, (v, backend) in enumerate(
        [(100.0, "cpu"), (5000.0, "tpu"), (101.0, "cpu")], 1
    ):
        m = extract_measurement(
            _wrapper(i, v, stamp=_stamp(backend)), f"BENCH_s{i:02d}.json"
        )
        m["class"] = provenance_class(m["provenance"])
        db.records.append(m)
    rep = db.report()
    assert rep["sentinel"]["pass"]
    classes = rep["metrics"]["throughput_sps"]["classes"]
    assert set(classes) == {"cpu_twin", "tpu"}
    assert len(classes["cpu_twin"]["points"]) == 2
    assert len(classes["tpu"]["points"]) == 1
    # and unstamped history is its own island too
    m = extract_measurement(_wrapper(4, 40.0), "BENCH_s04.json")
    m["class"] = provenance_class(m["provenance"])
    db.records.append(m)
    assert db.report()["sentinel"]["pass"]


@smoke
def test_neutral_metrics_never_flag():
    db = BenchDB("/nonexistent/never-written")
    db.records = []
    for i, batch in enumerate([32, 2048], 1):
        db.records.append({
            "source": f"BENCH_s{i:02d}", "order": i, "class": "cpu_twin",
            "metrics": {"batch": batch}, "spread": {}, "provenance": None,
        })
    rep = db.report()
    assert rep["sentinel"]["pass"]
    assert rep["sentinel"]["checked_deltas"] == 0


# ---------------------------------------------------------------- debt

def _ledger():
    return {
        "schema": 1,
        "entries": [
            {"id": "bench-widened", "metric": "widened_gemm_speedup",
             "condition": "backend==tpu", "command": "python bench.py",
             "target": ">= 3x", "status": "open"},
            {"id": "full-wall", "metric": "full_fedavg_tpu:wall_seconds",
             "condition": "backend==tpu",
             "command": "python benchmarks/full_schedule_tpu.py --preset fedavg",
             "target": None, "status": "open"},
        ],
    }


def _record(metrics, stamp):
    return {"source": "x", "order": 1, "metrics": metrics,
            "spread": {}, "provenance": stamp,
            "class": provenance_class(stamp)}


@smoke
def test_tpu_measurement_closes_debt():
    doc = _ledger()
    closed = close_entries(
        doc, _record({"widened_gemm_speedup": 3.4}, _stamp("tpu"))
    )
    assert closed == ["bench-widened"]
    entry = doc["entries"][0]
    assert entry["status"] == "closed"
    assert entry["closed_by"]["class"] == "tpu"
    assert entry["closed_by"]["value"] == 3.4
    assert len(open_entries(doc)) == 1


@smoke
def test_cpu_twin_and_unstamped_never_close_tpu_debt():
    doc = _ledger()
    assert close_entries(
        doc, _record({"widened_gemm_speedup": 9.9}, _stamp("cpu"))
    ) == []
    assert close_entries(
        doc, _record({"widened_gemm_speedup": 9.9}, None)
    ) == []
    assert len(open_entries(doc)) == 2


@smoke
def test_namespaced_metric_matches_base_name():
    doc = _ledger()
    closed = close_entries(
        doc, _record({"full_fedavg_tpu:wall_seconds": 88.0}, _stamp("tpu"))
    )
    assert closed == ["full-wall"]
    assert doc["entries"][1]["closed_by"]["value"] == 88.0


@smoke
def test_emit_script_dedups_commands_and_parses():
    doc = _ledger()
    doc["entries"].append({
        "id": "bench-probe", "metric": "probe_batch_speedup",
        "condition": "backend==tpu", "command": "python bench.py",
        "target": ">= 1.3x", "status": "open",
    })
    script = emit_script(doc)
    # one bench run pays both bench metrics: the command appears ONCE
    assert script.count("python bench.py") == 1
    assert script.splitlines()[0] == "#!/usr/bin/env bash"
    assert "set -e" in script
    assert "probe_batch_speedup" in script and "widened_gemm_speedup" in script


# -------------------------------------------------- verbs, end to end

@smoke
def test_trend_e2e_isolation_and_debt(tmp_path, capsys):
    # the full verb path: CPU-twin wrappers + a committed-style DEBT
    # ledger -> every backend==tpu entry stays open; then one TPU
    # wrapper arrives and pays its entry.
    for i, v in enumerate([100.0, 103.0], 1):
        (tmp_path / f"BENCH_s{i:02d}.json").write_text(
            json.dumps(_wrapper(i, v, stamp=_stamp("cpu"),
                                metric="widened_gemm_speedup"))
        )
    debt_file = tmp_path / "DEBT.json"
    save_debt(str(debt_file), _ledger())
    store = str(tmp_path / "t.jsonl")

    rc = trend_main([str(tmp_path), "--store", store,
                     "--debt", str(debt_file), "--quiet"])
    assert rc == 0
    assert len(open_entries(load_debt(str(debt_file)))) == 2

    (tmp_path / "BENCH_s03.json").write_text(
        json.dumps(_wrapper(3, 3.4, stamp=_stamp("tpu"),
                            metric="widened_gemm_speedup"))
    )
    rc = trend_main([str(tmp_path / "BENCH_s03.json"), "--store", store,
                     "--debt", str(debt_file), "--quiet"])
    assert rc == 0
    doc = load_debt(str(debt_file))
    assert [e["id"] for e in open_entries(doc)] == ["full-wall"]
    assert doc["entries"][0]["closed_by"]["class"] == "tpu"
    capsys.readouterr()


@smoke
def test_trend_verb_flags_regression_exit_code(tmp_path, capsys):
    for i, v in enumerate([100.0, 40.0], 1):
        (tmp_path / f"BENCH_s{i:02d}.json").write_text(
            json.dumps(_wrapper(i, v, stamp=_stamp("cpu")))
        )
    rc = trend_main([str(tmp_path), "--store", str(tmp_path / "t.jsonl"),
                     "--debt", "none", "--quiet",
                     "--md", str(tmp_path / "r.md")])
    assert rc == 1
    assert "REGRESSION" in (tmp_path / "r.md").read_text()
    capsys.readouterr()


@smoke
def test_debt_verb_emits_script(tmp_path, capsys):
    debt_file = tmp_path / "DEBT.json"
    save_debt(str(debt_file), _ledger())
    rc = debt_main(["--file", str(debt_file),
                    "--script", str(tmp_path / "pay.sh"), "--quiet"])
    assert rc == 0
    script = (tmp_path / "pay.sh").read_text()
    assert "full_schedule_tpu.py" in script
    capsys.readouterr()


@smoke
def test_committed_debt_ledger_covers_perf_md(tmp_path):
    # the repo's own DEBT.json: loadable, all-open, backend==tpu
    # conditions, and the emitted script names every owed command class
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    doc = load_debt(os.path.join(root, "DEBT.json"))
    opens = open_entries(doc)
    assert len(opens) >= 6
    assert all("backend==tpu" in e["condition"] for e in opens)
    script = emit_script(doc)
    for needle in (
        "--preset fedavg",
        "--linesearch-probes 4",
        "--exchange-dtype bfloat16",
        "--client-fold vmap",
        "client_scaling_tpu.py",
        "python bench.py",
    ):
        assert needle in script, f"debt script is missing {needle}"


@smoke
def test_rel_noise_floor_matches_committed_history():
    # the committed BENCH_r01-r05 trajectory (mfu dips 12% between
    # rounds) must sit inside the floor — the no-false-positives
    # acceptance criterion pins the constant
    assert REL_NOISE_FLOOR >= 0.15
