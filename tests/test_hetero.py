"""Straggler-adaptive deadline rounds: speed-axis purity, strict plan
loading, ragged step budgets, and the acceptance contract — with one 3x
slow client per round and the deadline at the median client time, the
deadline run finishes within 2 accuracy points of the fault-free run
while the total simulated round wall-clock drops >= 2x vs the stall
path; a deadline no client misses reproduces the lockstep trajectory
BITWISE and the folded dispatch stays `{round: 1, round_init: 1}`.

Smoke tier: plan/loader/injector units. Unmarked (middle) tier: the
tier-1 gates above (fused path — the tier-1 wall sits near its
timeout). Slow tier: the unfused and admm/BB uniform-budget legs, the
all-zero-budget keeps-z invariant, partial-budget fused==unfused,
composition with corruption + trimmed + quarantine, the streaming
path, and crash+resume stream identity with heterogeneity records
(the CLI flavor lives in scripts/ci.sh hetero_smoke).
"""

import json

import numpy as np
import pytest

from federated_pytorch_test_tpu.data import synthetic_cifar
from federated_pytorch_test_tpu.engine import Trainer, get_preset
from federated_pytorch_test_tpu.fault import FaultInjector, FaultPlan

smoke = pytest.mark.smoke
slow = pytest.mark.slow


# ------------------------------------------------------------ speed schedule


@smoke
def test_plan_speed_axis_deterministic_and_separately_folded():
    plan = FaultPlan(seed=3, dropout_p=0.4, corrupt_k=1, slow_k=2,
                     slow_factor=3.0)
    s0 = plan.client_speeds(16, 1, 2, 0)
    s1 = FaultPlan(
        seed=3, dropout_p=0.4, corrupt_k=1, slow_k=2, slow_factor=3.0
    ).client_speeds(16, 1, 2, 0)
    # pure in (seed, cursor): a fresh plan derives the identical speeds
    np.testing.assert_array_equal(s0, s1)
    # slow_k slows EXACTLY k clients, at the configured factor
    assert int((s0 != 1.0).sum()) == 2
    assert set(np.unique(s0)) == {1.0, 3.0}
    # different cursors draw different victims over enough rounds
    assert any(
        not np.array_equal(s0, plan.client_speeds(16, 1, 2, a))
        for a in range(1, 8)
    )
    # separate seed fold: adding the speed axis perturbs neither the
    # dropout masks nor the corruption schedule of the same plan
    bare = FaultPlan(seed=3, dropout_p=0.4, corrupt_k=1)
    np.testing.assert_array_equal(
        plan.participation(16, 0, 1, 2), bare.participation(16, 0, 1, 2)
    )
    np.testing.assert_array_equal(
        plan.corruption(16, 0, 1, 2)[0], bare.corruption(16, 0, 1, 2)[0]
    )
    # probability form
    p = FaultPlan(seed=5, slow_p=0.5)
    hits = np.mean(
        [(p.client_speeds(32, i, 0, 0) != 1.0).mean() for i in range(40)]
    )
    assert 0.4 < hits < 0.6
    # a homogeneous plan emits all-nominal speeds and no hetero flag
    assert not bare.has_heterogeneity
    assert (bare.client_speeds(8, 0, 0, 0) == 1.0).all()


@smoke
def test_plan_loader_rejects_bad_speed_and_deadline_fields():
    plan = FaultPlan(seed=2, slow_k=1, slow_factor=2.5, step_time_s=0.5)
    assert FaultPlan.from_json(plan.to_json()) == plan
    # out-of-range values surface the offending FIELD, not a stack trace
    with pytest.raises(ValueError, match="slow_p"):
        FaultPlan.from_json(json.dumps({"slow_p": 1.5}))
    with pytest.raises(ValueError, match="slow_factor"):
        FaultPlan.from_json(json.dumps({"slow_factor": 0.5}))
    with pytest.raises(ValueError, match="slow_factor"):
        FaultPlan.from_json(json.dumps({"slow_factor": float("inf")}))
    with pytest.raises(ValueError, match="step_time_s"):
        FaultPlan.from_json(json.dumps({"step_time_s": 0.0}))
    with pytest.raises(ValueError, match="slow_k must be >= 0"):
        FaultPlan.from_json(json.dumps({"slow_k": -1}))
    # wrong-typed values fail AT LOAD naming the field
    with pytest.raises(ValueError, match="slow_k must be an int"):
        FaultPlan.from_json(json.dumps({"slow_k": 1.5}))
    with pytest.raises(ValueError, match="step_time_s must be a number"):
        FaultPlan.from_json(json.dumps({"step_time_s": "1.0"}))
    # unknown keys still rejected by name (the new fields joined the set)
    with pytest.raises(ValueError, match=r"slow_factr.*valid fields"):
        FaultPlan.from_json(json.dumps({"slow_factr": 2.0}))


@smoke
def test_plan_inline_slow_spec():
    # int first part = exactly-k, float = per-client probability
    k = FaultPlan.parse("seed=1,slow=2:4")
    assert (k.slow_k, k.slow_p, k.slow_factor) == (2, 0.0, 4.0)
    p = FaultPlan.parse("slow=0.25,step_time=0.5")
    assert (p.slow_k, p.slow_p, p.step_time_s) == (0, 0.25, 0.5)
    assert FaultPlan.parse("slow=1").slow_factor == 3.0  # the default
    with pytest.raises(ValueError, match="slow spec"):
        FaultPlan.parse("slow=1:3:9")
    # round-trips through JSON
    assert FaultPlan.from_json(k.to_json()) == k


@smoke
def test_injector_step_budgets_and_slow_k_guard():
    plan = FaultPlan(seed=1, slow_k=1, slow_factor=3.0, step_time_s=1.0)
    inj = FaultInjector(plan, n_clients=3)
    total = 6
    speeds = inj.speeds_for_round(0, 0, 2)
    assert speeds.shape == (2, 3)
    # deadline = nominal full-work time: fast clients afford every step,
    # the 3x client exactly a third
    budgets = inj.step_budgets_for_round(0, 0, 2, total, deadline_s=6.0)
    assert budgets.shape == (2, 3) and budgets.dtype == np.int32
    np.testing.assert_array_equal(budgets[speeds == 1.0], total)
    np.testing.assert_array_equal(budgets[speeds == 3.0], total // 3)
    # a deadline shorter than one slow step zeroes the slow budget
    b0 = inj.step_budgets_for_round(0, 0, 2, total, deadline_s=2.9)
    np.testing.assert_array_equal(b0[speeds == 3.0], 0)
    # and one every client beats is all-full (the bitwise-identity regime)
    np.testing.assert_array_equal(
        inj.step_budgets_for_round(0, 0, 2, total, deadline_s=1e9),
        np.full((2, 3), total),
    )
    # exact-boundary robustness: a deadline of EXACTLY n steps' time
    # yields budget n even when step_time is a non-representable decimal
    # (0.9/0.3 floats to 2.99999... — a bare floor read 2 and falsely
    # flagged nominal clients as misses)
    from federated_pytorch_test_tpu.fault import step_budgets

    np.testing.assert_array_equal(
        step_budgets(np.ones(4, np.float32), 0.3, 1000, 0.9), [3] * 4
    )
    np.testing.assert_array_equal(
        step_budgets(np.full(1, 3.0, np.float32), 0.1, 100, 0.6), [2]
    )
    # slow_k > K rejected where the plan meets the run, like corrupt_k
    with pytest.raises(ValueError, match="slow_k=5 exceeds n_clients=3"):
        FaultInjector(FaultPlan(slow_k=5), n_clients=3)
    with pytest.raises(ValueError, match="slow_k=5 exceeds n_clients=3"):
        FaultPlan(slow_k=5).client_speeds(3, 0, 0, 0)


@smoke
def test_injected_summary_deadline_rows():
    plan = FaultPlan(
        seed=1, slow_k=1, slow_factor=3.0,
        straggler_p=1.0, straggler_delay_s=10.0,
    )
    inj = FaultInjector(plan, n_clients=3)
    # deadline at the nominal full-work time: exactly the one slow client
    # misses each exchange, and every 10 s stall exceeds (is capped at)
    # the deadline
    s = inj.injected_summary(2, [0], 2, total_steps=4, deadline_s=4.0)
    assert s["deadline_misses"] == 2 * 2 * 1
    assert s["stragglers"] == 4 and s["capped_stalls"] == 4
    # pure in the plan: a second derivation agrees (resume-proof)
    assert inj.injected_summary(2, [0], 2, total_steps=4, deadline_s=4.0) == s
    # no deadline -> no deadline rows (the pre-heterogeneity scoreboard)
    s2 = inj.injected_summary(2, [0], 2)
    assert "deadline_misses" not in s2 and "capped_stalls" not in s2


# ------------------------------------------------ trainer-level (mid tier)


@pytest.fixture(scope="module")
def _src():
    return synthetic_cifar(n_train=240, n_test=60)


@pytest.fixture(scope="module")
def _src_hard():
    # discriminating oracle (data/cifar.py docstring, as in test_robust):
    # label noise + prototype overlap give the accuracy curve shape, so
    # lost local work SHOWS as lost points instead of hiding behind a
    # separable toy task
    return synthetic_cifar(n_train=240, n_test=240, label_noise=0.25,
                           overlap=0.35)


def _tiny(preset="fedavg", **over):
    base = dict(
        batch=40, nloop=1, nadmm=2, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
    )
    base.update(over)
    return get_preset(preset, **base)


def _run(cfg, src):
    tr = Trainer(cfg, verbose=False, source=src)
    tr.run()
    return tr


def _final_flat(tr):
    return np.asarray(tr._fetch(tr.flat))


def _losses(tr):
    return [r["value"] for r in tr.recorder.series["train_loss"]]


@pytest.mark.parametrize(
    "preset,over,fuses",
    [
        # the budgeted tier-1 gate: the FUSED (folded, default) path —
        # the unfused leg and the admm/BB variant ride the slow tier
        # (the tier-1 wall sits near its timeout; unfused==fused ragged
        # equality is also covered by the partial-budget test below)
        ("fedavg", dict(nadmm=2), (True,)),
        pytest.param("fedavg", dict(nadmm=2), (False,), marks=slow),
        pytest.param(
            # nadmm=3 with BB on crosses a due BB step inside the ragged
            # scan — the trickiest consensus state to keep bit-equal
            "admm", dict(nadmm=3, bb_update=True), (True, False),
            marks=slow,
        ),
    ],
)
def test_uniform_budgets_bit_identical(preset, over, fuses, _src):
    """THE bitwise gate: a ragged program under a deadline NO client
    misses (all-full budgets) reproduces the lockstep trajectory bit for
    bit — params and every per-minibatch loss — with the speed axis live
    in the plan."""
    plain = _run(_tiny(preset, **over), _src)
    ragged_cfg = _tiny(
        preset, fault_plan="seed=3,slow=1:3", round_deadline=1e6, **over
    )
    for fuse in fuses:
        tr = _run(ragged_cfg.replace(fuse_rounds=fuse), _src)
        assert tr._ragged_enabled()
        # the deadline bit: budgets recorded all-full, nobody missed
        total = tr._round_total_steps()
        for r in tr.recorder.series["step_budget"]:
            assert r["value"] == [total] * tr.cfg.n_clients
        assert "deadline_miss" not in tr.recorder.series
        np.testing.assert_array_equal(_final_flat(plain), _final_flat(tr))
        assert _losses(plain) == _losses(tr)


@slow
def test_all_zero_budget_exchange_keeps_z(_src):
    """The all-dropped invariant's deadline mirror: a deadline shorter
    than one slow step gives EVERY client budget 0 — no local work, no
    reports, and the exchange keeps z exactly (dual residual 0); the
    round leaves the parameters untouched."""
    cfg = _tiny(
        "fedavg",
        fault_plan="seed=1,slow=1:3",  # heterogeneity live, irrelevant
        round_deadline=0.5,  # < one nominal step (step_time_s = 1.0)
    )
    tr = Trainer(cfg, verbose=False, source=_src)
    entry = _final_flat(tr)
    tr.run()
    np.testing.assert_array_equal(_final_flat(tr), entry)
    assert all(
        r["value"] == 0.0 for r in tr.recorder.series["dual_residual"]
    )
    # every client missed, every exchange; nobody transmitted
    for r in tr.recorder.series["deadline_miss"]:
        assert r["value"]["clients"] == list(range(cfg.n_clients))
    assert all(r["value"] == 0 for r in tr.recorder.series["comm_bytes"])
    assert all(
        r["value"]["survivors"] == 0
        for r in tr.recorder.series["participation"]
    )


@slow
def test_ragged_composes_with_corruption_trimmed_quarantine(_src):
    """Ragged budgets + dropout + in-transit corruption + trimmed-mean +
    auto-quarantine, all in one program: fused == unfused bitwise, and
    the partial updates trip no rollback."""
    cfg = _tiny(
        "admm", nadmm=3, bb_update=True,
        fault_plan="seed=9,dropout=0.2,corrupt=1:gauss:0.5,slow=1:3",
        round_deadline=2.0,  # S=2 at batch 40: slow client budget 0
        robust_agg="trimmed", robust_f=1, quarantine_z=1.0,
        fault_mode="rollback",
    )
    flats = {}
    for fuse in (True, False):
        tr = _run(cfg.replace(fuse_rounds=fuse), _src)
        assert "round_rollback" not in [
            f["value"]["kind"] for f in tr.recorder.series.get("fault", [])
        ]
        flats[fuse] = _final_flat(tr)
    np.testing.assert_array_equal(flats[True], flats[False])


@slow
def test_ragged_fused_equals_unfused_partial_budgets(_src):
    """Real partial budgets (the slow client completes a strict subset
    of its steps): the fused scan's in-carry last-loss and step masks
    replay the unfused schedule bit for bit, per-minibatch losses
    included."""
    cfg = _tiny(
        "fedavg", batch=20, nadmm=2,
        fault_plan="seed=1,slow=1:3",
        round_deadline=4.0,  # S=4 at batch 20: slow budget 1, fast full
    )
    runs = {f: _run(cfg.replace(fuse_rounds=f), _src) for f in (True, False)}
    np.testing.assert_array_equal(
        _final_flat(runs[True]), _final_flat(runs[False])
    )
    assert _losses(runs[True]) == _losses(runs[False])
    for tr in runs.values():
        budgets = [r["value"] for r in tr.recorder.series["step_budget"]]
        assert any(
            0 < min(b) < tr._round_total_steps() for b in budgets
        ), "the probe must actually exercise PARTIAL budgets"


@slow
def test_ragged_streaming_path(_src):
    """Ragged budgets through the host-streaming (unfused, chunked)
    epoch path: a deadline no client misses is bitwise identical to the
    plain streaming run, and a real deadline records partial budgets."""
    base = _tiny(
        "fedavg", batch=20,
        hbm_data_budget_mb=0,  # force streaming (dataset ~1 MB > 0)
        stream_chunk_steps=3,  # 4 minibatches/epoch: chunk of 3 + tail 1
    )
    plain = _run(base, _src)
    full = _run(
        base.replace(fault_plan="seed=1,slow=1:3", round_deadline=1e6), _src
    )
    assert full._stream and not full._fused_enabled()
    np.testing.assert_array_equal(_final_flat(plain), _final_flat(full))
    assert _losses(plain) == _losses(full)
    partial = _run(
        base.replace(fault_plan="seed=1,slow=1:3", round_deadline=4.0), _src
    )
    budgets = [r["value"] for r in partial.recorder.series["step_budget"]]
    assert any(0 < min(b) < partial._round_total_steps() for b in budgets)
    assert "deadline_miss" in partial.recorder.series


# ------------------------------------------------- the acceptance contract


def _accept_cfg(**over):
    # nloop=1, nadmm=2 (not the robust suite's 2x3): the probe's cost
    # rides the tier-1 wall, two exchanges already cross a mask re-draw,
    # and the measured accuracy delta at this size is 0.000 vs the
    # 2-point gate — ample margin
    base = dict(
        batch=20, nloop=1, nadmm=2, max_groups=1, model="net",
        check_results=True, eval_batch=80, synthetic_ok=True,
    )
    base.update(over)
    return get_preset("fedavg", **base)


def _final_acc(tr):
    v = tr.recorder.latest("test_accuracy")
    return float(np.mean(v)) if v is not None else None


def _sim_round_walls(tr):
    return [r["value"]["round"] for r in tr.recorder.series["client_time"]]


def test_deadline_rounds_degrade_gracefully(_src_hard):
    """THE acceptance gate: one 3x slow client per round, deadline at the
    median client time (= the nominal full-work time). The deadline run
    finishes within 2 accuracy points of the fault-free run while the
    total simulated round wall-clock drops >= 2x vs the stall path, and
    the folded dispatch budget holds with the ragged machinery in the
    program."""
    plan = "seed=7,slow=1:3"
    free = _run(_accept_cfg(), _src_hard)
    acc_free = _final_acc(free)

    # the stall path: same fleet, no deadline — the slowest client sets
    # every round's simulated wall (check_results off, one loop, one
    # exchange: only the client_time telemetry is consumed, and slow_k=1
    # makes every round's wall the same 3x draw, so one round prices it)
    stall = _run(
        _accept_cfg(
            fault_plan=plan, nloop=1, nadmm=1, check_results=False
        ),
        _src_hard,
    )
    stall_walls = _sim_round_walls(stall)
    assert stall_walls, "heterogeneous runs must record client_time"

    # deadline = median client time: [3T, T, T] -> median T = 4 steps
    tr = _run(
        _accept_cfg(fault_plan=plan, round_deadline=4.0), _src_hard
    )
    acc = _final_acc(tr)
    assert acc is not None and abs(acc - acc_free) <= 0.02, (acc, acc_free)
    # every round one client missed the deadline with a PARTIAL (not
    # zero) budget — the FedADMM inexact-local-work regime
    for r in tr.recorder.series["step_budget"]:
        assert sorted(r["value"]) == [1, 4, 4]
    assert len(tr.recorder.series["deadline_miss"]) == len(
        tr.recorder.series["step_budget"]
    )
    # simulated wall: stall rounds cost 3T, deadline rounds T
    walls = _sim_round_walls(tr)
    speedup = float(np.mean(stall_walls)) / float(np.mean(walls))
    assert speedup >= 2.0, (stall_walls, walls)
    # the folded one-dispatch round survives the ragged machinery
    for r in tr.recorder.series["dispatch_count"]:
        assert r["value"] == {"round": 1, "round_init": 1, "total": 2}
    # scoreboard rows agree with the recorded misses (pure in the plan)
    inj = tr.injector.injected_summary(
        tr.cfg.nloop, tr.group_order, tr.cfg.nadmm,
        total_steps=tr._round_total_steps(), deadline_s=4.0,
    )
    assert inj["deadline_misses"] == sum(
        len(r["value"]["clients"])
        for r in tr.recorder.series["deadline_miss"]
    )


@slow
def test_crash_resume_stream_identity_with_hetero_records(
    _src, tmp_path, norm_stream
):
    """The stream-identity contract extended to the heterogeneity layer:
    a deadline chaos run killed by a planned crash and resumed yields
    the uninterrupted twin's stream — client_time, step_budget, and
    deadline_miss records included."""
    from federated_pytorch_test_tpu.fault import InjectedCrash

    def cfgh(tag, plan):
        return _tiny(
            nloop=2, save_model=True, check_results=True, eval_batch=30,
            batch=20, fault_plan=plan, round_deadline=4.0,
            checkpoint_dir=str(tmp_path / tag),
            metrics_stream=str(tmp_path / f"{tag}.jsonl"),
        )

    plan = "seed=13,dropout=0.3,slow=1:3"
    tr_a = Trainer(cfgh("a", plan), verbose=False, source=_src)
    tr_a.run()
    for name in ("client_time", "step_budget", "deadline_miss"):
        assert name in tr_a.recorder.series  # the records under test

    gid = tr_a.group_order[0]
    cfg_b = cfgh("b", f"{plan},crash=1:{gid}:0")
    tr_b = Trainer(cfg_b, verbose=False, source=_src)
    with pytest.raises(InjectedCrash):
        tr_b.run()
    tr_b2 = Trainer(cfg_b.replace(resume="auto"), verbose=False, source=_src)
    assert tr_b2._completed_nloops == 1
    tr_b2.run()

    # the shared twin-stream normalizer (tests/conftest.py norm_stream)
    assert norm_stream(tmp_path / "a.jsonl") == norm_stream(tmp_path / "b.jsonl")
    # the scoreboard's deadline rows are resume-proof too
    inj_a = dict(tr_a.recorder.latest("injected_faults"))
    inj_b = dict(tr_b2.recorder.latest("injected_faults"))
    assert inj_a["deadline_misses"] == inj_b["deadline_misses"] > 0
