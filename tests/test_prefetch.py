"""Pipelined cohort prefetch contracts (clients/prefetch.py, docs/SCALE.md).

The perf claim is that loop n+1's cohort gather runs on a background
thread while loop n trains; the CORRECTNESS claim — gated here — is that
nothing observable changes:

* **bitwise fallback** — prefetch-on trajectories (params, store rows,
  every recorded series) equal prefetch-off's bit for bit, with the
  cohort overlap case (consecutive cohorts sharing members, whose rows
  the intervening scatter rewrites) deliberately forced;
* **dispatch budget** — the folded round stays {round: 1, round_init: 1}
  with the prefetch on (gather/adoption are host-side);
* **decision points** — uniform weighting (decision pure in (seed,
  nloop), gather overlaps the whole loop) AND telemetry weighting with
  churn composed (decision pinned at scatter-finalize) both stream
  byte-identically to the synchronous path, so the prefetch knob is
  tag-excluded like the other dispatch-shape knobs;
* **crash mid-prefetch** — a planned crash while a prefetch is in
  flight resumes clean: the resumed stream and store equal an
  uninterrupted twin's (slow tier; tier-2 spill_smoke runs the same
  contract at N=1M through the real CLI).
"""

import json

import numpy as np
import pytest

from federated_pytorch_test_tpu.clients import CohortPrefetcher
from federated_pytorch_test_tpu.data import synthetic_cifar
from federated_pytorch_test_tpu.engine import ExperimentConfig, Trainer, get_preset

SRC = synthetic_cifar(n_train=240, n_test=60)

SERIES = (
    "train_loss", "dual_residual", "primal_residual", "mean_rho",
    "test_accuracy", "cohort", "cohort_weight", "availability",
)


def tiny(preset: str, **over) -> ExperimentConfig:
    base = dict(
        batch=40, nloop=3, max_groups=1, model="net",
        check_results=True, eval_batch=30, synthetic_ok=True,
    )
    base.update(over)
    return get_preset(preset, **base)


def _run(cfg):
    tr = Trainer(cfg, verbose=False, source=SRC)
    rec = tr.run()
    return tr, rec


def _assert_twin(tr_on, rec_on, tr_off, rec_off, n_virtual):
    np.testing.assert_array_equal(
        np.asarray(tr_on.flat), np.asarray(tr_off.flat)
    )
    ids = np.arange(n_virtual)
    assert tr_on.store.fields == tr_off.store.fields
    for name in tr_on.store.fields:
        np.testing.assert_array_equal(
            tr_on.store.gather(name, ids), tr_off.store.gather(name, ids)
        )
    for name in SERIES:
        a = [r["value"] for r in rec_on.series.get(name, [])]
        b = [r["value"] for r in rec_off.series.get(name, [])]
        assert a == b, name


# ------------------------------------------------------------ unit level


@pytest.mark.smoke
def test_prefetcher_match_discard_and_error_fallback():
    def worker(nloop, ids, dirty):
        if nloop == 9:
            raise RuntimeError("boom")
        return {"nloop": int(nloop), "dirty": list(dirty)}

    p = CohortPrefetcher(worker)
    assert p.take(0, [1, 2]) is None  # nothing pending
    p.launch(1, np.array([1, 2]), np.array([2, 3]))
    assert p.in_flight == 1
    # mismatched loop or cohort: discard, caller gathers synchronously
    # (the superseded thread finishes into the void)
    assert p.take(2, np.array([1, 2])) is None
    p.launch(1, np.array([1, 2]), np.array([], np.int64))
    assert p.take(1, np.array([1, 3])) is None
    # the matching take joins the thread and returns its payload
    p.launch(3, np.array([4, 5]), np.array([7], np.int64))
    assert p.take(3, np.array([4, 5])) == {"nloop": 3, "dirty": [7]}
    assert p.in_flight is None
    # a worker exception degrades to None + a warning, never a raise
    p.launch(9, np.array([4, 5]), np.array([], np.int64))
    with pytest.warns(UserWarning, match="boom"):
        assert p.take(9, np.array([4, 5])) is None
    # cancel drops the pending work
    p.launch(4, np.array([6]), np.array([], np.int64))
    p.cancel()
    assert p.take(4, np.array([6])) is None


def test_prefetcher_transient_io_retries_before_degrading():
    # the Failure rule's first half: a worker tripping over transient
    # I/O (flaky disk, chaos-injected ioerror) gets the shared bounded
    # retry and the payload is ADOPTED — no synchronous degrade
    calls = [0]

    def flaky(nloop, ids, dirty):
        calls[0] += 1
        if calls[0] < 3:
            raise OSError("injected storage I/O error")
        return {"ok": calls[0]}

    p = CohortPrefetcher(flaky, io_retries=3)
    p.launch(0, np.array([1]), np.array([], np.int64))
    with pytest.warns(UserWarning, match="retrying"):
        assert p.take(0, np.array([1])) == {"ok": 3}
    assert calls[0] == 3

    # exhausted retries degrade to the synchronous gather (None),
    # naming the chunk file when the error carries one
    from federated_pytorch_test_tpu.fault import IntegrityError

    def rotted(nloop, ids, dirty):
        raise IntegrityError(
            "chunk failed checksum verification",
            path="/store/chunk_000007_v00000042.npz",
        )

    p = CohortPrefetcher(rotted, io_retries=2)
    p.launch(1, np.array([2]), np.array([], np.int64))
    with pytest.warns(UserWarning) as rec:
        assert p.take(1, np.array([2])) is None
    text = "\n".join(str(w.message) for w in rec)
    assert "chunk file: /store/chunk_000007_v00000042.npz" in text
    assert "gathering synchronously" in text

    # deterministic (non-I/O) worker bugs fail FAST: one attempt only
    calls[0] = 0

    def buggy(nloop, ids, dirty):
        calls[0] += 1
        raise TypeError("bug")

    p = CohortPrefetcher(buggy, io_retries=3)
    p.launch(2, np.array([3]), np.array([], np.int64))
    with pytest.warns(UserWarning, match="TypeError"):
        assert p.take(2, np.array([3])) is None
    assert calls[0] == 1


# --------------------------------------------------- engine-level bitwise


def test_prefetch_matches_sync_bitwise_with_overlap():
    """THE fallback gate: prefetch-on == prefetch-off bit for bit —
    params, store rows, every series — with C=4 of N=6, so consecutive
    cohorts ALWAYS share members and the adoption-time overlap patch
    (the rows the intervening scatter rewrote) is exercised every loop.
    The folded dispatch budget survives alongside."""
    common = dict(nadmm=2, virtual_clients=6, cohort=4, data_shards=4)
    tr_on, rec_on = _run(tiny("fedavg", **common))
    tr_off, rec_off = _run(tiny("fedavg", prefetch=False, **common))
    assert tr_on._prefetch is not None and tr_off._prefetch is None
    _assert_twin(tr_on, rec_on, tr_off, rec_off, 6)
    for r in rec_on.series["dispatch_count"]:
        assert r["value"] == {"round": 1, "round_init": 1, "total": 2}, r


@pytest.mark.slow
def test_prefetch_matches_sync_bitwise_admm_lazy_fields():
    """The admm leg: per-group rho fields register at the group's FIRST
    scatter — mid-prefetch for the loop-1 gather, exercising the
    adoption path that gathers fields unknown at launch time."""
    common = dict(
        nadmm=3, bb_update=True, virtual_clients=6, cohort=4,
        data_shards=4,
    )
    tr_on, rec_on = _run(tiny("admm", **common))
    tr_off, rec_off = _run(tiny("admm", prefetch=False, **common))
    _assert_twin(tr_on, rec_on, tr_off, rec_off, 6)
    assert sorted(tr_on._rho_store) == sorted(tr_off._rho_store)
    for g in tr_on._rho_store:
        np.testing.assert_array_equal(
            np.asarray(tr_on._rho_store[g]),
            np.asarray(tr_off._rho_store[g]),
        )


def test_prefetch_stream_identity_telemetry_churn(tmp_path):
    """The pinned decision point: telemetry weighting draws from
    reliability state committed at scatter time, churn restricts the
    pool — with prefetch on, the draw happens at scatter-finalize on
    the main thread and the streamed records (cohort, cohort_weight,
    availability included) are byte-identical to the synchronous
    path's. The prefetch knob is tag-excluded, so the headers match
    too (the splice-accepted rule for dispatch-shape knobs)."""
    streams = {}
    for on in (True, False):
        cfg = tiny(
            "fedavg",
            nloop=2,
            nadmm=2,
            virtual_clients=12,
            cohort=4,
            data_shards=4,
            cohort_weighting="telemetry",
            fault_plan="seed=5,dropout=0.3,churn=0.3:2",
            prefetch=on,
            metrics_stream=str(tmp_path / f"p{int(on)}.jsonl"),
        )
        tr = Trainer(cfg, verbose=False, source=SRC)
        tr.run()
        out = []
        for line in open(cfg.metrics_stream):
            d = json.loads(line)
            d.pop("t", None)
            d.pop("crc", None)  # per-line checksums differ with content
            if d.get("series") == "step_time":
                d["value"] = {
                    k: v for k, v in d["value"].items() if k != "seconds"
                }
            out.append(d)
        streams[on] = out
    assert streams[True] == streams[False]
    # headers included: prefetch must not enter the stream tag
    assert streams[True][0]["event"] == "stream_header"


@pytest.mark.slow
def test_crash_mid_prefetch_resumes_clean(tmp_path):
    """A planned crash at (nloop=0, gid, nadmm=1) fires while loop 1's
    prefetch is in flight (it launched at loop 0's gather). The daemon
    thread dies with the process; the rerun restores the checkpointed
    store, re-gathers cold, and its stream + store equal an
    uninterrupted twin's."""
    from federated_pytorch_test_tpu.fault import InjectedCrash

    def cfg_for(tag, plan):
        return tiny(
            "fedavg",
            nloop=2,
            nadmm=2,
            virtual_clients=32,
            cohort=4,
            data_shards=4,
            cohort_seed=9,
            save_model=True,
            resume="auto",
            store_chunk_clients=8,
            store_resident_chunks=2,
            fault_plan=plan,
            checkpoint_dir=str(tmp_path / f"ckpt_{tag}"),
            metrics_stream=str(tmp_path / f"{tag}.jsonl"),
        )

    cfg = cfg_for("run", "seed=5,dropout=0.3,crash=0:2:1")
    tr = Trainer(cfg, verbose=False, source=SRC)
    with pytest.raises(InjectedCrash):
        tr.run()
    tr2 = Trainer(cfg, verbose=False, source=SRC)
    tr2.run()
    twin = Trainer(
        cfg_for("twin", "seed=5,dropout=0.3"), verbose=False, source=SRC
    )
    twin.run()

    def norm(path):
        out = []
        for line in open(path):
            d = json.loads(line)
            d.pop("t", None)
            d.pop("crc", None)  # per-line checksums differ with content
            if d.get("event") == "stream_header":
                d.pop("tag", None)  # plans differ by the crash point
            if d.get("series") == "step_time":
                d["value"] = {
                    k: v for k, v in d["value"].items() if k != "seconds"
                }
            out.append(d)
        return out

    a = norm(str(tmp_path / "run.jsonl"))
    b = norm(str(tmp_path / "twin.jsonl"))
    assert a == b, f"streams differ: {len(a)} vs {len(b)} records"
    ids = np.arange(32)
    assert tr2.store.fields == twin.store.fields
    for name in tr2.store.fields:
        np.testing.assert_array_equal(
            tr2.store.gather(name, ids), twin.store.gather(name, ids)
        )
