"""Flight recorder + memory/profiler telemetry tests (obs/flight.py,
obs/memory.py, obs/console.py — docs/OBSERVABILITY.md).

Smoke tier: ring-buffer bounds and the one segmentation rule
(`dispatch_count` closes a bucket), incident rising-edge dedupe +
budget, strict bundle-schema validation naming the field, the
`--flight-window`/`--profile-budget` config validation satellites, and
the memory readers' graceful-None contract.

Middle (default) tier: the trainer-level contracts — an anomalous run
dumps a bundle whose in-bundle series match the stream's last W rounds
EXACTLY (the acceptance criterion: the ring is a sink, mirroring what
the JSONL file persists), the `memory`/`incident` series stay OUT of
the stream (process facts — crash+resume twin identity untouched), the
folded dispatch stays `{round: 1, round_init: 1}` with all three
pillars on, the anomaly-armed profiler captures within budget, `watch
--once` renders the run directory, the analysis-only knobs stay out of
the stream tag, and incident determinism on resume: a crashed+resumed
run's bundles equal the uninterrupted twin's (modulo wall-clock/tag/
memory — the stream normalizer's rules), with the dying process's
crash bundle cleaned up at the restore point like the truncated stream
tail it describes.
"""

import copy
import glob
import json
import os

import pytest

from federated_pytorch_test_tpu.obs import (
    MAX_INCIDENTS,
    FlightRecorder,
    validate_incident,
)

smoke = pytest.mark.smoke


# ------------------------------------------------------- ring mechanics


def _round_records(fl, r, *, anomalies=None, group=2):
    """Feed one synthetic round through the sink protocol; returns the
    round's stream-line dicts (what the bucket must hold)."""
    recs = [
        {"series": "train_loss", "t": 0.1 * r, "value": [float(r)],
         "nloop": r, "group": group},
    ]
    if anomalies is not None:
        recs.append(
            {"series": "health", "t": 0.1 * r,
             "value": {"round": r, "anomalies": list(anomalies),
                       "window": {}},
             "nloop": r, "group": group}
        )
    recs.append(
        {"series": "dispatch_count", "t": 0.1 * r,
         "value": {"round": 1, "round_init": 1, "total": 2},
         "nloop": r, "group": group}
    )
    for d in recs:
        d = dict(d)
        fl.record(d.pop("series"), d)
    return recs


@smoke
def test_flight_ring_keeps_last_window_rounds(tmp_path):
    fl = FlightRecorder(window=3, dir=str(tmp_path / "inc"), tag="t")
    fl.open()
    expected = {}
    for r in range(7):
        expected[r] = _round_records(fl, r)
    rounds = fl.rounds()
    assert len(rounds) == 3  # bounded: only the last W closed rounds
    assert [b["nloop"] for b in rounds] == [4, 5, 6]
    assert [b["group"] for b in rounds] == [2, 2, 2]
    assert rounds[-1]["records"] == expected[6]
    # a boundary leaves the open bucket empty; a mid-round record lands
    # in it (what a crash dump captures of the dying round)
    assert fl.partial() == []
    fl.record("train_loss", {"t": 9.9, "value": [7.0], "nloop": 7})
    assert [d["series"] for d in fl.partial()] == ["train_loss"]
    with pytest.raises(ValueError):
        FlightRecorder(window=0, dir=str(tmp_path / "x"))


@smoke
def test_incident_rising_edge_dedupe_and_budget(tmp_path):
    fl = FlightRecorder(window=2, dir=str(tmp_path / "inc"), tag="tag")
    fl.open()

    def round_(r, anomalies):
        _round_records(fl, r, anomalies=anomalies)
        if anomalies:
            return fl.incident(
                anomalies, nloop=r, group=2, round_ix=r, extra={}
            )
        return None

    assert round_(0, []) is None
    p1 = round_(1, ["loss_plateau"])
    assert p1 is not None and os.path.exists(p1)
    # chronic: the SAME kind next round dumps nothing
    assert round_(2, ["loss_plateau"]) is None
    # a NEW kind alongside the chronic one is a fresh incident
    p3 = round_(3, ["loss_plateau", "rollback"])
    assert p3 is not None
    doc = json.load(open(p3))
    validate_incident(doc)
    assert doc["kind"] == "anomaly"
    assert doc["anomalies"] == ["loss_plateau", "rollback"]
    assert doc["tag"] == "tag"
    assert len(doc["rounds"]) == 2  # ring bound, not run length
    # budget: a pathological every-round-new-kind run caps out
    fl2 = FlightRecorder(window=1, dir=str(tmp_path / "inc2"))
    fl2.open()
    dumped = 0
    for r in range(MAX_INCIDENTS + 5):
        _round_records(fl2, r, anomalies=[f"kind{r}"])
        if fl2.incident([f"kind{r}"], nloop=r, group=0, round_ix=r):
            dumped += 1
    assert dumped == MAX_INCIDENTS
    # the crash dump fires once, bypassing the edge rule
    assert fl2.crash_dump(nloop=99, round_ix=99) is not None
    assert fl2.crash_dump(nloop=99, round_ix=99) is None


@smoke
def test_flight_replay_rebuilds_ring_and_edge_state(tmp_path):
    """The resume mechanism: a recorder fed a stream's replayed records
    (JSON round-tripped, like obs/sinks.py hands them over) holds the
    identical ring and re-decides the rising edge identically."""
    live = FlightRecorder(window=2, dir=str(tmp_path / "a"), tag="t")
    live.open()
    stream = []
    for r in range(4):
        stream.extend(
            _round_records(live, r, anomalies=["rollback"] if r >= 2 else [])
        )
    resumed = FlightRecorder(window=2, dir=str(tmp_path / "b"), tag="t")
    resumed.open()
    resumed.replay(
        (d["series"], {k: v for k, v in d.items() if k != "series"})
        for d in (json.loads(json.dumps(x)) for x in stream)
    )
    assert resumed.rounds() == live.rounds()
    # round 3's chronic rollback must dedupe on BOTH (edge state replayed)
    assert live.incident(["rollback"], nloop=3, group=2, round_ix=3) is None
    assert (
        resumed.incident(["rollback"], nloop=3, group=2, round_ix=3) is None
    )


@smoke
def test_incident_schema_validation_names_the_field(tmp_path):
    good = {
        "schema": 1, "kind": "anomaly", "anomalies": ["rollback"],
        "nloop": 0, "group": 2, "round": 0, "tag": "x", "window": 4,
        "rounds": [{"nloop": 0, "group": 2,
                    "records": [{"series": "train_loss", "value": [1.0]}]}],
    }
    validate_incident(good)
    for field, bad_value in (
        ("schema", 99),
        ("kind", "meltdown"),
        ("anomalies", "rollback"),
        ("nloop", -1),
        ("round", True),
        ("window", 0),
        ("tag", None),
        ("group", "g"),
        ("rounds", {}),
    ):
        with pytest.raises(ValueError, match=field):
            validate_incident({**good, field: bad_value})
    with pytest.raises(ValueError, match="rounds"):
        validate_incident({**good, "rounds": [good["rounds"][0]] * 9})
    with pytest.raises(ValueError, match="partial_round"):
        validate_incident({**good, "kind": "crash"})
    validate_incident({**good, "kind": "crash", "partial_round": []})


@smoke
def test_flight_and_profiler_config_validation_names_the_field():
    from federated_pytorch_test_tpu.engine import get_preset

    with pytest.raises(ValueError, match="flight_window"):
        get_preset("fedavg", flight_window=0)
    with pytest.raises(ValueError, match="flight_window"):
        get_preset("fedavg", flight_window=True)
    with pytest.raises(ValueError, match="flight_window"):
        get_preset("fedavg", flight_window=2.5)
    with pytest.raises(ValueError, match="profile_budget"):
        get_preset("fedavg", profile_budget=0)
    with pytest.raises(ValueError, match="profile_budget"):
        get_preset("fedavg", profile_budget=True)
    # a budget without the trigger directory is a mistake, not a no-op
    with pytest.raises(ValueError, match="profile_budget"):
        get_preset("fedavg", profile_budget=5)
    get_preset("fedavg", profile_on_anomaly="/tmp/p", profile_budget=5)
    # the two jax.profiler windows cannot nest
    with pytest.raises(ValueError, match="profile_on_anomaly"):
        get_preset("fedavg", profile_on_anomaly="/tmp/p", profile_dir="/tmp/q")
    # captures are armed by health anomalies: without the monitor the
    # knob could never fire — a config mistake, not a no-op
    with pytest.raises(ValueError, match="profile_on_anomaly"):
        get_preset(
            "fedavg", profile_on_anomaly="/tmp/p", health_monitor=False
        )


@smoke
def test_memory_readers_graceful_and_sane():
    from federated_pytorch_test_tpu.obs import (
        host_rss_bytes,
        host_rss_peak_bytes,
        memory_record,
    )

    rec = memory_record()
    assert set(rec) == {"rss_bytes", "peak_rss_bytes", "devices"}
    # this host is Linux: /proc gives real numbers, peak >= current
    rss, peak = host_rss_bytes(), host_rss_peak_bytes()
    if rss is not None and peak is not None:
        assert 0 < rss <= peak
    # devices: one entry per addressable device, dict or graceful None
    assert len(rec["devices"]) >= 1
    assert all(d is None or isinstance(d, dict) for d in rec["devices"])
    json.dumps(rec)  # the record must be stream-serializable as-is


# ----------------------------------- Trainer integration (middle tier)
# Unmarked: tier-1 over the same tiny model/config family as
# tests/test_health.py so the persistent compile cache amortizes them.


@pytest.fixture(scope="module")
def _src():
    from federated_pytorch_test_tpu.data import synthetic_cifar

    return synthetic_cifar(n_train=240, n_test=60)


def _tiny(**over):
    from federated_pytorch_test_tpu.engine import get_preset

    base = dict(
        batch=40, nloop=2, nadmm=2, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
    )
    base.update(over)
    return get_preset("fedavg", **base)


@pytest.fixture(scope="module")
def incident_run(_src, tmp_path_factory):
    """One anomalous run with all three pillars on: nan_burst corruption
    under the mean combiner + rollback mode → every round rolls back →
    the health engine fires (nonfinite + rollback) → one incident
    bundle (rising edge), one profiler capture (budget 1).

    `jax.profiler.trace` is STUBBED here: a real CPU capture costs ~90 s
    of profiler post-processing — the arming/budget/record logic is what
    the tier-1 gate covers, and the tier-2 incident_smoke (scripts/
    ci.sh) performs one real capture through the CLI."""
    import contextlib
    from unittest import mock

    import jax

    from federated_pytorch_test_tpu.engine import Trainer

    tmp = tmp_path_factory.mktemp("flight")
    cfg = _tiny(
        metrics_stream=str(tmp / "m.jsonl"),
        fault_plan="seed=5,corrupt=1:nan_burst",
        fault_mode="rollback",
        flight_window=4,
        profile_on_anomaly=str(tmp / "prof"),
        profile_budget=1,
    )
    profiled = []

    @contextlib.contextmanager
    def fake_trace(log_dir):
        profiled.append(log_dir)
        yield

    with mock.patch.object(jax.profiler, "trace", fake_trace):
        tr = Trainer(cfg, verbose=False, source=_src)
        tr.run()
    return tr, cfg, tmp, profiled


def _stream_rounds(path):
    """Segment a JSONL stream into rounds on `dispatch_count` — the
    flight ring's one boundary rule."""
    rounds, cur = [], []
    for line in open(path):
        rec = json.loads(line)
        if "series" not in rec:
            continue
        # the line-format CRC is stamped at serialization (v2 stream,
        # fault/io.py) — not a record field the ring ever saw
        rec.pop("crc", None)
        cur.append(rec)
        if rec["series"] == "dispatch_count":
            rounds.append(cur)
            cur = []
    return rounds


def test_incident_bundle_matches_stream_last_w_rounds(incident_run):
    tr, cfg, tmp, _ = incident_run
    bundles = sorted(glob.glob(str(tmp / "m.jsonl.incidents" / "*.json")))
    assert len(bundles) == 1  # chronic anomaly: one rising-edge dump
    doc = json.load(open(bundles[0]))
    validate_incident(doc)
    assert set(doc["anomalies"]) >= {"nonfinite", "rollback"}
    assert doc["tag"] == tr._stream_tag()
    # THE acceptance criterion: in-bundle series == the stream's last W
    # rounds EXACTLY (raw record dicts, wall-clock fields included — the
    # ring is a sink mirroring the very lines the file holds)
    rounds = _stream_rounds(tmp / "m.jsonl")
    held = rounds[: doc["round"] + 1][-doc["window"]:]
    assert [b["records"] for b in doc["rounds"]] == held
    # the bundle is self-contained: plan slice names the corruption
    # victims, the memory snapshot rides along
    assert doc["fault_plan"]["slice"], doc["fault_plan"]
    assert doc["memory"] is not None
    # the recorder's own incident record points at the bundle
    inc = tr.recorder.series["incident"]
    assert len(inc) == 1
    assert inc[0]["value"]["bundle"] == os.path.basename(bundles[0])


def test_memory_and_incident_series_stay_out_of_the_stream(incident_run):
    """The stream=False exclusion satellite: memory numbers and incident
    pointers are process facts — present in the in-memory store, absent
    from the JSONL stream, so the crash+resume twin-identity gates
    (tests/test_obs.py) hold with both pillars on by default."""
    tr, cfg, tmp, _ = incident_run
    mem = tr.recorder.series["memory"]
    assert len(mem) == cfg.nloop  # one record per partition round
    v = mem[-1]["value"]
    assert v["rss_bytes"] is None or v["rss_bytes"] > 0
    streamed = {
        json.loads(line).get("series") for line in open(tmp / "m.jsonl")
    }
    assert "memory" not in streamed
    assert "incident" not in streamed
    assert "profile_capture" not in streamed
    assert "health" in streamed  # the trigger series IS streamed


def test_folded_dispatch_budget_with_all_pillars_on(incident_run):
    """The acceptance dispatch gate: flight ring, memory telemetry, and
    the armed profiler consume already-recorded host data — the folded
    round still dispatches exactly {round, round_init}."""
    tr, _, _, _ = incident_run
    for rec in tr.recorder.series["dispatch_count"]:
        assert rec["value"] == {"round": 1, "round_init": 1, "total": 2}


def test_profiler_armed_and_captured_within_budget(incident_run):
    tr, cfg, tmp, profiled = incident_run
    caps = tr.recorder.series["profile_capture"]
    # anomalies fire every round; budget 1 → exactly one capture, taken
    # the round AFTER the first alert (the stubbed window was entered
    # exactly once — the real-capture leg is tier-2 incident_smoke)
    assert len(caps) == len(profiled) == 1
    assert caps[0]["nloop"] == 1
    assert caps[0]["value"]["dir"] == profiled[0]
    assert os.path.isdir(caps[0]["value"]["dir"])


def test_watch_once_renders_the_run_dir(incident_run, capsys):
    from federated_pytorch_test_tpu.obs.console import watch_main

    _, _, tmp, _ = incident_run
    # a parseable-but-foreign bundle beside the real one must degrade to
    # a label, never crash the dashboard
    foreign = tmp / "m.jsonl.incidents" / "incident-9-9.json"
    foreign.write_text('{"what": "not an incident"}')
    try:
        assert watch_main([str(tmp), "--once"]) == 0
        out = capsys.readouterr().out
    finally:
        os.remove(foreign)
    assert "m  [fedavg:seed0]" in out
    assert "(completed)" in out  # the sidecar's terminal-state stamp
    assert "incident-0-0.json" in out
    assert "incident-9-9.json[?]" in out
    assert "health 2 rounds monitored" in out


def test_flight_knobs_excluded_from_stream_tag(incident_run):
    """Analysis-only knobs splice (the health-knob rule): the tag digest
    reads only (cfg, injector), so a shallow copy with a swapped cfg
    probes it without paying another Trainer build."""
    tr, cfg, _, _ = incident_run
    tag = tr._stream_tag()
    probe = copy.copy(tr)
    probe.cfg = cfg.replace(
        flight_recorder=False, flight_window=16, memory_telemetry=False,
        profile_on_anomaly=None, profile_budget=3,
    )
    assert probe._stream_tag() == tag
    probe.cfg = cfg.replace(nadmm=3)  # a real knob still refuses
    assert probe._stream_tag() != tag


@pytest.mark.slow
def test_incident_determinism_on_resume(_src, tmp_path):
    """Crashed+resumed bundles equal the uninterrupted twin's modulo
    wall-clock fields, the tag, and the memory snapshot (process facts
    — the stream normalizer's exclusions); the dying process's crash
    bundle is cleaned up at the restore point like the truncated
    stream tail it describes.

    Slow tier (3 trainer runs ≈ 17 s — the tier-1 wall sits at the
    870 s gate's edge, the PR-9 re-tiering rule): tier-1 keeps the
    bundle==stream acceptance and the smoke-tier replay/edge-state
    mechanics; the end-to-end crash leg also rides the driver-level
    chaos smokes."""
    from federated_pytorch_test_tpu.engine import Trainer
    from federated_pytorch_test_tpu.fault import InjectedCrash

    common = dict(
        fault_mode="rollback", save_model=True, resume="auto",
        flight_window=4,
    )
    plan = "seed=5,corrupt=1:nan_burst"
    cfg = _tiny(
        metrics_stream=str(tmp_path / "a.jsonl"),
        checkpoint_dir=str(tmp_path / "ckpt"),
        fault_plan=plan + ",crash=1:2:0",
        **common,
    )
    tr = Trainer(cfg, verbose=False, source=_src)
    with pytest.raises(InjectedCrash):
        tr.run()
    tr.close()

    def bundles(stream):
        out = {}
        for p in glob.glob(str(stream) + ".incidents/*.json"):
            out[os.path.basename(p)] = json.load(open(p))
        return out

    crashed = bundles(tmp_path / "a.jsonl")
    # the dying process dumped its crash bundle beside the anomaly one
    assert {d["kind"] for d in crashed.values()} == {"anomaly", "crash"}

    tr2 = Trainer(cfg, verbose=False, source=_src)
    tr2.run()
    tr2.close()
    twin_cfg = _tiny(
        metrics_stream=str(tmp_path / "b.jsonl"),
        checkpoint_dir=str(tmp_path / "ckpt_twin"),
        fault_plan=plan,
        **common,
    )
    tw = Trainer(twin_cfg, verbose=False, source=_src)
    tw.run()
    tw.close()

    def norm(doc):
        doc = dict(doc)
        doc.pop("tag", None)
        doc.pop("memory", None)  # RSS is a process fact
        fp = doc.get("fault_plan")
        if fp:
            # the twins' plans legitimately differ by the crash point
            fp = {k: v for k, v in fp.items() if k == "slice"}
            doc["fault_plan"] = fp

        def scrub(rec):
            rec = {k: v for k, v in rec.items() if k != "t"}
            if rec.get("series") == "step_time" and isinstance(
                rec.get("value"), dict
            ):
                rec["value"] = {
                    k: v for k, v in rec["value"].items() if k != "seconds"
                }
            return rec

        doc["rounds"] = [
            {**b, "records": [scrub(r) for r in b["records"]]}
            for b in doc["rounds"]
        ]
        return doc

    resumed = {k: norm(v) for k, v in bundles(tmp_path / "a.jsonl").items()}
    twin = {k: norm(v) for k, v in bundles(tmp_path / "b.jsonl").items()}
    # resume deleted the stale crash bundle (its loop re-ran); what
    # remains is the identical incident set, bundle for bundle
    assert all(d["kind"] == "anomaly" for d in resumed.values())
    assert resumed == twin
