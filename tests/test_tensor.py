"""Tensor-parallelism tests on the 8-device virtual CPU mesh.

The TP contract (parallel/tensor.py): Megatron-annotated params on a
`model` mesh axis give (a) genuinely distributed parameter storage,
(b) bit-compatible numerics with the replicated model, and (c) XLA-
inserted collectives — no shard_map, no manual psum.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from federated_pytorch_test_tpu.models import TransformerLM
from federated_pytorch_test_tpu.models.base import init_client_params
from federated_pytorch_test_tpu.parallel import CLIENT_AXIS
from federated_pytorch_test_tpu.parallel.tensor import (
    MODEL_AXIS,
    client_model_mesh,
    model_mesh,
    shard_params_tp,
    tp_param_specs,
    validate_tp_divisibility,
)

# spec/guard tests (no jit of the full model) are smoke; the
# compile-heavy numerics tests ride the unmarked middle tier


def _lm():
    return TransformerLM(vocab=64, dim=64, num_heads=4, max_len=32)


def _init(model, seed=0):
    tokens = jnp.zeros((2, 16), jnp.int32)
    return model.init(jax.random.PRNGKey(seed), tokens)["params"], tokens


def _loss(model, params, tokens):
    logits = model.apply({"params": params}, tokens)
    targets = jnp.roll(tokens, -1, axis=1)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, targets[..., None], axis=-1))


@pytest.mark.smoke
def test_tp_specs_follow_megatron_alternation():
    model = _lm()
    params, _ = _init(model)
    specs = tp_param_specs(params)
    blk = specs["block0"]
    # column-parallel: output features split, bias split
    assert tuple(blk["attn"]["qkv"]["kernel"]) == (None, MODEL_AXIS)
    assert tuple(blk["attn"]["qkv"]["bias"]) == (MODEL_AXIS,)
    assert tuple(blk["fc1"]["kernel"]) == (None, MODEL_AXIS)
    # row-parallel: input features split, bias replicated
    assert tuple(blk["attn"]["proj"]["kernel"]) == (MODEL_AXIS, None)
    assert tuple(blk["attn"]["proj"]["bias"]) == ()
    assert tuple(blk["fc2"]["kernel"]) == (MODEL_AXIS, None)
    # embeddings / norms replicated
    assert tuple(specs["embed"]["embedding"]) == ()
    assert tuple(specs["pos_embed"]) == ()
    assert tuple(blk["ln1"]["scale"]) == ()


@pytest.mark.smoke
def test_tp_params_are_distributed():
    model = _lm()
    params, _ = _init(model)
    mesh = model_mesh(4)
    sharded = shard_params_tp(params, mesh)
    qkv = sharded["block0"]["attn"]["qkv"]["kernel"]
    # each device holds 1/4 of the output features
    shapes = {s.data.shape for s in qkv.addressable_shards}
    assert shapes == {(64, 3 * 64 // 4)}
    ln = sharded["block0"]["ln1"]["scale"]
    assert {s.data.shape for s in ln.addressable_shards} == {(64,)}


@pytest.mark.smoke
def test_tp_divisibility_is_validated():
    model = TransformerLM(vocab=64, dim=64, num_heads=4, max_len=32)
    params, _ = _init(model)
    mesh = model_mesh(5)  # 192 qkv outputs % 5 != 0
    with pytest.raises(ValueError, match="not divisible"):
        validate_tp_divisibility(params, tp_param_specs(params), mesh)


@pytest.mark.parametrize("d_model", [2, 4, 8])
def test_tp_forward_and_grads_match_replicated(d_model):
    model = _lm()
    params, tokens = _init(model)
    ref_logits = model.apply({"params": params}, tokens)
    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: _loss(model, p, tokens)
    )(params)

    mesh = model_mesh(d_model)
    sharded = shard_params_tp(params, mesh)
    tp_logits = jax.jit(lambda p, t: model.apply({"params": p}, t))(
        sharded, tokens
    )
    np.testing.assert_allclose(
        np.asarray(tp_logits), np.asarray(ref_logits), atol=2e-5, rtol=1e-5
    )
    tp_loss, tp_grads = jax.jit(
        jax.value_and_grad(lambda p, t: _loss(model, p, t))
    )(sharded, tokens)
    np.testing.assert_allclose(float(tp_loss), float(ref_loss), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=1e-4
        ),
        tp_grads,
        ref_grads,
    )
    # gradient shardings follow the param shardings (the update stays local)
    gq = tp_grads["block0"]["attn"]["qkv"]["kernel"]
    assert {s.data.shape for s in gq.addressable_shards} == {
        (64, 3 * 64 // d_model)
    }


def test_tp_inserts_collectives_where_row_parallel_needs_them():
    model = _lm()
    params, tokens = _init(model)
    mesh = model_mesh(4)

    def fwd_hlo(p):
        return (
            jax.jit(lambda p, t: model.apply({"params": p}, t))
            .lower(p, tokens)
            .compile()
            .as_text()
        )

    # negative control: fully replicated params compile to a forward with
    # no cross-device traffic at all
    from federated_pytorch_test_tpu.parallel import replicate

    hlo_repl = fwd_hlo(replicate(params, mesh))
    assert "all-reduce" not in hlo_repl and "all-gather" not in hlo_repl

    # Megatron shardings: the row-parallel completions (proj/fc2) force
    # cross-device reduces into the same forward (XLA may lower some as
    # reduce-scatter+all-gather pairs)
    hlo_tp = fwd_hlo(shard_params_tp(params, mesh))
    assert "all-reduce" in hlo_tp or "reduce-scatter" in hlo_tp


def test_tp_head_major_qkv_keeps_attention_local():
    # d_model=4 divides num_heads=4: the head-major qkv layout means every
    # device holds whole heads (q,k,v together), so the forward needs NO
    # all-gather — the row-parallel all-reduces are the only collective
    # traffic. This is the discriminating assert: with the old
    # [q-heads, k-heads, v-heads] layout XLA must regather k/v before
    # attention and an all-gather (or all-to-all) appears.
    model = _lm()
    params, tokens = _init(model)
    mesh = model_mesh(4)
    hlo = (
        jax.jit(lambda p, t: model.apply({"params": p}, t))
        .lower(shard_params_tp(params, mesh), tokens)
        .compile()
        .as_text()
    )
    assert "all-reduce" in hlo
    assert "all-to-all" not in hlo
    # no k/v regather. XLA may legitimately lower an all-reduce as a
    # reduce-scatter+all-gather pair, so a bare "no all-gather" would be a
    # latent flake — an UNPAIRED all-gather is what betrays a regather.
    assert hlo.count("all-gather") == hlo.count("reduce-scatter")


def test_tp_small_classifier_head_stays_replicated():
    from federated_pytorch_test_tpu.models import ViT

    model = ViT(num_classes=10, dim=64, num_heads=4)
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3), jnp.float32)
    )["params"]
    mesh = model_mesh(4)
    sharded = shard_params_tp(params, mesh)  # must not raise
    # the 10-way head cannot split by 4 -> replicated whole on every device
    head = sharded["head"]["kernel"]
    assert {s.data.shape for s in head.addressable_shards} == {(64, 10)}
    # while the blocks around it still shard
    fc1 = sharded["block0"]["fc1"]["kernel"]
    assert {s.data.shape for s in fc1.addressable_shards} == {(64, 256 // 4)}


@pytest.mark.smoke
def test_tp_client_axis_mismatch_fails_loudly():
    # K not divisible by the mesh's clients axis cannot be demoted
    # (replicating K would silently turn client parallelism off) — it must
    # be the module's clear error, not a raw device_put failure
    model = _lm()
    stacked = init_client_params(model, 3)["params"]
    with pytest.raises(ValueError, match="clients axis"):
        shard_params_tp(stacked, client_model_mesh(2, 4), client_axis=True)


@pytest.mark.smoke
def test_tp_rejects_mesh_that_shards_nothing():
    model = _lm()
    params, _ = _init(model)
    with pytest.raises(ValueError, match="no parameter axis"):
        shard_params_tp(params, model_mesh(7))


@pytest.mark.smoke
def test_tp_rejects_mesh_without_model_axis():
    from federated_pytorch_test_tpu.parallel import client_mesh

    model = _lm()
    params, _ = _init(model)
    with pytest.raises(ValueError, match="no 'model' axis"):
        shard_params_tp(params, client_mesh(4))


def test_tp_composes_with_client_axis():
    k, d_clients, d_model = 4, 2, 4
    model = _lm()
    stacked = init_client_params(model, k)["params"]
    # differentiate the clients so the test discriminates axis mix-ups
    stacked = jax.tree.map(
        lambda x: x * (1 + 0.1 * jnp.arange(k, dtype=x.dtype).reshape(
            (k,) + (1,) * (x.ndim - 1))),
        stacked,
    )
    tokens = jnp.tile(jnp.arange(16, dtype=jnp.int32)[None], (k, 2, 1))

    ref = jax.vmap(lambda p, t: model.apply({"params": p}, t))(stacked, tokens)

    mesh = client_model_mesh(d_clients, d_model)
    assert mesh.shape[CLIENT_AXIS] == d_clients
    sharded = shard_params_tp(stacked, mesh, client_axis=True)
    out = jax.jit(
        jax.vmap(lambda p, t: model.apply({"params": p}, t))
    )(sharded, tokens)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-5
    )


@pytest.mark.smoke
def test_tp_pair_demotion_keeps_megatron_pairs_consistent():
    # qkv's output axis (24) divides d_model=3 but proj's input axis (8)
    # does not: without pair demotion qkv would shard alone and GSPMD
    # would silently insert resharding between the pair (ADVICE r3) —
    # both sides must come out replicated, with a warning
    from jax.sharding import PartitionSpec as P

    tree = {
        "attn": {
            "qkv": {
                "kernel": np.zeros((8, 24), np.float32),
                "bias": np.zeros((24,), np.float32),
            },
            "proj": {
                "kernel": np.zeros((8, 8), np.float32),
                "bias": np.zeros((8,), np.float32),
            },
        }
    }
    mesh = model_mesh(3)
    with pytest.warns(UserWarning, match="demoting its Megatron partner"):
        specs = tp_param_specs(tree, mesh=mesh)
    assert all(s == P() for s in jax.tree.leaves(
        specs, is_leaf=lambda x: isinstance(x, P)))

    # sanity: with a divisible mesh the same tree shards both sides
    mesh2 = model_mesh(2)
    specs2 = tp_param_specs(tree, mesh=mesh2)
    assert specs2["attn"]["qkv"]["kernel"] == P(None, MODEL_AXIS)
    assert specs2["attn"]["proj"]["kernel"] == P(MODEL_AXIS, None)


def test_tp_specs_handle_list_nested_submodules():
    # list/tuple children flatten to SequenceKey path entries, which have
    # neither .key nor .name: naive name extraction yielded None there and
    # made mixed demoted-scope tuples unsortable (ADVICE r4). Two
    # non-divisible pairs nested under a LIST must both demote, warning,
    # without a TypeError from sorting the demotion set.
    from jax.sharding import PartitionSpec as P

    block = {
        "qkv": {
            "kernel": np.zeros((8, 24), np.float32),
            "bias": np.zeros((24,), np.float32),
        },
        "proj": {
            "kernel": np.zeros((8, 8), np.float32),
            "bias": np.zeros((8,), np.float32),
        },
    }
    tree = {"blocks": [block, block], "head": {
        "kernel": np.zeros((8, 10), np.float32)}}
    mesh = model_mesh(3)
    with pytest.warns(UserWarning, match="demoting its Megatron partner"):
        specs = tp_param_specs(tree, mesh=mesh)
    assert all(s == P() for s in jax.tree.leaves(
        specs, is_leaf=lambda x: isinstance(x, P)))


def test_path_names_cover_all_key_kinds():
    from federated_pytorch_test_tpu.parallel import path_names

    tree = {"a": [np.zeros(1), {"b": np.zeros(1)}]}
    paths = [
        path_names(p)
        for p, _ in jax.tree_util.tree_flatten_with_path(tree)[0]
    ]
    assert paths == [("a", 0), ("a", 1, "b")]
