"""Unit tests for the jittable stochastic L-BFGS.

Strategy per SURVEY.md §4: validate the core numerics on analytic problems
(quadratics with known minimizers, Rosenbrock), the stochastic machinery on
a minibatched least-squares problem, and the NaN guards that the reference
carries (reference src/lbfgsnew.py:542,679-681).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from federated_pytorch_test_tpu.optim import (
    LBFGSConfig,
    lbfgs_init,
    lbfgs_step,
)

pytestmark = pytest.mark.slow  # heavy tier (jit-compile dominated)


def _quadratic(n=12, seed=0):
    rng = np.random.RandomState(seed)
    m = rng.randn(n, n)
    a = m @ m.T + n * np.eye(n)
    b = rng.randn(n)
    x_star = np.linalg.solve(a, b)
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)

    def loss(x):
        return 0.5 * x @ (a @ x) - b @ x

    return loss, jnp.asarray(x_star, jnp.float32)


def test_quadratic_converges_fullbatch_linesearch():
    loss, x_star = _quadratic()
    cfg = LBFGSConfig(max_iter=30, history_size=7, line_search=True)
    x = jnp.zeros_like(x_star)
    state = lbfgs_init(x, cfg)
    for _ in range(3):
        x, state, aux = lbfgs_step(loss, x, state, cfg)
    np.testing.assert_allclose(np.asarray(x), np.asarray(x_star), atol=1e-2)


def test_quadratic_converges_fixed_step():
    # no line search: relies on the 1/sum|g| step seed + curvature updates
    loss, x_star = _quadratic(n=6, seed=1)
    cfg = LBFGSConfig(lr=0.05, max_iter=80, history_size=7, line_search=False)
    x = jnp.zeros_like(x_star)
    state = lbfgs_init(x, cfg)
    for _ in range(5):
        x, state, aux = lbfgs_step(loss, x, state, cfg)
    assert float(loss(x)) < float(loss(jnp.zeros_like(x))) - 0.5 * abs(
        float(loss(x_star))
    ) or float(jnp.linalg.norm(x - x_star)) < 0.1


def test_rosenbrock_descends():
    def loss(x):
        return (1.0 - x[0]) ** 2 + 100.0 * (x[1] - x[0] ** 2) ** 2

    cfg = LBFGSConfig(max_iter=40, history_size=10, line_search=True)
    x = jnp.asarray([-1.2, 1.0], jnp.float32)
    state = lbfgs_init(x, cfg)
    for _ in range(6):
        x, state, aux = lbfgs_step(loss, x, state, cfg)
    assert float(loss(x)) < 1e-2
    np.testing.assert_allclose(np.asarray(x), [1.0, 1.0], atol=0.2)


def test_history_accumulates_and_caps():
    loss, _ = _quadratic(n=8, seed=2)
    cfg = LBFGSConfig(max_iter=4, history_size=3, line_search=True)
    x = jnp.ones((8,), jnp.float32)
    state = lbfgs_init(x, cfg)
    x, state, _ = lbfgs_step(loss, x, state, cfg)
    assert int(state.hist_count) <= 3
    for _ in range(4):
        x, state, _ = lbfgs_step(loss, x, state, cfg)
    assert int(state.hist_count) <= 3
    assert int(state.n_iter) >= 4


def test_batch_mode_least_squares_descends():
    # K minibatches of a linear regression; one lbfgs_step per batch, as in
    # the reference training loops (reference src/federated_trio.py:304-338).
    rng = np.random.RandomState(3)
    w_true = rng.randn(16).astype(np.float32)
    feats = rng.randn(40, 16).astype(np.float32)
    targets = feats @ w_true + 0.01 * rng.randn(40).astype(np.float32)
    batches = [
        (jnp.asarray(feats[i : i + 8]), jnp.asarray(targets[i : i + 8]))
        for i in range(0, 40, 8)
    ]

    cfg = LBFGSConfig(
        max_iter=4, history_size=10, line_search=True, batch_mode=True
    )
    x = jnp.zeros((16,), jnp.float32)
    state = lbfgs_init(x, cfg)

    def make_loss(bf, bt):
        return lambda w: jnp.mean((bf @ w - bt) ** 2)

    full = make_loss(jnp.asarray(feats), jnp.asarray(targets))
    loss_before = float(full(x))
    for epoch in range(3):
        for bf, bt in batches:
            x, state, aux = lbfgs_step(make_loss(bf, bt), x, state, cfg)
    loss_after = float(full(x))
    assert loss_after < 0.1 * loss_before
    assert np.isfinite(np.asarray(x)).all()
    # running inter-batch statistics were populated
    assert float(jnp.sum(jnp.abs(state.running_avg))) > 0.0


def test_step_is_jittable_and_pure():
    loss, _ = _quadratic(n=5, seed=4)
    cfg = LBFGSConfig(max_iter=6, history_size=4, line_search=True)
    x = jnp.ones((5,), jnp.float32)
    state = lbfgs_init(x, cfg)

    stepped = jax.jit(lambda xx, ss: lbfgs_step(loss, xx, ss, cfg))
    x1, s1, a1 = stepped(x, state)
    x2, s2, a2 = stepped(x, state)
    np.testing.assert_array_equal(np.asarray(x1), np.asarray(x2))
    np.testing.assert_array_equal(np.asarray(s1.d), np.asarray(s2.d))


def test_nan_client_isolated_under_vmap():
    # One client with a NaN loss must come out of a vmapped step with its
    # params untouched while healthy siblings still optimize (the batched
    # while body runs for everyone; the NaN client's carry must be frozen).
    loss_good, _ = _quadratic(n=6, seed=9)
    cfg = LBFGSConfig(max_iter=4, history_size=3, line_search=True)
    switches = jnp.asarray([0.0, 1.0], jnp.float32)  # 1.0 => NaN loss

    def one(x, sw):
        def loss(xx):
            return jnp.where(sw > 0.5, jnp.nan, 1.0) * loss_good(xx)

        state = lbfgs_init(x, cfg)
        x1, _, aux = lbfgs_step(loss, x, state, cfg)
        return x1, aux.n_inner

    x0 = jnp.ones((2, 6), jnp.float32)
    x1, n_inner = jax.vmap(one)(x0, switches)
    np.testing.assert_array_equal(np.asarray(x1[1]), np.asarray(x0[1]))
    assert int(n_inner[1]) == 0
    # the healthy client actually moved
    assert float(jnp.linalg.norm(x1[0] - x0[0])) > 1e-3
    assert np.isfinite(np.asarray(x1[0])).all()


def test_nan_gradient_leaves_params_unchanged():
    # reference src/lbfgsnew.py:541-542: a NaN gradient norm at entry skips
    # the whole optimization loop.
    def loss(x):
        return jnp.sum(x) * jnp.nan

    cfg = LBFGSConfig(max_iter=4, line_search=True)
    x = jnp.ones((3,), jnp.float32)
    state = lbfgs_init(x, cfg)
    x1, state1, aux = lbfgs_step(loss, x, state, cfg)
    np.testing.assert_array_equal(np.asarray(x1), np.asarray(x))
    assert int(aux.n_inner) == 0


def test_float64_dtype_generic():
    # dtype genericity: the optimizer must work under jax_enable_x64
    # (float64 problems), not just the f32 default.
    jax.config.update("jax_enable_x64", True)
    try:
        rng = np.random.RandomState(7)
        m = rng.randn(6, 6)
        a = jnp.asarray(m @ m.T + 6 * np.eye(6), jnp.float64)
        b = jnp.asarray(rng.randn(6), jnp.float64)

        def loss(x):
            return 0.5 * x @ (a @ x) - b @ x

        cfg = LBFGSConfig(max_iter=20, history_size=5, line_search=True)
        x = jnp.zeros((6,), jnp.float64)
        state = lbfgs_init(x, cfg)
        for _ in range(2):
            x, state, aux = lbfgs_step(loss, x, state, cfg)
        assert x.dtype == jnp.float64
        x_star = np.linalg.solve(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(np.asarray(x), x_star, atol=1e-5)
    finally:
        jax.config.update("jax_enable_x64", False)


@pytest.mark.parametrize("batch_mode", [False, True])
def test_vmap_matches_sequential(batch_mode):
    # The engine vmaps lbfgs_step over the local client block; a batched
    # while_loop keeps running every element until ALL are done, so the
    # bodies must freeze finished elements. Heterogeneous problems (very different
    # conditioning => different line-search/iteration counts) must match
    # between vmapped and one-at-a-time execution.
    #
    # The full-batch cubic search estimates derivatives by central
    # differences with step 1e-6 (reference src/lbfgsnew.py:209-217), which
    # sits at f32's resolution limit of the loss — batched-vs-unbatched
    # matvec reduction-order noise gets chaotically amplified there. So the
    # cubic variant is checked in f64 where the probe is well-conditioned;
    # the Armijo variant (what every reference driver uses) is checked in
    # f32, the training dtype.
    dtype = jnp.float32 if batch_mode else jnp.float64
    if not batch_mode:
        jax.config.update("jax_enable_x64", True)
    try:
        cfg = LBFGSConfig(
            max_iter=4, history_size=5, line_search=True, batch_mode=batch_mode
        )
        scales = jnp.asarray([1.0, 50.0, 0.02, 7.0], dtype)
        mats = []
        rhs = []
        for s in range(4):
            rng = np.random.RandomState(s)
            m = rng.randn(10, 10)
            mats.append(m @ m.T + (10.0 ** (s - 1)) * np.eye(10))
            rhs.append(rng.randn(10))
        a_all = jnp.asarray(np.stack(mats), dtype)
        b_all = jnp.asarray(np.stack(rhs), dtype)

        def loss_k(x, a, b, scale):
            return scale * (0.5 * x @ (a @ x) - b @ x)

        x0 = jnp.ones((4, 10), dtype)

        def one(x, a, b, scale):
            state = lbfgs_init(x, cfg)
            return lbfgs_step(
                lambda xx: loss_k(xx, a, b, scale), x, state, cfg
            )[0]

        batched = jax.vmap(one)(x0, a_all, b_all, scales)
        for k in range(4):
            xk = one(x0[k], a_all[k], b_all[k], scales[k])
            np.testing.assert_allclose(
                np.asarray(batched[k]), np.asarray(xk), rtol=1e-4, atol=1e-5,
                err_msg=f"client {k} diverges between vmapped and sequential",
            )
    finally:
        if not batch_mode:
            jax.config.update("jax_enable_x64", False)


def test_zero_gradient_early_exit():
    loss, x_star = _quadratic(n=4, seed=5)
    cfg = LBFGSConfig(max_iter=4, line_search=True)
    state = lbfgs_init(x_star, cfg)
    x1, state1, aux = lbfgs_step(loss, x_star, state, cfg)
    np.testing.assert_allclose(np.asarray(x1), np.asarray(x_star), atol=1e-4)


def test_compact_direction_matches_two_loop():
    # The compact representation (optim/compact.py) must produce the SAME
    # direction as the masked two-loop recursion for any history fill level:
    # empty, partial, full, and with a degenerate (zero-curvature) slot.
    from federated_pytorch_test_tpu.optim.compact import compact_direction
    from federated_pytorch_test_tpu.optim.lbfgs import _two_loop_direction

    jax.config.update("jax_enable_x64", True)
    try:
        rng = np.random.RandomState(11)
        m, n = 6, 20
        for count in [0, 1, 3, 6]:
            s_hist = jnp.asarray(rng.randn(m, n))
            y_hist = jnp.asarray(rng.randn(m, n))
            # make curvature products positive for valid slots, as the
            # acceptance guard guarantees (reference src/lbfgsnew.py:596)
            y_hist = y_hist + s_hist  # biases y.s upward
            g = jnp.asarray(rng.randn(n))
            h_diag = jnp.asarray(0.37)
            cnt = jnp.int32(count)
            d_ref = _two_loop_direction(g, s_hist, y_hist, cnt, h_diag)
            d_new = compact_direction(g, s_hist, y_hist, cnt, h_diag)
            np.testing.assert_allclose(
                np.asarray(d_new), np.asarray(d_ref), rtol=1e-9, atol=1e-10,
                err_msg=f"count={count}",
            )
    finally:
        jax.config.update("jax_enable_x64", False)


def test_compact_vs_two_loop_end_to_end():
    # Full optimizer agreement between the two direction backends on a
    # quadratic (f64 so reduction-order noise cannot hide a real bug).
    jax.config.update("jax_enable_x64", True)
    try:
        rng = np.random.RandomState(12)
        mm = rng.randn(8, 8)
        a = jnp.asarray(mm @ mm.T + 8 * np.eye(8))
        b = jnp.asarray(rng.randn(8))

        def loss(x):
            return 0.5 * x @ (a @ x) - b @ x

        xs = {}
        for method in ("compact", "two_loop"):
            cfg = LBFGSConfig(
                max_iter=10, history_size=5, line_search=True, direction=method
            )
            x = jnp.zeros((8,), jnp.float64)
            state = lbfgs_init(x, cfg)
            for _ in range(3):
                x, state, _ = lbfgs_step(loss, x, state, cfg)
            xs[method] = np.asarray(x)
        np.testing.assert_allclose(xs["compact"], xs["two_loop"], rtol=1e-8)
    finally:
        jax.config.update("jax_enable_x64", False)


def test_has_aux_entry_aux_is_the_entry_evaluation():
    # LBFGSAux.entry_aux carries the user aux of the ENTRY evaluation —
    # what callers fall back to when the NaN-step fallback leaves
    # `aux_ok` False. Without it the engine's folded diagnostic forward
    # reported the entry OBJECTIVE (penalties included) on fallback
    # steps while the explicit path reports penalty-free data loss: two
    # meanings in one train_loss series (ISSUE 2 satellite).
    cfg = LBFGSConfig(
        max_iter=3, history_size=4, line_search=True, batch_mode=True
    )

    def loss_aux(x):
        data = jnp.sum((x - 1.0) ** 2)
        penalty = 7.0 + jnp.sum(x**2)  # stands in for elastic-net/ADMM
        return data + penalty, (data, x * 2.0)

    x0 = jnp.asarray(np.r_[0.4, -0.3, 2.0], jnp.float32)
    state = lbfgs_init(x0, cfg)
    x1, _, aux = lbfgs_step(loss_aux, x0, state, cfg, has_aux=True)

    entry_data, entry_extra = aux.entry_aux
    np.testing.assert_allclose(
        float(entry_data), float(jnp.sum((x0 - 1.0) ** 2)), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(entry_extra), np.asarray(x0) * 2.0, rtol=1e-6
    )
    # entry aux is NOT the final-point aux (the step moved), and is NOT
    # the total objective (the penalty stays out of it)
    final_data, _ = aux.aux
    assert bool(aux.aux_ok)
    assert float(final_data) < float(entry_data)
    assert abs(float(entry_data) - float(aux.loss)) > 1.0  # loss includes penalty
