"""Unit tests for the jittable stochastic L-BFGS.

Strategy per SURVEY.md §4: validate the core numerics on analytic problems
(quadratics with known minimizers, Rosenbrock), the stochastic machinery on
a minibatched least-squares problem, and the NaN guards that the reference
carries (reference src/lbfgsnew.py:542,679-681).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from federated_pytorch_test_tpu.optim import (
    LBFGSConfig,
    lbfgs_init,
    lbfgs_step,
)


def _quadratic(n=12, seed=0):
    rng = np.random.RandomState(seed)
    m = rng.randn(n, n)
    a = m @ m.T + n * np.eye(n)
    b = rng.randn(n)
    x_star = np.linalg.solve(a, b)
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)

    def loss(x):
        return 0.5 * x @ (a @ x) - b @ x

    return loss, jnp.asarray(x_star, jnp.float32)


def test_quadratic_converges_fullbatch_linesearch():
    loss, x_star = _quadratic()
    cfg = LBFGSConfig(max_iter=30, history_size=7, line_search=True)
    x = jnp.zeros_like(x_star)
    state = lbfgs_init(x, cfg)
    for _ in range(3):
        x, state, aux = lbfgs_step(loss, x, state, cfg)
    np.testing.assert_allclose(np.asarray(x), np.asarray(x_star), atol=1e-2)


def test_quadratic_converges_fixed_step():
    # no line search: relies on the 1/sum|g| step seed + curvature updates
    loss, x_star = _quadratic(n=6, seed=1)
    cfg = LBFGSConfig(lr=0.05, max_iter=80, history_size=7, line_search=False)
    x = jnp.zeros_like(x_star)
    state = lbfgs_init(x, cfg)
    for _ in range(5):
        x, state, aux = lbfgs_step(loss, x, state, cfg)
    assert float(loss(x)) < float(loss(jnp.zeros_like(x))) - 0.5 * abs(
        float(loss(x_star))
    ) or float(jnp.linalg.norm(x - x_star)) < 0.1


def test_rosenbrock_descends():
    def loss(x):
        return (1.0 - x[0]) ** 2 + 100.0 * (x[1] - x[0] ** 2) ** 2

    cfg = LBFGSConfig(max_iter=40, history_size=10, line_search=True)
    x = jnp.asarray([-1.2, 1.0], jnp.float32)
    state = lbfgs_init(x, cfg)
    for _ in range(6):
        x, state, aux = lbfgs_step(loss, x, state, cfg)
    assert float(loss(x)) < 1e-2
    np.testing.assert_allclose(np.asarray(x), [1.0, 1.0], atol=0.2)


def test_history_accumulates_and_caps():
    loss, _ = _quadratic(n=8, seed=2)
    cfg = LBFGSConfig(max_iter=4, history_size=3, line_search=True)
    x = jnp.ones((8,), jnp.float32)
    state = lbfgs_init(x, cfg)
    x, state, _ = lbfgs_step(loss, x, state, cfg)
    assert int(state.hist_count) <= 3
    for _ in range(4):
        x, state, _ = lbfgs_step(loss, x, state, cfg)
    assert int(state.hist_count) <= 3
    assert int(state.n_iter) >= 4


def test_batch_mode_least_squares_descends():
    # K minibatches of a linear regression; one lbfgs_step per batch, as in
    # the reference training loops (reference src/federated_trio.py:304-338).
    rng = np.random.RandomState(3)
    w_true = rng.randn(16).astype(np.float32)
    feats = rng.randn(40, 16).astype(np.float32)
    targets = feats @ w_true + 0.01 * rng.randn(40).astype(np.float32)
    batches = [
        (jnp.asarray(feats[i : i + 8]), jnp.asarray(targets[i : i + 8]))
        for i in range(0, 40, 8)
    ]

    cfg = LBFGSConfig(
        max_iter=4, history_size=10, line_search=True, batch_mode=True
    )
    x = jnp.zeros((16,), jnp.float32)
    state = lbfgs_init(x, cfg)

    def make_loss(bf, bt):
        return lambda w: jnp.mean((bf @ w - bt) ** 2)

    full = make_loss(jnp.asarray(feats), jnp.asarray(targets))
    loss_before = float(full(x))
    for epoch in range(3):
        for bf, bt in batches:
            x, state, aux = lbfgs_step(make_loss(bf, bt), x, state, cfg)
    loss_after = float(full(x))
    assert loss_after < 0.1 * loss_before
    assert np.isfinite(np.asarray(x)).all()
    # running inter-batch statistics were populated
    assert float(jnp.sum(jnp.abs(state.running_avg))) > 0.0


def test_step_is_jittable_and_pure():
    loss, _ = _quadratic(n=5, seed=4)
    cfg = LBFGSConfig(max_iter=6, history_size=4, line_search=True)
    x = jnp.ones((5,), jnp.float32)
    state = lbfgs_init(x, cfg)

    stepped = jax.jit(lambda xx, ss: lbfgs_step(loss, xx, ss, cfg))
    x1, s1, a1 = stepped(x, state)
    x2, s2, a2 = stepped(x, state)
    np.testing.assert_array_equal(np.asarray(x1), np.asarray(x2))
    np.testing.assert_array_equal(np.asarray(s1.d), np.asarray(s2.d))


def test_nan_gradient_leaves_params_unchanged():
    # reference src/lbfgsnew.py:541-542: a NaN gradient norm at entry skips
    # the whole optimization loop.
    def loss(x):
        return jnp.sum(x) * jnp.nan

    cfg = LBFGSConfig(max_iter=4, line_search=True)
    x = jnp.ones((3,), jnp.float32)
    state = lbfgs_init(x, cfg)
    x1, state1, aux = lbfgs_step(loss, x, state, cfg)
    np.testing.assert_array_equal(np.asarray(x1), np.asarray(x))
    assert int(aux.n_inner) == 0


def test_float64_dtype_generic():
    # dtype genericity: the optimizer must work under jax_enable_x64
    # (float64 problems), not just the f32 default.
    jax.config.update("jax_enable_x64", True)
    try:
        rng = np.random.RandomState(7)
        m = rng.randn(6, 6)
        a = jnp.asarray(m @ m.T + 6 * np.eye(6), jnp.float64)
        b = jnp.asarray(rng.randn(6), jnp.float64)

        def loss(x):
            return 0.5 * x @ (a @ x) - b @ x

        cfg = LBFGSConfig(max_iter=20, history_size=5, line_search=True)
        x = jnp.zeros((6,), jnp.float64)
        state = lbfgs_init(x, cfg)
        for _ in range(2):
            x, state, aux = lbfgs_step(loss, x, state, cfg)
        assert x.dtype == jnp.float64
        x_star = np.linalg.solve(np.asarray(a), np.asarray(b))
        np.testing.assert_allclose(np.asarray(x), x_star, atol=1e-5)
    finally:
        jax.config.update("jax_enable_x64", False)


def test_zero_gradient_early_exit():
    loss, x_star = _quadratic(n=4, seed=5)
    cfg = LBFGSConfig(max_iter=4, line_search=True)
    state = lbfgs_init(x_star, cfg)
    x1, state1, aux = lbfgs_step(loss, x_star, state, cfg)
    np.testing.assert_allclose(np.asarray(x1), np.asarray(x_star), atol=1e-4)
