"""Observability-layer tests (obs/, docs/OBSERVABILITY.md).

Smoke tier: JSONL sink truncation/replay mechanics, comm-ledger
arithmetic against hand-computed bytes, Chrome-trace validity, recorder
envelope/atomic-save.

Middle (default) tier: the trainer-level contracts —

* the acceptance invariant: a run killed by a `FaultPlan` crash point and
  resumed with `resume='auto'` yields a JSONL metric stream identical
  (modulo wall-clock fields) to the same seed run uninterrupted;
* `comm_bytes` equals `group_size_bytes x participating_clients` for
  fedavg AND admm, with and without dropout masks;
* the `dispatch_count` series reproduces the fused-round one-dispatch
  property (tests/test_fused_round.py) as a recorded metric;
* `--trace-out` writes Chrome trace-event JSON with nested
  round/epoch/consensus spans;
* `--diagnostics-every` records `group_distance` matching a numpy
  recomputation.
"""

import json

import numpy as np
import pytest

from federated_pytorch_test_tpu.obs import CommLedger, JsonlSink, TraceRecorder
from federated_pytorch_test_tpu.partition import Partition, Segment
from federated_pytorch_test_tpu.utils import MetricsRecorder

smoke = pytest.mark.smoke

DTYPE_BYTES = 4  # float32 params throughout


# ------------------------------------------------------------ JSONL sink


@smoke
def test_jsonl_sink_commit_resume_truncation(tmp_path):
    p = tmp_path / "m.jsonl"
    sink = JsonlSink(str(p), tag="t1")
    assert sink.open() == []  # fresh stream
    sink.record("a", {"t": 0.1, "value": 1, "nloop": 0})
    sink.commit(0)
    sink.record("a", {"t": 0.2, "value": 2, "nloop": 1})  # uncommitted tail
    sink.close()
    with open(p, "ab") as f:  # torn final line from a crash mid-write
        f.write(b'{"series": "a", "val')

    # resume at loop 1: keep through marker 0, drop the tail + torn line,
    # and hand back the kept records for replay
    s2 = JsonlSink(str(p), tag="t1")
    assert s2.open(resume_nloops=1) == [("a", {"t": 0.1, "value": 1, "nloop": 0})]
    s2.record("a", {"t": 0.5, "value": 9, "nloop": 1})
    s2.commit(1)
    s2.close()
    lines = [json.loads(l) for l in p.read_text().splitlines()]
    assert lines[0]["event"] == "stream_header"
    assert [l["value"] for l in lines if "series" in l] == [1, 9]
    assert [l["nloop"] for l in lines if l.get("event") == "nloop_complete"] == [0, 1]

    # resume at loop 0 keeps the header only (every round will re-run)
    s3 = JsonlSink(str(p), tag="t1")
    assert s3.open(resume_nloops=0) == []
    s3.close()
    lines = [json.loads(l) for l in p.read_text().splitlines()]
    assert len(lines) == 1 and lines[0]["event"] == "stream_header"


@smoke
def test_jsonl_sink_rejects_foreign_or_out_of_step_streams(tmp_path):
    p = tmp_path / "m.jsonl"
    sink = JsonlSink(str(p), tag="exp-a")
    sink.open()
    sink.record("a", {"t": 0.1, "value": 1, "nloop": 0})
    sink.commit(0)
    sink.close()
    # a different experiment writing to the same path must not splice
    s2 = JsonlSink(str(p), tag="exp-b")
    with pytest.warns(UserWarning, match="different experiment"):
        assert s2.open(resume_nloops=1) == []
    s2.close()
    assert json.loads(p.read_text().splitlines()[0])["tag"] == "exp-b"
    # checkpoints ahead of the stream (missing marker): fresh, loudly
    s3 = JsonlSink(str(p), tag="exp-b")
    with pytest.warns(UserWarning, match="no commit marker"):
        assert s3.open(resume_nloops=5) == []
    s3.close()


@smoke
def test_recorder_sink_forwarding_and_stream_opt_out():
    class Capture:
        def __init__(self):
            self.records = []

        def record(self, name, rec):
            self.records.append((name, rec))

        def flush(self):
            pass

        def commit(self, nloop):
            self.records.append(("__commit__", nloop))

        def close(self):
            pass

    rec = MetricsRecorder(verbose=False)
    cap = Capture()
    # replay seeds the series and the poisoned cursor without re-sinking
    replay = [
        ("train_loss", {"t": 0.0, "value": [1.0], "nloop": 0}),
        ("nonfinite_flag", {"t": 0.1, "value": {"series": "train_loss", "nloop": 0}}),
    ]
    rec.add_sink(cap, replay=replay)
    assert rec.series["train_loss"][0]["value"] == [1.0]
    assert rec.first_nonfinite == {"series": "train_loss", "nloop": 0}
    assert cap.records == []
    # live records stream; stream=False ones stay process-local
    rec.log("comm_bytes", 7, nloop=0)
    rec.log("recompile_count", 3, stream=False, nloop=0)
    rec.commit_loop(0)
    assert [r[0] for r in cap.records] == ["comm_bytes", "__commit__"]
    assert "recompile_count" in rec.series


@smoke
def test_recorder_envelope_and_atomic_save(tmp_path):
    rec = MetricsRecorder(verbose=False)
    rec.batch_losses(
        [0.5, float("nan")], nloop=0, group=1, nadmm=2, epoch=0, minibatch=3
    )
    doc = json.loads(rec.to_json())
    # the poisoned-round cursor survives serialization (it used to be
    # dropped: only `series` was dumped)
    assert doc["first_nonfinite"]["series"] == "train_loss"
    assert doc["first_nonfinite"]["nadmm"] == 2
    assert doc["series"]["train_loss"][0]["minibatch"] == 3
    p = tmp_path / "metrics.json"
    rec.save(str(p))
    assert json.loads(p.read_text()) == doc
    # the tmp staging file never survives a successful save
    assert not list(tmp_path.glob("*.tmp"))


# ------------------------------------------------------------ comm ledger


@smoke
def test_comm_ledger_hand_computed_arithmetic():
    part = Partition(groups=((Segment(0, 10),), (Segment(10, 30),)), total=40)
    led = CommLedger(part, n_clients=4, dtype_bytes=4, data_floor_bytes=1000)
    assert led.round_bytes(0, 4) == 10 * 4 * 4
    assert led.round_bytes(1, 3) == 30 * 4 * 3
    assert led.full_round_bytes(2) == 40 * 4 * 2
    assert led.savings_vs_full([0, 1]) == (40 * 2) / (10 + 30)

    rec = MetricsRecorder(verbose=False)
    led.record(rec, 0, 3, nloop=0, nadmm=1)
    r = rec.series["comm_bytes"][0]
    assert r["value"] == 10 * 4 * 3 and r["survivors"] == 3 and r["group"] == 0
    s = led.summary()
    assert s["rounds"] == 1
    assert s["bytes_total"] == 120
    assert s["bytes_total_bidirectional"] == 240
    assert s["bytes_full_exchange"] == 40 * 4 * 3
    assert s["savings_vs_full"] == 4.0
    assert s["vs_data_floor"] == 0.12

    # absorbing replayed records reproduces the totals (resume path)
    led2 = CommLedger(part, 4, dtype_bytes=4, data_floor_bytes=1000)
    led2.absorb(rec.series["comm_bytes"])
    assert led2.summary() == s


# ----------------------------------------------------------- trace export


@smoke
def test_trace_recorder_chrome_format_and_nesting(tmp_path):
    tr = TraceRecorder()
    with tr.span("round", nloop=0, group=2):
        with tr.span("epoch", epoch=0):
            pass
    tr.instant("fault:nonfinite_loss", clients=[1])
    tr.counter("dispatches", {"epoch": 3})
    with pytest.raises(RuntimeError):  # spans survive exceptions
        with tr.span("boom"):
            raise RuntimeError("x")
    path = tr.save(str(tmp_path / "t.json"))
    doc = json.load(open(path))
    assert isinstance(doc["traceEvents"], list)
    evs = {e["name"]: e for e in doc["traceEvents"]}
    assert {"round", "epoch", "boom"} <= set(evs)
    rnd, ep = evs["round"], evs["epoch"]
    assert rnd["ph"] == ep["ph"] == "X"
    # time containment = Perfetto nesting: epoch inside round
    assert rnd["ts"] <= ep["ts"]
    assert rnd["ts"] + rnd["dur"] >= ep["ts"] + ep["dur"]
    assert evs["fault:nonfinite_loss"]["ph"] == "i"
    assert evs["dispatches"]["ph"] == "C"
    assert not list(tmp_path.glob("*.tmp"))


# --------------------------------------------------- roofline telemetry


@smoke
def test_chip_peaks_lookup():
    from federated_pytorch_test_tpu.obs import chip_peaks

    assert chip_peaks("TPU v5 lite") == (197.0, 819.0)
    assert chip_peaks("TPU v4 (something)") == (275.0, 1228.0)
    assert chip_peaks("cpu") == (None, None)


@smoke
def test_lbfgs_round_cost_hand_checked_arithmetic():
    """The analytic cost model's terms, hand-computed: n=1000, m=10,
    4 inner iterations, default func evals (1 + max_iter = 5), one
    client, one step, f32."""
    from federated_pytorch_test_tpu.obs import lbfgs_round_cost

    c = lbfgs_round_cost(
        n_params=1000, history=10, max_iter=4, k_clients=1, steps=1,
    )
    # params: 5 evals x 2n values; history: 4 x (2*10*1000 + 2*1000)
    assert c["hbm_bytes"] == (5 * 2000 + 4 * 22000) * 4
    assert c["flops"] == 4 * 8.0 * 10 * 1000  # BLAS1 only
    assert c["model_flops_included"] is False
    assert c["func_evals_per_step"] == 5

    # the probe-fan amortization: 4 extra probe evals share ONE widened
    # parameter stream at ls_probes=4 (the --linesearch-probes lever)
    seq = lbfgs_round_cost(
        n_params=1000, history=10, max_iter=4, k_clients=1, steps=1,
        func_evals_per_step=9, ls_probes=1,
    )
    fan = lbfgs_round_cost(
        n_params=1000, history=10, max_iter=4, k_clients=1, steps=1,
        func_evals_per_step=9, ls_probes=4,
    )
    assert seq["hbm_bytes"] - fan["hbm_bytes"] == (4 - 1) * 2000 * 4
    # multipliers: steps x nepoch x nadmm x K
    big = lbfgs_round_cost(
        n_params=1000, history=10, max_iter=4, k_clients=3, steps=2,
        nepoch=2, nadmm=5,
    )
    assert big["hbm_bytes"] == c["hbm_bytes"] * 3 * 2 * 2 * 5
    assert big["steps_per_round"] == 60


@smoke
def test_roofline_record_hand_checked():
    from federated_pytorch_test_tpu.obs import roofline_record

    r = roofline_record(
        wall_s=2.0, flops=197e12, hbm_bytes=819e9,
        device_kind="TPU v5 lite",
    )
    # half of each peak in 2 s: 50% MFU, 50% HBM, intensity at the ridge
    assert r["achieved_tflops"] == pytest.approx(98.5)
    assert r["mfu"] == pytest.approx(0.5)
    assert r["achieved_hbm_gbps"] == pytest.approx(409.5)
    assert r["achieved_hbm_frac"] == pytest.approx(0.5)
    assert r["arithmetic_intensity"] == pytest.approx(240.5, abs=0.1)
    assert r["ridge_intensity"] == pytest.approx(240.5, abs=0.1)
    assert r["bound"] == "compute"
    # memory-bound verdict below the ridge
    low = roofline_record(
        wall_s=1.0, flops=1e12, hbm_bytes=819e9, device_kind="TPU v5 lite",
    )
    assert low["bound"] == "memory"
    # unknown chip: achieved rates only, no fractions or verdict
    cpu = roofline_record(wall_s=1.0, flops=1e9, hbm_bytes=1e9,
                          device_kind="cpu")
    assert "mfu" not in cpu and "bound" not in cpu
    assert cpu["arithmetic_intensity"] == 1.0


# ----------------------------------- Trainer integration (middle tier)
# Unmarked (neither smoke nor slow): tier-1 tests over the same tiny
# model/config family as tests/test_fault.py so the persistent compile
# cache amortizes them.


@pytest.fixture(scope="module")
def _src():
    from federated_pytorch_test_tpu.data import synthetic_cifar

    return synthetic_cifar(n_train=240, n_test=60)


def _tiny(preset="fedavg", **over):
    from federated_pytorch_test_tpu.engine import get_preset

    base = dict(
        batch=40, nloop=1, nadmm=2, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
    )
    base.update(over)
    return get_preset(preset, **base)


@pytest.fixture(scope="module")
def fused_run(_src, tmp_path_factory):
    """One fused tiny run with every obs output on, shared by the tests."""
    from federated_pytorch_test_tpu.engine import Trainer

    tmp = tmp_path_factory.mktemp("obs_fused")
    cfg = _tiny(
        metrics_stream=str(tmp / "m.jsonl"),
        trace_out=str(tmp / "t.json"),
        diagnostics_every=1,
    )
    tr = Trainer(cfg, verbose=False, source=_src)
    # AOT-seed the round program: stashes its XLA cost counts so the run
    # ends with a `roofline` record (asserted below; shares this run)
    tr.compile_round(tr.group_order[0])
    tr.run()
    return tr, cfg, tmp


@pytest.fixture(scope="module")
def unfused_run(_src, tmp_path_factory):
    from federated_pytorch_test_tpu.engine import Trainer

    tmp = tmp_path_factory.mktemp("obs_unfused")
    cfg = _tiny(
        fuse_rounds=False, check_results=True, eval_batch=30,
        trace_out=str(tmp / "t.json"),
    )
    tr = Trainer(cfg, verbose=False, source=_src)
    tr.run()
    return tr, cfg, tmp


def test_dispatch_count_series_reproduces_one_dispatch_property(fused_run):
    tr, cfg, _ = fused_run
    recs = tr.recorder.series["dispatch_count"]
    assert len(recs) == cfg.nloop * 1  # one record per partition round
    d = recs[0]["value"]
    # THE fused-round property (tests/test_fused_round.py), as a metric:
    # one round-program dispatch, zero per-epoch/consensus dispatches
    assert d["round"] == 1
    assert "epoch" not in d and "consensus" not in d
    assert d["round_init"] == 1  # the tiny per-round init program
    assert d["diagnostics"] == 1  # the --diagnostics-every sample counts too
    # recompiles recorded (this process compiled the programs it ran)
    rc = tr.recorder.series["recompile_count"]
    assert len(rc) == len(recs) and rc[0]["value"] >= 1


def test_dispatch_count_series_unfused_counts_every_program(unfused_run):
    tr, cfg, _ = unfused_run
    d = tr.recorder.series["dispatch_count"][0]["value"]
    assert "round" not in d
    assert d["epoch"] == cfg.nadmm * cfg.nepoch
    assert d["consensus"] == cfg.nadmm
    assert d["eval"] == cfg.nadmm  # check_results cadence
    assert d["health"] == cfg.nadmm  # per-round param finiteness check


def test_comm_bytes_full_participation_and_stream_content(fused_run):
    tr, cfg, tmp = fused_run
    gid = tr.group_order[0]
    gsize = tr.partition.group_size(gid)
    recs = tr.recorder.series["comm_bytes"]
    assert len(recs) == cfg.nadmm
    for r in recs:  # no fault plan: every client participates
        assert r["value"] == gsize * DTYPE_BYTES * cfg.n_clients
        assert r["survivors"] == cfg.n_clients
    s = tr.recorder.latest("comm_summary")
    assert s["bytes_total"] == sum(r["value"] for r in recs)
    assert s["bytes_full_exchange"] == (
        tr.partition.total * DTYPE_BYTES * cfg.n_clients * cfg.nadmm
    )
    assert s["savings_vs_full"] == round(
        s["bytes_full_exchange"] / s["bytes_total"], 4
    )

    lines = [json.loads(l) for l in open(tmp / "m.jsonl")]
    stream_series = {l["series"] for l in lines if "series" in l}
    assert {"train_loss", "comm_bytes", "dispatch_count", "comm_summary"} <= stream_series
    # recompile counts are process-local facts: never streamed
    assert "recompile_count" not in stream_series
    assert any(l.get("event") == "nloop_complete" for l in lines)


@pytest.mark.parametrize("preset", ["fedavg", "admm"])
def test_comm_bytes_match_hand_computed_under_dropout(_src, preset):
    from federated_pytorch_test_tpu.engine import Trainer
    from federated_pytorch_test_tpu.fault import FaultPlan

    cfg = _tiny(preset, fault_plan="seed=11,dropout=0.4")
    tr = Trainer(cfg, verbose=False, source=_src)
    tr.run()
    gid = tr.group_order[0]
    gsize = tr.partition.group_size(gid)
    plan = FaultPlan.parse("seed=11,dropout=0.4")
    recs = tr.recorder.series["comm_bytes"]
    assert len(recs) == cfg.nadmm
    for a, r in enumerate(recs):
        surv = int(plan.participation(cfg.n_clients, 0, gid, a).sum())
        # the acceptance formula: group_size_bytes x participating clients
        assert r["value"] == gsize * DTYPE_BYTES * surv
        assert r["survivors"] == surv
        assert (r["nloop"], r["group"], r["nadmm"]) == (0, gid, a)
    s = tr.recorder.latest("comm_summary")
    assert s["bytes_total"] == sum(r["value"] for r in recs)
    assert s["bytes_full_exchange"] == sum(
        tr.partition.total * DTYPE_BYTES * r["survivors"] for r in recs
    )


def test_strategy_none_records_no_comm(_src):
    from federated_pytorch_test_tpu.engine import Trainer

    cfg = _tiny("no_consensus", nepoch=2, nadmm=1)
    tr = Trainer(cfg, verbose=False, source=_src)
    tr.run()
    assert "comm_bytes" not in tr.recorder.series
    s = tr.recorder.latest("comm_summary")
    assert s["rounds"] == 0 and s["savings_vs_full"] is None


def test_trace_out_nested_round_epoch_consensus_spans(unfused_run):
    _, _, tmp = unfused_run
    doc = json.load(open(tmp / "t.json"))
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)
    # the eval span is SPLIT (docs/OBSERVABILITY.md): enqueue (the async
    # dispatch, inside the round) vs harvest (the deferred device->host
    # fetch, at the round-boundary flush — outside the round span)
    assert {
        "round", "epoch", "consensus", "eval_enqueue", "eval_harvest"
    } <= set(by_name)

    def inside(inner, outer):
        return (
            outer["ts"] <= inner["ts"]
            and outer["ts"] + outer["dur"] >= inner["ts"] + inner["dur"]
        )

    rnd = by_name["round"][0]
    for name in ("epoch", "consensus", "eval_enqueue"):
        assert all(inside(e, rnd) for e in by_name[name]), name
    # every enqueued eval is harvested, after its enqueue
    assert len(by_name["eval_harvest"]) == len(by_name["eval_enqueue"])
    assert by_name["eval_harvest"][0]["ts"] >= by_name["eval_enqueue"][0]["ts"]
    # span context keys survive into args (greppable in Perfetto)
    assert by_name["epoch"][0]["args"]["nadmm"] == 0


def test_trace_out_fused_round_span(fused_run):
    _, _, tmp = fused_run
    doc = json.load(open(tmp / "t.json"))
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    rnd = next(e for e in evs if e["name"] == "round")
    fr = next(e for e in evs if e["name"] == "fused_round")
    assert rnd["ts"] <= fr["ts"]
    assert rnd["ts"] + rnd["dur"] >= fr["ts"] + fr["dur"]
    # dispatch counters ride along as Chrome counter events
    assert any(e.get("ph") == "C" for e in doc["traceEvents"])


def test_diagnostics_every_matches_numpy_recomputation(fused_run):
    tr, cfg, _ = fused_run
    recs = tr.recorder.series["group_distance"]
    assert len(recs) == cfg.nloop  # one round per loop at cadence 1
    vals = np.asarray(recs[-1]["value"])
    assert vals.shape == (tr.partition.num_groups,)

    flat = np.asarray(tr._fetch(tr.flat), np.float64)
    diff = flat - flat.mean(axis=0)
    expected = []
    for g in range(tr.partition.num_groups):
        mask = np.zeros(flat.shape[1], bool)
        for s in tr.partition.groups[g]:
            mask[s.start : s.start + s.size] = True
        expected.append(np.linalg.norm(diff[:, mask], axis=1).mean())
    np.testing.assert_allclose(vals, expected, rtol=1e-4, atol=1e-6)


def test_compile_round_stashes_cost_and_records_roofline(fused_run):
    """AOT-compiling the round program (the fused_run fixture seeds it)
    stashes its exact XLA FLOP/byte counts; the run then ends with a
    measured `roofline` record over the median fused-round wall —
    process-local (stream=False), like recompile_count."""
    tr, _, tmp = fused_run
    gid = tr.group_order[0]
    assert gid in tr._round_cost
    c = tr._round_cost[gid]
    assert c["flops"] > 0 and c["hbm_bytes"] > 0
    recs = tr.recorder.series["roofline"]
    assert len(recs) == 1 and recs[0]["group"] == gid
    v = recs[0]["value"]
    assert v["source"] == "xla_cost_analysis"
    assert v["wall_s"] > 0
    # XLA's counts over the measured wall: intensity = flops/bytes
    assert v["arithmetic_intensity"] == pytest.approx(
        c["flops"] / c["hbm_bytes"], abs=0.1
    )
    # never streamed: walls are process facts (a resumed run's differ)
    lines = [json.loads(l) for l in open(tmp / "m.jsonl")]
    assert "roofline" not in {l.get("series") for l in lines}


def test_metrics_stream_crash_resume_identical(_src, tmp_path):
    """THE acceptance invariant: a chaos run killed by a planned crash and
    resumed with resume='auto' yields a JSONL stream identical (modulo
    wall-clock fields) to the same seed run uninterrupted."""
    from federated_pytorch_test_tpu.engine import Trainer
    from federated_pytorch_test_tpu.fault import InjectedCrash

    common = dict(nloop=2, save_model=True)
    cfg_a = _tiny(
        checkpoint_dir=str(tmp_path / "a"),
        metrics_stream=str(tmp_path / "a.jsonl"),
        fault_plan="seed=13,dropout=0.3",
        **common,
    )
    tr_a = Trainer(cfg_a, verbose=False, source=_src)
    tr_a.run()

    gid = tr_a.group_order[0]
    cfg_b = _tiny(
        checkpoint_dir=str(tmp_path / "b"),
        metrics_stream=str(tmp_path / "b.jsonl"),
        fault_plan=f"seed=13,dropout=0.3,crash=1:{gid}:0",
        **common,
    )
    tr_b = Trainer(cfg_b, verbose=False, source=_src)
    with pytest.raises(InjectedCrash):
        tr_b.run()
    # the crashed stream holds loop-1 records past the last commit marker
    lines_b = [json.loads(l) for l in open(tmp_path / "b.jsonl")]
    markers = [l for l in lines_b if l.get("event") == "nloop_complete"]
    assert [m["nloop"] for m in markers] == [0]
    assert any(l.get("nloop") == 1 for l in lines_b if "series" in l)

    # fresh-process analogue: resume from the loop-1 checkpoint; the
    # stream truncates its partial loop-1 tail and continues
    tr_b2 = Trainer(cfg_b.replace(resume="auto"), verbose=False, source=_src)
    assert tr_b2._completed_nloops == 1
    tr_b2.run()

    def normalize(path):
        out = []
        for line in open(path):
            d = json.loads(line)
            if d.get("event") == "stream_header":
                d.pop("tag")  # the twins' plans differ by the crash point
            d.pop("t", None)  # wall-clock timestamps
            d.pop("crc", None)  # per-line checksums differ with content
            if d.get("series") == "step_time":
                d["value"] = {
                    k: v for k, v in d["value"].items() if k != "seconds"
                }
            out.append(d)
        return out

    assert normalize(tmp_path / "a.jsonl") == normalize(tmp_path / "b.jsonl")
    # the in-memory store is continuous too: replayed + re-run records
    # reproduce the uninterrupted run's series exactly
    la = [r["value"] for r in tr_a.recorder.series["train_loss"]]
    lb = [r["value"] for r in tr_b2.recorder.series["train_loss"]]
    assert la == lb
    assert (
        tr_a.recorder.latest("comm_summary")
        == tr_b2.recorder.latest("comm_summary")
    )

    # a resume WITHOUT a metric stream still seeds the comm ledger: the
    # skipped loop-0 traffic is recomputed from the pure fault masks
    tr_c = Trainer(
        cfg_b.replace(resume="auto", metrics_stream=None),
        verbose=False,
        source=_src,
    )
    assert tr_c._completed_nloops == 2  # tr_b2 finished the run above
    all_bytes = [r["value"] for r in tr_a.recorder.series["comm_bytes"]]
    s = tr_c._comm.summary()
    assert s["rounds"] == len(all_bytes)
    assert s["bytes_total"] == sum(all_bytes)
    # the stream tag digests the config minus pure output paths: the same
    # experiment with or without a stream shares identity, a different
    # fault plan does not
    assert tr_c._stream_tag() == tr_b2._stream_tag()
    assert tr_a._stream_tag() != tr_b2._stream_tag()
