"""Chaos-harness unit tests (fault/chaos.py): generator validity and
determinism, the FaultPlan serialization round-trip property over
generator draws, the KNOB_DOMAINS error-message meta-test, shrinker
1-minimality, and the repro-bundle format.

Everything here is PURE HOST — the invariant oracle's actual Trainer
runs live in the tier-2 `chaos_smoke` CI leg (scripts/ci.sh), which
also plants a broken combiner and asserts the harness catches, shrinks,
and replays the violation. These tests pin the machinery that leg
depends on, at tier-1 cost.
"""

import dataclasses
import json

import pytest

from federated_pytorch_test_tpu.engine.config import KNOB_DOMAINS, get_preset
from federated_pytorch_test_tpu.fault import (
    AXES,
    KNOB_GROUPS,
    PLAN_DOMAINS,
    ChaosCase,
    ChaosPlanGenerator,
    CrashPoint,
    FaultPlan,
    load_repro_bundle,
    norm_stream_records,
    shrink,
    write_repro_bundle,
)
from federated_pytorch_test_tpu.fault.chaos import AXIS_FIELDS, components

smoke = pytest.mark.smoke

N_DRAWS = 60  # property-test sample size: covers several full rotations


def _valid_config(case: ChaosCase):
    """Build the exact config the oracle would run (fault/chaos.py
    `_build_cfg` semantics minus the run-dir paths)."""
    over = case.config_overrides()
    over.update(
        model="net", batch=40, check_results=False, synthetic_ok=True,
        shuffle_group_order=False, resume="auto",
    )
    over.setdefault("max_groups", 1)
    return get_preset("fedavg", **over)


# ------------------------------------------------------------- generator


@smoke
def test_plan_domains_name_real_fields():
    plan_fields = {f.name for f in dataclasses.fields(FaultPlan)}
    for axis, spec in PLAN_DOMAINS.items():
        assert axis in AXES
        for field in spec:
            assert field in plan_fields, f"{axis} draws unknown {field}"
    # the shrinker's reset map covers every drawn field (plus the
    # structural extras: crashes, the p-knob of k-targeted axes)
    for axis in AXES:
        for field in AXIS_FIELDS[axis]:
            assert field in plan_fields


@smoke
def test_generator_is_pure_in_seed_and_index():
    gen_a, gen_b = ChaosPlanGenerator(seed=7), ChaosPlanGenerator(seed=7)
    for i in range(N_DRAWS):
        assert gen_a.draw(i) == gen_b.draw(i)
    # a different generator seed perturbs the composed cases (the
    # deterministic probes 0-2 are seed-independent by design)
    other = ChaosPlanGenerator(seed=8)
    assert any(other.draw(i) != gen_a.draw(i) for i in range(3, N_DRAWS))


@smoke
def test_generator_draws_valid_configs_by_construction():
    """The tentpole's core claim: every draw passes the strict config
    validators — the fuzzer explores INSIDE the domain table, so a
    violation found by a soak is an engine bug, never a bad draw."""
    gen = ChaosPlanGenerator(seed=0)
    for i in range(N_DRAWS):
        case = gen.draw(i)
        cfg = _valid_config(case)  # raises ValueError on any bad draw
        assert cfg.nloop == case.base["nloop"]
        # validity couplings hold structurally too
        if "churn" in case.axes:
            assert "cohort" in case.knobs
        if "deadline" in case.knobs:
            assert "speed" in case.axes
        if case.plan.corrupt_mode == "nan_burst" and "corruption" in case.axes:
            assert "robust" in case.knobs
            assert "quarantine" not in case.knobs


@smoke
def test_generator_coverage_rotation():
    """Axis i%7 and knob group i%8 are forced into case i: every axis
    and every lattice knob group appears within the first rotation of
    composed cases — a 50-case soak cannot miss one."""
    gen = ChaosPlanGenerator(seed=0)
    axes, groups = set(), set()
    for i in range(3, 3 + max(len(AXES), len(KNOB_GROUPS)) * 2):
        case = gen.draw(i)
        axes |= set(case.axes)
        groups |= set(case.knobs)
    assert axes == set(AXES)
    assert groups == set(KNOB_GROUPS)


@smoke
def test_plan_roundtrip_property_over_generator_draws():
    """FaultPlan serialization round-trip as a property test over the
    fuzzer's own distribution: every drawn plan survives
    to_json -> from_json exactly (the strict loader — unknown keys and
    drifted crash schemas are rejected, not coerced)."""
    gen = ChaosPlanGenerator(seed=3)
    for i in range(N_DRAWS):
        plan = gen.draw(i).plan
        assert FaultPlan.from_json(plan.to_json()) == plan
    # strictness rider: a round-tripped doc with one foreign key fails
    doc = json.loads(gen.draw(5).plan.to_json())
    doc["droput_p"] = 0.5  # the typo from_json exists to catch
    with pytest.raises(ValueError, match="droput_p"):
        FaultPlan.from_json(json.dumps(doc))


@smoke
def test_case_doc_roundtrip():
    gen = ChaosPlanGenerator(seed=1)
    for i in range(0, N_DRAWS, 7):
        case = gen.draw(i)
        again = ChaosCase.from_doc(json.loads(json.dumps(case.to_doc())))
        assert again == case


# ---------------------------------------------------- knob-domain table


@smoke
def test_knob_domains_bad_values_name_the_field():
    """The exported knob-domain meta-test (ISSUE 20 satellite): walk
    engine.KNOB_DOMAINS, inject each entry's out-of-range `bad` value
    into the context its `requires` supplies, and assert the validator
    rejects it with an error NAMING the offending field — the contract
    that makes a fuzzer violation message actionable."""
    for field, spec in KNOB_DOMAINS.items():
        overrides = {**spec["requires"], field: spec["bad"]}
        with pytest.raises(ValueError, match=field):
            get_preset("fedavg", **overrides)


@smoke
def test_knob_domains_table_shape():
    for field, spec in KNOB_DOMAINS.items():
        assert spec["kind"] in ("choice", "int", "float", "flag"), field
        assert "bad" in spec and "requires" in spec, field
        if spec["kind"] == "choice":
            assert spec["bad"] not in spec["choices"], field


# -------------------------------------------------------------- shrinker


def _composed_case() -> ChaosCase:
    """A deliberately over-wide case for shrinker tests."""
    return ChaosCase(
        index=99, gen_seed=0,
        axes=("dropout", "straggler", "crash", "corruption", "speed"),
        plan=FaultPlan(
            seed=9, dropout_p=0.3, straggler_p=0.5, straggler_delay_s=0.002,
            corrupt_k=1, corrupt_mode="scale", corrupt_strength=4.0,
            slow_k=1, slow_factor=2.0, step_time_s=0.001,
            crashes=(CrashPoint(1, 2, 0),),
        ),
        knobs={
            "robust": {"robust_agg": "median", "robust_f": 1},
            "probes": {"linesearch_probes": 2},
        },
        base={"n_clients": 5, "strategy": "fedavg", "nloop": 2, "nadmm": 2},
    )


@smoke
def test_shrink_reaches_one_minimal_fixpoint():
    """Greedy delta-debugging on a stub oracle: the violation holds iff
    the corruption axis AND the robust knob survive. The shrunk case
    must keep exactly those and be 1-minimal — every remaining
    component's removal kills the (stub) violation."""
    test_fn = lambda c: "corruption" in c.axes and "robust" in c.knobs
    shrunk = shrink(_composed_case(), test_fn)
    assert test_fn(shrunk)
    assert "corruption" in shrunk.axes
    assert set(shrunk.knobs) == {"robust"}
    assert not shrunk.plan.crashes
    assert shrunk.base["nloop"] == 1
    assert shrunk.base["n_clients"] == 3
    # axes reduced to the load-bearing one (+ nothing else)
    assert shrunk.axes == ("corruption",)
    # 1-minimality, verified literally: no single further reduction
    # still violates
    for name, reduced in components(shrunk):
        assert not test_fn(reduced), f"{name} was removable"
    # removed axes' plan fields are back at dataclass defaults, so the
    # shrunk plan serializes small and honest
    assert shrunk.plan.dropout_p == 0.0
    assert shrunk.plan.straggler_p == 0.0
    assert shrunk.plan.slow_k == 0


@smoke
def test_shrink_keeps_everything_when_all_load_bearing():
    case = _composed_case()
    everything = (set(case.axes), set(case.knobs), case.base["nloop"])
    test_fn = lambda c: (
        (set(c.axes), set(c.knobs), c.base["nloop"]) == everything
        and bool(c.plan.crashes) and c.base["n_clients"] == 5
    )
    assert shrink(case, test_fn) == case


@smoke
def test_shrink_preserves_validity_couplings():
    """Reductions that would turn an engine-bug repro into a
    self-inflicted invalid config are never offered: the cohort group
    is pinned under churn, the robust defense under nan_burst, and
    removing the speed axis takes the deadline knob with it."""
    churn_case = ChaosCase(
        index=1, gen_seed=0, axes=("crash", "speed", "churn"),
        plan=FaultPlan(
            seed=1, churn_p=0.2, slow_k=1, slow_factor=2.0,
            step_time_s=0.001, crashes=(CrashPoint(1, 2, 0),),
        ),
        knobs={
            "cohort": {"virtual_clients": 8, "cohort": 4,
                       "cohort_weighting": "uniform"},
            "deadline": {"round_deadline": "auto"},
        },
        base={"n_clients": 3, "strategy": "fedavg", "nloop": 2, "nadmm": 2},
    )
    offered = dict(components(churn_case))
    assert "knob:cohort" not in offered  # churn needs the sampler pool
    assert "clients:3" not in offered  # n_clients is dead in cohort mode
    # dropping the speed axis drops the deadline knob with it
    assert "deadline" not in offered["axis:speed"].knobs

    nan_case = ChaosCase(
        index=2, gen_seed=0, axes=("corruption", "crash"),
        plan=FaultPlan(
            seed=2, corrupt_k=1, corrupt_mode="nan_burst",
            crashes=(CrashPoint(1, 2, 0),),
        ),
        knobs={"robust": {"robust_agg": "median", "robust_f": 1}},
        base={"n_clients": 5, "strategy": "fedavg", "nloop": 2, "nadmm": 2},
        tags=("robust_finite",),
    )
    offered = dict(components(nan_case))
    assert "knob:robust" not in offered  # undefended nan_burst is unfair
    # ...but the corruption axis itself may go (taking the tag along)
    assert "robust_finite" not in offered["axis:corruption"].tags


# ---------------------------------------------------------- repro bundle


@smoke
def test_repro_bundle_roundtrip_and_tamper_detection(tmp_path):
    case = _composed_case()
    verdict = {
        "violations": [{"invariant": "robust_finite", "detail": "stub"}],
        "crashes_fired": 1,
    }
    path = str(tmp_path / "repro.json")
    doc = write_repro_bundle(path, case, verdict, str(tmp_path))
    assert doc["chaos_repro"] == 1
    loaded_case, loaded_doc = load_repro_bundle(path)
    assert loaded_case == case
    assert loaded_doc["violations"] == verdict["violations"]
    # a hand-edited bundle fails its crc instead of being trusted
    tampered = json.load(open(path))
    tampered["case"]["base"]["nloop"] = 5
    with open(path, "w") as f:
        json.dump(tampered, f)
    with pytest.raises(ValueError, match="crc"):
        load_repro_bundle(path)
    # a non-bundle is refused by format version, before crc
    with open(path, "w") as f:
        json.dump({"workload": "chaos_soak"}, f)
    with pytest.raises(ValueError, match="not a chaos repro"):
        load_repro_bundle(path)


# ------------------------------------------------------------ normalizer


@smoke
def test_norm_stream_records_drops_wallclock_only(tmp_path):
    """The one-definition normalizer (conftest's `norm_stream` fixture
    delegates here): wall-clock fields, per-line crcs, the header tag,
    and step_time seconds are ignored; everything else must survive."""
    a, b = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    lines = [
        {"event": "stream_header", "tag": "run-a", "schema": 3},
        {"series": "loss", "value": 1.5, "nloop": 0, "t": 1.0, "crc": "xx"},
        {"series": "step_time", "value": {"seconds": 0.5, "steps": 4},
         "t": 2.0},
    ]
    with open(a, "w") as f:
        for d in lines:
            f.write(json.dumps(d) + "\n")
    lines[0]["tag"] = "run-b"
    lines[1]["t"], lines[1]["crc"] = 9.0, "yy"
    lines[2]["value"]["seconds"] = 77.0
    with open(b, "w") as f:
        for d in lines:
            f.write(json.dumps(d) + "\n")
    assert norm_stream_records(a) == norm_stream_records(b)
    # a VALUE divergence is preserved, not normalized away
    lines[1]["value"] = 2.5
    with open(b, "w") as f:
        for d in lines:
            f.write(json.dumps(d) + "\n")
    assert norm_stream_records(a) != norm_stream_records(b)


# ------------------------------------------------------- tolerated aborts


@smoke
def test_injected_storage_error_classifier():
    """The oracle tolerates exactly the shim's own loud failure — an
    OSError with the injected marker and a storage errno — and nothing
    else. A real disk error, a plain crash, or a marker-less OSError
    must still count as a `run_completes` violation."""
    import errno

    from federated_pytorch_test_tpu.fault.chaos import (
        _injected_storage_error,
    )

    yes = [
        OSError(errno.EIO, "injected I/O error writing metrics stream"),
        OSError(errno.EIO, "injected storage I/O error reading /x.npz"),
        OSError(errno.ENOSPC, "injected ENOSPC writing checkpoint"),
    ]
    no = [
        OSError(errno.EIO, "Input/output error"),  # a REAL disk failure
        OSError(errno.ENOENT, "injected ... wrong errno"),
        ValueError("injected I/O error"),  # not an OSError at all
        RuntimeError("boom"),
    ]
    for e in yes:
        assert _injected_storage_error(e), e
    for e in no:
        assert not _injected_storage_error(e), e
