"""In-run health engine tests (obs/health.py, docs/OBSERVABILITY.md).

Smoke tier: P² percentile sketches against exact numpy percentiles on
adversarial sequences (sorted, reversed, constant, heavy-tailed — the
ISSUE-10 coverage list), engine anomaly detection on synthetic record
sequences, and the replay-identity mechanics the crash/resume contract
rides on.

Middle (default) tier: the trainer-level contracts — a `health` record
per partition round with ZERO extra device dispatches (the folded round
stays `{round: 1, round_init: 1}`), the record reaching the JSONL
stream, and the stream-tag hygiene satellite: the analysis-only health
knobs are OUT of the header tag, so a resumed run that flips them still
splices (the splice-ACCEPTED regression beside the refused-splice ones
in tests/test_exchange.py). The full crashed+resumed-equals-twin stream
identity — now including `health` records — stays where it lives:
tests/test_obs.py::test_metrics_stream_crash_resume_identical.
"""

import json

import numpy as np
import pytest

from federated_pytorch_test_tpu.obs import (
    HealthEngine,
    P2Quantile,
    PercentileSketch,
)

smoke = pytest.mark.smoke


# ------------------------------------------------------------ P² sketches


def _adversarial_sequences():
    rng = np.random.default_rng(7)
    return {
        "sorted": np.arange(1.0, 1001.0),
        "reversed": np.arange(1000.0, 0.0, -1.0),
        "constant": np.full(500, 3.25),
        # Pareto(α=2): the heavy tail where naive estimators smear
        "heavy_tailed": rng.pareto(2.0, 2000) + 1.0,
    }


@smoke
@pytest.mark.parametrize("q", [0.5, 0.95, 0.99])
def test_p2_sketch_tracks_numpy_percentiles(q):
    """The P² estimate must land inside the exact value envelope of the
    nearby ranks (±3 percent points) on every adversarial sequence —
    rank-error bounded, the honest accuracy claim for a 5-marker
    sketch."""
    for name, xs in _adversarial_sequences().items():
        p = P2Quantile(q)
        for x in xs:
            p.update(float(x))
        assert p.count == len(xs)
        lo, hi = np.percentile(
            xs, [max(0.0, q - 0.03) * 100, min(1.0, q + 0.03) * 100]
        )
        assert lo <= p.value() <= hi, (name, q, p.value(), (lo, hi))


@smoke
def test_p2_sketch_exact_below_five_and_ignores_nonfinite():
    p = P2Quantile(0.5)
    assert p.value() is None
    for x in (5.0, float("nan"), 1.0, float("inf"), 3.0):
        p.update(x)
    # non-finite observations never enter (a NaN marker would poison
    # every later estimate); <5 observations interpolate exactly
    assert p.count == 3
    assert p.value() == 3.0

    s = PercentileSketch()
    assert s.estimates() is None
    for x in range(1, 101):
        s.update(x)
    est = s.estimates()
    assert est["n"] == 100
    assert est["p50"] == pytest.approx(np.percentile(range(1, 101), 50), rel=0.05)
    assert set(est) == {"p50", "p95", "p99", "n"}


@smoke
def test_p2_rejects_degenerate_quantiles():
    with pytest.raises(ValueError):
        P2Quantile(0.0)
    with pytest.raises(ValueError):
        P2Quantile(1.0)
    with pytest.raises(ValueError):
        HealthEngine(window=0)


# ------------------------------------------------------- engine mechanics


def _round_records(loss, *, norms=None, times=None, extra=()):
    """One synthetic round's streamed records (the engine's input set)."""
    recs = [("train_loss", {"t": 0.0, "value": list(loss), "nloop": 0})]
    if norms is not None:
        recs.append(("update_norm", {"t": 0.0, "value": list(norms)}))
    if times is not None:
        recs.append(("client_time", {"t": 0.0, "value": dict(times)}))
    recs.append(("comm_bytes", {"t": 0.0, "value": 100, "survivors": 3}))
    recs.extend(extra)
    return recs


def _run_round(engine, records):
    for name, rec in records:
        engine.observe(name, rec)
    return engine.round_record()


@smoke
def test_engine_counters_sketches_and_window_rates():
    eng = HealthEngine(window=4)
    val, anomalies = _run_round(
        eng,
        _round_records(
            [1.0, 2.0, float("nan")],
            norms=[0.5, None, 1.5],
            times={"p50": 1.0, "p95": 2.0, "p99": 2.2, "max": 2.5, "round": 2.5},
            extra=[
                ("quarantine", {"t": 0.0, "value": {"clients": [2]}}),
                ("deadline_miss", {"t": 0.0, "value": {"clients": [0, 2]}}),
                ("fault", {"t": 0.0, "value": {"kind": "nonfinite_loss",
                                               "clients": [2]}}),
            ],
        ),
    )
    # 2 deadline-missing clients against an empty window is a spike
    # (the flight recorder's trigger set — obs/flight.py)
    assert anomalies == ["nonfinite", "deadline_miss_spike"]
    w = val["window"]
    assert w["rounds"] == 1
    # 1 NaN loss entry + 1 null norm = 2 non-finite observations
    assert w["nonfinite_rate"] == 2.0
    assert w["fault_rate"] == 1.0
    assert w["quarantine_rate"] == 1.0
    assert w["deadline_miss_rate"] == 2.0
    assert w["loss_mean"] == pytest.approx(1.5)
    assert val["train_loss"]["n"] == 2  # finite entries only
    assert val["update_norm"]["n"] == 2
    # the deadline signal: sketch over per-exchange cross-client p95s
    assert val["client_time"]["n"] == 1
    assert val["round"] == 0


@smoke
def test_engine_loss_explosion_rollback_and_plateau():
    eng = HealthEngine(window=3, explode_factor=10.0)
    for _ in range(3):
        _, an = _run_round(eng, _round_records([1.0, 1.0]))
        assert an == []
    # 100x the windowed median: explosion
    _, an = _run_round(eng, _round_records([100.0, 100.0]))
    assert "loss_explosion" in an
    # a 3-client quarantine against a quiet window is a burst; the SAME
    # chronic count the next rounds is absorbed by the window and stops
    # alerting (spike semantics, not a rate alarm)
    burst = HealthEngine(window=3)
    _, an = _run_round(burst, _round_records([1.0, 1.0]))
    assert an == []
    q = [("quarantine", {"t": 0.0, "value": {"clients": [0, 1, 2]}})]
    _, an = _run_round(burst, _round_records([1.0, 1.0], extra=q))
    assert an == ["quarantine_burst"]
    _, an = _run_round(burst, _round_records([1.0, 1.0], extra=q))
    assert "quarantine_burst" not in an
    # a single flagged client never pages (floor of 2)
    solo = HealthEngine(window=3)
    _, an = _run_round(
        solo,
        _round_records(
            [1.0, 1.0],
            extra=[("quarantine", {"t": 0.0, "value": {"clients": [2]}})],
        ),
    )
    assert an == []
    # a rollback fault flags the round
    _, an = _run_round(
        eng,
        _round_records(
            [1.0, 1.0],
            extra=[("fault", {"t": 0.0,
                              "value": {"kind": "round_rollback",
                                        "clients": []}})],
        ),
    )
    assert "rollback" in an

    flat = HealthEngine(window=3, plateau_rtol=1e-3)
    an_hist = []
    for _ in range(5):
        _, an = _run_round(flat, _round_records([0.7, 0.7]))
        an_hist.append(an)
    # plateau needs the window FULL plus the current round (4 means at
    # window=3), then fires every flat round after
    assert an_hist[:3] == [[], [], []]
    assert all("loss_plateau" in an for an in an_hist[3:])


@smoke
def test_engine_replay_rebuilds_identical_state():
    """The crash/resume mechanism: an engine fed a stream's replayed
    records (JSON round-tripped, health records marking round
    boundaries) continues with records identical to the uninterrupted
    engine's — the health half of the stream-identity contract."""
    rounds = [
        _round_records([2.0 - 0.2 * r, 2.1 - 0.2 * r],
                       norms=[0.1 * (r + 1), 0.2 * (r + 1)])
        for r in range(6)
    ]
    live = HealthEngine(window=3)
    stream, values = [], []
    for recs in rounds:
        stream.extend(recs)
        for name, rec in recs:
            live.observe(name, rec)
        val, _ = live.round_record()
        stream.append(("health", {"t": 0.0, "value": val}))
        values.append(val)

    # cut after round 4's health record, JSON round-trip like the sink
    cut = [i for i, (n, _) in enumerate(stream) if n == "health"][3] + 1
    replayed = [
        (n, json.loads(json.dumps(r))) for n, r in stream[:cut]
    ]
    resumed = HealthEngine(window=3)
    resumed.replay(replayed)
    assert resumed.rounds == 4
    for r in range(4, 6):
        for name, rec in rounds[r]:
            resumed.observe(name, json.loads(json.dumps(rec)))
        val, _ = resumed.round_record()
        assert val == values[r], r


# ----------------------------------- Trainer integration (middle tier)
# Unmarked: tier-1 over the same tiny model/config family as
# tests/test_obs.py so the persistent compile cache amortizes them.


@pytest.fixture(scope="module")
def _src():
    from federated_pytorch_test_tpu.data import synthetic_cifar

    return synthetic_cifar(n_train=240, n_test=60)


def _tiny(**over):
    from federated_pytorch_test_tpu.engine import get_preset

    base = dict(
        batch=40, nloop=2, nadmm=2, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
    )
    base.update(over)
    return get_preset("fedavg", **base)


@pytest.fixture(scope="module")
def health_run(_src, tmp_path_factory):
    from federated_pytorch_test_tpu.engine import Trainer

    tmp = tmp_path_factory.mktemp("health")
    cfg = _tiny(
        metrics_stream=str(tmp / "m.jsonl"),
        checkpoint_dir=str(tmp / "ckpt"),
        save_model=True,  # the splice test below resumes this run
    )
    tr = Trainer(cfg, verbose=False, source=_src)
    tr.run()
    return tr, cfg, tmp


def test_health_series_one_record_per_round_zero_dispatches(health_run):
    tr, cfg, _ = health_run
    recs = tr.recorder.series["health"]
    # one record per partition round, cursor-stamped
    assert len(recs) == cfg.nloop * 1
    assert [(r["nloop"], r["group"]) for r in recs] == [
        (n, tr.group_order[0]) for n in range(cfg.nloop)
    ]
    # the ISSUE-10 dispatch gate: sketches/monitor add NO device work —
    # the folded round still dispatches exactly {round, round_init}
    d = tr.recorder.series["dispatch_count"][0]["value"]
    assert d == {"round": 1, "round_init": 1, "total": 2}
    v = recs[-1]["value"]
    assert v["anomalies"] == []  # healthy run
    assert v["train_loss"]["n"] > 0
    assert v["window"]["rounds"] == min(cfg.nloop, 8 + 1)
    # loss sketch saw every finite per-client loss entry
    n_entries = sum(
        len(r["value"]) for r in tr.recorder.series["train_loss"]
    )
    assert v["train_loss"]["n"] == n_entries


def test_health_records_reach_the_stream(health_run):
    _, _, tmp = health_run
    lines = [json.loads(l) for l in open(tmp / "m.jsonl")]
    health = [l for l in lines if l.get("series") == "health"]
    assert len(health) == 2
    # streamed records carry the full structured value
    assert {"round", "anomalies", "window", "train_loss"} <= set(
        health[-1]["value"]
    )


def test_health_splice_accepted_on_resumed_stream(_src, health_run, tmp_path):
    """The splice-ACCEPTED regression (ISSUE-10 satellite): the
    analysis-only health knobs must not change the stream identity — a
    resumed run may flip them and still splice (no fresh-stream
    warning, the replayed records rebuilding the engine's state),
    exactly like the dispatch-shape fold/async knobs and unlike the
    trajectory-changing probes/codec knobs whose refused-splice twins
    live in tests/test_exchange.py."""
    import shutil
    import warnings as _warnings

    from federated_pytorch_test_tpu.engine import Trainer

    tr, cfg, tmp = health_run
    tag = tr._stream_tag()
    n_health = len(tr.recorder.series["health"])
    # resume the finished run on a COPY of its stream (opening truncates
    # the post-marker tail), with BOTH health knobs flipped
    stream_copy = str(tmp_path / "m.jsonl")
    shutil.copy(tmp / "m.jsonl", stream_copy)
    with _warnings.catch_warnings(record=True) as caught:
        _warnings.simplefilter("always")
        tr2 = Trainer(
            cfg.replace(
                resume="auto", metrics_stream=stream_copy, health_window=32
            ),
            verbose=False,
            source=_src,
        )
    refusals = [
        w for w in caught
        if "different experiment" in str(w.message)
        or "no commit marker" in str(w.message)
    ]
    assert not refusals, [str(w.message) for w in refusals]
    assert tr2._completed_nloops == cfg.nloop
    # tag identity is the splice mechanism: health knobs are OUT. The
    # digest reads only (cfg, injector), so a shallow copy with a
    # swapped cfg probes it without paying another Trainer build
    # (tier-1 wall budget — the suite sits near the 870 s gate)
    assert tr2._stream_tag() == tag
    import copy

    probe = copy.copy(tr)
    probe.cfg = cfg.replace(health_monitor=False)
    assert probe._stream_tag() == tag
    # a real experiment knob still refuses (the PR-3 contract intact)
    probe.cfg = cfg.replace(nadmm=3)
    assert probe._stream_tag() != tag
    # the replayed stream seeded both the series and the engine
    assert len(tr2.recorder.series["health"]) == n_health
    assert tr2._health_engine.rounds == n_health
    assert tr2._health_engine.loss.count == tr._health_engine.loss.count
