"""Codec-zoo + adaptive layer-group scheduler tests (exchange/,
docs/PERF.md §Codec zoo).

Smoke tier: codec protocol properties for every zoo member —
`decode(encode(x))` error bounds vs exact math, non-finite preservation
(liars stay visible), exact `bytes_on_wire` arithmetic, the identity
short-circuit — plus strict config/CLI validation naming the field and
the GroupScheduler's policy units (warmup order, drift argmax, skip
rule, replay parity).

Middle (default) tier: the trainer-level contracts —

* `comm_bytes` under topk equals `kept * 8 * survivors` with survivors
  from the PURE plan masks, hand-checked at two survivor counts (the
  bf16 test's pattern; the q8 formula is hand-checked in the same run
  family's smoke assertions and ci.sh codec_smoke);
* the PR-5 corruption acceptance gate (1 liar/round, trimmed(1),
  quarantine) holds under the top-k codec with error feedback AND the
  adaptive scheduler in the program — zero rollbacks, within 2 points
  of fault-free, folded dispatch {round: 1, round_init: 1};
* every zoo/scheduler knob is trajectory-changing: stream-tag member,
  refused splice (mirroring the PR-9 bf16 regressions).

Slow tier: the q8 mirror of the robust gate, fused==unfused bitwise
with topk+EF in the program, EF persistence through the ClientStore,
and crash+resume stream identity with `group_schedule` /
`group_distance` records. Tier-2 `codec_smoke` (scripts/ci.sh) drives
the 3-codec sweep + frontier acceptance through the real CLI.
"""

import json
import math

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from federated_pytorch_test_tpu.data import synthetic_cifar
from federated_pytorch_test_tpu.engine import (
    ExperimentConfig,
    Trainer,
    get_preset,
)
from federated_pytorch_test_tpu.exchange import (
    EXCHANGE_CODECS,
    GROUP_SCHEDULES,
    GroupScheduler,
    QuantCodec,
    TopKCodec,
    make_codec,
)
from federated_pytorch_test_tpu.obs import JsonlSink

smoke = pytest.mark.smoke


# --------------------------------------------------- codec property units


@smoke
def test_topk_roundtrip_matches_exact_selection():
    """decode(encode(x)) keeps EXACTLY the k largest magnitudes (bit
    for bit) and zeros the rest — vs the numpy oracle, 1-D and 2-D."""
    c = make_codec(exchange_codec="topk", topk_fraction=0.25)
    assert not c.is_identity and not c.flat_wire
    rng = np.random.RandomState(0)
    for shape in ((16,), (3, 40)):
        x = jnp.asarray(rng.randn(*shape).astype(np.float32))
        r = np.asarray(c.roundtrip(x))
        xn = np.asarray(x).reshape(-1, shape[-1])
        k = c.kept(shape[-1])
        for row, rr in zip(xn, r.reshape(-1, shape[-1])):
            idx = np.argsort(-np.abs(row), kind="stable")[:k]
            exp = np.zeros_like(row)
            exp[idx] = row[idx]
            np.testing.assert_array_equal(rr, exp)
    # error bound: dropping the smallest magnitudes never increases the
    # per-coordinate error past the dropped value itself
    x = jnp.asarray(rng.randn(100).astype(np.float32))
    r = np.asarray(c.roundtrip(x))
    err = np.abs(r - np.asarray(x))
    kept_min = np.sort(np.abs(np.asarray(x)))[::-1][c.kept(100) - 1]
    assert err.max() <= kept_min + 1e-12


@smoke
def test_topk_kept_arithmetic_and_nonfinite_visibility():
    c = make_codec(exchange_codec="topk", topk_fraction=0.1)
    assert c.kept(100) == 10 and c.kept(101) == 11 and c.kept(1) == 1
    assert TopKCodec(fraction=1.0).kept(7) == 7
    # a nan_burst liar's non-finite values rank ABOVE every finite
    # magnitude: the corruption always reaches the wire
    row = jnp.asarray([1e6, -1e5, np.nan, np.inf, 0.1] + [0.01] * 15,
                      jnp.float32)
    r = np.asarray(c.roundtrip(row))  # k = 2 of 20
    assert np.isnan(r).sum() == 1 and np.isposinf(r).sum() == 1
    assert (r[np.isfinite(r)] == 0).all()  # finite values lost the seats


@smoke
def test_quant_roundtrip_error_bounds_and_determinism():
    """|roundtrip(x) - x| < one quantization step (max|x| / (2^(b-1)-1))
    for q8 AND q4; the deterministic dither makes repeat encodes
    bit-identical (the crash/resume wire contract)."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 200).astype(np.float32) * 3.0)
    for bits, q in ((8, 127.0), (4, 7.0)):
        c = make_codec(exchange_codec="quant", quant_bits=bits)
        r = np.asarray(c.roundtrip(x))
        step = np.abs(np.asarray(x)).max(axis=-1, keepdims=True) / q
        assert (np.abs(r - np.asarray(x)) < step + 1e-6).all(), bits
        np.testing.assert_array_equal(r, np.asarray(c.roundtrip(x)))
    # an all-zero slice is stable (scale guard), non-finites pass through
    c8 = make_codec(exchange_codec="quant")
    np.testing.assert_array_equal(
        np.asarray(c8.roundtrip(jnp.zeros(5, jnp.float32))), np.zeros(5)
    )
    bad = np.asarray(
        c8.roundtrip(jnp.asarray([np.nan, np.inf, -np.inf, 2.0], jnp.float32))
    )
    assert np.isnan(bad[0]) and np.isposinf(bad[1]) and np.isneginf(bad[2])
    assert np.isfinite(bad[3])


@smoke
def test_zoo_bytes_on_wire_formulas_and_identity_short_circuit():
    topk = make_codec(exchange_codec="topk", topk_fraction=0.1)
    q8 = make_codec(exchange_codec="quant", quant_bits=8)
    q4 = make_codec(exchange_codec="quant", quant_bits=4)
    for n in (1, 13, 577440):
        assert topk.bytes_on_wire(n) == topk.kept(n) * 8  # index+value
        assert q8.bytes_on_wire(n) == 4 + n  # scale header + 1 B/value
        assert q4.bytes_on_wire(n) == 4 + math.ceil(n / 2)
    assert topk.bytes_on_wire(0) == q8.bytes_on_wire(0) == 0
    # the identity short-circuit: make_codec(None) is the dense member
    # and its roundtrip inserts NO op (the engine compiles it away)
    ident = make_codec("float32", None)
    assert ident.is_identity and ident.flat_wire
    x = jnp.arange(5, dtype=jnp.float32)
    assert ident.roundtrip(x) is x
    assert not make_codec("bfloat16", None).is_identity
    # labels are what report's frontier prints
    assert topk.label() == "topk(0.1)" and q8.label() == "q8"
    assert q4.describe() == {"name": "quant", "label": "q4", "bits": 4}


# ---------------------------------------------------- validation surfaces


@smoke
def test_config_rejects_bad_zoo_knobs_naming_the_field():
    with pytest.raises(ValueError, match="exchange_codec"):
        ExperimentConfig(exchange_codec="gzip")
    with pytest.raises(ValueError, match="exchange_codec"):
        ExperimentConfig(exchange_codec="topk", exchange_dtype="bfloat16")
    with pytest.raises(ValueError, match="topk_fraction"):
        ExperimentConfig(exchange_codec="topk", topk_fraction=0.0)
    with pytest.raises(ValueError, match="topk_fraction"):
        ExperimentConfig(exchange_codec="topk", topk_fraction=1.5)
    with pytest.raises(ValueError, match="topk_fraction"):
        ExperimentConfig(exchange_codec="topk", topk_fraction=True)
    with pytest.raises(ValueError, match="topk_fraction"):
        # a zoo parameter without its member is a mistake, not a no-op
        ExperimentConfig(topk_fraction=0.5)
    with pytest.raises(ValueError, match="quant_bits"):
        ExperimentConfig(exchange_codec="quant", quant_bits=16)
    with pytest.raises(ValueError, match="quant_bits"):
        ExperimentConfig(quant_bits=4)
    with pytest.raises(ValueError, match="error_feedback"):
        ExperimentConfig(error_feedback=True)  # identity has no error
    with pytest.raises(ValueError, match="group_schedule"):
        ExperimentConfig(group_schedule="random")
    with pytest.raises(ValueError, match="group_schedule"):
        ExperimentConfig(group_schedule="adaptive", strategy="none")
    with pytest.raises(ValueError, match="group_skip_frac"):
        ExperimentConfig(
            group_schedule="adaptive", group_skip_frac=1.0
        )
    with pytest.raises(ValueError, match="group_skip_frac"):
        ExperimentConfig(group_skip_frac=0.1)  # needs adaptive
    # the happy paths: every vocabulary member + EF on every lossy codec
    for codec in EXCHANGE_CODECS:
        ExperimentConfig(exchange_codec=codec, error_feedback=True)
    ExperimentConfig(exchange_dtype="bfloat16", error_feedback=True)
    for sched in GROUP_SCHEDULES:
        ExperimentConfig(group_schedule=sched)
    ExperimentConfig(group_schedule="adaptive", group_skip_frac=0.25)


@smoke
def test_make_codec_rejects_unknown_member():
    with pytest.raises(ValueError, match="exchange_codec"):
        make_codec(exchange_codec="gzip")
    with pytest.raises(ValueError, match="topk_fraction"):
        TopKCodec(fraction=0.0)
    with pytest.raises(ValueError, match="quant_bits"):
        QuantCodec(bits=6)


@smoke
def test_cli_rejects_bad_zoo_flags():
    # in-process: the config error surfaces BEFORE any training,
    # naming the offending field (the auto-generated flag surface)
    from federated_pytorch_test_tpu.__main__ import main

    with pytest.raises(ValueError, match="exchange_codec"):
        main(["--preset", "fedavg", "--exchange-codec", "gzip"])
    with pytest.raises(ValueError, match="topk_fraction"):
        main(["--preset", "fedavg", "--exchange-codec", "topk",
              "--topk-fraction", "0"])
    with pytest.raises(ValueError, match="quant_bits"):
        main(["--preset", "fedavg", "--exchange-codec", "quant",
              "--quant-bits", "5"])
    with pytest.raises(ValueError, match="error_feedback"):
        main(["--preset", "fedavg", "--error-feedback"])
    with pytest.raises(ValueError, match="group_schedule"):
        main(["--preset", "fedavg", "--group-schedule", "sometimes"])
    with pytest.raises(ValueError, match="group_skip_frac"):
        main(["--preset", "fedavg", "--group-skip-frac", "0.5"])


# ------------------------------------------------ GroupScheduler units


@smoke
def test_group_scheduler_policy():
    s = GroupScheduler([2, 0, 1], skip_frac=0.1)
    # warmup: round-robin order while any remaining group is unobserved
    assert s.decide(set()) == (2, {"source": "warmup"})
    s.observe("group_distance", {"value": [0.5, 3.0, 1.0]})
    # argmax drift over the remaining groups
    gid, info = s.decide(set())
    assert gid == 1 and info["source"] == "drift" and info["drift"] == 3.0
    # no-replacement within a loop: the visited set narrows the pool
    assert s.decide({1})[0] == 2  # 1.0 beats 0.5
    # skip rule: best remaining drift <= skip_frac * peak sends nothing
    s.observe("group_distance", {"value": [0.01, 3.0, 0.02]})
    gid, info = s.decide({1, 2})
    assert gid == 0 and info.get("skipped") is True
    # ...but NEVER on a loop's first slot (visited empty): an all-quiet
    # fleet still trains its top-drift group each loop, so the signal
    # can rebound — skipping a whole loop would be an absorbing state
    s.observe("group_distance", {"value": [0.001, 0.002, 0.003]})
    gid, info = s.decide(set())
    assert gid == 2 and "skipped" not in info  # argmax of the quiet fleet
    # ties break toward the earlier round-robin position
    t = GroupScheduler([2, 0, 1])
    t.observe("group_distance", {"value": [1.0, 1.0, 1.0]})
    assert t.decide(set())[0] == 2
    # non-finite drift is ignored (a rolled-back round's poisoned
    # signal must not wedge the argmax), keeping the last estimate
    t.observe("group_distance", {"value": [float("nan")] * 3})
    assert t.decide(set())[0] == 2
    with pytest.raises(ValueError, match="group_skip_frac"):
        GroupScheduler([0], skip_frac=1.0)
    with pytest.raises(ValueError, match="visited"):
        GroupScheduler([0]).decide({0})


@smoke
def test_group_scheduler_replay_parity():
    """A scheduler fed records via replay() decides exactly like one
    that observed them live — the crash/resume purity contract."""
    records = [
        ("group_distance", {"value": [0.5, 3.0, 1.0]}),
        ("train_loss", {"value": [1.0]}),  # foreign series ignored
        ("group_distance", {"value": [2.0, 0.1, 0.4]}),
    ]
    live = GroupScheduler([0, 1, 2], skip_frac=0.05)
    for name, rec in records:
        live.observe(name, rec)
    resumed = GroupScheduler([0, 1, 2], skip_frac=0.05)
    resumed.replay(records)
    for visited in (set(), {0}, {0, 1}):
        assert live.decide(visited) == resumed.decide(visited)


# ----------------------------------- registry: schedule + codec columns


def _write_stream(path, tag, records):
    with open(path, "w") as f:
        f.write(json.dumps(
            {"event": "stream_header", "version": 1, "tag": tag}
        ) + "\n")
        for series, rec in records:
            f.write(json.dumps({"series": series, **rec}) + "\n")


@smoke
def test_report_labels_skipping_and_match_on_new_tags(tmp_path):
    """The frontier labels points with codec+scheduler config, flags
    dominated points explicitly, sums bytes_saved_by_skipping from
    skipped group_schedule records — and `--match` still filters on the
    preset:seed prefix of tags whose config digest carries the new
    knobs."""
    from federated_pytorch_test_tpu.obs.registry import (
        RunRegistry,
        render_markdown,
    )

    common = [
        ("comm_bytes", {"value": 1000, "nloop": 0, "group": 0,
                        "nadmm": 0, "survivors": 3}),
        ("test_accuracy", {"value": [0.5, 0.5, 0.5], "nloop": 0,
                           "group": 0, "nadmm": 0}),
    ]
    _write_stream(
        tmp_path / "dense.jsonl", "fedavg:seed0:cfgaaaa:noplan",
        common + [("comm_summary", {"value": {
            "exchange_dtype": "float32", "codec":
                {"name": "identity", "label": "identity"}}})],
    )
    _write_stream(
        tmp_path / "sparse.jsonl", "fedavg:seed0:cfgbbbb:noplan",
        [
            ("group_schedule", {"value": {
                "slot": 0, "group": 1, "source": "drift",
                "skipped": True, "saved_bytes": 444}, "nloop": 0}),
            ("comm_bytes", {"value": 200, "nloop": 0, "group": 0,
                            "nadmm": 0, "survivors": 3}),
            ("test_accuracy", {"value": [0.5, 0.5, 0.5], "nloop": 0,
                               "group": 0, "nadmm": 0}),
            ("comm_summary", {"value": {
                "exchange_dtype": "float32", "codec":
                    {"name": "topk", "label": "topk(0.1)",
                     "fraction": 0.1}}}),
        ],
    )
    reg = RunRegistry()
    assert reg.ingest_dir(str(tmp_path)) == []
    doc = reg.report()
    sparse = doc["runs"]["sparse"]
    assert sparse["config"] == {
        "codec": "topk(0.1)", "schedule": "adaptive",
        "label": "topk(0.1)/adaptive",
    }
    assert sparse["bytes_saved_by_skipping"] == 444
    assert sparse["skipped_rounds"] == 1
    assert doc["runs"]["dense"]["config"]["label"] == "identity/roundrobin"
    front = {p["run"]: p for p in doc["frontier"]}
    assert front["sparse"]["pareto"] and not front["dense"]["pareto"]
    assert front["sparse"]["config"] == "topk(0.1)/adaptive"
    md = render_markdown(doc)
    assert "topk(0.1)/adaptive" in md and "dominated" in md
    assert "444" in md  # the bytes-saved column
    # --match still pins the experiment family through the new tags
    reg2 = RunRegistry(match="fedavg:seed0")
    assert reg2.ingest_dir(str(tmp_path)) == []
    reg3 = RunRegistry(match="fedavg:seed1")
    assert len(reg3.ingest_dir(str(tmp_path))) == 2


# ------------------------------------------------ trainer-level (mid tier)


@pytest.fixture(scope="module")
def _src():
    return synthetic_cifar(n_train=240, n_test=60)


def _tiny(preset="fedavg", **over):
    base = dict(
        batch=40, nloop=1, nadmm=2, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
    )
    base.update(over)
    return get_preset(preset, **base)


def test_topk_comm_bytes_hand_checked(_src):
    """THE sparse ledger contract: every `comm_bytes` record equals
    `kept * 8 * survivors` with survivors from the PURE plan masks —
    seed=8 draws a full exchange AND a dropped-client one (3 then 2
    survivors), so the index+value pricing is checked at two survivor
    counts; the summary carries the codec descriptor and a doubled-up
    savings ratio vs the dense f32 arithmetic."""
    tr = Trainer(
        _tiny(fault_plan="seed=8,dropout=0.3", exchange_codec="topk",
              topk_fraction=0.25),
        verbose=False, source=_src,
    )
    tr.run()
    gid = tr.group_order[0]
    gsize = tr.partition.group_size(gid)
    k = min(gsize, max(1, math.ceil(0.25 * gsize)))
    recs = tr.recorder.series["comm_bytes"]
    assert {r["survivors"] for r in recs} == {3, 2}
    for r in recs:
        survivors = int(tr.injector.mask(r["nloop"], gid, r["nadmm"]).sum())
        assert r["survivors"] == survivors
        assert r["value"] == k * 8 * survivors  # u32 index + f32 value
    s = tr.recorder.latest("comm_summary")
    assert s["codec"] == {
        "name": "topk", "label": "topk(0.25)", "fraction": 0.25,
    }
    assert s["wire_bytes_per_value"] is None  # no flat per-value width
    assert s["bytes_total"] == sum(r["value"] for r in recs)
    # full-model baseline stays at the f32 parameter width
    assert s["bytes_full_exchange"] == (
        tr.partition.total * 4 * sum(r["survivors"] for r in recs)
    )
    assert s["savings_vs_full"] == pytest.approx(
        (tr.partition.total * 4) / (k * 8), rel=1e-3
    )


def test_topk_robust_gate_with_ef_and_adaptive(
    src_hard_accept, fault_free_accept, accept_cfg
):
    """The PR-5 corruption acceptance gate UNDER the sparse codec with
    error feedback and the adaptive scheduler all in the program: 1
    client corrupted per round (scale λ=10, garbling the sparse wire in
    transit), trimmed(1) + z-score quarantine on the DECODED views —
    zero rollbacks, within 2 points of fault-free, folded dispatch
    budget {round: 1, round_init: 1} with the drift signal in-scan and
    the slot decision memoized at round start. (The q8 mirror runs in
    the slow tier; the ≤25%-bytes frontier acceptance runs through the
    real CLI in scripts/ci.sh codec_smoke.)"""
    tr = Trainer(
        accept_cfg(
            exchange_codec="topk", topk_fraction=0.1, error_feedback=True,
            group_schedule="adaptive",
            fault_plan="seed=7,corrupt=1:scale:10",
            robust_agg="trimmed", robust_f=1, quarantine_z=1.0,
        ),
        verbose=False, source=src_hard_accept,
    )
    tr.run()
    kinds = [f["value"]["kind"] for f in tr.recorder.series.get("fault", [])]
    assert "round_rollback" not in kinds
    assert "nonfinite_params" not in kinds
    acc = float(np.mean(tr.recorder.latest("test_accuracy")))
    acc_free = float(
        np.mean(fault_free_accept.recorder.latest("test_accuracy"))
    )
    assert abs(acc - acc_free) <= 0.02, (acc, acc_free)
    for r in tr.recorder.series["dispatch_count"]:
        assert r["value"] == {"round": 1, "round_init": 1, "total": 2}
    # the scheduler decided every slot and streamed the evidence
    assert len(tr.recorder.series["group_schedule"]) == tr.cfg.nloop
    assert len(tr.recorder.series["group_distance"]) == tr.cfg.nloop
    # the EF residual persisted for the next loop's exchanges
    assert sorted(tr._ef_store) == [tr.group_order[0]]


def test_zoo_knobs_are_stream_tag_members(_src, tmp_path):
    """Every trajectory-changing zoo/scheduler knob changes the stream
    tag (a resumed run that flips one gets a fresh stream, never a
    splice) — the PR-9 bf16 pattern extended to the new knobs."""
    base = _tiny()
    base_tag = Trainer(base, verbose=False, source=_src)._stream_tag()
    tags = {}
    for key, (k, v) in {
        "topk": ("exchange_codec", "topk"),
        "quant": ("exchange_codec", "quant"),
        "bits": ("quant_bits", 4),
        "frac": ("topk_fraction", 0.5),
        "ef": ("error_feedback", True),
        "sched": ("group_schedule", "adaptive"),
        "skip": ("group_skip_frac", 0.2),
    }.items():
        over = {k: v}
        if k == "quant_bits":
            over["exchange_codec"] = "quant"
        if k == "topk_fraction":
            over["exchange_codec"] = "topk"
        if k == "error_feedback":
            over["exchange_codec"] = "topk"
        if k == "group_skip_frac":
            over["group_schedule"] = "adaptive"
        tags[key] = Trainer(
            base.replace(**over), verbose=False, source=_src
        )._stream_tag()
        assert tags[key] != base_tag, key
    assert len(set(tags.values())) == len(tags)  # all distinct configs

    # and the sink REFUSES a stream written under another codec's tag
    p = str(tmp_path / "zoo.jsonl")
    sink = JsonlSink(p, tag=base_tag)
    sink.open()
    sink.record("a", {"t": 0.1, "value": 1, "nloop": 0})
    sink.commit(0)
    sink.close()
    s2 = JsonlSink(p, tag=tags["topk"])
    with pytest.warns(UserWarning, match="different experiment"):
        assert s2.open(resume_nloops=1) == []
    s2.close()


# --------------------------------------------------- slow-tier contracts


@pytest.mark.slow
def test_q8_robust_gate_within_two_points(
    src_hard_accept, fault_free_accept, accept_cfg
):
    """The q8 mirror of the corruption acceptance gate: quantized wire,
    trimmed(1) + quarantine on decoded views, zero rollbacks, within 2
    points of fault-free."""
    tr = Trainer(
        accept_cfg(
            exchange_codec="quant", quant_bits=8,
            fault_plan="seed=7,corrupt=1:scale:10",
            robust_agg="trimmed", robust_f=1, quarantine_z=1.0,
        ),
        verbose=False, source=src_hard_accept,
    )
    tr.run()
    kinds = [f["value"]["kind"] for f in tr.recorder.series.get("fault", [])]
    assert "round_rollback" not in kinds
    acc = float(np.mean(tr.recorder.latest("test_accuracy")))
    acc_free = float(
        np.mean(fault_free_accept.recorder.latest("test_accuracy"))
    )
    assert abs(acc - acc_free) <= 0.02, (acc, acc_free)


@pytest.mark.slow
def test_topk_ef_adaptive_fused_unfused_bitwise(_src):
    """The fused round replays the unfused schedule bit for bit with
    the sparse codec, the EF carry, AND the drift signal in the program
    (the in-scan group_distances equals the standalone dispatch's — the
    shared-body contract), including identical slot decisions."""
    cfg = _tiny(
        nloop=2, max_groups=2, exchange_codec="topk", topk_fraction=0.25,
        error_feedback=True, group_schedule="adaptive",
        fault_plan="seed=8,dropout=0.3",
    )
    outs = {}
    for fuse in (True, False):
        tr = Trainer(cfg.replace(fuse_rounds=fuse), verbose=False, source=_src)
        tr.run()
        outs[fuse] = (
            np.asarray(tr._fetch(tr.flat)),
            [
                (r["nloop"], r["value"]["slot"], r["value"]["group"])
                for r in tr.recorder.series["group_schedule"]
            ],
            {g: np.asarray(tr._fetch(e)) for g, e in tr._ef_store.items()},
        )
    np.testing.assert_array_equal(outs[True][0], outs[False][0])
    assert outs[True][1] == outs[False][1]
    assert sorted(outs[True][2]) == sorted(outs[False][2])
    for g in outs[True][2]:
        np.testing.assert_array_equal(outs[True][2][g], outs[False][2][g])


@pytest.mark.slow
def test_ef_rides_the_client_store_in_cohort_mode(_src):
    """Cohort mode persists the EF residual per VIRTUAL client: the
    store grows `ef/<gid>` fields at scatter, later loops gather them
    back, and pristine clients gather the zero fill."""
    tr = Trainer(
        _tiny(
            nloop=2, exchange_codec="topk", topk_fraction=0.25,
            error_feedback=True,
            virtual_clients=6, cohort=3, data_shards=6,
        ),
        verbose=False, source=_src,
    )
    tr.run()
    gid = tr.group_order[0]
    name = f"ef/{gid}"
    assert name in tr.store.fields
    sampled = sorted(
        {c for r in tr.recorder.series["cohort"] for c in r["value"]["clients"]}
    )
    ids = np.arange(6)
    rows = tr.store.gather(name, ids)
    # at least one sampled client carries a nonzero residual; never-
    # sampled clients hold the pristine zero fill
    assert np.abs(rows[sampled]).max() > 0
    untouched = [i for i in ids if i not in sampled]
    if untouched:
        assert np.abs(rows[untouched]).max() == 0


@pytest.mark.slow
def test_adaptive_crash_resume_stream_identity(_src, tmp_path, norm_stream):
    """Crash+resume under topk+EF+adaptive: the resumed stream —
    `group_schedule` decisions and `group_distance` drift records
    included — is identical to an uninterrupted twin's, and the EF
    residual restores from the checkpoint (the decisions replay, never
    re-derive from a cold scheduler)."""
    from federated_pytorch_test_tpu.fault import InjectedCrash

    common = dict(
        nloop=2, max_groups=2, exchange_codec="topk", topk_fraction=0.25,
        error_feedback=True, group_schedule="adaptive",
        robust_agg="trimmed", robust_f=1,
        save_model=True, resume="auto",
    )
    crash_cfg = _tiny(
        **common,
        fault_plan="seed=8,dropout=0.3,crash=1:2:0",
        checkpoint_dir=str(tmp_path / "ckpt"),
        metrics_stream=str(tmp_path / "run.jsonl"),
    )
    with pytest.raises(InjectedCrash):
        Trainer(crash_cfg, verbose=False, source=_src).run()
    tr = Trainer(crash_cfg, verbose=False, source=_src)
    assert tr._completed_nloops == 1  # restored, decisions replayed
    tr.run()
    twin = Trainer(
        _tiny(
            **common,
            fault_plan="seed=8,dropout=0.3",
            checkpoint_dir=str(tmp_path / "ckpt_twin"),
            metrics_stream=str(tmp_path / "twin.jsonl"),
        ),
        verbose=False, source=_src,
    )
    twin.run()
    a = norm_stream(str(tmp_path / "run.jsonl"))
    b = norm_stream(str(tmp_path / "twin.jsonl"))
    assert a == b
    assert any(d.get("series") == "group_schedule" for d in a)
    assert any(d.get("series") == "group_distance" for d in a)
    for g in twin._ef_store:
        np.testing.assert_array_equal(
            np.asarray(tr._fetch(tr._ef_store[g])),
            np.asarray(twin._fetch(twin._ef_store[g])),
        )


@pytest.mark.slow
def test_adaptive_resume_requires_stream(_src, tmp_path):
    """Resuming an adaptive run without a metrics stream is refused:
    the slot decisions replay from the stream, never re-derive."""
    from federated_pytorch_test_tpu.fault import InjectedCrash

    cfg = _tiny(
        nloop=2, max_groups=2, group_schedule="adaptive",
        fault_plan="seed=8,crash=1:2:0",
        save_model=True, resume="auto",
        checkpoint_dir=str(tmp_path / "ckpt"),
    )
    with pytest.raises(InjectedCrash):
        Trainer(cfg, verbose=False, source=_src).run()
    with pytest.raises(ValueError, match="group-schedule adaptive"):
        Trainer(cfg, verbose=False, source=_src)
