"""Fault-tolerance tests: plan determinism, masked-aggregation identities,
torn-checkpoint fallback, crash/resume replay, rollback, retry/backoff.

The masked-aggregation identity block is the satellite contract from the
fault PR: the all-ones mask is BIT-identical to the unmasked path, a
single-survivor round returns that client's block verbatim, and an
all-dropped round leaves the consensus state untouched.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from federated_pytorch_test_tpu.consensus import (
    ADMMConfig,
    ADMMState,
    admm_init,
    admm_round,
    fedavg_init,
    fedavg_round,
)
from federated_pytorch_test_tpu.fault import (
    CrashPoint,
    FaultInjector,
    FaultPlan,
    InjectedCrash,
)
from federated_pytorch_test_tpu.parallel import CLIENT_AXIS, client_mesh, shard_map

K, N = 3, 11

smoke = pytest.mark.smoke


def _spmd(mesh, fn, *args, out_specs=P()):
    return jax.jit(
        shard_map(
            fn,
            mesh=mesh,
            in_specs=tuple(P(CLIENT_AXIS) for _ in args),
            out_specs=out_specs,
        )
    )(*args)


@pytest.fixture(params=[1, 3], ids=["D1", "D3"])
def mesh(request):
    return client_mesh(request.param)


# --------------------------------------------------------------- FaultPlan


@smoke
def test_plan_masks_deterministic_and_replayable():
    plan = FaultPlan(seed=3, dropout_p=0.4)
    a = plan.participation(64, 1, 2, 0)
    # a FRESH plan object derives the identical mask: pure in (seed, cursor)
    b = FaultPlan(seed=3, dropout_p=0.4).participation(64, 1, 2, 0)
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.float32 and set(np.unique(a)) <= {0.0, 1.0}
    # different cursors and different seeds give different masks
    assert not np.array_equal(a, plan.participation(64, 1, 2, 1))
    assert not np.array_equal(
        a, FaultPlan(seed=4, dropout_p=0.4).participation(64, 1, 2, 0)
    )
    # dropout rate lands near p over many rounds
    drops = np.mean(
        [1.0 - plan.participation(64, i, 0, 0).mean() for i in range(50)]
    )
    assert 0.3 < drops < 0.5


@smoke
def test_plan_no_dropout_is_all_ones():
    np.testing.assert_array_equal(
        FaultPlan(seed=0).participation(8, 0, 0, 0), np.ones(8, np.float32)
    )


@smoke
def test_plan_straggler_deterministic_and_independent_of_masks():
    plan = FaultPlan(seed=5, dropout_p=0.3, straggler_p=0.5, straggler_delay_s=0.25)
    delays = [plan.straggler_delay(0, g, 0) for g in range(40)]
    assert delays == [
        FaultPlan(
            seed=5, dropout_p=0.3, straggler_p=0.5, straggler_delay_s=0.25
        ).straggler_delay(0, g, 0)
        for g in range(40)
    ]
    assert set(delays) == {0.0, 0.25}
    # adding stragglers must not perturb the dropout masks (separate fold)
    bare = FaultPlan(seed=5, dropout_p=0.3)
    np.testing.assert_array_equal(
        plan.participation(16, 0, 1, 2), bare.participation(16, 0, 1, 2)
    )


@smoke
def test_plan_json_roundtrip_and_inline_spec(tmp_path):
    plan = FaultPlan(
        seed=9,
        dropout_p=0.25,
        straggler_p=0.1,
        straggler_delay_s=0.5,
        crashes=(CrashPoint(0, 1, 2),),
    )
    assert FaultPlan.from_json(plan.to_json()) == plan
    # file path form
    path = tmp_path / "plan.json"
    path.write_text(plan.to_json())
    assert FaultPlan.parse(str(path)) == plan
    # inline form
    parsed = FaultPlan.parse("seed=9,dropout=0.25,straggler=0.1:0.5,crash=0:1:2")
    assert parsed == plan


@smoke
def test_plan_parse_rejects_garbage():
    with pytest.raises(ValueError, match="bad fault-plan item"):
        FaultPlan.parse("not-a-file-and-not-a-spec")
    with pytest.raises(ValueError, match="unknown fault-plan key"):
        FaultPlan.parse("seed=1,banana=2")
    with pytest.raises(ValueError, match="nloop:gid:nadmm"):
        FaultPlan.parse("crash=1:2")
    with pytest.raises(ValueError, match="dropout_p"):
        FaultPlan(dropout_p=1.5)


@smoke
def test_injector_crash_fires_once_per_state_dir(tmp_path):
    plan = FaultPlan(crashes=(CrashPoint(0, 0, 1),))
    inj = FaultInjector(plan, n_clients=3, state_dir=str(tmp_path))
    inj.maybe_crash(0, 0, 0)  # not the planned point: no-op
    with pytest.raises(InjectedCrash):
        inj.maybe_crash(0, 0, 1)
    # the sentinel persists: the SAME injector and a FRESH process
    # (new injector over the same state dir) both skip the fired point
    inj.maybe_crash(0, 0, 1)
    FaultInjector(plan, 3, state_dir=str(tmp_path)).maybe_crash(0, 0, 1)
    # without a state dir the record is process-local only
    inj2 = FaultInjector(plan, 3)
    with pytest.raises(InjectedCrash):
        inj2.maybe_crash(0, 0, 1)
    inj2.maybe_crash(0, 0, 1)


@smoke
def test_injector_sentinels_are_scoped_to_the_plan(tmp_path):
    """A DIFFERENT plan sharing the checkpoint dir must still crash: the
    sentinel carries the plan identity, not just the round cursor."""
    a = FaultPlan(seed=1, crashes=(CrashPoint(0, 0, 1),))
    with pytest.raises(InjectedCrash):
        FaultInjector(a, 3, state_dir=str(tmp_path)).maybe_crash(0, 0, 1)
    b = FaultPlan(seed=2, crashes=(CrashPoint(0, 0, 1),))
    with pytest.raises(InjectedCrash):
        FaultInjector(b, 3, state_dir=str(tmp_path)).maybe_crash(0, 0, 1)
    # the SAME plan over the same dir stays suppressed (fire-once)
    FaultInjector(a, 3, state_dir=str(tmp_path)).maybe_crash(0, 0, 1)


# ----------------------------------------- masked aggregation identities


@smoke
def test_fedavg_all_ones_mask_bit_identical(mesh):
    x = np.random.default_rng(0).normal(size=(K, N)).astype(np.float32) * 3
    ones = np.ones(K, np.float32)

    def unmasked(xl):
        st, met = fedavg_round(xl, fedavg_init(N))
        return st.z, met["dual_residual"]

    def masked(xl, m):
        st, met = fedavg_round(xl, fedavg_init(N), mask=m)
        return st.z, met["dual_residual"]

    z0, d0 = _spmd(mesh, unmasked, jnp.asarray(x), out_specs=(P(), P()))
    z1, d1 = _spmd(
        mesh, masked, jnp.asarray(x), jnp.asarray(ones), out_specs=(P(), P())
    )
    np.testing.assert_array_equal(np.asarray(z0), np.asarray(z1))
    np.testing.assert_array_equal(np.asarray(d0), np.asarray(d1))


@smoke
def test_fedavg_single_survivor_returns_that_block_verbatim(mesh):
    x = np.random.default_rng(1).normal(size=(K, N)).astype(np.float32)
    m = np.zeros(K, np.float32)
    m[1] = 1.0

    def body(xl, ml):
        st, met = fedavg_round(xl, fedavg_init(N), mask=ml)
        return st.z, met["survivors"]

    z, s = _spmd(
        mesh, body, jnp.asarray(x), jnp.asarray(m), out_specs=(P(), P())
    )
    np.testing.assert_array_equal(np.asarray(z), x[1])
    assert float(s) == 1.0


@smoke
def test_fedavg_all_dropped_keeps_previous_z(mesh):
    x = np.random.default_rng(2).normal(size=(K, N)).astype(np.float32)
    z_prev = np.random.default_rng(3).normal(size=N).astype(np.float32)

    def body(xl):
        st, met = fedavg_round(
            xl,
            # previous consensus state, as it would arrive mid-run
            fedavg_init(N)._replace(z=jnp.asarray(z_prev)),
            mask=jnp.zeros((xl.shape[0],), jnp.float32),
        )
        return st.z, met["dual_residual"], met["survivors"]

    z, dual, s = _spmd(mesh, body, jnp.asarray(x), out_specs=(P(), P(), P()))
    np.testing.assert_array_equal(np.asarray(z), z_prev)
    assert float(dual) == 0.0 and float(s) == 0.0


def _admm_trajectory(mesh, xs, cfg, mask=None):
    """Run len(xs) ADMM rounds inside shard_map, return final (z, y, rho)."""

    def body(*xls):
        ms = None
        if mask is not None:
            *xls, ms = xls
        st = admm_init(xls[0], cfg)
        for nadmm, xl in enumerate(xls):
            st, met = admm_round(xl, st, jnp.int32(nadmm), cfg, mask=ms)
        return st.z, st.y, st.rho, met.survivors

    args = [jnp.asarray(x) for x in xs]
    if mask is not None:
        args.append(jnp.asarray(mask))
    return _spmd(
        mesh, body, *args,
        out_specs=(P(), P(CLIENT_AXIS), P(CLIENT_AXIS), P()),
    )


@smoke
@pytest.mark.parametrize("bb", [False, True], ids=["fixed-rho", "bb"])
def test_admm_all_ones_mask_bit_identical(mesh, bb):
    cfg = ADMMConfig(rho0=0.01, bb_update=bb, bb_period=2)
    rng = np.random.default_rng(4)
    xs = [rng.normal(size=(K, N)).astype(np.float32) * 2 for _ in range(3)]
    z0, y0, r0, _ = _admm_trajectory(mesh, xs, cfg)
    z1, y1, r1, s = _admm_trajectory(mesh, xs, cfg, mask=np.ones(K, np.float32))
    np.testing.assert_array_equal(np.asarray(z0), np.asarray(z1))
    np.testing.assert_array_equal(np.asarray(y0), np.asarray(y1))
    np.testing.assert_array_equal(np.asarray(r0), np.asarray(r1))
    assert float(s) == K


@smoke
def test_admm_all_dropped_keeps_z_and_y(mesh):
    cfg = ADMMConfig(rho0=0.5)
    rng = np.random.default_rng(5)
    x_warm = rng.normal(size=(K, N)).astype(np.float32)
    x_next = rng.normal(size=(K, N)).astype(np.float32)

    def body(xa, xb):
        st = admm_init(xa, cfg)
        st, _ = admm_round(xa, st, jnp.int32(0), cfg)  # warm-up: z,y nonzero
        z_before, y_before = st.z, st.y
        st, met = admm_round(
            xb, st, jnp.int32(1), cfg,
            mask=jnp.zeros((xb.shape[0],), jnp.float32),
        )
        return (
            st.z, z_before, st.y, y_before, met.dual_residual, met.survivors
        )

    z, zb, y, yb, dual, s = _spmd(
        mesh, body, jnp.asarray(x_warm), jnp.asarray(x_next),
        out_specs=(P(), P(), P(CLIENT_AXIS), P(CLIENT_AXIS), P(), P()),
    )
    np.testing.assert_array_equal(np.asarray(z), np.asarray(zb))
    np.testing.assert_array_equal(np.asarray(y), np.asarray(yb))
    assert float(dual) == 0.0 and float(s) == 0.0


@smoke
def test_admm_dropped_client_keeps_its_dual(mesh):
    cfg = ADMMConfig(rho0=0.3)
    rng = np.random.default_rng(6)
    x = rng.normal(size=(K, N)).astype(np.float32)
    m = np.ones(K, np.float32)
    m[0] = 0.0

    def body(xl, ml):
        st = admm_init(xl, cfg)
        st, _ = admm_round(xl, st, jnp.int32(0), cfg, mask=ml)
        return st.y

    y = np.asarray(
        _spmd(mesh, body, jnp.asarray(x), jnp.asarray(m),
              out_specs=P(CLIENT_AXIS))
    )
    # dropped client 0: y stays at its init (zero); survivors moved
    np.testing.assert_array_equal(y[0], np.zeros(N, np.float32))
    assert np.abs(y[1:]).max() > 0


# ------------------------------------------------- checkpoint atomicity


@smoke
def test_checkpoint_atomic_write_and_torn_fallback(tmp_path):
    from federated_pytorch_test_tpu.utils import load_checkpoint, save_checkpoint

    d = str(tmp_path)
    save_checkpoint(d, {"v": np.arange(4.0), "step": np.int64(1)}, step=1)
    save_checkpoint(d, {"v": np.arange(4.0) * 2, "step": np.int64(2)}, step=2)
    # no staging dirs survive a successful save
    assert not [p for p in os.listdir(d) if p.startswith(".tmp_step")]

    # torn write: step_3 exists but its payload is garbage
    torn = tmp_path / "step_3"
    torn.mkdir()
    (torn / "checkpoint").write_bytes(b"\x00garbage")
    with pytest.warns(UserWarning, match="skipping unreadable checkpoint"):
        state = load_checkpoint(d)
    assert int(state["step"]) == 2  # fell back to the newest READABLE one

    # an abandoned staging dir is never considered a checkpoint
    (tmp_path / ".tmp_step_9").mkdir()
    assert int(load_checkpoint(d)["step"]) == 2

    # explicit step: failures propagate (no silent substitution)...
    with pytest.raises(Exception):
        load_checkpoint(d, step=3)
    # ...and absence is loud
    with pytest.raises(FileNotFoundError):
        load_checkpoint(d, step=7)
    with pytest.raises(FileNotFoundError):
        load_checkpoint(str(tmp_path / "empty"))


@smoke
def test_checkpoint_overwrite_same_step(tmp_path):
    from federated_pytorch_test_tpu.utils import load_checkpoint, save_checkpoint

    d = str(tmp_path)
    save_checkpoint(d, {"v": np.zeros(3)}, step=1)
    save_checkpoint(d, {"v": np.ones(3)}, step=1)
    np.testing.assert_array_equal(load_checkpoint(d)["v"], np.ones(3))


# ------------------------------------------------ metrics NaN/Inf guard


@smoke
def test_recorder_flags_first_nonfinite_with_cursor():
    from federated_pytorch_test_tpu.utils import MetricsRecorder

    rec = MetricsRecorder(verbose=False)
    rec.batch_losses([0.5, 0.4, 0.3], nloop=0, group=1, nadmm=0, epoch=0, minibatch=0)
    assert rec.first_nonfinite is None
    rec.batch_losses(
        [0.5, float("nan"), 0.3], nloop=0, group=1, nadmm=2, epoch=0, minibatch=3
    )
    assert rec.first_nonfinite == {
        "series": "train_loss",
        "nloop": 0, "group": 1, "nadmm": 2, "epoch": 0, "minibatch": 3,
    }
    # frozen at the FIRST observation: later non-finites don't move it
    rec.residuals(float("inf"), 1.0, None, nloop=0, group=2, nadmm=0, group_size=9)
    assert rec.first_nonfinite["group"] == 1
    assert len(rec.series["nonfinite_flag"]) == 1


@smoke
def test_recorder_flags_nonfinite_residual():
    from federated_pytorch_test_tpu.utils import MetricsRecorder

    rec = MetricsRecorder(verbose=False)
    rec.residuals(0.1, float("inf"), 0.01, nloop=3, group=0, nadmm=1, group_size=4)
    assert rec.first_nonfinite == {
        "series": "residuals", "nloop": 3, "group": 0, "nadmm": 1,
    }


# ---------------------------------------------- multihost retry/backoff


@smoke
def test_initialize_distributed_retries_then_succeeds(monkeypatch):
    from federated_pytorch_test_tpu.parallel import multihost

    calls, sleeps, shutdowns = [], [], []

    def flaky(**kw):
        calls.append(kw)
        if len(calls) < 3:
            raise RuntimeError("connection refused: coordinator not up")

    monkeypatch.setattr(multihost.jax.distributed, "initialize", flaky)
    monkeypatch.setattr(
        multihost.jax.distributed, "shutdown", lambda: shutdowns.append(1)
    )
    monkeypatch.setattr(multihost.jax, "process_index", lambda: 0)
    monkeypatch.setattr(multihost.time, "sleep", sleeps.append)
    with pytest.warns(UserWarning, match="retrying"):
        rank = multihost.initialize_distributed(
            coordinator_address="host:1234", num_processes=2, process_id=0,
            backoff_s=2.0,
        )
    assert rank == 0
    assert len(calls) == 3
    assert sleeps == [2.0, 4.0]  # exponential backoff between attempts
    # a failed initialize leaves partial global state that makes the next
    # call die on "called once" — each failure must be shutdown-cleared
    assert len(shutdowns) == 2


@smoke
def test_initialize_distributed_failed_connect_state_is_cleared(monkeypatch):
    """The jax 0.4.x trap: after a failed connect, a re-initialize raises
    'should only be called once' — that must NOT be read as benign
    pre-initialization (split-brain), and shutdown must make retries real.
    """
    from federated_pytorch_test_tpu.parallel import multihost

    calls, shutdowns = [], []

    def stateful_init(**kw):
        calls.append(kw)
        if len(shutdowns) < len(calls) - 1:
            raise RuntimeError(
                "distributed.initialize should only be called once."
            )
        if len(calls) < 3:
            raise RuntimeError("connection refused: coordinator not up")

    monkeypatch.setattr(multihost.jax.distributed, "initialize", stateful_init)
    monkeypatch.setattr(
        multihost.jax.distributed, "shutdown", lambda: shutdowns.append(1)
    )
    monkeypatch.setattr(multihost.jax, "process_index", lambda: 0)
    monkeypatch.setattr(multihost.time, "sleep", lambda s: None)
    with pytest.warns(UserWarning):
        rank = multihost.initialize_distributed(
            coordinator_address="host:1234", num_processes=2, process_id=0,
        )
    assert rank == 0
    assert len(calls) == 3  # the third connect actually reached the network


@smoke
def test_initialize_distributed_bounded_attempts_fail_loud(monkeypatch):
    from federated_pytorch_test_tpu.parallel import multihost

    def always_down(**kw):
        raise RuntimeError("connection refused")

    monkeypatch.setattr(multihost.jax.distributed, "initialize", always_down)
    monkeypatch.setattr(multihost.time, "sleep", lambda s: None)
    with pytest.warns(UserWarning):
        with pytest.raises(RuntimeError, match="after 3 attempts"):
            multihost.initialize_distributed(
                coordinator_address="host:1234", num_processes=2,
                process_id=0, max_attempts=3,
            )


@smoke
def test_initialize_distributed_already_initialized_is_benign(monkeypatch):
    from federated_pytorch_test_tpu.parallel import multihost

    def double_init(**kw):
        raise RuntimeError("distributed runtime is already initialized")

    monkeypatch.setattr(multihost.jax.distributed, "initialize", double_init)
    monkeypatch.setattr(multihost.jax, "process_index", lambda: 1)
    assert (
        multihost.initialize_distributed(
            coordinator_address="host:1234", num_processes=2, process_id=1
        )
        == 1
    )


# ----------------------------------- Trainer-level chaos (middle tier)
# Unmarked (neither smoke nor slow): tier-1 tests that pay one tiny-model
# jit compile each; the persistent compile cache (conftest) amortizes them.


@pytest.fixture(scope="module")
def _src():
    from federated_pytorch_test_tpu.data import synthetic_cifar

    return synthetic_cifar(n_train=240, n_test=60)


def _tiny(**over):
    from federated_pytorch_test_tpu.engine import get_preset

    base = dict(
        batch=40, nloop=1, nadmm=2, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
    )
    base.update(over)
    return get_preset("fedavg", **base)


def _final_flat(trainer):
    return np.asarray(trainer._fetch(trainer.flat))


def test_trainer_chaos_run_is_deterministic(_src):
    from federated_pytorch_test_tpu.engine import Trainer

    cfg = _tiny(fault_plan="seed=11,dropout=0.4")
    outs = []
    for _ in range(2):
        tr = Trainer(cfg, verbose=False, source=_src)
        tr.run()
        outs.append(_final_flat(tr))
    np.testing.assert_array_equal(outs[0], outs[1])
    # the recorded participation matches the plan's masks exactly
    gid = tr.group_order[0]
    plan = FaultPlan.parse("seed=11,dropout=0.4")
    expected = [
        int(plan.participation(cfg.n_clients, 0, gid, a).sum())
        for a in range(cfg.nadmm)
    ]
    survs = [r["value"]["survivors"] for r in tr.recorder.series["participation"]]
    assert survs == expected


def test_trainer_all_ones_plan_bit_identical_to_no_plan(_src):
    from federated_pytorch_test_tpu.engine import Trainer

    tr0 = Trainer(_tiny(), verbose=False, source=_src)
    tr0.run()
    tr1 = Trainer(
        _tiny(fault_plan="seed=11,dropout=0.0"), verbose=False, source=_src
    )
    tr1.run()
    np.testing.assert_array_equal(_final_flat(tr0), _final_flat(tr1))
    # no participation series on a no-chaos-effect... the plan IS active,
    # so the series exists but always reports full participation
    survs = [r["value"]["survivors"] for r in tr1.recorder.series["participation"]]
    assert set(survs) == {tr1.cfg.n_clients}
    # losses recorded identically
    l0 = [r["value"] for r in tr0.recorder.series["train_loss"]]
    l1 = [r["value"] for r in tr1.recorder.series["train_loss"]]
    assert l0 == l1


def test_trainer_crash_resume_replays_exact_trajectory(_src, tmp_path):
    """The acceptance invariant: dropout + one injected crash + auto-resume
    reproduces the exact final state of the same plan WITHOUT the crash."""
    from federated_pytorch_test_tpu.engine import Trainer

    common = dict(
        nloop=2, save_model=True, fault_plan="seed=13,dropout=0.3",
    )
    # straight-through run (no crash) — the target trajectory
    cfg_a = _tiny(checkpoint_dir=str(tmp_path / "a"), **common)
    tr_a = Trainer(cfg_a, verbose=False, source=_src)
    tr_a.run()

    # crashing run: planned crash mid-loop-1, then auto-resume
    gid = tr_a.group_order[0]
    crash_plan = f"seed=13,dropout=0.3,crash=1:{gid}:0"
    cfg_b = _tiny(
        checkpoint_dir=str(tmp_path / "b"), **{**common, "fault_plan": crash_plan}
    )
    tr_b = Trainer(cfg_b, verbose=False, source=_src)
    with pytest.raises(InjectedCrash):
        tr_b.run()
    # fresh process analogue: new Trainer, resume='auto' — the crash
    # sentinel persisted next to the checkpoints, so the point is skipped
    tr_b2 = Trainer(
        cfg_b.replace(resume="auto"), verbose=False, source=_src
    )
    assert tr_b2._completed_nloops == 1  # restored the loop-1 checkpoint
    tr_b2.run()
    np.testing.assert_array_equal(_final_flat(tr_a), _final_flat(tr_b2))


def test_trainer_resume_auto_without_checkpoint_starts_fresh(_src, tmp_path):
    from federated_pytorch_test_tpu.engine import Trainer

    cfg = _tiny(resume="auto", checkpoint_dir=str(tmp_path / "none"))
    tr = Trainer(cfg, verbose=False, source=_src)  # must not raise
    assert tr._completed_nloops == 0


def test_trainer_rollback_discards_poisoned_round(_src):
    from federated_pytorch_test_tpu.engine import Trainer

    cfg = _tiny(fault_mode="rollback")
    tr = Trainer(cfg, verbose=False, source=_src)
    before = _final_flat(tr)
    # poison the round via the detection hook (forcing a real NaN out of
    # the optimizer needs contrived data; the rollback contract is what
    # matters: poisoned round in, entry state out)
    tr._check_losses = lambda losses, **ctx: setattr(tr, "_round_poisoned", True)
    tr.run_round(0, tr.group_order[0])
    after = _final_flat(tr)
    np.testing.assert_array_equal(before, after)
    faults = tr.recorder.series["fault"]
    assert faults[-1]["value"]["kind"] == "round_rollback"


def test_check_losses_sets_poisoned_in_rollback_mode(_src):
    from federated_pytorch_test_tpu.engine import Trainer

    tr = Trainer(_tiny(fault_mode="rollback"), verbose=False, source=_src)
    tr._check_losses(
        np.asarray([[0.1, np.nan, 0.2]]), nloop=0, group=0, nadmm=0, epoch=0
    )
    assert tr._round_poisoned
    assert tr.recorder.series["fault"][-1]["value"]["kind"] == "nonfinite_loss"
