"""Widened client GEMM (`--client-fold`) tests: parity, dispatch budget,
grouped-GEMM kernel units, and the stream-tag refused-splice contract
(docs/PERF.md §Widened GEMM).

The fold's whole contract is PARITY: `--client-fold gemm` re-batches the
line-search probe fan at the tree level (engine/steps.py `fan_fn` →
optim/lbfgs.py → linesearch.py `fan_phi`) so probe-invariant layers run
ONCE per fan and the active group's contraction widens, while `vmap`
compiles today's probe-batched programs byte-for-byte. The K-axis
contraction order of every dot is preserved by the fold (only the
batching changes), so on CPU the two folds must agree BITWISE — same
final parameters, same dispatch budget, same behavior under the
fault/robust/codec stack.

Smoke tier: grouped-GEMM kernel units (einsum == vmap bitwise, Pallas
interpret parity, shape/backend validation), config validation,
`active_leaf_mask`/`fold_params` semantics, FOLD_LAYERS metadata.

Middle (default) tier — the tier-1 wall sits AT the 870 s driver
timeout on the 1-core host (867.66 s measured this session), so this
tier keeps only ~8 s: the BatchNorm-CNN and ResNet-block
direct-`lbfgs_step` parity legs at P=4 (the fold LIVE, through the
exact steps.py fan construction, gemm == vmap bitwise) and
`client_fold` in the stream tag with the refused-splice regression.

Slow tier: everything else — the P=1 inertness legs, simple CNN
through the full engine at P∈{1,4}, TransformerLM and MoE direct
parity, the engine chaos-stack gate (dispatch budget
`{round: 1, round_init: 1}` with dropout + corruption + trimmed +
topk all live AND engine-level gemm == vmap bitwise), the
ragged-budget + quarantine composition leg (fused == unfused
bitwise), the admm+BB leg, and the gemm fused==unfused leg. Tier-2
`widened_smoke` (scripts/ci.sh) adds the real-CLI crash/resume +
vmap-rerun contract and re-asserts the dispatch budget on the stream.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.flatten_util import ravel_pytree

from federated_pytorch_test_tpu.data import synthetic_cifar
from federated_pytorch_test_tpu.engine import (
    ExperimentConfig,
    Trainer,
    get_preset,
)
from federated_pytorch_test_tpu.models import Net
from federated_pytorch_test_tpu.models.base import (
    PartitionedModel,
    active_leaf_mask,
    fold_params,
)
from federated_pytorch_test_tpu.obs import JsonlSink
from federated_pytorch_test_tpu.ops import grouped_matmul, grouped_matmul_pallas
from federated_pytorch_test_tpu.optim import (
    LBFGSConfig,
    lbfgs_init,
    lbfgs_step,
)

smoke = pytest.mark.smoke


@pytest.fixture(scope="module")
def _src():
    return synthetic_cifar(n_train=240, n_test=60)


def _tiny(preset="fedavg", **over):
    base = dict(
        batch=40, nloop=1, nadmm=2, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
    )
    base.update(over)
    return get_preset(preset, **base)


def _final_flat(tr):
    return np.asarray(tr._fetch(tr.flat))


# ------------------------------------------------ grouped-GEMM kernel units


@smoke
def test_grouped_matmul_einsum_matches_vmap_bitwise():
    """The einsum backend IS the vmap-of-matmul lowering — bitwise, in
    f32 and bf16 (what lets models/moe.py swap formulations freely)."""
    rng = np.random.RandomState(0)
    for g, m, k, n in ((4, 33, 7, 5), (3, 128, 64, 32), (1, 8, 16, 8)):
        for dt in (jnp.float32, jnp.bfloat16):
            lhs = jnp.asarray(rng.randn(g, m, k), dt)
            rhs = jnp.asarray(rng.randn(g, k, n), dt)
            ref = jax.vmap(jnp.matmul)(lhs, rhs)
            np.testing.assert_array_equal(
                np.asarray(grouped_matmul(lhs, rhs)), np.asarray(ref)
            )


@smoke
def test_grouped_matmul_pallas_interpret_matches_einsum():
    """The Pallas kernel (interpret mode on this host) reproduces the
    einsum contraction, tile-tail shapes included (M/N padding is
    confined to discarded rows/cols because K is never tiled)."""
    rng = np.random.RandomState(1)
    for g, m, k, n in ((4, 160, 400, 120), (3, 13, 257, 9), (1, 8, 128, 128)):
        lhs = jnp.asarray(rng.randn(g, m, k), jnp.float32)
        rhs = jnp.asarray(rng.randn(g, k, n), jnp.float32)
        np.testing.assert_allclose(
            np.asarray(grouped_matmul_pallas(lhs, rhs)),
            np.asarray(grouped_matmul(lhs, rhs)),
            rtol=1e-6, atol=1e-5,
        )


@smoke
def test_grouped_matmul_validation():
    ok = jnp.zeros((2, 4, 3)), jnp.zeros((2, 3, 5))
    with pytest.raises(ValueError, match="backend"):
        grouped_matmul(*ok, backend="magic")
    with pytest.raises(ValueError, match="shapes"):
        grouped_matmul_pallas(jnp.zeros((2, 4, 3)), jnp.zeros((3, 3, 5)))
    with pytest.raises(ValueError, match="shapes"):
        grouped_matmul_pallas(jnp.zeros((2, 4, 3)), jnp.zeros((2, 4, 5)))


# ----------------------------------------------------- config + metadata


@smoke
def test_client_fold_validation_names_the_field():
    assert ExperimentConfig().client_fold == "gemm"  # the engine default
    with pytest.raises(ValueError, match="client_fold"):
        ExperimentConfig(client_fold="wide")


@smoke
def test_fold_layers_metadata_on_every_model():
    """Each model family declares its fold-legality table (docs/PERF.md
    §Widened GEMM renders it) with only the two defined verdicts."""
    from federated_pytorch_test_tpu.models import (
        Net1,
        Net2,
        ResNet18,
        TransformerLM,
        ViT,
    )

    for cls in (Net, Net1, Net2, ResNet18, TransformerLM, ViT):
        assert cls.FOLD_LAYERS, cls.__name__
        assert set(cls.FOLD_LAYERS.values()) <= {"free", "grouped"}, (
            cls.__name__
        )


@smoke
def test_active_leaf_mask_and_fold_params_semantics():
    """The fan's selective batching: group fc1 marks exactly fc1's
    kernel+bias active; fold_params takes active leaves from the probed
    tree and everything else from the frozen one."""
    m = Net()
    params = m.init(jax.random.PRNGKey(0), m.dummy_input())["params"]
    flat, unravel = ravel_pytree(params)
    part = Net.partition(params)
    gid = 2  # fc1 (GROUP_PATHS order: conv1, conv2, fc1, fc2, fc3)
    mask = active_leaf_mask(unravel, part, gid)
    assert sum(mask) == 2 and not all(mask)
    probed = jax.tree.map(lambda l: l + 1.0, params)
    merged = fold_params(probed, params, mask)
    for layer in params:
        src = probed if layer == "fc1" else params
        for leaf in params[layer]:
            np.testing.assert_array_equal(
                np.asarray(merged[layer][leaf]),
                np.asarray(src[layer][leaf]),
            )


# -------------------------------------- per-model parity: direct harness
#
# The engine path normalizes u8 images, so token models (and tiny inline
# BN models) go through the exact steps.py fan construction against a
# direct `lbfgs_step`: same `active_leaf_mask`/`fold_params` selective
# batching, same `fan_fn(x, d, alphas)` contract, compared against the
# fan-less call that compiles today's probe-batched program.


class _BNNet(PartitionedModel):
    """Tiny BatchNorm CNN: conv+BN ("free" layers) ahead of two dense
    groups — the norm-layer fold-legality leg of the parity suite."""

    GROUP_PATHS = (
        (("conv1",), ("bn1",)),
        (("fc1",),),
        (("fc2",),),
    )
    LINEAR_GROUP_IDS = (1, 2)
    TRAIN_ORDER = (0, 1, 2)
    FOLD_LAYERS = {"conv": "free", "norm": "free", "dense": "grouped"}

    @classmethod
    def input_shape(cls):
        return (12, 12, 3)

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(8, (3, 3), name="conv1")(x)
        x = nn.BatchNorm(use_running_average=not train, name="bn1")(x)
        x = nn.elu(x)
        x = x.mean(axis=(1, 2))
        x = nn.elu(nn.Dense(16, name="fc1")(x))
        return nn.Dense(10, name="fc2")(x)


class _ResBlockNet(PartitionedModel):
    """Tiny residual block (conv+BN, conv+BN, identity skip) between a
    stem conv and a head — the ResNet-block leg of the parity suite."""

    GROUP_PATHS = (
        (("conv_in",),),
        (
            ("block_conv1",), ("block_bn1",),
            ("block_conv2",), ("block_bn2",),
        ),
        (("fc",),),
    )
    LINEAR_GROUP_IDS = (2,)
    TRAIN_ORDER = (0, 1, 2)
    FOLD_LAYERS = {"conv": "free", "norm": "free", "dense": "grouped"}

    @classmethod
    def input_shape(cls):
        return (12, 12, 3)

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.elu(nn.Conv(8, (3, 3), name="conv_in")(x))
        h = nn.Conv(8, (3, 3), name="block_conv1")(x)
        h = nn.BatchNorm(use_running_average=not train, name="block_bn1")(h)
        h = nn.elu(h)
        h = nn.Conv(8, (3, 3), name="block_conv2")(h)
        h = nn.BatchNorm(use_running_average=not train, name="block_bn2")(h)
        x = nn.elu(x + h)
        x = x.mean(axis=(1, 2))
        return nn.Dense(10, name="fc")(x)


def _direct_parity(part, flat0, unravel, loss_of_params, probes, gids):
    """gemm (steps.py fan construction) == vmap (fan-less) through
    `lbfgs_step`, bitwise, per active group."""
    cfg = LBFGSConfig(
        max_iter=2, history_size=3, line_search=True, batch_mode=True,
        ls_probes=probes,
    )
    for gid in gids:
        x0 = part.extract(flat0, gid)
        mask = active_leaf_mask(unravel, part, gid)
        # the fan only folds anything when the mask is MIXED: active
        # leaves stay probe-batched, the rest are genuinely frozen
        assert any(mask) and not all(mask), (gid, mask)
        frozen = unravel(flat0)

        def objective_with(params_of, x, _gid=gid):
            full = part.insert(flat0, _gid, x)
            return loss_of_params(params_of(full))

        def loss_fn(x):
            return objective_with(unravel, x)

        def params_of(full):
            return fold_params(unravel(full), frozen, mask)

        def fan_fn(x_cur, d, alphas):
            def phi(a):
                return objective_with(params_of, x_cur + a * d), ()

            return jax.vmap(phi)(alphas)

        outs = {}
        for label, fan in (("vmap", None), ("gemm", fan_fn)):
            step = jax.jit(
                lambda x, st, _fan=fan: lbfgs_step(
                    loss_fn, x, st, cfg, fan_fn=_fan
                )
            )
            x, st = x0, lbfgs_init(x0, cfg)
            for _ in range(2):
                x, st, _aux = step(x, st)
            outs[label] = np.asarray(jax.device_get(x))
        np.testing.assert_array_equal(outs["gemm"], outs["vmap"]), gid


def _ce_loss(logits, labels):
    lp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(lp, labels[:, None], axis=1))


# P=4 legs stay tier-1 (the fold is LIVE); the P=1 inertness legs ride
# the slow tier — the tier-1 wall sits at the 870 s driver timeout
_PROBE_FAN = [pytest.param(1, marks=pytest.mark.slow), 4]


@pytest.mark.parametrize("probes", _PROBE_FAN)
def test_widened_parity_bn_cnn(probes):
    m = _BNNet()
    rng = jax.random.PRNGKey(0)
    images = jax.random.normal(jax.random.PRNGKey(1), (8, 12, 12, 3))
    labels = jnp.arange(8) % 10
    variables = m.init(rng, images, train=False)
    params, bstats = variables["params"], variables["batch_stats"]
    flat0, unravel = ravel_pytree(params)
    part = _BNNet.partition(params)

    def loss(p):
        logits = m.apply(
            {"params": p, "batch_stats": bstats}, images, train=False
        )
        return _ce_loss(logits, labels)

    # gid 1 = fc1: conv+BN frozen ("free"), the dense contraction active
    _direct_parity(part, flat0, unravel, loss, probes, gids=(1,))


@pytest.mark.parametrize("probes", _PROBE_FAN)
def test_widened_parity_resnet_block(probes):
    m = _ResBlockNet()
    images = jax.random.normal(jax.random.PRNGKey(2), (8, 12, 12, 3))
    labels = jnp.arange(8) % 10
    variables = m.init(jax.random.PRNGKey(0), images, train=False)
    params, bstats = variables["params"], variables["batch_stats"]
    flat0, unravel = ravel_pytree(params)
    part = _ResBlockNet.partition(params)

    def loss(p):
        logits = m.apply(
            {"params": p, "batch_stats": bstats}, images, train=False
        )
        return _ce_loss(logits, labels)

    # gid 1 = the residual block itself; gid 2 = the head dense
    _direct_parity(part, flat0, unravel, loss, probes, gids=(1, 2))


@pytest.mark.slow
@pytest.mark.parametrize("probes", [1, 4])
def test_widened_parity_transformer_lm(probes):
    from federated_pytorch_test_tpu.models import TransformerLM

    lm = TransformerLM(vocab=32, dim=16, num_heads=2, max_len=16)
    tokens = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
    params = lm.init(jax.random.PRNGKey(0), tokens)["params"]
    flat0, unravel = ravel_pytree(params)
    part = TransformerLM.partition(params)

    def loss(p):
        logits = lm.apply({"params": p}, tokens)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32))
        tgt = jnp.roll(tokens, -1, axis=1)
        return -jnp.mean(jnp.take_along_axis(lp, tgt[..., None], axis=-1))

    # gid 1 = block0 (qkv/mlp active, embed+other blocks frozen);
    # gid 5 = head (everything else frozen — the widest frozen prefix)
    _direct_parity(part, flat0, unravel, loss, probes, gids=(1, 5))


@pytest.mark.slow
@pytest.mark.parametrize("probes", [1, 4])
def test_widened_parity_moe(probes):
    from federated_pytorch_test_tpu.models import TransformerLM

    lm = TransformerLM(
        vocab=32, dim=16, num_heads=2, max_len=16, moe_experts=2
    )
    tokens = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
    params = lm.init(jax.random.PRNGKey(0), tokens)["params"]
    flat0, unravel = ravel_pytree(params)
    part = TransformerLM.partition(params)

    def loss(p):
        logits, mut = lm.apply(
            {"params": p}, tokens, mutable=["intermediates"]
        )
        aux = sum(jax.tree.leaves(mut["intermediates"]))
        lp = jax.nn.log_softmax(logits.astype(jnp.float32))
        tgt = jnp.roll(tokens, -1, axis=1)
        ce = -jnp.mean(jnp.take_along_axis(lp, tgt[..., None], axis=-1))
        return ce + 0.01 * aux

    # gid 1 = block0: the expert stacks' grouped GEMMs + routing active
    _direct_parity(part, flat0, unravel, loss, probes, gids=(1,))


# ----------------------------------------- engine-level parity + budget


@pytest.mark.slow
@pytest.mark.parametrize("probes", [1, 4])
def test_widened_parity_net_engine_bitwise(_src, probes):
    """THE parity gate: full engine rounds (simple CNN) under gemm and
    vmap land on bitwise-identical parameters. At P=1 no fan exists to
    fold — the knob is inert by construction — and at P=4 the fold is
    live yet preserves every reduction order. Slow tier: the tier-1
    chaos-stack test below already holds engine-level gemm==vmap at
    P=4; this adds the P=1 inertness leg and the chaos-free twin."""
    flats = {}
    for fold_mode in ("gemm", "vmap"):
        tr = Trainer(
            _tiny(linesearch_probes=probes, client_fold=fold_mode),
            verbose=False, source=_src,
        )
        tr.run()
        flats[fold_mode] = _final_flat(tr)
    np.testing.assert_array_equal(flats["gemm"], flats["vmap"])


@pytest.mark.slow
def test_widened_dispatch_budget_with_chaos_stack(_src):
    """The folded one-dispatch budget holds with the fold live and the
    ENTIRE fault/robust/codec stack in the program: dropout +
    in-transit corruption + trimmed(1) + topk codec + folded evals —
    still `{round: 1, round_init: 1}` per round under gemm, and the
    same chaos trajectory is bitwise-identical to the vmap fold's.
    Slow tier (two full engine compiles, ~14 s): the measured tier-1
    wall hit 867 s of the 870 s driver budget with this leg in it; the
    tier-2 widened_smoke asserts the same budget on a real-CLI stream."""
    base = _tiny(
        check_results=True, eval_batch=30, linesearch_probes=4,
        fault_plan="seed=8,dropout=0.3,corrupt=1:gauss:0.5",
        robust_agg="trimmed", robust_f=1, exchange_codec="topk",
    )
    flats = {}
    for fold_mode in ("gemm", "vmap"):
        tr = Trainer(
            base.replace(client_fold=fold_mode), verbose=False, source=_src
        )
        tr.run()
        flats[fold_mode] = _final_flat(tr)
        if fold_mode == "gemm":
            for r in tr.recorder.series["dispatch_count"]:
                assert r["value"] == {
                    "round": 1, "round_init": 1, "total": 2,
                }
    np.testing.assert_array_equal(flats["gemm"], flats["vmap"])


@pytest.mark.slow
def test_widened_ragged_quarantine_fused_unfused_bitwise(_src):
    """The composition leg: ragged per-client step budgets (speed axis
    live, deadline nobody misses) + auto-quarantine + trimmed(1), all
    under the gemm fold — fused == unfused bitwise."""
    cfg = _tiny(
        linesearch_probes=4, client_fold="gemm",
        fault_plan="seed=3,slow=1:3", round_deadline=1e6,
        robust_agg="trimmed", robust_f=1, quarantine_z=1.0,
    )
    flats = {}
    for fuse in (True, False):
        tr = Trainer(
            cfg.replace(fuse_rounds=fuse), verbose=False, source=_src
        )
        tr.run()
        assert tr._ragged_enabled()
        flats[fuse] = _final_flat(tr)
    np.testing.assert_array_equal(flats[True], flats[False])


@pytest.mark.slow
def test_widened_admm_bb_parity_bitwise(_src):
    """The admm+BB leg (slow tier — two more program compiles): the fold
    under consensus ADMM with BB-adaptive rho, gemm == vmap bitwise."""
    cfg = _tiny("admm", bb_update=True, linesearch_probes=4)
    flats = {}
    for fold_mode in ("gemm", "vmap"):
        tr = Trainer(
            cfg.replace(client_fold=fold_mode), verbose=False, source=_src
        )
        tr.run()
        flats[fold_mode] = _final_flat(tr)
        assert all(
            np.isfinite(r["value"]) for r in tr.recorder.series["mean_rho"]
        )
    np.testing.assert_array_equal(flats["gemm"], flats["vmap"])


@pytest.mark.slow
def test_widened_gemm_fused_unfused_bitwise(_src):
    """The fused round replays the unfused schedule bit for bit with the
    WIDENED fan in the program (the gemm twin of test_exchange.py's
    probe-fan leg)."""
    cfg = _tiny(
        check_results=True, eval_batch=30, linesearch_probes=4,
        client_fold="gemm",
    )
    flats = {}
    for fuse in (True, False):
        tr = Trainer(
            cfg.replace(fuse_rounds=fuse), verbose=False, source=_src
        )
        tr.run()
        flats[fuse] = _final_flat(tr)
    np.testing.assert_array_equal(flats[True], flats[False])


# -------------------------------------------- stream-tag refused splice


def test_client_fold_is_stream_tag_member(_src, tmp_path):
    """`client_fold` changes which program trains (and, off-CPU, can
    change accumulated ulps), so it joins `linesearch_probes` in the
    stream header tag — a resumed run that flips it gets a fresh
    stream, never a splice."""
    base = _tiny()
    tag_gemm = Trainer(base, verbose=False, source=_src)._stream_tag()
    tag_vmap = Trainer(
        base.replace(client_fold="vmap"), verbose=False, source=_src
    )._stream_tag()
    assert tag_gemm != tag_vmap

    p = str(tmp_path / "fold.jsonl")
    sink = JsonlSink(p, tag=tag_gemm)
    sink.open()
    sink.record("a", {"t": 0.1, "value": 1, "nloop": 0})
    sink.commit(0)
    sink.close()
    s2 = JsonlSink(p, tag=tag_vmap)
    with pytest.warns(UserWarning, match="different experiment"):
        assert s2.open(resume_nloops=1) == []
    s2.close()
