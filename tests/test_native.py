"""Tests for the native C++ data-loader runtime (native/cifar_loader.cpp).

The contract: byte-identical decode vs the numpy path, exactly-once epoch
coverage from the prefetching batcher, determinism in the seed, and a
clean fallback when the native library is disabled.
"""

import os
import subprocess

import numpy as np
import pytest

from federated_pytorch_test_tpu.data import native

pytestmark = pytest.mark.smoke  # fast CI tier


def _native_available() -> bool:
    return native.get_lib() is not None


# applied per-test (NOT module-wide) so the fallback-contract test below
# still runs on machines without a C++ toolchain — where the fallback IS
# the production code path
needs_native = pytest.mark.skipif(
    not _native_available(), reason="native loader unavailable (no g++?)"
)


def _numpy_chw_to_hwc(flat):
    return flat.reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1).copy()


@needs_native
def test_chw_to_hwc_byte_identical():
    rng = np.random.default_rng(0)
    flat = rng.integers(0, 256, size=(257, 3072), dtype=np.uint8)
    np.testing.assert_array_equal(native.chw_to_hwc(flat), _numpy_chw_to_hwc(flat))


@pytest.mark.parametrize("label_bytes", [1, 2])
@needs_native
def test_decode_records_byte_identical(label_bytes):
    rng = np.random.default_rng(1)
    raw = rng.integers(0, 256, size=(133, label_bytes + 3072), dtype=np.uint8)
    img, lbl = native.decode_records(raw, label_bytes)
    np.testing.assert_array_equal(lbl, raw[:, label_bytes - 1].astype(np.int32))
    np.testing.assert_array_equal(img, _numpy_chw_to_hwc(raw[:, label_bytes:]))


@needs_native
def test_bin_archive_loader_uses_native(tmp_path):
    # a miniature cifar-10 binary archive: loader output must equal a
    # direct decode of the records
    rng = np.random.default_rng(2)
    d = tmp_path / "cifar-10-batches-bin"
    d.mkdir()
    recs = {}
    for fn, n in [(f"data_batch_{i}.bin", 20) for i in range(1, 6)] + [
        ("test_batch.bin", 10)
    ]:
        raw = rng.integers(0, 256, size=(n, 3073), dtype=np.uint8)
        raw[:, 0] %= 10
        raw.tofile(d / fn)
        recs[fn] = raw

    from federated_pytorch_test_tpu.data import load_cifar10

    src = load_cifar10(str(tmp_path))
    assert src.train_images.shape == (100, 32, 32, 3)
    exp = np.concatenate(
        [_numpy_chw_to_hwc(recs[f"data_batch_{i}.bin"][:, 1:]) for i in range(1, 6)]
    )
    np.testing.assert_array_equal(src.train_images, exp)
    np.testing.assert_array_equal(
        src.test_labels, recs["test_batch.bin"][:, 0].astype(np.int32)
    )


def _epoch_of(batcher, n, batch):
    """Consume one epoch's worth of batches; returns (images, labels)."""
    imgs, lbls = [], []
    for _ in range(n // batch):
        i, l = next(batcher)
        assert len(i) == batch
        imgs.append(i)
        lbls.append(l)
    return np.concatenate(imgs), np.concatenate(lbls)


@needs_native
def test_batcher_exactly_once_per_epoch():
    rng = np.random.default_rng(3)
    n, batch = 96, 16
    images = rng.integers(0, 256, size=(n, 32, 32, 3), dtype=np.uint8)
    labels = np.arange(n, dtype=np.int32)  # unique => multiset check
    with native.PrefetchBatcher(images, labels, batch, seed=7) as b:
        _, l1 = _epoch_of(b, n, batch)
        _, l2 = _epoch_of(b, n, batch)
    # each epoch covers every sample exactly once, in a fresh order
    np.testing.assert_array_equal(np.sort(l1), labels)
    np.testing.assert_array_equal(np.sort(l2), labels)
    assert not np.array_equal(l1, l2)


@needs_native
def test_batcher_images_match_labels():
    # image rows must travel with their labels through the shuffle
    rng = np.random.default_rng(4)
    n, batch = 64, 8
    images = rng.integers(0, 256, size=(n, 32, 32, 3), dtype=np.uint8)
    labels = np.arange(n, dtype=np.int32)
    with native.PrefetchBatcher(images, labels, batch, seed=0) as b:
        img, lbl = next(b)
    for i in range(batch):
        np.testing.assert_array_equal(img[i], images[lbl[i]])


@needs_native
def test_batcher_deterministic_in_seed():
    rng = np.random.default_rng(5)
    n, batch = 48, 12
    images = rng.integers(0, 256, size=(n, 32, 32, 3), dtype=np.uint8)
    labels = np.arange(n, dtype=np.int32)
    with native.PrefetchBatcher(images, labels, batch, seed=42) as a:
        _, la = _epoch_of(a, n, batch)
    with native.PrefetchBatcher(images, labels, batch, seed=42) as b:
        _, lb = _epoch_of(b, n, batch)
    np.testing.assert_array_equal(la, lb)


@needs_native
def test_batcher_tail_semantics():
    rng = np.random.default_rng(6)
    images = rng.integers(0, 256, size=(50, 32, 32, 3), dtype=np.uint8)
    labels = np.arange(50, dtype=np.int32)
    # drop_last: only full batches
    with native.PrefetchBatcher(images, labels, 16, seed=0, drop_last=True) as b:
        seen = [len(next(b)[1]) for _ in range(6)]  # two epochs of 3
    assert all(s == 16 for s in seen)
    # keep the tail: epoch = 3 full + one 2-sample batch
    with native.PrefetchBatcher(images, labels, 16, seed=0, drop_last=False) as b:
        sizes = [len(next(b)[1]) for _ in range(4)]
    assert sorted(sizes) == [2, 16, 16, 16]


@needs_native
def test_batcher_rejects_oversized_batch():
    images = np.zeros((30, 32, 32, 3), np.uint8)
    labels = np.zeros((30,), np.int32)
    with pytest.raises(ValueError, match="batch"):
        native.PrefetchBatcher(images, labels, 64)


@needs_native
def test_batcher_closed_raises_stopiteration():
    images = np.zeros((32, 32, 32, 3), np.uint8)
    labels = np.zeros((32,), np.int32)
    b = native.PrefetchBatcher(images, labels, 8)
    next(b)
    b.close()
    with pytest.raises(StopIteration):
        next(b)


def test_decode_shape_validation():
    # mismatched record width must raise, not read out of bounds
    raw = np.zeros((4, 3074), np.uint8)  # cifar-100 width
    with pytest.raises(ValueError, match="label_bytes"):
        native.decode_records(raw, 1)
    with pytest.raises(ValueError, match="multiple of 3072"):
        native.chw_to_hwc(np.zeros((10, 3000), np.uint8))
    # a single flat image is accepted like numpy reshape(-1, ...) was
    one = np.arange(3072, dtype=np.uint8)
    np.testing.assert_array_equal(
        native.chw_to_hwc(one), _numpy_chw_to_hwc(one[None])
    )


def test_tsan_stress_harness():
    # race detection (SURVEY.md §5 — absent in the reference): the C++
    # stress harness runs the batcher's pathological schedules (destroy
    # while a consumer is blocked / entering, rapid churn, reentrant
    # decode) under ThreadSanitizer; any race/use-after-free is fatal
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native_dir = os.path.join(repo, "native")
    build = subprocess.run(
        ["make", "build/stress_tsan"], cwd=native_dir, capture_output=True,
        text=True, timeout=300,
    )
    if build.returncode != 0:  # toolchain without libtsan: skip, not fail
        pytest.skip(f"TSAN build unavailable: {build.stderr[-200:]}")
    r = subprocess.run(
        [os.path.join(native_dir, "build", "stress_tsan")],
        capture_output=True, text=True, timeout=300,
    )
    if "FATAL: ThreadSanitizer" in r.stderr and "data race" not in r.stderr:
        # TSAN runtime can't initialize on this kernel (e.g. mmap_rnd_bits)
        pytest.skip(f"TSAN runtime unavailable: {r.stderr[:160]}")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "stress OK" in r.stdout


def test_numpy_fallback_same_contract():
    # FEDTPU_NO_NATIVE forces the fallback in a fresh interpreter; the
    # loader must produce identical decode bytes and valid epochs
    code = """
import numpy as np
from federated_pytorch_test_tpu.data import native

assert native.get_lib() is None
rng = np.random.default_rng(0)
flat = rng.integers(0, 256, size=(17, 3072), dtype=np.uint8)
out = native.chw_to_hwc(flat)
np.testing.assert_array_equal(out, flat.reshape(-1,3,32,32).transpose(0,2,3,1))
images = rng.integers(0, 256, size=(40, 32, 32, 3), dtype=np.uint8)
labels = np.arange(40, dtype=np.int32)
with native.PrefetchBatcher(images, labels, 8, seed=1) as b:
    got = np.concatenate([next(b)[1] for _ in range(5)])
np.testing.assert_array_equal(np.sort(got), labels)
print("fallback OK")
"""
    env = dict(os.environ, FEDTPU_NO_NATIVE="1", JAX_PLATFORMS="cpu")
    r = subprocess.run(
        ["python", "-c", code], capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))), env=env,
    )
    assert r.returncode == 0, r.stderr
    assert "fallback OK" in r.stdout
