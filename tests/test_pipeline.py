"""Pipeline-parallelism tests on the 8-device virtual CPU mesh.

The PP contract (parallel/pipeline.py): the SPMD ppermute pipeline over a
`stages` mesh axis computes exactly the sequential composition of its
stages — values AND gradients — and composes with the federated clients
axis on a 2-D mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from federated_pytorch_test_tpu.parallel import shard_map
from jax.sharding import PartitionSpec as P

from federated_pytorch_test_tpu.models.transformer import Block
from federated_pytorch_test_tpu.parallel import CLIENT_AXIS
from federated_pytorch_test_tpu.parallel.pipeline import (
    STAGE_AXIS,
    client_stage_mesh,
    pipeline_apply,
    spmd_pipeline,
    stack_stage_params,
    stage_mesh,
)

# the stage-count guard (no jit) is smoke; the compile-heavy numerics
# tests ride the unmarked middle tier

DIM, HEADS, S_STAGES, M_MICRO = 16, 2, 4, 6


def _stages_and_data(seed=0):
    blk = Block(DIM, HEADS, attn_impl="dense", causal=True, name="stage")
    x0 = jnp.zeros((2, 8, DIM), jnp.float32)  # [micro_batch, seq, dim]
    keys = jax.random.split(jax.random.PRNGKey(seed), S_STAGES)
    stage_params = [blk.init(k, x0) for k in keys]
    rng = np.random.default_rng(seed)
    xs = jnp.asarray(
        rng.normal(size=(M_MICRO,) + x0.shape), jnp.float32
    )
    return blk, stage_params, xs


def _sequential(blk, stage_params, xs):
    y = xs
    for p in stage_params:
        y = jax.vmap(lambda x: blk.apply(p, x))(y)
    return y


def test_pipeline_matches_sequential_composition():
    blk, stage_params, xs = _stages_and_data()
    ref = _sequential(blk, stage_params, xs)
    mesh = stage_mesh(S_STAGES)
    stacked = stack_stage_params(stage_params)
    out = jax.jit(
        lambda p, x: pipeline_apply(blk.apply, p, x, mesh)
    )(stacked, xs)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-5
    )


def test_pipeline_gradients_match_sequential():
    blk, stage_params, xs = _stages_and_data(seed=1)
    mesh = stage_mesh(S_STAGES)
    stacked = stack_stage_params(stage_params)

    def loss_pp(p, x):
        return jnp.sum(pipeline_apply(blk.apply, p, x, mesh) ** 2)

    def loss_seq(ps, x):
        return jnp.sum(_sequential(blk, ps, x) ** 2)

    l_pp, g_pp = jax.jit(jax.value_and_grad(loss_pp))(stacked, xs)
    l_sq, g_sq = jax.value_and_grad(loss_seq)(stage_params, xs)
    np.testing.assert_allclose(float(l_pp), float(l_sq), rtol=1e-5)
    g_sq_stacked = stack_stage_params(g_sq)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=1e-4
        ),
        g_pp,
        g_sq_stacked,
    )


@pytest.mark.smoke
def test_pipeline_stage_count_must_match_mesh():
    blk, stage_params, xs = _stages_and_data()
    mesh = stage_mesh(2)  # 4 stacked stages on a 2-device stages axis
    stacked = stack_stage_params(stage_params)
    with pytest.raises(ValueError, match="one stage per device"):
        pipeline_apply(blk.apply, stacked, xs, mesh)


@pytest.mark.smoke
def test_pipeline_rejects_mesh_without_stages_axis():
    from federated_pytorch_test_tpu.parallel import client_mesh

    blk, stage_params, xs = _stages_and_data()
    stacked = stack_stage_params(stage_params)
    with pytest.raises(ValueError, match="no 'stages' axis"):
        pipeline_apply(blk.apply, stacked, xs, client_mesh(4))


def test_pipeline_composes_with_client_axis():
    # 2 clients x 4 stages: per-client pipelines with DIFFERENT params and
    # data run simultaneously; each must equal its own sequential run
    blk, stage_params, xs = _stages_and_data(seed=2)
    k = 2
    mesh = client_stage_mesh(k, S_STAGES)
    stacked = stack_stage_params(stage_params)
    # client c's params are scaled so the two pipelines discriminate
    per_client = jax.tree.map(
        lambda a: jnp.stack([a, 1.25 * a]), stacked
    )  # [K, S, ...]
    xs_k = jnp.stack([xs, xs[::-1]])  # [K, M, ...]

    def body(p_loc, x_loc):
        # shard_map local view: leading client axis of size 1
        out = spmd_pipeline(
            blk.apply,
            jax.tree.map(lambda a: a[0], p_loc),
            x_loc[0],
        )
        return out[None]

    run = shard_map(
        body,
        mesh=mesh,
        in_specs=(
            jax.tree.map(lambda _: P(CLIENT_AXIS, STAGE_AXIS), stacked),
            P(CLIENT_AXIS),
        ),
        out_specs=P(CLIENT_AXIS),
    )
    out = jax.jit(run)(per_client, xs_k)
    for c in range(k):
        ps_c = [
            jax.tree.map(lambda a: (1.0 if c == 0 else 1.25) * a, p)
            for p in stage_params
        ]
        ref_c = _sequential(blk, ps_c, np.asarray(xs_k[c]))
        np.testing.assert_allclose(
            np.asarray(out[c]), np.asarray(ref_c), atol=2e-5, rtol=1e-5
        )


@pytest.mark.smoke
def test_pipeline_rejects_heterogeneous_stage_stacks():
    # a malformed stacked tree (leaves with different leading dims) must
    # hit the friendly guard, not an opaque sharding/shape error later
    mesh = stage_mesh(S_STAGES)
    bad = {
        "a": np.zeros((S_STAGES, 3), np.float32),
        "b": np.zeros((S_STAGES - 1, 3), np.float32),
    }
    with pytest.raises(ValueError, match="inconsistent leading dims"):
        pipeline_apply(
            lambda p, x: x, bad, np.zeros((M_MICRO, 1), np.float32), mesh
        )


@pytest.mark.smoke
def test_pipeline_rejects_scalar_leaves_in_stack():
    mesh = stage_mesh(S_STAGES)
    bad = {
        "a": np.zeros((S_STAGES, 3), np.float32),
        "scale": 1.0,  # plain Python scalar: cannot carry a stage axis
    }
    with pytest.raises(ValueError, match="inconsistent leading dims"):
        pipeline_apply(
            lambda p, x: x, bad, np.zeros((M_MICRO, 1), np.float32), mesh
        )
