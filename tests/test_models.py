"""Model zoo tests: shapes, metadata, common-seed init, BN locality."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from federated_pytorch_test_tpu.models import (
    MODELS,
    Net,
    Net1,
    Net2,
    ResNet18,
    init_client_params,
)
from federated_pytorch_test_tpu.partition.flat import total_size


@pytest.mark.parametrize("name,model_cls", sorted(MODELS.items()))
def test_forward_shapes(name, model_cls):
    model = model_cls()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((2, 32, 32, 3)), train=False)
    out = model.apply(variables, jnp.ones((2, 32, 32, 3)), train=False)
    assert out.shape == (2, 10)
    assert bool(jnp.all(jnp.isfinite(out)))


def test_param_counts_match_reference():
    # Reference torch param counts (SURVEY.md §2.2 C2): Net ~62K, Net1 ~890K,
    # Net2 ~2.5M — exact counts computed from the layer shapes.
    counts = {}
    for name, cls in MODELS.items():
        variables = cls().init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
        counts[name] = total_size(variables["params"])
    assert counts["net"] == 62_006
    assert counts["net1"] == 890_410
    assert counts["net2"] == 2_513_418
    assert counts["resnet18"] == 11_173_962


def test_common_seed_init_identical_across_clients():
    stacked = init_client_params(Net1(), n_clients=4, seed=0)
    for leaf in jax.tree_util.tree_leaves(stacked):
        assert leaf.shape[0] == 4
        np.testing.assert_array_equal(np.asarray(leaf[0]), np.asarray(leaf[3]))


def test_resnet_batch_stats_separate_collection():
    model = ResNet18()
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), train=False)
    assert "batch_stats" in variables
    out, mutated = model.apply(
        variables, jnp.ones((2, 32, 32, 3)), train=True, mutable=["batch_stats"]
    )
    assert out.shape == (2, 10)
    # training mode updates running stats
    before = jax.tree_util.tree_leaves(variables["batch_stats"])
    after = jax.tree_util.tree_leaves(mutated["batch_stats"])
    assert any(
        not np.allclose(np.asarray(a), np.asarray(b)) for a, b in zip(before, after)
    )


def test_train_order_is_a_permutation():
    for cls in (Net, Net1, Net2):
        assert sorted(cls.TRAIN_ORDER) == list(range(len(cls.GROUP_PATHS)))
