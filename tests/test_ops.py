"""Tests for the Pallas hot-op kernels (ops/compact_pallas.py).

Off-TPU the kernels run in Pallas interpret mode (conftest pins the CPU
platform), so these tests exercise the exact code path the TPU compiles.
Comparisons are against the pure-JAX compact representation
(optim/compact.py), itself validated against the two-loop recursion in
tests/test_lbfgs.py; tolerances are relative because the kernels fix f32
accumulation while XLA may pick a different reduction order.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from federated_pytorch_test_tpu.ops import (
    compact_direction_pallas,
    fused_gram_projections,
)
from federated_pytorch_test_tpu.optim import LBFGSConfig, lbfgs_init, lbfgs_step
from federated_pytorch_test_tpu.optim.compact import compact_direction

pytestmark = pytest.mark.smoke  # fast CI tier


def _rel_close(a, b, rtol):
    scale = np.max(np.abs(np.asarray(b))) + 1e-30
    np.testing.assert_allclose(
        np.asarray(a) / scale, np.asarray(b) / scale, atol=rtol
    )


def _history(m, n, seed, curvature=True):
    rng = np.random.default_rng(seed)
    s = jnp.asarray(rng.normal(size=(m, n)), jnp.float32) * 0.1
    noise = jnp.asarray(rng.normal(size=(m, n)), jnp.float32)
    if curvature:
        d = jnp.asarray(rng.uniform(0.5, 2.0, size=n), jnp.float32)
        y = s * d + 0.01 * noise  # y ≈ B s, B SPD => well-conditioned R
    else:
        y = noise * 0.1
    g = jnp.asarray(rng.normal(size=n), jnp.float32)
    return s, y, g


def test_fused_gram_projections_all_contractions():
    # one fused pass == the four separate contractions
    m, n = 10, 5000  # n not a tile multiple => exercises the tail mask
    s, y, g = _history(m, n, 0)
    sy, yy, p, q = fused_gram_projections(s, y, g)
    np.testing.assert_allclose(np.asarray(sy), np.asarray(s @ y.T), rtol=2e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(yy), np.asarray(y @ y.T), rtol=2e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(p), np.asarray(s @ g), rtol=2e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(q), np.asarray(y @ g), rtol=2e-5, atol=1e-5)


@pytest.mark.parametrize("count", [0, 1, 4, 10])
def test_pallas_direction_matches_compact(count):
    m, n = 10, 5000
    s, y, g = _history(m, n, 1)
    c, hd = jnp.int32(count), jnp.float32(0.7)
    ref = compact_direction(g, s, y, c, hd)
    pal = compact_direction_pallas(g, s, y, c, hd)
    _rel_close(pal, ref, 1e-5)


def test_pallas_direction_degenerate_slot():
    # a zero-curvature slot (y_i . s_i == 0) must contribute nothing
    m, n = 8, 3000
    s, y, g = _history(m, n, 2)
    y = y.at[3].set(0.0)
    ref = compact_direction(g, s, y, jnp.int32(m), jnp.float32(1.0))
    pal = compact_direction_pallas(g, s, y, jnp.int32(m), jnp.float32(1.0))
    _rel_close(pal, ref, 1e-5)


def test_pallas_direction_vmap_jit():
    # the engine vmaps the direction over clients inside a jitted epoch
    K, m, n = 4, 6, 2500
    parts = [_history(m, n, 10 + k) for k in range(K)]
    ss = jnp.stack([p[0] for p in parts])
    ys = jnp.stack([p[1] for p in parts])
    gs = jnp.stack([p[2] for p in parts])
    cs = jnp.asarray([0, 2, 5, 6], jnp.int32)
    hs = jnp.asarray([1.0, 0.5, 2.0, 0.9], jnp.float32)
    ref = jax.vmap(compact_direction)(gs, ss, ys, cs, hs)
    pal = jax.jit(jax.vmap(compact_direction_pallas))(gs, ss, ys, cs, hs)
    _rel_close(pal, ref, 1e-5)


def test_lbfgs_pallas_backend_end_to_end():
    # full optimizer agreement between 'pallas' and 'compact' backends on
    # a quadratic (f32; both paths share every non-direction op)
    rng = np.random.RandomState(12)
    mm = rng.randn(16, 16)
    a = jnp.asarray(mm @ mm.T + 16 * np.eye(16), jnp.float32)
    b = jnp.asarray(rng.randn(16), jnp.float32)

    def loss(x):
        return 0.5 * x @ (a @ x) - b @ x

    xs = {}
    for method in ("compact", "pallas"):
        cfg = LBFGSConfig(
            max_iter=10, history_size=5, line_search=True, direction=method
        )
        x = jnp.zeros((16,), jnp.float32)
        state = lbfgs_init(x, cfg)
        for _ in range(3):
            x, state, _ = lbfgs_step(loss, x, state, cfg)
        xs[method] = np.asarray(x)
    _rel_close(xs["pallas"], xs["compact"], 1e-4)
    # and it actually minimizes
    x_star = np.linalg.solve(np.asarray(a), np.asarray(b))
    assert np.linalg.norm(xs["pallas"] - x_star) < 1e-2 * np.linalg.norm(x_star)
