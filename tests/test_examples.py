"""The examples are contracts too: run the federated-LM capstone.

`examples/federated_lm.py` composes the framework's two halves — the
reference's partial-parameter FedAvg recipe (common init, per-group
L-BFGS epochs, masked psum averaging, per-client eval) applied to
TransformerLM clients over client-biased token streams. The example
asserts its own invariants (group sync bit-equality, accuracy >= 5x
chance); this test runs it end-to-end in a fresh interpreter the way a
user would.
"""

import os
import subprocess
import sys

import pytest

from federated_pytorch_test_tpu.utils import compile_cache_dir

pytestmark = pytest.mark.slow  # heavy tier (jit-compile dominated)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_federated_lm_example_learns():
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        JAX_PLATFORMS="cpu",
        NLOOP="1",
        K="4",
        SEQ="32",
        # fresh interpreter, no conftest: reuse the persistent compile
        # cache so repeat CI runs skip the example's XLA compiles
        JAX_COMPILATION_CACHE_DIR=compile_cache_dir(),
        TF_CPP_MIN_LOG_LEVEL="3",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "federated_lm.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=1500,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "final per-client next-token accuracy" in proc.stdout
