"""The examples are contracts too: run the federated-LM capstone.

`examples/federated_lm.py` composes the framework's two halves — the
reference's partial-parameter FedAvg recipe (common init, per-group
L-BFGS epochs, masked psum averaging, per-client eval) applied to
TransformerLM clients over client-biased token streams. The example
asserts its own invariants (group sync bit-equality, accuracy >= 5x
chance); this test runs it end-to-end in a fresh interpreter the way a
user would.
"""

import os
import subprocess
import sys

import pytest

from federated_pytorch_test_tpu.utils import compile_cache_dir

pytestmark = pytest.mark.slow  # heavy tier (jit-compile dominated)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_federated_lm_example_learns():
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
        JAX_PLATFORMS="cpu",
        NLOOP="1",
        K="4",
        SEQ="32",
        # fresh interpreter, no conftest: reuse the persistent compile
        # cache so repeat CI runs skip the example's XLA compiles
        JAX_COMPILATION_CACHE_DIR=compile_cache_dir(),
        TF_CPP_MIN_LOG_LEVEL="3",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "federated_lm.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=1500,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "final per-client next-token accuracy" in proc.stdout


def test_long_context_lm_example_runs_and_matches_dense():
    # the sequence-parallel recipe as a user runs it: 8-device virtual
    # ring, the script's own ring==dense loss identity, and two L-BFGS
    # steps on the copy task (tiny SEQ keeps compiles in seconds)
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        JAX_PLATFORMS="cpu",
        SEQ="64",
        STEPS="2",
        JAX_COMPILATION_CACHE_DIR=compile_cache_dir(),
        TF_CPP_MIN_LOG_LEVEL="3",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "long_context_lm.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=1500,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "ring == dense loss check" in proc.stdout
    # the two L-BFGS steps must improve the copy-task loss
    lines = {
        ln.split("=")[0].strip(): float(ln.split("=")[1].split()[0])
        for ln in proc.stdout.splitlines()
        if ln.startswith("loss[")
    }
    assert lines["loss[2]"] < lines["loss[0]"], proc.stdout


def test_pod_scale64_example_smoke(tmp_path):
    # the pod recipe script end to end on the dev box: the SAME
    # initialize_distributed -> multihost_client_mesh -> Trainer.run ->
    # recorder.save path a pod runs, shrunk via the script's env
    # overrides (K=8 simple-CNN clients, one group, one round)
    out = tmp_path / "scale64_metrics.json"
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
        JAX_PLATFORMS="cpu",
        # NTRAIN/NTEST only shrink the SYNTHETIC fallback; point the data
        # root at an empty dir so a real archive on the host can't turn
        # the smoke test into a full-CIFAR run
        CIFAR_DATA_DIR=str(tmp_path / "no-archive-here"),
        K="8",
        MODEL="net",
        NLOOP="1",
        NADMM="1",
        BATCH="4",
        NTRAIN="64",
        NTEST="16",
        MAX_GROUPS="1",
        METRICS_OUT=str(out),
        JAX_COMPILATION_CACHE_DIR=compile_cache_dir(),
        TF_CPP_MIN_LOG_LEVEL="3",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", "pod_scale64.py")],
        env=env,
        capture_output=True,
        text=True,
        timeout=1500,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "scale64 run complete" in proc.stdout
    import json

    rec = json.loads(out.read_text())["series"]  # MetricsRecorder.to_json
    # the scale64 presets run with check_results=False (throughput mode),
    # so the recorded series are losses/residuals, not accuracies
    assert rec["train_loss"], "no loss series recorded"
    import math

    assert all(
        math.isfinite(v) for r in rec["train_loss"] for v in r["value"]
    ), "non-finite training loss"
