"""Closed-loop fleet control: churn axis, auto-deadlines, telemetry-
steered cohorts, and the quarantine-release rule.

Smoke tier: churn-schedule purity + strict loading (the same regression
set the other four axes have), DeadlineController units, sampler
availability/telemetry units, config validation. Unmarked (middle)
tier: the tier-1 gates — the formerly-collapsing quarantine_z +
trimmed(1) @ K=3 combo now holds the accuracy gate (the PR-9 pitfall,
fixed by releasing quarantine at a <= 2f trusted cohort), and a
crashed+resumed `--round-deadline auto` run's stream is byte-identical
to its uninterrupted twin's (deadline decisions replayed from the
stream, never re-estimated). Slow tier: the fleet acceptance gate —
churn + stragglers + liars, where `auto` matches the fixed-deadline
sweep's best point and dominates the rest on the report's
convergence-vs-deadline frontier (the CLI flavor is scripts/ci.sh
fleet_smoke).
"""

import json

import numpy as np
import pytest

from federated_pytorch_test_tpu.clients import CohortSampler
from federated_pytorch_test_tpu.data import synthetic_cifar
from federated_pytorch_test_tpu.engine import Trainer, get_preset
from federated_pytorch_test_tpu.fault import SEED_FOLDS, FaultPlan
from federated_pytorch_test_tpu.obs import (
    DEADLINE_WARMUP_OBS,
    DeadlineController,
)

smoke = pytest.mark.smoke
slow = pytest.mark.slow


# ------------------------------------------------------------ churn schedule


@smoke
def test_churn_availability_pure_and_separately_folded():
    plan = FaultPlan(seed=3, dropout_p=0.4, corrupt_k=1, slow_k=2,
                     churn_p=0.3, churn_mean_absence=2.0)
    a0 = plan.availability(64, 4)
    a1 = FaultPlan(
        seed=3, dropout_p=0.4, corrupt_k=1, slow_k=2,
        churn_p=0.3, churn_mean_absence=2.0,
    ).availability(64, 4)
    # pure in (seed, nloop): a fresh plan derives the identical pool
    np.testing.assert_array_equal(a0, a1)
    assert 0 < a0.sum() < 64  # churn actually removed someone
    # different loops churn different pools over enough loops
    assert any(
        not np.array_equal(a0, plan.availability(64, t))
        for t in range(5, 12)
    )
    # separate seed fold: adding churn perturbs NO per-round schedule
    bare = FaultPlan(seed=3, dropout_p=0.4, corrupt_k=1, slow_k=2)
    np.testing.assert_array_equal(
        plan.participation(64, 0, 1, 2), bare.participation(64, 0, 1, 2)
    )
    np.testing.assert_array_equal(
        plan.corruption(64, 0, 1, 2)[0], bare.corruption(64, 0, 1, 2)[0]
    )
    np.testing.assert_array_equal(
        plan.client_speeds(64, 0, 1, 2), bare.client_speeds(64, 0, 1, 2)
    )
    # ...and the churn draws are not the dropout draws under another name
    assert not np.array_equal(
        plan.availability(64, 0), plan.participation(64, 0, 0, 0)
    )
    # a churn-free plan has everyone available
    assert bare.availability(64, 3).sum() == 64


@smoke
def test_churn_fold_registered_and_distinct():
    assert "churn" in SEED_FOLDS
    folds = list(SEED_FOLDS.values())
    assert len(folds) == len(set(folds)), SEED_FOLDS
    # legacy offsets untouched (the regression the registry exists for)
    assert SEED_FOLDS["dropout"] == 0
    assert SEED_FOLDS["straggler"] == 1
    assert SEED_FOLDS["corruption"] == 2
    assert SEED_FOLDS["speed"] == 3
    assert SEED_FOLDS["cohort"] == 4


@smoke
def test_churn_absences_persist_mean_absence_loops():
    # with certain departure every loop and mean_absence >> 1, a client
    # absent at loop t must (almost surely) still be absent at t+1 —
    # the renewal construction carries in-flight absences forward
    plan = FaultPlan(seed=1, churn_p=1.0, churn_mean_absence=50.0)
    a3, a4 = plan.availability(256, 3), plan.availability(256, 4)
    gone3 = np.where(a3 == 0)[0]
    assert gone3.size > 200  # churn_p=1: nearly everyone is absent
    still_gone = (a4[gone3] == 0).mean()
    assert still_gone > 0.9, still_gone


@smoke
def test_plan_loader_rejects_bad_churn_fields():
    # strict JSON: range/type errors naming the field
    base = json.loads(FaultPlan(seed=1).to_json())
    for field, val, frag in (
        ("churn_p", 1.5, "churn_p"),
        ("churn_p", "0.3", "churn_p"),
        ("churn_mean_absence", 0.5, "churn_mean_absence"),
        ("churn_mean_absence", True, "churn_mean_absence"),
    ):
        d = dict(base)
        d[field] = val
        with pytest.raises(ValueError, match=frag):
            FaultPlan.from_json(json.dumps(d))
    # inline key: p alone, p:mean, malformed
    p = FaultPlan.parse("seed=2,churn=0.25")
    assert p.churn_p == 0.25 and p.churn_mean_absence == 2.0
    p = FaultPlan.parse("seed=2,churn=0.25:4")
    assert p.churn_mean_absence == 4.0
    with pytest.raises(ValueError, match="churn spec"):
        FaultPlan.parse("churn=0.2:3:4")
    # the unknown-key error advertises the new key
    with pytest.raises(ValueError, match="churn"):
        FaultPlan.parse("churns=0.2")


# ------------------------------------------------------- deadline controller


@smoke
def test_deadline_controller_warmup_then_sketch_and_replay():
    ctl = DeadlineController(0.5, warmup_s=4.0)
    dl, info = ctl.decide()
    assert (dl, info["source"]) == (4.0, "warmup")
    recs = [
        ("client_time", {"value": {"p95": float(v)}})
        for v in (3.0, 3.5, 4.0, 9.0, 3.2, 3.1)
    ]
    for name, rec in recs[: DEADLINE_WARMUP_OBS - 1]:
        ctl.observe(name, rec)
    assert ctl.decide()[1]["source"] == "warmup"  # still short one obs
    for name, rec in recs[DEADLINE_WARMUP_OBS - 1:]:
        ctl.observe(name, rec)
    dl, info = ctl.decide()
    assert info["source"] == "sketch" and info["n_obs"] == len(recs)
    assert 3.0 <= dl <= 4.0  # the p50 is not the 9.0 outlier
    # replay identity: a fresh controller fed the same records decides
    # identically (the crash+resume contract's unit form)
    twin = DeadlineController(0.5, warmup_s=4.0)
    twin.replay(recs)
    assert twin.decide() == ctl.decide()
    # non-client_time and malformed records are ignored
    ctl.observe("train_loss", {"value": [1.0]})
    ctl.observe("client_time", {"value": "garbage"})
    assert ctl.decide() == twin.decide()


@smoke
def test_config_round_deadline_auto_validation():
    cfg = get_preset("fedavg", round_deadline="auto")
    assert cfg.round_deadline == "auto:p50" and cfg.deadline_is_auto
    assert cfg.deadline_quantile == 0.5
    cfg = get_preset("fedavg", round_deadline="auto:p95")
    assert cfg.deadline_quantile == 0.95
    # numeric strings normalize to the float they always were (the CLI
    # hands everything through as a string now)
    cfg = get_preset("fedavg", round_deadline="4")
    assert cfg.round_deadline == 4.0 and not cfg.deadline_is_auto
    for bad in ("auto:p0", "auto:p100", "auto:", "never", "-2", "nan"):
        with pytest.raises(ValueError, match="round_deadline"):
            get_preset("fedavg", round_deadline=bad)


# ------------------------------------------------------------- sampler units


def _avail_every_other(nloop):
    # even loops: first half available; odd loops: everyone
    avail = np.ones(32, np.float32)
    if nloop % 2 == 0:
        avail[16:] = 0.0
    return avail


@smoke
def test_sampler_draws_only_from_available_pool():
    s = CohortSampler(32, 4, seed=5, availability=_avail_every_other)
    for nloop in (0, 2, 4):
        assert s.cohort(nloop).max() < 16
    # unrestricted loops can reach the whole population over time
    assert max(s.cohort(t).max() for t in (1, 3, 5, 7, 9)) >= 16
    # purity: a fresh sampler replays the identical schedule
    t = CohortSampler(32, 4, seed=5, availability=_avail_every_other)
    for nloop in range(6):
        np.testing.assert_array_equal(s.cohort(nloop), t.cohort(nloop))


@smoke
def test_sampler_recalls_absent_clients_when_pool_short():
    # only 2 of 32 available but C=4: the whole pool trains and the
    # remainder is recalled from the absent side, deterministically
    def nearly_empty(nloop):
        avail = np.zeros(32, np.float32)
        avail[[3, 7]] = 1.0
        return avail

    s = CohortSampler(32, 4, seed=5, availability=nearly_empty)
    ids = s.cohort(0)
    assert ids.size == 4 and {3, 7} <= set(ids.tolist())
    t = CohortSampler(32, 4, seed=5, availability=nearly_empty)
    np.testing.assert_array_equal(ids, t.cohort(0))


@smoke
def test_sampler_telemetry_weighting_biases_and_validates():
    w = np.ones(32)
    w[0] = 100.0  # client 0 hugely reliable
    w[1] = 1e-3   # client 1 flaky
    s = CohortSampler(32, 4, seed=9, weighting="telemetry",
                      telemetry_weights=lambda: w)
    counts = np.zeros(32)
    for nloop in range(200):
        counts[s.cohort(nloop)] += 1
    assert counts[0] > counts.mean() * 2
    assert counts[1] < counts.mean() / 2
    # provider contract: [N] finite positive — anything else is refused
    for bad in (np.zeros(32), np.ones(31), np.full(32, np.nan)):
        b = CohortSampler(32, 4, seed=9, weighting="telemetry",
                          telemetry_weights=lambda bad=bad: bad)
        with pytest.raises(ValueError, match="telemetry_weights"):
            b.cohort(0)
    with pytest.raises(ValueError, match="telemetry"):
        CohortSampler(32, 4, weighting="telemetry")
    # seeded history REPLAYS instead of re-drawing (the resume substrate)
    r = CohortSampler(32, 4, seed=9, weighting="telemetry",
                      telemetry_weights=lambda: np.ones(32))
    r.seed_history(0, [9, 3, 30, 17])
    np.testing.assert_array_equal(r.cohort(0), [3, 9, 17, 30])
    with pytest.raises(ValueError, match="seeded cohort"):
        r.seed_history(1, [1, 2])


# ------------------------------------------------ trainer-level (mid tier)


@pytest.fixture(scope="module")
def _src():
    return synthetic_cifar(n_train=240, n_test=60)


def _tiny(preset="fedavg", **over):
    base = dict(
        batch=40, nloop=1, nadmm=2, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
    )
    base.update(over)
    return get_preset(preset, **base)


def test_churn_requires_virtual_clients(_src):
    with pytest.raises(ValueError, match="churn"):
        Trainer(
            _tiny(fault_plan="seed=1,churn=0.3"), verbose=False, source=_src
        )
    with pytest.raises(ValueError, match="identity"):
        Trainer(
            _tiny(
                fault_plan="seed=1,churn=0.3", virtual_clients=3, cohort=3,
                cohort_weighting="identity",
            ),
            verbose=False,
            source=_src,
        )


# slow tier per the PR-9 rule (three trainer runs, ~29 s — the tier-1
# wall sits at the 870 s driver budget); tier-2 fleet_smoke holds the
# same crashed+resumed-equals-twin contract, deadline records included,
# through the real CLI every CI run
@pytest.mark.slow
def test_auto_deadline_crash_resume_stream_identity(
    _src, tmp_path, norm_stream
):
    """THE auto-deadline replay gate: a crashed+resumed
    `--round-deadline auto` run's metrics stream is byte-identical to
    its uninterrupted twin's — every `deadline` decision re-derived
    from the replayed sketch state, never re-estimated fresh — and the
    stream shows the warmup -> sketch handover."""
    from federated_pytorch_test_tpu.fault import InjectedCrash

    # the tier-1 wall pays for every second here (3 trainer processes):
    # a private 120-sample source gives ONE lockstep step per epoch at
    # batch 40, 3x3 exchanges outgrow the 5-observation warmup (loop
    # 2's decision is sketch-sourced — the replay matters exactly when
    # the sketch is live), the crash lands in the LAST loop so the
    # resumed process re-runs one loop, and only the runs that RESUME
    # checkpoint (the twin's trajectory and stream are
    # checkpoint-independent)
    src = synthetic_cifar(n_train=120, n_test=30)

    def cfga(tag, plan, save=True):
        return _tiny(
            nloop=3, nadmm=3, save_model=save,
            fault_plan=plan, round_deadline="auto",
            checkpoint_dir=str(tmp_path / tag),
            metrics_stream=str(tmp_path / f"{tag}.jsonl"),
        )

    plan = "seed=6,slow=1:3"
    cfg_a = cfga("a", plan, save=False)
    tr_a = Trainer(cfg_a, verbose=False, source=src)
    tr_a.run()
    tr_a.close()
    dls = [
        (r["value"]["source"], r["value"]["seconds"])
        for r in tr_a.recorder.series["deadline"]
    ]
    assert dls[0][0] == "warmup"
    assert dls[-1][0] == "sketch"  # 3x3 exchanges outgrow the warmup

    gid = tr_a.group_order[0]
    cfg_b = cfga("b", f"{plan},crash=2:{gid}:0")
    tr_b = Trainer(cfg_b, verbose=False, source=src)
    with pytest.raises(InjectedCrash):
        tr_b.run()
    tr_b.close()
    # resuming WITHOUT a stream to replay the decisions from is refused
    # (a cold sketch would silently shift every post-resume budget)
    with pytest.raises(ValueError, match="metrics-stream"):
        Trainer(
            cfg_b.replace(resume="auto", metrics_stream=None),
            verbose=False, source=src,
        )
    tr_b2 = Trainer(cfg_b.replace(resume="auto"), verbose=False, source=src)
    assert tr_b2._completed_nloops == 2
    # the resumed controller replayed the stream: its memoized decisions
    # cover the completed loops' rounds
    assert (0, gid) in tr_b2._deadline_decisions
    tr_b2.run()
    tr_b2.close()
    assert norm_stream(tmp_path / "a.jsonl") == norm_stream(
        tmp_path / "b.jsonl"
    )
    # the scoreboard's deadline rows survive resume (dict-valued lookup)
    inj_a = dict(tr_a.recorder.latest("injected_faults"))
    inj_b = dict(tr_b2.recorder.latest("injected_faults"))
    assert inj_a["deadline_misses"] == inj_b["deadline_misses"] > 0


def test_quarantine_release_restores_trimmed_accuracy(
    src_hard_accept, fault_free_accept, accept_cfg
):
    """The PR-9 pitfall, fixed: quarantine_z=1.0 + trimmed(1) at K=3
    used to collapse accuracy ~40 points (the mid-round quarantine left
    trimmed(1)-of-2 trimming every coordinate and keeping z). With the
    release rule — the quarantine mask stands down for any exchange
    whose trusted cohort would be <= 2f — the combo now holds the
    2-point acceptance gate while the quarantine DETECTION still fires
    on the liar, every exchange stays at 3 survivors, and no uplink is
    attributed as wasted (released suspects' bytes are consumed).
    Deliberately NOT the old never-gated combo test: this one gates
    accuracy, which is the point of the fix."""
    tr = Trainer(
        accept_cfg(
            fault_plan="seed=7,corrupt=1:scale:10",
            robust_agg="trimmed", robust_f=1, quarantine_z=1.0,
        ),
        verbose=False, source=src_hard_accept,
    )
    tr.run()
    kinds = {r["value"]["kind"] for r in tr.recorder.series.get("fault", [])}
    assert "round_rollback" not in kinds
    acc = float(np.mean(tr.recorder.latest("test_accuracy")))
    acc_free = float(np.mean(fault_free_accept.recorder.latest(
        "test_accuracy"
    )))
    assert abs(acc - acc_free) <= 0.02, (acc, acc_free)
    # detection unchanged: the liar is still flagged...
    assert tr.recorder.series.get("quarantine"), "quarantine never fired"
    # ...but the release keeps every exchange at full participation
    # (trusted cohort would be 2 <= 2f, so the mask stands down)
    assert all(
        r["value"]["survivors"] == 3
        for r in tr.recorder.series["participation"]
    )
    assert not tr.recorder.latest("comm_summary").get(
        "bytes_quarantined_wasted"
    )
    tr.close()


# ------------------------------------------------------ fleet acceptance


@slow
def test_fleet_acceptance_auto_beats_fixed_sweep(tmp_path):
    """ROADMAP item 3's acceptance, pytest flavor (the 10k-phone CLI
    flavor is scripts/ci.sh fleet_smoke): a virtual fleet with churn,
    Bernoulli 4x stragglers, and corrupting liars, swept over three
    fixed deadlines — too-tight (below one nominal step: no client ever
    reports, accuracy stays at chance), mid, and slowest-full-work —
    plus `auto`. The report's convergence-vs-deadline frontier must
    show `auto` reaching the sweep's best accuracy at a simulated round
    wall <= the best-accuracy fixed point's, Pareto-undominated, and
    for every OTHER fixed point either strictly more accurate (the
    too-tight pick) or strictly cheaper at no accuracy cost (the
    too-long picks) — and the folded dispatch stays
    {round: 1, round_init: 1} with the whole closed loop in-program."""
    from federated_pytorch_test_tpu.obs.registry import RunRegistry

    src = synthetic_cifar(
        n_train=8 * 20 * 2, n_test=240, label_noise=0.25, overlap=0.35
    )
    total = 2  # 40-sample shards at batch 20
    slow_f = 4.0
    base = dict(
        batch=20, nloop=6, nadmm=2, max_groups=1, model="net",
        check_results=True, eval_batch=80, synthetic_ok=True,
        virtual_clients=64, cohort=8, data_shards=8,
        cohort_weighting="telemetry", store_chunk_clients=8,
        robust_agg="trimmed", robust_f=1,
        fault_plan=(
            f"seed=11,churn=0.1:2,slow=0.08:{slow_f:g},"
            "corrupt=0.05:scale:10"
        ),
    )
    sweeps = {
        "fx_tight": 0.5,  # < one nominal step: nobody ever reports
        "fx_mid": float(total) * 2.0,
        "fx_slowest": float(total) * slow_f,
        "auto": "auto",
    }
    for label, deadline in sweeps.items():
        cfg = get_preset(
            "fedavg", **base, round_deadline=deadline,
            checkpoint_dir=str(tmp_path / f"ck_{label}"),
            metrics_stream=str(tmp_path / f"{label}.jsonl"),
        )
        tr = Trainer(cfg, verbose=False, source=src)
        tr.run()
        for r in tr.recorder.series["dispatch_count"]:
            assert r["value"] == {"round": 1, "round_init": 1, "total": 2}
        tr.close()

    reg = RunRegistry()
    assert not reg.ingest_dir(str(tmp_path))
    doc = reg.report()
    front = {p["run"]: p for p in doc["deadline_frontier"]}
    assert set(front) == set(sweeps)
    auto = front["auto"]
    fixed = [front[k] for k in sweeps if k != "auto"]
    best_fixed = max(
        fixed, key=lambda p: (p["final_accuracy"], -p["sim_round_wall_s"])
    )
    # auto reaches the sweep's best accuracy at <= the best point's wall
    assert auto["final_accuracy"] >= best_fixed["final_accuracy"] - 0.02
    assert auto["sim_round_wall_s"] <= best_fixed["sim_round_wall_s"] + 1e-9
    assert auto["pareto"], doc["deadline_frontier"]
    # ...and strictly beats every OTHER fixed deadline on the frontier:
    # strictly more accurate than the too-tight pick, strictly cheaper
    # than the too-long ones at no accuracy cost
    for p in fixed:
        if p is best_fixed:
            continue
        beats_on_accuracy = auto["final_accuracy"] > p["final_accuracy"] + 0.02
        beats_on_wall = (
            auto["sim_round_wall_s"] < p["sim_round_wall_s"] - 1e-9
            and auto["final_accuracy"] >= p["final_accuracy"] - 0.02
        )
        assert beats_on_accuracy or beats_on_wall, (p, auto)
    # the too-tight pick really is the degenerate regime (nobody
    # reports, accuracy at chance) — the asymmetry the closed loop is
    # worth running for
    assert front["fx_tight"]["final_accuracy"] < 0.3
