"""CLI surface tests: the `python -m federated_pytorch_test_tpu` driver."""

import pytest

import json
import os
import subprocess
import sys

pytestmark = pytest.mark.slow  # heavy tier (jit-compile dominated)

from federated_pytorch_test_tpu.utils import compile_cache_dir

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    XLA_FLAGS="--xla_force_host_platform_device_count=8",
    # the CLI subprocess is a fresh interpreter with no conftest: point
    # it at the same persistent compile cache so repeat CI runs skip the
    # XLA compiles (the CLI honors the standard jax env var)
    JAX_COMPILATION_CACHE_DIR=compile_cache_dir(),
    TF_CPP_MIN_LOG_LEVEL="3",
)


def _run(*args, timeout=600):
    return subprocess.run(
        [sys.executable, "-m", "federated_pytorch_test_tpu", *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=ENV,
    )


def test_list_presets():
    r = _run("--list-presets", timeout=120)
    assert r.returncode == 0, r.stderr
    for name in ("fedavg", "admm_resnet", "fedavg_scale64"):
        assert name in r.stdout


def test_unknown_preset_rejected():
    r = _run("--preset", "nope", timeout=120)
    assert r.returncode != 0
    assert "invalid choice" in r.stderr


def test_tiny_training_run_with_metrics_out(tmp_path):
    out = tmp_path / "metrics.json"
    empty = tmp_path / "no-archive"
    empty.mkdir()
    r = _run(
        "--preset", "fedavg",
        "--model", "net",
        # deterministic synthetic fallback: an empty data root, so a real
        # archive on this machine can't silently replace the tiny dataset
        "--data-root", str(empty),
        "--batch", "40",
        "--nloop", "1",
        "--nepoch", "1",
        "--nadmm", "1",
        "--n-clients", "4",
        "--synthetic-n-train", "480",
        "--synthetic-n-test", "64",
        # two of net's five groups: the CLI surface under test (arg
        # parsing, training dispatch, metrics writing) is identical per
        # group, and each extra group is another program to trace
        "--max-groups", "2",
        "--no-check-results",
        "--quiet",
        "--metrics-out", str(out),
    )
    assert r.returncode == 0, r.stderr[-2000:]
    doc = json.loads(out.read_text())  # envelope: series + nonfinite cursor
    series = doc["series"]
    assert doc["first_nonfinite"] is None  # healthy run
    assert "train_loss" in series and "dual_residual" in series
    assert len(series["train_loss"][-1]["value"]) == 4  # per-client losses
    # the observability summary lines made it to stdout
    assert "# series:" in r.stdout and "# comm:" in r.stdout
