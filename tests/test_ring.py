"""Ring attention / sequence parallelism tests (8-device CPU mesh).

The ring path must be EXACTLY dense attention (same math, blockwise online
softmax), so every test compares against `dense_attention` on the
unsharded sequence: forward (causal and not), gradients, a multi-block
seq-parallel transformer stack, and the ViT family's engine integration.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from federated_pytorch_test_tpu.parallel import shard_map

from federated_pytorch_test_tpu.parallel import (
    SEQ_AXIS,
    dense_attention,
    ring_attention,
)

pytestmark = pytest.mark.slow  # heavy tier (jit-compile dominated)


def _seq_mesh(p=8):
    devs = jax.devices()
    if len(devs) < p:
        pytest.skip(f"need {p} devices, have {len(devs)}")
    return Mesh(np.asarray(devs[:p]), (SEQ_AXIS,))


def _qkv(b=2, s=64, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


def _ring_apply(mesh, q, k, v, causal):
    spec = P(None, SEQ_AXIS, None, None)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=SEQ_AXIS, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    mesh = _seq_mesh()
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = _ring_apply(mesh, q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


def test_ring_gradients_match_dense():
    mesh = _seq_mesh()
    q, k, v = _qkv(seed=1)

    def loss_ring(q, k, v):
        return jnp.sum(_ring_apply(mesh, q, k, v, True) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5)


def test_ring_uneven_heads_and_scale():
    # non-default sm_scale and head sizes exercise the scale plumb
    mesh = _seq_mesh()
    q, k, v = _qkv(b=1, s=32, h=2, d=16, seed=2)
    ref = dense_attention(q, k, v, sm_scale=0.05)
    spec = P(None, SEQ_AXIS, None, None)
    out = shard_map(
        functools.partial(ring_attention, axis_name=SEQ_AXIS, sm_scale=0.05),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_matches_dense(causal):
    # ring schedule x Pallas flash block kernel (use_flash=True): the
    # two-level streaming path must still be EXACT dense attention.
    # 4 devices x 128-token local shards (the kernel's tile height).
    mesh = _seq_mesh(p=4)
    q, k, v = _qkv(b=1, s=512, h=2, d=16, seed=11)
    ref = dense_attention(q, k, v, causal=causal)
    spec = P(None, SEQ_AXIS, None, None)
    out = shard_map(
        functools.partial(
            ring_attention, axis_name=SEQ_AXIS, causal=causal, use_flash=True
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,  # pallas interpret mode can't propagate vma
    )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=3e-5, atol=3e-6
    )


def test_ring_flash_gradients_match_dense():
    mesh = _seq_mesh(p=4)
    q, k, v = _qkv(b=1, s=512, h=1, d=16, seed=12)
    spec = P(None, SEQ_AXIS, None, None)
    ring_fn = shard_map(
        functools.partial(
            ring_attention, axis_name=SEQ_AXIS, causal=True, use_flash=True
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,  # pallas interpret mode can't propagate vma
    )

    def loss_ring(q, k, v):
        return jnp.sum(ring_fn(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4,
            err_msg=f"d{name}",
        )


def test_ring_flash_transformer_block_matches_dense():
    # the MODEL-LEVEL wiring: a transformer Block with
    # attn_impl='ring_flash' running sequence-sharded == the dense block
    # (the 'flash' and 'ring' branches have analogous end-to-end tests)
    from federated_pytorch_test_tpu.models.transformer import Block

    mesh = _seq_mesh(p=4)
    rng = np.random.default_rng(13)
    b, s, dim = 1, 512, 16  # 128 tokens/device: the kernel tile height
    x = jnp.asarray(rng.normal(size=(b, s, dim)), jnp.float32)

    dense_blk = Block(dim, 2, attn_impl="dense", name="b0")
    rf_blk = Block(dim, 2, attn_impl="ring_flash", name="b0")
    params = dense_blk.init(jax.random.PRNGKey(0), x)
    ref = dense_blk.apply(params, x)

    out = shard_map(
        lambda xs: rf_blk.apply(params, xs),
        mesh=mesh,
        in_specs=P(None, SEQ_AXIS, None),
        out_specs=P(None, SEQ_AXIS, None),
        check_vma=False,  # pallas interpret mode can't propagate vma
    )(x)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5
    )


def test_seq_parallel_block_stack_matches_dense():
    # a 2-block transformer stack running fully sequence-sharded (ring
    # attention; LN/MLP/residual are per-token) == the dense stack
    from federated_pytorch_test_tpu.models.transformer import Block

    mesh = _seq_mesh()
    rng = np.random.default_rng(3)
    b, s, dim = 2, 64, 32
    x = jnp.asarray(rng.normal(size=(b, s, dim)), jnp.float32)

    dense1 = Block(dim, 4, attn_impl="dense", name="b0")
    ring1 = Block(dim, 4, attn_impl="ring", name="b0")
    params = dense1.init(jax.random.PRNGKey(0), x)

    ref = dense1.apply(params, x)

    fn = shard_map(
        lambda xs: ring1.apply(params, xs),
        mesh=mesh,
        in_specs=P(None, SEQ_AXIS, None),
        out_specs=P(None, SEQ_AXIS, None),
    )
    out = fn(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)


def test_client_seq_mesh_composes_federated_and_ring():
    # 2 clients x 4-device sequence rings on one 2-D mesh: each client
    # runs a ring-attention transformer block on its own params over its
    # own sequence shard, then a client-axis collective averages a
    # statistic — both communication patterns in ONE shard_map, matching
    # the per-client dense reference exactly
    from federated_pytorch_test_tpu.models.transformer import Block
    from federated_pytorch_test_tpu.parallel import (
        CLIENT_AXIS,
        client_mean,
        client_seq_mesh,
    )

    if len(jax.devices()) < 8:
        pytest.skip("need 8 devices")
    mesh = client_seq_mesh(2, 4)

    rng = np.random.default_rng(7)
    k, b, s, dim = 2, 2, 32, 16
    x = jnp.asarray(rng.normal(size=(k, b, s, dim)), jnp.float32)

    dense_blk = Block(dim, 4, attn_impl="dense", name="b0")
    ring_blk = Block(dim, 4, attn_impl="ring", name="b0")
    params = jax.vmap(
        lambda key: dense_blk.init(key, x[0])
    )(jax.random.split(jax.random.PRNGKey(0), k))  # per-client params

    ref = jnp.stack(
        [
            dense_blk.apply(jax.tree.map(lambda p: p[i], params), x[i])
            for i in range(k)
        ]
    )
    ref_stat = jnp.mean(jnp.sum(ref**2, axis=(1, 2, 3)))

    def body(params_loc, xs):
        # [1, b, s/4, dim] local shard; one client per mesh row
        out = ring_blk.apply(jax.tree.map(lambda p: p[0], params_loc), xs[0])
        stat = client_mean(
            jnp.sum(out**2)[None, None], axis_name=CLIENT_AXIS
        )  # [1]: psum over clients of this device's seq-shard partial
        return out[None], stat

    pspec = jax.tree.map(lambda _: P(CLIENT_AXIS), params)
    out, stat = shard_map(
        body,
        mesh=mesh,
        in_specs=(pspec, P(CLIENT_AXIS, None, SEQ_AXIS, None)),
        out_specs=(P(CLIENT_AXIS, None, SEQ_AXIS, None), P((CLIENT_AXIS, SEQ_AXIS))),
    )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)
    # stat: each device computed mean over clients of its seq-shard's
    # partial sum; summing the 2 identical client rows x 4 shard partials
    # recovers the global statistic
    np.testing.assert_allclose(
        float(np.asarray(stat).reshape(2, 4)[0].sum()),
        float(ref_stat),
        rtol=2e-4,
    )


def test_transformer_lm_seq_parallel_forward():
    # the FULL causal LM (embedding + positions + blocks + head) run
    # sequence-sharded with ring attention == the dense unsharded model
    from federated_pytorch_test_tpu.models import TransformerLM

    mesh = _seq_mesh()
    rng = np.random.default_rng(8)
    b, s = 2, 64
    tokens = jnp.asarray(rng.integers(0, 256, size=(b, s)), jnp.int32)

    dense_lm = TransformerLM(attn_impl="dense", dim=32, num_heads=2)
    ring_lm = TransformerLM(attn_impl="ring", dim=32, num_heads=2)
    params = dense_lm.init(jax.random.PRNGKey(0), tokens)

    ref = dense_lm.apply(params, tokens)  # [B, S, V]

    def body(tok_shard):
        # contiguous shard => global positions from the ring index
        p = jax.lax.psum(1, SEQ_AXIS)
        my = jax.lax.axis_index(SEQ_AXIS)
        blk = s // p
        positions = (my * blk + jnp.arange(blk))[None, :]
        return ring_lm.apply(params, tok_shard, positions=positions)

    out = shard_map(
        body,
        mesh=mesh,
        in_specs=P(None, SEQ_AXIS),
        out_specs=P(None, SEQ_AXIS, None),
    )(tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=3e-4, atol=3e-5)


def test_transformer_lm_trains_with_lbfgs():
    # long-context family x the framework's own inner optimizer: next-token
    # loss on a periodic sequence drops fast through the flat-vector API
    from federated_pytorch_test_tpu.models import TransformerLM
    from federated_pytorch_test_tpu.optim import LBFGSConfig, lbfgs_init, lbfgs_step
    from federated_pytorch_test_tpu.partition import flatten_params

    import optax

    lm = TransformerLM(dim=32, num_heads=2, vocab=16)
    rng = np.random.default_rng(9)
    base = rng.integers(0, 16, size=8)
    seq = jnp.asarray(np.tile(base, 9)[: 64 + 1], jnp.int32)  # periodic
    tokens, targets = seq[None, :-1], seq[None, 1:]

    params = lm.init(jax.random.PRNGKey(0), tokens)["params"]
    flat, unravel = flatten_params(params)

    def loss_fn(f):
        logits = lm.apply({"params": unravel(f)}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), targets
        ).mean()

    cfg = LBFGSConfig(max_iter=4, history_size=10, line_search=True, batch_mode=True)
    state = lbfgs_init(flat, cfg)
    step = jax.jit(lambda f, s: lbfgs_step(loss_fn, f, s, cfg))
    l0 = float(loss_fn(flat))
    for _ in range(10):
        flat, state, _ = step(flat, state)
    l1 = float(loss_fn(flat))
    assert l1 < 0.5 * l0, (l0, l1)

    # partition metadata: head group alone is regularizable
    part = lm.partition(params)
    assert part.num_groups == 6 and part.linear_group_ids == (5,)
    assert sum(part.group_size(g) for g in range(6)) == part.total


def test_vit_partition_and_forward():
    from federated_pytorch_test_tpu.models import ViT

    model = ViT(num_classes=100, dim=32)
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x, train=False)["params"]
    logits = model.apply({"params": params}, x, train=False)
    assert logits.shape == (2, 100)

    part = ViT.partition(params)
    assert part.num_groups == 6
    assert part.linear_group_ids == (5,)
    # every parameter belongs to exactly one group (build_partition raises
    # otherwise); sizes must sum to the total
    assert sum(part.group_size(g) for g in range(6)) == part.total
    # the regularized group is the classifier head ALONE (dim x classes
    # weight + bias) — LayerNorm params must never receive elastic net
    assert part.group_size(5) == 32 * 100 + 100


def test_seq_shard_roundtrip():
    from federated_pytorch_test_tpu.parallel import seq_shard, seq_unshard

    mesh = _seq_mesh()
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.normal(size=(2, 64, 5)), jnp.float32)

    def rt(xs):
        local = seq_shard(xs)
        assert local.shape == (2, 8, 5)
        return seq_unshard(local)

    # the gathered result is equal on every device but the varying-axis
    # checker can't prove it (the shard index is device-dependent)
    out = shard_map(
        rt, mesh=mesh, in_specs=P(), out_specs=P(SEQ_AXIS), check_vma=False
    )(x)
    out = out.reshape(-1, *x.shape[1:])[: x.shape[0]]  # first device's copy
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_vit_trains_in_engine():
    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    src = synthetic_cifar(n_train=240, n_test=60)
    cfg = get_preset(
        "fedavg", model="vit", batch=40, nloop=1, nadmm=2, check_results=False
    )
    tr = Trainer(cfg, verbose=False, source=src)
    tr.group_order = tr.group_order[:2]
    rec = tr.run()
    losses = rec.series["train_loss"]
    assert np.mean(losses[-1]["value"]) < np.mean(losses[0]["value"])
    flat = np.asarray(tr.flat)
    gid = tr.group_order[-1]
    for seg in tr.partition.groups[gid]:
        blk = flat[:, seg.start : seg.start + seg.size]
        assert np.abs(blk - blk[:1]).max() == 0.0


def test_three_axis_mesh_composes_tp_and_ring():
    # the 3-axis composition proof (round-4 VERDICT item 2): one
    # (clients, model, seq) mesh where TP shards each client's qkv/proj
    # pairs over `model` (GSPMD auto axes), ring attention shards the
    # sequence over `seq`, and a consensus collective reduces over
    # `clients` — all in ONE hybrid shard_map (manual clients+seq, auto
    # model via jax.shard_map's axis_names), numerically identical to
    # the per-client single-device dense reference.
    from federated_pytorch_test_tpu.models.transformer import Block
    from federated_pytorch_test_tpu.parallel import (
        CLIENT_AXIS,
        client_mean,
        client_model_seq_mesh,
        tp_param_specs,
    )

    if len(jax.devices()) < 8:
        pytest.skip("need 8 devices")
    dc, dm, ds = 2, 2, 2
    mesh3 = client_model_seq_mesh(dc, dm, ds)

    rng = np.random.default_rng(3)
    b, s, dim, heads = 1, 32, 16, 2  # dm divides heads: head-local TP
    x = jnp.asarray(rng.normal(size=(dc, b, s, dim)), jnp.float32)

    dense_blk = Block(dim, heads, attn_impl="dense", causal=True, name="b0")
    ring_blk = Block(dim, heads, attn_impl="ring", causal=True, name="b0")
    params = jax.vmap(lambda key: dense_blk.init(key, x[0]))(
        jax.random.split(jax.random.PRNGKey(0), dc)
    )

    ref = jnp.stack([
        dense_blk.apply(jax.tree.map(lambda p: p[i], params), x[i])
        for i in range(dc)
    ])
    ref_stat = jnp.sum(ref**2) / dc

    # TP shardings apply unchanged on the 3-axis mesh (specs only name
    # clients/model; seq never appears in a param spec)
    specs = {"params": tp_param_specs(
        params["params"], client_axis=True, mesh=mesh3)}
    assert specs["params"]["attn"]["qkv"]["kernel"] == P(
        CLIENT_AXIS, None, "model")
    sh_params = jax.tree.map(
        lambda a, sp: jax.device_put(a, NamedSharding(mesh3, sp)),
        params, specs)
    sh_x = jax.device_put(
        x, NamedSharding(mesh3, P(CLIENT_AXIS, None, SEQ_AXIS, None)))

    def body(params_loc, xs):
        out = ring_blk.apply(jax.tree.map(lambda p: p[0], params_loc), xs[0])
        stat = client_mean(jnp.sum(out**2)[None, None], axis_name=CLIENT_AXIS)
        return out[None], stat

    pspec = jax.tree.map(lambda _: P(CLIENT_AXIS), params)
    fwd = shard_map(
        body,
        mesh=mesh3,
        in_specs=(pspec, P(CLIENT_AXIS, None, SEQ_AXIS, None)),
        out_specs=(P(CLIENT_AXIS, None, SEQ_AXIS, None),
                   P((CLIENT_AXIS, SEQ_AXIS))),
        axis_names={CLIENT_AXIS, SEQ_AXIS},
        check_vma=False,
    )
    compiled = jax.jit(fwd).lower(sh_params, sh_x).compile()
    out, stat = compiled(sh_params, sh_x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    # the consensus stat: each seq position holds the client-mean of its
    # local partial (the model-sharded dims are already reduced by GSPMD
    # inside the body); one client row's seq partials sum to the global
    parts = np.asarray(stat).reshape(dc, ds)
    np.testing.assert_allclose(parts[0].sum(), float(ref_stat), rtol=2e-4)
    # TP is ACTIVE inside the hybrid body, not silently all-gathered
    # away: the compiled program carries cross-device reduces beyond the
    # single consensus psum — a replicated-params run of the same body
    # has only the consensus collective
    hlo = compiled.as_text()
    assert "all-reduce" in hlo or "reduce-scatter" in hlo

    # gradients flow through all three axes at once
    def loss(p, xx):
        o, _ = fwd(p, xx)
        return jnp.sum(o**2)

    gr = jax.jit(jax.grad(loss))(sh_params, sh_x)
    gq = gr["params"]["attn"]["qkv"]["kernel"]
    assert gq.sharding.spec == P(CLIENT_AXIS, None, "model")  # stays sharded
    gn = np.sqrt(sum(float(np.sum(np.square(g)))
                     for g in jax.tree.leaves(gr)))
    assert np.isfinite(gn) and gn > 0.0
