"""REAL multi-process distributed test: 2 OS processes, one client mesh.

The rest of the suite simulates the cluster with 8 virtual devices in ONE
process; here two separate processes (4 virtual CPU devices each) join a
JAX distributed runtime and run a FedAvg round on an 8-client mesh that
spans the process boundary — the closest this CI can get to multi-host
TPU (the process boundary stands in for DCN). Asserts:

* both processes finish and report IDENTICAL metrics (the SPMD contract);
* the consensus broadcast synchronized the active group across all 8
  clients, i.e. the weighted-psum collective crossed processes;
* the run matches the SAME workload on a single-process 8-device mesh
  (the multi-process data/placement paths change nothing numerically).

Slow (two interpreters, distributed init, fresh compiles): ~3-4 min on
the 1-core CI box.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # heavy tier (jit-compile dominated)

_WORKER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "multiprocess_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_workers(nproc: int, timeout: float = 480.0, ndev: int = 4,
                 mode: str = "resident"):
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    from federated_pytorch_test_tpu.utils import compile_cache_dir

    # fresh interpreters, no conftest: share the persistent compile cache
    env.setdefault("JAX_COMPILATION_CACHE_DIR", compile_cache_dir())
    env.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(i), str(nproc), str(port),
             str(ndev), mode],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
        assert all(p.returncode == 0 for p in procs), (
            "\n\n".join(o[-3000:] for o in outs)
        )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    results = []
    for out in outs:
        lines = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
        assert lines, out[-3000:]
        results.append(json.loads(lines[-1][len("RESULT "):]))
    return results


def test_two_process_fedavg_round_matches_single_process():
    r0, r1 = _run_workers(2)

    # SPMD: every process computed the same global story
    assert r0["gid"] == r1["gid"]
    np.testing.assert_allclose(r0["flat_sum"], r1["flat_sum"], rtol=0)
    np.testing.assert_allclose(r0["accs"], r1["accs"], rtol=0)
    np.testing.assert_allclose(r0["dual"], r1["dual"], rtol=0)
    # consensus crossed the process boundary: active group bit-identical
    # across all 8 clients
    assert r0["sync_err"] == 0.0

    # and the whole thing equals the single-process 8-device run
    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    if len(__import__("jax").devices()) < 8:
        pytest.skip("need 8 devices for the single-process twin")
    k = 8
    src = synthetic_cifar(n_train=8 * k, n_test=2 * k)
    cfg = get_preset(
        "fedavg", model="net", n_clients=k, batch=4, nloop=1, nadmm=1,
        check_results=False,
    )
    tr = Trainer(cfg, verbose=False, source=src)
    gid = tr.group_order[0]
    tr.run_round(nloop=0, gid=gid)
    flat_sum = float(np.float64(np.asarray(tr._fetch(tr.flat)).sum()))
    accs = [float(a) for a in tr.evaluate()]

    assert gid == r0["gid"]
    np.testing.assert_allclose(flat_sum, r0["flat_sum"], rtol=1e-6)
    np.testing.assert_allclose(accs, r0["accs"], rtol=0)


def test_four_process_hybrid_mesh_matches_single_process():
    # round-4 VERDICT item 4: the pod recipe's DCN-aware mesh layout runs
    # under test, not just its 2-process special case. 4 OS processes x 2
    # virtual devices join one 8-client mesh; on a sliceless backend each
    # process boundary is a DCN island, so multihost_client_mesh routes
    # through mesh_utils.create_hybrid_device_mesh (process_is_granule) —
    # the worker records the call. The workload is IDENTICAL to the
    # 2-process test (k=8, same data/config/seed), so the whole 4-way
    # run must reproduce the same metrics as a single-process 8-device
    # mesh: the layout path changes nothing numerically.
    results = _run_workers(4, timeout=600.0, ndev=2)

    r0 = results[0]
    # the hybrid/DCN-aware layout path actually built this mesh (the
    # worker's JSON round-trip makes the shape a list)
    assert r0["hybrid_dcn_shapes"] == [[4]]
    for r in results[1:]:
        assert r["gid"] == r0["gid"]
        np.testing.assert_allclose(r["flat_sum"], r0["flat_sum"], rtol=0)
        np.testing.assert_allclose(r["accs"], r0["accs"], rtol=0)
    assert r0["sync_err"] == 0.0  # consensus crossed 3 process boundaries

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    if len(__import__("jax").devices()) < 8:
        pytest.skip("need 8 devices for the single-process twin")
    k = 8
    src = synthetic_cifar(n_train=8 * k, n_test=2 * k)
    cfg = get_preset(
        "fedavg", model="net", n_clients=k, batch=4, nloop=1, nadmm=1,
        check_results=False,
    )
    tr = Trainer(cfg, verbose=False, source=src)
    gid = tr.group_order[0]
    tr.run_round(nloop=0, gid=gid)
    flat_sum = float(np.float64(np.asarray(tr._fetch(tr.flat)).sum()))
    accs = [float(a) for a in tr.evaluate()]

    assert gid == r0["gid"]
    np.testing.assert_allclose(flat_sum, r0["flat_sum"], rtol=1e-6)
    np.testing.assert_allclose(accs, r0["accs"], rtol=0)


def test_two_process_streaming_matches_single_process_streaming():
    # round-4 VERDICT item 8: streaming x multi-process, implemented as
    # HOST-SHARDED streaming — each process runs PrefetchBatchers only
    # for the clients its mesh devices own, and `_put` assembles the
    # global chunk from per-process columns. The streams are pure
    # functions of (seed, batch, client), so the 2-process run must
    # reproduce a single-process streaming run's metrics exactly.
    r0, r1 = _run_workers(2, mode="stream")
    assert r0["gid"] == r1["gid"]
    np.testing.assert_allclose(r0["flat_sum"], r1["flat_sum"], rtol=0)
    np.testing.assert_allclose(r0["accs"], r1["accs"], rtol=0)
    assert r0["sync_err"] == 0.0

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset

    if len(__import__("jax").devices()) < 8:
        pytest.skip("need 8 devices for the single-process twin")
    k = 8
    src = synthetic_cifar(n_train=8 * k, n_test=2 * k)
    cfg = get_preset(
        "fedavg", model="net", n_clients=k, batch=4, nloop=1, nadmm=1,
        check_results=False, hbm_data_budget_mb=0, stream_chunk_steps=1,
    )
    tr = Trainer(cfg, verbose=False, source=src)
    assert tr._stream and len(tr._batchers) == k  # all clients local here
    gid = tr.group_order[0]
    tr.run_round(nloop=0, gid=gid)
    flat_sum = float(np.float64(np.asarray(tr._fetch(tr.flat)).sum()))
    accs = [float(a) for a in tr.evaluate()]
    for b in tr._batchers.values():
        b.close()

    assert gid == r0["gid"]
    np.testing.assert_allclose(flat_sum, r0["flat_sum"], rtol=1e-6)
    np.testing.assert_allclose(accs, r0["accs"], rtol=0)
