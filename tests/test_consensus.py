"""Consensus strategy tests: FedAvg / ADMM / BB-rho vs. a literal numpy
re-derivation of the reference's sequential three-client arithmetic."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from federated_pytorch_test_tpu.parallel import shard_map
from jax.sharding import PartitionSpec as P

from federated_pytorch_test_tpu.consensus import (
    ADMMConfig,
    admm_init,
    admm_penalty,
    admm_round,
    elastic_net,
    fedavg_init,
    fedavg_round,
    soft_threshold,
)
from federated_pytorch_test_tpu.parallel import CLIENT_AXIS, client_mesh

pytestmark = pytest.mark.smoke  # fast CI tier

K, N = 3, 11


def _spmd(mesh, fn, *args, out_specs=None):
    """Run `fn` inside shard_map with client-sharded inputs."""
    out_specs = out_specs if out_specs is not None else P()
    return jax.jit(
        shard_map(
            fn,
            mesh=mesh,
            in_specs=tuple(P(CLIENT_AXIS) for _ in args),
            out_specs=out_specs,
        )
    )(*args)


@pytest.fixture(params=[1, 3], ids=["D1", "D3"])
def mesh(request):
    return client_mesh(request.param)


def test_fedavg_round_matches_reference(mesh):
    # reference src/federated_trio.py:353-363
    rng = np.random.default_rng(0)
    x = rng.normal(size=(K, N)).astype(np.float32)

    def body(xl):
        st = fedavg_init(N)
        st, metrics = fedavg_round(xl, st)
        return st.z, metrics["dual_residual"]

    z, dual = _spmd(mesh, body, jnp.asarray(x), out_specs=(P(), P()))
    np.testing.assert_allclose(z, x.mean(0), rtol=1e-6)
    # z starts at 0 => first dual residual is ||znew||/N (reference quirk)
    np.testing.assert_allclose(dual, np.linalg.norm(x.mean(0)) / N, rtol=1e-6)


def test_fedavg_equal_clients_is_noop(mesh):
    # property (SURVEY.md §4b): K identical clients -> the average equals
    # every client's x, so broadcasting z back changes nothing
    rng = np.random.default_rng(1)
    x1 = rng.normal(size=N).astype(np.float32)
    x = np.broadcast_to(x1, (K, N)).copy()

    def body(xl):
        st = fedavg_init(N)
        st, _ = fedavg_round(xl, st)
        return st.z

    z = _spmd(mesh, body, jnp.asarray(x), out_specs=P())
    np.testing.assert_allclose(np.asarray(z), x1, rtol=1e-6)


def test_admm_penalty_formula():
    rng = np.random.default_rng(1)
    x, y, z = (rng.normal(size=N).astype(np.float32) for _ in range(3))
    rho = np.float32(0.37)
    got = admm_penalty(jnp.asarray(x), jnp.asarray(y), jnp.asarray(z), jnp.asarray([rho]))
    want = y @ (x - z) + 0.5 * rho * ((x - z) @ (x - z))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def _numpy_admm_round(x, y, z, rho):
    """Literal reference arithmetic (src/consensus_admm_trio.py:502-514)."""
    znew = sum(y[k] + rho[k] * x[k] for k in range(K)) / rho.sum()
    dual = np.linalg.norm(z - znew) / N
    ynew = np.stack([y[k] + rho[k] * (x[k] - znew) for k in range(K)])
    primal = sum(np.linalg.norm(x[k] - znew) for k in range(K)) / (K * N)
    return znew, ynew, primal, dual


def test_admm_round_fixed_rho_matches_reference(mesh):
    rng = np.random.default_rng(2)
    cfg = ADMMConfig(rho0=0.001, bb_update=False)
    x1 = rng.normal(size=(K, N)).astype(np.float32)
    x2 = rng.normal(size=(K, N)).astype(np.float32)

    def body(xa, xb):
        st = admm_init(xa, cfg)
        st, m1 = admm_round(xa, st, jnp.int32(0), cfg)
        st, m2 = admm_round(xb, st, jnp.int32(1), cfg)
        return st.z, st.y, m2.primal_residual, m2.dual_residual

    z, y, primal, dual = _spmd(
        mesh, body, jnp.asarray(x1), jnp.asarray(x2),
        out_specs=(P(), P(CLIENT_AXIS), P(), P()),
    )

    rho = np.full(K, 0.001, np.float32)
    z_np = np.zeros(N, np.float32)
    y_np = np.zeros((K, N), np.float32)
    z_np, y_np, _, _ = _numpy_admm_round(x1, y_np, z_np, rho)
    z_np, y_np, primal_np, dual_np = _numpy_admm_round(x2, y_np, z_np, rho)

    np.testing.assert_allclose(z, z_np, rtol=1e-4)
    np.testing.assert_allclose(y, y_np, rtol=1e-4, atol=1e-7)
    np.testing.assert_allclose(primal, primal_np, rtol=1e-4)
    np.testing.assert_allclose(dual, dual_np, rtol=1e-4)


def _bb_reference_rho(rho, yhat, yhat0, x, x0, cfg):
    """Literal reference BB rule (src/consensus_admm_trio.py:407-429)."""
    dy, dx = yhat - yhat0, x - x0
    d11, d12, d22 = dy @ dy, dy @ dx, dx @ dx
    if abs(d12) > cfg.bb_epsilon and d11 > cfg.bb_epsilon and d22 > cfg.bb_epsilon:
        alpha = d12 / np.sqrt(d11 * d22)
        alpha_sd = d11 / d12
        alpha_mg = d12 / d22
        alpha_hat = alpha_mg if 2 * alpha_mg > alpha_sd else alpha_sd - 0.5 * alpha_mg
        if alpha >= cfg.bb_alphacorrmin and alpha_hat < cfg.bb_rhomax:
            return alpha_hat
    return rho


@pytest.mark.parametrize("scale", [1.0, 1e-4, -1.0])
def test_bb_rho_matches_reference_rule(scale):
    # scale=1: typically accepted; 1e-4: ill-posed (below eps); -1: negative
    # d12 rejected by the correlation guard
    from federated_pytorch_test_tpu.consensus.admm import _bb_new_rho

    cfg = ADMMConfig(bb_update=True)
    rng = np.random.default_rng(3)
    yhat = rng.normal(size=N).astype(np.float32) * abs(scale)
    yhat0 = np.zeros(N, np.float32)
    x = (rng.normal(size=N) * scale).astype(np.float32)
    x0 = np.zeros(N, np.float32)
    rho = np.float32(0.001)

    got = _bb_new_rho(
        jnp.asarray([rho]), jnp.asarray(yhat), jnp.asarray(yhat0),
        jnp.asarray(x), jnp.asarray(x0), cfg,
    )
    want = _bb_reference_rho(rho, yhat, yhat0, x, x0, cfg)
    np.testing.assert_allclose(np.squeeze(got), want, rtol=1e-5)


def test_bb_rho_accepts_crafted_spectral_step():
    """dy = 0.05*dx gives alpha=1, alphaMG=0.05 < rhomax -> accepted."""
    from federated_pytorch_test_tpu.consensus.admm import _bb_new_rho

    cfg = ADMMConfig(bb_update=True)
    rng = np.random.default_rng(6)
    dx = rng.normal(size=N).astype(np.float32) * 3
    dy = 0.05 * dx
    got = _bb_new_rho(
        jnp.asarray([0.001]), jnp.asarray(dy), jnp.zeros(N, jnp.float32),
        jnp.asarray(dx), jnp.zeros(N, jnp.float32), cfg,
    )
    np.testing.assert_allclose(np.squeeze(got), 0.05, rtol=1e-5)


def test_bb_full_trajectory_matches_numpy_mirror(mesh):
    """Three ADMM iterations with BB on: the jitted SPMD state trajectory
    (rho, z, y, and the BB carry stores) must match a literal numpy
    re-derivation of reference src/consensus_admm_trio.py:399-513."""
    cfg = ADMMConfig(rho0=0.001, bb_update=True, bb_period=2)
    rng = np.random.default_rng(4)
    xs = [rng.normal(size=(K, N)).astype(np.float32) * 3 for _ in range(3)]

    def body(x0, x1, x2):
        st = admm_init(x0, cfg)
        rhos = []
        for nadmm, x in enumerate((x0, x1, x2)):
            st, _ = admm_round(x, st, jnp.int32(nadmm), cfg)
            rhos.append(st.rho)
        return (*rhos, st.z, st.y)

    r0, r1, r2, z, y = _spmd(
        mesh, body, *map(jnp.asarray, xs),
        out_specs=(P(CLIENT_AXIS), P(CLIENT_AXIS), P(CLIENT_AXIS), P(), P(CLIENT_AXIS)),
    )

    # numpy mirror of the reference loop
    rho = np.full(K, cfg.rho0, np.float32)
    z_np = np.zeros(N, np.float32)
    y_np = np.zeros((K, N), np.float32)
    yhat0 = xs[0].copy()  # reference quirk: yhat0 init = starting params
    x0_np = np.zeros((K, N), np.float32)
    rho_traj = []
    for nadmm, x in enumerate(xs):
        if nadmm == 0:
            x0_np = x.copy()
        elif nadmm % cfg.bb_period == 0:
            yhat = y_np + rho[:, None] * (x - z_np)
            for k in range(K):
                rho[k] = _bb_reference_rho(rho[k], yhat[k], yhat0[k], x[k], x0_np[k], cfg)
            yhat0, x0_np = yhat, x.copy()
        z_np, y_np, _, _ = _numpy_admm_round(x, y_np, z_np, rho)
        rho_traj.append(rho.copy())

    np.testing.assert_allclose(np.squeeze(r0), rho_traj[0], rtol=1e-5)
    np.testing.assert_allclose(np.squeeze(r1), rho_traj[1], rtol=1e-5)
    np.testing.assert_allclose(np.squeeze(r2), rho_traj[2], rtol=1e-5)
    np.testing.assert_allclose(z, z_np, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(y, y_np, rtol=1e-4, atol=1e-6)


def test_admm_converges_on_convex_quadratic(mesh):
    """Property test (SURVEY.md §4b): on K local quadratics
    f_k(x) = 0.5||x - c_k||^2, exact x-updates drive the primal residual
    toward 0 and z toward a weighted fixed point."""
    cfg = ADMMConfig(rho0=0.5, bb_update=False)
    rng = np.random.default_rng(5)
    c = rng.normal(size=(K, N)).astype(np.float32)

    def body(cents):
        st = admm_init(cents, cfg)

        def one_iter(carry, nadmm):
            st = carry
            # exact x-update: argmin_x 0.5||x-c||^2 + y(x-z) + rho/2||x-z||^2
            x = (cents - st.y + st.rho * st.z) / (1.0 + st.rho)
            st, m = admm_round(x, st, nadmm, cfg)
            return st, (m.primal_residual, m.dual_residual)

        st, (primals, duals) = jax.lax.scan(one_iter, st, jnp.arange(30))
        return primals, duals

    primals, duals = _spmd(mesh, body, jnp.asarray(c), out_specs=(P(), P()))
    assert primals[-1] < primals[2] * 0.1
    assert duals[-1] < 1e-4


def test_elastic_net_and_soft_threshold():
    v = jnp.asarray([-2.0, 0.05, 1.5])
    np.testing.assert_allclose(
        elastic_net(v, 1e-4, 1e-4), 1e-4 * 3.55 + 1e-4 * (4 + 0.0025 + 2.25), rtol=1e-5
    )
    np.testing.assert_allclose(
        soft_threshold(v, 0.1), np.asarray([-1.9, 0.0, 1.4]), rtol=1e-6, atol=1e-8
    )


def test_fedavg_soft_threshold_z(mesh):
    # elastic-net consensus option: znew is soft-shrunk before broadcast
    x = jnp.asarray(np.random.RandomState(3).randn(K, N), jnp.float32)
    state = fedavg_init(N)

    def fn(xl):
        st, met = fedavg_round(xl, state, z_soft_threshold=0.5)
        return st.z

    z = np.asarray(_spmd(mesh, fn, x))
    expected = np.asarray(soft_threshold(jnp.asarray(x.mean(0)), 0.5))
    np.testing.assert_allclose(z, expected, rtol=1e-6, atol=1e-6)
    # shrinkage actually fires: small coords are exactly zero
    assert (np.abs(z) < np.abs(x.mean(0)) + 1e-9).all()


def test_admm_soft_threshold_z(mesh):
    cfg = ADMMConfig(rho0=0.5, z_soft_threshold=0.3)
    x = jnp.asarray(np.random.RandomState(4).randn(K, N), jnp.float32)

    def fn(xl):
        st = admm_init(xl, cfg)
        st2, met = admm_round(xl, st, jnp.int32(0), cfg)
        return st2.z

    z = np.asarray(_spmd(mesh, fn, x))
    # y=0, equal rho => znew = soft_threshold(mean(x))
    expected = np.asarray(soft_threshold(jnp.asarray(x.mean(0)), 0.3))
    np.testing.assert_allclose(z, expected, rtol=1e-5, atol=1e-7)
