"""Unit tests for the convergence-parity comparator's verdict logic.

The recorded artifact (benchmarks/convergence_parity.json) is produced
by `compare()`; its one-sided primary oracle — parity or BETTER — must
not regress: a framework that beats the reference beyond the band is a
pass, a framework that trails beyond the band is a fail, and the
symmetric trajectory bands stay informational either way.
"""

import importlib.util
import os
import sys

import pytest

pytestmark = pytest.mark.smoke

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_compare():
    mod = sys.modules.get("convergence_parity")
    if mod is None:  # load the module exactly once per session
        spec = importlib.util.spec_from_file_location(
            "convergence_parity",
            os.path.join(REPO, "benchmarks", "convergence_parity.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sys.modules["convergence_parity"] = mod
    return mod.compare


def _run(final_fw, final_ref, strategy="fedavg"):
    compare = _load_compare()
    fw = {"acc": [[0.1], [final_fw]], "dual": [1e-3], "primal": [], "mean_rho": []}
    ref = {"acc": [[0.1], [final_ref]], "dual": [1e-3], "primal": [], "mean_rho": []}
    return compare(fw, ref, strategy)


def test_framework_winning_beyond_band_passes_primary_oracle():
    v = _run(0.50, 0.30)
    assert v["framework_ge_reference_minus_band"]
    assert v["framework_beats_reference"]
    assert v["both_above_2x_chance"]
    assert v["primary_pass"]
    # the symmetric band legitimately fails when one side wins big —
    # recorded, but not the primary criterion
    assert not v["acc_final_within_band"]


def test_framework_trailing_beyond_band_fails_primary_oracle():
    v = _run(0.30, 0.50)
    assert not v["framework_ge_reference_minus_band"]
    assert not v["framework_beats_reference"]
    assert not v["primary_pass"]


def test_near_chance_results_fail_even_when_matching():
    # 0.12 vs 0.12: within band but meaningless — both near chance (0.1)
    v = _run(0.12, 0.12)
    assert v["acc_final_within_band"]
    assert not v["both_above_2x_chance"]
    assert not v["primary_pass"]


def test_within_band_parity_passes_all_primary_criteria():
    v = _run(0.55, 0.58)
    assert v["framework_ge_reference_minus_band"]
    assert v["both_above_2x_chance"]
    assert v["acc_final_within_band"]
    assert v["primary_pass"]


def test_chance_floor_scales_with_num_classes():
    # ADVICE r4: a 100-class config must clear 2x its own 0.01 chance,
    # not inherit the 10-class 0.2 bar (and vice versa: 0.12 acc is a
    # meaningful pass at 100 classes, a near-chance fail at 10)
    compare = _load_compare()
    fw = {"acc": [[0.02], [0.12]], "dual": [1e-3], "primal": [],
          "mean_rho": []}
    ref = {"acc": [[0.02], [0.12]], "dual": [1e-3], "primal": [],
           "mean_rho": []}
    v10 = compare(fw, ref, "fedavg", num_classes=10)
    v100 = compare(fw, ref, "fedavg", num_classes=100)
    assert not v10["both_above_2x_chance"] and not v10["primary_pass"]
    assert v100["both_above_2x_chance"] and v100["primary_pass"]
    assert v100["num_classes"] == 100


def test_matched_pass_requires_present_and_true_bands():
    # matched-dynamics oracle: one bool the gate reads (never the key
    # set). All bands present+true -> pass; a MISSING residual series
    # (dual curve empty -> dual_log10_median None, band key absent) must
    # FAIL, not pass by omission; a dissimilar final accuracy fails too.
    compare = _load_compare()

    def mk(dual):
        return {"acc": [[0.1], [0.5]], "dual": dual, "primal": [],
                "mean_rho": []}

    ok = compare(mk([1e-3]), mk([1.1e-3]), "fedavg", matched=True)
    assert ok["matched_pass"]

    missing = compare(mk([]), mk([]), "fedavg", matched=True)
    assert "dual_within_half_order" not in missing
    assert not missing["matched_pass"]

    off_band = compare(mk([1e-1]), mk([1e-3]), "fedavg", matched=True)
    assert not off_band["matched_pass"]

    fw = {"acc": [[0.1], [0.55]], "dual": [1e-3], "primal": [],
          "mean_rho": []}
    rf = {"acc": [[0.1], [0.30]], "dual": [1e-3], "primal": [],
          "mean_rho": []}
    dissimilar = compare(fw, rf, "fedavg", matched=True)
    assert dissimilar["primary_pass"] and not dissimilar["matched_pass"]

    # non-matched calls never emit the key
    assert "matched_pass" not in compare(mk([1e-3]), mk([1e-3]), "fedavg")
