"""Roofline-lever tests: the multi-alpha line-search probe fan and the
bf16 exchange codec (docs/PERF.md).

Smoke tier: codec arithmetic, config/CLI validation naming the field,
probe-fan ladder semantics vs the sequential search.

Middle (default) tier: the trainer-level contracts —

* `comm_bytes` under the bf16 codec is EXACTLY half the f32 ledger for
  the same plan, hand-checked against the pure participation masks
  (`group_size * 2 * survivors`), legacy and cohort mode;
* the f32 identity codec and `linesearch_probes=1` are the engine
  defaults — their programs are the unchanged pre-PR programs, so the
  P=4 / bf16 runs are compared against them as live baselines;
* P=4 keeps the folded dispatch budget `{round: 1, round_init: 1}`
  (mid tier) and the fused==unfused bitwise contract (fedavg AND
  admm+BB, slow tier — the tier-1 wall sits at the 870 s driver
  timeout, see conftest.py);
* bf16 convergence lands within 2 accuracy points of the f32 run on the
  discriminating synthetic, and the Byzantine acceptance gate
  (1 corrupted client/round + trimmed(1) + quarantine) still holds with
  the combiners operating on decoded f32 views;
* `linesearch_probes` and `exchange_dtype` are trajectory-changing
  knobs: they live in the metrics-stream header tag (unlike the
  dispatch-shape-only fold/async knobs) and a reconfigured stream is
  REFUSED, not spliced.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from federated_pytorch_test_tpu.data import synthetic_cifar
from federated_pytorch_test_tpu.engine import ExperimentConfig, Trainer, get_preset
from federated_pytorch_test_tpu.exchange import (
    EXCHANGE_DTYPES,
    get_codec,
)
from federated_pytorch_test_tpu.obs import CommLedger, JsonlSink
from federated_pytorch_test_tpu.optim import LBFGSConfig
from federated_pytorch_test_tpu.optim.linesearch import (
    backtracking_armijo_aux,
    backtracking_armijo_probes_aux,
)

smoke = pytest.mark.smoke


# ------------------------------------------------------------ codec units


@smoke
def test_bf16_codec_roundtrip_semantics():
    c = get_codec("bfloat16")
    assert not c.is_identity and c.bytes_per_value == 2
    # values with a 7-bit mantissa survive exactly (bf16 ⊂ f32)
    exact = jnp.asarray([0.0, 1.0, -2.5, 0.15625, 1.5 * 2.0**40], jnp.float32)
    np.testing.assert_array_equal(np.asarray(c.roundtrip(exact)), np.asarray(exact))
    # everything else rounds to nearest-even within 2^-8 relative
    x = jnp.asarray(np.random.RandomState(0).randn(256), jnp.float32)
    r = np.asarray(c.roundtrip(x))
    rel = np.abs(r - np.asarray(x)) / np.maximum(np.abs(np.asarray(x)), 1e-30)
    assert rel.max() <= 2.0**-8
    assert r.dtype == np.float32
    # non-finite values survive as themselves (a nan_burst liar stays
    # self-evidently corrupt to the combiners' exclusion logic)
    bad = jnp.asarray([np.nan, np.inf, -np.inf], jnp.float32)
    r = np.asarray(c.roundtrip(bad))
    assert np.isnan(r[0]) and np.isposinf(r[1]) and np.isneginf(r[2])
    assert c.encode(exact).dtype == jnp.bfloat16


@smoke
def test_codec_bytes_on_wire_and_identity():
    ident = get_codec("float32")
    bf16 = get_codec("bfloat16")
    assert ident.is_identity and ident.bytes_per_value == 4
    for n in (0, 1, 577440):
        assert bf16.bytes_on_wire(n) * 2 == ident.bytes_on_wire(n)
    x = jnp.arange(5, dtype=jnp.float32)
    assert ident.roundtrip(x) is x  # bit-transparent, no op inserted


@smoke
def test_get_codec_rejects_unknown():
    with pytest.raises(ValueError, match="exchange_dtype"):
        get_codec("float16")


# ---------------------------------------------------- validation surfaces


@smoke
def test_config_rejects_bad_roofline_knobs():
    with pytest.raises(ValueError, match="linesearch_probes"):
        ExperimentConfig(linesearch_probes=0)
    with pytest.raises(ValueError, match="linesearch_probes"):
        ExperimentConfig(linesearch_probes=2.5)
    with pytest.raises(ValueError, match="exchange_dtype"):
        ExperimentConfig(exchange_dtype="float16")
    # the happy path and the vocabulary agree
    for d in EXCHANGE_DTYPES:
        ExperimentConfig(exchange_dtype=d, linesearch_probes=4)


@smoke
def test_lbfgs_config_rejects_bad_probes():
    with pytest.raises(ValueError, match="ls_probes"):
        LBFGSConfig(ls_probes=0)


@smoke
def test_cli_rejects_bad_roofline_flags():
    # in-process: the config error must surface BEFORE any training,
    # naming the offending field
    from federated_pytorch_test_tpu.__main__ import main

    with pytest.raises(ValueError, match="linesearch_probes"):
        main(["--preset", "fedavg", "--linesearch-probes", "0"])
    with pytest.raises(ValueError, match="exchange_dtype"):
        main(["--preset", "fedavg", "--exchange-dtype", "float16"])


# ------------------------------------------------- probe-fan ladder units


def _quad_phi(scale, minimum=0.013):
    def phi_aux(a):
        l = scale * (a - minimum) ** 2 + 0.5
        return l, (l * 2.0,)

    return phi_aux


@smoke
def test_probe_fan_selects_sequential_alpha():
    """The fan accepts the IDENTICAL ladder rung as the sequential
    search for every fan width, including the exhausted-ladder fallback
    (rung 35) and fans wider than the ladder."""
    for scale in (1.0, 1e6):
        phi = _quad_phi(scale)
        f_old = phi(jnp.float32(0.0))[0]
        a_seq, _, aux_seq = backtracking_armijo_aux(
            phi, f_old, jnp.float32(-1.0), jnp.float32(1.0)
        )
        for p in (1, 2, 4, 7, 40):
            a_fan, _, aux_fan = backtracking_armijo_probes_aux(
                phi, f_old, jnp.float32(-1.0), jnp.float32(1.0), probes=p
            )
            assert float(a_fan) == float(a_seq), (scale, p)
            assert float(aux_fan[0]) == float(aux_seq[0]), (scale, p)
    # never-satisfying: both land on rung 35
    bad = lambda a: (a * 0 + 10.0, ())
    a_seq, e_seq, _ = backtracking_armijo_aux(
        bad, jnp.float32(0.0), jnp.float32(1.0), jnp.float32(1.0)
    )
    a_fan, e_fan, _ = backtracking_armijo_probes_aux(
        bad, jnp.float32(0.0), jnp.float32(1.0), jnp.float32(1.0), probes=4
    )
    assert float(a_fan) == float(a_seq) and int(e_fan) == int(e_seq) == 36


@smoke
def test_probe_fan_counts_evals_honestly_and_is_vmap_safe():
    """One widened fan charges its full width: a rung-6 accept costs 7
    sequential evals but 8 fanned ones at P=4 (two full fans) — the
    amortization is visible, not hidden. Heterogeneous clients under
    vmap keep per-client counts (the frozen sibling stops charging)."""
    phi = _quad_phi(1.0)
    f_old = phi(jnp.float32(0.0))[0]
    _, e_seq, _ = backtracking_armijo_aux(
        phi, f_old, jnp.float32(-1.0), jnp.float32(1.0)
    )
    _, e_fan, _ = backtracking_armijo_probes_aux(
        phi, f_old, jnp.float32(-1.0), jnp.float32(1.0), probes=4
    )
    assert int(e_seq) == 7 and int(e_fan) == 8

    # vmap: an immediately-accepting client charges one fan only while
    # its sibling keeps fanning — and both match their solo runs
    minima = jnp.asarray([0.9, 0.013], jnp.float32)  # rung 0 vs rung 6

    def one(m):
        phi = _quad_phi(1.0, m)
        f0 = phi(jnp.float32(0.0))[0]
        a, e, _ = backtracking_armijo_probes_aux(
            phi, f0, jnp.float32(-1.0), jnp.float32(1.0), probes=4
        )
        return a, e

    a_v, e_v = jax.vmap(one)(minima)
    for k in range(2):
        a_s, e_s = one(minima[k])
        assert float(a_v[k]) == float(a_s)
        assert int(e_v[k]) == int(e_s)
    assert int(e_v[0]) == 4 and int(e_v[1]) == 8

    with pytest.raises(ValueError, match="probes"):
        backtracking_armijo_probes_aux(
            phi, f_old, jnp.float32(-1.0), jnp.float32(1.0), probes=0
        )


# ------------------------------------------------ trainer-level (mid tier)


@pytest.fixture(scope="module")
def _src():
    return synthetic_cifar(n_train=240, n_test=60)


def _tiny(preset="fedavg", **over):
    base = dict(
        batch=40, nloop=1, nadmm=2, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
    )
    base.update(over)
    return get_preset(preset, **base)


def _final_flat(tr):
    return np.asarray(tr._fetch(tr.flat))


def test_bf16_comm_bytes_exactly_half_hand_checked(_src):
    """THE ledger contract: under the bf16 codec every `comm_bytes`
    record equals `group_size * 2 * survivors` with survivors from the
    PURE plan masks — exactly half the f32 ledger's PR-3 contract
    (`group_size * 4 * survivors`, hand-checked against the same masks in
    tests/test_obs.py, so the f32 side needs no second trainer run here)
    — and the summary reports the wire format + doubled savings. seed=8
    draws a full exchange AND a dropped-client one (survivors 3 then 2),
    so the halving is checked at two different survivor counts."""
    tr = Trainer(
        _tiny(fault_plan="seed=8,dropout=0.3", exchange_dtype="bfloat16"),
        verbose=False, source=_src,
    )
    tr.run()
    gid = tr.group_order[0]
    gsize = tr.partition.group_size(gid)
    recs = tr.recorder.series["comm_bytes"]
    assert len(recs) == 2
    assert {r["survivors"] for r in recs} == {3, 2}
    for r in recs:
        survivors = int(tr.injector.mask(r["nloop"], gid, r["nadmm"]).sum())
        assert r["survivors"] == survivors
        assert r["value"] == gsize * 2 * survivors  # the bf16 wire
        assert 2 * r["value"] == gsize * 4 * survivors  # half the f32 wire
    s16 = tr.recorder.latest("comm_summary")
    assert s16["exchange_dtype"] == "bfloat16"
    assert s16["wire_bytes_per_value"] == 2
    assert s16["bytes_total"] == sum(r["value"] for r in recs)
    # the full-model baseline stays at the f32 PARAMETER width
    # (compression is part of the savings being measured), so the
    # codec's factor lands in the savings ratio: exactly 2x the pure
    # identity-ledger arithmetic for the same partition + visit order
    assert s16["bytes_full_exchange"] == (
        tr.partition.total * 4 * sum(r["survivors"] for r in recs)
    )
    l32 = CommLedger(tr.partition, tr.cfg.n_clients, dtype_bytes=4)
    assert s16["savings_vs_full"] == pytest.approx(
        2 * l32.savings_vs_full(tr.group_order), rel=1e-3
    )


@pytest.mark.slow
def test_bf16_comm_bytes_halved_in_cohort_mode(_src):
    """The same wire contract through the cohort path (clients/,
    docs/SCALE.md): sampled-cohort exchanges record halved bytes too."""
    runs = {}
    for dtype in ("float32", "bfloat16"):
        tr = Trainer(
            _tiny(
                nloop=2, exchange_dtype=dtype,
                virtual_clients=6, cohort=3, data_shards=6,
            ),
            verbose=False, source=_src,
        )
        tr.run()
        runs[dtype] = tr
    b32 = [r["value"] for r in runs["float32"].recorder.series["comm_bytes"]]
    b16 = [r["value"] for r in runs["bfloat16"].recorder.series["comm_bytes"]]
    assert b32 and all(v32 == 2 * v16 for v32, v16 in zip(b32, b16))
    gsize = runs["bfloat16"].partition.group_size(
        runs["bfloat16"].group_order[0]
    )
    assert b16[0] == gsize * 2 * runs["bfloat16"].cfg.n_clients


def test_probe_fan_dispatch_budget(_src):
    """P=4 (+ bf16, the levers compose) keeps the folded one-dispatch
    budget — the probe fan and the codec live INSIDE the one round
    program (the fused==unfused bitwise leg of the same config is the
    slow-tier test below; this is the tier-1 dispatch-shape gate)."""
    cfg = _tiny(
        check_results=True, eval_batch=30, linesearch_probes=4,
        exchange_dtype="bfloat16",
    )
    tr = Trainer(cfg, verbose=False, source=_src)
    tr.run()
    for r in tr.recorder.series["dispatch_count"]:
        assert r["value"] == {"round": 1, "round_init": 1, "total": 2}


@pytest.mark.slow
def test_probe_fan_fused_unfused_bitwise(_src):
    """The fused round replays the unfused schedule bit for bit with the
    fan + codec in the program (fedavg; admm+BB has its own slow leg)."""
    cfg = _tiny(
        check_results=True, eval_batch=30, linesearch_probes=4,
        exchange_dtype="bfloat16",
    )
    flats = {}
    for fuse in (True, False):
        tr = Trainer(cfg.replace(fuse_rounds=fuse), verbose=False, source=_src)
        tr.run()
        flats[fuse] = _final_flat(tr)
    np.testing.assert_array_equal(flats[True], flats[False])


@pytest.mark.slow
def test_admm_bb_probe_fan_fused_unfused_bitwise(_src):
    """The admm+BB leg of the same contract (slow tier — two more
    program compiles): probe fan + codec + BB-rho, fused == unfused."""
    cfg = _tiny(
        "admm", bb_update=True, linesearch_probes=4,
        exchange_dtype="bfloat16",
    )
    flats = {}
    for fuse in (True, False):
        tr = Trainer(cfg.replace(fuse_rounds=fuse), verbose=False, source=_src)
        tr.run()
        flats[fuse] = _final_flat(tr)
        # BB adaptation ran on f32 client state: rho recorded and finite
        assert all(
            np.isfinite(r["value"]) for r in tr.recorder.series["mean_rho"]
        )
    np.testing.assert_array_equal(flats[True], flats[False])


# ------------------------------------------------- the acceptance gates
#
# `src_hard_accept` (the discriminating oracle), `accept_cfg` (the gate
# config builder) and `fault_free_accept` (the fault-free f32 baseline
# run) are session fixtures in conftest.py, shared with test_robust.py's
# Byzantine gates — one baseline run for the whole suite.


def _final_acc(tr):
    v = tr.recorder.latest("test_accuracy")
    return float(np.mean(v)) if v is not None else None


def _fault_kinds(tr):
    return [f["value"]["kind"] for f in tr.recorder.series.get("fault", [])]


@pytest.mark.slow
def test_bf16_convergence_within_gate(src_hard_accept, fault_free_accept, accept_cfg):
    """The codec's convergence contract: one round-to-nearest-even per
    exchanged value per round costs no more than 2 accuracy points vs
    the f32 run on the discriminating synthetic."""
    tr = Trainer(
        accept_cfg(exchange_dtype="bfloat16"), verbose=False,
        source=src_hard_accept,
    )
    tr.run()
    acc_f32 = _final_acc(fault_free_accept)
    acc_b16 = _final_acc(tr)
    assert acc_b16 is not None and abs(acc_b16 - acc_f32) <= 0.02, (
        acc_b16, acc_f32,
    )
    assert "round_rollback" not in _fault_kinds(tr)


def test_bf16_robust_gate_within_two_points(
    src_hard_accept, fault_free_accept, accept_cfg
):
    """The Byzantine acceptance gate UNDER the codec — the bf16 mirror of
    test_robust.py's f32 gate: 1 client corrupted per round (scale λ=10,
    garbling the bf16 wire in transit), trimmed(1) operating on the
    DECODED f32 views — zero rollbacks, fault-free-level accuracy
    (within 2 points), and the folded dispatch budget with codec +
    defense in-program."""
    tr = Trainer(
        accept_cfg(
            exchange_dtype="bfloat16",
            fault_plan="seed=7,corrupt=1:scale:10",
            robust_agg="trimmed", robust_f=1,
        ),
        verbose=False, source=src_hard_accept,
    )
    tr.run()
    assert "round_rollback" not in _fault_kinds(tr)
    assert "nonfinite_params" not in _fault_kinds(tr)
    acc = _final_acc(tr)
    acc_free = _final_acc(fault_free_accept)
    assert acc is not None and abs(acc - acc_free) <= 0.02, (acc, acc_free)
    # the folded dispatch budget holds with codec + defense in-program
    for r in tr.recorder.series["dispatch_count"]:
        assert r["value"] == {"round": 1, "round_init": 1, "total": 2}


@pytest.mark.slow
def test_bf16_quarantine_still_fires_on_liar(_src):
    """The z-score quarantine consumes DECODED f32 update norms, so a
    bf16-encoded liar is still identified — and ONLY corruption victims
    are flagged (the codec's rounding of honest updates is not mistaken
    for an attack). Slow tier (PR-11 wall budget): tier-2 bf16_smoke
    asserts quarantine-fires-under-the-codec on the real CLI stream. No accuracy gate here on purpose: the codec
    contract is that the quarantine statistics see the same evidence
    (the trimmed(1)@K=3 accuracy behavior is its own contract — the
    2f quarantine-release rule, gated in tests/test_fleet.py; under it
    the liar is re-flagged at every exchange of the round, which this
    test's victims-only assert accommodates)."""
    tr = Trainer(
        _tiny(
            exchange_dtype="bfloat16",
            fault_plan="seed=7,corrupt=1:scale:10",
            robust_agg="trimmed", robust_f=1, quarantine_z=1.0,
        ),
        verbose=False, source=_src,
    )
    tr.run()
    q = tr.recorder.series.get("quarantine", [])
    assert q, "quarantine never fired under the bf16 codec"
    gid = tr.group_order[0]
    modes = np.asarray(
        tr.injector.corruption_for_round(0, gid, tr.cfg.nadmm)[0]
    )
    victims = {int(k) for k in np.nonzero(modes.any(axis=0))[0]}
    flagged = {int(c) for r in q for c in r["value"]["clients"]}
    assert flagged and flagged <= victims, (flagged, victims)


@pytest.mark.slow
def test_probe_fan_converges_like_sequential(
    src_hard_accept, fault_free_accept, accept_cfg
):
    """P=4 selects the same ladder rungs the sequential search does;
    accumulated ulp drift must stay within the 2-point accuracy gate on
    the discriminating synthetic."""
    tr = Trainer(
        accept_cfg(linesearch_probes=4), verbose=False, source=src_hard_accept
    )
    tr.run()
    acc4 = _final_acc(tr)
    acc1 = _final_acc(fault_free_accept)
    assert acc4 is not None and abs(acc4 - acc1) <= 0.02, (acc4, acc1)


# -------------------------------------------- stream-tag refused splice


def test_roofline_knobs_are_stream_tag_members(_src, tmp_path):
    """`linesearch_probes` / `exchange_dtype` change the trajectory, so
    they must change the stream tag (a resumed run that flips one gets a
    fresh stream, never a splice) — unlike the dispatch-shape-only
    fold/async knobs, whose streams are identical by contract."""
    base = _tiny()
    tr = Trainer(base, verbose=False, source=_src)
    tags = {over: Trainer(
        base.replace(**{k: v}), verbose=False, source=_src
    )._stream_tag() for over, (k, v) in {
        "probes": ("linesearch_probes", 4),
        "bf16": ("exchange_dtype", "bfloat16"),
        "fold": ("fold_eval", False),
        "async": ("async_eval", False),
    }.items()}
    assert tags["probes"] != tr._stream_tag()
    assert tags["bf16"] != tr._stream_tag()
    # the dispatch-shape knobs deliberately share identity
    assert tags["fold"] == tr._stream_tag()
    assert tags["async"] == tr._stream_tag()

    # and the sink REFUSES a stream written under the other tag: the
    # refused-splice regression for the new knobs
    import json as _json

    for other in ("probes", "bf16"):
        p = str(tmp_path / f"{other}.jsonl")
        sink = JsonlSink(p, tag=tr._stream_tag())
        sink.open()
        sink.record("a", {"t": 0.1, "value": 1, "nloop": 0})
        sink.commit(0)
        sink.close()
        s2 = JsonlSink(p, tag=tags[other])
        with pytest.warns(UserWarning, match="different experiment"):
            assert s2.open(resume_nloops=1) == []
        s2.close()
        with open(p) as f:
            assert _json.loads(f.readline())["tag"] == tags[other]
