"""Virtual-client store + cohort sampling contracts (clients/, docs/SCALE.md).

The cross-device scale PR's gates, in the default tier:

* **bitwise bridge** — N=K virtual clients with C=K identity sampling
  reproduce the legacy every-client-every-round trajectory bit for bit
  (params, rho store, every recorded series), fused here and unfused in
  the slow tier, fedavg AND admm incl. BB-rho;
* **one-dispatch budget** — a sampled-cohort round's dispatch count
  stays exactly {round: 1, round_init: 1} (gather/scatter live outside
  the program);
* **replayability** — the sampler is pure in (seed, nloop), uniform
  (chi-square), weighted sampling follows sample counts, and a
  crashed+resumed cohort run's metric stream and store contents are
  identical to an uninterrupted twin's (the tier-1 small-N fast variant
  of scripts/ci.sh cohort_smoke);
* **O(C) checkpoints** — a save's dirty-chunk delta scales with the
  cohort, not the population;
* **seed-fold registry** — all schedule axes (dropout, straggler,
  corruption, speed, cohort) hold distinct folds.
"""

import json
import os

import numpy as np
import pytest

from federated_pytorch_test_tpu.clients import ClientStore, CohortSampler
from federated_pytorch_test_tpu.data import synthetic_cifar
from federated_pytorch_test_tpu.engine import ExperimentConfig, Trainer, get_preset
from federated_pytorch_test_tpu.fault import SEED_FOLDS, FaultPlan

SRC = synthetic_cifar(n_train=240, n_test=60)

SERIES = (
    "train_loss", "dual_residual", "primal_residual", "mean_rho",
    "test_accuracy",
)


def tiny(preset: str, **over) -> ExperimentConfig:
    base = dict(
        batch=40, nloop=2, max_groups=1, model="net",
        check_results=True, eval_batch=30, synthetic_ok=True,
    )
    base.update(over)
    return get_preset(preset, **base)


def _run(cfg):
    tr = Trainer(cfg, verbose=False, source=SRC)
    rec = tr.run()
    return tr, rec


# --------------------------------------------------------------- seed folds


@pytest.mark.smoke
def test_seed_folds_distinct():
    # the registry's whole point: no two schedule axes may share a fold,
    # or their draws would be correlated silently
    folds = list(SEED_FOLDS.values())
    assert len(folds) == len(set(folds)), SEED_FOLDS
    assert set(SEED_FOLDS) >= {
        "dropout", "straggler", "corruption", "speed", "cohort"
    }


@pytest.mark.smoke
def test_registry_folds_match_legacy_offsets():
    # the refactor moved magic numbers into SEED_FOLDS; the schedules
    # existing plans produce must be unchanged (replayability across
    # versions — a re-run chaos experiment must draw the same faults)
    plan = FaultPlan(
        seed=5, dropout_p=0.3, straggler_p=0.5, straggler_delay_s=1.0,
        corrupt_p=0.2, slow_p=0.2,
    )
    rng = np.random.default_rng([5, 0, 1, 2])
    np.testing.assert_array_equal(
        plan.participation(8, 0, 1, 2), (rng.random(8) >= 0.3).astype(np.float32)
    )
    rng = np.random.default_rng([6, 0, 1, 2])
    assert plan.straggler_delay(0, 1, 2) == (
        1.0 if rng.random() < 0.5 else 0.0
    )
    rng = np.random.default_rng([7, 0, 1, 2])
    modes, _, _ = plan.corruption(8, 0, 1, 2)
    np.testing.assert_array_equal((modes != 0), rng.random(8) < 0.2)
    rng = np.random.default_rng([8, 0, 1, 2])
    speeds = plan.client_speeds(8, 0, 1, 2)
    np.testing.assert_array_equal(speeds != 1.0, rng.random(8) < 0.2)


# ------------------------------------------------------------------ sampler


@pytest.mark.smoke
def test_cohort_sampler_pure_sorted_replayable():
    s1 = CohortSampler(100, 8, seed=3)
    s2 = CohortSampler(100, 8, seed=3)
    for nloop in (0, 1, 7, 1):  # out-of-order replay (resume) included
        a, b = s1.cohort(nloop), s2.cohort(nloop)
        np.testing.assert_array_equal(a, b)
        assert a.dtype == np.int64 and np.all(np.diff(a) > 0)
        assert a.min() >= 0 and a.max() < 100
    assert not np.array_equal(s1.cohort(0), s1.cohort(1))
    assert not np.array_equal(
        CohortSampler(100, 8, seed=4).cohort(0), s1.cohort(0)
    )


@pytest.mark.smoke
def test_cohort_sampler_distinct_from_dropout_draws():
    # the reserved fold: cohort_seed == plan seed must still give
    # independent draws (same base seed, different SEED_FOLDS offset)
    plan = FaultPlan(seed=3, dropout_p=0.5)
    s = CohortSampler(16, 16, seed=3)  # C=N: a permutation-free draw
    # the sampler's rng stream differs from the dropout stream: compare
    # the raw first draws of each fold
    a = np.random.default_rng([3, 0]).random(16)
    b = np.random.default_rng([3 + SEED_FOLDS["cohort"], 0]).random(16)
    assert not np.allclose(a, b)
    del plan, s  # constructed to prove the API composes


@pytest.mark.smoke
def test_cohort_sampler_uniform_chi_square():
    n, c, loops = 20, 5, 400
    s = CohortSampler(n, c, seed=1)
    counts = s.participation_counts(loops)
    assert counts.sum() == c * loops
    expected = c * loops / n  # 100 per client
    chi2 = float(((counts - expected) ** 2 / expected).sum())
    # dof = 19; the 0.999 quantile is ~43.8 — a seeded draw far above it
    # means the sampler is biased, not unlucky
    assert chi2 < 43.8, (chi2, counts.tolist())


@pytest.mark.smoke
def test_cohort_sampler_weighted_by_samples():
    n, c, loops = 10, 2, 600
    counts = np.ones(n)
    counts[0] = 50.0  # client 0 holds 50x the data
    s = CohortSampler(n, c, seed=2, weighting="samples", sample_counts=counts)
    picked = s.participation_counts(loops)
    assert picked.sum() == c * loops
    # client 0 must dominate; without-replacement caps it at once per loop
    assert picked[0] > 0.8 * loops
    assert picked[0] > 3 * picked[1:].max()


@pytest.mark.smoke
def test_cohort_sampler_validation():
    with pytest.raises(ValueError, match="cohort"):
        CohortSampler(4, 5)
    with pytest.raises(ValueError, match="identity"):
        CohortSampler(4, 2, weighting="identity")
    with pytest.raises(ValueError, match="sample_counts"):
        CohortSampler(4, 2, weighting="samples")
    with pytest.raises(ValueError, match="positive"):
        CohortSampler(
            4, 2, weighting="samples", sample_counts=[1, 0, 1, 1]
        )


@pytest.mark.smoke
def test_fault_identity_follows_virtual_id():
    # the same virtual client sampled into two different cohorts carries
    # the same per-round fault row: schedules are keyed by virtual id,
    # and a cohort is only a projection of them
    plan = FaultPlan(seed=9, dropout_p=0.4, corrupt_p=0.3)
    full = plan.participation(50, 2, 1, 0)
    modes, _, _ = plan.corruption(50, 2, 1, 0)
    a = np.array([3, 17, 30])
    b = np.array([17, 22, 41])
    np.testing.assert_array_equal(full[a][1], full[b][0])  # client 17
    np.testing.assert_array_equal(modes[a][1], modes[b][0])


# -------------------------------------------------------------------- store


@pytest.mark.smoke
def test_store_pristine_gather_and_roundtrip():
    st = ClientStore(40, np.arange(40) % 5, np.full(40, 7), chunk_clients=8)
    st.register_field("flat", np.arange(3, dtype=np.float32))
    g = st.gather("flat", np.array([0, 39]))
    np.testing.assert_array_equal(g, np.tile(np.arange(3, dtype=np.float32), (2, 1)))
    assert st.materialized_chunks() == 0  # gather never materializes
    rows = np.stack([np.full(3, 5, np.float32), np.full(3, 6, np.float32)])
    st.scatter("flat", np.array([1, 33]), rows)
    np.testing.assert_array_equal(
        st.gather("flat", np.array([33, 1, 2])),
        np.stack([rows[1], rows[0], np.arange(3, dtype=np.float32)]),
    )
    assert st.materialized_chunks() == 2
    with pytest.raises(IndexError):
        st.gather("flat", np.array([40]))
    with pytest.raises(ValueError, match="dtype"):
        st.scatter("flat", np.array([0]), np.zeros((1, 3), np.float64))
    with pytest.raises(ValueError, match="different fill"):
        st.register_field("flat", np.zeros(3, np.float32))


@pytest.mark.smoke
def test_store_checkpoint_delta_is_o_cohort(tmp_path):
    # N=1024 clients in 64 chunks; a C=8 cohort dirties <= 8 chunks, so
    # each save writes <= 8 chunk files + 1 manifest — never O(N)
    n, chunk, c = 1024, 16, 8
    st = ClientStore(n, np.arange(n) % 4, np.full(n, 5), chunk_clients=chunk)
    st.register_field("flat", np.zeros(4, np.float32))
    d = str(tmp_path)
    root = os.path.join(d, "client_store")
    rng = np.random.default_rng(0)
    seen = set()
    for step in range(1, 4):
        ids = np.sort(rng.choice(n, c, replace=False))
        st.scatter(
            "flat", ids,
            np.full((c, 4), float(step), np.float32),
        )
        before = set(os.listdir(root)) if os.path.isdir(root) else set()
        st.save(d, step)
        new = set(os.listdir(root)) - before
        new_chunks = {f for f in new if f.startswith("chunk_")}
        assert len(new_chunks) <= len(st.touched_chunks(ids)) <= c, new
        assert f"manifest_step_{step}.json" in new
        seen |= {int(i) for i in ids}
    # a fresh store restored from the last manifest sees every write
    st2 = ClientStore(n, np.arange(n) % 4, np.full(n, 5), chunk_clients=chunk)
    st2.register_field("flat", np.zeros(4, np.float32))
    st2.load(d, 3)
    all_ids = np.arange(n)
    np.testing.assert_array_equal(
        st2.gather("flat", all_ids), st.gather("flat", all_ids)
    )
    # population/chunking mismatches refuse to restore
    st3 = ClientStore(n + 1, np.zeros(n + 1), np.ones(n + 1), chunk_clients=chunk)
    st3.register_field("flat", np.zeros(4, np.float32))
    with pytest.raises(ValueError, match="n_virtual"):
        st3.load(d, 3)
    # retention: only the newest keep_manifests (2) snapshots remain —
    # older manifests pruned, superseded chunk versions GC'd, so disk
    # stays O(touched population) + keep*O(C), not O(loops * C)
    entries = set(os.listdir(root))
    manifests = {e for e in entries if e.startswith("manifest_")}
    assert manifests == {"manifest_step_2.json", "manifest_step_3.json"}
    referenced = set()
    for m in manifests:
        referenced |= set(
            json.load(open(os.path.join(root, m)))["chunks"].values()
        )
    assert {e for e in entries if e.startswith("chunk_")} == referenced


@pytest.mark.smoke
def test_mmap_npz_fallback_paths(tmp_path):
    # the zero-copy reader's contract: anything its in-place zip parse
    # cannot handle — compressed members, Fortran order, a foreign zip
    # layout — falls back to a full np.load with IDENTICAL values, and
    # unparsable bytes raise IntegrityError naming the file, never
    # returning garbage rows
    import io
    import zipfile

    from federated_pytorch_test_tpu.clients.store import (
        _mmap_npz,
        _npz_from_bytes,
    )
    from federated_pytorch_test_tpu.fault import IntegrityError

    a = np.arange(12, dtype=np.float32).reshape(3, 4)

    # the fast path itself: read-only in-place views
    plain = str(tmp_path / "plain.npz")
    np.savez(plain, a=a)
    out = _mmap_npz(plain)
    np.testing.assert_array_equal(out["a"], a)
    assert not out["a"].flags.writeable

    # compressed members: np.savez_compressed -> full-read fallback
    comp = str(tmp_path / "comp.npz")
    np.savez_compressed(comp, a=a)
    np.testing.assert_array_equal(_mmap_npz(comp)["a"], a)

    # Fortran-order member: the view parse refuses, the fallback reads
    fort = str(tmp_path / "fort.npz")
    np.savez(fort, a=np.asfortranarray(a))
    np.testing.assert_array_equal(_mmap_npz(fort)["a"], a)

    # foreign zip layout (deflated npy written by a plain zip tool)
    foreign = str(tmp_path / "foreign.npz")
    buf = io.BytesIO()
    np.save(buf, a)
    with zipfile.ZipFile(foreign, "w", zipfile.ZIP_DEFLATED) as zf:
        zf.writestr("a.npy", buf.getvalue())
    np.testing.assert_array_equal(_mmap_npz(foreign)["a"], a)

    # truncation: the mmap path raises (np.load refuses the torn zip)
    data = open(plain, "rb").read()
    trunc = str(tmp_path / "trunc.npz")
    with open(trunc, "wb") as f:
        f.write(data[: len(data) // 2])
    with pytest.raises(Exception):
        _mmap_npz(trunc)
    # ...and the verified byte path wraps it as corruption, named
    with pytest.raises(IntegrityError) as ei:
        _npz_from_bytes(data[: len(data) // 2], trunc)
    assert ei.value.path == trunc
    with pytest.raises(IntegrityError):
        _npz_from_bytes(b"not a zip at all", trunc)
    # an intact buffer parses identically through the byte path
    np.testing.assert_array_equal(_npz_from_bytes(data, plain)["a"], a)


@pytest.mark.smoke
def test_store_manifest_commit_is_atomic(tmp_path):
    # chunk files land before the manifest: a "crash" between the two
    # (simulated by saving chunks then corrupting the new manifest)
    # leaves the PREVIOUS manifest restorable
    n, chunk = 32, 8
    d = str(tmp_path)
    st = ClientStore(n, np.zeros(n), np.ones(n), chunk_clients=chunk)
    st.register_field("flat", np.zeros(2, np.float32))
    st.scatter("flat", np.array([0]), np.ones((1, 2), np.float32))
    st.save(d, 1)
    st.scatter("flat", np.array([0]), np.full((1, 2), 9, np.float32))
    st.save(d, 2)
    os.remove(os.path.join(d, "client_store", "manifest_step_2.json"))
    st2 = ClientStore(n, np.zeros(n), np.ones(n), chunk_clients=chunk)
    st2.register_field("flat", np.zeros(2, np.float32))
    with pytest.raises(FileNotFoundError):
        st2.load(d, 2)
    st2.load(d, 1)  # the previous snapshot is intact (versioned chunks)
    np.testing.assert_array_equal(
        st2.gather("flat", np.array([0]))[0], np.ones(2, np.float32)
    )


@pytest.mark.smoke
def test_store_spill_eviction_mmap_bitwise(tmp_path):
    # the spilled-store gate (docs/SCALE.md §Spilled store): a budget-1
    # store — every scatter beyond one chunk forces an eviction (dirty
    # chunks spill, clean ones drop) and gathers serve evicted rows off
    # memory-mapped .npz reads — must hold EXACTLY the rows an
    # unbounded in-RAM twin holds, bit for bit, through interleaved
    # scatters, saves, and gathers
    n, chunk = 64, 8
    rng = np.random.default_rng(3)
    d_s = str(tmp_path / "spill")
    st = ClientStore(
        n, np.arange(n) % 4, np.full(n, 5), chunk_clients=chunk,
        resident_chunks=1, spill_dir=d_s,
    )
    twin = ClientStore(
        n, np.arange(n) % 4, np.full(n, 5), chunk_clients=chunk
    )
    for s in (st, twin):
        s.register_field("flat", np.zeros(4, np.float32))
        s.register_field("telem", np.zeros((), np.float32))
    for step in range(1, 5):
        ids = np.sort(rng.choice(n, 6, replace=False))
        rows = rng.normal(size=(6, 4)).astype(np.float32)
        tel = rng.normal(size=6).astype(np.float32)
        for s in (st, twin):
            s.scatter("flat", ids, rows)
            s.scatter("telem", ids, tel)
        assert st.materialized_chunks() <= 1 + len(st.touched_chunks(ids))
        if step == 2:
            st.save(d_s, step)
            twin.save(str(tmp_path / "twin"), step)
    all_ids = np.arange(n)
    for name in ("flat", "telem"):
        np.testing.assert_array_equal(
            st.gather(name, all_ids), twin.gather(name, all_ids)
        )
    res = st.residency()
    assert res["resident_chunks"] <= 1
    assert res["evictions"] > 0 and res["spill_reads"] > 0
    assert res["spill_bytes"] > 0  # dirty evictions spilled real bytes
    summ = st.summary()
    for key in ("resident_chunks", "evictions", "spill_bytes"):
        assert key in summ, summ
    # a budget needs somewhere to spill
    with pytest.raises(ValueError, match="spill_dir"):
        ClientStore(n, np.zeros(n), np.ones(n), resident_chunks=1)
    # and the save directory must be the spill directory — a manifest
    # elsewhere could never reference the spilled versions
    with pytest.raises(ValueError, match="spill"):
        st.save(str(tmp_path / "elsewhere"), 9)


@pytest.mark.smoke
def test_store_lazy_load_serves_from_disk(tmp_path):
    # load() makes manifest chunks addressable WITHOUT reading them
    # into RAM: a restored million-client store must not cost O(touched)
    # resident memory. Gathers read rows off the mmap; a scatter
    # materializes (and re-dirties) just its chunks.
    n, chunk = 48, 8
    d = str(tmp_path)
    st = ClientStore(n, np.zeros(n), np.ones(n), chunk_clients=chunk)
    st.register_field("flat", np.arange(3, dtype=np.float32))
    ids = np.array([0, 9, 40])
    rows = np.stack([np.full(3, v, np.float32) for v in (1, 2, 3)])
    st.scatter("flat", ids, rows)
    st.save(d, 1)
    st2 = ClientStore(n, np.zeros(n), np.ones(n), chunk_clients=chunk)
    st2.register_field("flat", np.arange(3, dtype=np.float32))
    st2.load(d, 1)
    assert st2.materialized_chunks() == 0  # nothing resident
    np.testing.assert_array_equal(
        st2.gather("flat", np.array([9, 0, 40, 5])),
        np.stack([rows[1], rows[0], rows[2],
                  np.arange(3, dtype=np.float32)]),
    )
    assert st2.residency()["spill_reads"] > 0  # served off the mmap
    assert st2.materialized_chunks() == 0  # gather never materializes
    # scatter to a loaded chunk round-trips through the file copy
    st2.scatter("flat", np.array([1]), np.full((1, 3), 7, np.float32))
    np.testing.assert_array_equal(
        st2.gather("flat", np.array([1, 0]))[1], rows[0]
    )
    # a half-deleted store fails at restore, not mid-run
    st3 = ClientStore(n, np.zeros(n), np.ones(n), chunk_clients=chunk)
    st3.register_field("flat", np.arange(3, dtype=np.float32))
    root = os.path.join(d, "client_store")
    victim = [f for f in os.listdir(root) if f.startswith("chunk_")][0]
    os.rename(os.path.join(root, victim), os.path.join(root, victim) + ".gone")
    with pytest.raises(FileNotFoundError, match="chunk file"):
        st3.load(d, 1)


@pytest.mark.smoke
def test_store_spill_between_saves_stays_crash_safe(tmp_path):
    # an eviction-spill written BETWEEN saves is uncommitted state: a
    # crash before the next manifest leaves resume at the previous
    # committed snapshot (the versioned-chunk fallback, unchanged), and
    # the spilled orphan is GC'd by a later save rather than corrupting
    # anything
    n, chunk = 32, 8
    d = str(tmp_path)
    st = ClientStore(
        n, np.zeros(n), np.ones(n), chunk_clients=chunk,
        resident_chunks=1, spill_dir=d,
    )
    st.register_field("flat", np.zeros(2, np.float32))
    st.scatter("flat", np.array([0]), np.ones((1, 2), np.float32))
    st.save(d, 1)
    # dirty two chunks; the budget spills the LRU one immediately
    st.scatter("flat", np.array([0]), np.full((1, 2), 9, np.float32))
    st.scatter("flat", np.array([17]), np.full((1, 2), 5, np.float32))
    assert st.residency()["evictions"] > 0
    # "crash": a fresh store restores the ONLY committed snapshot
    st2 = ClientStore(n, np.zeros(n), np.ones(n), chunk_clients=chunk)
    st2.register_field("flat", np.zeros(2, np.float32))
    st2.load(d, 1)
    np.testing.assert_array_equal(
        st2.gather("flat", np.array([0]))[0], np.ones(2, np.float32)
    )
    np.testing.assert_array_equal(
        st2.gather("flat", np.array([17]))[0], np.zeros(2, np.float32)
    )


# ------------------------------------------------------------- config gates


@pytest.mark.smoke
def test_config_cohort_validation():
    with pytest.raises(ValueError, match="cohort size"):
        ExperimentConfig(virtual_clients=8)
    with pytest.raises(ValueError, match="cohort must be"):
        ExperimentConfig(virtual_clients=8, cohort=9)
    with pytest.raises(ValueError, match="identity"):
        ExperimentConfig(
            virtual_clients=8, cohort=4, cohort_weighting="identity"
        )
    with pytest.raises(ValueError, match="virtual_clients"):
        ExperimentConfig(cohort=4)
    with pytest.raises(ValueError, match="init_model"):
        ExperimentConfig(virtual_clients=8, cohort=4, init_model=False)
    with pytest.raises(ValueError, match="streaming"):
        ExperimentConfig(virtual_clients=8, cohort=4, hbm_data_budget_mb=1)
    # n_clients is DERIVED in cohort mode: the program width is the cohort
    cfg = ExperimentConfig(virtual_clients=8, cohort=4, n_clients=3)
    assert cfg.n_clients == 4
    # trimmed-mean sizing reads the derived width
    with pytest.raises(ValueError, match="trimmed"):
        ExperimentConfig(
            virtual_clients=8, cohort=2, robust_agg="trimmed", robust_f=1
        )
    # the spilled-store / prefetch knobs are cohort knobs like the rest
    with pytest.raises(ValueError, match="store_resident_chunks"):
        ExperimentConfig(
            virtual_clients=8, cohort=4, store_resident_chunks=0
        )
    with pytest.raises(ValueError, match="virtual_clients"):
        ExperimentConfig(store_resident_chunks=4)
    with pytest.raises(ValueError, match="virtual_clients"):
        ExperimentConfig(prefetch=False)


# ---------------------------------------------------- engine-level contracts


@pytest.mark.parametrize(
    "preset,over",
    [
        # one loop: the gather-from-pristine-store path (cross-loop
        # scatter->gather is covered by the admm leg and the crash test)
        ("fedavg", dict(nadmm=2, nloop=1)),
        # BB-rho crossing a due step inside the fused scan PLUS the rho
        # store roundtripping through the virtual-client store each loop.
        # Slow tier per the PR-9 rule (admm legs ride the slow tier:
        # four program compiles, ~31 s, and the tier-1 wall sits at the
        # 870 s driver budget) — like the unfused sibling below
        pytest.param(
            "admm", dict(nadmm=3, bb_update=True), marks=pytest.mark.slow
        ),
    ],
)
def test_identity_cohort_matches_legacy_bitwise(preset, over):
    """THE bridge gate: N=K, C=K, identity sampling == legacy, bit for
    bit — params, BB rho, and every recorded series (fused path; the
    unfused leg runs in the slow tier)."""
    tr_l, rec_l = _run(tiny(preset, **over))
    tr_c, rec_c = _run(
        tiny(
            preset,
            virtual_clients=3,
            cohort=3,
            cohort_weighting="identity",
            **over,
        )
    )
    np.testing.assert_array_equal(np.asarray(tr_l.flat), np.asarray(tr_c.flat))
    assert sorted(tr_l._rho_store) == sorted(tr_c._rho_store)
    for g in tr_l._rho_store:
        np.testing.assert_array_equal(
            np.asarray(tr_l._rho_store[g]), np.asarray(tr_c._rho_store[g])
        )
    for name in SERIES:
        a = [r["value"] for r in rec_l.series.get(name, [])]
        b = [r["value"] for r in rec_c.series.get(name, [])]
        assert a == b, name
    # and the store holds exactly the final device state
    np.testing.assert_array_equal(
        tr_c.store.gather("flat", np.arange(3)), np.asarray(tr_c.flat)
    )


@pytest.mark.slow
@pytest.mark.parametrize(
    "preset,over",
    [
        ("fedavg", dict(nadmm=2)),
        ("admm", dict(nadmm=3, bb_update=True)),
    ],
)
def test_identity_cohort_matches_legacy_bitwise_unfused(preset, over):
    tr_l, rec_l = _run(tiny(preset, fuse_rounds=False, **over))
    tr_c, rec_c = _run(
        tiny(
            preset,
            fuse_rounds=False,
            virtual_clients=3,
            cohort=3,
            cohort_weighting="identity",
            **over,
        )
    )
    assert not tr_c._fused_enabled()
    np.testing.assert_array_equal(np.asarray(tr_l.flat), np.asarray(tr_c.flat))
    for name in SERIES:
        a = [r["value"] for r in rec_l.series.get(name, [])]
        b = [r["value"] for r in rec_c.series.get(name, [])]
        assert a == b, name


def test_sampled_cohort_round_is_one_dispatch():
    """The dispatch-budget gate survives cohort mode: gather/scatter are
    host-side, so every partition round of a sampled-cohort loop still
    costs exactly {round: 1, round_init: 1}."""
    cfg = tiny(
        "fedavg",
        nadmm=2,
        virtual_clients=40,
        cohort=4,
        data_shards=4,
        fault_plan="seed=5,dropout=0.3",
    )
    tr, rec = _run(cfg)
    for r in rec.series["dispatch_count"]:
        assert r["value"] == {"round": 1, "round_init": 1, "total": 2}, r
    # membership recorded per loop, C ids each, all in range
    cohorts = [r["value"]["clients"] for r in rec.series["cohort"]]
    assert len(cohorts) == cfg.nloop
    for ids in cohorts:
        assert len(ids) == 4 and all(0 <= i < 40 for i in ids)
    part = rec.latest("cohort_participation")
    assert part["n_virtual"] == 40 and part["cohort"] == 4
    assert part["sampled_ever"] >= 4


@pytest.mark.slow
def test_cohort_crash_resume_stream_and_store_identity(tmp_path):
    """Small-N variant of scripts/ci.sh cohort_smoke: a planned
    crash mid-run, recovered via rerun — the resumed stream equals the
    uninterrupted twin's (cohort records included) and both stores hold
    identical rows for the whole population. Slow tier (PR-11 wall
    budget): the same contract runs end-to-end in tier-2 cohort_smoke
    AND fleet_smoke (which adds telemetry/churn state to the store),
    and tier-1 keeps the auto-deadline crash+resume identity gate
    (tests/test_fleet.py) exercising the stream-replay machinery."""
    from federated_pytorch_test_tpu.fault import InjectedCrash

    def cfg_for(tag, fault_plan):
        return tiny(
            "fedavg",
            nloop=2,
            nadmm=2,
            virtual_clients=32,
            cohort=4,
            data_shards=4,
            cohort_seed=9,
            save_model=True,
            resume="auto",
            store_chunk_clients=8,
            fault_plan=fault_plan,
            checkpoint_dir=str(tmp_path / f"ckpt_{tag}"),
            metrics_stream=str(tmp_path / f"{tag}.jsonl"),
        )

    cfg = cfg_for("run", "seed=5,dropout=0.3,crash=1:2:0")
    tr = Trainer(cfg, verbose=False, source=SRC)
    with pytest.raises(InjectedCrash):
        tr.run()
    tr2 = Trainer(cfg, verbose=False, source=SRC)
    tr2.run()
    twin = Trainer(
        cfg_for("twin", "seed=5,dropout=0.3"), verbose=False, source=SRC
    )
    twin.run()

    def norm(path):
        out = []
        for line in open(path):
            d = json.loads(line)
            d.pop("t", None)
            d.pop("crc", None)  # per-line checksums differ with content
            if d.get("event") == "stream_header":
                d.pop("tag", None)  # plans differ by the crash point
            if d.get("series") == "step_time":
                d["value"] = {
                    k: v for k, v in d["value"].items() if k != "seconds"
                }
            out.append(d)
        return out

    a = norm(str(tmp_path / "run.jsonl"))
    b = norm(str(tmp_path / "twin.jsonl"))
    assert a == b, f"streams differ: {len(a)} vs {len(b)} records"
    cohorts = [d for d in a if d.get("series") == "cohort"]
    assert len(cohorts) == 2
    ids = np.arange(32)
    assert tr2.store.fields == twin.store.fields
    for name in tr2.store.fields:
        np.testing.assert_array_equal(
            tr2.store.gather(name, ids), twin.store.gather(name, ids)
        )


def test_cohort_axis_sharded_across_mesh():
    """The cohort axis rides parallel/shardmap.py across the mesh: with
    C=8 on the 8-device CPU mesh every device owns exactly one cohort
    slot, and growing N leaves the per-device footprint unchanged."""
    import jax

    shapes = {}
    for n_virtual in (8, 64):
        cfg = tiny(
            "fedavg",
            nloop=1,
            nadmm=1,
            batch=10,
            virtual_clients=n_virtual,
            cohort=8,
            data_shards=8,
        )
        tr = Trainer(cfg, verbose=False, source=SRC)
        tr._begin_loop_cohort(0)
        assert len(tr.flat.sharding.device_set) == len(jax.devices())
        local = {
            s.data.shape for s in tr.flat.addressable_shards
        }
        assert len(local) == 1
        shapes[n_virtual] = next(iter(local))
    # per-device slice identical whatever the population size
    assert shapes[8] == shapes[64]
    assert shapes[8][0] == 1  # one client row per device at C=8
