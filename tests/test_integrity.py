"""Storage-integrity axis tests (fault/io.py, clients/store.py repair
ladder, fault/scrub.py, the engine-level heal gates).

The robustness PR's contracts:

* **checksums** — every spilled/checkpointed chunk and every v2
  manifest/stream line carries a crc32 the reader verifies BEFORE any
  row reaches a gather; legacy digest-less files are accepted
  read-only;
* **chaos axis** — `storage=<p>:<mode>[:strength]` draws per-I/O-op
  faults from its own seed fold, deterministically, independent of the
  wire axes;
* **repair ladder** — verification failure past the bounded retry
  adopts the newest intact prior version, else re-initializes the
  chunk pristine (counted), else — repair disabled — refuses loudly
  naming the chunk;
* **zero trajectory change** — a bit-rotted read heals on the verified
  retry (the disk is intact; only the returned buffer was corrupted),
  so a chaos run's final params and store rows are identical to a
  never-faulted twin's, and the fused round stays one dispatch;
* **scrub** — the offline CLI verb exits nonzero naming every corrupt
  file, and exits zero after `--repair`.
"""

import json
import os
import subprocess
import sys
import warnings

import numpy as np
import pytest

from federated_pytorch_test_tpu.clients import ClientStore
from federated_pytorch_test_tpu.fault import (
    SEED_FOLDS,
    FaultPlan,
    IntegrityError,
    StorageFaultShim,
    checksum,
    retry_io,
    stamp_crc,
    storage_shim_for,
    verify_crc,
    verify_digest,
)
from federated_pytorch_test_tpu.fault.io import retry_delay, retry_schedule
from federated_pytorch_test_tpu.fault.scrub import scrub_main

smoke = pytest.mark.smoke


# --------------------------------------------------------------- plan axis


@smoke
def test_storage_axis_parse_and_fold():
    plan = FaultPlan.parse("seed=3,storage=0.2:bitrot:4")
    assert plan.storage_p == 0.2
    assert plan.storage_mode == "bitrot"
    assert plan.storage_strength == 4.0
    assert plan.has_storage
    # strength is optional; every documented mode parses
    for mode in ("bitrot", "torn", "ioerror", "enospc"):
        p = FaultPlan.parse(f"seed=1,storage=0.5:{mode}")
        assert p.storage_mode == mode and p.storage_strength == 1.0
    assert not FaultPlan(seed=1).has_storage
    # the axis owns its registered fold, distinct from every other
    assert SEED_FOLDS["storage"] == 6
    assert len(set(SEED_FOLDS.values())) == len(SEED_FOLDS)


@smoke
def test_storage_axis_rejects_garbage():
    with pytest.raises(ValueError, match="storage"):
        FaultPlan.parse("seed=1,storage=0.5")  # missing mode
    with pytest.raises(ValueError, match="storage_mode"):
        FaultPlan.parse("seed=1,storage=0.5:gamma_rays")
    with pytest.raises(ValueError, match="storage_p"):
        FaultPlan.parse("seed=1,storage=1.5:bitrot")
    with pytest.raises(ValueError, match="storage_strength"):
        FaultPlan.parse("seed=1,storage=0.5:bitrot:0")


# -------------------------------------------------------------- checksums


@smoke
def test_checksum_digest_roundtrip_and_tamper():
    data = b"the quick brown fox" * 100
    d = checksum(data)
    assert set(d) == {"alg", "crc", "size"} and d["size"] == len(data)
    assert verify_digest(data, d)
    assert not verify_digest(data[:-1], d)  # size mismatch
    flipped = bytearray(data)
    flipped[7] ^= 1
    assert not verify_digest(bytes(flipped), d)  # single bit flip
    assert verify_digest(data, None)  # legacy: nothing to check
    # a digest under an algorithm this host lacks is accepted, loudly
    with pytest.warns(UserWarning, match="cannot verify"):
        assert verify_digest(data, {"alg": "sha9000", "crc": "xx"})


@smoke
def test_stamp_crc_verify_roundtrip():
    d = {"event": "x", "step": 3, "value": {"loss": 0.125, "ok": True}}
    line = stamp_crc(d)
    parsed = json.loads(line)
    assert verify_crc(parsed)
    assert list(parsed)[-1] == "crc"  # spliced as the trailing field
    # stripping crc restores the original document exactly
    parsed.pop("crc")
    assert parsed == d
    # any field tamper fails the check
    bad = json.loads(line)
    bad["step"] = 4
    assert not verify_crc(bad)
    # a document without a crc never verifies (version gates first)
    assert not verify_crc(d)
    assert verify_crc(json.loads(stamp_crc({})))


@smoke
def test_retry_io_bounded_backoff():
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise OSError("transient")
        return "ok"

    with pytest.warns(UserWarning, match="retrying"):
        assert retry_io(flaky, what="t", backoff_s=0.0) == "ok"
    assert calls[0] == 3
    # exhausted attempts re-raise the LAST error
    with pytest.warns(UserWarning):
        with pytest.raises(OSError, match="always"):
            retry_io(
                lambda: (_ for _ in ()).throw(OSError("always")),
                what="t", attempts=2, backoff_s=0.0,
            )
    # non-retried exception types propagate immediately
    def boom():
        raise KeyError("not retried")

    with pytest.raises(KeyError):
        retry_io(boom, what="t", backoff_s=0.0)
    with pytest.raises(ValueError, match="attempts"):
        retry_io(lambda: None, what="t", attempts=0)


@smoke
def test_retry_jitter_deterministic_schedule():
    """The seeded backoff jitter (fault/io.py retry_delay): the sleep
    after attempt `a` of operation label `what` is a pure function of
    (what, a) — replayable chaos runs wait identical schedules — while
    still decorrelating DIFFERENT operations (no retry convoy when one
    injected fault trips many I/O paths at once)."""
    # unit-pinned: these exact seconds are the published schedule for
    # the store's chunk-read label at the default backoff — a changed
    # RNG fold or jitter law must show up here, not in flaky CI walls
    pinned = [0.04917203210491001, 0.06603219602252655, 0.29041871410669723]
    assert retry_schedule("client_store chunk read", 4) == pinned
    # pure in (what, attempt): the same call yields the same seconds
    assert retry_schedule("client_store chunk read", 4) == pinned
    assert retry_delay("client_store chunk read", 1) == pinned[1]
    # different labels decorrelate
    other = retry_schedule("metrics stream write", 4)
    assert other != pinned
    # jittered exponential envelope: base * 2^a * [0.5, 1.5)
    for a in range(6):
        d = retry_delay("envelope check", a, backoff_s=0.05)
        assert 0.5 * 0.05 * 2**a <= d < 1.5 * 0.05 * 2**a
    # the cap bounds the pre-jitter term (so the jittered sleep stays
    # within [0.5, 1.5) * cap no matter how late the attempt)
    d = retry_delay("x", 30, backoff_s=0.05, cap_s=0.2)
    assert 0.5 * 0.2 <= d < 1.5 * 0.2
    # a schedule is one delay per RETRY (attempts - 1)
    assert len(retry_schedule("x", 1)) == 0
    assert len(retry_schedule("x", 5)) == 4


# -------------------------------------------------------------- fault shim


@smoke
def test_shim_deterministic_and_mode_shapes(tmp_path):
    path = str(tmp_path / "blob.bin")
    payload = bytes(range(256)) * 64
    with open(path, "wb") as f:
        f.write(payload)

    def reads(mode, n=40, p=0.5):
        shim = StorageFaultShim(
            FaultPlan.parse(f"seed=3,storage={p}:{mode}:2")
        )
        out = []
        for _ in range(n):
            try:
                out.append(shim.read_bytes(path))
            except OSError as e:
                out.append(("OSError", e.errno))
        return shim, out

    s1, a = reads("bitrot")
    s2, b = reads("bitrot")
    assert a == b  # pure in (plan seed, direction, op ordinal)
    assert s1.injected == s2.injected > 0
    corrupted = [x for x in a if x != payload]
    assert len(corrupted) == s1.injected
    for x in corrupted:  # bitrot preserves length, flips bits
        assert len(x) == len(payload)
    # the file on disk is never touched: a clean re-read always heals
    assert open(path, "rb").read() == payload

    _, torn = reads("torn")
    assert any(len(x) < len(payload) for x in torn if isinstance(x, bytes))
    _, ioerr = reads("ioerror")
    assert ("OSError", 5) in ioerr  # EIO refusals instead of bytes

    # write side: only the error modes fire; corruption is read-side
    rot = StorageFaultShim(FaultPlan.parse("seed=3,storage=0.99:bitrot"))
    for _ in range(20):
        rot.before_write("x")  # never raises
    nospc = StorageFaultShim(FaultPlan.parse("seed=3,storage=0.99:enospc"))
    with pytest.raises(OSError) as ei:
        for _ in range(20):
            nospc.before_write("x")
    assert ei.value.errno == 28  # ENOSPC

    # shim construction is gated on a scheduled storage axis
    assert storage_shim_for(None) is None
    assert storage_shim_for(FaultPlan(seed=1)) is None
    assert storage_shim_for(FaultPlan.parse("seed=1,storage=0.1:torn")) is not None
    with pytest.raises(ValueError, match="storage_p"):
        StorageFaultShim(FaultPlan(seed=1))


# ------------------------------------------------- store verify + repair


def _mini_store(n=8, chunk=4, **kw):
    st = ClientStore(
        n, np.zeros(n), np.ones(n), chunk_clients=chunk, **kw
    )
    st.register_field("flat", np.zeros(3, np.float32))
    return st


def _rows(n, val):
    return np.full((n, 3), float(val), np.float32)


def _chunk_file(d, cid=0):
    root = os.path.join(d, "client_store")
    return root, sorted(
        f for f in os.listdir(root)
        if f.startswith(f"chunk_{cid:06d}_") and f.endswith(".npz")
    )


def _flip_byte(path, offset=200):
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


def test_store_detects_bitrot_before_adoption_and_reinits(tmp_path):
    d = str(tmp_path)
    st = _mini_store()
    st.scatter("flat", np.arange(8), _rows(8, 7))
    st.save(d, 1)
    root, files = _chunk_file(d, cid=0)
    assert len(files) == 1
    _flip_byte(os.path.join(root, files[0]))

    # a fresh store (resume) must catch the rot BEFORE any row lands
    st2 = _mini_store()
    st2.load(d, 1)
    with pytest.warns(UserWarning, match="re-initialized pristine"):
        got = st2.gather("flat", np.array([0, 1]))
    # no intact version anywhere -> pristine by construction, counted
    np.testing.assert_array_equal(got, _rows(2, 0))
    dig = st2.integrity_digest()
    assert dig["failures"] >= 1 and dig["repairs_reinit"] == 1
    repaired = st2.take_repaired()
    assert set(repaired) == {0, 1, 2, 3}  # every row of chunk 0
    assert st2.take_repaired() == {}  # drained

    # rung 3: repair disabled -> loud refusal naming the chunk
    st3 = _mini_store(repair=False)
    st3.load(d, 1)
    with pytest.raises(IntegrityError, match=files[0]):
        st3.gather("flat", np.array([0]))


def test_store_repair_adopts_newest_intact_prior_version(tmp_path):
    d = str(tmp_path)
    st = _mini_store()
    st.scatter("flat", np.arange(8), _rows(8, 1))
    st.save(d, 1)
    st.scatter("flat", np.arange(4), _rows(4, 2))
    st.save(d, 2)
    root, files = _chunk_file(d, cid=0)
    assert len(files) == 2  # both versions retained (keep_manifests=2)
    _flip_byte(os.path.join(root, files[-1]))  # rot the NEWEST version

    st2 = _mini_store()
    st2.load(d, 2)
    with pytest.warns(UserWarning, match="adopted prior intact"):
        got = st2.gather("flat", np.array([0, 1]))
    np.testing.assert_array_equal(got, _rows(2, 1))  # prior step's rows
    dig = st2.integrity_digest()
    assert dig["repairs_prior"] == 1 and dig["repairs_reinit"] == 0
    # the unrotted chunk still serves its newest rows
    np.testing.assert_array_equal(
        st2.gather("flat", np.array([7])), _rows(1, 1)
    )


def test_store_verify_all_is_the_strict_gate(tmp_path):
    d = str(tmp_path)
    st = _mini_store()
    st.scatter("flat", np.arange(8), _rows(8, 3))
    st.save(d, 1)
    st2 = _mini_store()
    st2.load(d, 1)
    out = st2.verify_all()
    assert out["verified"] == out["chunks"] == 2
    root, files = _chunk_file(d, cid=1)
    _flip_byte(os.path.join(root, files[0]))
    st3 = _mini_store()
    st3.load(d, 1)
    # no adoption, no repair: resume-time refusal naming the file
    with pytest.warns(UserWarning):  # the bounded retry warns per attempt
        with pytest.raises(IntegrityError, match=files[0]):
            st3.verify_all()


def test_manifest_self_crc_and_legacy_v1_accept(tmp_path):
    d = str(tmp_path)
    st = _mini_store()
    st.scatter("flat", np.arange(8), _rows(8, 9))
    path = st.save(d, 1)
    manifest = json.load(open(path))
    assert manifest["version"] == 2 and verify_crc(manifest)

    # a parsable manifest with a stale crc is bit rot, refused loudly
    tampered = dict(manifest)
    tampered["step"] = 99
    with open(path, "w") as f:
        json.dump(tampered, f)
    st2 = _mini_store()
    with pytest.raises(IntegrityError, match="checksum"):
        st2.load(d, 1)

    # legacy v1 (pre-checksum) manifests stay loadable read-only
    legacy = {k: v for k, v in manifest.items() if k not in ("crc", "digests")}
    legacy["version"] = 1
    with open(path, "w") as f:
        json.dump(legacy, f)
    st3 = _mini_store()
    st3.load(d, 1)
    np.testing.assert_array_equal(
        st3.gather("flat", np.array([5])), _rows(1, 9)
    )
    assert st3.integrity_digest()["failures"] == 0


# ------------------------------------------------------------------ scrub


def _seeded_store_dir(tmp_path, versions=1):
    d = str(tmp_path)
    st = _mini_store()
    for step in range(1, versions + 1):
        st.scatter("flat", np.arange(8), _rows(8, step))
        st.save(d, step)
    return d


def test_scrub_detects_names_then_repairs(tmp_path, capsys):
    d = _seeded_store_dir(tmp_path)
    assert scrub_main([d]) == 0  # clean store scrubs clean
    root, files = _chunk_file(d, cid=0)
    _flip_byte(os.path.join(root, files[0]))

    assert scrub_main([d]) == 1  # detect: nonzero, naming the chunk
    out = capsys.readouterr().out
    assert "CORRUPT" in out and files[0] in out

    assert scrub_main([d, "--repair"]) == 0  # repair resolves it
    out = capsys.readouterr().out
    assert "repaired" in out and files[0] in out
    assert os.path.exists(os.path.join(root, files[0] + ".corrupt"))
    assert scrub_main([d]) == 0  # and the store scrubs clean again

    # the repaired (chunk-dropped) store loads: rows re-init pristine
    st = _mini_store()
    st.load(d, 1)
    np.testing.assert_array_equal(
        st.gather("flat", np.array([0])), _rows(1, 0)
    )
    np.testing.assert_array_equal(
        st.gather("flat", np.array([6])), _rows(1, 1)
    )


def test_scrub_repair_prefers_prior_version(tmp_path, capsys):
    d = _seeded_store_dir(tmp_path, versions=2)
    root, files = _chunk_file(d, cid=0)
    _flip_byte(os.path.join(root, files[-1]))
    assert scrub_main([d, "--repair"]) == 0
    assert "adopted prior version" in capsys.readouterr().out
    st = _mini_store()
    st.load(d, 2)
    np.testing.assert_array_equal(
        st.gather("flat", np.array([0])), _rows(1, 1)
    )
    assert scrub_main([d]) == 0


def test_scrub_quarantines_rotted_manifest(tmp_path, capsys):
    d = _seeded_store_dir(tmp_path)
    root = os.path.join(d, "client_store")
    mpath = os.path.join(root, "manifest_step_1.json")
    doc = json.load(open(mpath))
    doc["step"] = 42  # parsable, but the self-crc is now stale
    with open(mpath, "w") as f:
        json.dump(doc, f)
    assert scrub_main([d]) == 1
    assert "manifest_step_1.json" in capsys.readouterr().out
    assert scrub_main([d, "--repair"]) == 0
    assert os.path.exists(mpath + ".corrupt") and not os.path.exists(mpath)


@smoke
def test_scrub_empty_dir_is_clean(tmp_path, capsys):
    assert scrub_main([str(tmp_path)]) == 0
    assert "no store manifests" in capsys.readouterr().out
    assert scrub_main([str(tmp_path / "missing")]) == 1


def test_scrub_cli_verb_is_engine_import_free(tmp_path):
    # the report/watch rule: the verb must run without initializing any
    # accelerator backend (scrubbing a dead host's store)
    d = _seeded_store_dir(tmp_path)
    env = dict(os.environ, JAX_PLATFORMS="please_explode")
    out = subprocess.run(
        [sys.executable, "-m", "federated_pytorch_test_tpu", "scrub", d],
        capture_output=True, text=True, env=env, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "# scrub:" in out.stdout


# ------------------------------------- engine-level heal gates (tier 1)
# The tentpole acceptance: injected storage chaos heals on the verified
# retry with ZERO trajectory change, and the fused round stays one
# dispatch. Seed/p chosen so the schedule exercises detection and heal
# without exhausting the bounded retry (a triple-fault chunk would
# legitimately re-init — that ladder rung is unit-tested above).


@pytest.fixture(scope="module")
def _src():
    from federated_pytorch_test_tpu.data import synthetic_cifar

    return synthetic_cifar(n_train=240, n_test=60)


def _chaos_cfg(ckpt_dir, fault_plan=None):
    from federated_pytorch_test_tpu.engine import get_preset

    return get_preset(
        "fedavg", batch=40, nloop=3, nadmm=2, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
        virtual_clients=32, cohort=4, data_shards=4, cohort_seed=9,
        cohort_weighting="telemetry",  # all-N gathers re-read every spill
        store_chunk_clients=8, store_resident_chunks=1, prefetch=False,
        checkpoint_dir=str(ckpt_dir), fault_plan=fault_plan,
    )


@pytest.fixture(scope="module")
def _twin(_src, tmp_path_factory):
    from federated_pytorch_test_tpu.engine import Trainer

    tr = Trainer(
        _chaos_cfg(tmp_path_factory.mktemp("twin")),
        verbose=False, source=_src,
    )
    tr.run()
    return tr


@pytest.mark.parametrize("mode", ["bitrot", "ioerror"])
def test_engine_storage_chaos_heals_with_zero_trajectory_change(
    mode, _src, _twin, tmp_path
):
    from federated_pytorch_test_tpu.engine import Trainer

    cfg = _chaos_cfg(tmp_path / "ckpt", f"seed=7,storage=0.4:{mode}")
    tr = Trainer(cfg, verbose=False, source=_src)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # per-attempt retry warnings
        rec = tr.run()

    # chaos actually fired...
    assert tr._storage_shim is not None and tr._storage_shim.injected > 0
    dig = tr.store.integrity_digest()
    assert dig["retry_heals"] > 0  # ...and the verified retry healed it
    if mode == "bitrot":
        # rot was DETECTED (checksum failure) before any row landed
        assert dig["failures"] > 0
    # zero repairs: the heal never rewrote history
    assert dig["repairs_prior"] == 0 and dig["repairs_reinit"] == 0

    # disarm the shim for the post-mortem: the chaos axis covered the
    # RUN; the gathers below are this test's own inspection reads
    tr.store._io = None

    # the headline gate: bit-identical trajectory to the unfaulted twin
    np.testing.assert_array_equal(
        np.asarray(tr._fetch(tr.flat)), np.asarray(_twin._fetch(_twin.flat))
    )
    ids = np.arange(32)
    assert tr.store.fields == _twin.store.fields
    for name in tr.store.fields:
        np.testing.assert_array_equal(
            tr.store.gather(name, ids), _twin.store.gather(name, ids)
        )

    # the folded dispatch budget survives the storage axis
    for r in rec.series["dispatch_count"]:
        assert r["value"] == {"round": 1, "round_init": 1, "total": 2}, r

    # scoreboard + integrity record surface the axis
    assert rec.latest("injected_faults")["storage_faults"] > 0
    assert rec.latest("integrity")["retry_heals"] > 0
