"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; the client mesh axis is
exercised on XLA's host platform with 8 virtual devices instead (the
TPU-native analogue of the reference's in-process three-client simulation;
see SURVEY.md §4).

The ambient environment registers a real-TPU PJRT plugin ("axon") via
sitecustomize at interpreter start and pins jax to it; initializing that
backend dials a tunnel and blocks forever from inside the test runner. The
plugin factory is therefore dropped before any backend is instantiated and
the platform is forced back to cpu. This must run before any test module
imports jax numerics, hence it lives at conftest import time.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

from jax._src import xla_bridge as _xb  # noqa: E402

_xb._backend_factories.pop("axon", None)
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", False)
