"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; the client mesh axis is
exercised on XLA's host platform with 8 virtual devices instead (the
TPU-native analogue of the reference's in-process three-client simulation;
see SURVEY.md §4).

The ambient environment registers a real-TPU PJRT plugin ("axon") via
sitecustomize at interpreter start and pins jax to it; initializing that
backend dials a tunnel and blocks forever from inside the test runner. The
plugin factory is therefore dropped before any backend is instantiated and
the platform is forced back to cpu. This must run before any test module
imports jax numerics, hence it lives at conftest import time.
"""

import os

import pytest

# silence the cache loader's per-entry E-level banner (multi-KB of
# machine-feature noise per hit). TSL reads this env var at the FIRST
# C++ log emission, which happens during backend init inside
# force_host_cpu — so it must be set before that call, not after.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

from federated_pytorch_test_tpu.utils import compile_cache_dir, force_host_cpu

jax = force_host_cpu(min_devices=8)
jax.config.update("jax_enable_x64", False)

# persistent compilation cache: repeat CI runs skip every XLA backend
# compile that took >1 s
_cache = compile_cache_dir()
os.makedirs(_cache, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


# --------------------------------------------- the shared acceptance run
#
# test_robust.py (Byzantine gates) and test_exchange.py (codec gates)
# both compare against THE SAME fault-free f32 baseline on the same
# discriminating synthetic. Session scope keeps it to one ~70 s trainer
# run for the whole suite instead of one per module — the tier-1 wall
# (ROADMAP's 870 s gate) pays for every duplicate.


@pytest.fixture(scope="session")
def norm_stream():
    """THE twin-stream normalizer (the pytest face of scripts/ci.sh
    `assert_stream_identity`): parse a JSONL metric stream into records
    equal modulo wall-clock fields — the `t` stamp, `step_time` seconds
    — and the header tag (crashed+resumed twins' plans legitimately
    differ by the fired crash point). Every crash+resume identity test
    must normalize through this one definition: a wall-clock field added
    to the stream format is then ignored (or surfaced) everywhere at
    once instead of by three drifting copies."""
    import json

    def norm(path):
        out = []
        for line in open(path):
            d = json.loads(line)
            d.pop("t", None)
            d.pop("crc", None)  # per-line checksums differ with content
            if d.get("event") == "stream_header":
                d.pop("tag", None)
            if d.get("series") == "step_time":
                d["value"] = {
                    k: v for k, v in d["value"].items() if k != "seconds"
                }
            out.append(d)
        return out

    return norm


@pytest.fixture(scope="session")
def src_hard_accept():
    """The discriminating acceptance oracle (data/cifar.py): label noise
    + prototype overlap keep accuracy off the ceiling, so robustness or
    codec damage SHOWS as lost points instead of hiding behind a
    separable toy task."""
    from federated_pytorch_test_tpu.data import synthetic_cifar

    return synthetic_cifar(
        n_train=240, n_test=240, label_noise=0.25, overlap=0.35
    )


@pytest.fixture(scope="session")
def accept_cfg():
    """Builder for the acceptance-gate config — the ONE definition both
    gate modules derive their variants from (a drifted copy would gate
    against a different baseline than it runs)."""
    from federated_pytorch_test_tpu.engine import get_preset

    def build(**over):
        base = dict(
            batch=40, nloop=2, nadmm=3, max_groups=1, model="net",
            check_results=True, eval_batch=80, fault_mode="rollback",
            synthetic_ok=True,
        )
        base.update(over)
        return get_preset("fedavg", **base)

    return build


@pytest.fixture(scope="session")
def fault_free_accept(src_hard_accept, accept_cfg):
    """The completed fault-free f32 acceptance run (trainer, post-run)."""
    from federated_pytorch_test_tpu.engine import Trainer

    tr = Trainer(accept_cfg(), verbose=False, source=src_hard_accept)
    tr.run()
    return tr
