"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; the client mesh axis is
exercised on XLA's host platform with 8 virtual devices instead (the
TPU-native analogue of the reference's in-process three-client simulation;
see SURVEY.md §4).

The ambient environment registers a real-TPU PJRT plugin ("axon") via
sitecustomize at interpreter start and pins jax to it; initializing that
backend dials a tunnel and blocks forever from inside the test runner. The
plugin factory is therefore dropped before any backend is instantiated and
the platform is forced back to cpu. This must run before any test module
imports jax numerics, hence it lives at conftest import time.
"""

import os

import pytest

# silence the cache loader's per-entry E-level banner (multi-KB of
# machine-feature noise per hit). TSL reads this env var at the FIRST
# C++ log emission, which happens during backend init inside
# force_host_cpu — so it must be set before that call, not after.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

from federated_pytorch_test_tpu.utils import compile_cache_dir, force_host_cpu

jax = force_host_cpu(min_devices=8)
jax.config.update("jax_enable_x64", False)

# persistent compilation cache: repeat CI runs skip every XLA backend
# compile that took >1 s
_cache = compile_cache_dir()
os.makedirs(_cache, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


# --------------------------------------------- the shared acceptance run
#
# test_robust.py (Byzantine gates) and test_exchange.py (codec gates)
# both compare against THE SAME fault-free f32 baseline on the same
# discriminating synthetic. Session scope keeps it to one ~70 s trainer
# run for the whole suite instead of one per module — the tier-1 wall
# (ROADMAP's 870 s gate) pays for every duplicate.


@pytest.fixture(scope="session")
def norm_stream():
    """THE twin-stream normalizer, now defined once in
    fault/chaos.py (`norm_stream_records` — the chaos oracle's
    stream-identity invariant runs through the same code path as every
    crash+resume identity test and ci.sh `assert_stream_identity`): a
    wall-clock field added to the stream format is ignored (or
    surfaced) everywhere at once instead of by three drifting copies."""
    from federated_pytorch_test_tpu.fault.chaos import norm_stream_records

    return norm_stream_records


@pytest.fixture(scope="session")
def src_hard_accept():
    """The discriminating acceptance oracle (data/cifar.py): label noise
    + prototype overlap keep accuracy off the ceiling, so robustness or
    codec damage SHOWS as lost points instead of hiding behind a
    separable toy task."""
    from federated_pytorch_test_tpu.data import synthetic_cifar

    return synthetic_cifar(
        n_train=240, n_test=240, label_noise=0.25, overlap=0.35
    )


@pytest.fixture(scope="session")
def accept_cfg():
    """Builder for the acceptance-gate config — the ONE definition both
    gate modules derive their variants from (a drifted copy would gate
    against a different baseline than it runs)."""
    from federated_pytorch_test_tpu.engine import get_preset

    def build(**over):
        base = dict(
            batch=40, nloop=2, nadmm=3, max_groups=1, model="net",
            check_results=True, eval_batch=80, fault_mode="rollback",
            synthetic_ok=True,
        )
        base.update(over)
        return get_preset("fedavg", **base)

    return build


@pytest.fixture(scope="session")
def fault_free_accept(src_hard_accept, accept_cfg):
    """The completed fault-free f32 acceptance run (trainer, post-run)."""
    from federated_pytorch_test_tpu.engine import Trainer

    tr = Trainer(accept_cfg(), verbose=False, source=src_hard_accept)
    tr.run()
    return tr
