"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; the client mesh axis is
exercised on XLA's host platform with 8 virtual devices instead (the
TPU-native analogue of the reference's in-process three-client simulation;
see SURVEY.md §4).

The ambient environment registers a real-TPU PJRT plugin ("axon") via
sitecustomize at interpreter start and pins jax to it; initializing that
backend dials a tunnel and blocks forever from inside the test runner. The
plugin factory is therefore dropped before any backend is instantiated and
the platform is forced back to cpu. This must run before any test module
imports jax numerics, hence it lives at conftest import time.
"""

from federated_pytorch_test_tpu.utils import force_host_cpu

jax = force_host_cpu(min_devices=8)
jax.config.update("jax_enable_x64", False)
