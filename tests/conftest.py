"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; the client mesh axis is
exercised on XLA's host platform with 8 virtual devices instead (the
TPU-native analogue of the reference's in-process three-client simulation;
see SURVEY.md §4).

The ambient environment registers a real-TPU PJRT plugin ("axon") via
sitecustomize at interpreter start and pins jax to it; initializing that
backend dials a tunnel and blocks forever from inside the test runner. The
plugin factory is therefore dropped before any backend is instantiated and
the platform is forced back to cpu. This must run before any test module
imports jax numerics, hence it lives at conftest import time.
"""

import os

# silence the cache loader's per-entry E-level banner (multi-KB of
# machine-feature noise per hit). TSL reads this env var at the FIRST
# C++ log emission, which happens during backend init inside
# force_host_cpu — so it must be set before that call, not after.
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

from federated_pytorch_test_tpu.utils import compile_cache_dir, force_host_cpu

jax = force_host_cpu(min_devices=8)
jax.config.update("jax_enable_x64", False)

# persistent compilation cache: repeat CI runs skip every XLA backend
# compile that took >1 s
_cache = compile_cache_dir()
os.makedirs(_cache, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", _cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
