"""Unit tests for the flat codec and partition specs.

Covers the capability contract of the reference's freeze/flat machinery
(reference src/federated_trio.py:120-196): extract/insert round trips,
exact tiling of the parameter space, and group sizes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from federated_pytorch_test_tpu.models import Net, Net1, Net2, ResNet18
from federated_pytorch_test_tpu.partition import (
    Partition,
    Segment,
    build_partition,
    flatten_params,
)
from federated_pytorch_test_tpu.partition.flat import leaf_offsets, total_size


def _init(model):
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    return model.init(rng, x, train=False)


@pytest.fixture(scope="module")
def net_params():
    return _init(Net())["params"]


def test_flatten_round_trip(net_params):
    flat, unravel = flatten_params(net_params)
    assert flat.ndim == 1
    restored = unravel(flat)
    for a, b in zip(
        jax.tree_util.tree_leaves(restored), jax.tree_util.tree_leaves(net_params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_leaf_offsets_cover_everything(net_params):
    offs = leaf_offsets(net_params)
    assert offs[0][1] == 0
    sizes = sum(o[2] for o in offs)
    assert sizes == total_size(net_params)


@pytest.mark.parametrize("model_cls", [Net, Net1, Net2])
def test_simple_model_partitions_tile(model_cls):
    params = _init(model_cls())["params"]
    part = model_cls.partition(params)
    part.validate()
    assert part.num_groups == len(model_cls.GROUP_PATHS)
    assert sorted(part.train_order) == list(range(part.num_groups))
    flat, _ = flatten_params(params)
    assert sum(part.group_size(g) for g in range(part.num_groups)) == flat.shape[0]


def test_net_group_sizes_match_reference_shapes():
    # Layer param counts from reference src/simple_models.py:9-17:
    # conv1 3->6 5x5 (+bias), conv2 6->16 5x5, fc1 400->120, fc2 120->84, fc3 84->10.
    params = _init(Net())["params"]
    part = Net.partition(params)
    expected = [
        5 * 5 * 3 * 6 + 6,
        5 * 5 * 6 * 16 + 16,
        400 * 120 + 120,
        120 * 84 + 84,
        84 * 10 + 10,
    ]
    assert [part.group_size(g) for g in range(5)] == expected


def test_extract_insert_round_trip(net_params):
    part = Net.partition(net_params)
    flat, _ = flatten_params(net_params)
    for g in range(part.num_groups):
        vec = part.extract(flat, g)
        assert vec.shape == (part.group_size(g),)
        flat2 = part.insert(flat, g, jnp.zeros_like(vec))
        # the group is zeroed, everything else untouched
        mask = np.asarray(part.mask(g))
        np.testing.assert_array_equal(np.asarray(flat2)[mask], 0.0)
        np.testing.assert_array_equal(np.asarray(flat2)[~mask], np.asarray(flat)[~mask])
        # and re-inserting the extracted values restores the original
        flat3 = part.insert(flat2, g, vec)
        np.testing.assert_array_equal(np.asarray(flat3), np.asarray(flat))


def test_extract_insert_jit_compatible(net_params):
    part = Net.partition(net_params)
    flat, _ = flatten_params(net_params)

    @jax.jit
    def roundtrip(f):
        v = part.extract(f, 2)
        return part.insert(f, 2, v * 2.0)

    out = roundtrip(flat)
    mask = np.asarray(part.mask(2))
    np.testing.assert_allclose(np.asarray(out)[mask], 2 * np.asarray(flat)[mask], rtol=1e-6)


def test_resnet18_partition_has_ten_blocks():
    variables = _init(ResNet18())
    part = ResNet18.partition(variables["params"])
    assert part.num_groups == 10
    part.validate()
    # linear head: 512*10 + 10 params (reference src/federated_trio_resnet.py:130)
    assert part.group_size(9) == 512 * 10 + 10
    # stem: 3x3x3x64 conv + bn scale/bias (reference :124-125)
    assert part.group_size(0) == 3 * 3 * 3 * 64 + 64 + 64


def test_resnet18_total_param_count_matches_torch_resnet18():
    # Torch CIFAR ResNet18 (reference src/federated_trio_resnet.py:151)
    # has 11,173,962 trainable params.
    variables = _init(ResNet18())
    assert total_size(variables["params"]) == 11_173_962


def test_bad_partition_rejected():
    tpl = {"a": jnp.zeros((4,)), "b": jnp.zeros((4,))}
    with pytest.raises(ValueError):
        build_partition(tpl, [ (("a",),) ])  # leaves 'b' unclaimed
    with pytest.raises(ValueError):
        build_partition(tpl, [ (("a",),), (("a",), ("b",)) ])  # 'a' claimed twice
    part = Partition(groups=((Segment(0, 4),), (Segment(5, 3),)), total=8)
    with pytest.raises(ValueError):
        part.validate()  # gap at 4
