"""Byzantine-robust aggregation tests: corruption-fault purity, strict
plan loading, robust combiners vs numpy, auto-quarantine, and the
acceptance contract — under a plan corrupting one client per round,
`--robust-agg trimmed --robust-f 1` finishes with zero rollback rounds
and fault-free-level accuracy while `--robust-agg mean` on the same plan
degrades or rolls back; the folded dispatch shape stays
`{round: 1, round_init: 1}` throughout, and crash+resume stream identity
holds with quarantine records in the stream.

Smoke tier: plan/loader units and the SPMD combiner math. Unmarked
(middle) tier: trainer-level end-to-end runs.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from federated_pytorch_test_tpu.consensus import (
    apply_corruption,
    robust_combine,
    update_suspects,
)
from federated_pytorch_test_tpu.data import synthetic_cifar
from federated_pytorch_test_tpu.engine import Trainer, get_preset
from federated_pytorch_test_tpu.fault import CORRUPT_MODES, FaultPlan
from federated_pytorch_test_tpu.parallel import CLIENT_AXIS, client_mesh, shard_map

smoke = pytest.mark.smoke

K, N = 6, 11


def _spmd(mesh, fn, *args, out_specs=P()):
    return jax.jit(
        shard_map(
            fn,
            mesh=mesh,
            in_specs=tuple(P(CLIENT_AXIS) for _ in args),
            out_specs=out_specs,
        )
    )(*args)


@pytest.fixture(params=[1, 3], ids=["D1", "D3"])
def mesh(request):
    return client_mesh(request.param)


# ------------------------------------------------------ corruption schedule


@smoke
def test_plan_corruption_deterministic_and_separately_folded():
    plan = FaultPlan(seed=3, dropout_p=0.4, corrupt_k=2, corrupt_mode="scale")
    m0, s0, r0 = plan.corruption(16, 1, 2, 0)
    m1, s1, r1 = FaultPlan(
        seed=3, dropout_p=0.4, corrupt_k=2, corrupt_mode="scale"
    ).corruption(16, 1, 2, 0)
    # pure in (seed, cursor): a fresh plan derives the identical schedule
    np.testing.assert_array_equal(m0, m1)
    np.testing.assert_array_equal(s0, s1)
    np.testing.assert_array_equal(r0, r1)
    # corrupt_k corrupts EXACTLY k clients, with the configured mode code
    assert int((m0 != 0).sum()) == 2
    assert set(np.unique(m0)) == {0, CORRUPT_MODES["scale"]}
    # different cursors draw different victims over enough rounds
    assert any(
        not np.array_equal(m0, plan.corruption(16, 1, 2, a)[0])
        for a in range(1, 8)
    )
    # separate seed fold: adding corruption perturbs neither the dropout
    # masks nor the straggler schedule of the same plan
    bare = FaultPlan(seed=3, dropout_p=0.4)
    np.testing.assert_array_equal(
        plan.participation(16, 0, 1, 2), bare.participation(16, 0, 1, 2)
    )
    # probability form
    p = FaultPlan(seed=5, corrupt_p=0.5, corrupt_mode="gauss")
    hits = np.mean(
        [(p.corruption(32, i, 0, 0)[0] != 0).mean() for i in range(40)]
    )
    assert 0.4 < hits < 0.6
    # a corruption-free plan emits all-clean rows and no corrupt flag
    assert not bare.has_corruption
    assert not bare.corruption(8, 0, 0, 0)[0].any()


@smoke
def test_plan_json_loader_rejects_unknown_and_out_of_range():
    plan = FaultPlan(seed=2, corrupt_k=1, corrupt_mode="nan_burst")
    assert FaultPlan.from_json(plan.to_json()) == plan
    # unknown top-level key: named, with the valid set
    bad = json.loads(plan.to_json())
    bad["droput_p"] = 0.3  # the typo the strict loader exists for
    with pytest.raises(ValueError, match=r"droput_p.*valid fields"):
        FaultPlan.from_json(json.dumps(bad))
    # malformed crash entry: named by index and expected keys
    with pytest.raises(ValueError, match=r"crashes\[0\].*nloop"):
        FaultPlan.from_json(json.dumps({"crashes": [{"nloop": 0, "gid": 1}]}))
    # out-of-range values surface the offending FIELD, not a stack trace
    with pytest.raises(ValueError, match="corrupt_p"):
        FaultPlan.from_json(json.dumps({"corrupt_p": 1.5}))
    with pytest.raises(ValueError, match="corrupt_strength"):
        FaultPlan.from_json(json.dumps({"corrupt_strength": float("inf")}))
    with pytest.raises(ValueError, match="corrupt_mode"):
        FaultPlan.from_json(json.dumps({"corrupt_mode": "bitflip"}))
    with pytest.raises(ValueError, match="dropout_p"):
        FaultPlan.from_json(json.dumps({"dropout_p": -0.1}))
    # wrong-typed values fail AT LOAD naming the field — not rounds
    # later inside numpy with an opaque TypeError
    with pytest.raises(ValueError, match="corrupt_k must be an int"):
        FaultPlan.from_json(json.dumps({"corrupt_k": 2.5}))
    with pytest.raises(ValueError, match="dropout_p must be a number"):
        FaultPlan.from_json(json.dumps({"dropout_p": "0.3"}))
    with pytest.raises(ValueError, match=r"crashes\[0\].nloop must be an int"):
        FaultPlan.from_json(
            json.dumps({"crashes": [{"nloop": 1.9, "gid": 0, "nadmm": 0}]})
        )
    # a wrong-typed crashes container is rejected, not silently emptied
    with pytest.raises(ValueError, match="crashes must be a list"):
        FaultPlan.from_json(json.dumps({"crashes": {}}))
    # not even an object
    with pytest.raises(ValueError, match="must be an object"):
        FaultPlan.from_json("[1, 2]")


@smoke
def test_plan_inline_corrupt_spec():
    # int first part = exactly-k, float = per-client probability
    k = FaultPlan.parse("seed=1,corrupt=2:signflip")
    assert (k.corrupt_k, k.corrupt_p, k.corrupt_mode) == (2, 0.0, "signflip")
    p = FaultPlan.parse("corrupt=0.25:gauss:0.5")
    assert (p.corrupt_k, p.corrupt_p, p.corrupt_strength) == (0, 0.25, 0.5)
    with pytest.raises(ValueError, match="corrupt spec"):
        FaultPlan.parse("corrupt=1")
    # round-trips through JSON
    assert FaultPlan.from_json(k.to_json()) == k


@smoke
def test_apply_corruption_modes(mesh):
    x = np.random.default_rng(0).normal(size=(K, N)).astype(np.float32)
    #          clean  scale  flip  nan   gauss  clean
    modes = np.asarray([0, 1, 2, 3, 4, 0], np.int32)
    strength = np.full(K, 10.0, np.float32)
    seeds = np.arange(100, 100 + K, dtype=np.int32)

    out = np.asarray(
        _spmd(
            mesh, apply_corruption,
            jnp.asarray(x), jnp.asarray(modes), jnp.asarray(strength),
            jnp.asarray(seeds),
            out_specs=P(CLIENT_AXIS),
        )
    )
    # mode 0 selects the input BITS verbatim — the transparency the
    # robust_agg='mean' bit-identity contract rides on
    np.testing.assert_array_equal(out[0], x[0])
    np.testing.assert_array_equal(out[5], x[5])
    np.testing.assert_array_equal(out[1], x[1] * 10.0)
    np.testing.assert_array_equal(out[2], -x[2])
    assert np.isnan(out[3]).all()
    assert np.isfinite(out[4]).all() and not np.allclose(out[4], x[4])
    # gauss is deterministic in its seed: a second application matches
    out2 = np.asarray(
        _spmd(
            mesh, apply_corruption,
            jnp.asarray(x), jnp.asarray(modes), jnp.asarray(strength),
            jnp.asarray(seeds),
            out_specs=P(CLIENT_AXIS),
        )
    )
    np.testing.assert_array_equal(out, out2)


# --------------------------------------------------------- robust combiners


@smoke
def test_median_and_trimmed_match_numpy_under_mask(mesh):
    rng = np.random.default_rng(1)
    x = rng.normal(size=(K, N)).astype(np.float32) * 3
    mask = np.asarray([1, 0, 1, 1, 1, 0], np.float32)  # 4 survivors
    alive = x[mask > 0]

    prev = jnp.zeros(N, jnp.float32)
    med = np.asarray(
        _spmd(
            mesh,
            lambda xl, ml: robust_combine(xl, ml, "median", prev=prev)[0],
            jnp.asarray(x), jnp.asarray(mask),
        )
    )
    np.testing.assert_allclose(med, np.median(alive, axis=0), rtol=1e-6)

    tr = np.asarray(
        _spmd(
            mesh,
            lambda xl, ml: robust_combine(xl, ml, "trimmed", trim_f=1, prev=prev)[0],
            jnp.asarray(x), jnp.asarray(mask),
        )
    )
    ref = np.mean(np.sort(alive, axis=0)[1:-1], axis=0)
    np.testing.assert_allclose(tr, ref, rtol=1e-6)


@smoke
def test_trimmed_tolerates_f_corrupted_survivors(mesh):
    rng = np.random.default_rng(2)
    x = rng.normal(size=(K, N)).astype(np.float32)
    ones = np.ones(K, np.float32)
    for poison in (x[0] * 1e4, np.full(N, np.nan, np.float32)):
        xc = x.copy()
        xc[2] = poison  # one Byzantine survivor
        out = np.asarray(
            _spmd(
                mesh,
                lambda xl, ml: robust_combine(
                    xl, ml, "trimmed", trim_f=1,
                    prev=jnp.zeros(N, jnp.float32),
                )[0],
                jnp.asarray(xc), jnp.asarray(ones),
            )
        )
        honest = np.delete(x, 2, axis=0)
        assert np.isfinite(out).all()
        # the poisoned coordinate never enters the window: the result is
        # bounded by the honest values coordinate-wise
        assert (out >= honest.min(axis=0) - 1e-5).all()
        assert (out <= honest.max(axis=0) + 1e-5).all()


@smoke
def test_trimmed_falls_back_to_median_when_overtrimmed(mesh):
    x = np.random.default_rng(3).normal(size=(K, N)).astype(np.float32)
    mask = np.asarray([1, 1, 0, 0, 0, 0], np.float32)  # 2 survivors <= 2f
    out = np.asarray(
        _spmd(
            mesh,
            lambda xl, ml: robust_combine(
                xl, ml, "trimmed", trim_f=1, prev=jnp.zeros(N, jnp.float32)
            )[0],
            jnp.asarray(x), jnp.asarray(mask),
        )
    )
    np.testing.assert_allclose(out, np.median(x[:2], axis=0), rtol=1e-6)


@smoke
def test_clip_bounds_outliers_and_drops_nonfinite(mesh):
    rng = np.random.default_rng(4)
    prev = rng.normal(size=N).astype(np.float32)
    x = prev[None, :] + rng.normal(size=(K, N)).astype(np.float32)
    ones = np.ones(K, np.float32)
    xc = x.copy()
    xc[1] = prev + (x[1] - prev) * 1e6  # huge-norm update
    xc[4] = np.nan  # non-finite update

    def body(xl, ml):
        return robust_combine(xl, ml, "clip", prev=jnp.asarray(prev))[0]

    out = np.asarray(_spmd(mesh, body, jnp.asarray(xc), jnp.asarray(ones)))
    assert np.isfinite(out).all()
    # every contribution was clipped to the median update norm: the
    # combined update cannot exceed it
    honest_norms = np.linalg.norm(x[[0, 2, 3, 5]] - prev, axis=1)
    assert np.linalg.norm(out - prev) <= np.median(honest_norms) * 1.5 + 1e-5
    # all updates non-finite: the previous consensus state is returned
    allnan = np.full((K, N), np.nan, np.float32)
    out2 = np.asarray(_spmd(mesh, body, jnp.asarray(allnan), jnp.asarray(ones)))
    np.testing.assert_array_equal(out2, prev)


@smoke
def test_update_suspects_flags_outlier_and_nonfinite(mesh):
    prev = np.zeros(N, np.float32)
    x = np.zeros((K, N), np.float32)
    x[:, 0] = [1.0, 1.1, 0.9, 1.0, 10.0, np.nan]  # norms: ~1 x4, 10, nan
    ones = np.ones(K, np.float32)

    def body(xl, ml):
        return update_suspects(xl, jnp.asarray(prev), ml, 1.0)

    u, s = _spmd(
        mesh, body, jnp.asarray(x), jnp.asarray(ones),
        out_specs=(P(CLIENT_AXIS), P(CLIENT_AXIS)),
    )
    u, s = np.asarray(u), np.asarray(s)
    np.testing.assert_allclose(u[:4], [1.0, 1.1, 0.9, 1.0], rtol=1e-5)
    assert np.isnan(u[5])
    np.testing.assert_array_equal(s, [0, 0, 0, 0, 1, 1])
    # a dropped client is never suspect, whatever it holds
    m2 = ones.copy()
    m2[4] = 0.0
    _, s2 = _spmd(
        mesh, body, jnp.asarray(x), jnp.asarray(m2),
        out_specs=(P(CLIENT_AXIS), P(CLIENT_AXIS)),
    )
    assert np.asarray(s2)[4] == 0.0
    # a finite cohort smaller than 3 (judged client included): norm
    # z-scores flag nobody (non-finite still is)
    m3 = np.asarray([1, 0, 0, 0, 1, 1], np.float32)
    x3 = x.copy()
    x3[4, 0] = 100.0
    _, s3 = _spmd(
        mesh,
        lambda xl, ml: update_suspects(xl, jnp.asarray(prev), ml, 1.0),
        jnp.asarray(x3), jnp.asarray(m3),
        out_specs=(P(CLIENT_AXIS), P(CLIENT_AXIS)),
    )
    np.testing.assert_array_equal(np.asarray(s3), [0, 0, 0, 0, 0, 1])


@smoke
def test_all_nonfinite_exchange_keeps_z_through_soft_threshold(mesh):
    """The keep-previous fallback must survive the elastic-net soft
    threshold: an exchange whose every survivor is non-finite keeps z
    EXACTLY (not a shrunk copy), like an all-dropped round."""
    from federated_pytorch_test_tpu.consensus import FedAvgState, fedavg_round

    z_prev = np.random.default_rng(8).normal(size=N).astype(np.float32)
    allnan = np.full((K, N), np.nan, np.float32)
    ones = np.ones(K, np.float32)

    def body(xl, ml):
        st, met = fedavg_round(
            xl, FedAvgState(z=jnp.asarray(z_prev)), z_soft_threshold=0.5,
            mask=ml, combine="trimmed", robust_f=1,
        )
        return st.z, met["dual_residual"]

    z, dual = _spmd(
        mesh, body, jnp.asarray(allnan), jnp.asarray(ones),
        out_specs=(P(), P()),
    )
    np.testing.assert_array_equal(np.asarray(z), z_prev)
    assert float(dual) == 0.0


@smoke
def test_injector_rejects_corrupt_k_exceeding_clients(tmp_path):
    from federated_pytorch_test_tpu.fault import FaultInjector

    plan = FaultPlan(corrupt_k=5, corrupt_mode="scale")
    with pytest.raises(ValueError, match="corrupt_k=5 exceeds n_clients=3"):
        FaultInjector(plan, n_clients=3)
    FaultInjector(plan, n_clients=5)  # exactly-K is allowed
    # the direct plan API agrees with the injector — no silent capping
    with pytest.raises(ValueError, match="corrupt_k=5 exceeds n_clients=3"):
        plan.corruption(3, 0, 0, 0)
    assert int((plan.corruption(5, 0, 0, 0)[0] != 0).sum()) == 5


# ------------------------------------------------ trainer-level (mid tier)


@pytest.fixture(scope="module")
def _src():
    return synthetic_cifar(n_train=240, n_test=60)


def _tiny(preset="fedavg", **over):
    base = dict(
        batch=40, nloop=1, nadmm=2, max_groups=1, model="net",
        check_results=False, synthetic_ok=True,
    )
    base.update(over)
    return get_preset(preset, **base)


def _final_flat(tr):
    return np.asarray(tr._fetch(tr.flat))


def test_scale_one_corruption_is_bit_transparent(_src):
    """The robust_agg='mean' bit-identity contract, exercised through the
    live corruption machinery: a corruption-capable program whose only
    fault multiplies an update by exactly 1.0 reproduces the clean run's
    trajectory bit for bit (mode-0 clients ride the same select)."""
    t0 = Trainer(_tiny(), verbose=False, source=_src)
    t0.run()
    t1 = Trainer(
        _tiny(fault_plan="seed=7,corrupt=1:scale:1"), verbose=False, source=_src
    )
    t1.run()
    np.testing.assert_array_equal(_final_flat(t0), _final_flat(t1))
    l0 = [r["value"] for r in t0.recorder.series["train_loss"]]
    l1 = [r["value"] for r in t1.recorder.series["train_loss"]]
    assert l0 == l1


@pytest.mark.parametrize("preset", ["fedavg", "admm"])
def test_all_quarantined_round_keeps_z_fused_and_unfused(preset, _src):
    """The all-dropped invariant's quarantine mirror: the hair-trigger
    threshold (z=0) quarantines every client at the first exchange, so
    the second exchange has no trusted survivors and keeps z unchanged —
    dual residual exactly 0 — for fedavg AND admm, fused and unfused,
    with bit-identical trajectories across the two paths."""
    flats = {}
    for fuse in (True, False):
        tr = Trainer(
            _tiny(preset, quarantine_z=0.0, fuse_rounds=fuse),
            verbose=False, source=_src,
        )
        tr.run()
        q = tr.recorder.series["quarantine"]
        assert q[0]["nadmm"] == 0
        assert q[0]["value"]["clients"] == list(range(tr.cfg.n_clients))
        duals = [r["value"] for r in tr.recorder.series["dual_residual"]]
        assert duals[1] == 0.0  # z unchanged through the quarantined round
        # update norms recorded for every exchange
        assert len(tr.recorder.series["update_norm"]) == tr.cfg.nadmm
        flats[fuse] = _final_flat(tr)
    np.testing.assert_array_equal(flats[True], flats[False])


def test_corrupted_round_fused_equals_unfused(_src):
    """Corruption rows as scan xs + in-carry quarantine replay the exact
    unfused schedule: bit-identical final state (the gauss mode's
    on-device noise included)."""
    cfg = _tiny(
        "admm", fault_plan="seed=9,dropout=0.2,corrupt=1:gauss:0.5",
        robust_agg="median", quarantine_z=1.0, bb_update=True,
    )
    flats = {}
    for fuse in (True, False):
        tr = Trainer(cfg.replace(fuse_rounds=fuse), verbose=False, source=_src)
        tr.run()
        flats[fuse] = _final_flat(tr)
    np.testing.assert_array_equal(flats[True], flats[False])


# ------------------------------------------------- the acceptance contract
#
# the discriminating oracle (`src_hard_accept` — label noise + prototype
# overlap keep accuracy off the ceiling so corruption damage SHOWS), the
# gate config builder (`accept_cfg`) and the fault-free f32 baseline run
# (`fault_free_accept`) are session fixtures in conftest.py, shared with
# test_exchange.py's codec gates — one baseline run for the whole suite.


def _final_acc(tr):
    v = tr.recorder.latest("test_accuracy")
    return float(np.mean(v)) if v is not None else None


def _fault_kinds(tr):
    return [f["value"]["kind"] for f in tr.recorder.series.get("fault", [])]


# the nan_burst leg re-runs the identical gate with a second corruption
# mode; tier-1 sits at the 870 s driver timeout (the wall, not the test
# count, is the scarce resource — measured 859 s at the pre-PR-9 seed), so
# the scale leg carries the gate in tier-1 and nan_burst rides tier-2
@pytest.mark.parametrize(
    "mode",
    ["scale", pytest.param("nan_burst", marks=pytest.mark.slow)],
)
def test_trimmed_survives_corruption_mean_does_not(
    mode, src_hard_accept, fault_free_accept, accept_cfg
):
    """THE acceptance gate: one client corrupted per round (scale λ=10 /
    nan_burst). trimmed(f=1) finishes with ZERO rollback rounds and
    fault-free-level accuracy (within 2 points) in the folded one-dispatch
    round; mean on the same plan degrades to chance or rolls back."""
    plan = f"seed=7,corrupt=1:{mode}:10"
    acc_free = _final_acc(fault_free_accept)

    tr = Trainer(
        accept_cfg(fault_plan=plan, robust_agg="trimmed", robust_f=1),
        verbose=False, source=src_hard_accept,
    )
    tr.run()
    assert "round_rollback" not in _fault_kinds(tr)
    assert "nonfinite_params" not in _fault_kinds(tr)
    acc = _final_acc(tr)
    assert acc is not None and abs(acc - acc_free) <= 0.02, (acc, acc_free)
    # the folded dispatch budget holds with the defense in the program
    for r in tr.recorder.series["dispatch_count"]:
        assert r["value"] == {"round": 1, "round_init": 1, "total": 2}

    tm = Trainer(
        accept_cfg(fault_plan=plan, robust_agg="mean"),
        verbose=False, source=src_hard_accept,
    )
    tm.run()
    rolled = "round_rollback" in _fault_kinds(tm)
    acc_m = _final_acc(tm)
    degraded = acc_m is None or acc_m < acc_free - 0.02
    assert rolled or degraded, (mode, acc_m, acc_free, _fault_kinds(tm))


@pytest.mark.slow
def test_crash_resume_stream_identity_with_quarantine_records(
    _src, tmp_path, norm_stream
):
    """The PR-3/PR-4 stream-identity contract extended to the robust
    layer: a corruption+quarantine chaos run killed by a planned crash
    and resumed yields the uninterrupted twin's stream — quarantine,
    update_norm, and quarantined-comm records included. Slow tier (three
    trainer runs): the CORE crash-resume identity stays tier-1 in
    test_obs.py/test_fold_eval.py; this variant adds the robust-layer
    records and rides tier-2 with the hetero/cohort variants."""
    from federated_pytorch_test_tpu.fault import InjectedCrash

    def cfgq(tag, plan):
        return _tiny(
            nloop=2, save_model=True, check_results=True, eval_batch=30,
            fault_plan=plan, robust_agg="trimmed", robust_f=1,
            quarantine_z=1.0,
            checkpoint_dir=str(tmp_path / tag),
            metrics_stream=str(tmp_path / f"{tag}.jsonl"),
        )

    plan = "seed=13,dropout=0.3,corrupt=1:scale:10"
    tr_a = Trainer(cfgq("a", plan), verbose=False, source=_src)
    tr_a.run()
    assert "quarantine" in tr_a.recorder.series  # the records under test

    gid = tr_a.group_order[0]
    cfg_b = cfgq("b", f"{plan},crash=1:{gid}:0")
    tr_b = Trainer(cfg_b, verbose=False, source=_src)
    with pytest.raises(InjectedCrash):
        tr_b.run()
    tr_b2 = Trainer(cfg_b.replace(resume="auto"), verbose=False, source=_src)
    assert tr_b2._completed_nloops == 1
    tr_b2.run()

    # the shared twin-stream normalizer (tests/conftest.py norm_stream)
    assert norm_stream(tmp_path / "a.jsonl") == norm_stream(tmp_path / "b.jsonl")
    # the resume-proof chaos scoreboard agrees on everything but the
    # crash the twins differ by (and it never streams — stream identity
    # above would otherwise be impossible by construction)
    inj_a = dict(tr_a.recorder.latest("injected_faults"))
    inj_b = dict(tr_b2.recorder.latest("injected_faults"))
    assert (inj_a.pop("crashes"), inj_b.pop("crashes")) == (0, 1)
    assert inj_a == inj_b


def test_nan_burst_stream_is_strict_json(_src, tmp_path):
    """A nan-burst-corrupted sender's update norm records as null, never
    as a bare NaN token — the JSONL stream must stay RFC-8259 parseable
    (docs/OBSERVABILITY.md tells users to jq it)."""
    cfg = _tiny(
        fault_plan="seed=7,corrupt=1:nan_burst", robust_agg="trimmed",
        robust_f=1, quarantine_z=1.0,
        metrics_stream=str(tmp_path / "m.jsonl"),
    )
    tr = Trainer(cfg, verbose=False, source=_src)
    tr.run()

    def strict(s):  # reject the NaN/Infinity extensions json.loads allows
        return json.loads(
            s, parse_constant=lambda tok: (_ for _ in ()).throw(
                ValueError(f"non-strict JSON token {tok}")
            )
        )

    lines = [strict(l) for l in open(tmp_path / "m.jsonl")]
    unorms = [l for l in lines if l.get("series") == "update_norm"]
    assert unorms and any(None in l["value"] for l in unorms)
    # ...and the corrupted sender was quarantined off the null evidence
    assert any(l.get("series") == "quarantine" for l in lines)


@pytest.mark.slow
def test_comm_ledger_attributes_quarantined_uplink(_src):
    """comm_bytes counts every TRANSMITTING client (a quarantined sender
    doesn't know it's excluded), and the summary attributes the
    quarantined share as wasted — hand-computed from the suspect series.
    Slow tier (PR-11 wall budget): the zero-waste side of the attribution
    is gated tier-1 by the quarantine-release test (tests/test_fleet.py)
    and the stream-level comm contract by tier-2 bf16_smoke.

    MEDIAN combiner on purpose: under trimmed(f) the quarantine-release
    rule (docs/FAULT.md §Quarantine) un-excludes suspects whenever the
    trusted cohort would shrink to <= 2f — at K=3 that is every exchange
    after the first flag, so nothing would ever be wasted and this test
    would exercise nothing. The release is trimmed-scoped; median keeps
    the pre-release exclusion semantics this contract is about (the
    release's own zero-waste accounting is gated in tests/test_fleet.py).
    """
    cfg = _tiny(
        fault_plan="seed=7,corrupt=1:scale:10", robust_agg="median",
        quarantine_z=1.0, nadmm=3,
    )
    tr = Trainer(cfg, verbose=False, source=_src)
    tr.run()
    gid = tr.group_order[0]
    gsize = tr.partition.group_size(gid)
    dtype_bytes = 4
    k = cfg.n_clients
    recs = tr.recorder.series["comm_bytes"]
    assert len(recs) == cfg.nadmm
    # no dropout in the plan: every client transmits every exchange
    for r in recs:
        assert r["value"] == gsize * dtype_bytes * k
        assert r["survivors"] == k
    # quarantined-at-exchange-a = clients flagged at exchanges < a
    flagged = set()
    expected_wasted = 0
    by_nadmm = {
        r["nadmm"]: r["value"]["clients"]
        for r in tr.recorder.series.get("quarantine", [])
    }
    for a, r in enumerate(recs):
        assert r.get("quarantined", 0) == len(flagged)
        expected_wasted += gsize * dtype_bytes * len(flagged)
        flagged |= set(by_nadmm.get(a, []))
    assert flagged, "the scale-10 corruption should trigger quarantines"
    s = tr.recorder.latest("comm_summary")
    assert s["bytes_quarantined_wasted"] == expected_wasted
