"""Flash-attention kernel tests: exactness vs the dense reference.

The kernels run in Pallas interpret mode on the CPU test platform — the
same code path the TPU compiles. Forward AND backward (custom flash-2
VJP) must match `parallel.dense_attention`'s values and gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from federated_pytorch_test_tpu.ops.flash_attention import flash_attention
from federated_pytorch_test_tpu.parallel import dense_attention


def _qkv(b=2, s=256, h=2, d=32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_dense(causal):
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense(causal):
    q, k, v = _qkv(b=1, s=128, h=2, d=16, seed=1)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=f"d{name}",
        )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_multiblock(causal):
    # s=384 => 3 tiles: exercises cross-block accumulation and BOTH
    # causal skip bounds in the backward kernels (which degenerate to a
    # single iteration at s=128)
    q, k, v = _qkv(b=1, s=384, h=1, d=16, seed=5)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4,
            err_msg=f"d{name}",
        )


def test_flash_2048_tokens_match_dense():
    # nothing is whole-sequence-resident in VMEM (S is HBM-bound only);
    # 16x16 streamed-grid blocks, compared in full against dense
    q, k, v = _qkv(b=1, s=2048, h=1, d=16, seed=6)
    ref = dense_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=3e-5, atol=3e-6)


def test_flash_custom_scale_and_jit():
    q, k, v = _qkv(b=1, s=128, h=1, d=64, seed=2)
    ref = dense_attention(q, k, v, sm_scale=0.07)
    out = jax.jit(lambda *a: flash_attention(*a, sm_scale=0.07))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)
    # static numpy scalars are fine; only traced values are rejected
    out = flash_attention(q, k, v, sm_scale=np.float32(0.07))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)
    with pytest.raises(TypeError, match="static"):
        jax.jit(lambda q, k, v, sc: flash_attention(q, k, v, sm_scale=sc))(
            q, k, v, jnp.float32(0.07)
        )


def test_flash_rejects_ragged_seq():
    q, k, v = _qkv(s=100)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v)


def test_flash_in_transformer_lm_matches_dense():
    # the model-family wiring: TransformerLM(attn_impl='flash') == dense
    from federated_pytorch_test_tpu.models import TransformerLM

    rng = np.random.default_rng(4)
    tokens = jnp.asarray(rng.integers(0, 64, size=(2, 128)), jnp.int32)
    dense_lm = TransformerLM(attn_impl="dense", dim=32, num_heads=2, vocab=64)
    flash_lm = TransformerLM(attn_impl="flash", dim=32, num_heads=2, vocab=64)
    params = dense_lm.init(jax.random.PRNGKey(0), tokens)
    ref = dense_lm.apply(params, tokens)
    out = flash_lm.apply(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)

    # and gradients flow through the custom VJP inside the full model
    def loss(p, lm):
        return jnp.sum(lm.apply(p, tokens) ** 2)

    gf = jax.grad(lambda p: loss(p, flash_lm))(params)
    gd = jax.grad(lambda p: loss(p, dense_lm))(params)
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4)


def test_flash_long_context_values_stay_exact():
    # 1024 tokens, causal — the regime dense attention exists to avoid;
    # spot-check rows against a numpy softmax computed directly
    q, k, v = _qkv(b=1, s=1024, h=1, d=16, seed=3)
    out = flash_attention(q, k, v, causal=True)
    qn, kn, vn = (np.asarray(x)[0, :, 0, :] for x in (q, k, v))
    for row in (0, 511, 1023):
        sc = (qn[row] @ kn[: row + 1].T) / np.sqrt(16.0)
        p = np.exp(sc - sc.max())
        p /= p.sum()
        np.testing.assert_allclose(
            np.asarray(out)[0, row, 0, :], p @ vn[: row + 1],
            rtol=3e-5, atol=3e-6, err_msg=f"row {row}",
        )
