"""Flash-attention kernel tests: exactness vs the dense reference.

The kernels run in Pallas interpret mode on the CPU test platform — the
same code path the TPU compiles. Forward AND backward (custom flash-2
VJP) must match `parallel.dense_attention`'s values and gradients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from federated_pytorch_test_tpu.ops.flash_attention import (
    flash_attention,
    flash_block,
)
from federated_pytorch_test_tpu.parallel import dense_attention

pytestmark = pytest.mark.slow  # heavy tier (jit-compile dominated)


def _qkv(b=2, s=256, h=2, d=32, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, s, h, d)), jnp.float32)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_dense(causal):
    q, k, v = _qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_dense(causal):
    q, k, v = _qkv(b=1, s=128, h=2, d=16, seed=1)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-5,
            err_msg=f"d{name}",
        )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_multiblock(causal):
    # s=384 with forced 128-row tiles => 3 tiles per axis: exercises
    # cross-block accumulation and BOTH causal skip bounds in the backward
    # kernels. The explicit block_q/block_k matter: the 512 default would
    # resolve to ONE 384-row tile and the multi-tile init/flush paths of
    # the triangular dq/dkv kernels would never run.
    q, k, v = _qkv(b=1, s=384, h=1, d=16, seed=5)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
            ** 2
        )

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4,
            err_msg=f"d{name}",
        )


def test_flash_2048_tokens_match_dense():
    # nothing is whole-sequence-resident in VMEM (S is HBM-bound only);
    # 16x16 streamed-grid blocks, compared in full against dense
    q, k, v = _qkv(b=1, s=2048, h=1, d=16, seed=6)
    ref = dense_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=3e-5, atol=3e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_multiblock_default_tiles(causal):
    # s=1024 with the DEFAULT 512 tiles => 2x2 triangular tile grid:
    # gradient coverage for the production tile shape (the forced-128
    # test above covers 3x3; the s=2048 test is forward-only)
    q, k, v = _qkv(b=1, s=1024, h=1, d=16, seed=11)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4,
            err_msg=f"d{name}",
        )


def test_flash_custom_scale_and_jit():
    q, k, v = _qkv(b=1, s=128, h=1, d=64, seed=2)
    ref = dense_attention(q, k, v, sm_scale=0.07)
    out = jax.jit(lambda *a: flash_attention(*a, sm_scale=0.07))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)
    # static numpy scalars are fine; only traced values are rejected
    out = flash_attention(q, k, v, sm_scale=np.float32(0.07))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-6)
    with pytest.raises(TypeError, match="static"):
        jax.jit(lambda q, k, v, sc: flash_attention(q, k, v, sm_scale=sc))(
            q, k, v, jnp.float32(0.07)
        )


def test_flash_default_precision_mode():
    # precision='default' (single bf16 MXU passes) must stay close to the
    # f32 reference — loose tolerance, it exists to be fast, not exact —
    # and gradients must flow; bogus precision names must be rejected
    q, k, v = _qkv(b=1, s=256, h=2, d=32, seed=10)
    ref = dense_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, precision="default")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)

    g = jax.grad(
        lambda q: jnp.sum(
            flash_attention(q, k, v, causal=True, precision="default") ** 2
        )
    )(q)
    assert np.isfinite(np.asarray(g)).all()

    with pytest.raises(ValueError, match="precision"):
        flash_attention(q, k, v, precision="fast")


def test_auto_attn_dispatch_matches_measured_crossover():
    # attn_impl='auto' picks dense below the measured flash crossover
    # (round 5: S>=1024 'default' — flash wins 1.55x there — and
    # S>=2048 'highest'; benchmarks/long_context_tpu.json,
    # flash_f32_tiles.json) and flash above it. Bit-equality against
    # the explicit impls proves which core ran (same params, same ops).
    from federated_pytorch_test_tpu.models.transformer import (
        MultiHeadAttention,
    )

    rng = np.random.default_rng(12)

    def outs(s, prec):
        x = jnp.asarray(rng.normal(size=(1, s, 32)), jnp.float32)
        mods = {
            name: MultiHeadAttention(
                32, 2, attn_impl=name, causal=True, attn_precision=prec
            )
            for name in ("auto", "dense", "flash")
        }
        params = mods["dense"].init(jax.random.PRNGKey(0), x)
        return {n: np.asarray(m.apply(params, x)) for n, m in mods.items()}

    o = outs(256, None)  # f32, short: auto must BE dense
    np.testing.assert_array_equal(o["auto"], o["dense"])
    o = outs(2048, "default")  # past the crossover: flash
    np.testing.assert_array_equal(o["auto"], o["flash"])
    assert np.abs(o["flash"] - o["dense"]).max() > 0.0  # distinct cores
    o = outs(1024, "default")  # 'default' crossover moved here (1.55x)
    np.testing.assert_array_equal(o["auto"], o["flash"])
    o = outs(1024, None)  # 'highest' at S=1024: dense still wins (0.72x)
    np.testing.assert_array_equal(o["auto"], o["dense"])


def test_flash_rejects_ragged_seq():
    q, k, v = _qkv(s=100)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v)


def test_flash_in_transformer_lm_matches_dense():
    # the model-family wiring: TransformerLM(attn_impl='flash') == dense
    from federated_pytorch_test_tpu.models import TransformerLM

    rng = np.random.default_rng(4)
    tokens = jnp.asarray(rng.integers(0, 64, size=(2, 128)), jnp.int32)
    dense_lm = TransformerLM(attn_impl="dense", dim=32, num_heads=2, vocab=64)
    flash_lm = TransformerLM(attn_impl="flash", dim=32, num_heads=2, vocab=64)
    params = dense_lm.init(jax.random.PRNGKey(0), tokens)
    ref = dense_lm.apply(params, tokens)
    out = flash_lm.apply(params, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-5)

    # and gradients flow through the custom VJP inside the full model
    def loss(p, lm):
        return jnp.sum(lm.apply(p, tokens) ** 2)

    gf = jax.grad(lambda p: loss(p, flash_lm))(params)
    gd = jax.grad(lambda p: loss(p, dense_lm))(params)
    for a, b in zip(jax.tree.leaves(gf), jax.tree.leaves(gd)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-4)


def test_flash_block_offsets_and_merge():
    # flash_block with global offsets is the ring's per-step partial:
    # folding the two partials of a split K/V axis with the online-softmax
    # merge must reproduce full causal attention over S=256 exactly
    q, k, v = _qkv(b=1, s=256, h=2, d=16, seed=7)
    ref = dense_attention(q, k, v, causal=True)

    qb = q[:, 128:, :, :]  # rows 128..255
    o_parts, lse_parts = [], []
    for j in (0, 1):
        kb = k[:, 128 * j : 128 * (j + 1), :, :]
        vb = v[:, 128 * j : 128 * (j + 1), :, :]
        o, lse = flash_block(
            qb, kb, vb, jnp.int32(128), jnp.int32(128 * j), causal=True
        )  # o [B,H,Sq,D]: kernel-native accumulator layout
        o_parts.append(o)
        lse_parts.append(lse)
    m = jnp.maximum(lse_parts[0], lse_parts[1])
    w0, w1 = (jnp.exp(l - m) for l in lse_parts)
    merged = (o_parts[0] * w0[..., None] + o_parts[1] * w1[..., None]) / (
        w0 + w1
    )[..., None]
    merged = jnp.transpose(merged, (0, 2, 1, 3))
    np.testing.assert_allclose(
        np.asarray(merged), np.asarray(ref)[:, 128:], rtol=2e-5, atol=2e-6
    )

    # a block entirely in the causal future: zero output, -BIG lse
    o, lse = flash_block(
        q[:, :128], k[:, 128:], v[:, 128:], jnp.int32(0), jnp.int32(128),
        causal=True,
    )
    assert float(jnp.abs(o).max()) == 0.0
    assert float(lse.max()) <= -1e29


def test_flash_block_unaligned_offsets():
    # k_off - q_off not a multiple of the tile height: a KEPT tile then
    # contains rows with no visible key at all. Those rows must emit
    # o = 0 / lse = -BIG (and zero gradients), and the visible rows must
    # stay exact — the regression case for the in-tile all-masked-row
    # guard in the forward and backward kernels.
    q, k, v = _qkv(b=1, s=128, h=1, d=16, seed=9)
    off = 64
    o, lse = flash_block(q, k, v, jnp.int32(0), jnp.int32(off), causal=True)
    # o is [B, H, Sq, D] (head-major, the merge-accumulator layout)
    assert float(jnp.abs(o[:, :, :off]).max()) == 0.0
    assert float(lse[:, :, :off].max()) <= -1e29
    # visible rows r >= off see keys with kpos = off + col <= r
    qn, kn, vn = (np.asarray(x)[0, :, 0, :] for x in (q, k, v))
    for row in (off, 100, 127):
        sc = (qn[row] @ kn[: row - off + 1].T) / np.sqrt(16.0)
        pr = np.exp(sc - sc.max())
        pr /= pr.sum()
        np.testing.assert_allclose(
            np.asarray(o)[0, 0, row, :], pr @ vn[: row - off + 1],
            rtol=3e-5, atol=3e-6, err_msg=f"row {row}",
        )

    # gradients: masked rows contribute nothing, so dq there is 0 and
    # the total grads equal those of a loss over visible rows only
    def loss(q, k, v):
        o, _ = flash_block(q, k, v, jnp.int32(0), jnp.int32(off), causal=True)
        return jnp.sum(o**2)

    dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    assert float(jnp.abs(dq[:, :off]).max()) == 0.0

    def loss_dense(q, k, v):
        sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(16.0)
        qi = jnp.arange(128)[:, None]
        ki = off + jnp.arange(128)[None, :]
        sc = jnp.where((ki <= qi)[None, None], sc, -1e30)
        o = jnp.einsum("bhqk,bkhd->bhqd", jax.nn.softmax(sc, axis=-1), v)
        return jnp.sum(o[:, :, off:] ** 2)

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip((dq, dk, dv), gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4,
            err_msg=f"d{name}",
        )


def test_flash_block_lse_gradient():
    # d lse/d scores == softmax: the custom VJP folds the lse cotangent
    # into delta. Check grads of a loss that uses BOTH outputs against
    # autodiff through an explicit dense (o, lse) computation.
    q, k, v = _qkv(b=1, s=128, h=1, d=16, seed=8)

    def loss_flash(q, k, v):
        o, lse = flash_block(q, k, v, jnp.int32(0), jnp.int32(0), causal=True)
        return jnp.sum(o**2) + jnp.sum(jnp.sin(lse))

    def loss_dense(q, k, v):
        scale = 1.0 / np.sqrt(16.0)
        sc = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        qi = jnp.arange(128)[:, None]
        ki = jnp.arange(128)[None, :]
        sc = jnp.where((ki <= qi)[None, None], sc, -1e30)
        lse = jax.scipy.special.logsumexp(sc, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bhqd", jax.nn.softmax(sc, axis=-1), v)
        return jnp.sum(o**2) + jnp.sum(jnp.sin(lse))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4,
            err_msg=f"d{name}",
        )


def test_flash_long_context_values_stay_exact():
    # 1024 tokens, causal — the regime dense attention exists to avoid;
    # spot-check rows against a numpy softmax computed directly
    q, k, v = _qkv(b=1, s=1024, h=1, d=16, seed=3)
    out = flash_attention(q, k, v, causal=True)
    qn, kn, vn = (np.asarray(x)[0, :, 0, :] for x in (q, k, v))
    for row in (0, 511, 1023):
        sc = (qn[row] @ kn[: row + 1].T) / np.sqrt(16.0)
        p = np.exp(sc - sc.max())
        p /= p.sum()
        np.testing.assert_allclose(
            np.asarray(out)[0, row, 0, :], p @ vn[: row + 1],
            rtol=3e-5, atol=3e-6, err_msg=f"row {row}",
        )


def test_flash_bf16_inputs_match_dense():
    # the round-5 bf16-resident path end to end: bf16 tiles stay bf16
    # through the kernels (keep_bf16), the probability tile feeds the MXU
    # in bf16 at 'default' precision (cast16), the fused softmax
    # denominator rides the augmented-V dot (fuse_l), and s % 1024 == 0
    # picks the measured 1024 default tile. Values and gradients must
    # stay within bf16 rounding class of the f32 dense reference.
    q, k, v = _qkv(b=1, s=1024, h=2, d=16, seed=13)
    q16, k16, v16 = (x.astype(jnp.bfloat16) for x in (q, k, v))
    ref = dense_attention(q, k, v, causal=True)
    out = flash_attention(q16, k16, v16, causal=True, precision="default")
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=0.06, atol=0.03
    )

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, precision="default")
        return jnp.sum(o.astype(jnp.float32) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q16, k16, v16)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        assert a.dtype == jnp.bfloat16, f"d{name} cotangent dtype"
        denom = np.maximum(np.abs(np.asarray(b)), 1.0)
        rel = np.max(np.abs(np.asarray(a, np.float32) - np.asarray(b)) / denom)
        assert rel < 0.08, f"d{name} rel err {rel}"


def test_flash_bf16_highest_precision_keeps_f32_probabilities():
    # bf16 inputs with precision='highest' must NOT take the cast16/fuse_l
    # shortcuts: probabilities stay f32, so values sit much closer to the
    # f32 dense reference than the bf16-rounded default path
    q, k, v = _qkv(b=1, s=256, h=1, d=16, seed=14)
    q16, k16, v16 = (x.astype(jnp.bfloat16) for x in (q, k, v))
    ref = dense_attention(
        q16.astype(jnp.float32), k16.astype(jnp.float32),
        v16.astype(jnp.float32), causal=True,
    )
    out = flash_attention(q16, k16, v16, causal=True, precision="highest")
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), rtol=2e-2, atol=8e-3
    )
