"""Chaos smoke test through the CLI: kill a run mid-flight, resume it.

The end-to-end acceptance path of the fault PR (docs/FAULT.md): a seeded
2-outer-loop synthetic-CIFAR run with dropout and one planned crash exits
non-zero on the injected crash, and rerunning the IDENTICAL command with
`--resume auto` recovers from the latest checkpoint and completes. Not
marked slow — this is the tier-1 proof that crash recovery works from a
cold process, not just in-process — but kept to one tiny model and one
partition group so the compile cache amortizes it.
"""

import json
import os
import subprocess
import sys

import pytest

from federated_pytorch_test_tpu.utils import compile_cache_dir

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    XLA_FLAGS="--xla_force_host_platform_device_count=8",
    JAX_COMPILATION_CACHE_DIR=compile_cache_dir(),
    TF_CPP_MIN_LOG_LEVEL="3",
)


def _run(*args, timeout=600):
    return subprocess.run(
        [sys.executable, "-m", "federated_pytorch_test_tpu", *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=ENV,
    )


def test_chaos_kill_and_resume_via_cli(tmp_path):
    out = tmp_path / "metrics.json"
    empty = tmp_path / "no-archive"
    empty.mkdir()
    args = [
        "--preset", "fedavg",
        "--model", "net",
        "--data-root", str(empty),  # force the deterministic synthetic set
        "--batch", "40",
        "--nloop", "2",
        "--nepoch", "1",
        "--nadmm", "1",
        "--n-clients", "4",
        "--synthetic-n-train", "480",
        "--synthetic-n-test", "64",
        "--max-groups", "1",
        "--no-check-results",
        "--save-model",
        "--resume", "auto",
        "--checkpoint-dir", str(tmp_path / "ckpt"),
        # dropout chaos + a planned crash in outer loop 1. The crash
        # cursor must name the round actually trained: net's partition
        # train_order visits group 2 first, so max-groups=1 trains gid 2
        # every loop.
        "--fault-plan", "seed=21,dropout=0.3,crash=1:2:0",
        "--quiet",
        "--metrics-out", str(out),
    ]

    first = _run(*args)
    assert first.returncode != 0, "planned crash must exit non-zero"
    assert "InjectedCrash" in first.stderr or "planned crash" in first.stderr

    second = _run(*args)  # the IDENTICAL command: operator just reruns it
    assert second.returncode == 0, second.stderr[-2000:]
    series = json.loads(out.read_text())["series"]
    assert "train_loss" in series and "dual_residual" in series
    # chaos telemetry made it through the full pipeline
    assert "participation" in series
    # loop-1 rounds ran in the resumed process (cursor restored to 1)
    assert any(r["nloop"] == 1 for r in series["dual_residual"])


def test_fault_plan_flag_rejects_garbage():
    r = _run("--preset", "fedavg", "--fault-plan", "banana=1", timeout=120)
    assert r.returncode != 0
    assert "unknown fault-plan key" in r.stderr
