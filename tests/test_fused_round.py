"""Fused-round contract tests (engine/steps.py build_round_fn).

The tentpole claim of the fusion PR, verified in the DEFAULT tier:

* ONE jitted dispatch per `run_round` partition-group round — all
  `nepoch` epochs and every consensus/ADMM exchange of the `nadmm` scan
  execute inside a single program launch (the dispatch-count test wraps
  the round program and poisons the per-dispatch epoch/consensus
  programs);
* the fused trajectory is BIT-identical to the unfused path — params,
  consensus state, the persistent ADMM rho store, and every recorded
  series (per-minibatch losses, residuals, accuracies) — for fedavg AND
  admm, healthy and poisoned (`fault_mode='rollback'`) rounds alike;
* the escape hatch (`--no-fuse-rounds`) and every documented fallback
  condition actually reach the unfused path.

BN-stats equality under fusion runs against a minimal BatchNorm CNN
registered by the test (ResNet18 — the registry's only batch-stats
model — costs minutes of CPU execution per epoch on small CI hosts).
"""

import numpy as np
import pytest

from federated_pytorch_test_tpu.data import synthetic_cifar
from federated_pytorch_test_tpu.engine import ExperimentConfig, Trainer, get_preset

SRC = synthetic_cifar(n_train=240, n_test=60)


def tiny(preset: str, **over) -> ExperimentConfig:
    base = dict(
        batch=40, nloop=1, max_groups=1, model="net",
        check_results=True, eval_batch=30, synthetic_ok=True,
    )
    base.update(over)
    return get_preset(preset, **base)


def _run(cfg):
    tr = Trainer(cfg, verbose=False, source=SRC)
    rec = tr.run()
    return tr, rec


def _series(rec, name):
    return [r["value"] for r in rec.series.get(name, [])]


@pytest.mark.parametrize(
    "preset,over",
    [
        ("fedavg", dict(nadmm=2)),
        # nadmm=3 with BB on crosses a due BB step (period 2) inside the
        # fused scan — the trickiest consensus state to keep bit-equal.
        # Slow tier per the PR-9 rule (admm legs ride the slow tier:
        # two extra program compiles, ~17 s, and the tier-1 wall sits
        # at the 870 s driver budget); the fedavg leg keeps the
        # fused==unfused contract in tier 1
        pytest.param(
            "admm", dict(nadmm=3, bb_update=True), marks=pytest.mark.slow
        ),
    ],
)
def test_fused_matches_unfused_bit_identical(preset, over):
    runs = {}
    for fuse in (True, False):
        tr, rec = _run(tiny(preset, fuse_rounds=fuse, **over))
        assert tr._fused_enabled() == fuse
        runs[fuse] = (tr, rec)
    tr_f, rec_f = runs[True]
    tr_u, rec_u = runs[False]

    np.testing.assert_array_equal(np.asarray(tr_f.flat), np.asarray(tr_u.flat))
    # stats: trivial (empty) for the BN-less CNN, asserted for shape of
    # the contract; the real BN case is test_fused_bn_stats_match_unfused
    import jax

    for a, b in zip(jax.tree.leaves(tr_f.stats), jax.tree.leaves(tr_u.stats)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert sorted(tr_f._rho_store) == sorted(tr_u._rho_store)
    for g in tr_f._rho_store:
        np.testing.assert_array_equal(
            np.asarray(tr_f._rho_store[g]), np.asarray(tr_u._rho_store[g])
        )

    # every recorded series, bit for bit and cursor for cursor
    for name in ("train_loss", "dual_residual", "primal_residual",
                 "mean_rho", "test_accuracy"):
        a = [
            (r["nloop"], r["group"], r["nadmm"], np.asarray(r["value"]).tolist())
            for r in rec_f.series.get(name, [])
        ]
        b = [
            (r["nloop"], r["group"], r["nadmm"], np.asarray(r["value"]).tolist())
            for r in rec_u.series.get(name, [])
        ]
        assert a == b, name


def test_fused_round_is_one_dispatch():
    cfg = tiny("fedavg", nadmm=2, nepoch=2, check_results=False)
    tr = Trainer(cfg, verbose=False, source=SRC)
    gid = tr.group_order[0]

    fn = tr._round_fn(gid)
    calls = []

    def counted(*args, **kw):
        calls.append(1)
        return fn(*args, **kw)

    tr._round_fns[gid] = counted

    # the per-dispatch programs must never launch on the fused path
    def boom(*args, **kw):
        raise AssertionError("unfused program dispatched on the fused path")

    tr._epoch_fns[gid] = boom
    tr._consensus_fns[gid] = boom

    tr.run_round(nloop=0, gid=gid)
    assert calls == [1], "fused round must be exactly ONE program dispatch"

    # ...and the one dispatch delivered the whole round's telemetry:
    # nadmm*nepoch epochs of per-minibatch losses + nadmm consensus
    # rounds (240 train / 3 clients = 80/client; batch 40 => S=2)
    losses = tr.recorder.series["train_loss"]
    assert len(losses) == 2 * 2 * (80 // cfg.batch)  # nadmm*nepoch*S
    assert len(tr.recorder.series["dual_residual"]) == 2  # one per nadmm
    phases = {t["value"]["phase"] for t in tr.recorder.series["step_time"]}
    assert phases == {"fused_round"}


def test_fused_rollback_matches_unfused_on_poisoned_round():
    # the rollback poisoned-round case of the satellite contract: a
    # NaN-poisoned client makes every loss/param check fire through the
    # fused round's on-device flags, and the transactional rollback
    # restores the entry state exactly as the unfused path does
    import jax.numpy as jnp

    outs = {}
    for fuse in (True, False):
        cfg = tiny(
            "fedavg", nadmm=2, check_results=False,
            fault_mode="rollback", fuse_rounds=fuse,
        )
        tr = Trainer(cfg, verbose=False, source=SRC)
        tr.flat = tr.flat.at[1].set(jnp.nan)
        entry = np.asarray(tr.flat).copy()
        tr.run_round(nloop=0, gid=tr.group_order[0])
        kinds = [f["value"]["kind"] for f in tr.recorder.series["fault"]]
        outs[fuse] = (entry, np.asarray(tr.flat), kinds)

    for fuse, (entry, final, kinds) in outs.items():
        # rollback restored the (poisoned) entry state wholesale
        np.testing.assert_array_equal(final, entry)
        assert "nonfinite_loss" in kinds, fuse
        # post-consensus params flagged via the fused scan's on-device
        # flags (the FedAvg mean propagates client 1's NaN to everyone)
        assert "nonfinite_params" in kinds, fuse
        assert kinds[-1] == "round_rollback", fuse
    # identical fault records, fused or not
    assert outs[True][2] == outs[False][2]
    np.testing.assert_array_equal(outs[True][1], outs[False][1])


def test_fused_straggler_stalls_truncate_at_crash_point():
    # a planned crash at consensus iteration c means the unfused replay
    # never reaches the stalls of iterations > c; the fused path serves
    # its stalls up-front, so it must truncate the schedule there — and
    # the resumed run (crash sentinel fired) must serve the full one
    from federated_pytorch_test_tpu.fault.plan import InjectedCrash

    plan = "seed=7,straggler=1.0:0.01,crash=0:{gid}:0"
    cfg0 = tiny("fedavg", nadmm=3, check_results=False,
                fault_plan="seed=7,straggler=1.0:0.01")
    gid = Trainer(cfg0, verbose=False, source=SRC).group_order[0]

    cfg = cfg0.replace(fault_plan=plan.format(gid=gid))
    tr = Trainer(cfg, verbose=False, source=SRC)
    with pytest.raises(InjectedCrash):
        tr.run_round(nloop=0, gid=gid)
    waits = [
        t["nadmm"] for t in tr.recorder.series["step_time"]
        if t["value"]["phase"] == "straggler_wait"
    ]
    # straggler_p=1: every iteration stalls, but only up to the crash
    # at nadmm=0 — exactly what the unfused replay would serve
    assert waits == [0], waits

    # resumed process analogue: a fresh injector over the same state —
    # here, the same in-process injector whose fire-once record is set —
    # serves the full schedule, like the unfused resumed run
    tr.run_round(nloop=0, gid=gid)
    waits2 = [
        t["nadmm"] for t in tr.recorder.series["step_time"]
        if t["value"]["phase"] == "straggler_wait"
    ]
    assert waits2 == [0, 0, 1, 2], waits2


def test_fused_fallback_conditions_reach_unfused_path():
    # the escape hatch
    tr = Trainer(
        tiny("fedavg", fuse_rounds=False), verbose=False, source=SRC
    )
    assert not tr._fused_enabled()
    # per-epoch eval cadence (strategy 'none' + check_results) needs the
    # unfused path: the fused program only snapshots consensus boundaries
    tr = Trainer(
        tiny("no_consensus", model="net", nepoch=1), verbose=False, source=SRC
    )
    assert not tr._fused_enabled()
    # per-batch eval interleaving
    tr = Trainer(
        tiny("fedavg", eval_every_batch=True), verbose=False, source=SRC
    )
    assert not tr._fused_enabled()
    # host-streaming data is inherently multi-dispatch
    tr = Trainer(
        tiny("fedavg", hbm_data_budget_mb=0), verbose=False, source=SRC
    )
    assert not tr._fused_enabled()
    for b in (tr._batchers or {}).values():
        b.close()
    # the fused scan respects the long-scan dispatch cap: 2 steps/epoch
    # x nadmm=2 > max_scan_steps=3 falls back
    tr = Trainer(
        tiny("fedavg", nadmm=2, max_scan_steps=3), verbose=False, source=SRC
    )
    assert not tr._fused_enabled()
    # ...and the default config on this schedule fuses
    tr = Trainer(tiny("fedavg", nadmm=2), verbose=False, source=SRC)
    assert tr._fused_enabled()


def test_compile_round_seeds_fused_program():
    # the AOT seeding path lowers the FUSED program without executing a
    # training step, and the seeded trainer then matches an unseeded twin
    cfg = tiny("fedavg", nadmm=1, check_results=False)
    tr = Trainer(cfg, verbose=False, source=SRC)
    gid = tr.group_order[0]
    before = np.asarray(tr.flat).copy()
    tr.compile_round(gid)
    np.testing.assert_array_equal(np.asarray(tr.flat), before)
    tr.run_round(nloop=0, gid=gid)
    twin = Trainer(cfg, verbose=False, source=SRC)
    twin.run_round(nloop=0, gid=gid)
    np.testing.assert_array_equal(np.asarray(tr.flat), np.asarray(twin.flat))


def test_fused_bn_stats_match_unfused():
    # the (flat, STATS, rho) clause of the contract for a model that has
    # batch stats: the BN running statistics thread through the fused
    # scan's carry exactly as through per-epoch dispatches. ResNet18 is
    # the registry's only batch-stats model but costs many minutes of
    # CPU execution per epoch on a small CI host (its line-search probes
    # are full model passes), so this registers a MINIMAL BatchNorm CNN
    # — same stats machinery (train-mode batch statistics, folded
    # diagnostic refresh, client-local running stats), net-sized cost.
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from federated_pytorch_test_tpu.models import MODELS
    from federated_pytorch_test_tpu.models.base import PartitionedModel

    class TinyBN(PartitionedModel):
        GROUP_PATHS = ((("conv1",), ("bn1",)), (("fc",),))
        LINEAR_GROUP_IDS = (1,)
        TRAIN_ORDER = (0, 1)

        num_classes: int = 10

        @nn.compact
        def __call__(self, x: jnp.ndarray, train: bool = True) -> jnp.ndarray:
            dt = self.dtype
            x = nn.Conv(8, (3, 3), strides=(2, 2), dtype=dt, name="conv1")(x)
            x = nn.BatchNorm(
                use_running_average=not train, dtype=dt, name="bn1"
            )(x)
            x = nn.relu(x)
            x = x.reshape((x.shape[0], -1))
            return nn.Dense(self.num_classes, dtype=dt, name="fc")(x)

    MODELS["_test_tiny_bn"] = TinyBN
    try:
        outs = {}
        for fuse in (True, False):
            cfg = tiny(
                "fedavg", model="_test_tiny_bn", nadmm=2,
                check_results=False, fuse_rounds=fuse,
            )
            tr = Trainer(cfg, verbose=False, source=SRC)
            assert tr.has_stats
            tr.run()
            outs[fuse] = (
                np.asarray(tr.flat).copy(),
                [np.asarray(x).copy() for x in jax.tree.leaves(tr.stats)],
            )
    finally:
        del MODELS["_test_tiny_bn"]
    np.testing.assert_array_equal(outs[True][0], outs[False][0])
    assert outs[True][1], "batch_stats collection must be non-trivial"
    for a, b in zip(outs[True][1], outs[False][1]):
        np.testing.assert_array_equal(a, b)
