"""MoE layer + expert-parallelism tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from federated_pytorch_test_tpu.models.moe import (
    EXPERT_AXIS,
    MoEMLP,
    client_expert_mesh,
    ep_param_specs,
    expert_mesh,
    shard_params_ep,
)

# spec/guard tests (no jit) are smoke; the compile-heavy numerics tests
# ride the unmarked middle tier

DIM, E = 8, 4


def _layer(**kw):
    return MoEMLP(dim=DIM, n_experts=E, mlp_ratio=2, **kw)


def _init(layer, b=2, s=16, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(b, s, DIM)), jnp.float32)
    params = layer.init(jax.random.PRNGKey(seed), x)["params"]
    return params, x


def test_moe_matches_manual_top1_routing():
    # ample capacity: every token must get gate_prob * mlp_{argmax}(x)
    layer = _layer(capacity_factor=float(E))  # capacity == tokens
    params, x = _init(layer)
    out = layer.apply({"params": params}, x)
    xt = np.asarray(x).reshape(-1, DIM)
    logits = xt @ np.asarray(params["gate"]["kernel"]) + np.asarray(
        params["gate"]["bias"]
    )
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    idx = probs.argmax(-1)
    w1, b1 = np.asarray(params["w1"]), np.asarray(params["b1"])
    w2, b2 = np.asarray(params["w2"]), np.asarray(params["b2"])
    want = np.stack([
        probs[t, idx[t]] * (
            np.asarray(jax.nn.gelu(jnp.asarray(xt[t] @ w1[idx[t]] + b1[idx[t]])))
            @ w2[idx[t]] + b2[idx[t]]
        )
        for t in range(xt.shape[0])
    ]).reshape(np.asarray(out).shape)
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-5, rtol=1e-5)


def test_moe_capacity_overflow_rides_residual():
    # capacity 1 with 32 tokens: most tokens overflow and contribute 0
    # (block residual carries them); kept tokens still get routed output
    layer = _layer(capacity_factor=1.0 / 8)
    params, x = _init(layer, s=16)
    out = np.asarray(layer.apply({"params": params}, x)).reshape(-1, DIM)
    zero_rows = np.sum(np.all(np.abs(out) < 1e-12, axis=1))
    # E experts x capacity ceil(32/4 * 1/8)=1 slot => at most E nonzero rows
    assert zero_rows >= out.shape[0] - E


def test_moe_aux_loss_is_one_at_uniform_routing():
    layer = _layer(return_aux=True, capacity_factor=float(E))
    params, x = _init(layer)
    # zero the gate: uniform probs, aux == E * sum(frac_e * 1/E) == 1
    params = jax.tree.map(np.zeros_like, params)
    _, aux = layer.apply({"params": params}, x)
    assert abs(float(aux) - 1.0) < 1e-6


@pytest.mark.smoke
def test_ep_specs_shard_only_expert_stacks():
    layer = _layer()
    params, _ = _init(layer)
    specs = ep_param_specs(params, E)
    assert tuple(specs["w1"]) == (EXPERT_AXIS,)
    assert tuple(specs["w2"]) == (EXPERT_AXIS,)
    assert tuple(specs["b1"]) == (EXPERT_AXIS,)
    assert tuple(specs["gate"]["kernel"]) == ()
    assert tuple(specs["gate"]["bias"]) == ()


@pytest.mark.parametrize("de", [2, 4])
def test_ep_forward_and_grads_match_replicated(de):
    layer = _layer(capacity_factor=2.0)
    params, x = _init(layer, seed=3)

    def loss(p, xx):
        return jnp.mean(layer.apply({"params": p}, xx) ** 2)

    ref_l, ref_g = jax.value_and_grad(loss)(params, x)
    mesh = expert_mesh(de)
    sh = shard_params_ep(params, mesh, E)
    # expert stacks are distributed, E/de experts per device
    assert {s.data.shape[0] for s in sh["w1"].addressable_shards} == {E // de}
    tp_l, tp_g = jax.jit(jax.value_and_grad(loss))(sh, x)
    np.testing.assert_allclose(float(tp_l), float(ref_l), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=3e-5, rtol=1e-4
        ),
        tp_g,
        ref_g,
    )


def test_ep_composes_with_client_axis():
    layer = _layer(capacity_factor=2.0)
    params, x = _init(layer, seed=4)
    k = 2
    stacked = jax.tree.map(
        lambda a: jnp.stack([a, 1.5 * a]), params
    )
    xs = jnp.stack([x, x[:, ::-1]])
    ref = jax.vmap(lambda p, xx: layer.apply({"params": p}, xx))(stacked, xs)
    mesh = client_expert_mesh(k, 4)
    sh = shard_params_ep(stacked, mesh, E, client_axis=True)
    out = jax.jit(
        jax.vmap(lambda p, xx: layer.apply({"params": p}, xx))
    )(sh, xs)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=1e-5
    )


def test_moe_transformer_lm_trains_end_to_end():
    # the model-family wiring: TransformerLM(moe_experts=E) routes every
    # block's MLP through the switch layer and still backprops; expert
    # stacks appear under block*/moe and shard with ep_param_specs
    from federated_pytorch_test_tpu.models import TransformerLM

    lm = TransformerLM(vocab=32, dim=16, num_heads=2, max_len=16,
                       moe_experts=E)
    tokens = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
    params = lm.init(jax.random.PRNGKey(0), tokens)["params"]
    assert "moe" in params["block0"] and "w1" in params["block0"]["moe"]

    def loss(p):
        logits = lm.apply({"params": p}, tokens)
        lp = jax.nn.log_softmax(logits.astype(jnp.float32))
        tgt = jnp.roll(tokens, -1, axis=1)
        return -jnp.mean(jnp.take_along_axis(lp, tgt[..., None], axis=-1))

    l, g = jax.jit(jax.value_and_grad(loss))(params)
    assert np.isfinite(float(l))
    gn = np.sqrt(sum(float(np.sum(np.square(x))) for x in jax.tree.leaves(g)))
    assert np.isfinite(gn) and gn > 0
    # expert-parallel shardings apply through the whole model tree
    specs = ep_param_specs(params, E)
    assert tuple(specs["block0"]["moe"]["w1"]) == (EXPERT_AXIS,)
    assert tuple(specs["block0"]["attn"]["qkv"]["kernel"]) == ()
    sh = shard_params_ep(params, expert_mesh(4), E)
    l_sh = jax.jit(loss)(sh)
    np.testing.assert_allclose(float(l_sh), float(l), rtol=1e-6)


def test_moe_aux_loss_reachable_through_transformer_lm():
    # the load-balance term is sown into `intermediates`, so a wrapping
    # model exposes it without any wiring — and including it in the loss
    # backprops into the gate (the documented recipe, models/moe.py)
    from federated_pytorch_test_tpu.models import TransformerLM

    lm = TransformerLM(vocab=32, dim=16, num_heads=2, max_len=16,
                       moe_experts=E)
    tokens = jnp.tile(jnp.arange(8, dtype=jnp.int32)[None], (2, 1))
    params = lm.init(jax.random.PRNGKey(0), tokens)["params"]

    def loss(p):
        logits, mut = lm.apply(
            {"params": p}, tokens, mutable=["intermediates"]
        )
        aux_terms = jax.tree.leaves(mut["intermediates"])
        assert len(aux_terms) == 4  # one per block
        lp = jax.nn.log_softmax(logits.astype(jnp.float32))
        tgt = jnp.roll(tokens, -1, axis=1)
        ce = -jnp.mean(jnp.take_along_axis(lp, tgt[..., None], axis=-1))
        return ce + 0.01 * sum(aux_terms)

    l, g = jax.jit(jax.value_and_grad(loss))(params)
    assert np.isfinite(float(l))
    gate_gn = float(
        jnp.sum(jnp.abs(g["block0"]["moe"]["gate"]["kernel"]))
    )
    assert np.isfinite(gate_gn) and gate_gn > 0


@pytest.mark.smoke
def test_ep_guards():
    layer = _layer()
    params, _ = _init(layer)
    from federated_pytorch_test_tpu.parallel import client_mesh

    with pytest.raises(ValueError, match="no 'experts' axis"):
        shard_params_ep(params, client_mesh(4), E)
    with pytest.raises(ValueError, match="not divisible"):
        shard_params_ep(params, expert_mesh(3), E)
    with pytest.raises(ValueError, match="client_axis=True needs"):
        shard_params_ep(params, expert_mesh(4), E, client_axis=True)


@pytest.mark.smoke
def test_ep_specs_require_a_moe_scope():
    # an unrelated param named w1 with a matching leading axis must NOT be
    # sharded on the experts axis (ADVICE r3): expert leaves are only
    # recognized inside a scope named like 'moe' or alongside a `gate`
    # projection (MoEMLP's own structure)
    from jax.sharding import PartitionSpec as P

    lookalike = {"custom": {"w1": np.zeros((E, 3), np.float32)}}
    specs = ep_param_specs(lookalike, E)
    assert specs["custom"]["w1"] == P()

    # a bare MoEMLP param tree (gate sibling, no enclosing scope) shards
    layer = _layer()
    params, _ = _init(layer)
    bare = ep_param_specs(params, E)
    assert bare["w1"] == P(EXPERT_AXIS)
    assert bare["gate"]["kernel"] == P()

    # and a 'moe'-named scope shards even without the gate visible
    scoped = {"moe": {"w1": np.zeros((E, 3), np.float32)}}
    assert ep_param_specs(scoped, E)["moe"]["w1"] == P(EXPERT_AXIS)
