"""Folded + async eval contract tests (the eval-tail PR).

The tentpole claims, verified in the default (tier-1) tier:

* a fused round with `check_results` carries its evals INSIDE the one
  jitted dispatch: `dispatch_count` reads exactly
  `{round: 1, round_init: 1}` with ZERO standalone eval dispatches —
  the dispatch-budget gate that makes an eval-launch regression fail
  fast;
* the accuracy trajectory — values AND cursors — is bit-identical
  across every eval mode (folded / async-outside / sync-outside), for
  fedavg AND admm incl. a due BB-rho step inside the fused scan;
* the JSONL metric stream is record-for-record identical across eval
  modes (modulo wall-clock fields), deferred records are always
  resolved BEFORE their loop's `nloop_complete` marker, and a chaos
  run crashed+resumed with deferred evals reproduces the uninterrupted
  stream;
* a `fault_mode='rollback'` round discards its evals: the poisoned
  round contributes no `test_accuracy` records, in any eval mode;
* the test sweep is staged once at trainer init: enqueueing an eval
  performs no host<->device transfer at all (jax.transfer_guard).

Smoke tier: the recorder-level `Deferred` mechanics (order-preserving
pending queue, commit-time resolution, discard).
"""

import json

import numpy as np
import pytest

from federated_pytorch_test_tpu.data import synthetic_cifar
from federated_pytorch_test_tpu.engine import Trainer, get_preset
from federated_pytorch_test_tpu.utils import Deferred, MetricsRecorder

smoke = pytest.mark.smoke

SRC = synthetic_cifar(n_train=240, n_test=60)

# the three eval modes of a FUSED run (bench.py's `eval_mode` headline
# values): folded = evals inside the round program (default), async =
# standalone eval program on the round's snapshots with the host fetch
# deferred to the round boundary, sync = same program, blocking fetch at
# the call site (the pre-async behavior, kept as the escape hatch)
MODES = {
    "folded": {},
    "async": dict(fold_eval=False),
    "sync": dict(fold_eval=False, async_eval=False),
}


def tiny(preset="fedavg", **over):
    base = dict(
        batch=40, nloop=2, nadmm=2, max_groups=1, model="net",
        check_results=True, eval_batch=30, synthetic_ok=True,
    )
    base.update(over)
    return get_preset(preset, **base)


# --------------------------------------------------- recorder-level units


@smoke
def test_deferred_records_preserve_order_and_resolve_before_commit():
    class Capture:
        def __init__(self):
            self.events = []

        def record(self, name, rec):
            self.events.append((name, rec["value"]))

        def flush(self):
            pass

        def commit(self, nloop):
            self.events.append(("__commit__", nloop))

        def close(self):
            pass

    rec = MetricsRecorder(verbose=False)
    cap = Capture()
    rec.add_sink(cap)
    rec.log("a", 1)
    rec.log("acc", Deferred(lambda: [0.5]))
    rec.log("b", 3)  # queues BEHIND the pending deferred record
    assert cap.events == [("a", 1)]
    # latest() resolves without disturbing the queue
    assert rec.latest("acc") == [0.5]
    assert [n for n, _ in cap.events] == ["a"]
    # the commit marker may only be written AFTER every pending record
    # is resolved and sunk, in logging order
    rec.commit_loop(0)
    assert cap.events == [("a", 1), ("acc", [0.5]), ("b", 3), ("__commit__", 0)]
    assert rec.series["acc"][0]["value"] == [0.5]
    # to_json materializes (a thunk is not JSON)
    assert json.loads(rec.to_json())["series"]["acc"][0]["value"] == [0.5]


@smoke
def test_discard_pending_drops_queue_and_series():
    rec = MetricsRecorder(verbose=False)
    rec.log("test_accuracy", Deferred(lambda: [1.0]), nloop=0)
    rec.log("other", 7, nloop=0)
    rec.discard_pending("test_accuracy")
    rec.flush()
    assert "test_accuracy" not in rec.series
    assert rec.series["other"][0]["value"] == 7


@smoke
def test_deferred_accuracies_print_at_harvest(capsys):
    rec = MetricsRecorder(verbose=True)
    rec.accuracies(Deferred(lambda: [0.25]), nloop=0, group=0, nadmm=0)
    assert "Accuracy" not in capsys.readouterr().out
    rec.flush()
    assert "Accuracy of client 1" in capsys.readouterr().out
    assert rec.series["test_accuracy"][0]["value"] == [0.25]


# ------------------------------------------------ cross-mode equivalence


@pytest.fixture(scope="module")
def runs(tmp_path_factory):
    """One tiny fused fedavg run per eval mode, metric streams on."""
    out = {}
    for mode, over in MODES.items():
        tmp = tmp_path_factory.mktemp(f"fold_{mode}")
        cfg = tiny(metrics_stream=str(tmp / "m.jsonl"), **over)
        tr = Trainer(cfg, verbose=False, source=SRC)
        tr.run()
        out[mode] = (tr, cfg, tmp / "m.jsonl")
    return out


def test_modes_reach_their_paths(runs):
    tr_f, _, _ = runs["folded"]
    tr_a, _, _ = runs["async"]
    tr_s, cfg_s, _ = runs["sync"]
    assert tr_f._fused_enabled() and tr_f._fold_eval_enabled()
    assert tr_a._fused_enabled() and not tr_a._fold_eval_enabled()
    assert not cfg_s.async_eval and not tr_s._fold_eval_enabled()


def test_folded_round_dispatch_budget(runs):
    """THE dispatch-budget gate: a folded `check_results` round is
    exactly one round program + one init program — no standalone eval
    dispatches, no health checks, nothing else."""
    tr, cfg, _ = runs["folded"]
    recs = tr.recorder.series["dispatch_count"]
    assert len(recs) == cfg.nloop
    for r in recs:
        assert r["value"] == {"round": 1, "round_init": 1, "total": 2}
    # ...while the outside-eval modes dispatch the standalone program
    for mode in ("async", "sync"):
        d = runs[mode][0].recorder.series["dispatch_count"][0]["value"]
        assert d["eval"] == cfg.nadmm, mode


def test_accuracy_trajectory_bit_identical_across_modes(runs):
    series = {}
    for mode, (tr, _, _) in runs.items():
        series[mode] = [
            (r["nloop"], r["group"], r["nadmm"], r["value"])
            for r in tr.recorder.series["test_accuracy"]
        ]
        flats = {m: np.asarray(t.flat) for m, (t, _, _) in runs.items()}
    assert series["folded"] == series["sync"]
    assert series["async"] == series["sync"]
    np.testing.assert_array_equal(flats["folded"], flats["sync"])
    np.testing.assert_array_equal(flats["async"], flats["sync"])


def _normalize_stream(path):
    out = []
    for line in open(path):
        d = json.loads(line)
        d.pop("t", None)  # wall-clock
        d.pop("crc", None)  # per-line checksums differ with content
        if d.get("series") == "step_time":
            d["value"] = {k: v for k, v in d["value"].items() if k != "seconds"}
        out.append(d)
    return out


def test_streams_record_for_record_identical_across_modes(runs):
    streams = {m: _normalize_stream(p) for m, (_, _, p) in runs.items()}
    # the deferred-vs-blocking harvest is INVISIBLE in the stream: async
    # and sync are record-for-record identical, dispatch counts included
    # (both dispatch the standalone eval program). All three modes share
    # the stream tag — fold_eval/async_eval are excluded from the config
    # digest exactly because of this test.
    assert streams["async"] == streams["sync"]
    # the folded stream differs ONLY in the dispatch_count values (fewer
    # programs launched is the headline, and it is recorded honestly)
    def blur_dispatch(recs):
        return [
            {**d, "value": None} if d.get("series") == "dispatch_count" else d
            for d in recs
        ]

    assert blur_dispatch(streams["folded"]) == blur_dispatch(streams["sync"])


def test_deferred_records_land_before_their_commit_marker(runs):
    _, cfg, path = runs["async"]
    seen_markers = []
    for line in open(path):
        d = json.loads(line)
        if d.get("event") == "nloop_complete":
            seen_markers.append(int(d["nloop"]))
        elif d.get("series") == "test_accuracy":
            # a loop's eval records must precede its commit marker: the
            # marker's durability contract covers them
            assert d["nloop"] not in seen_markers
    assert seen_markers == list(range(cfg.nloop))


# slow tier per the PR-9 rule: the admm+BB legs ride the slow tier (two
# extra program compiles, ~17 s) — the tier-1 wall sits at the 870 s
# driver budget; the fedavg fold/sync trajectory legs above stay tier-1
@pytest.mark.slow
def test_admm_bb_trajectory_identical_folded_vs_sync():
    outs = {}
    for mode in ("folded", "sync"):
        cfg = tiny("admm", nloop=1, nadmm=3, bb_update=True, **MODES[mode])
        tr = Trainer(cfg, verbose=False, source=SRC)
        tr.run()
        outs[mode] = (
            np.asarray(tr.flat).copy(),
            [r["value"] for r in tr.recorder.series["test_accuracy"]],
            [r["value"] for r in tr.recorder.series["mean_rho"]],
        )
    np.testing.assert_array_equal(outs["folded"][0], outs["sync"][0])
    assert outs["folded"][1] == outs["sync"][1]
    assert outs["folded"][2] == outs["sync"][2]


def test_compile_round_seeds_folded_program():
    # AOT seeding lowers the FOLDED signature (test sweep included)
    # without executing anything
    cfg = tiny(nloop=1)
    tr = Trainer(cfg, verbose=False, source=SRC)
    assert tr._fold_eval_enabled()
    before = np.asarray(tr.flat).copy()
    tr.compile_round(tr.group_order[0])
    np.testing.assert_array_equal(np.asarray(tr.flat), before)


# ------------------------------------------------------- fault interplay


def test_crash_resume_stream_identical_with_deferred_evals(tmp_path):
    """The PR-3 stream-identity contract, now WITH eval records in the
    stream (check_results on, folded by default): a chaos run killed by
    a planned crash and resumed yields the uninterrupted run's stream."""
    from federated_pytorch_test_tpu.fault import InjectedCrash

    common = dict(save_model=True)
    cfg_a = tiny(
        checkpoint_dir=str(tmp_path / "a"),
        metrics_stream=str(tmp_path / "a.jsonl"),
        fault_plan="seed=13,dropout=0.3",
        **common,
    )
    tr_a = Trainer(cfg_a, verbose=False, source=SRC)
    tr_a.run()

    gid = tr_a.group_order[0]
    cfg_b = tiny(
        checkpoint_dir=str(tmp_path / "b"),
        metrics_stream=str(tmp_path / "b.jsonl"),
        fault_plan=f"seed=13,dropout=0.3,crash=1:{gid}:0",
        **common,
    )
    tr_b = Trainer(cfg_b, verbose=False, source=SRC)
    with pytest.raises(InjectedCrash):
        tr_b.run()
    tr_b2 = Trainer(cfg_b.replace(resume="auto"), verbose=False, source=SRC)
    assert tr_b2._completed_nloops == 1
    tr_b2.run()

    def norm(path):
        recs = _normalize_stream(path)
        for d in recs:
            if d.get("event") == "stream_header":
                d.pop("tag")  # the twins' plans differ by the crash point
        return recs

    assert norm(tmp_path / "a.jsonl") == norm(tmp_path / "b.jsonl")
    acc_a = [r["value"] for r in tr_a.recorder.series["test_accuracy"]]
    acc_b = [r["value"] for r in tr_b2.recorder.series["test_accuracy"]]
    assert acc_a == acc_b


@pytest.mark.parametrize("mode", ["folded", "sync"])
def test_rollback_round_discards_its_evals(mode, tmp_path):
    """A rolled-back round is discarded wholesale — its eval records go
    with it, identically in every eval mode (docs/FAULT.md)."""
    import jax.numpy as jnp

    cfg = tiny(
        nloop=1, fault_mode="rollback",
        metrics_stream=str(tmp_path / f"{mode}.jsonl"),
        **MODES[mode],
    )
    tr = Trainer(cfg, verbose=False, source=SRC)
    tr.flat = tr.flat.at[1].set(jnp.nan)
    entry = np.asarray(tr.flat).copy()
    tr.run_round(nloop=0, gid=tr.group_order[0])
    tr.close()

    np.testing.assert_array_equal(np.asarray(tr.flat), entry)
    kinds = [f["value"]["kind"] for f in tr.recorder.series["fault"]]
    assert kinds[-1] == "round_rollback"
    assert "test_accuracy" not in tr.recorder.series
    lines = [json.loads(l) for l in open(tmp_path / f"{mode}.jsonl")]
    assert not any(l.get("series") == "test_accuracy" for l in lines)
    # ...but the round's OTHER telemetry (losses, residuals) streamed
    assert any(l.get("series") == "train_loss" for l in lines)


def test_warn_mode_keeps_poisoned_round_evals():
    # only ROLLBACK discards: a warn-mode poisoned round records its
    # evals exactly as before (nothing was rolled back)
    import jax.numpy as jnp

    cfg = tiny(nloop=1, fault_mode="warn")
    tr = Trainer(cfg, verbose=False, source=SRC)
    tr.flat = tr.flat.at[1].set(jnp.nan)
    tr.run_round(nloop=0, gid=tr.group_order[0])
    assert len(tr.recorder.series["test_accuracy"]) == cfg.nadmm


# --------------------------------------------------- staging regression


def test_eval_enqueue_performs_no_transfers():
    """The test sweep is device-resident from trainer init: enqueueing
    an eval moves NOTHING between host and device (the old path paid a
    D2H fetch of the mask total per call, and the harvest sync); the
    deferred harvest is the only transfer, and it happens off-guard."""
    import jax

    cfg = tiny(nloop=1, fold_eval=False)
    tr = Trainer(cfg, verbose=False, source=SRC)
    for arr in (tr.test_imgs, tr.test_labels, tr.test_mask):
        assert arr.committed  # staged once, to an explicit sharding
    baseline = tr.evaluate()  # warm: compiles the eval program
    with jax.transfer_guard("disallow"):
        d = tr.evaluate_deferred()
    np.testing.assert_array_equal(d.resolve(), baseline)
