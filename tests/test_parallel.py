"""Mesh + collectives tests on the 8-device virtual CPU mesh.

Every collective must give identical results for any device count D
dividing K (clients per device = K/D) — the property that lets the same
train step run on 1 real chip (K=3, D=1) and a v4-64 (K=64, D=64).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from federated_pytorch_test_tpu.parallel import shard_map
from jax.sharding import PartitionSpec as P

from federated_pytorch_test_tpu.parallel import (
    CLIENT_AXIS,
    all_clients,
    client_mean,
    client_mesh,
    client_sum,
    shard_clients,
    weighted_client_mean,
)

pytestmark = pytest.mark.smoke  # fast CI tier


def _run(mesh, fn, *args):
    sharded = shard_map(
        fn,
        mesh=mesh,
        in_specs=tuple(P(CLIENT_AXIS) for _ in args),
        out_specs=P(),
    )
    return jax.jit(sharded)(*args)


@pytest.mark.parametrize("k,d", [(8, 8), (8, 4), (8, 2), (8, 1), (3, 1), (6, 2)])
def test_client_sum_invariant_to_device_count(k, d):
    mesh = client_mesh(d)
    x = jnp.arange(k * 5, dtype=jnp.float32).reshape(k, 5)
    out = _run(mesh, lambda v: client_sum(v), x)
    np.testing.assert_allclose(out, np.asarray(x).sum(0), rtol=1e-6)


@pytest.mark.parametrize("k,d", [(8, 8), (8, 2), (3, 1)])
def test_client_mean_matches_fedavg_average(k, d):
    mesh = client_mesh(d)
    x = jnp.arange(k * 4, dtype=jnp.float32).reshape(k, 4) * 0.1
    out = _run(mesh, lambda v: client_mean(v), x)
    np.testing.assert_allclose(out, np.asarray(x).mean(0), rtol=1e-6)


@pytest.mark.parametrize("k,d", [(8, 8), (8, 4), (3, 1), (6, 3)])
def test_weighted_client_mean_is_admm_z_update(k, d):
    # z = sum_k (y_k + rho_k x_k) / sum_k rho_k, via v = y/rho + x, w = rho
    # (reference src/consensus_admm_trio.py:502)
    rng = np.random.default_rng(0)
    n = 7
    x = rng.normal(size=(k, n)).astype(np.float32)
    y = rng.normal(size=(k, n)).astype(np.float32)
    rho = rng.uniform(0.1, 1.0, size=(k, 1)).astype(np.float32)

    mesh = client_mesh(d)
    out = _run(
        mesh,
        lambda xv, yv, rv: weighted_client_mean(yv / rv + xv, rv),
        jnp.asarray(x),
        jnp.asarray(y),
        jnp.asarray(rho),
    )
    expect = (y + rho * x).sum(0) / rho.sum(0)
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_all_clients_gathers_in_order():
    mesh = client_mesh(4)
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    sharded = shard_map(
        all_clients, mesh=mesh, in_specs=(P(CLIENT_AXIS),), out_specs=P(CLIENT_AXIS)
    )
    out = jax.jit(sharded)(x)
    # each device's output block is the full gathered [K, 1]; collected
    # along the out spec it reproduces the stacked order per device block
    assert out.shape[0] == 8 * 4 or out.shape[0] == 8
    np.testing.assert_allclose(np.asarray(out)[:8, 0], np.arange(8))


def test_shard_clients_places_leading_axis():
    mesh = client_mesh(8)
    x = jnp.zeros((8, 3))
    sx = shard_clients(x, mesh)
    assert sx.sharding.spec == P(CLIENT_AXIS)


def test_largest_feasible_mesh():
    from federated_pytorch_test_tpu.parallel import largest_feasible_mesh, mesh_size

    assert mesh_size(largest_feasible_mesh(3)) == 3  # 3 | 3 <= 8
    assert mesh_size(largest_feasible_mesh(8)) == 8
    assert mesh_size(largest_feasible_mesh(12)) == 6  # largest divisor <= 8
    assert mesh_size(largest_feasible_mesh(7)) == 7


def test_group_distances_matches_numpy():
    from federated_pytorch_test_tpu.parallel import group_distances
    from federated_pytorch_test_tpu.partition import Partition, Segment

    k, n = 4, 10
    part = Partition(groups=((Segment(0, 6),), (Segment(6, 4),)), total=n)
    rng = np.random.RandomState(0)
    x = rng.randn(k, n).astype(np.float32)

    mesh = client_mesh(2)
    out = _run(mesh, lambda v: group_distances(v, part), jnp.asarray(x))

    center = x.mean(0)
    expected = [
        np.mean([np.linalg.norm((x[c] - center)[s.start : s.start + s.size])
                 for c in range(k)])
        for s in [part.groups[0][0], part.groups[1][0]]
    ]
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_multihost_helpers_single_process():
    # single-process: initialize is a no-op returning 0; the multihost
    # mesh degrades to the plain client mesh over local devices
    import jax

    from federated_pytorch_test_tpu.parallel import (
        initialize_distributed,
        mesh_size,
        multihost_client_mesh,
    )

    assert initialize_distributed() == 0
    m = multihost_client_mesh(8)
    assert mesh_size(m) == min(8, len(jax.devices()))
    m = multihost_client_mesh(6)  # 6 clients on 8 devices -> 6-device mesh
    assert 6 % mesh_size(m) == 0
