"""Data pipeline tests: splits, normalization, lockstep batching."""

import numpy as np
import pytest

from federated_pytorch_test_tpu.data import (
    client_splits,
    client_stats,
    make_federated,
    normalize,
    synthetic_cifar,
)


def test_client_splits_match_reference_thirds():
    # reference src/no_consensus_trio.py:28-30
    assert client_splits(50_000, 3) == ((0, 16666), (16666, 33333), (33333, 50000))


def test_client_splits_cover_disjoint():
    splits = client_splits(1000, 7)
    assert splits[0][0] == 0 and splits[-1][1] == 1000
    for (s0, e0), (s1, e1) in zip(splits, splits[1:]):
        assert e0 == s1


def test_biased_stats_match_reference():
    # reference src/no_consensus_trio.py:34-45
    mean, std = client_stats(3, biased=True)
    np.testing.assert_allclose(mean, [0.5, 0.3, 0.6])
    np.testing.assert_allclose(std, [0.5, 0.4, 0.5])
    mean_u, std_u = client_stats(3, biased=False)
    np.testing.assert_allclose(mean_u, 0.5)
    np.testing.assert_allclose(std_u, 0.5)


def test_normalize_matches_torchvision_formula():
    img = np.arange(2 * 2 * 3, dtype=np.uint8).reshape(1, 2, 2, 3) * 20
    out = np.asarray(normalize(img, 0.3, 0.4))
    np.testing.assert_allclose(out, (img / 255.0 - 0.3) / 0.4, rtol=1e-6)


def test_normalize_per_client_stats_broadcast_on_client_axis():
    # K == C == 3: [K] stats must hit the leading client axis, never the
    # trailing channel axis
    img = np.full((3, 2, 4, 4, 3), 128, np.uint8)  # [K,B,H,W,C] uniform gray
    mean, std = client_stats(3, biased=True)
    out = np.asarray(normalize(img, mean, std))
    x = 128 / 255.0
    for k, (m, s) in enumerate(zip(mean, std)):
        np.testing.assert_allclose(out[k], (x - m) / s, rtol=1e-5)


@pytest.fixture(scope="module")
def fed():
    src = synthetic_cifar(n_train=600, n_test=100, num_classes=10, seed=0)
    return make_federated(src, n_clients=3, biased=True)


def test_federated_shapes(fed):
    assert fed.train_images.shape == (3, 200, 32, 32, 3)
    assert fed.train_images.dtype == np.uint8
    assert fed.test_images.shape == (100, 32, 32, 3)


def test_shards_disjoint(fed):
    # contiguous split of a deterministic source: shard contents differ
    assert not np.array_equal(fed.train_images[0], fed.train_images[1])


def test_epoch_lockstep_batches(fed):
    batches = list(fed.epoch(batch=64, seed=1))
    assert len(batches) == 200 // 64
    imgs, labels = batches[0]
    assert imgs.shape == (3, 64, 32, 32, 3)
    assert labels.shape == (3, 64)
    assert labels.dtype == np.int32


def test_epoch_reshuffles_and_is_deterministic(fed):
    a = list(fed.epoch(batch=64, seed=1))
    b = list(fed.epoch(batch=64, seed=1))
    c = list(fed.epoch(batch=64, seed=2))
    np.testing.assert_array_equal(a[0][1], b[0][1])
    assert not np.array_equal(a[0][1], c[0][1])


def test_epoch_samples_only_own_shard(fed):
    # every emitted image of client k must come from shard k
    shard0 = fed.train_images[0].reshape(200, -1)
    for imgs, _ in fed.epoch(batch=64, seed=3):
        emitted = imgs[0].reshape(64, -1)
        # membership via row-hash
        h_shard = {r.tobytes() for r in shard0}
        assert all(r.tobytes() in h_shard for r in emitted)
        break


def test_test_batches_pad_and_mask(fed):
    batches = list(fed.test_batches(batch=64))
    assert len(batches) == 2
    imgs, labels, mask = batches[-1]
    assert imgs.shape == (64, 32, 32, 3)
    assert mask.sum() == 100 - 64
    total = sum(m.sum() for _, _, m in batches)
    assert total == 100


def test_bin_format_roundtrip(tmp_path):
    # write a tiny cifar-10-batches-bin layout and read it back
    import os

    d = tmp_path / "cifar-10-batches-bin"
    d.mkdir()
    rng = np.random.default_rng(0)
    n = 4
    img = rng.integers(0, 256, size=(n, 3, 32, 32), dtype=np.uint8)
    lab = rng.integers(0, 10, size=(n, 1), dtype=np.uint8)
    rec = np.concatenate([lab, img.reshape(n, -1)], axis=1)
    for i in range(1, 6):
        rec.tofile(os.fspath(d / f"data_batch_{i}.bin"))
    rec.tofile(os.fspath(d / "test_batch.bin"))

    from federated_pytorch_test_tpu.data import load_cifar10

    src = load_cifar10(os.fspath(tmp_path))
    assert src.train_images.shape == (5 * n, 32, 32, 3)
    np.testing.assert_array_equal(src.test_labels, lab[:, 0])
    # HWC conversion: plane-major bytes -> channel-last pixels
    np.testing.assert_array_equal(
        src.test_images[0, :, :, 0], img[0, 0]
    )


def test_missing_root_falls_back_to_synthetic(tmp_path):
    import warnings as w

    from federated_pytorch_test_tpu.data import load_cifar

    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        src = load_cifar("cifar10", root=str(tmp_path / "nope"))
    assert src.name == "synthetic"
    assert any("synthetic" in str(x.message) for x in rec)


def test_synthetic_learnable_separation():
    # class prototypes should make a nearest-centroid rule beat chance easily
    src = synthetic_cifar(n_train=2000, n_test=500, num_classes=10, seed=0)
    x = src.train_images.reshape(2000, -1).astype(np.float32)
    cents = np.stack(
        [x[src.train_labels == c].mean(0) for c in range(10)]
    )
    xt = src.test_images.reshape(500, -1).astype(np.float32)
    pred = np.argmin(
        ((xt[:, None] - cents[None]) ** 2).sum(-1), axis=1
    )
    acc = (pred == src.test_labels).mean()
    assert acc > 0.5


def test_synthetic_hardness_knobs():
    # the discriminating-oracle knobs (benchmarks/convergence_parity.py):
    # label_noise flips ~that fraction of labels deterministically, and
    # overlap blends neighbouring class prototypes
    from federated_pytorch_test_tpu.data import synthetic_cifar

    easy = synthetic_cifar(n_train=2000, n_test=10, seed=0)
    hard = synthetic_cifar(
        n_train=2000, n_test=10, seed=0, overlap=0.35, label_noise=0.25
    )
    # determinism
    again = synthetic_cifar(
        n_train=2000, n_test=10, seed=0, overlap=0.35, label_noise=0.25
    )
    np.testing.assert_array_equal(hard.train_images, again.train_images)
    np.testing.assert_array_equal(hard.train_labels, again.train_labels)
    # flipped fraction ~ label_noise (images drawn identically => same
    # underlying class stream; only the labels move)
    flipped = float(np.mean(hard.train_labels != easy.train_labels))
    assert 0.18 <= flipped <= 0.32, flipped
    # overlap pulls neighbouring prototypes together: the mean distance
    # between adjacent class prototypes must shrink
    def proto_gap(srcx):
        # per-class mean image approximates the prototype
        protos = np.stack([
            srcx.train_images[srcx.train_labels == c].mean(axis=0)
            for c in range(10)
        ])
        return float(np.mean(np.abs(protos - np.roll(protos, 1, axis=0))))

    assert proto_gap(hard) < proto_gap(easy)
