"""Worker for the REAL multi-process test (tests/test_multiprocess.py).

Each OS process claims 4 virtual CPU devices and joins a 2-process JAX
distributed runtime: 8 global devices, one `clients` mesh spanning BOTH
processes. The FedAvg round then exercises the cross-process paths the
in-process suite cannot: `_put` via `make_array_from_callback` (each
process supplies its own client shards), the consensus `psum` across the
process boundary, and `_fetch` via `process_allgather`.

Invoked as:
    python tests/multiprocess_worker.py <process_id> <num_processes> <port>

Prints one line `RESULT <json>` with round metrics; the parent asserts
both processes agree and match the single-process run bit-for-bit.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    )
    from federated_pytorch_test_tpu.utils import force_host_cpu

    jax = force_host_cpu()
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
        cluster_detection_method="deactivate",
    )
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.devices()) == 4 * nproc

    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset
    from federated_pytorch_test_tpu.parallel import multihost_client_mesh

    k = 4 * nproc
    src = synthetic_cifar(n_train=8 * k, n_test=2 * k)
    cfg = get_preset(
        "fedavg", model="net", n_clients=k, batch=4, nloop=1, nadmm=1,
        check_results=False,
    )
    mesh = multihost_client_mesh(k)
    tr = Trainer(cfg, verbose=False, source=src, mesh=mesh)
    gid = tr.group_order[0]
    tr.run_round(nloop=0, gid=gid)

    flat = tr._fetch(tr.flat)
    accs = tr.evaluate()
    # the active group's coords must agree across ALL K clients (the
    # consensus broadcast crossed the process boundary)
    sync_err = 0.0
    for seg in tr.partition.groups[gid]:
        blk = flat[:, seg.start : seg.start + seg.size]
        sync_err = max(sync_err, float(np.abs(blk - blk[:1]).max()))

    out = {
        "process": pid,
        "gid": int(gid),
        "sync_err": sync_err,
        "flat_sum": float(np.float64(flat.sum())),
        "accs": [float(a) for a in accs],
        "dual": float(tr.recorder.latest("dual_residual")),
    }
    print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
