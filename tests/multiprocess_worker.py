"""Worker for the REAL multi-process test (tests/test_multiprocess.py).

Each OS process claims 4 virtual CPU devices and joins a 2-process JAX
distributed runtime: 8 global devices, one `clients` mesh spanning BOTH
processes. The FedAvg round then exercises the cross-process paths the
in-process suite cannot: `_put` via `make_array_from_callback` (each
process supplies its own client shards), the consensus `psum` across the
process boundary, and `_fetch` via `process_allgather`.

Invoked as:
    python tests/multiprocess_worker.py <process_id> <num_processes> <port> \
        [devices_per_process=4]

Prints one line `RESULT <json>` with round metrics; the parent asserts
all processes agree and match the single-process run bit-for-bit.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    ndev = int(sys.argv[4]) if len(sys.argv) > 4 else 4
    mode = sys.argv[5] if len(sys.argv) > 5 else "resident"

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={ndev}"
    )
    from federated_pytorch_test_tpu.utils import force_host_cpu

    jax = force_host_cpu()
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
        cluster_detection_method="deactivate",
    )
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.devices()) == ndev * nproc

    import numpy as np

    from federated_pytorch_test_tpu.data import synthetic_cifar
    from federated_pytorch_test_tpu.engine import Trainer, get_preset
    from federated_pytorch_test_tpu.parallel import multihost_client_mesh

    # record whether the DCN-aware hybrid layout path actually built the
    # mesh (multihost.py routes through mesh_utils when >1 island)
    from jax.experimental import mesh_utils

    hybrid_calls = []
    _orig_hybrid = mesh_utils.create_hybrid_device_mesh

    def _recording_hybrid(*args, **kwargs):
        # record AFTER success: multihost.py catches a raising hybrid
        # call and falls back to plain device order — that fallback must
        # not read as "the hybrid path built this mesh"
        result = _orig_hybrid(*args, **kwargs)
        hybrid_calls.append(kwargs.get("dcn_mesh_shape"))
        return result

    mesh_utils.create_hybrid_device_mesh = _recording_hybrid

    k = ndev * nproc
    src = synthetic_cifar(n_train=8 * k, n_test=2 * k)
    over = {}
    if mode == "stream":
        # host-sharded streaming: every process batches only its own
        # clients (engine/trainer.py assemble + _local_clients)
        over = dict(hbm_data_budget_mb=0, stream_chunk_steps=1)
    cfg = get_preset(
        "fedavg", model="net", n_clients=k, batch=4, nloop=1, nadmm=1,
        check_results=False, **over,
    )
    mesh = multihost_client_mesh(k)
    tr = Trainer(cfg, verbose=False, source=src, mesh=mesh)
    if mode == "stream":
        assert tr._stream, "streaming mode did not engage"
        assert len(tr._batchers) == ndev, (
            "each process must batch ONLY its local clients",
            sorted(tr._batchers),
        )
    gid = tr.group_order[0]
    tr.run_round(nloop=0, gid=gid)

    flat = tr._fetch(tr.flat)
    accs = tr.evaluate()
    # the active group's coords must agree across ALL K clients (the
    # consensus broadcast crossed the process boundary)
    sync_err = 0.0
    for seg in tr.partition.groups[gid]:
        blk = flat[:, seg.start : seg.start + seg.size]
        sync_err = max(sync_err, float(np.abs(blk - blk[:1]).max()))

    out = {
        "process": pid,
        "gid": int(gid),
        "sync_err": sync_err,
        "flat_sum": float(np.float64(flat.sum())),
        "accs": [float(a) for a in accs],
        "dual": float(tr.recorder.latest("dual_residual")),
        "hybrid_dcn_shapes": hybrid_calls,
    }
    print("RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
