#!/usr/bin/env bash
# CI gate, tiered (markers declared in pyproject.toml):
#
#   tier 0  pytest -m smoke        — <2 min on the virtual 8-device CPU
#                                    mesh: kernels, consensus math,
#                                    collectives, fault-plan purity,
#                                    obs units (JSONL sink truncation,
#                                    comm-ledger arithmetic, trace JSON,
#                                    deferred-record queue mechanics)
#   tier 1  pytest -m 'not slow'   — the DEFAULT budgeted gate (the
#                                    driver's verify command): smoke plus
#                                    the middle tier (partition, models,
#                                    trainer-level chaos, fused-round
#                                    bit-identity, crash/resume metric-
#                                    stream continuity, dispatch/trace
#                                    integration — tests/test_obs.py —
#                                    and the eval-tail contracts: the
#                                    folded-round dispatch-budget gate
#                                    (dispatch_count == {round:1,
#                                    round_init:1}), cross-eval-mode
#                                    stream identity, rollback eval
#                                    discard — tests/test_fold_eval.py),
#                                    ~7 min
#   tier 2  pytest -m slow         — full integration (~20+ min): engine
#                                    sweeps, resnet-engine runs,
#                                    streaming-equivalence, Pallas
#                                    interpret kernels, ring, 2- and
#                                    4-process distributed runs
#
# Usage:
#   scripts/ci.sh            # tier 1 then tier 2 (both tiers, full CI)
#   CI_TIER=1 scripts/ci.sh  # tier 1 only (the under-budget default gate)
#   CI_TIER=0 scripts/ci.sh  # smoke only (the old fast gate)
#   CI_TIER=2 scripts/ci.sh  # slow tier only
#
# tests/conftest.py forces the CPU platform and 8 virtual devices, so no
# TPU is needed; the persistent compile cache amortizes repeat runs.
set -euo pipefail
cd "$(dirname "$0")/.."

tier="${CI_TIER:-all}"
case "$tier" in
  0) python -m pytest tests/ -m smoke -q "$@" ;;
  1) python -m pytest tests/ -m 'not slow' -q "$@" ;;
  2) python -m pytest tests/ -m slow -q "$@" ;;
  all)
    python -m pytest tests/ -m 'not slow' -q "$@"
    python -m pytest tests/ -m slow -q "$@"
    ;;
  *) echo "unknown CI_TIER='$tier' (want 0, 1, 2 or all)" >&2; exit 2 ;;
esac
