#!/usr/bin/env bash
# CI gate, tiered (markers declared in pyproject.toml):
#
#   tier 0  pytest -m smoke        — <2 min on the virtual 8-device CPU
#                                    mesh: kernels, consensus math,
#                                    collectives, fault-plan purity,
#                                    obs units (JSONL sink truncation,
#                                    comm-ledger arithmetic, trace JSON,
#                                    deferred-record queue mechanics)
#   tier 1  pytest -m 'not slow'   — the DEFAULT budgeted gate (the
#                                    driver's verify command): smoke plus
#                                    the middle tier (partition, models,
#                                    trainer-level chaos, fused-round
#                                    bit-identity, crash/resume metric-
#                                    stream continuity, dispatch/trace
#                                    integration — tests/test_obs.py —
#                                    and the eval-tail contracts: the
#                                    folded-round dispatch-budget gate
#                                    (dispatch_count == {round:1,
#                                    round_init:1}), cross-eval-mode
#                                    stream identity, rollback eval
#                                    discard — tests/test_fold_eval.py),
#                                    ~7 min
#   tier 2  pytest -m slow         — full integration (~20+ min): engine
#                                    sweeps, resnet-engine runs,
#                                    streaming-equivalence, Pallas
#                                    interpret kernels, ring, 2- and
#                                    4-process distributed runs, the
#                                    heavy heterogeneity contracts
#                                    (tests/test_hetero.py slow tier:
#                                    admm/BB uniform-budget bitwise,
#                                    ragged + corruption + trimmed +
#                                    quarantine composition, crash/
#                                    resume stream identity with
#                                    deadline records), plus the CLI
#                                    smokes below: chaos_smoke
#                                    (corruption plan + trimmed combiner
#                                    + quarantine + planned crash,
#                                    recovered end to end with --resume
#                                    auto) and hetero_smoke (speed-
#                                    heterogeneous plan + round deadline
#                                    + trimmed combiner + planned crash,
#                                    recovered via rerun, crashed+resumed
#                                    stream identical to the
#                                    uninterrupted twin's), bf16_smoke
#                                    (bf16 exchange codec + trimmed
#                                    combiner + corruption + quarantine
#                                    + planned crash recovered via
#                                    rerun, halved comm ledger asserted
#                                    on the stream) and
#                                    cohort_smoke (10k virtual clients,
#                                    C=8 cohorts, dropout+corruption
#                                    keyed by virtual id, trimmed
#                                    combiner, planned crash recovered
#                                    via rerun — store manifest + stream
#                                    + cohort sequence all splice, twin
#                                    stream-identity asserted)
#
# Usage:
#   scripts/ci.sh            # tier 1 then tier 2 (both tiers, full CI)
#   CI_TIER=1 scripts/ci.sh  # tier 1 only (the under-budget default gate)
#   CI_TIER=0 scripts/ci.sh  # smoke only (the old fast gate)
#   CI_TIER=2 scripts/ci.sh  # slow tier only
#
# tests/conftest.py forces the CPU platform and 8 virtual devices, so no
# TPU is needed; the persistent compile cache amortizes repeat runs.
set -euo pipefail
cd "$(dirname "$0")/.."

assert_stream_identity() {
  # THE twin-compare normalizer, shared by every smoke that proves
  # crashed+resumed stream identity: records equal modulo wall-clock
  # fields ("t", step_time seconds) and the header tag (the twins'
  # plans legitimately differ by the crash point). $1/$2: the two JSONL
  # streams; $3: extra python asserts evaluated with the normalized
  # record list bound as `recs`.
  python - "$1" "$2" "${3:-}" <<'PY'
import json, sys

def norm(path):
    out = []
    for line in open(path):
        d = json.loads(line)
        d.pop("t", None)
        if d.get("event") == "stream_header":
            d.pop("tag", None)
        if d.get("series") == "step_time":
            d["value"] = {k: v for k, v in d["value"].items() if k != "seconds"}
        out.append(d)
    return out

a, b = norm(sys.argv[1]), norm(sys.argv[2])
assert a == b, f"streams differ: {len(a)} vs {len(b)} records"
if sys.argv[3]:
    exec(sys.argv[3], {"recs": a})
PY
}

chaos_smoke() {
  # End-to-end Byzantine chaos through the REAL CLI: one client per round
  # sends a 10x-scaled update, trimmed-mean(1) + auto-quarantine defend,
  # and a planned crash at (nloop=1, gid=2, nadmm=0) kills the first run
  # mid-experiment (gid 2 is model net's first train_order group). The
  # recovery procedure is rerunning the IDENTICAL command: --resume auto
  # restores the checkpoint, the metric stream splices, and the run
  # finishes with zero rollback rounds.
  local d; d="$(mktemp -d)"
  local cmd=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 240 --synthetic-n-test 60 --batch 40
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30
    --fault-plan "seed=5,corrupt=1:scale:10,crash=1:2:0"
    --robust-agg trimmed --robust-f 1 --quarantine-z 1.0
    --fault-mode rollback --save-model --resume auto
    --checkpoint-dir "$d/ckpt" --metrics-stream "$d/run.jsonl")
  echo "chaos smoke: expecting the planned crash..."
  if "${cmd[@]}" > "$d/run1.log" 2>&1; then
    echo "chaos smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/run1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "chaos smoke: resuming..."
  "${cmd[@]}" > "$d/run2.log" 2>&1 || {
    echo "chaos smoke FAILED: resume did not finish" >&2
    tail -20 "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  # 2 nloops x 1 group x 2 exchanges, one corrupted client each = 4
  grep -q '# faults injected: .*corruptions=4' "$d/run2.log" || {
    echo "chaos smoke FAILED: missing/incorrect injected-faults line" >&2
    grep '# faults' "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  if grep -q 'round_rollback' "$d/run.jsonl"; then
    echo "chaos smoke FAILED: the robust combiner let a round roll back" >&2
    rm -rf "$d"; return 1
  fi
  echo "chaos smoke OK"
  rm -rf "$d"
}

hetero_smoke() {
  # End-to-end deadline rounds through the REAL CLI: one 3x slow client
  # per round (speed axis), a round deadline at the nominal full-work
  # time (4 lockstep steps at batch 20: the slow client's budget is 1 —
  # a PARTIAL contribution every exchange), the trimmed combiner riding
  # along, and a planned crash at (nloop=1, gid=2, nadmm=0) killing the
  # first run. Recovery is rerunning the IDENTICAL command; an
  # uninterrupted twin (same plan minus the crash point) then proves
  # crashed+resumed stream identity — client_time/step_budget/
  # deadline_miss records included — modulo wall-clock fields and the
  # header tag the twins legitimately differ in.
  local d; d="$(mktemp -d)"
  local common=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 240 --synthetic-n-test 60 --batch 20
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30
    --round-deadline 4 --robust-agg trimmed --robust-f 1
    --fault-mode rollback --save-model --resume auto)
  local cmd=("${common[@]}"
    --fault-plan "seed=6,slow=1:3,crash=1:2:0"
    --checkpoint-dir "$d/ckpt" --metrics-stream "$d/run.jsonl")
  local twin=("${common[@]}"
    --fault-plan "seed=6,slow=1:3"
    --checkpoint-dir "$d/ckpt_twin" --metrics-stream "$d/twin.jsonl")
  echo "hetero smoke: expecting the planned crash..."
  if "${cmd[@]}" > "$d/run1.log" 2>&1; then
    echo "hetero smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/run1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "hetero smoke: resuming..."
  "${cmd[@]}" > "$d/run2.log" 2>&1 || {
    echo "hetero smoke FAILED: resume did not finish" >&2
    tail -20 "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  "${twin[@]}" > "$d/twin.log" 2>&1 || {
    echo "hetero smoke FAILED: the uninterrupted twin did not finish" >&2
    tail -20 "$d/twin.log" >&2; rm -rf "$d"; return 1
  }
  # 2 nloops x 1 group x 2 exchanges, the one slow client misses each
  grep -q '# faults injected: .*deadline_misses=4' "$d/run2.log" || {
    echo "hetero smoke FAILED: missing/incorrect deadline scoreboard" >&2
    grep '# faults' "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  if grep -q 'round_rollback' "$d/run.jsonl"; then
    echo "hetero smoke FAILED: partial updates tripped a rollback" >&2
    rm -rf "$d"; return 1
  fi
  assert_stream_identity "$d/run.jsonl" "$d/twin.jsonl" '
assert any(d.get("series") == "deadline_miss" for d in recs)
assert any(d.get("series") == "client_time" for d in recs)
' || {
    echo "hetero smoke FAILED: crashed+resumed stream differs from twin" >&2
    rm -rf "$d"; return 1
  }
  echo "hetero smoke OK"
  rm -rf "$d"
}

bf16_smoke() {
  # End-to-end bf16 exchange codec through the REAL CLI (exchange/,
  # docs/PERF.md): every consensus exchange ships the group slice as
  # bfloat16 (half the uplink bytes on the ledger), one client per round
  # sends a 10x-scaled update, trimmed-mean(1) + auto-quarantine defend
  # ON THE DECODED f32 VIEWS, and a planned crash at (nloop=1, gid=2,
  # nadmm=0) kills the first run. Recovery is rerunning the IDENTICAL
  # command; an uninterrupted twin (same plan minus the crash) then
  # proves crashed+resumed stream identity under the codec — comm_bytes
  # records included (exactly half the f32 ledger, asserted below) —
  # with zero rollbacks and the quarantine still firing.
  local d; d="$(mktemp -d)"
  local common=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 240 --synthetic-n-test 60 --batch 40
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30
    --exchange-dtype bfloat16
    --robust-agg trimmed --robust-f 1 --quarantine-z 1.0
    --fault-mode rollback --save-model --resume auto)
  local cmd=("${common[@]}"
    --fault-plan "seed=5,corrupt=1:scale:10,crash=1:2:0"
    --checkpoint-dir "$d/ckpt" --metrics-stream "$d/run.jsonl")
  local twin=("${common[@]}"
    --fault-plan "seed=5,corrupt=1:scale:10"
    --checkpoint-dir "$d/ckpt_twin" --metrics-stream "$d/twin.jsonl")
  echo "bf16 smoke: expecting the planned crash..."
  if "${cmd[@]}" > "$d/run1.log" 2>&1; then
    echo "bf16 smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/run1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "bf16 smoke: resuming..."
  "${cmd[@]}" > "$d/run2.log" 2>&1 || {
    echo "bf16 smoke FAILED: resume did not finish" >&2
    tail -20 "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  "${twin[@]}" > "$d/twin.log" 2>&1 || {
    echo "bf16 smoke FAILED: the uninterrupted twin did not finish" >&2
    tail -20 "$d/twin.log" >&2; rm -rf "$d"; return 1
  }
  if grep -q 'round_rollback' "$d/run.jsonl"; then
    echo "bf16 smoke FAILED: the codec broke the robust combiner (rollback)" >&2
    rm -rf "$d"; return 1
  fi
  assert_stream_identity "$d/run.jsonl" "$d/twin.jsonl" '
comm = [d for d in recs if d.get("series") == "comm_bytes"]
assert comm, "no comm_bytes records"
summ = [d for d in recs if d.get("series") == "comm_summary"][-1]["value"]
assert summ["exchange_dtype"] == "bfloat16", summ
assert summ["wire_bytes_per_value"] == 2, summ
# half the f32 ledger exactly: per-survivor wire bytes are constant
# across exchanges (one group) and 2 bytes/value — i.e. exactly half the
# 4-byte parameter width (the exact hand-check vs masks lives in
# tests/test_exchange.py; here the stream must be self-consistent)
per = {d["value"] // d["survivors"] for d in comm if d["survivors"]}
assert len(per) == 1, per
assert summ["bytes_total"] == sum(d["value"] for d in comm), summ
assert any(d.get("series") == "quarantine" for d in recs), (
    "quarantine never fired under the codec")
' || {
    echo "bf16 smoke FAILED: crashed+resumed stream differs from twin" >&2
    rm -rf "$d"; return 1
  }
  echo "bf16 smoke OK"
  rm -rf "$d"
}

cohort_smoke() {
  # End-to-end cross-device scale through the REAL CLI (clients/,
  # docs/SCALE.md): 10k virtual clients mapped onto 8 data shards, a
  # C=8 cohort per outer loop, a dropout+corruption plan keyed by
  # VIRTUAL client id, the trimmed combiner, and a planned crash at
  # (nloop=1, gid=2, nadmm=0) killing the first run after loop 0's
  # store scatter + dirty-chunk checkpoint. Recovery is rerunning the
  # IDENTICAL command (--resume auto restores the checkpoint AND the
  # store manifest, and the pure cohort sampler re-derives every
  # historical cohort); an uninterrupted twin (same plan minus the
  # crash) then proves crashed+resumed stream identity — cohort
  # membership records included. Small-N fast variants of the same
  # contracts run in tier 1 (tests/test_clients.py).
  local d; d="$(mktemp -d)"
  local common=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 320 --synthetic-n-test 60 --batch 20
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30
    --virtual-clients 10000 --cohort 8 --data-shards 8 --cohort-seed 11
    --store-chunk-clients 8
    --robust-agg trimmed --robust-f 1
    --save-model --resume auto)
  local cmd=("${common[@]}"
    --fault-plan "seed=7,dropout=0.2,corrupt=0.05:scale:10,crash=1:2:0"
    --checkpoint-dir "$d/ckpt" --metrics-stream "$d/run.jsonl")
  local twin=("${common[@]}"
    --fault-plan "seed=7,dropout=0.2,corrupt=0.05:scale:10"
    --checkpoint-dir "$d/ckpt_twin" --metrics-stream "$d/twin.jsonl")
  echo "cohort smoke: expecting the planned crash..."
  if "${cmd[@]}" > "$d/run1.log" 2>&1; then
    echo "cohort smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/run1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "cohort smoke: resuming..."
  "${cmd[@]}" > "$d/run2.log" 2>&1 || {
    echo "cohort smoke FAILED: resume did not finish" >&2
    tail -20 "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  "${twin[@]}" > "$d/twin.log" 2>&1 || {
    echo "cohort smoke FAILED: the uninterrupted twin did not finish" >&2
    tail -20 "$d/twin.log" >&2; rm -rf "$d"; return 1
  }
  grep -q '# cohort: 8 of 10000 virtual clients' "$d/run2.log" || {
    echo "cohort smoke FAILED: missing/incorrect cohort summary line" >&2
    grep '# cohort' "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  assert_stream_identity "$d/run.jsonl" "$d/twin.jsonl" '
cohorts = [d for d in recs if d.get("series") == "cohort"]
assert len(cohorts) == 2, cohorts
assert all(len(d["value"]["clients"]) == 8 for d in cohorts)
assert any(d.get("series") == "cohort_participation" for d in recs)
' || {
    echo "cohort smoke FAILED: crashed+resumed stream differs from twin" >&2
    rm -rf "$d"; return 1
  }
  echo "cohort smoke OK"
  rm -rf "$d"
}

tier="${CI_TIER:-all}"
case "$tier" in
  0) python -m pytest tests/ -m smoke -q "$@" ;;
  1) python -m pytest tests/ -m 'not slow' -q "$@" ;;
  2)
    python -m pytest tests/ -m slow -q "$@"
    chaos_smoke
    hetero_smoke
    bf16_smoke
    cohort_smoke
    ;;
  all)
    python -m pytest tests/ -m 'not slow' -q "$@"
    python -m pytest tests/ -m slow -q "$@"
    chaos_smoke
    hetero_smoke
    bf16_smoke
    cohort_smoke
    ;;
  *) echo "unknown CI_TIER='$tier' (want 0, 1, 2 or all)" >&2; exit 2 ;;
esac
