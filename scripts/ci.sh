#!/usr/bin/env bash
# CI gate, tiered (markers declared in pyproject.toml):
#
#   tier 0  pytest -m smoke        — <2 min on the virtual 8-device CPU
#                                    mesh: kernels, consensus math,
#                                    collectives, fault-plan purity,
#                                    obs units (JSONL sink truncation,
#                                    comm-ledger arithmetic, trace JSON,
#                                    deferred-record queue mechanics)
#   tier 1  pytest -m 'not slow'   — the DEFAULT budgeted gate (the
#                                    driver's verify command): smoke plus
#                                    the middle tier (partition, models,
#                                    trainer-level chaos, fused-round
#                                    bit-identity, crash/resume metric-
#                                    stream continuity, dispatch/trace
#                                    integration — tests/test_obs.py —
#                                    and the eval-tail contracts: the
#                                    folded-round dispatch-budget gate
#                                    (dispatch_count == {round:1,
#                                    round_init:1}), cross-eval-mode
#                                    stream identity, rollback eval
#                                    discard — tests/test_fold_eval.py),
#                                    ~7 min
#   tier 2  pytest -m slow         — full integration (~20+ min): engine
#                                    sweeps, resnet-engine runs,
#                                    streaming-equivalence, Pallas
#                                    interpret kernels, ring, 2- and
#                                    4-process distributed runs, the
#                                    heavy heterogeneity contracts
#                                    (tests/test_hetero.py slow tier:
#                                    admm/BB uniform-budget bitwise,
#                                    ragged + corruption + trimmed +
#                                    quarantine composition, crash/
#                                    resume stream identity with
#                                    deadline records), plus the CLI
#                                    smokes below: byzantine_smoke
#                                    (corruption plan + trimmed combiner
#                                    + quarantine + planned crash,
#                                    recovered end to end with --resume
#                                    auto) and hetero_smoke (speed-
#                                    heterogeneous plan + round deadline
#                                    + trimmed combiner + planned crash,
#                                    recovered via rerun, crashed+resumed
#                                    stream identical to the
#                                    uninterrupted twin's), bf16_smoke
#                                    (bf16 exchange codec + trimmed
#                                    combiner + corruption + quarantine
#                                    + planned crash recovered via
#                                    rerun, halved comm ledger asserted
#                                    on the stream), codec_smoke (the
#                                    codec-zoo frontier probe: identity/
#                                    topk(0.1)+EF+adaptive/q8 sweep over
#                                    one corruption+dropout plan, topk
#                                    crashed + resumed with twin stream
#                                    identity incl. group_schedule
#                                    records, `report` gating the
#                                    <=25%-bytes / within-2-points
#                                    frontier acceptance) and
#                                    cohort_smoke (10k virtual clients,
#                                    C=8 cohorts, dropout+corruption
#                                    keyed by virtual id, trimmed
#                                    combiner, planned crash recovered
#                                    via rerun — store manifest + stream
#                                    + cohort sequence all splice, twin
#                                    stream-identity asserted),
#                                    spill_smoke (the million-client
#                                    shape: N=1M lazy virtual clients,
#                                    --store-resident-chunks pinned to 2
#                                    so evictions/spills fire, planned
#                                    crash recovered via rerun with twin
#                                    stream identity, and the bounded-
#                                    RSS gate — sidecar peak RSS at 1M
#                                    within 1.25x of the 10k run's),
#                                    fleet_smoke (the closed loop at 10k
#                                    virtual clients: churn + speed +
#                                    corruption plan, --round-deadline
#                                    auto, telemetry-weighted cohorts,
#                                    planned crash recovered via rerun
#                                    with twin stream-compare over the
#                                    deadline/availability/cohort_weight
#                                    records) and
#                                    report_smoke (f32-vs-bf16 codec
#                                    sweep through the `report` CLI:
#                                    convergence-vs-bytes frontier with
#                                    exactly-halved bf16 uplink, and the
#                                    crashed+resumed sweep's report
#                                    byte-identical to the twin's) and
#                                    incident_smoke (flight recorder
#                                    through the real CLI: corruption
#                                    plan -> health fires -> incident
#                                    bundle written + schema-validated
#                                    + in-bundle series == stream tail,
#                                    real anomaly-armed jax.profiler
#                                    capture, `report --incidents`
#                                    table, `watch --once` renders) and
#                                    integrity_smoke (storage chaos at
#                                    100k clients: bitrot plan + planned
#                                    crash recovered via rerun with twin
#                                    stream identity, transient-ioerror
#                                    write plan survived via retry,
#                                    scrub detect-then-repair, nonzero
#                                    storage_faults= scoreboard) and
#                                    widened_smoke (the widened client
#                                    GEMM — docs/PERF.md §Widened GEMM:
#                                    --client-fold gemm with a P=4 probe
#                                    fan under dropout+corruption +
#                                    trimmed(1) + topk codec, planned
#                                    crash recovered via rerun with twin
#                                    stream identity and the per-round
#                                    {round: 1} dispatch budget held on
#                                    the stream, then a --client-fold
#                                    vmap rerun whose stream matches the
#                                    gemm twin's bitwise modulo the
#                                    fold-mode tag — the documented
#                                    CPU tolerance) and trend_smoke
#                                    (the provenance+trend layer —
#                                    obs/benchdb.py: two probe-gated
#                                    bench runs wrapped as BENCH_*.json,
#                                    trend report byte-identical on
#                                    re-ingest, a synthetic 2x slowdown
#                                    flagged by the regression sentinel
#                                    while the twin-noise rerun passes,
#                                    and the CPU-twin runs leaving every
#                                    backend==tpu DEBT.json entry open —
#                                    the class-isolation rule end to end)
#                                    and chaos_smoke (the chaos HARNESS
#                                    — fault/chaos.py: a fixed-seed
#                                    soak of composed fuzzer-drawn
#                                    plans must clear the invariant
#                                    oracle clean, then a deliberately
#                                    broken robust combiner
#                                    (CHAOS_PLANT_BUG=combiner) must be
#                                    CAUGHT, SHRUNK to a <=2-axis repro
#                                    bundle, and REPLAYED from the
#                                    bundle via chaos --repro — the
#                                    oracle's own false-negative test)
#
# Every tier starts with a PREFLIGHT stray-process check (see
# preflight() below): the tier-1 wall sits within ~10 s of the driver's
# 870 s timeout, and a leftover benchmark process eating a host core
# has silently inflated it before. Findings are recorded as JSON in
# $CI_PREFLIGHT_JSON (default ci_preflight.json) for the round's CI
# artifact — and every pytest tier run through run_tier() APPENDS its
# suite wall + pass count to the same file, so the tier-1-at-the-edge
# trend (PR 10 note) is data, not anecdote. After the tiers, trend_feed()
# stamps the preflight JSON with a host provenance stamp
# (obs/provenance.py host_stamp — the suite always runs the forced-CPU
# mesh) and ingests it into the trend store ($CI_TREND_STORE, default
# ci_trend.jsonl), so the tier walls become a queryable trajectory the
# `trend` verb's sentinel watches.
#
# Usage:
#   scripts/ci.sh            # tier 1 then tier 2 (both tiers, full CI)
#   CI_TIER=1 scripts/ci.sh  # tier 1 only (the under-budget default gate)
#   CI_TIER=0 scripts/ci.sh  # smoke only (the old fast gate)
#   CI_TIER=2 scripts/ci.sh  # slow tier only
#
# tests/conftest.py forces the CPU platform and 8 virtual devices, so no
# TPU is needed; the persistent compile cache amortizes repeat runs.
set -euo pipefail
cd "$(dirname "$0")/.."

preflight() {
  # Stray-CPU-hog check BEFORE the suite starts: a leftover benchmark
  # process from a crashed session once ate one of the two host cores
  # for hours and silently inflated the tier-1 wall to within seconds
  # of the driver's 870 s timeout (CHANGES.md PR 9 session note). Warn
  # loudly and record the finding as JSON ($CI_PREFLIGHT_JSON, default
  # ci_preflight.json — embed it in the round's CI_r*.json artifact) so
  # a slow suite can be told apart from a contended host after the fact.
  local out="${CI_PREFLIGHT_JSON:-ci_preflight.json}"
  python - "$out" <<'PY' || true
import json, os, subprocess, sys

me, shell = os.getpid(), os.getppid()
hogs, err = [], None
try:
    ps = subprocess.run(
        ["ps", "-eo", "pid,ppid,pcpu,comm"],
        capture_output=True, text=True, timeout=10,
    ).stdout
    for line in ps.splitlines()[1:]:
        parts = line.split(None, 3)
        if len(parts) < 4:
            continue
        try:
            pid, ppid, pcpu = int(parts[0]), int(parts[1]), float(parts[2])
        except ValueError:
            continue
        if pid in (me, shell) or ppid == me:
            continue  # this check and its shell are not strays
        if pcpu > 50.0:
            hogs.append({"pid": pid, "pcpu": pcpu, "comm": parts[3]})
except Exception as e:  # a broken ps must not block CI
    err = f"{type(e).__name__}: {e}"[:200]
doc = {"threshold_pcpu": 50.0, "stray_cpu_hogs": hogs}
if err:
    doc["error"] = err
with open(sys.argv[1], "w") as f:
    json.dump(doc, f, indent=1)
    f.write("\n")
if hogs:
    print(
        "CI PREFLIGHT WARNING: stray process(es) eating >50% of a host "
        "core before the suite starts:", file=sys.stderr,
    )
    for h in hogs:
        print(
            f"  pid={h['pid']} pcpu={h['pcpu']} {h['comm']}",
            file=sys.stderr,
        )
    print(
        "  the tier-1 wall budget sits within ~10 s of the 870 s "
        f"timeout — kill the strays or expect a timeout (recorded in "
        f"{sys.argv[1]})", file=sys.stderr,
    )
PY
}

run_tier() {
  # Run one pytest tier and APPEND {tier, wall_s, passed, rc} to the
  # preflight JSON (ISSUE-14 satellite): the tier-1 wall has sat within
  # tens of seconds of the driver's 870 s timeout since PR 9, and until
  # now the trend lived in CHANGES.md prose. $1: tier label; rest:
  # pytest args.
  local label="$1"; shift
  local log rc t0
  log="$(mktemp)"
  t0=$SECONDS
  set +e
  python -m pytest "$@" 2>&1 | tee "$log"
  rc=${PIPESTATUS[0]}
  set -e
  python - "$label" "$((SECONDS - t0))" "$rc" "$log" \
    "${CI_PREFLIGHT_JSON:-ci_preflight.json}" <<'PY' || true
import json, re, sys

label, wall, rc, log, out = sys.argv[1:6]
passed = 0
for m in re.finditer(r"(\d+) passed", open(log, errors="replace").read()):
    passed = int(m.group(1))
try:
    with open(out) as f:
        doc = json.load(f)
except Exception:
    doc = {}
doc.setdefault("tiers", []).append(
    {"tier": label, "wall_s": int(wall), "passed": passed, "rc": int(rc)}
)
with open(out, "w") as f:
    json.dump(doc, f, indent=1)
    f.write("\n")
print(f"ci: tier {label} wall={wall}s passed={passed} rc={rc} -> {out}")
PY
  rm -f "$log"
  return "$rc"
}

assert_stream_identity() {
  # THE twin-compare normalizer, shared by every smoke that proves
  # crashed+resumed stream identity: records equal modulo wall-clock
  # fields ("t", step_time seconds) and the header tag (the twins'
  # plans legitimately differ by the crash point). $1/$2: the two JSONL
  # streams; $3: extra python asserts evaluated with the normalized
  # record list bound as `recs`.
  python - "$1" "$2" "${3:-}" <<'PY'
import json, sys

def norm(path):
    out = []
    for line in open(path):
        d = json.loads(line)
        d.pop("t", None)
        d.pop("crc", None)
        if d.get("event") == "stream_header":
            d.pop("tag", None)
        if d.get("series") == "step_time":
            d["value"] = {k: v for k, v in d["value"].items() if k != "seconds"}
        out.append(d)
    return out

a, b = norm(sys.argv[1]), norm(sys.argv[2])
assert a == b, f"streams differ: {len(a)} vs {len(b)} records"
if sys.argv[3]:
    exec(sys.argv[3], {"recs": a})
PY
}

byzantine_smoke() {
  # End-to-end Byzantine chaos through the REAL CLI: one client per round
  # sends a 10x-scaled update, trimmed-mean(1) + auto-quarantine defend,
  # and a planned crash at (nloop=1, gid=2, nadmm=0) kills the first run
  # mid-experiment (gid 2 is model net's first train_order group). The
  # recovery procedure is rerunning the IDENTICAL command: --resume auto
  # restores the checkpoint, the metric stream splices, and the run
  # finishes with zero rollback rounds.
  local d; d="$(mktemp -d)"
  local cmd=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 240 --synthetic-n-test 60 --batch 40
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30
    --fault-plan "seed=5,corrupt=1:scale:10,crash=1:2:0"
    --robust-agg trimmed --robust-f 1 --quarantine-z 1.0
    --fault-mode rollback --save-model --resume auto
    --checkpoint-dir "$d/ckpt" --metrics-stream "$d/run.jsonl")
  echo "byzantine smoke: expecting the planned crash..."
  if "${cmd[@]}" > "$d/run1.log" 2>&1; then
    echo "byzantine smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/run1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "byzantine smoke: resuming..."
  "${cmd[@]}" > "$d/run2.log" 2>&1 || {
    echo "byzantine smoke FAILED: resume did not finish" >&2
    tail -20 "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  # 2 nloops x 1 group x 2 exchanges, one corrupted client each = 4
  grep -q '# faults injected: .*corruptions=4' "$d/run2.log" || {
    echo "byzantine smoke FAILED: missing/incorrect injected-faults line" >&2
    grep '# faults' "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  if grep -q 'round_rollback' "$d/run.jsonl"; then
    echo "byzantine smoke FAILED: the robust combiner let a round roll back" >&2
    rm -rf "$d"; return 1
  fi
  echo "byzantine smoke OK"
  rm -rf "$d"
}

chaos_smoke() {
  # The chaos HARNESS end to end (fault/chaos.py, ISSUE 20): two legs.
  #
  # Leg 1 — fixed-seed soak: the first handful of fuzzer-drawn composed
  # plans (the three deterministic invariant probes + composed cases)
  # must clear the full invariant oracle with ZERO violations. Every
  # verdict streams to verdicts.jsonl; the chaos_soak.json workload
  # summary is crc-self-verified and fed to the trend store by
  # trend_feed (it carries a host provenance stamp).
  #
  # Leg 2 — the planted bug: CHAOS_PLANT_BUG=combiner swaps the
  # Byzantine-robust combiner for a naive masked mean that averages
  # NaNs straight in. The harness must CATCH the robust_finite
  # violation (exit 2), SHRINK it to a repro bundle of <= 2 fault axes,
  # REPLAY the bundle to the same violation under the planted bug
  # (chaos --repro, exit 0), and see it NOT reproduce on the honest
  # engine (exit 1) — the oracle's own false-negative test.
  local d t0; d="$(mktemp -d)"; t0=$SECONDS
  echo "chaos smoke: soaking fixed-seed composed plans under the oracle..."
  if ! python -m federated_pytorch_test_tpu chaos \
      --cases 5 --seed 0 --budget-s 900 --out "$d/soak" \
      > "$d/soak.log" 2>&1; then
    echo "chaos smoke FAILED: clean-engine soak found a violation" >&2
    tail -30 "$d/soak.log" >&2; rm -rf "$d"; return 1
  fi
  python - "$d/soak" <<'PY' || { rm -rf "$d"; return 1; }
import json, sys

from federated_pytorch_test_tpu.fault.io import verify_crc

out = sys.argv[1]
doc = json.load(open(f"{out}/chaos_soak.json"))
assert verify_crc(doc), "soak summary failed its own crc"
assert doc["workload"] == "chaos_soak" and doc["violations"] == 0, doc
verdicts = [json.loads(l) for l in open(f"{out}/verdicts.jsonl")]
assert len(verdicts) == doc["cases_cleared"] >= 5
assert all(v["ok"] for v in verdicts)
assert verdicts[0]["provenance"]["backend"] == "cpu"
cov = verdicts[-1]["coverage"]
print(f"chaos smoke: {len(verdicts)} plans clean, axes={sorted(cov['axes'])}")
PY
  echo "chaos smoke: planting a broken combiner..."
  set +e
  CHAOS_PLANT_BUG=combiner python -m federated_pytorch_test_tpu chaos \
    --cases 3 --seed 0 --out "$d/plant" > "$d/plant.log" 2>&1
  local rc=$?
  set -e
  if [ "$rc" -ne 2 ]; then
    echo "chaos smoke FAILED: planted combiner bug not caught (rc=$rc)" >&2
    tail -30 "$d/plant.log" >&2; rm -rf "$d"; return 1
  fi
  local bundle="$d/plant/repro-0000.json"
  python - "$bundle" <<'PY' || { rm -rf "$d"; return 1; }
import json, sys

doc = json.load(open(sys.argv[1]))
axes = doc["case"]["axes"]
assert len(axes) <= 2, f"shrunk repro kept {len(axes)} axes: {axes}"
bad = {v["invariant"] for v in doc["violations"]}
assert "robust_finite" in bad, bad
print(f"chaos smoke: shrunk to axes={axes}, violations={sorted(bad)}")
PY
  echo "chaos smoke: replaying the shrunk bundle..."
  CHAOS_PLANT_BUG=combiner python -m federated_pytorch_test_tpu chaos \
    --repro "$bundle" --out "$d/replay" > "$d/replay.log" 2>&1 || {
    echo "chaos smoke FAILED: bundle did not reproduce under the bug" >&2
    tail -10 "$d/replay.log" >&2; rm -rf "$d"; return 1
  }
  if python -m federated_pytorch_test_tpu chaos \
      --repro "$bundle" --out "$d/replay2" > "$d/replay2.log" 2>&1; then
    echo "chaos smoke FAILED: bundle 'reproduced' on the honest engine" >&2
    tail -10 "$d/replay2.log" >&2; rm -rf "$d"; return 1
  fi
  # feed this smoke's wall to the preflight JSON like run_tier does, so
  # the chaos soak's cost is a trend-store trajectory too
  python - chaos_smoke "$((SECONDS - t0))" \
    "${CI_PREFLIGHT_JSON:-ci_preflight.json}" <<'PY' || true
import json, sys

label, wall, out = sys.argv[1:4]
try:
    with open(out) as f:
        doc = json.load(f)
except Exception:
    doc = {}
doc.setdefault("tiers", []).append(
    {"tier": label, "wall_s": int(wall), "passed": 2, "rc": 0}
)
with open(out, "w") as f:
    json.dump(doc, f, indent=1)
    f.write("\n")
PY
  echo "chaos smoke OK"
  rm -rf "$d"
}

hetero_smoke() {
  # End-to-end deadline rounds through the REAL CLI: one 3x slow client
  # per round (speed axis), a round deadline at the nominal full-work
  # time (4 lockstep steps at batch 20: the slow client's budget is 1 —
  # a PARTIAL contribution every exchange), the trimmed combiner riding
  # along, and a planned crash at (nloop=1, gid=2, nadmm=0) killing the
  # first run. Recovery is rerunning the IDENTICAL command; an
  # uninterrupted twin (same plan minus the crash point) then proves
  # crashed+resumed stream identity — client_time/step_budget/
  # deadline_miss records included — modulo wall-clock fields and the
  # header tag the twins legitimately differ in.
  local d; d="$(mktemp -d)"
  local common=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 240 --synthetic-n-test 60 --batch 20
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30
    --round-deadline 4 --robust-agg trimmed --robust-f 1
    --fault-mode rollback --save-model --resume auto)
  local cmd=("${common[@]}"
    --fault-plan "seed=6,slow=1:3,crash=1:2:0"
    --checkpoint-dir "$d/ckpt" --metrics-stream "$d/run.jsonl")
  local twin=("${common[@]}"
    --fault-plan "seed=6,slow=1:3"
    --checkpoint-dir "$d/ckpt_twin" --metrics-stream "$d/twin.jsonl")
  echo "hetero smoke: expecting the planned crash..."
  if "${cmd[@]}" > "$d/run1.log" 2>&1; then
    echo "hetero smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/run1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "hetero smoke: resuming..."
  "${cmd[@]}" > "$d/run2.log" 2>&1 || {
    echo "hetero smoke FAILED: resume did not finish" >&2
    tail -20 "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  "${twin[@]}" > "$d/twin.log" 2>&1 || {
    echo "hetero smoke FAILED: the uninterrupted twin did not finish" >&2
    tail -20 "$d/twin.log" >&2; rm -rf "$d"; return 1
  }
  # 2 nloops x 1 group x 2 exchanges, the one slow client misses each
  grep -q '# faults injected: .*deadline_misses=4' "$d/run2.log" || {
    echo "hetero smoke FAILED: missing/incorrect deadline scoreboard" >&2
    grep '# faults' "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  if grep -q 'round_rollback' "$d/run.jsonl"; then
    echo "hetero smoke FAILED: partial updates tripped a rollback" >&2
    rm -rf "$d"; return 1
  fi
  assert_stream_identity "$d/run.jsonl" "$d/twin.jsonl" '
assert any(d.get("series") == "deadline_miss" for d in recs)
assert any(d.get("series") == "client_time" for d in recs)
' || {
    echo "hetero smoke FAILED: crashed+resumed stream differs from twin" >&2
    rm -rf "$d"; return 1
  }
  echo "hetero smoke OK"
  rm -rf "$d"
}

bf16_smoke() {
  # End-to-end bf16 exchange codec through the REAL CLI (exchange/,
  # docs/PERF.md): every consensus exchange ships the group slice as
  # bfloat16 (half the uplink bytes on the ledger), one client per round
  # sends a 10x-scaled update, trimmed-mean(1) + auto-quarantine defend
  # ON THE DECODED f32 VIEWS, and a planned crash at (nloop=1, gid=2,
  # nadmm=0) kills the first run. Recovery is rerunning the IDENTICAL
  # command; an uninterrupted twin (same plan minus the crash) then
  # proves crashed+resumed stream identity under the codec — comm_bytes
  # records included (exactly half the f32 ledger, asserted below) —
  # with zero rollbacks and the quarantine still firing.
  local d; d="$(mktemp -d)"
  local common=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 240 --synthetic-n-test 60 --batch 40
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30
    --exchange-dtype bfloat16
    --robust-agg trimmed --robust-f 1 --quarantine-z 1.0
    --fault-mode rollback --save-model --resume auto)
  local cmd=("${common[@]}"
    --fault-plan "seed=5,corrupt=1:scale:10,crash=1:2:0"
    --checkpoint-dir "$d/ckpt" --metrics-stream "$d/run.jsonl")
  local twin=("${common[@]}"
    --fault-plan "seed=5,corrupt=1:scale:10"
    --checkpoint-dir "$d/ckpt_twin" --metrics-stream "$d/twin.jsonl")
  echo "bf16 smoke: expecting the planned crash..."
  if "${cmd[@]}" > "$d/run1.log" 2>&1; then
    echo "bf16 smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/run1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "bf16 smoke: resuming..."
  "${cmd[@]}" > "$d/run2.log" 2>&1 || {
    echo "bf16 smoke FAILED: resume did not finish" >&2
    tail -20 "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  "${twin[@]}" > "$d/twin.log" 2>&1 || {
    echo "bf16 smoke FAILED: the uninterrupted twin did not finish" >&2
    tail -20 "$d/twin.log" >&2; rm -rf "$d"; return 1
  }
  if grep -q 'round_rollback' "$d/run.jsonl"; then
    echo "bf16 smoke FAILED: the codec broke the robust combiner (rollback)" >&2
    rm -rf "$d"; return 1
  fi
  assert_stream_identity "$d/run.jsonl" "$d/twin.jsonl" '
comm = [d for d in recs if d.get("series") == "comm_bytes"]
assert comm, "no comm_bytes records"
summ = [d for d in recs if d.get("series") == "comm_summary"][-1]["value"]
assert summ["exchange_dtype"] == "bfloat16", summ
assert summ["wire_bytes_per_value"] == 2, summ
# half the f32 ledger exactly: per-survivor wire bytes are constant
# across exchanges (one group) and 2 bytes/value — i.e. exactly half the
# 4-byte parameter width (the exact hand-check vs masks lives in
# tests/test_exchange.py; here the stream must be self-consistent)
per = {d["value"] // d["survivors"] for d in comm if d["survivors"]}
assert len(per) == 1, per
assert summ["bytes_total"] == sum(d["value"] for d in comm), summ
assert any(d.get("series") == "quarantine" for d in recs), (
    "quarantine never fired under the codec")
' || {
    echo "bf16 smoke FAILED: crashed+resumed stream differs from twin" >&2
    rm -rf "$d"; return 1
  }
  echo "bf16 smoke OK"
  rm -rf "$d"
}

cohort_smoke() {
  # End-to-end cross-device scale through the REAL CLI (clients/,
  # docs/SCALE.md): 10k virtual clients mapped onto 8 data shards, a
  # C=8 cohort per outer loop, a dropout+corruption plan keyed by
  # VIRTUAL client id, the trimmed combiner, and a planned crash at
  # (nloop=1, gid=2, nadmm=0) killing the first run after loop 0's
  # store scatter + dirty-chunk checkpoint. Recovery is rerunning the
  # IDENTICAL command (--resume auto restores the checkpoint AND the
  # store manifest, and the pure cohort sampler re-derives every
  # historical cohort); an uninterrupted twin (same plan minus the
  # crash) then proves crashed+resumed stream identity — cohort
  # membership records included. Small-N fast variants of the same
  # contracts run in tier 1 (tests/test_clients.py).
  local d; d="$(mktemp -d)"
  local common=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 320 --synthetic-n-test 60 --batch 20
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30
    --virtual-clients 10000 --cohort 8 --data-shards 8 --cohort-seed 11
    --store-chunk-clients 8
    --robust-agg trimmed --robust-f 1
    --save-model --resume auto)
  local cmd=("${common[@]}"
    --fault-plan "seed=7,dropout=0.2,corrupt=0.05:scale:10,crash=1:2:0"
    --checkpoint-dir "$d/ckpt" --metrics-stream "$d/run.jsonl")
  local twin=("${common[@]}"
    --fault-plan "seed=7,dropout=0.2,corrupt=0.05:scale:10"
    --checkpoint-dir "$d/ckpt_twin" --metrics-stream "$d/twin.jsonl")
  echo "cohort smoke: expecting the planned crash..."
  if "${cmd[@]}" > "$d/run1.log" 2>&1; then
    echo "cohort smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/run1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "cohort smoke: resuming..."
  "${cmd[@]}" > "$d/run2.log" 2>&1 || {
    echo "cohort smoke FAILED: resume did not finish" >&2
    tail -20 "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  "${twin[@]}" > "$d/twin.log" 2>&1 || {
    echo "cohort smoke FAILED: the uninterrupted twin did not finish" >&2
    tail -20 "$d/twin.log" >&2; rm -rf "$d"; return 1
  }
  grep -q '# cohort: 8 of 10000 virtual clients' "$d/run2.log" || {
    echo "cohort smoke FAILED: missing/incorrect cohort summary line" >&2
    grep '# cohort' "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  assert_stream_identity "$d/run.jsonl" "$d/twin.jsonl" '
cohorts = [d for d in recs if d.get("series") == "cohort"]
assert len(cohorts) == 2, cohorts
assert all(len(d["value"]["clients"]) == 8 for d in cohorts)
assert any(d.get("series") == "cohort_participation" for d in recs)
' || {
    echo "cohort smoke FAILED: crashed+resumed stream differs from twin" >&2
    rm -rf "$d"; return 1
  }
  echo "cohort smoke OK"
  rm -rf "$d"
}

spill_smoke() {
  # Million-client fleet on one host through the REAL CLI (clients/,
  # docs/SCALE.md §Spilled store): N=1,000,000 lazy virtual clients, a
  # C=16 cohort per loop, the store's resident set pinned to TWO chunks
  # (--store-resident-chunks 2, 8-client chunks) so every loop's
  # scatter forces clean-chunk evictions and dirty-chunk spills, and a
  # planned crash at (nloop=1, gid=2, nadmm=0) killing the first run
  # while loop 1's prefetched gather is being consumed. Recovery is
  # rerunning the IDENTICAL command; an uninterrupted twin proves
  # crashed+resumed stream identity. The bounded-RSS gate reads peak
  # host RSS off each run's status sidecar: the N=1M twin must land
  # within 1.25x of an otherwise-identical N=10k run (flat in N) and
  # under an absolute ceiling — a store that silently materialized the
  # population would blow both.
  local d; d="$(mktemp -d)"
  local base=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 320 --synthetic-n-test 60 --batch 20
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30
    --cohort 16 --data-shards 8 --cohort-seed 11
    --store-chunk-clients 8 --store-resident-chunks 2
    --save-model --resume auto)
  local cmd=("${base[@]}" --virtual-clients 1000000
    --fault-plan "seed=7,dropout=0.2,crash=1:2:0"
    --checkpoint-dir "$d/ckpt" --metrics-stream "$d/run.jsonl")
  local twin=("${base[@]}" --virtual-clients 1000000
    --fault-plan "seed=7,dropout=0.2"
    --checkpoint-dir "$d/ckpt_twin" --metrics-stream "$d/twin.jsonl")
  local small=("${base[@]}" --virtual-clients 10000
    --fault-plan "seed=7,dropout=0.2"
    --checkpoint-dir "$d/ckpt_small" --metrics-stream "$d/small.jsonl")
  echo "spill smoke: expecting the planned crash..."
  if "${cmd[@]}" > "$d/run1.log" 2>&1; then
    echo "spill smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/run1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "spill smoke: resuming..."
  "${cmd[@]}" > "$d/run2.log" 2>&1 || {
    echo "spill smoke FAILED: resume did not finish" >&2
    tail -20 "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  "${twin[@]}" > "$d/twin.log" 2>&1 || {
    echo "spill smoke FAILED: the 1M twin did not finish" >&2
    tail -20 "$d/twin.log" >&2; rm -rf "$d"; return 1
  }
  "${small[@]}" > "$d/small.log" 2>&1 || {
    echo "spill smoke FAILED: the 10k baseline did not finish" >&2
    tail -20 "$d/small.log" >&2; rm -rf "$d"; return 1
  }
  grep -q '# cohort: 16 of 1000000 virtual clients' "$d/run2.log" || {
    echo "spill smoke FAILED: missing/incorrect cohort summary line" >&2
    grep '# cohort' "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  grep -q '# store: .*eviction' "$d/run2.log" || {
    echo "spill smoke FAILED: the residency budget forced no evictions" >&2
    grep '# store' "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  assert_stream_identity "$d/run.jsonl" "$d/twin.jsonl" '
cohorts = [d for d in recs if d.get("series") == "cohort"]
assert len(cohorts) == 2, cohorts
assert all(len(d["value"]["clients"]) == 16 for d in cohorts)
assert any(d.get("series") == "cohort_participation" for d in recs)
' || {
    echo "spill smoke FAILED: crashed+resumed stream differs from twin" >&2
    rm -rf "$d"; return 1
  }
  if ! python - "$d/twin.jsonl.status.json" "$d/small.jsonl.status.json" <<'PY'
import json, sys
big = json.load(open(sys.argv[1]))
small = json.load(open(sys.argv[2]))
for doc, name in ((big, "1M"), (small, "10k")):
    assert doc.get("completed"), f"{name} sidecar not stamped completed"
peak_big = big["memory"]["peak_rss_bytes"]
peak_small = small["memory"]["peak_rss_bytes"]
assert peak_big and peak_small, (peak_big, peak_small)
ratio = peak_big / peak_small
# flat in N: 100x the population, within 1.25x the peak RSS (the
# store is lazy + spilled; what remains O(N) is int64 metadata and
# the fault plan's per-round [nadmm, N] draws)
assert ratio <= 1.25, f"peak RSS ratio 1M/10k = {ratio:.3f} > 1.25"
# and an absolute sanity ceiling for the whole process (jax + data +
# store): a population-sized store would be ~250 GB of flat rows
assert peak_big < 6 * 2**30, f"peak RSS {peak_big/2**30:.2f} GiB >= 6 GiB"
st = big.get("store") or {}
assert st.get("resident_budget") == 2, st
assert st.get("evictions", 0) > 0, st
print(
    f"spill smoke: peak RSS 1M={peak_big/2**20:.0f} MiB "
    f"10k={peak_small/2**20:.0f} MiB (ratio {ratio:.3f}); "
    f"evictions={st.get('evictions')} spill_bytes={st.get('spill_bytes')}"
)
PY
  then
    echo "spill smoke FAILED: bounded-RSS gate" >&2
    rm -rf "$d"; return 1
  fi
  echo "spill smoke OK"
  rm -rf "$d"
}

fleet_smoke() {
  # End-to-end CLOSED-LOOP fleet control through the REAL CLI (the
  # ROADMAP-item-3 scenario at population scale): 10k virtual clients
  # with availability churn (churn=0.1:2), Bernoulli 4x stragglers, and
  # corrupting liars; `--round-deadline auto` tracks the online
  # client_time sketch, `--cohort-weighting telemetry` steers sampling
  # by the store's accumulated reliability state, trimmed(1) +
  # quarantine (with the 2f release rule) defend, and a planned crash
  # at (nloop=1, gid=2, nadmm=0) kills the first run AFTER loop 0's
  # scatter committed the telemetry + cohort history. Recovery is
  # rerunning the IDENTICAL command (--resume auto restores checkpoint,
  # store, cohort history, and replays the deadline decisions from the
  # stream); an uninterrupted twin (same plan minus the crash) then
  # proves crashed+resumed stream identity — deadline, availability,
  # cohort_weight, and cohort records included.
  local d; d="$(mktemp -d)"
  local common=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 320 --synthetic-n-test 60 --batch 20
    --nloop 3 --nadmm 2 --max-groups 1 --eval-batch 30
    --virtual-clients 10000 --cohort 8 --data-shards 8 --cohort-seed 11
    --store-chunk-clients 8 --cohort-weighting telemetry
    --round-deadline auto
    --robust-agg trimmed --robust-f 1 --quarantine-z 1.0
    --save-model --resume auto)
  local plan="seed=7,churn=0.1:2,slow=0.08:4,corrupt=0.05:scale:10"
  local cmd=("${common[@]}"
    --fault-plan "$plan,crash=1:2:0"
    --checkpoint-dir "$d/ckpt" --metrics-stream "$d/run.jsonl")
  local twin=("${common[@]}"
    --fault-plan "$plan"
    --checkpoint-dir "$d/ckpt_twin" --metrics-stream "$d/twin.jsonl")
  echo "fleet smoke: expecting the planned crash..."
  if "${cmd[@]}" > "$d/run1.log" 2>&1; then
    echo "fleet smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/run1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "fleet smoke: resuming..."
  "${cmd[@]}" > "$d/run2.log" 2>&1 || {
    echo "fleet smoke FAILED: resume did not finish" >&2
    tail -20 "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  "${twin[@]}" > "$d/twin.log" 2>&1 || {
    echo "fleet smoke FAILED: the uninterrupted twin did not finish" >&2
    tail -20 "$d/twin.log" >&2; rm -rf "$d"; return 1
  }
  # the scoreboard's churn row (population client-loop absences) is
  # pure in the plan, so the resumed run prints a nonzero total
  grep -Eq '# faults injected: .*churned=[1-9]' "$d/run2.log" || {
    echo "fleet smoke FAILED: missing/zero churned scoreboard row" >&2
    grep '# faults' "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  assert_stream_identity "$d/run.jsonl" "$d/twin.jsonl" '
dl = [d for d in recs if d.get("series") == "deadline"]
assert dl and all(d["value"]["source"] in ("warmup", "sketch") for d in dl)
assert any(d.get("series") == "availability" for d in recs)
assert any(d.get("series") == "cohort_weight" for d in recs)
assert any(d.get("series") == "client_time" for d in recs)
cohorts = [d for d in recs if d.get("series") == "cohort"]
assert len(cohorts) == 3 and all(
    len(d["value"]["clients"]) == 8 for d in cohorts)
' || {
    echo "fleet smoke FAILED: crashed+resumed stream differs from twin" >&2
    rm -rf "$d"; return 1
  }
  echo "fleet smoke OK"
  rm -rf "$d"
}

codec_smoke() {
  # End-to-end codec zoo + adaptive layer-group scheduling through the
  # REAL CLI (exchange/, docs/PERF.md §Codec zoo): a 3-codec sweep —
  # identity/roundrobin baseline, topk(0.1)+error-feedback under the
  # ADAPTIVE scheduler, and q8 — over the identical corruption+dropout
  # plan with the trimmed combiner. The topk run is CRASHED by a
  # planned crash at (nloop=1, gid=2, nadmm=0) and recovered by
  # rerunning the identical command (--resume auto replays the slot
  # decisions and drift signal from the stream); an uninterrupted twin
  # proves crashed+resumed stream identity — group_schedule and
  # group_distance records included. `report` over the sweep then
  # gates the ISSUE-13 frontier acceptance: the sparse point lands
  # within 2 accuracy points of the f32/roundrobin baseline at <= 25%
  # of its cumulative uplink bytes (topk(0.1) prices at 20%: 8 bytes
  # per kept pair on a tenth of the coordinates vs 4 bytes/value
  # dense), with the report byte-identical between the crashed+resumed
  # sweep dir and the twin dir.
  local d; d="$(mktemp -d)"
  mkdir -p "$d/a" "$d/b"
  local plan="seed=5,dropout=0.2,corrupt=1:scale:10"
  local base=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 240 --synthetic-n-test 60 --batch 40
    --nloop 2 --nadmm 2 --max-groups 2 --eval-batch 30
    --robust-agg trimmed --robust-f 1
    --fault-mode rollback --save-model --resume auto)
  echo "codec smoke: f32/roundrobin baseline..."
  "${base[@]}" --fault-plan "$plan" \
    --checkpoint-dir "$d/ckpt_f32" --metrics-stream "$d/a/f32.jsonl" \
    > "$d/f32.log" 2>&1 || {
    echo "codec smoke FAILED: f32 baseline did not finish" >&2
    tail -20 "$d/f32.log" >&2; rm -rf "$d"; return 1
  }
  cp "$d/a/f32.jsonl" "$d/b/f32.jsonl"
  local topk=("${base[@]}" --exchange-codec topk --topk-fraction 0.1
    --error-feedback --group-schedule adaptive)
  local crash=("${topk[@]}" --fault-plan "$plan,crash=1:2:0"
    --checkpoint-dir "$d/ckpt_topk" --metrics-stream "$d/a/topk.jsonl")
  echo "codec smoke: expecting the planned topk crash..."
  if "${crash[@]}" > "$d/topk1.log" 2>&1; then
    echo "codec smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/topk1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "codec smoke: resuming..."
  "${crash[@]}" > "$d/topk2.log" 2>&1 || {
    echo "codec smoke FAILED: resume did not finish" >&2
    tail -20 "$d/topk2.log" >&2; rm -rf "$d"; return 1
  }
  "${topk[@]}" --fault-plan "$plan" \
    --checkpoint-dir "$d/ckpt_topk_twin" --metrics-stream "$d/b/topk.jsonl" \
    > "$d/twin.log" 2>&1 || {
    echo "codec smoke FAILED: the uninterrupted twin did not finish" >&2
    tail -20 "$d/twin.log" >&2; rm -rf "$d"; return 1
  }
  echo "codec smoke: q8 run..."
  "${base[@]}" --exchange-codec quant --quant-bits 8 --fault-plan "$plan" \
    --checkpoint-dir "$d/ckpt_q8" --metrics-stream "$d/a/q8.jsonl" \
    > "$d/q8.log" 2>&1 || {
    echo "codec smoke FAILED: q8 run did not finish" >&2
    tail -20 "$d/q8.log" >&2; rm -rf "$d"; return 1
  }
  cp "$d/a/q8.jsonl" "$d/b/q8.jsonl"
  if grep -q 'round_rollback' "$d/a/topk.jsonl" "$d/a/q8.jsonl"; then
    echo "codec smoke FAILED: a codec broke the robust combiner (rollback)" >&2
    rm -rf "$d"; return 1
  fi
  assert_stream_identity "$d/a/topk.jsonl" "$d/b/topk.jsonl" '
sched = [d for d in recs if d.get("series") == "group_schedule"]
assert sched and all(
    d["value"]["source"] in ("warmup", "drift") for d in sched)
assert any(d.get("series") == "group_distance" for d in recs)
summ = [d for d in recs if d.get("series") == "comm_summary"][-1]["value"]
assert summ["codec"]["label"] == "topk(0.1)", summ
' || {
    echo "codec smoke FAILED: crashed+resumed stream differs from twin" >&2
    rm -rf "$d"; return 1
  }
  python -m federated_pytorch_test_tpu report "$d/a" \
    --json "$d/a.json" --md "$d/a.md" --quiet || {
    echo "codec smoke FAILED: report over the sweep dir errored" >&2
    rm -rf "$d"; return 1
  }
  python -m federated_pytorch_test_tpu report "$d/b" \
    --json "$d/b.json" --md "$d/b.md" --quiet || {
    echo "codec smoke FAILED: report over the twin dir errored" >&2
    rm -rf "$d"; return 1
  }
  cmp -s "$d/a.json" "$d/b.json" && cmp -s "$d/a.md" "$d/b.md" || {
    echo "codec smoke FAILED: crashed+resumed report differs from twin" >&2
    diff "$d/a.json" "$d/b.json" | head -20 >&2; rm -rf "$d"; return 1
  }
  python - "$d/a.json" <<'PY' || { rm -rf "$d"; return 1; }
import json, sys

doc = json.load(open(sys.argv[1]))
runs = doc["runs"]
assert set(runs) == {"f32", "topk", "q8"}, sorted(runs)
f32, topk, q8 = runs["f32"], runs["topk"], runs["q8"]
assert f32["config"]["label"] == "identity/roundrobin", f32["config"]
assert topk["config"]["label"] == "topk(0.1)/adaptive", topk["config"]
assert q8["config"]["label"] == "q8/roundrobin", q8["config"]
# THE frontier acceptance (ISSUE 13): the sparse+scheduled point lands
# within 2 accuracy points of the f32/roundrobin baseline at <= 25% of
# its cumulative uplink bytes (bf16's halving was 50%)
assert topk["total_comm_bytes"] <= 0.25 * f32["total_comm_bytes"], (
    topk["total_comm_bytes"], f32["total_comm_bytes"])
assert topk["final_accuracy"] >= f32["final_accuracy"] - 0.02, (
    topk["final_accuracy"], f32["final_accuracy"])
# q8 prices at ~25.1% (scale header over 1 byte/value) — cheaper than
# bf16's 50% but above the 25% gate; the frontier shows both points
assert q8["total_comm_bytes"] < 0.27 * f32["total_comm_bytes"]
# the cheapest codec is on the frontier, the baseline is dominated or
# the single most-accurate point; every frontier row carries its
# codec+scheduler label
front = {p["run"]: p for p in doc["frontier"]}
assert front["topk"]["pareto"], doc["frontier"]
assert front["topk"]["config"] == "topk(0.1)/adaptive"
print("codec smoke: frontier acceptance OK",
      {k: (v["total_comm_bytes"], v["final_accuracy"])
       for k, v in runs.items()})
PY
  echo "codec smoke OK"
  rm -rf "$d"
}

report_smoke() {
  # End-to-end cross-run registry through the REAL CLI (obs/registry.py,
  # docs/OBSERVABILITY.md): a two-point codec sweep — identical configs
  # except f32 vs bf16 exchange wire format, same corruption plan — whose
  # streams land in one directory, and `report` turns it into the
  # convergence-vs-bytes frontier in one command (the bf16 run's uplink
  # is exactly half the f32 run's for the identical schedule). The bf16
  # run is additionally CRASHED by a planned crash at (nloop=1, gid=2,
  # nadmm=0) and recovered by rerunning the identical command; an
  # uninterrupted twin directory (same f32 stream file, twin bf16 plan
  # minus the crash) then gates the registry's determinism contract:
  # `report` over the crashed+resumed sweep is BYTE-identical (JSON and
  # markdown) to the twin sweep's — no wall-clock or tag content leaks
  # into the report.
  local d; d="$(mktemp -d)"
  mkdir -p "$d/a" "$d/b"
  local base=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 240 --synthetic-n-test 60 --batch 40
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30
    --robust-agg trimmed --robust-f 1
    --fault-mode rollback --save-model --resume auto)
  echo "report smoke: f32 baseline run..."
  "${base[@]}" --fault-plan "seed=5,corrupt=1:scale:10" \
    --checkpoint-dir "$d/ckpt_f32" --metrics-stream "$d/a/f32.jsonl" \
    > "$d/f32.log" 2>&1 || {
    echo "report smoke FAILED: f32 run did not finish" >&2
    tail -20 "$d/f32.log" >&2; rm -rf "$d"; return 1
  }
  cp "$d/a/f32.jsonl" "$d/b/f32.jsonl"
  local crash=("${base[@]}" --exchange-dtype bfloat16
    --fault-plan "seed=5,corrupt=1:scale:10,crash=1:2:0"
    --checkpoint-dir "$d/ckpt_bf" --metrics-stream "$d/a/bf16.jsonl")
  echo "report smoke: expecting the planned bf16 crash..."
  if "${crash[@]}" > "$d/bf1.log" 2>&1; then
    echo "report smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/bf1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "report smoke: resuming..."
  "${crash[@]}" > "$d/bf2.log" 2>&1 || {
    echo "report smoke FAILED: resume did not finish" >&2
    tail -20 "$d/bf2.log" >&2; rm -rf "$d"; return 1
  }
  "${base[@]}" --exchange-dtype bfloat16 \
    --fault-plan "seed=5,corrupt=1:scale:10" \
    --checkpoint-dir "$d/ckpt_bf_twin" --metrics-stream "$d/b/bf16.jsonl" \
    > "$d/twin.log" 2>&1 || {
    echo "report smoke FAILED: the uninterrupted twin did not finish" >&2
    tail -20 "$d/twin.log" >&2; rm -rf "$d"; return 1
  }
  python -m federated_pytorch_test_tpu report "$d/a" \
    --json "$d/a.json" --md "$d/a.md" --quiet || {
    echo "report smoke FAILED: report over the sweep dir errored" >&2
    rm -rf "$d"; return 1
  }
  python -m federated_pytorch_test_tpu report "$d/b" \
    --json "$d/b.json" --md "$d/b.md" --quiet || {
    echo "report smoke FAILED: report over the twin dir errored" >&2
    rm -rf "$d"; return 1
  }
  cmp -s "$d/a.json" "$d/b.json" && cmp -s "$d/a.md" "$d/b.md" || {
    echo "report smoke FAILED: crashed+resumed report differs from twin" >&2
    diff "$d/a.json" "$d/b.json" | head -20 >&2; rm -rf "$d"; return 1
  }
  python - "$d/a.json" <<'PY' || { rm -rf "$d"; return 1; }
import json, sys

doc = json.load(open(sys.argv[1]))
runs = doc["runs"]
assert set(runs) == {"f32", "bf16"}, sorted(runs)
f32, bf16 = runs["f32"], runs["bf16"]
# identical schedule, half the wire width: exactly half the bytes
assert f32["total_comm_bytes"] == 2 * bf16["total_comm_bytes"], (
    f32["total_comm_bytes"], bf16["total_comm_bytes"])
assert bf16["comm"]["exchange_dtype"] == "bfloat16", bf16["comm"]
assert f32["evals"] == bf16["evals"] > 0
# the cheaper codec is on the frontier by construction
front = {p["run"]: p["pareto"] for p in doc["frontier"]}
assert front["bf16"], doc["frontier"]
# the health engine monitored every round of both runs
assert f32["health"]["records"] == bf16["health"]["records"] > 0
print("report smoke: frontier + health checks OK")
PY
  echo "report smoke OK"
  rm -rf "$d"
}

incident_smoke() {
  # Flight-recorder forensics through the REAL CLI (obs/flight.py,
  # ISSUE 14): a nan_burst corruption under the MEAN combiner poisons
  # every round's consensus, rollback mode sacrifices the round, and
  # the health engine fires (nonfinite + rollback) -> the flight
  # recorder dumps one incident bundle (rising edge) beside the stream
  # and the anomaly-armed profiler captures ONE round (budget 1, the
  # real jax.profiler leg — tier-1 stubs it for wall budget). Assert
  # the bundle exists, validates against the schema, its in-bundle
  # series match the stream's last W rounds EXACTLY (the acceptance
  # criterion), `report --incidents` tables it, and `watch --once`
  # renders the directory without error.
  local d; d="$(mktemp -d)"
  python -m federated_pytorch_test_tpu --preset fedavg --quiet \
    --synthetic-n-train 240 --synthetic-n-test 60 --batch 40 \
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30 \
    --fault-plan "seed=5,corrupt=1:nan_burst" --fault-mode rollback \
    --profile-on-anomaly "$d/prof" --profile-budget 1 \
    --metrics-stream "$d/run.jsonl" > "$d/run.log" 2>&1 || {
    echo "incident smoke FAILED: the run did not finish" >&2
    tail -20 "$d/run.log" >&2; rm -rf "$d"; return 1
  }
  python - "$d" <<'PY' || { rm -rf "$d"; return 1; }
import glob, json, os, sys

from federated_pytorch_test_tpu.obs.flight import validate_incident

d = sys.argv[1]
bundles = sorted(
    glob.glob(os.path.join(d, "run.jsonl.incidents", "incident-*.json"))
)
assert len(bundles) == 1, bundles  # chronic anomaly: one rising-edge dump
doc = json.load(open(bundles[0]))
validate_incident(doc)
assert set(doc["anomalies"]) >= {"nonfinite", "rollback"}, doc["anomalies"]
# in-bundle series match the stream's last W rounds EXACTLY: segment
# the stream on dispatch_count (the round's final streamed record)
rounds, cur = [], []
for line in open(os.path.join(d, "run.jsonl")):
    rec = json.loads(line)
    if "series" not in rec:
        continue
    cur.append(rec)
    if rec["series"] == "dispatch_count":
        rounds.append(cur)
        cur = []
held = rounds[: doc["round"] + 1][-doc["window"]:]
assert [b["records"] for b in doc["rounds"]] == held, "bundle != stream tail"
# the real profiler capture landed (round AFTER the first alert)
caps = glob.glob(os.path.join(d, "prof", "round-*", "**", "*"),
                 recursive=True)
assert any(os.path.isfile(p) for p in caps), "no profiler capture files"
print("incident smoke: bundle schema + stream-tail match + capture OK",
      os.path.basename(bundles[0]))
PY
  python -m federated_pytorch_test_tpu report "$d" --incidents \
    --json "$d/report.json" --quiet || {
    echo "incident smoke FAILED: report --incidents errored" >&2
    rm -rf "$d"; return 1
  }
  grep -q '"incidents"' "$d/report.json" || {
    echo "incident smoke FAILED: report JSON has no incidents table" >&2
    rm -rf "$d"; return 1
  }
  python -m federated_pytorch_test_tpu watch "$d" --once > "$d/watch.out" || {
    echo "incident smoke FAILED: watch --once errored" >&2
    tail -20 "$d/watch.out" >&2; rm -rf "$d"; return 1
  }
  grep -q 'incident-0-0.json' "$d/watch.out" || {
    echo "incident smoke FAILED: watch output missing the incident line" >&2
    cat "$d/watch.out" >&2; rm -rf "$d"; return 1
  }
  echo "incident smoke OK"
  rm -rf "$d"
}

integrity_smoke() {
  # Storage-integrity axis through the REAL CLI (fault/io.py,
  # docs/FAULT.md §Storage-integrity axis): a 100k-client spilled run
  # (telemetry weighting, so every loop re-reads the spilled chunks
  # through the verify-on-read path) under an injected bitrot plan
  # with a planned crash at (nloop=1, gid=2, nadmm=0), recovered by
  # rerunning the IDENTICAL command — resume-time verify_all and the
  # bounded retry heal every hit (the disk is intact; only read
  # buffers are corrupted), so the crashed+resumed stream is
  # byte-identical to an uninterrupted twin's. A second leg survives a
  # transient-ioerror plan on the write paths (spills, stream lines,
  # checkpoint staging). Then the offline ladder: bit-flip a chunk
  # file in the twin's store, `scrub` exits nonzero NAMING it,
  # `scrub --repair` resolves it, and a re-scrub is clean. Both run
  # logs must show a nonzero `storage_faults=` scoreboard entry.
  # --no-prefetch pins the shim's per-op draw schedule: background
  # gathers would interleave read ordinals nondeterministically.
  local d; d="$(mktemp -d)"
  local base=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 320 --synthetic-n-test 60 --batch 20
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30
    --virtual-clients 100000 --cohort 16 --data-shards 8 --cohort-seed 11
    --cohort-weighting telemetry --no-prefetch
    --store-chunk-clients 8 --store-resident-chunks 2
    --save-model --resume auto)
  local cmd=("${base[@]}" --fault-plan "seed=7,storage=0.1:bitrot,crash=1:2:0"
    --checkpoint-dir "$d/ckpt" --metrics-stream "$d/run.jsonl")
  local twin=("${base[@]}" --fault-plan "seed=7,storage=0.1:bitrot"
    --checkpoint-dir "$d/ckpt_twin" --metrics-stream "$d/twin.jsonl")
  echo "integrity smoke: expecting the planned crash..."
  if "${cmd[@]}" > "$d/run1.log" 2>&1; then
    echo "integrity smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/run1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "integrity smoke: resuming through the verify gate..."
  "${cmd[@]}" > "$d/run2.log" 2>&1 || {
    echo "integrity smoke FAILED: resume did not finish" >&2
    tail -20 "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  "${twin[@]}" > "$d/twin.log" 2>&1 || {
    echo "integrity smoke FAILED: the twin did not finish" >&2
    tail -20 "$d/twin.log" >&2; rm -rf "$d"; return 1
  }
  for log in run2 twin; do
    grep -Eq 'storage_faults=[1-9]' "$d/$log.log" || {
      echo "integrity smoke FAILED: $log scoreboard shows no storage faults" >&2
      grep '# faults injected' "$d/$log.log" >&2; rm -rf "$d"; return 1
    }
  done
  grep -q 'checksum verification' "$d/run2.log" || {
    echo "integrity smoke FAILED: no bitrot hit was ever detected" >&2
    rm -rf "$d"; return 1
  }
  assert_stream_identity "$d/run.jsonl" "$d/twin.jsonl" '
assert not any(d.get("series") == "incident" for d in recs)
' || {
    echo "integrity smoke FAILED: crashed+resumed stream differs from twin" >&2
    rm -rf "$d"; return 1
  }
  if ! python - "$d/run.jsonl.status.json" <<'PY'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc.get("completed"), "sidecar not stamped completed"
dig = doc.get("integrity") or {}
assert dig.get("checksums") and dig.get("verified_reads", 0) > 0, dig
assert dig.get("failures", 0) > 0, dig          # rot was DETECTED...
assert dig.get("retry_heals", 0) > 0, dig       # ...and healed
assert not dig.get("repairs_prior") and not dig.get("repairs_reinit"), dig
assert doc.get("storage_faults", 0) > 0, doc.get("storage_faults")
print(
    f"integrity smoke: verified_reads={dig['verified_reads']} "
    f"failures={dig['failures']} retry_heals={dig['retry_heals']}"
)
PY
  then
    echo "integrity smoke FAILED: integrity sidecar gate" >&2
    rm -rf "$d"; return 1
  fi
  echo "integrity smoke: surviving a transient-ioerror write plan..."
  "${base[@]}" --fault-plan "seed=3,storage=0.05:ioerror" \
    --checkpoint-dir "$d/ckpt_io" --metrics-stream "$d/io.jsonl" \
    > "$d/io.log" 2>&1 || {
    echo "integrity smoke FAILED: ioerror plan run did not finish" >&2
    tail -20 "$d/io.log" >&2; rm -rf "$d"; return 1
  }
  grep -Eq 'storage_faults=[1-9]' "$d/io.log" && grep -q 'retrying' "$d/io.log" || {
    echo "integrity smoke FAILED: ioerror plan injected/retried nothing" >&2
    rm -rf "$d"; return 1
  }
  echo "integrity smoke: scrub detect-then-repair..."
  local chunk
  chunk="$(ls "$d/ckpt_twin/client_store/" | grep '^chunk_' | head -1)"
  python -c "
p = '$d/ckpt_twin/client_store/$chunk'
b = bytearray(open(p, 'rb').read()); b[120] ^= 0xFF
open(p, 'wb').write(bytes(b))"
  if python -m federated_pytorch_test_tpu scrub "$d/ckpt_twin" > "$d/scrub1.out" 2>&1; then
    echo "integrity smoke FAILED: scrub missed the corrupt chunk" >&2
    cat "$d/scrub1.out" >&2; rm -rf "$d"; return 1
  fi
  grep -q "CORRUPT $chunk" "$d/scrub1.out" || {
    echo "integrity smoke FAILED: scrub did not name the chunk" >&2
    cat "$d/scrub1.out" >&2; rm -rf "$d"; return 1
  }
  python -m federated_pytorch_test_tpu scrub "$d/ckpt_twin" --repair \
    > "$d/scrub2.out" 2>&1 || {
    echo "integrity smoke FAILED: scrub --repair left problems" >&2
    cat "$d/scrub2.out" >&2; rm -rf "$d"; return 1
  }
  python -m federated_pytorch_test_tpu scrub "$d/ckpt_twin" > "$d/scrub3.out" 2>&1 || {
    echo "integrity smoke FAILED: store still dirty after repair" >&2
    cat "$d/scrub3.out" >&2; rm -rf "$d"; return 1
  }
  echo "integrity smoke OK"
  rm -rf "$d"
}

widened_smoke() {
  # Widened client GEMM through the REAL CLI (engine/steps.py,
  # ops/grouped_gemm.py, docs/PERF.md §Widened GEMM): a P=4 probe fan
  # under --client-fold gemm — the fold that turns the K-client x
  # P-probe fan into one wide contraction — with a dropout+corruption
  # plan, trimmed(1), and the topk codec riding the exchange, and a
  # planned crash at (nloop=1, gid=2, nadmm=0) killing the first run.
  # Recovery is rerunning the IDENTICAL command; an uninterrupted twin
  # proves crashed+resumed stream identity, with the per-round
  # {round: 1} dispatch budget asserted ON THE STREAM (the fold must
  # not cost a dispatch). Then the escape hatch: a --client-fold vmap
  # rerun of the twin's exact plan, whose stream must match the gemm
  # twin's within the documented tolerance — on the CPU twin that
  # tolerance is BITWISE (docs/PERF.md fallback matrix) modulo the
  # fold-mode tag the step_time/epoch records deliberately carry and
  # the stream-tag header (the knob is a tag member).
  local d; d="$(mktemp -d)"
  local common=(python -m federated_pytorch_test_tpu --preset fedavg --quiet
    --synthetic-n-train 240 --synthetic-n-test 60 --batch 40
    --nloop 2 --nadmm 2 --max-groups 1 --eval-batch 30
    --linesearch-probes 4
    --exchange-codec topk --topk-fraction 0.1
    --robust-agg trimmed --robust-f 1
    --fault-mode rollback --save-model --resume auto)
  local plan="seed=8,dropout=0.3,corrupt=1:gauss:0.5"
  local cmd=("${common[@]}" --client-fold gemm
    --fault-plan "$plan,crash=1:2:0"
    --checkpoint-dir "$d/ckpt" --metrics-stream "$d/run.jsonl")
  local twin=("${common[@]}" --client-fold gemm
    --fault-plan "$plan"
    --checkpoint-dir "$d/ckpt_twin" --metrics-stream "$d/twin.jsonl")
  local vmapped=("${common[@]}" --client-fold vmap
    --fault-plan "$plan"
    --checkpoint-dir "$d/ckpt_vmap" --metrics-stream "$d/vmap.jsonl")
  echo "widened smoke: expecting the planned crash..."
  if "${cmd[@]}" > "$d/run1.log" 2>&1; then
    echo "widened smoke FAILED: the planned crash never fired" >&2
    tail -5 "$d/run1.log" >&2; rm -rf "$d"; return 1
  fi
  echo "widened smoke: resuming..."
  "${cmd[@]}" > "$d/run2.log" 2>&1 || {
    echo "widened smoke FAILED: resume did not finish" >&2
    tail -20 "$d/run2.log" >&2; rm -rf "$d"; return 1
  }
  "${twin[@]}" > "$d/twin.log" 2>&1 || {
    echo "widened smoke FAILED: the uninterrupted twin did not finish" >&2
    tail -20 "$d/twin.log" >&2; rm -rf "$d"; return 1
  }
  assert_stream_identity "$d/run.jsonl" "$d/twin.jsonl" '
dc = [d for d in recs if d.get("series") == "dispatch_count"]
assert dc, "no dispatch_count records"
# the fold must not cost a dispatch: every round is ONE round dispatch
# (plus the first round its init), faults+trimmed+topk live inside it
assert all(d["value"].get("round") == 1 for d in dc), dc
assert not any(d["value"].get("epoch") for d in dc), dc
st = [d for d in recs if d.get("series") == "step_time"]
assert any(d["value"]["phase"] == "fused_round" for d in st), "not fused"
assert all(
    d.get("client_fold") == "gemm"
    for d in st if d["value"]["phase"] == "fused_round"
), "fused_round spans not tagged with the fold mode"
summ = [d for d in recs if d.get("series") == "comm_summary"][-1]["value"]
assert summ["codec"]["label"] == "topk(0.1)", summ
' || {
    echo "widened smoke FAILED: crashed+resumed stream differs from twin" >&2
    rm -rf "$d"; return 1
  }
  echo "widened smoke: vmap escape-hatch rerun..."
  "${vmapped[@]}" > "$d/vmap.log" 2>&1 || {
    echo "widened smoke FAILED: the vmap rerun did not finish" >&2
    tail -20 "$d/vmap.log" >&2; rm -rf "$d"; return 1
  }
  # the cross-fold compare: same normalization as assert_stream_identity
  # PLUS the fold-mode tag (step_time/epoch records carry client_fold by
  # design — it is the ONLY legitimate cross-fold difference on CPU)
  python - "$d/twin.jsonl" "$d/vmap.jsonl" <<'PY' || {
import json, sys

def norm(path):
    out = []
    for line in open(path):
        d = json.loads(line)
        d.pop("t", None)
        d.pop("crc", None)
        d.pop("client_fold", None)
        if d.get("event") == "stream_header":
            d.pop("tag", None)
        if d.get("series") == "step_time":
            d["value"] = {k: v for k, v in d["value"].items() if k != "seconds"}
        out.append(d)
    return out

a, b = norm(sys.argv[1]), norm(sys.argv[2])
assert a == b, f"gemm vs vmap streams differ: {len(a)} vs {len(b)} records"
print(f"widened smoke: gemm == vmap over {len(a)} records (CPU bitwise)")
PY
    echo "widened smoke FAILED: vmap stream differs from gemm beyond the fold tag" >&2
    rm -rf "$d"; return 1
  }
  echo "widened smoke OK"
  rm -rf "$d"
}

trend_smoke() {
  # The provenance+trend layer end to end (obs/provenance.py,
  # obs/benchdb.py, obs/debt.py — ISSUE-18): two probe-gated bench runs
  # (flagship headline only: BENCH_PROBES=0 skips the subsystem probe
  # suite, BENCH_SWEEP=0 the utilization sweep) wrapped as the driver's
  # {n, cmd, rc, tail, parsed} BENCH_*.json format, then four gates:
  #   1. DETERMINISM — the trend report is byte-identical when the same
  #      wrappers are re-ingested (digest-deduped append-only store);
  #   2. TWIN NOISE — two honest back-to-back CPU runs of the same
  #      commit must NOT trip the regression sentinel (the >=25% noise
  #      band, widened by each headline's own sps_p25/p75 spread);
  #   3. SENTINEL — a synthetic 2x slowdown of the same provenance
  #      class IS flagged, exit nonzero, metric named;
  #   4. ISOLATION — the CPU-twin measurements leave every
  #      backend==tpu DEBT.json entry open (a twin can never pay TPU
  #      debt), and the `debt` verb still emits a syntactically valid
  #      payment script for them.
  local d; d="$(mktemp -d)"
  echo "trend smoke: two probe-gated bench runs..."
  # BENCH_MODEL=net: the flagship resnet18 L-BFGS epoch costs minutes
  # per draw on the CPU twin; the tiny CNN drives the identical timing
  # path in seconds (bench.py renames the headline metric so these rows
  # can never touch the resnet18 trajectory)
  local benv=(env BENCH_DEVICE=cpu BENCH_PROBES=0 BENCH_SWEEP=0
              BENCH_MODEL=net BENCH_BATCH=8 BENCH_REPEATS=5 BENCH_STEPS=2)
  "${benv[@]}" python bench.py > "$d/b1.log" 2>&1 || {
    echo "trend smoke FAILED: bench run 1 died" >&2
    tail -20 "$d/b1.log" >&2; rm -rf "$d"; return 1
  }
  "${benv[@]}" python bench.py > "$d/b2.log" 2>&1 || {
    echo "trend smoke FAILED: bench run 2 died" >&2
    tail -20 "$d/b2.log" >&2; rm -rf "$d"; return 1
  }
  # wrap each run's final stdout line exactly the way the driver does,
  # plus the synthetic regression: run 2's headline again, value halved
  # (same provenance class — the sentinel MUST see it)
  python - "$d" <<'PY' || { rm -rf "$d"; return 1; }
import json, sys

d = sys.argv[1]
for i in (1, 2):
    tail = open(f"{d}/b{i}.log").read().strip().splitlines()[-1]
    parsed = json.loads(tail)
    assert parsed.get("provenance", {}).get("cpu_twin") is True, \
        "bench headline is missing the cpu_twin provenance stamp"
    with open(f"{d}/BENCH_s{i:02d}.json", "w") as f:
        json.dump({"n": i, "cmd": "python bench.py", "rc": 0,
                   "tail": tail, "parsed": parsed}, f)
slow = json.loads(open(f"{d}/b2.log").read().strip().splitlines()[-1])
slow["value"] = slow["value"] / 2.0
for k in ("sps_p25", "sps_p75"):
    if slow.get(k):
        slow[k] = slow[k] / 2.0
with open(f"{d}/slowdown.json", "w") as f:
    json.dump({"n": 3, "cmd": "python bench.py", "rc": 0,
               "tail": "", "parsed": slow}, f)
PY
  echo "trend smoke: ingest + twin-noise + determinism gates..."
  python -m federated_pytorch_test_tpu trend \
    "$d/BENCH_s01.json" "$d/BENCH_s02.json" \
    --store "$d/t.jsonl" --json "$d/r1.json" --md "$d/r1.md" \
    --debt none --quiet || {
    echo "trend smoke FAILED: the twin-noise rerun tripped the sentinel" >&2
    cat "$d/r1.md" >&2; rm -rf "$d"; return 1
  }
  python -m federated_pytorch_test_tpu trend \
    "$d/BENCH_s01.json" "$d/BENCH_s02.json" \
    --store "$d/t.jsonl" --json "$d/r2.json" --md "$d/r2.md" \
    --debt none --quiet || {
    echo "trend smoke FAILED: re-ingest tripped the sentinel" >&2
    rm -rf "$d"; return 1
  }
  cmp -s "$d/r1.json" "$d/r2.json" && cmp -s "$d/r1.md" "$d/r2.md" || {
    echo "trend smoke FAILED: report not byte-identical on re-ingest" >&2
    diff "$d/r1.json" "$d/r2.json" | head -20 >&2; rm -rf "$d"; return 1
  }
  echo "trend smoke: synthetic 2x slowdown must be flagged..."
  if python -m federated_pytorch_test_tpu trend "$d/slowdown.json" \
       --store "$d/t.jsonl" --md "$d/r3.md" --debt none --quiet; then
    echo "trend smoke FAILED: the 2x slowdown sailed past the sentinel" >&2
    rm -rf "$d"; return 1
  fi
  grep -q "REGRESSION" "$d/r3.md" || {
    echo "trend smoke FAILED: regression not named in the report" >&2
    rm -rf "$d"; return 1
  }
  echo "trend smoke: CPU-twin measurements must not pay TPU debt..."
  cp DEBT.json "$d/DEBT.json"
  python -m federated_pytorch_test_tpu trend \
    "$d/BENCH_s01.json" "$d/BENCH_s02.json" \
    --store "$d/t_debt.jsonl" --debt "$d/DEBT.json" --quiet || true
  python - "$d/DEBT.json" <<'PY' || { rm -rf "$d"; return 1; }
import json, sys

doc = json.load(open(sys.argv[1]))
still_open = [e for e in doc["entries"] if e.get("status", "open") == "open"]
assert len(still_open) == len(doc["entries"]), (
    "a CPU-twin measurement closed TPU debt: "
    + str([e["id"] for e in doc["entries"] if e not in still_open])
)
print(f"trend smoke: all {len(still_open)} backend==tpu entries stayed open")
PY
  python -m federated_pytorch_test_tpu debt --file "$d/DEBT.json" \
    --script "$d/remeasure.sh" --quiet > /dev/null || {
    echo "trend smoke FAILED: the debt verb died" >&2
    rm -rf "$d"; return 1
  }
  bash -n "$d/remeasure.sh" || {
    echo "trend smoke FAILED: emitted payment script does not parse" >&2
    rm -rf "$d"; return 1
  }
  echo "trend smoke OK"
  rm -rf "$d"
}

trend_feed() {
  # Feed this CI session's walls into the trend store (ISSUE-18
  # satellite): stamp the preflight+tiers JSON with a host provenance
  # stamp (host_stamp — the suite always runs the forced-CPU virtual
  # mesh, so backend:cpu is the honest label), then ingest it. Advisory:
  # a trend-store hiccup must never fail a green suite, hence || true.
  local pf="${CI_PREFLIGHT_JSON:-ci_preflight.json}"
  [ -f "$pf" ] || return 0
  python - "$pf" <<'PY' || true
import json, sys

from federated_pytorch_test_tpu.obs.provenance import host_stamp

path = sys.argv[1]
doc = json.load(open(path))
doc["provenance"] = host_stamp()
with open(path, "w") as f:
    json.dump(doc, f, indent=1)
    f.write("\n")
PY
  python -m federated_pytorch_test_tpu trend "$pf" \
    --store "${CI_TREND_STORE:-ci_trend.jsonl}" --debt none --quiet \
    || true
}

tier="${CI_TIER:-all}"
preflight
case "$tier" in
  0) run_tier smoke tests/ -m smoke -q "$@" ;;
  1) run_tier tier1 tests/ -m 'not slow' -q "$@" ;;
  2)
    run_tier slow tests/ -m slow -q "$@"
    byzantine_smoke
    hetero_smoke
    bf16_smoke
    codec_smoke
    cohort_smoke
    spill_smoke
    fleet_smoke
    report_smoke
    incident_smoke
    integrity_smoke
    widened_smoke
    trend_smoke
    chaos_smoke
    ;;
  all)
    run_tier tier1 tests/ -m 'not slow' -q "$@"
    run_tier slow tests/ -m slow -q "$@"
    byzantine_smoke
    hetero_smoke
    bf16_smoke
    codec_smoke
    cohort_smoke
    spill_smoke
    fleet_smoke
    report_smoke
    incident_smoke
    integrity_smoke
    widened_smoke
    trend_smoke
    chaos_smoke
    ;;
  *) echo "unknown CI_TIER='$tier' (want 0, 1, 2 or all)" >&2; exit 2 ;;
esac
trend_feed
