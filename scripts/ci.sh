#!/usr/bin/env bash
# CI gate: the smoke tier on the virtual 8-device CPU mesh (<2 min).
#
# Tiers (markers declared in pyproject.toml):
#   pytest -m smoke                     — this script's gate, <2 min
#   pytest -m "not smoke and not slow"  — middle tier (~3 min): partition,
#                                         models
#   pytest -m slow                      — full integration (~20+ min):
#                                         engine sweeps, Pallas interpret
#                                         kernels, ring, 2-process runs
# Run all three for a full validation; tests/conftest.py forces the CPU
# platform and 8 virtual devices, so no TPU is needed.
set -euo pipefail
cd "$(dirname "$0")/.."
python -m pytest tests/ -m smoke -q "$@"
