"""Fabricate a bit-exact-FORMAT CIFAR archive from the synthetic dataset.

This environment has no network egress and no CIFAR archive anywhere on
disk (verified: only keras loader *code* is present, which would
download). The real-archive CODE PATH — binary record decoding through
the native loader (native/cifar_loader.cpp), full 50,000/10,000 scale,
16,666-sample client shards — is still a capability that must be
demonstrable end-to-end, so this script writes the framework's
deterministic synthetic dataset (data/cifar.py `synthetic_cifar`) into
the EXACT published CIFAR binary layout:

    cifar-10-batches-bin/data_batch_{1..5}.bin   10,000 records each
    cifar-10-batches-bin/test_batch.bin          10,000 records
    record = 1 label byte + 3072 image bytes (1024 R, 1024 G, 1024 B
             planes, row-major) — the layout torchvision documents and
             `load_cifar10` / the native decoder consume.

    cifar-100 variant: cifar-100-binary/{train,test}.bin with 2 label
    bytes (coarse, fine) per record.

Every file's SHA-256 goes into MANIFEST.json next to the batches; a
second invocation regenerates and VERIFIES byte-identity (the generator
is deterministic in --seed), so any bitrot or nondeterminism fails
loudly instead of silently changing the dataset under a benchmark.

Usage:
    python scripts/make_cifar_archive.py --root .cache/data [--name cifar10]
    CIFAR_DATA_DIR=.cache/data python -m federated_pytorch_test_tpu ...
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from federated_pytorch_test_tpu.data.cifar import synthetic_cifar  # noqa: E402


def _to_records(images: np.ndarray, label_cols) -> np.ndarray:
    """[N,32,32,3] uint8 HWC + label column(s) -> [N, L+3072] records."""
    n = images.shape[0]
    planes = images.transpose(0, 3, 1, 2).reshape(n, 3072)  # HWC -> CHW planes
    cols = [c.astype(np.uint8)[:, None] for c in label_cols]
    return np.concatenate(cols + [planes], axis=1)


def build_archive(root: str, name: str, seed: int) -> dict:
    """Write the binary archive for `name` under `root`; return manifest."""
    num_classes = 10 if name == "cifar10" else 100
    src = synthetic_cifar(
        n_train=50_000, n_test=10_000, num_classes=num_classes, seed=seed
    )
    if name == "cifar10":
        d = os.path.join(root, "cifar-10-batches-bin")
        os.makedirs(d, exist_ok=True)
        files = {}
        tr = _to_records(src.train_images, [src.train_labels])
        for i in range(5):
            files[f"data_batch_{i + 1}.bin"] = tr[i * 10_000 : (i + 1) * 10_000]
        files["test_batch.bin"] = _to_records(src.test_images, [src.test_labels])
    else:
        d = os.path.join(root, "cifar-100-binary")
        os.makedirs(d, exist_ok=True)
        # coarse label: fine // 5 (the published archive's 20 superclasses
        # partition the 100 fine classes; for the synthetic stand-in the
        # mapping just has to be a deterministic function of fine)
        files = {
            "train.bin": _to_records(
                src.train_images,
                [src.train_labels // 5, src.train_labels],
            ),
            "test.bin": _to_records(
                src.test_images,
                [src.test_labels // 5, src.test_labels],
            ),
        }

    manifest = {"name": name, "seed": seed, "files": {}}
    for fn, recs in sorted(files.items()):
        raw = np.ascontiguousarray(recs).tobytes()
        manifest["files"][fn] = {
            "sha256": hashlib.sha256(raw).hexdigest(),
            "bytes": len(raw),
        }
        path = os.path.join(d, fn)
        if os.path.exists(path):
            with open(path, "rb") as f:
                if f.read() != raw:
                    raise RuntimeError(
                        f"{path} exists with DIFFERENT bytes than the "
                        f"deterministic generator produces (seed {seed}) — "
                        "refusing to overwrite; delete it to regenerate"
                    )
        else:
            with open(path, "wb") as f:
                f.write(raw)
    manifest_path = os.path.join(d, "MANIFEST.json")
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            prev = json.load(f)
        if prev != manifest:
            raise RuntimeError(
                f"{manifest_path} disagrees with the regenerated manifest — "
                "the generator is no longer byte-deterministic or the "
                "archive was modified"
            )
    else:
        with open(manifest_path, "w") as f:
            json.dump(manifest, f, indent=1)
    return manifest


def verify_roundtrip(root: str, name: str, seed: int) -> None:
    """The written archive must read back IDENTICAL to the source arrays
    through the real loader path (native decoder included)."""
    from federated_pytorch_test_tpu.data.cifar import load_cifar10, load_cifar100

    num_classes = 10 if name == "cifar10" else 100
    src = synthetic_cifar(
        n_train=50_000, n_test=10_000, num_classes=num_classes, seed=seed
    )
    loaded = (load_cifar10 if name == "cifar10" else load_cifar100)(root)
    assert np.array_equal(loaded.train_images, src.train_images)
    assert np.array_equal(loaded.train_labels, src.train_labels)
    assert np.array_equal(loaded.test_images, src.test_images)
    assert np.array_equal(loaded.test_labels, src.test_labels)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--root", default=".cache/data")
    ap.add_argument("--name", choices=["cifar10", "cifar100"], default="cifar10")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    manifest = build_archive(args.root, args.name, args.seed)
    verify_roundtrip(args.root, args.name, args.seed)
    print(json.dumps(manifest, indent=1))
    print(f"archive OK under {args.root} (round-trip verified)")


if __name__ == "__main__":
    main()
