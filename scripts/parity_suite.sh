#!/usr/bin/env bash
# One-command convergence-parity suite: torch reference (imported
# LBFGSNew) vs this framework on identical data, all five configurations,
# followed by a hard band check (exit 1 if ANY tolerance band fails).
#
#   scripts/parity_suite.sh                  # discriminating synthetic
#   PARITY_DATA=real CIFAR_DATA_DIR=/data \
#     scripts/parity_suite.sh                # the real CIFAR-10 archive
#
# The real-archive mode is the rehearsed path that retires the "all
# parity evidence is synthetic" cap of archive-less environments: both
# sides consume the SAME deterministic subsample of the archive (see
# benchmarks/convergence_parity.py:synthetic). Budget: the torch side
# pays ~36 s per ResNet lockstep minibatch on a 1-core host, so the two
# resnet configs are hours — run the suite detached.
#
# Knobs: PARITY_NLOOP (simple configs), PARITY_RESNET_NLOOP /
# PARITY_RESNET_NTRAIN (resnet configs), PARITY_MATCHED_NTRAIN (the
# matched-dynamics config; pinned to its measured 256 default),
# PARITY_RHO0.
set -euo pipefail
cd "$(dirname "$0")/.."

for cfg in fedavg_simple admm_simple fedavg_resnet admm_resnet \
           fedavg_resnet_matched; do
  echo "=== convergence_parity: ${cfg} ==="
  python benchmarks/convergence_parity.py "${cfg}"
done

python - <<'PY'
import json, sys

d = json.load(open("benchmarks/convergence_parity.json"))
bad = []
for name, r in sorted(d.items()):
    if not isinstance(r, dict) or "verdict" not in r:
        continue
    v = r["verdict"]
    # PRIMARY oracle (one-sided, parity-or-better): compare() emits the
    # verdict as one bool so this gate never mirrors its key set
    fails = [] if v.get("primary_pass", False) else ["primary_pass"]
    # trajectory-parity bands (residuals, rho, symmetric accuracy) are
    # REQUIRED only when the two sides converge to similar accuracy —
    # when the framework beats the reference beyond the band, the
    # trajectories legitimately diverge and the bands are informational.
    # Explicit whitelist: a future informational boolean in compare()
    # must not silently become a requirement here.
    BAND_KEYS = ("acc_final_within_band", "acc_mean_within_0.06",
                 "dual_within_half_order", "primal_within_half_order",
                 "rho_ratio_within_2x")
    similar = v.get("final_acc_diff", 1.0) <= v.get("acc_band", 0.05)
    # matched-dynamics configs carry a RECORDED flag (config.matched in
    # the artifact — semantics attached to the config, not its name);
    # they exist precisely to validate the residual trajectory by
    # measurement, so compare() emits their stricter oracle as ONE bool
    # (`matched_pass`: primary + similar + every strategy band present
    # and true — unit-tested in tests/test_parity_compare.py) and this
    # gate reads only that, mirroring no key set.
    if r.get("config", {}).get("matched") or name.endswith("_matched"):
        if not v.get("matched_pass", False):
            fails.append("matched_pass")
    elif similar:
        fails += [k for k in BAND_KEYS if k in v and not v[k]]
    beats = " (framework beats reference)" if v.get(
        "framework_beats_reference") and not similar else ""
    print(f"{name:16s} {'PASS' + beats if not fails else 'FAIL ' + str(fails)}")
    bad += [(name, f) for f in fails]
sys.exit(1 if bad else 0)
PY
