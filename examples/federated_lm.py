"""Federated causal-LM training: K transformer clients, one mesh axis.

The capstone composition of the framework's two halves. The reference
trains K CNN clients on disjoint CIFAR shards with partial-parameter
FedAvg (reference src/federated_trio.py); here the SAME recipe — common
init, per-group L-BFGS epochs, masked FedAvg collective, per-client eval
— runs on `TransformerLM` clients over disjoint TOKEN streams:

- each client's corpus is a Markov chain sharing a dominant transition
  (i -> i+1) but with a client-BIASED minor transition (i -> i+2+c), the
  LM analogue of the reference's biased per-client normalization
  (reference src/no_consensus_trio.py:32-50);
- the partition groups are the LM's own (embeddings, each block, head —
  models/transformer.py GROUP_PATHS), so only one group's coordinates
  cross the interconnect per round, exactly the reference's bandwidth
  contract (reference README.md:2);
- every client's stochastic L-BFGS epoch (line-search probes included)
  runs vmapped inside one jitted shard_map over the `clients` mesh axis,
  and the FedAvg z-update is a psum collective (consensus/fedavg.py).

On a CPU dev box:

    XLA_FLAGS=--xla_force_host_platform_device_count=4 \
    JAX_PLATFORMS=cpu python examples/federated_lm.py

On a TPU slice just run it — clients ride the ICI.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    from federated_pytorch_test_tpu.utils import force_host_cpu

    force_host_cpu()

import jax
import jax.numpy as jnp
import optax
from federated_pytorch_test_tpu.parallel import shard_map
from jax.sharding import PartitionSpec as P

from federated_pytorch_test_tpu.consensus import FedAvgState, fedavg_round
from federated_pytorch_test_tpu.models import TransformerLM, init_client_params
from federated_pytorch_test_tpu.optim import LBFGSConfig, lbfgs_init, lbfgs_step
from federated_pytorch_test_tpu.parallel import (
    CLIENT_AXIS,
    largest_feasible_mesh,
    shard_clients,
)
from federated_pytorch_test_tpu.partition import flatten_params

K = int(os.environ.get("K", "4"))  # clients
VOCAB = 32
SEQ = int(os.environ.get("SEQ", "32"))
BATCH = 8
N_BATCH = 4  # lockstep minibatches per epoch
NLOOP = int(os.environ.get("NLOOP", "2"))
SEED = 0


def markov_corpus(client: int, n_seq: int, rng: np.random.Generator):
    """Client-biased Markov chains: 85% i->i+1 (shared), 15% i->i+2+c."""
    minor = (2 + client) % VOCAB
    seqs = np.empty((n_seq, SEQ + 1), np.int64)
    for j in range(n_seq):
        tok = rng.integers(0, VOCAB)
        for t in range(SEQ + 1):
            seqs[j, t] = tok
            step = 1 if rng.random() < 0.85 else minor
            tok = (tok + step) % VOCAB
    return seqs


def main():
    mesh = largest_feasible_mesh(K)
    d = mesh.devices.size
    print(f"{K} LM clients on a {d}-device mesh "
          f"({mesh.devices.flat[0].platform}, {K // d} per device)")

    rng = np.random.default_rng(SEED)
    train = np.stack([markov_corpus(c, N_BATCH * BATCH, rng) for c in range(K)])
    test = np.stack([markov_corpus(c, 2 * BATCH, rng) for c in range(K)])
    # [K, n_batch, batch, SEQ+1] lockstep minibatches
    train = train.reshape(K, N_BATCH, BATCH, SEQ + 1)

    lm = TransformerLM(vocab=VOCAB, dim=32, num_heads=4, max_len=SEQ)
    variables = init_client_params(lm, K, seed=SEED)
    params0 = jax.tree.map(lambda x: x[0], variables["params"])
    flat0, unravel = flatten_params(params0)
    part = TransformerLM.partition(params0)
    n = int(flat0.shape[0])
    print(f"{n} params in {part.num_groups} partition groups "
          f"{[part.group_size(g) for g in range(part.num_groups)]}")

    flat = shard_clients(
        jnp.broadcast_to(flat0[None], (K, n)).astype(jnp.float32), mesh
    )
    train_d = shard_clients(jnp.asarray(train, jnp.int32), mesh)
    test_d = shard_clients(jnp.asarray(test, jnp.int32), mesh)

    cfg = LBFGSConfig(max_iter=4, history_size=10, line_search=True,
                      batch_mode=True)

    def ce(full_flat, toks):
        logits = lm.apply({"params": unravel(full_flat)}, toks[:, :-1])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), toks[:, 1:]
        ).mean()

    def make_round(gid):
        """One jitted epoch+consensus round for partition group `gid`."""

        def client_epoch(flat_c, batches):
            seg0 = part.extract(flat_c, gid)

            def one_batch(carry, toks):
                seg, state = carry

                def loss(v):
                    return ce(part.insert(flat_c, gid, v), toks)

                seg, state, _ = lbfgs_step(loss, seg, state, cfg)
                return (seg, state), loss(seg)

            # fresh optimizer per partition round (reference
            # src/federated_trio.py:273-275)
            (seg, _), losses = jax.lax.scan(
                one_batch, (seg0, lbfgs_init(seg0, cfg)), batches
            )
            return part.insert(flat_c, gid, seg), losses[-1]

        def round_fn(flat_loc, batches_loc, z):
            flat_loc, last_loss = jax.vmap(client_epoch)(flat_loc, batches_loc)
            x = jax.vmap(lambda f: part.extract(f, gid))(flat_loc)
            state, metrics = fedavg_round(x, FedAvgState(z=z))
            flat_loc = jax.vmap(
                lambda f: part.insert(f, gid, state.z)
            )(flat_loc)
            return flat_loc, last_loss, metrics["dual_residual"]

        return jax.jit(
            shard_map(
                round_fn,
                mesh=mesh,
                in_specs=(P(CLIENT_AXIS), P(CLIENT_AXIS), P()),
                out_specs=(P(CLIENT_AXIS), P(CLIENT_AXIS), P()),
                check_vma=False,
            )
        )

    def eval_fn(flat_loc, toks_loc):
        def client_acc(flat_c, toks):
            logits = lm.apply({"params": unravel(flat_c)}, toks[:, :-1])
            pred = jnp.argmax(logits, axis=-1)
            return jnp.mean((pred == toks[:, 1:]).astype(jnp.float32))

        return jax.vmap(client_acc)(flat_loc, toks_loc)

    evaluate = jax.jit(
        shard_map(
            eval_fn, mesh=mesh, in_specs=(P(CLIENT_AXIS), P(CLIENT_AXIS)),
            out_specs=P(CLIENT_AXIS), check_vma=False,
        )
    )

    rounds = {g: make_round(g) for g in part.train_order}
    print(f"chance accuracy = {1 / VOCAB:.3f}")
    for nloop in range(NLOOP):
        for gid in part.train_order:
            z0 = jnp.zeros((part.group_size(gid),), jnp.float32)
            flat, last_loss, dual = rounds[gid](flat, train_d, z0)
            accs = evaluate(flat, test_d)
            print(f"nloop {nloop} group {gid}: loss {np.mean(last_loss):.4f} "
                  f"dual {float(dual):.3e} acc {np.asarray(accs).round(3)}")
            # the averaged group is bit-identical across clients
            xg = np.asarray(
                jax.vmap(lambda f: part.extract(f, gid))(flat)
            )
            assert np.abs(xg - xg[:1]).max() == 0.0

    accs = np.asarray(evaluate(flat, test_d))
    print(f"final per-client next-token accuracy: {accs.round(3)}")
    assert accs.mean() > 5.0 / VOCAB, (
        f"federated LM failed to learn: {accs}"
    )


if __name__ == "__main__":
    main()
