"""K=64 clients on a TPU pod: one client per core, DCN-aware mesh.

Run THIS SAME script on every host of the slice/pod (standard JAX
multi-controller SPMD). `initialize_distributed()` must run before any
other JAX call; `multihost_client_mesh` lays the `clients` axis out so a
slice's clients are ICI-adjacent and consensus psums cross DCN once.

Single-host (or the dev box) it degrades gracefully: the mesh shrinks to
the local devices and the same code runs.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor an explicit CPU request: the ambient environment may pin jax to a
# TPU PJRT plugin that overrides JAX_PLATFORMS (see utils/hostcpu.py)
if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    from federated_pytorch_test_tpu.utils import force_host_cpu

    force_host_cpu()

from federated_pytorch_test_tpu.parallel import (
    initialize_distributed,
    multihost_client_mesh,
)

proc = initialize_distributed()  # BEFORE any other JAX call

from federated_pytorch_test_tpu.engine import Trainer, get_preset  # noqa: E402


def main():
    cfg = get_preset(os.environ.get("PRESET", "fedavg_scale64"))
    # dev-box dry run: shrink the preset through env overrides WITHOUT
    # changing the recipe (same init -> mesh -> Trainer.run -> save path
    # a pod runs); e.g. K=8 MODEL=net NLOOP=1 MAX_GROUPS=1 smoke-runs the
    # script on a laptop's virtual mesh (tests/test_examples.py)
    env_to_field = {
        "K": ("n_clients", int),
        "MODEL": ("model", str),
        "NLOOP": ("nloop", int),
        "NADMM": ("nadmm", int),
        "BATCH": ("batch", int),
        "NTRAIN": ("synthetic_n_train", int),
        "NTEST": ("synthetic_n_test", int),
        "MAX_GROUPS": ("max_groups", int),
    }
    over = {
        field: cast(os.environ[name])
        for name, (field, cast) in env_to_field.items()
        if name in os.environ
    }
    if over:
        cfg = cfg.replace(**over)
    mesh = multihost_client_mesh(cfg.n_clients)
    trainer = Trainer(cfg, verbose=(proc == 0), mesh=mesh)
    recorder = trainer.run()
    if proc == 0:
        out = os.environ.get("METRICS_OUT", "scale64_metrics.json")
        recorder.save(out)
        print(f"scale64 run complete -> {out}")


if __name__ == "__main__":
    main()
