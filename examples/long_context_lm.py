"""Sequence-parallel causal LM: the long-context recipe, end to end.

Shards a context of `SEQ` tokens over every available device as a ring
(`parallel/ring.py`), trains the TransformerLM with the framework's
jitted stochastic L-BFGS on a copy task, and checks the sharded loss
equals the dense one. On a CPU dev box run with a virtual ring:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/long_context_lm.py

On a TPU slice just run it — the ring rides the ICI.

`ATTN_IMPL=ring_flash` swaps each ring step's block compute to the
Pallas flash kernel (two-level streaming; needs SEQ such that every
device's shard is a multiple of 128, e.g. SEQ=1024 on 8 devices):

    ATTN_IMPL=ring_flash SEQ=1024 python examples/long_context_lm.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor an explicit CPU request: the ambient environment may pin jax to a
# TPU PJRT plugin that overrides JAX_PLATFORMS (see utils/hostcpu.py)
if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    from federated_pytorch_test_tpu.utils import force_host_cpu

    force_host_cpu()

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from federated_pytorch_test_tpu.models import TransformerLM
from federated_pytorch_test_tpu.optim import LBFGSConfig, lbfgs_init, lbfgs_step
from federated_pytorch_test_tpu.parallel import SEQ_AXIS
from federated_pytorch_test_tpu.partition import flatten_params

SEQ = int(os.environ.get("SEQ", "512"))
STEPS = int(os.environ.get("STEPS", "12"))
VOCAB = 64
ATTN_IMPL = os.environ.get("ATTN_IMPL", "ring")  # 'ring' | 'ring_flash'


def main():
    # 'dense'/'flash' would pass model validation but attend only over
    # each device's local shard inside the seq-axis shard_map — reject
    # them up front instead of failing the parity check obscurely
    assert ATTN_IMPL in ("ring", "ring_flash"), ATTN_IMPL
    devs = jax.devices()
    p = len(devs)
    assert SEQ % p == 0, f"SEQ={SEQ} must be divisible by {p} devices"
    if ATTN_IMPL == "ring_flash":
        assert (SEQ // p) % 128 == 0, (
            f"ring_flash needs 128-multiple shards; SEQ={SEQ} over {p} "
            f"devices gives {SEQ // p}"
        )
    mesh = Mesh(np.asarray(devs), (SEQ_AXIS,))
    print(f"{p}-device sequence ring on {devs[0].platform} ({ATTN_IMPL})")

    # params are attention-impl-agnostic: init the dense twin (ring
    # attention needs the seq axis bound, which only exists in shard_map)
    lm = TransformerLM(attn_impl=ATTN_IMPL, dim=64, num_heads=4, vocab=VOCAB,
                       max_len=SEQ)
    lm_dense = TransformerLM(attn_impl="dense", dim=64, num_heads=4,
                             vocab=VOCAB, max_len=SEQ)
    rng = np.random.default_rng(0)
    seq = jnp.asarray(np.tile(rng.integers(0, VOCAB, size=32), SEQ)[: SEQ + 1],
                      jnp.int32)
    tokens, targets = seq[None, :-1], seq[None, 1:]

    params = lm_dense.init(jax.random.PRNGKey(0), tokens)["params"]
    flat, unravel = flatten_params(params)

    def shard_loss(f, tok_shard, tgt_shard):
        # every device: its token shard, its global positions, ring attn
        my = jax.lax.axis_index(SEQ_AXIS)
        blk = SEQ // p
        pos = (my * blk + jnp.arange(blk))[None, :]
        logits = lm.apply({"params": unravel(f)}, tok_shard, positions=pos)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), tgt_shard
        ).sum()
        return jax.lax.psum(loss, SEQ_AXIS) / SEQ  # global mean

    from federated_pytorch_test_tpu.parallel import shard_map
    sharded = shard_map(
        shard_loss,
        mesh=mesh,
        in_specs=(P(), P(None, SEQ_AXIS), P(None, SEQ_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    loss_fn = lambda f: sharded(f, tokens, targets)  # noqa: E731

    # the sharded ring loss must equal the dense unsharded loss exactly
    def dense_loss(f):
        logits = lm_dense.apply({"params": unravel(f)}, tokens)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), targets
        ).mean()

    ring_l, dense_l = float(loss_fn(flat)), float(dense_loss(flat))
    assert abs(ring_l - dense_l) < 1e-3 * max(1.0, abs(dense_l)), (ring_l, dense_l)
    print(f"ring == dense loss check: {ring_l:.6f} vs {dense_l:.6f}")

    cfg = LBFGSConfig(max_iter=4, history_size=10, line_search=True,
                      batch_mode=True)
    state = lbfgs_init(flat, cfg)
    step = jax.jit(lambda f, s: lbfgs_step(loss_fn, f, s, cfg))

    print(f"loss[0] = {float(loss_fn(flat)):.4f}")
    for i in range(STEPS):
        flat, state, aux = step(flat, state)
    print(f"loss[{STEPS}] = {float(loss_fn(flat)):.4f}  "
          f"(func_evals={int(state.func_evals)})")


if __name__ == "__main__":
    main()
